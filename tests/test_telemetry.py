"""Production telemetry tier (core.telemetry + the serve/trace wiring):
the live SLO surface, Prometheus exposition (golden-file exact), the
metrics exporters, the flight-recorder postmortem path — including the
ISSUE 11 acceptance test that an injected runtime OOM inside a running
``Server`` in a FRESH process (tracing disabled) produces a schema-valid
postmortem dump containing the fault instant and the victim requests'
lifecycle spans."""

import glob
import json
import os
import subprocess
import sys
import textwrap
import threading
import time
import urllib.request

import numpy as np
import pytest

from keystone_tpu.core import telemetry, trace
from keystone_tpu.core.resilience import counters

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Postmortem caps/paths and SLO trackers are process-global."""
    telemetry._reset_state()
    trace.flight_reset()
    yield
    telemetry._reset_state()


# -- SLO tracker --------------------------------------------------------------


class TestSLOTracker:
    def test_window_percentiles_and_burn_rate(self):
        clock = {"t": 100.0}
        t = telemetry.SLOTracker(
            "eng", slo_ms=10.0, budget=0.1, window_s=60.0,
            clock=lambda: clock["t"],
        )
        for i, v in enumerate((1.0, 2.0, 3.0, 50.0, 4.0)):
            clock["t"] = 100.0 + i  # 1s apart -> QPS computable
            t.observe(v)
        s = t.summary()
        assert s["slo_ms"] == 10.0 and s["budget"] == 0.1
        w = s["window"]
        assert w["count"] == 5
        assert w["violations"] == 1  # the 50ms outlier
        # violation rate 0.2 against a 0.1 budget -> burning 2x budget
        assert w["burn_rate"] == pytest.approx(2.0)
        assert w["p99_ms"] == 50.0 and w["max_ms"] == 50.0
        assert w["qps"] == pytest.approx(5 / 4, rel=0.01)
        assert s["total"]["requests"] == 5 and s["total"]["errors"] == 0
        json.dumps(s)

    def test_errors_burn_budget_and_window_rolls(self):
        clock = {"t": 0.0}
        t = telemetry.SLOTracker(
            "eng", slo_ms=100.0, budget=0.5, window_s=10.0,
            clock=lambda: clock["t"],
        )
        t.observe(1.0, ok=False)  # an error inside SLO latency still burns
        assert t.summary()["window"]["violations"] == 1
        assert t.summary()["total"]["errors"] == 1
        clock["t"] = 100.0  # far past the window
        t.observe(1.0, ok=True)
        w = t.summary()["window"]
        assert w["count"] == 1 and w["violations"] == 0  # old error rolled off
        assert t.summary()["total"]["violations"] == 1  # totals never forget

    def test_env_targets_per_label(self, monkeypatch):
        monkeypatch.setenv(telemetry.SLO_MS_ENV, "25")
        assert telemetry.slo_target_ms("anything") == 25.0
        monkeypatch.setenv(
            telemetry.SLO_MS_ENV, "mnist_fft=20,default=75,cifar_conv=150"
        )
        assert telemetry.slo_target_ms("mnist_fft") == 20.0
        assert telemetry.slo_target_ms("cifar_conv") == 150.0
        assert telemetry.slo_target_ms("unknown") == 75.0
        monkeypatch.delenv(telemetry.SLO_MS_ENV)
        assert telemetry.slo_target_ms("x") == telemetry.DEFAULT_SLO_MS

    def test_registered_trackers_ride_in_metrics_snapshot(self):
        t = telemetry.register_slo("snap_probe", slo_ms=5.0)
        t.observe(1.0)
        snap = trace.metrics.snapshot()
        assert snap["slo"]["snap_probe"]["window"]["count"] == 1
        json.dumps(snap)  # bench embeds this verbatim


# -- Prometheus exposition ----------------------------------------------------


def test_prometheus_text_golden():
    """Exact exposition-format output for a fixed snapshot — counters,
    gauges, histogram summaries with quantile labels, and an adopted
    group flattened as counters."""
    m = trace.Metrics()
    m.inc("alpha_total", 3)
    m.gauge("queue_depth", 2.5)
    for v in (1.0, 2.0, 3.0, 4.0):
        m.observe("lat_ms", v)

    class Group:
        def snapshot(self, reset=False):
            return {"corrupt_image": 2}

    m.adopt("faults", Group())
    text = telemetry.prometheus_text(m.snapshot())
    assert text == textwrap.dedent(
        """\
        # TYPE keystone_alpha_total counter
        keystone_alpha_total 3
        # TYPE keystone_queue_depth gauge
        keystone_queue_depth 2.5
        # TYPE keystone_lat_ms summary
        keystone_lat_ms{quantile="0.50"} 3.0
        keystone_lat_ms{quantile="0.90"} 4.0
        keystone_lat_ms{quantile="0.99"} 4.0
        keystone_lat_ms_sum 10.0
        keystone_lat_ms_count 4
        # TYPE keystone_faults_corrupt_image counter
        keystone_faults_corrupt_image 2
        """
    )


def test_prometheus_text_labeled_golden():
    """ISSUE 20: the same fixed snapshot rendered with ``host``/``rank``
    labels — every sample line carries the sorted label block, the
    histogram quantile label composes AFTER the member labels, and the
    TYPE lines stay label-free (exposition-format exact)."""
    m = trace.Metrics()
    m.inc("alpha_total", 3)
    m.gauge("queue_depth", 2.5)
    for v in (1.0, 2.0, 3.0, 4.0):
        m.observe("lat_ms", v)

    class Group:
        def snapshot(self, reset=False):
            return {"corrupt_image": 2}

    m.adopt("faults", Group())
    text = telemetry.prometheus_text(
        m.snapshot(), labels={"host": "h0", "rank": 0}
    )
    assert text == textwrap.dedent(
        """\
        # TYPE keystone_alpha_total counter
        keystone_alpha_total{host="h0",rank="0"} 3
        # TYPE keystone_queue_depth gauge
        keystone_queue_depth{host="h0",rank="0"} 2.5
        # TYPE keystone_lat_ms summary
        keystone_lat_ms{host="h0",rank="0",quantile="0.50"} 3.0
        keystone_lat_ms{host="h0",rank="0",quantile="0.90"} 4.0
        keystone_lat_ms{host="h0",rank="0",quantile="0.99"} 4.0
        keystone_lat_ms_sum{host="h0",rank="0"} 10.0
        keystone_lat_ms_count{host="h0",rank="0"} 4
        # TYPE keystone_faults_corrupt_image counter
        keystone_faults_corrupt_image{host="h0",rank="0"} 2
        """
    )


def test_render_labels_sorts_escapes_and_skips_none():
    assert telemetry.render_labels(None) == ""
    assert telemetry.render_labels({}) == ""
    assert telemetry.render_labels({"rank": None}) == ""
    assert (
        telemetry.render_labels({"b": 'say "hi"\n', "a": "x\\y"})
        == '{a="x\\\\y",b="say \\"hi\\"\\n"}'
    )
    assert (
        telemetry.render_labels({"host": "h0"}, extra='quantile="0.99"')
        == '{host="h0",quantile="0.99"}'
    )
    assert telemetry.render_labels({}, extra='quantile="0.99"') == (
        '{quantile="0.99"}'
    )


def test_prometheus_text_without_labels_is_byte_identical():
    """labels=None must not perturb the un-labeled exposition the
    original golden test pins (single-process scrapes keep their bytes)."""
    m = trace.Metrics()
    m.inc("alpha_total", 3)
    assert telemetry.prometheus_text(m.snapshot()) == telemetry.prometheus_text(
        m.snapshot(), labels=None
    )
    assert telemetry.prometheus_text(m.snapshot(), labels={}) == (
        telemetry.prometheus_text(m.snapshot())
    )


def test_prometheus_text_sanitizes_names_and_skips_non_numeric():
    m = trace.Metrics()
    m.inc("weird.name-with/chars")

    class Group:
        def snapshot(self, reset=False):
            return {"nested": {"ok": 1, "label": "not-a-number"}}

    m.adopt("grp", Group())
    text = telemetry.prometheus_text(m.snapshot())
    assert "keystone_weird_name_with_chars 1" in text
    assert "keystone_grp_nested_ok 1" in text
    assert "not-a-number" not in text


def test_metrics_file_writer_atomic_and_periodic(tmp_path):
    path = str(tmp_path / "metrics.prom")
    trace.metrics.inc("writer_probe_total")
    w = telemetry.MetricsWriter(path, interval_s=0.05)
    w.start()
    try:
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if os.path.exists(path):
                break
            time.sleep(0.01)
        body = open(path).read()
        assert "keystone_writer_probe_total" in body
    finally:
        w.stop()
    # no temp litter from the atomic writes
    assert [p for p in os.listdir(tmp_path) if ".tmp" in p] == []


def test_metrics_http_endpoint(tmp_path):
    trace.metrics.inc("http_probe_total")
    server = telemetry.start_metrics_server(0)  # ephemeral port
    try:
        port = server.server_address[1]
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ).read().decode()
        assert "keystone_http_probe_total" in body
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/nope", timeout=10
            )
    finally:
        server.shutdown()


def test_statusz_and_healthz_endpoints():
    """The /statusz debug page (ISSUE 15): one JSON snapshot of provider
    state (router engines, ring/stream), SLO windows, and the numerics
    observatory — golden-pinned schema; /healthz answers liveness."""
    from keystone_tpu.core import numerics as knum

    telemetry.register_statusz("probe_provider", lambda: {"engines": 2})
    telemetry.register_statusz(
        "sick_provider", lambda: (_ for _ in ()).throw(RuntimeError("down"))
    )
    trace.metrics.gauge("statusz_probe_gauge", 7)
    server = telemetry.start_metrics_server(0)
    try:
        port = server.server_address[1]
        health = json.loads(
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10
            ).read()
        )
        assert health == {"ok": True}
        resp = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/statusz", timeout=10
        )
        assert resp.headers["Content-Type"] == "application/json"
        doc = json.loads(resp.read())
        # Golden schema: the keys operators script against.
        assert doc["schema"] == "keystone.statusz/1"
        assert set(doc) >= {
            "schema", "time_unix", "pid", "providers", "slo", "numerics",
            "faults", "counters", "gauges",
        }
        assert doc["providers"]["probe_provider"] == {"engines": 2}
        # One sick provider reports its error without blanking the page.
        assert "RuntimeError" in doc["providers"]["sick_provider"]["error"]
        assert doc["gauges"]["statusz_probe_gauge"] == 7
        assert set(doc["numerics"]) >= {
            "active", "sites", "conditioning", "provenance", "drift",
        }
        assert doc["pid"] == os.getpid()
    finally:
        server.shutdown()
        telemetry.unregister_statusz("probe_provider")
        telemetry.unregister_statusz("sick_provider")
        del knum


def test_statusz_carries_router_and_stream_state(tmp_path):
    """Routers and ingest streams self-register as /statusz providers and
    unregister on close — the page shows the CURRENT topology."""
    from keystone_tpu.core import frontend as kfrontend

    router = kfrontend.ShapeRouter(label="statusz_router")
    try:
        snap = telemetry.statusz_snapshot()
        assert "router:statusz_router" in snap["providers"]
        assert snap["providers"]["router:statusz_router"]["engines"] == {}
    finally:
        router.close()
    assert "router:statusz_router" not in (
        telemetry.statusz_snapshot()["providers"]
    )


# -- postmortem dumps ---------------------------------------------------------


def test_counted_fault_dumps_schema_valid_postmortem(tmp_path, monkeypatch):
    monkeypatch.setenv(telemetry.POSTMORTEM_DIR_ENV, str(tmp_path))
    assert not trace.enabled()
    with trace.span("doomed_phase", cat="probe"):
        pass
    counters.record("deadline_exceeded", "probe: watchdog tripped")
    dumps = glob.glob(str(tmp_path / "postmortem_deadline_exceeded_*.json"))
    assert len(dumps) == 1
    doc = json.load(open(dumps[0]))
    assert doc["schema"] == telemetry.POSTMORTEM_SCHEMA
    assert doc["fault"]["kind"] == "deadline_exceeded"
    assert doc["trace_enabled"] is False
    # the ring carried the pre-fault span AND the fault instant itself
    names = [e.get("name") for e in doc["flight"]]
    assert "doomed_phase" in names and "fault" in names
    assert doc["metrics"]["faults"]["deadline_exceeded"] >= 1
    assert dumps[0] in telemetry.postmortem_paths()


def test_postmortem_rate_cap_and_kind_filter(tmp_path, monkeypatch):
    monkeypatch.setenv(telemetry.POSTMORTEM_DIR_ENV, str(tmp_path))
    for _ in range(telemetry.MAX_DUMPS_PER_KIND + 3):
        counters.record("serve_burst_oom", "storm")
    assert (
        len(glob.glob(str(tmp_path / "postmortem_serve_burst_oom_*")))
        == telemetry.MAX_DUMPS_PER_KIND
    )
    # a non-postmortem fault family never dumps
    counters.record("io_retry", "transient")
    assert glob.glob(str(tmp_path / "postmortem_io_retry_*")) == []


def test_no_dump_without_dir(tmp_path, monkeypatch):
    monkeypatch.delenv(telemetry.POSTMORTEM_DIR_ENV, raising=False)
    assert telemetry.maybe_postmortem("serve_burst_oom", "no dir") is None
    assert telemetry.postmortem_paths() == []


def test_postmortems_linked_from_reports(tmp_path, monkeypatch):
    from keystone_tpu.core.memory import FitReport
    from keystone_tpu.core.serve import ServerStats

    monkeypatch.setenv(telemetry.POSTMORTEM_DIR_ENV, str(tmp_path))
    counters.record("nonfinite_model", "probe")
    [path] = telemetry.postmortem_paths()
    assert path in FitReport().record()["postmortems"]
    assert path in ServerStats().record()["postmortems"]


def test_telemetry_disabled_context():
    t = telemetry.register_slo("off_probe", slo_ms=5.0)
    prev_depth = trace.flight_depth()
    with telemetry.telemetry_disabled():
        assert trace.flight_depth() == 0
        t.observe(1.0)
        with trace.span("invisible"):
            pass
    assert trace.flight_depth() == prev_depth
    assert t.summary()["window"]["count"] == 0
    assert all(
        e.get("name") != "invisible" for e in trace.flight_events()
    )


# -- the fresh-process acceptance path (ISSUE 11) -----------------------------


def test_fresh_process_serve_oom_postmortem(tmp_path):
    """A runtime OOM inside a running ``Server`` in a FRESH interpreter
    with tracing DISABLED must produce a schema-valid flight-recorder
    postmortem containing the ``serve_burst_oom`` fault instant and the
    victim requests' lifecycle evidence: their ``serve.submit`` instants
    and the failed ``serve.execute`` span naming their id range — while
    the endpoint degrades and still answers every request bit-equal."""
    dump_dir = str(tmp_path / "dumps")
    script = textwrap.dedent(
        """
        import os
        os.environ['JAX_PLATFORMS'] = 'cpu'
        import sys
        sys.path.insert(0, 'tests')
        import numpy as np
        import jax.numpy as jnp
        import faults
        from keystone_tpu.core import serve as kserve, trace
        from keystone_tpu.core.pipeline import FunctionTransformer

        assert not trace.enabled(), 'tracing must be OFF for this proof'
        assert trace.flight_depth() > 0, 'flight ring must be on'
        rng = np.random.default_rng(0)
        w = jnp.asarray(rng.normal(size=(16,)).astype(np.float32))
        b = jnp.asarray(rng.normal(size=(16,)).astype(np.float32))
        pipe = FunctionTransformer(lambda x: jnp.maximum(x * w, b), name='pm')
        cfg = kserve.ServeConfig(buckets=(1, 2, 4), max_wait_ms=2.0)
        engine = kserve.ServingEngine(
            pipe, np.zeros(16, np.float32), config=cfg, label='pm')
        real = engine._execute
        state = {'n': 0}

        def failing(bucket, dev):
            if bucket == 4 and state['n'] < 1:
                state['n'] += 1
                raise faults.resource_exhausted_error()
            return real(bucket, dev)

        engine._execute = failing
        reqs = rng.normal(size=(12, 16)).astype(np.float32)
        with kserve.Server(engine) as server:
            futs = [server.submit(r) for r in reqs]
            answers = np.stack([f.result(30.0) for f in futs])
        engine._execute = real
        assert state['n'] == 1, 'the OOM was never injected'
        np.testing.assert_array_equal(answers, engine.offline(reqs))
        print('PM_SERVE_OK')
        """
    )
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        KEYSTONE_POSTMORTEM_DIR=dump_dir,
    )
    env.pop("KEYSTONE_TRACE", None)
    res = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True, text=True, timeout=300, env=env, cwd=_REPO,
    )
    assert res.returncode == 0, res.stderr[-2000:]
    assert "PM_SERVE_OK" in res.stdout

    dumps = glob.glob(os.path.join(dump_dir, "postmortem_serve_burst_oom_*"))
    assert len(dumps) == 1, dumps
    doc = json.load(open(dumps[0]))
    # schema-valid
    assert doc["schema"] == telemetry.POSTMORTEM_SCHEMA
    assert set(doc) >= {
        "schema", "time_unix", "pid", "fault", "trace_enabled",
        "flight_depth", "flight", "metrics",
    }
    assert doc["trace_enabled"] is False
    assert doc["fault"]["kind"] == "serve_burst_oom"
    flight = doc["flight"]
    # the triggering fault instant is in the ring
    fault_events = [
        e for e in flight
        if e.get("name") == "fault"
        and e.get("args", {}).get("kind") == "serve_burst_oom"
    ]
    assert fault_events, "fault instant missing from the flight ring"
    # the victim micro-batch: a serve.execute span that FAILED with the
    # injected error, naming its request-id range
    failed_exec = [
        e for e in flight
        if e.get("name") == "serve.execute" and e.get("args", {}).get("error")
    ]
    assert failed_exec, "no failed serve.execute span in the ring"
    args = failed_exec[0]["args"]
    assert args["req_first"] <= args["req_last"]
    # ...and the victims' births: serve.submit instants for that id range
    submitted = {
        e["args"]["request_id"]
        for e in flight
        if e.get("name") == "serve.submit"
    }
    victims = set(range(args["req_first"], args["req_last"] + 1))
    assert victims <= submitted, (victims, submitted)
    # the counters snapshot rode along
    assert doc["metrics"]["faults"]["serve_burst_oom"] >= 1


def _child_reports_writer_state(q):
    from keystone_tpu.core import telemetry as t

    q.put(t._env_writer is None and t._env_server is None)


def test_worker_process_does_not_activate_exporters(tmp_path, monkeypatch):
    """Spawned helper processes (decode workers) inherit the parent env;
    they must NOT each start a metrics writer clobbering the shared file
    (or race to bind the metrics port) — only the main process exports."""
    import multiprocessing

    monkeypatch.setenv(telemetry.METRICS_FILE_ENV, str(tmp_path / "w.prom"))
    ctx = multiprocessing.get_context("spawn")
    q = ctx.Queue()
    p = ctx.Process(target=_child_reports_writer_state, args=(q,))
    p.start()
    try:
        assert q.get(timeout=60) is True, (
            "a spawned child activated the env exporters"
        )
    finally:
        p.join(30)


def test_fresh_process_env_activates_metrics_file(tmp_path):
    """KEYSTONE_METRICS_FILE in the environment must stand up the periodic
    Prometheus writer for ANY process that imports the resilience layer —
    no serving, no explicit telemetry call."""
    path = str(tmp_path / "metrics.prom")
    script = textwrap.dedent(
        """
        import time
        from keystone_tpu.core.resilience import counters
        from keystone_tpu.core import trace
        trace.metrics.inc('env_probe_total', 7)
        time.sleep(0.3)
        print('ENV_METRICS_OK')
        """
    )
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        KEYSTONE_METRICS_FILE=path,
        KEYSTONE_METRICS_INTERVAL_S="0.05",
    )
    res = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True, text=True, timeout=120, env=env, cwd=_REPO,
    )
    assert res.returncode == 0, res.stderr[-2000:]
    assert "ENV_METRICS_OK" in res.stdout
    body = open(path).read()
    assert "keystone_env_probe_total 7" in body


# -- per-request lifecycle + stats-in-registry (the serve wiring) -------------


def _tiny_engine(rng):
    import jax.numpy as jnp

    from keystone_tpu.core import serve as kserve
    from keystone_tpu.core.pipeline import FunctionTransformer

    w = jnp.asarray(rng.normal(size=(16,)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(16,)).astype(np.float32))
    pipe = FunctionTransformer(lambda x: jnp.maximum(x * w, b), name="ph")
    cfg = kserve.ServeConfig(buckets=(1, 2, 4), max_wait_ms=2.0)
    return kserve.ServingEngine(
        pipe, np.zeros(16, np.float32), config=cfg, label="phase_probe"
    )


def test_request_phase_decomposition_and_ids(rng):
    from keystone_tpu.core import serve as kserve

    engine = _tiny_engine(rng)
    reqs = rng.normal(size=(10, 16)).astype(np.float32)
    with kserve.Server(engine) as server:
        futs = [server.submit(r) for r in reqs]
        for f in futs:
            f.result(30.0)
    ids = [f.request_id for f in futs]
    assert ids == sorted(ids) and len(set(ids)) == len(ids)
    assert ids[0] >= 1
    for f in futs:
        p = f.phases
        assert p is not None and p["request_id"] == f.request_id
        for key in kserve.PHASE_KEYS:
            assert key in p, key
        assert p["latency_ms"] > 0
        # the decomposition's parts never exceed the whole (answer slack
        # aside, each phase is a sub-interval of the request's life)
        parts = (
            p["queue_wait_ms"] + p["h2d_ms"] + p["device_wait_ms"]
            + p["execute_ms"] + p["d2h_ms"] + p["answer_ms"]
        )
        assert parts <= p["latency_ms"] * 1.5 + 1.0
        assert p["pad_overhead_ms"] <= p["execute_ms"] + 1e-9
    # aggregation used by serve_bench / results["serving"]
    bd = kserve.phase_breakdown([f.phases for f in futs])
    assert bd["requests"] == len(futs)
    assert bd["queue_wait_ms"]["p99"] >= bd["queue_wait_ms"]["mean"] >= 0


def test_server_stats_exported_into_metrics_registry(rng):
    from keystone_tpu.core import serve as kserve

    engine = _tiny_engine(rng)
    before = trace.metrics.snapshot()["counters"]
    reqs = rng.normal(size=(9, 16)).astype(np.float32)
    with kserve.Server(engine) as server:
        for f in [server.submit(r) for r in reqs]:
            f.result(30.0)
        stats = server.stats
    snap = trace.metrics.snapshot()
    c = snap["counters"]

    def delta(name):
        return c.get(name, 0) - before.get(name, 0)

    assert delta("serve_batches") == stats.batches
    flush_total = sum(
        delta(f"serve_flush_{r}") for r in ("full", "deadline", "idle")
    )
    assert flush_total == (
        stats.flush_full + stats.flush_deadline + stats.flush_idle
    )
    assert delta("serve_padded_rows") == stats.padded_rows
    assert snap["gauges"]["serve_mean_occupancy"] == pytest.approx(
        stats.occupancy(), abs=1e-6
    )
    # one snapshot covers serving: the SLO group is there too
    assert snap["slo"]["phase_probe"]["total"]["requests"] == 9


def test_bucket_retirement_exported(rng):
    sys.path.insert(0, os.path.join(_REPO, "tests"))
    import faults

    engine = _tiny_engine(rng)
    before = trace.metrics.snapshot()["counters"].get(
        "serve_bucket_retired", 0
    )
    engine._retire_bucket(4, "probe retirement")
    snap = trace.metrics.snapshot()
    assert snap["counters"]["serve_bucket_retired"] == before + 1
    assert snap["gauges"]["serve_live_buckets"] == 2
    del faults  # imported only to mirror the suite's path setup


def test_serve_bench_record_gains_phase_and_slo_sections(rng):
    from keystone_tpu.core import serve as kserve

    engine = _tiny_engine(rng)
    reqs = rng.normal(size=(24, 16)).astype(np.float32)
    rec = kserve.serve_bench(
        engine, reqs, clients=3, depth=4, unbatched_baseline=False
    )
    json.dumps(rec)
    bd = rec["phase_breakdown"]
    assert bd["requests"] == 24
    for key in ("queue_wait_ms", "execute_ms", "pad_overhead_ms"):
        assert {"mean", "p99"} <= set(bd[key])
    slo = rec["slo"]
    assert slo["label"] == "phase_probe"
    assert slo["total"]["requests"] == 24
    assert "burn_rate" in slo["window"]
