"""LinearPixels — the simplest image workload: grayscale pixels straight
into a linear solver
(reference src/main/scala/pipelines/images/cifar/LinearPixels.scala:14-55).

Pipeline: CIFAR load -> GrayScaler -> ImageVectorizer -> LinearMapEstimator
-> MaxClassifier -> MulticlassClassifierEvaluator; logs total train/test
accuracy exactly as the reference (:50-51).
"""

from __future__ import annotations

import argparse
import time
from dataclasses import dataclass

import jax.numpy as jnp

from ..core.logging import Logging, configure_logging
from ..core.pipeline import Pipeline
from ..evaluation.multiclass import MulticlassClassifierEvaluator
from ..loaders.cifar import LabeledImageBatch, cifar_loader
from ..ops.images import GrayScaler, ImageVectorizer
from ..ops.util import ClassLabelIndicatorsFromIntLabels, MaxClassifier
from ..parallel.mesh import parse_mesh
from ..solvers.linear import LinearMapEstimator


@dataclass
class LinearPixelsConfig:
    """Flag-parity with the reference scopt config (:57-62)."""

    train_location: str = ""
    test_location: str = ""
    num_classes: int = 10


class _Log(Logging):
    pass


def run(
    conf: LinearPixelsConfig,
    train: LabeledImageBatch,
    test: LabeledImageBatch,
    mesh=None,
) -> dict:
    configure_logging()
    log = _Log()
    t0 = time.perf_counter()

    featurizer = Pipeline([GrayScaler(), ImageVectorizer()])
    train_features = featurizer(jnp.asarray(train.images))
    labels = ClassLabelIndicatorsFromIntLabels(conf.num_classes)(train.labels)

    model = LinearMapEstimator(mesh=mesh).fit(train_features, labels)
    prediction = featurizer.then(model).then(MaxClassifier())

    n_train, n_test = len(train), len(test)
    train_pred = prediction(jnp.asarray(train.images))[:n_train]
    train_eval = MulticlassClassifierEvaluator(
        train_pred, train.labels, conf.num_classes
    )
    test_pred = prediction(jnp.asarray(test.images))[:n_test]
    test_eval = MulticlassClassifierEvaluator(
        test_pred, test.labels, conf.num_classes
    )

    results = {
        "train_accuracy": train_eval.total_accuracy,
        "test_accuracy": test_eval.total_accuracy,
        "seconds": time.perf_counter() - t0,
    }
    log.log_info("Training accuracy: \n%s", results["train_accuracy"])
    log.log_info("Test accuracy: \n%s", results["test_accuracy"])
    return results


def main(argv=None):
    p = argparse.ArgumentParser("LinearPixels")
    p.add_argument("--trainLocation", required=True)
    p.add_argument("--testLocation", required=True)
    p.add_argument(
        "--mesh",
        default=None,
        help="device mesh, e.g. '8' (data) or '4x2' (data x model)",
    )
    a = p.parse_args(argv)
    conf = LinearPixelsConfig(
        train_location=a.trainLocation, test_location=a.testLocation
    )
    train = cifar_loader(conf.train_location)
    test = cifar_loader(conf.test_location)
    return run(conf, train, test, mesh=parse_mesh(a.mesh))


if __name__ == "__main__":
    main()
