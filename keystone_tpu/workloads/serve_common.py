"""Shared serving glue for the workload CLIs (``--serve`` / ``--serveBench``).

Every workload that can checkpoint a fitted SERVABLE pipeline (one
Transformer chain: featurize -> model [-> classifier]) wires the same two
modes through here:

* ``--serve`` — warm-load the ``--pipelineFile`` artifact into a
  :class:`~..core.serve.ServingEngine` (cold start measured: checkpoint
  restore, per-bucket AOT compile, warmup), register it with a
  :class:`~..core.frontend.ShapeRouter` (the production front-end tier —
  ISSUE 12: every workload endpoint is shape-routed, so the serving record
  carries router stats: engines, routes, retires), answer every request
  through the routed online path, and assert the answers BIT-EQUAL the
  offline ``pipeline(x)`` — the smoke proof that the endpoint serves the
  same model it loaded.
* ``--serveBench`` — the SLO bench: N concurrent synthetic clients with
  pipelined depth drive the same endpoint; p50/p99 latency, sustained QPS,
  batcher occupancy, and the batched-vs-unbatched QPS ratio land in
  ``results["serving"]`` (the same record shape bench.py's ``serving``
  section emits).

Bucket/deadline knobs come from the ``KEYSTONE_SERVE_*`` env (see
core.serve / README): the CLI adds client-side shape only
(``--serveClients`` / ``--serveRequests``).
"""

from __future__ import annotations

import logging

import numpy as np

_logger = logging.getLogger("keystone_tpu.workloads.serve")


def add_serve_args(p) -> None:
    """The serving flag block every servable workload CLI shares."""
    p.add_argument(
        "--serve",
        action="store_true",
        help="warm-load --pipelineFile into a serving endpoint "
        "(core.serve: fused per-bucket AOT inference + dynamic request "
        "batcher), answer the test split through it, and assert served "
        "predictions bit-equal the offline apply",
    )
    p.add_argument(
        "--serveBench",
        action="store_true",
        help="the serving SLO bench: concurrent synthetic clients drive "
        "the warm endpoint; reports p50/p99 latency, sustained QPS, "
        "batcher occupancy, and batched-vs-unbatched QPS "
        "(KEYSTONE_SERVE_* env sets buckets / max wait)",
    )
    p.add_argument(
        "--serveClients",
        type=int,
        default=4,
        help="concurrent synthetic clients for --serve/--serveBench",
    )
    p.add_argument(
        "--serveRequests",
        type=int,
        default=256,
        help="max requests drawn from the test split for --serve/--serveBench",
    )
    p.add_argument(
        "--serveMesh",
        default=None,
        metavar="DxM",
        help="serve on an explicit device mesh, e.g. 2x1 — the checkpoint "
        "reshards onto it (topology-portable restore, even when it was "
        "recorded under a different topology) and every bucket "
        "AOT-compiles mesh-native; devices are taken in jax.devices() "
        "order",
    )


def resolve_serve_mesh(spec: str | None):
    """``--serveMesh DxM`` -> a live ``Mesh`` over the first D*M local
    devices (``None`` passes through — single-device serving unchanged)."""
    if spec is None:
        return None
    import jax

    from ..parallel.mesh import make_mesh

    try:
        data, model = (int(s) for s in spec.lower().split("x"))
    except ValueError:
        raise ValueError(
            f"--serveMesh {spec!r}: expected DxM (e.g. 2x1)"
        ) from None
    devs = jax.devices()
    if data * model > len(devs):
        raise ValueError(
            f"--serveMesh {spec}: needs {data * model} devices but this "
            f"process has {len(devs)}"
        )
    return make_mesh(data=data, model=model, devices=devs[: data * model])


def serve_fitted(
    pipeline_file: str,
    example,
    requests: np.ndarray,
    *,
    label: str,
    wrap=None,
    bench: bool = False,
    clients: int = 4,
    timeout: float = 120.0,
    mesh=None,
    log=None,
) -> dict:
    """Warm-load the fitted pipeline and serve ``requests`` through the
    online path; returns the JSON-able serving record (cold start + engine
    summary + either the smoke answers or the full SLO bench).  ``mesh``
    (from ``--serveMesh``) makes the endpoint topology-portable: the
    checkpoint restores through ``load_pipeline(mesh=)`` resharding and
    the engine AOT-compiles mesh-native (ISSUE 16)."""
    from ..core import serve as kserve

    lg = log or _logger
    requests = np.asarray(requests)
    engine, cold = kserve.load_engine(
        pipeline_file, example, label=label, wrap=wrap, mesh=mesh
    )
    record: dict = {"cold_start": cold}
    lg.info(
        "%s: serving cold start %.3fs (restore %.3fs, compile %.3fs, "
        "warmup %.3fs); live buckets %s%s",
        label,
        cold["cold_start_seconds"],
        cold["checkpoint_load_seconds"],
        cold["compile_seconds"],
        cold["warmup_seconds"],
        list(engine.buckets()),
        f"; mesh {cold['mesh']}" if mesh is not None else "",
    )
    if bench:
        record["bench"] = kserve.serve_bench(
            engine, requests, clients=clients, timeout=timeout
        )
        b = record["bench"]
        lg.info(
            "%s: SLO bench — %s requests via %s clients: p50 %.2fms, "
            "p99 %.2fms, %.1f QPS (unbatched %.1f, x%.2f), occupancy "
            "%.2f, bit_identical=%s",
            label, b["requests"], b["clients"], b["p50_latency_ms"],
            b["p99_latency_ms"], b["qps"], b.get("unbatched_qps", 0.0),
            b.get("batched_vs_unbatched_qps", 0.0),
            b["batcher"]["mean_occupancy"], b["predictions_bit_identical"],
        )
    else:
        import time

        from ..core import frontend as kfrontend

        offline = engine.offline(requests)
        t0 = time.perf_counter()
        # The single-engine demo path rides the SAME front-end tier a
        # multi-shape deployment uses: the engine registers with a
        # ShapeRouter and every request is routed by shape, so the
        # serving record proves the router out on every workload (and
        # carries its stats alongside the phase breakdown).
        with kfrontend.ShapeRouter(label=f"{label}_router") as router:
            key = router.add_engine(engine)
            server = router.server_for(key)
            futs = [router.submit(r) for r in requests]
            answers = np.stack([f.result(timeout) for f in futs])
            lat_ms = sorted(f.latency_seconds() * 1e3 for f in futs)
            stats = server.stats.record()
            slo = server.slo.summary()
            router_record = router.record()
        wall = time.perf_counter() - t0
        record["served"] = {
            "requests": int(requests.shape[0]),
            "qps": round(requests.shape[0] / wall, 2),
            "p50_latency_ms": round(kserve._percentile(lat_ms, 0.50), 3),
            "p99_latency_ms": round(kserve._percentile(lat_ms, 0.99), 3),
            "batcher": stats,
            # Per-phase latency decomposition + the live SLO surface
            # (ISSUE 11) — the smoke path reports the same telemetry
            # shape as the full --serveBench record.
            "phase_breakdown": kserve.phase_breakdown(
                [f.phases for f in futs if f.phases is not None]
            ),
            # The front-end tier's view of the same traffic (ISSUE 12):
            # live engines, routes, warm adds, retires, admission ledger.
            "router": router_record,
            "slo": slo,
            "predictions_bit_identical": bool(
                np.array_equal(answers, offline)
            ),
        }
        s = record["served"]
        if not engine.parity_ok:
            # The chain failed eager-parity at warmup (counted
            # serve_parity_unverified): the honest bar is determinism
            # against the engine's own bucketed AOT apply.
            s["parity_unverified"] = True
            s["predictions_deterministic"] = bool(
                np.array_equal(answers, engine.infer(requests))
            )
        lg.info(
            "%s: served %d requests, p50 %.2fms / p99 %.2fms, %.1f QPS, "
            "bit_identical=%s%s",
            label, s["requests"], s["p50_latency_ms"], s["p99_latency_ms"],
            s["qps"], s["predictions_bit_identical"],
            (
                f" (parity unverified; deterministic="
                f"{s['predictions_deterministic']})"
                if not engine.parity_ok
                else ""
            ),
        )
        healthy = s["predictions_bit_identical"] or (
            not engine.parity_ok and s["predictions_deterministic"]
        )
        if not healthy:
            # The typed-or-equal invariant, online: unequal served answers
            # are a contract violation, not a log line.
            raise AssertionError(
                f"{label}: served predictions differ from the offline "
                "pipeline(x) apply — refusing to report a healthy endpoint"
            )
    record["engine"] = engine.record()
    return record
