"""ImageNetSiftLcsFV — the north-star workload: SIFT + LCS Fisher-vector
features, 256k-dim class-weighted block solve, top-5 error
(reference src/main/scala/pipelines/images/imagenet/ImageNetSiftLcsFV.scala:25-268).

Per branch (SIFT / LCS):
  featurize -> [SIFT: signed-sqrt] -> PCA(descDim) fit-or-load -> BatchPCA ->
  GMM(vocabSize) fit-or-load -> FisherVector -> vectorize -> L2 -> signed-sqrt
  -> L2.
Branches are concatenated (ZipVectors) and solved with
BlockWeightedLeastSquares(4096, 1, λ, w); evaluation is top-5 error.
"""

from __future__ import annotations

import argparse
import time
from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from ..core import optimize, trace
from ..core.checkpoint import checkpoint_exists, load_pipeline, save_pipeline
from ..core.ingest import stream_batches
from ..core.logging import Logging, configure_logging, stage_timer
from ..core.memory import log_fit_report
from ..core.resilience import assert_all_finite
from ..loaders.image_loaders import (
    LabeledImages,
    imagenet_labels_map,
    imagenet_loader,
)
from ..ops.lcs import LCSExtractor
from ..ops.sift import SIFTExtractor
from ..ops.stats import SignedHellingerMapper
from ..ops.util import ClassLabelIndicatorsFromIntLabels, TopKClassifier
from ..parallel.mesh import parse_mesh
from ..solvers.gmm import GaussianMixtureModel, GaussianMixtureModelEstimator
from ..solvers.pca import BatchPCATransformer, compute_pca
from ..solvers.weighted import BlockWeightedLeastSquaresEstimator
from ..utils.stats import get_err_percent
from .fv_common import (
    collect_autotune,
    fisher_feature_pipeline,
    grayscale,
    plan_pca_materialization,
    record_stream_autotune,
    sample_columns,
    scatter_features,
    searched_bucket_featurize,
    stream_config_from_flags,
    stream_descriptor_buckets,
)

# Hard cap on the GMM EM training set (reference ImageNetSiftLcsFV.scala:85-86).
GMM_FIT_CAP = 1_000_000


@dataclass
class ImageNetStreamSource:
    """Streaming stand-in for :class:`LabeledImages` (core.ingest): each
    descriptor branch streams the tar — decode of batch *i+1* overlaps the
    device featurize of batch *i* — instead of decoding everything into
    host RAM first.  Both branches must observe the SAME survivor order
    (features are concatenated row-wise), which :meth:`record_names`
    asserts across passes."""

    data_path: str
    labels_path: str
    batch_size: int = 32
    #: closed-loop ingest autotuner on this source's streams (--autoTune)
    autotune: bool = False
    #: decode backend (--decodeBackend): None defers to env
    decode_backend: str | None = None
    #: snapshot cache root (--snapshotDir): decoded chunks keyed by tar +
    #: decode config + the synset filter's label-file identity
    snapshot_dir: str | None = None
    #: device-resident decode (--deviceDecode): entropy pass on the host,
    #: pixels born on-device fused into each descriptor branch
    device_decode: bool = False

    def __post_init__(self):
        self._names: list | None = None
        self._labels_map: dict | None = None

    @property
    def images(self) -> "ImageNetStreamSource":
        return self

    def labels_map(self) -> dict:
        if self._labels_map is None:
            self._labels_map = imagenet_labels_map(self.labels_path)
        return self._labels_map

    def record_names(self, names: list) -> None:
        if self._names is None:
            self._names = names
        elif self._names != names:
            raise RuntimeError(
                "streaming ingest order drifted between descriptor passes "
                f"({len(self._names)} vs {len(names)} survivors) — the "
                "SIFT and LCS branches would zip features of different "
                "images"
            )

    @property
    def labels(self) -> np.ndarray:
        if self._names is None:
            raise RuntimeError(
                "ImageNetStreamSource.labels before the descriptor pass"
            )
        lm = self.labels_map()
        return np.asarray(
            [lm[n.split("/")[0]] for n in self._names], np.int32
        )

    def __len__(self) -> int:
        if self._names is None:
            raise RuntimeError(
                "len(ImageNetStreamSource) before the descriptor pass"
            )
        return len(self._names)


def _streaming_buckets(src: ImageNetStreamSource, per_batch) -> dict:
    """One branch's descriptor pass over the stream (synset-filtered)."""
    lm = src.labels_map()

    def keep(name: str) -> bool:
        return name.split("/")[0] in lm

    # The synset filter derives from the labels file — its identity keys
    # the snapshot (a changed labels file changes the survivor set).
    # Computed unconditionally (one os.stat): inert when snapshots are
    # off, and an env-only KEYSTONE_SNAPSHOT_DIR is never silently inert.
    from ..core import snapshot as ksnap

    extra = f"imagenet:{ksnap.file_identity(src.labels_path)}"
    cfg = stream_config_from_flags(
        autotune=src.autotune,
        decode_backend=src.decode_backend,
        snapshot_dir=src.snapshot_dir,
        snapshot_extra=extra,
        device_decode=src.device_decode,
    )
    with stream_batches(
        src.data_path, src.batch_size, keep=keep, config=cfg
    ) as st:
        buckets, names = stream_descriptor_buckets(st, per_batch)
    src.record_names(names)
    record_stream_autotune(src, st)
    return buckets


@dataclass
class ImageNetSiftLcsFVConfig:
    """Flag-parity with the reference scopt config (:195-224)."""

    train_location: str = ""
    test_location: str = ""
    label_path: str = ""
    lam: float = 6e-5
    mixture_weight: float = 0.25
    desc_dim: int = 64
    vocab_size: int = 16
    sift_scale_step: int = 1
    lcs_stride: int = 4
    lcs_border: int = 16
    lcs_patch: int = 6
    sift_pca_file: str | None = None
    sift_gmm_mean_file: str | None = None
    sift_gmm_var_file: str | None = None
    sift_gmm_wts_file: str | None = None
    lcs_pca_file: str | None = None
    lcs_gmm_mean_file: str | None = None
    lcs_gmm_var_file: str | None = None
    lcs_gmm_wts_file: str | None = None
    num_pca_samples: int = int(1e7)
    num_gmm_samples: int = int(1e7)
    num_classes: int = 1000
    seed: int = 42
    # Whole-fitted-pipeline checkpoint stem (core.checkpoint): both
    # branches' PCA + GMM plus the weighted block solve in one artifact.
    pipeline_file: str | None = None
    # Cost-based auto-Cacher (core.optimize): per-branch probe-measured
    # decision on whether PCA-projected descriptors stay resident through
    # the GMM EM fit or are re-projected per consumer under a tight HBM
    # budget.  Decision tables in results["cache_plan"].
    auto_cache: bool = False
    # Placement search (core.autoshard): force the cost-model-ranked
    # candidate search for the weighted block solve (on by default via
    # KEYSTONE_AUTOSHARD); the searched table lands in
    # results["placement"] whenever a search ran.
    auto_shard: bool = False


class _Log(Logging):
    pass


def _fit_branch(
    conf: ImageNetSiftLcsFVConfig, desc_buckets: dict, pca_file, gmm_files,
    seed: int, label: str = "branch", mesh=None,
):
    """Fit (or load) the branch's PCA + GMM from TRAIN descriptors only —
    the reference fits once and applies the same featurizer to test
    (ImageNetSiftLcsFV.scala:69,91,145).

    Returns (batch_pca, gmm, train_pca_desc, cache_plan): the PCA-projected
    train buckets are returned so callers never re-project the training
    set.  With ``conf.auto_cache`` the optimizer decides whether that
    projection stays resident through the GMM EM fit (the HBM-heavy phase)
    or is deferred and re-projected — the reference's always-cache becomes
    a measured choice; ``cache_plan`` is the decision table (None when the
    pass is off)."""
    if pca_file is not None:
        pca_mat = jnp.asarray(
            np.loadtxt(pca_file, delimiter=",", ndmin=2).T, jnp.float32
        )
    else:
        samples = sample_columns(desc_buckets, conf.num_pca_samples, seed)
        pca_mat = compute_pca(samples.T, conf.desc_dim)
    batch_pca = BatchPCATransformer(pca_mat)

    def make_pca_desc() -> dict:
        return {
            shape: (idx, batch_pca(descs))
            for shape, (idx, descs) in desc_buckets.items()
        }

    mean_f, var_f, wts_f = gmm_files
    cache_plan = None
    materialize = True
    if conf.auto_cache:
        reuse = (0 if mean_f is not None else 1) + 1
        cache_plan, materialize = plan_pca_materialization(
            desc_buckets, batch_pca, reuse, mesh=mesh,
            label=f"{label}_pca_descriptors",
        )
    pca_desc = make_pca_desc() if materialize else None

    if mean_f is not None:
        gmm = GaussianMixtureModel.load(mean_f, var_f, wts_f)
    else:
        gmm_samples = sample_columns(
            pca_desc if pca_desc is not None else make_pca_desc(),
            conf.num_gmm_samples, seed + 1,
        )
        # The reference caps the EM training set at 1e6 samples regardless of
        # numGmmSamples (shuffleArray(...).take(1e6),
        # ImageNetSiftLcsFV.scala:85-86) — match it to bound EM compute/HBM.
        if gmm_samples.shape[1] > GMM_FIT_CAP:
            gmm_samples = gmm_samples[:, :GMM_FIT_CAP]
        gmm = GaussianMixtureModelEstimator(conf.vocab_size).fit(gmm_samples.T)
    assert_all_finite(gmm, "branch GMM fit")

    if pca_desc is None:
        # Deferred projection: materialized only now, AFTER the EM fit
        # released its working set — the recompute the plan priced in.
        pca_desc = make_pca_desc()
    return batch_pca, gmm, pca_desc, cache_plan


def sift_descriptor_buckets(
    conf: ImageNetSiftLcsFVConfig, images: list, mesh=None,
    placement_out=None,
) -> dict:
    """SIFT branch descriptors (:40-94): SIFT -> BatchSignedHellinger.
    With a mesh the bucket placement is chosen by the cost-model-ranked
    search (fv_common.searched_bucket_featurize; the hand row-sharded
    layout is the untrained head); ``placement_out`` receives the searched
    record under ``"featurize_sift"``."""
    # bf16 intermediates: measured +35% chain throughput at 99.5%-within-1
    # quantized-descriptor agreement (see SIFTExtractor docstring) — the
    # throughput workload opts in; the op default stays f32.
    sift = SIFTExtractor(
        scale_step=conf.sift_scale_step, compute_dtype=jnp.bfloat16
    )
    hell = SignedHellingerMapper()
    if isinstance(images, ImageNetStreamSource):
        return _streaming_buckets(
            images, lambda dev: hell(sift(grayscale(dev)))
        )
    buckets, placement = searched_bucket_featurize(
        "imagenet_sift_featurize", images,
        lambda dev: hell(sift(grayscale(dev))), mesh,
    )
    if placement_out is not None and placement is not None:
        placement_out["featurize_sift"] = placement
    return buckets


def lcs_descriptor_buckets(
    conf: ImageNetSiftLcsFVConfig, images: list, mesh=None,
    placement_out=None,
) -> dict:
    """LCS branch descriptors (:96-148): raw LCS straight into PCA, with
    the searched bucket placement under a mesh (record lands in
    ``placement_out["featurize_lcs"]``)."""
    lcs = LCSExtractor(conf.lcs_stride, conf.lcs_border, conf.lcs_patch)
    if isinstance(images, ImageNetStreamSource):
        return _streaming_buckets(images, lcs)
    buckets, placement = searched_bucket_featurize(
        "imagenet_lcs_featurize", images, lcs, mesh,
    )
    if placement_out is not None and placement is not None:
        placement_out["featurize_lcs"] = placement
    return buckets


def branch_features(
    conf: ImageNetSiftLcsFVConfig,
    train_images: list,
    test_images: list,
    descriptor_fn,
    pca_file,
    gmm_files,
    seed: int,
    mesh=None,
    placement_out=None,
):
    """Fit transformers on train, apply to train AND test.  Returns the
    fitted (batch_pca, gmm) too so callers can checkpoint the branch, and
    the auto-Cacher decision table (None when the pass is off).
    ``placement_out``: dict receiving the train pass's searched featurize
    placement record (see the descriptor functions)."""
    train_desc = descriptor_fn(
        conf, train_images, mesh, placement_out=placement_out
    )
    batch_pca, gmm, train_pca_desc, cache_plan = _fit_branch(
        conf, train_desc, pca_file, gmm_files, seed,
        label=descriptor_fn.__name__.replace("_descriptor_buckets", ""),
        mesh=mesh,
    )
    fisher = fisher_feature_pipeline(gmm)
    feat_dim = 2 * conf.desc_dim * conf.vocab_size
    train_feats = scatter_features(
        train_pca_desc, fisher, len(train_images), feat_dim
    )
    test_desc = descriptor_fn(conf, test_images, mesh)
    test_feats = scatter_features(
        test_desc, lambda d: fisher(batch_pca(d)), len(test_images), feat_dim
    )
    return train_feats, test_feats, batch_pca, gmm, cache_plan


def branch_test_features(
    conf: ImageNetSiftLcsFVConfig,
    test_images: list,
    descriptor_fn,
    batch_pca,
    gmm,
    mesh=None,
):
    """Apply an already-fitted branch (restored from a checkpoint) to test
    images only — the reload half of load-or-fit."""
    fisher = fisher_feature_pipeline(gmm)
    feat_dim = 2 * conf.desc_dim * conf.vocab_size
    test_desc = descriptor_fn(conf, test_images, mesh)
    return scatter_features(
        test_desc, lambda d: fisher(batch_pca(d)), len(test_images), feat_dim
    )


def run(
    conf: ImageNetSiftLcsFVConfig,
    train: LabeledImages,
    test: LabeledImages,
    mesh=None,
) -> dict:
    """With ``mesh``: featurization buckets are row-sharded over the data
    axis and the 2·2·descDim·vocabSize-feature class-weighted solve runs
    distributed — row-sharded population grams with ICI all-reduce and
    model-axis-sharded batched class solves (the reference runs this over
    partitioned RDDs + treeReduce, ImageNetSiftLcsFV.scala:150-195)."""
    configure_logging()
    log = _Log()
    t0 = time.perf_counter()

    sift_plan = lcs_plan = placement_rec = None
    feat_placements: dict = {}
    if conf.pipeline_file is not None and checkpoint_exists(conf.pipeline_file):
        # Load-or-fit of the whole fitted pipeline: skip training
        # featurization and every fit; score test with restored state.
        log.log_info("restoring fitted pipeline from %s", conf.pipeline_file)
        ck = load_pipeline(conf.pipeline_file)
        test_sift = branch_test_features(
            conf, test.images, sift_descriptor_buckets,
            ck["sift_pca"], ck["sift_gmm"], mesh,
        )
        test_lcs = branch_test_features(
            conf, test.images, lcs_descriptor_buckets,
            ck["lcs_pca"], ck["lcs_gmm"], mesh,
        )
        model = ck["model"]
        test_features = jnp.asarray(
            np.concatenate([test_sift, test_lcs], axis=1)
        )
    else:
        with stage_timer("sift_branch"):
            train_sift, test_sift, sift_pca, sift_gmm, sift_plan = branch_features(
                conf,
                train.images,
                test.images,
                sift_descriptor_buckets,
                conf.sift_pca_file,
                (conf.sift_gmm_mean_file, conf.sift_gmm_var_file, conf.sift_gmm_wts_file),
                conf.seed,
                mesh,
                placement_out=feat_placements,
            )
        with stage_timer("lcs_branch"):
            train_lcs, test_lcs, lcs_pca, lcs_gmm, lcs_plan = branch_features(
                conf,
                train.images,
                test.images,
                lcs_descriptor_buckets,
                conf.lcs_pca_file,
                (conf.lcs_gmm_mean_file, conf.lcs_gmm_var_file, conf.lcs_gmm_wts_file),
                conf.seed + 100,
                mesh,
                placement_out=feat_placements,
            )

        # ZipVectors (:179-183) — kept host-side; the solver shards its blocks
        train_features = np.concatenate([train_sift, train_lcs], axis=1)
        test_features = jnp.asarray(np.concatenate([test_sift, test_lcs], axis=1))

        labels = ClassLabelIndicatorsFromIntLabels(conf.num_classes)(train.labels)

        # 2·2·descDim·vocabSize features (:186-188)
        with stage_timer("solve"):
            solver = BlockWeightedLeastSquaresEstimator(
                4096, 1, conf.lam, conf.mixture_weight, mesh=mesh
            )
            model = solver.fit(
                train_features, labels,
                num_features=2 * 2 * conf.desc_dim * conf.vocab_size,
                plan=True if conf.auto_shard else None,
            )
            log_fit_report(solver, label="ImageNet weighted block solve")
            assert_all_finite(model, "ImageNet weighted block solve")
            rep = solver.last_fit_report
            placement_rec = rep.placement if rep is not None else None

        if conf.pipeline_file is not None:
            save_pipeline(
                conf.pipeline_file,
                {
                    "sift_pca": sift_pca,
                    "sift_gmm": sift_gmm,
                    "lcs_pca": lcs_pca,
                    "lcs_gmm": lcs_gmm,
                    "model": model,
                },
            )
            log.log_info("saved fitted pipeline to %s", conf.pipeline_file)

    with stage_timer("eval"):
        test_scores = model(test_features)
        k = min(5, conf.num_classes)
        topk = np.asarray(TopKClassifier(k)(test_scores))
        err = get_err_percent(topk, test.labels, k)
    results = {
        "top5_err_percent": err,
        "top1_err_percent": get_err_percent(topk, test.labels, 1),
        "seconds": time.perf_counter() - t0,
    }
    plans = {
        name: plan.record()
        for name, plan in (("sift", sift_plan), ("lcs", lcs_plan))
        if plan is not None
    }
    if plans:
        results["cache_plan"] = plans
        for name, plan in (("sift", sift_plan), ("lcs", lcs_plan)):
            if plan is not None:
                log.log_info("%s branch %s", name, plan.summary())
    if feat_placements:
        # The searched FEATURIZE placements (per descriptor branch) next
        # to the solve's — one audit home for every ranked placement.
        results["placement"] = {"solver": placement_rec, **feat_placements}
    elif placement_rec is not None:
        # The searched placement table for the weighted block solve —
        # candidates, deny/score rationale, predicted-vs-actual cost.
        results["placement"] = placement_rec
    autotune = collect_autotune(train, test)
    if autotune:
        results["autotune"] = autotune
    log.log_info("TEST Top-%d error is: %s %%", k, err)
    return results


def main(argv=None):
    p = argparse.ArgumentParser("ImageNetSiftLcsFV")
    p.add_argument("--trainLocation", required=True)
    p.add_argument("--testLocation", required=True)
    p.add_argument("--labelPath", required=True)
    p.add_argument("--lambda", dest="lam", type=float, default=6e-5)
    p.add_argument("--mixtureWeight", type=float, default=0.25)
    p.add_argument("--descDim", type=int, default=64)
    p.add_argument("--vocabSize", type=int, default=16)
    p.add_argument("--siftScaleStep", type=int, default=1)
    p.add_argument("--lcsStride", type=int, default=4)
    p.add_argument("--lcsBorder", type=int, default=16)
    p.add_argument("--lcsPatch", type=int, default=6)
    p.add_argument("--numPcaSamples", type=int, default=int(1e7))
    p.add_argument("--numGmmSamples", type=int, default=int(1e7))
    p.add_argument("--numClasses", type=int, default=1000)
    p.add_argument(
        "--pipelineFile",
        default=None,
        help="fitted-pipeline checkpoint stem: load-or-fit of both branches' "
        "PCA+GMM and the weighted solve",
    )
    p.add_argument(
        "--streamIngest",
        action="store_true",
        help="streaming ingest (core.ingest): decode tars WHILE the device "
        "featurizes, instead of decoding everything first",
    )
    p.add_argument(
        "--streamBatchSize",
        type=int,
        default=32,
        help="images per streamed device batch (--streamIngest only)",
    )
    p.add_argument(
        "--autoCache",
        action="store_true",
        help="cost-based auto-Cacher (core.optimize): per-branch "
        "probe-measured decision on PCA-descriptor residency vs "
        "re-projection (KEYSTONE_AUTOCACHE=1 equivalent)",
    )
    p.add_argument(
        "--autoShard",
        action="store_true",
        help="placement search (core.autoshard): force the cost-model "
        "ranked mesh/strategy candidate search for the weighted block "
        "solve and record the searched plan in results['placement'] (on "
        "by default; KEYSTONE_AUTOSHARD=0 disables it except here)",
    )
    p.add_argument(
        "--autoTune",
        action="store_true",
        help="closed-loop ingest autotuner on --streamIngest streams: "
        "retune decode width / ring depth / decode-ahead mid-stream "
        "(KEYSTONE_AUTOTUNE=1 equivalent)",
    )
    p.add_argument(
        "--decodeBackend",
        default=None,
        choices=("thread", "process"),
        help="decode backend for --streamIngest: 'process' decodes on "
        "spawned worker processes via shared memory "
        "(KEYSTONE_DECODE_BACKEND equivalent)",
    )
    p.add_argument(
        "--snapshotDir",
        default=None,
        help="snapshot cache root for --streamIngest streams "
        "(core.snapshot): first pass materializes decoded chunks, repeat "
        "runs stream the shards at IO speed "
        "(KEYSTONE_SNAPSHOT_DIR equivalent)",
    )
    p.add_argument(
        "--deviceDecode",
        action="store_true",
        help="device-resident JPEG decode for --streamIngest "
        "(ops.jpeg_device): host entropy pass only, pixels born on-device "
        "fused into each descriptor branch; unsupported JPEGs fall back "
        "to host decode counted per reason (KEYSTONE_DEVICE_DECODE=1 "
        "equivalent)",
    )
    p.add_argument(
        "--mesh",
        default=None,
        help="device mesh, e.g. '8' (data) or '4x2' (data x model)",
    )
    p.add_argument(
        "--trace",
        default=None,
        metavar="PATH",
        help="write a Chrome-trace JSON (Perfetto-loadable; .jsonl for the "
        "JSONL event log) of the run — the KEYSTONE_TRACE env equivalent",
    )
    for flag in (
        "siftPcaFile", "siftGmmMeanFile", "siftGmmVarFile", "siftGmmWtsFile",
        "lcsPcaFile", "lcsGmmMeanFile", "lcsGmmVarFile", "lcsGmmWtsFile",
    ):
        p.add_argument(f"--{flag}", default=None)
    a = p.parse_args(argv)
    if a.trace:
        trace.enable(a.trace)
    conf = ImageNetSiftLcsFVConfig(
        train_location=a.trainLocation,
        test_location=a.testLocation,
        label_path=a.labelPath,
        lam=a.lam,
        mixture_weight=a.mixtureWeight,
        desc_dim=a.descDim,
        vocab_size=a.vocabSize,
        sift_scale_step=a.siftScaleStep,
        lcs_stride=a.lcsStride,
        lcs_border=a.lcsBorder,
        lcs_patch=a.lcsPatch,
        sift_pca_file=a.siftPcaFile,
        sift_gmm_mean_file=a.siftGmmMeanFile,
        sift_gmm_var_file=a.siftGmmVarFile,
        sift_gmm_wts_file=a.siftGmmWtsFile,
        lcs_pca_file=a.lcsPcaFile,
        lcs_gmm_mean_file=a.lcsGmmMeanFile,
        lcs_gmm_var_file=a.lcsGmmVarFile,
        lcs_gmm_wts_file=a.lcsGmmWtsFile,
        num_pca_samples=a.numPcaSamples,
        num_gmm_samples=a.numGmmSamples,
        num_classes=a.numClasses,
        pipeline_file=a.pipelineFile,
        auto_cache=a.autoCache or optimize.auto_cache_env(),
        auto_shard=a.autoShard,
    )
    if conf.pipeline_file is not None and checkpoint_exists(conf.pipeline_file):
        # Restored runs never touch training data — skip decoding the
        # entire training tar set (the dominant reload-path cost).
        train = LabeledImages([], np.zeros(0, np.int32), [])
    elif a.streamIngest:
        train = ImageNetStreamSource(
            conf.train_location, conf.label_path,
            batch_size=a.streamBatchSize, autotune=a.autoTune,
            decode_backend=a.decodeBackend, snapshot_dir=a.snapshotDir,
            device_decode=a.deviceDecode,
        )
    else:
        train = imagenet_loader(conf.train_location, conf.label_path)
    if a.streamIngest:
        test = ImageNetStreamSource(
            conf.test_location, conf.label_path,
            batch_size=a.streamBatchSize, autotune=a.autoTune,
            decode_backend=a.decodeBackend, snapshot_dir=a.snapshotDir,
            device_decode=a.deviceDecode,
        )
    else:
        test = imagenet_loader(conf.test_location, conf.label_path)
    try:
        return run(conf, train, test, mesh=parse_mesh(a.mesh))
    finally:
        if a.trace:
            trace.flush()


if __name__ == "__main__":
    main()
