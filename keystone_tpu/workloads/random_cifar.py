"""RandomCifar — random (unwhitened) convolutional filters + linear solve
(reference src/main/scala/pipelines/images/cifar/RandomCifar.scala:17-70).

Like RandomPatchCifar but the filter bank is i.i.d. Gaussian instead of
ZCA-whitened patches, and the solver is a single LinearMapEstimator rather
than the blocked BCD: CIFAR load -> [Convolver(random filters, patch
normalization) -> SymmetricRectifier -> Pooler -> ImageVectorizer ->
StandardScaler] -> LinearMapEstimator(λ) -> MaxClassifier -> evaluator.
"""

from __future__ import annotations

import argparse
import time
from dataclasses import dataclass

import jax
import numpy as np

from ..core.logging import Logging, configure_logging
from ..core.pipeline import Pipeline
from ..core.resilience import assert_all_finite, numerics_guard_enabled
from ..evaluation.multiclass import MulticlassClassifierEvaluator
from ..loaders.cifar import LabeledImageBatch, cifar_loader
from ..ops.images import (
    Convolver,
    ImageVectorizer,
    Pooler,
    SymmetricRectifier,
)
from ..ops.stats import StandardScaler
from ..ops.util import ClassLabelIndicatorsFromIntLabels, MaxClassifier
from ..parallel.mesh import parse_mesh
from ..solvers.linear import LinearMapEstimator
from .cifar_random_patch import featurize_chunked


@dataclass
class RandomCifarWorkloadConfig:
    """Flag-parity with the reference scopt config (:72-95)."""

    train_location: str = ""
    test_location: str = ""
    num_filters: int = 100
    patch_size: int = 6
    pool_size: int = 14
    pool_stride: int = 13
    alpha: float = 0.25
    lam: float | None = None
    sample_frac: float | None = None
    seed: int = 42
    num_classes: int = 10
    num_channels: int = 3
    featurize_chunk: int = 2048


class _Log(Logging):
    pass


def run(
    conf: RandomCifarWorkloadConfig,
    train: LabeledImageBatch,
    test: LabeledImageBatch,
    mesh=None,
) -> dict:
    configure_logging()
    log = _Log()
    t0 = time.perf_counter()

    if conf.sample_frac is not None:
        rng = np.random.default_rng(conf.seed)
        keep = rng.random(len(train)) < conf.sample_frac
        train = LabeledImageBatch(train.images[keep], train.labels[keep])

    # Random Gaussian filter bank (reference :33: DenseMatrix.rand gaussian).
    key = jax.random.PRNGKey(conf.seed)
    filters = jax.random.normal(
        key,
        (
            conf.num_filters,
            conf.patch_size * conf.patch_size * conf.num_channels,
        ),
    )

    conv_pipe = Pipeline(
        [
            Convolver(
                filters,
                normalize_patches=True,
                img_channels=conf.num_channels,
            ),
            SymmetricRectifier(alpha=conf.alpha),
            Pooler(conf.pool_stride, conf.pool_size, None, "sum"),
            ImageVectorizer(),
        ]
    )
    feat_fn = jax.jit(conv_pipe.__call__)

    train_conv = featurize_chunked(
        feat_fn, train.images, conf.featurize_chunk, mesh=mesh
    )
    scaler = StandardScaler().fit(train_conv)
    train_features = scaler(train_conv)

    labels = ClassLabelIndicatorsFromIntLabels(conf.num_classes)(train.labels)
    model = LinearMapEstimator(lam=conf.lam, mesh=mesh).fit(train_features, labels)
    if numerics_guard_enabled():
        # Typed failure (FloatingPointError) instead of NaN predictions.
        assert_all_finite(model, "random-cifar model")

    def predict(features):
        return MaxClassifier()(model(features))

    train_eval = MulticlassClassifierEvaluator(
        predict(train_features)[: len(train)], train.labels, conf.num_classes
    )
    test_conv = featurize_chunked(
        feat_fn, test.images, conf.featurize_chunk, mesh=mesh
    )
    test_eval = MulticlassClassifierEvaluator(
        predict(scaler(test_conv))[: len(test)], test.labels, conf.num_classes
    )

    results = {
        "train_error": 100.0 * train_eval.total_error,
        "test_error": 100.0 * test_eval.total_error,
        "seconds": time.perf_counter() - t0,
    }
    log.log_info("Training error is: %s", train_eval.total_error)
    log.log_info("Test error is: %s", test_eval.total_error)
    return results


def main(argv=None):
    p = argparse.ArgumentParser("RandomCifar")
    p.add_argument("--trainLocation", required=True)
    p.add_argument("--testLocation", required=True)
    p.add_argument("--numFilters", type=int, default=100)
    p.add_argument("--patchSize", type=int, default=6)
    p.add_argument("--poolSize", type=int, default=14)
    p.add_argument("--poolStride", type=int, default=13)
    p.add_argument("--alpha", type=float, default=0.25)
    p.add_argument("--lambda", dest="lam", type=float, default=None)
    p.add_argument("--sampleFrac", type=float, default=None)
    p.add_argument(
        "--mesh",
        default=None,
        help="device mesh, e.g. '8' (data) or '4x2' (data x model)",
    )
    a = p.parse_args(argv)
    conf = RandomCifarWorkloadConfig(
        train_location=a.trainLocation,
        test_location=a.testLocation,
        num_filters=a.numFilters,
        patch_size=a.patchSize,
        pool_size=a.poolSize,
        pool_stride=a.poolStride,
        alpha=a.alpha,
        lam=a.lam,
        sample_frac=a.sampleFrac,
    )
    train = cifar_loader(conf.train_location)
    test = cifar_loader(conf.test_location)
    return run(conf, train, test, mesh=parse_mesh(a.mesh))


if __name__ == "__main__":
    main()
