"""StupidBackoffPipeline — n-gram language model training
(reference src/main/scala/pipelines/nlp/StupidBackoffPipeline.scala:9-59).

Flow: text lines -> Tokenizer -> WordFrequencyEncoder fit + encode ->
NGramsFeaturizer(2..n) -> NGramsCounts(noAdd) -> StupidBackoffEstimator ->
scores.  Prints corpus statistics and the first 100 trained scores exactly
as the reference (:45-53).

``--numParts`` keeps flag parity with the reference, where it controls the
InitialBigramPartitioner shuffle (StupidBackoff.scala:25-58); here scoring
is host-local, so the flag drives the same sharding function
(``shard_by_initial_bigram``) to report the shard layout a multi-host run
would use — and to assert the co-location invariant (every ngram on the
same shard as its scoring context).
"""

from __future__ import annotations

import argparse
import time
from collections import Counter
from dataclasses import dataclass

from ..core.logging import Logging, configure_logging
from ..ops.ngram_lm import (
    NGramIndexerImpl,
    NGramsCounts,
    StupidBackoffEstimator,
    shard_by_initial_bigram,
)
from ..ops.nlp import NGramsFeaturizer, Tokenizer, fit_word_frequency_encoder


@dataclass
class StupidBackoffConfig:
    """Flag-parity with the reference scopt config (:13-21)."""

    train_data: str = ""
    num_parts: int = 16
    n: int = 3


class _Log(Logging):
    pass


def run(conf: StupidBackoffConfig, lines: list) -> dict:
    configure_logging()
    log = _Log()
    t0 = time.perf_counter()

    text = Tokenizer()(lines)

    # Vocab generation step (:33-35)
    frequency_encode = fit_word_frequency_encoder(text)
    unigram_counts = frequency_encode.unigram_counts

    # NGram (n >= 2) generation step (:37-42)
    encoded = frequency_encode(text)
    ngrams = NGramsFeaturizer(range(2, conf.n + 1))(encoded)
    ngram_counts = NGramsCounts("noAdd")(ngrams)

    # Stupid backoff scoring step (:44-46)
    language_model = StupidBackoffEstimator(unigram_counts).fit(ngram_counts)
    scores = language_model.scores()

    # Shard layout a multi-host run would use (InitialBigramPartitioner):
    # every ngram must land with its scoring context (same first two words).
    indexer = NGramIndexerImpl()
    shard_sizes = Counter()
    for ngram in language_model.ngram_counts:
        shard = shard_by_initial_bigram(ngram, conf.num_parts, indexer)
        shard_sizes[shard] += 1
        if indexer.ngram_order(ngram) > 2:
            context = indexer.remove_current_word(ngram)
            if shard_by_initial_bigram(context, conf.num_parts, indexer) != shard:
                raise ValueError(
                    f"ngram {ngram} not co-located with context {context}"
                )

    results = {
        "num_tokens": language_model.num_tokens,
        "vocab_size": len(unigram_counts),
        "num_ngrams": len(scores),
        "shard_sizes": dict(shard_sizes),
        "seconds": time.perf_counter() - t0,
    }
    log.log_info(
        "number of tokens: %s\nsize of vocabulary: %s\nnumber of ngrams: %s",
        results["num_tokens"],
        results["vocab_size"],
        results["num_ngrams"],
    )
    log.log_info("trained scores of 100 ngrams in the corpus:")
    for ngram, score in list(scores.items())[:100]:
        log.log_info("%s -> %.6f", ngram, score)
    return results


def main(argv=None):
    p = argparse.ArgumentParser("StupidBackoffPipeline")
    p.add_argument("--trainData", required=True)
    p.add_argument("--numParts", type=int, default=16)
    p.add_argument("--n", type=int, default=3)
    a = p.parse_args(argv)
    conf = StupidBackoffConfig(train_data=a.trainData, num_parts=a.numParts, n=a.n)
    with open(conf.train_data, encoding="utf-8") as fh:
        lines = [ln.rstrip("\n") for ln in fh if ln.strip()]
    return run(conf, lines)


if __name__ == "__main__":
    main()
