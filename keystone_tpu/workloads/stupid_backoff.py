"""StupidBackoffPipeline — n-gram language model training
(reference src/main/scala/pipelines/nlp/StupidBackoffPipeline.scala:9-59).

Flow: text lines -> Tokenizer -> WordFrequencyEncoder fit + encode ->
NGramsFeaturizer(2..n) -> NGramsCounts(noAdd) -> StupidBackoffEstimator ->
scores.  Prints corpus statistics and the first 100 trained scores exactly
as the reference (:45-53).

``--numParts`` drives the reference's InitialBigramPartitioner layout
(StupidBackoff.scala:25-58) as an EXECUTABLE scoring path
(``ops.ngram_lm.sharded_scores``): the count table is partitioned by
initial bigram, each shard scores its ngrams against only shard-local
counts (plus the broadcast unigram table), and backoffs that shorten past
a shard's key are re-routed between rounds — the multi-host shuffle, run
host-locally.  The run asserts the sharded scores equal the single-table
model's bit-for-bit, which is the co-location invariant made a test
rather than a comment.
"""

from __future__ import annotations

import argparse
import time
from collections import Counter
from dataclasses import dataclass

from ..core.logging import Logging, configure_logging
from ..ops.ngram_lm import (
    NGramsCounts,
    StupidBackoffEstimator,
    sharded_scores,
)
from ..ops.nlp import NGramsFeaturizer, Tokenizer, fit_word_frequency_encoder


@dataclass
class StupidBackoffConfig:
    """Flag-parity with the reference scopt config (:13-21)."""

    train_data: str = ""
    num_parts: int = 16
    n: int = 3


class _Log(Logging):
    pass


def run(conf: StupidBackoffConfig, lines: list) -> dict:
    configure_logging()
    log = _Log()
    t0 = time.perf_counter()

    text = Tokenizer()(lines)

    # Vocab generation step (:33-35)
    frequency_encode = fit_word_frequency_encoder(text)
    unigram_counts = frequency_encode.unigram_counts

    # NGram (n >= 2) generation step (:37-42)
    encoded = frequency_encode(text)
    ngrams = NGramsFeaturizer(range(2, conf.n + 1))(encoded)
    ngram_counts = NGramsCounts("noAdd")(ngrams)

    # Stupid backoff scoring step (:44-46)
    language_model = StupidBackoffEstimator(unigram_counts).fit(ngram_counts)
    scores = language_model.scores()

    # The sharded scoring path (InitialBigramPartitioner, executable):
    # partition counts by initial bigram, score shard-locally with backoff
    # re-routing between rounds, and hold it to the single-table oracle.
    shard_scores, shard_sizes = sharded_scores(
        language_model.ngram_counts,
        unigram_counts,
        conf.num_parts,
        alpha=language_model.alpha,
    )
    if shard_scores != scores:
        diff = {
            k for k in scores
            if shard_scores.get(k) != scores[k]
        }
        raise ValueError(
            f"sharded scoring diverged from the single-table model on "
            f"{len(diff)} ngram(s) (e.g. {sorted(diff)[:3]}) — the "
            "co-location invariant is broken"
        )

    results = {
        "num_tokens": language_model.num_tokens,
        "vocab_size": len(unigram_counts),
        "num_ngrams": len(scores),
        "shard_sizes": dict(Counter(shard_sizes)),
        "sharded_scoring_equal": True,
        "seconds": time.perf_counter() - t0,
    }
    log.log_info(
        "number of tokens: %s\nsize of vocabulary: %s\nnumber of ngrams: %s",
        results["num_tokens"],
        results["vocab_size"],
        results["num_ngrams"],
    )
    log.log_info("trained scores of 100 ngrams in the corpus:")
    for ngram, score in list(scores.items())[:100]:
        log.log_info("%s -> %.6f", ngram, score)
    return results


def main(argv=None):
    p = argparse.ArgumentParser("StupidBackoffPipeline")
    p.add_argument("--trainData", required=True)
    p.add_argument("--numParts", type=int, default=16)
    p.add_argument("--n", type=int, default=3)
    a = p.parse_args(argv)
    conf = StupidBackoffConfig(train_data=a.trainData, num_parts=a.numParts, n=a.n)
    with open(conf.train_data, encoding="utf-8") as fh:
        lines = [ln.rstrip("\n") for ln in fh if ln.strip()]
    return run(conf, lines)


if __name__ == "__main__":
    main()
