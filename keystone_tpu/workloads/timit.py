"""TimitPipeline — phone classification with cosine random features and a
multi-epoch block solver
(reference src/main/scala/pipelines/speech/TimitPipeline.scala:20-115).

Per batch b of ``numCosines``: CosineRandomFeatures(440 -> 4096,
Gaussian or Cauchy W) then StandardScaler — the batches are the solver's
feature blocks; BlockLeastSquares runs ``numEpochs`` BCD sweeps over them;
evaluation streams through ``apply_and_evaluate`` exactly as the reference
does (:105-113).
"""

from __future__ import annotations

import argparse
import time
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ..core.logging import Logging, configure_logging
from ..core.memory import log_fit_report
from ..evaluation.multiclass import MulticlassClassifierEvaluator
from ..loaders.timit import TIMIT_DIMENSION, TIMIT_NUM_CLASSES, TimitFeaturesData, timit_features_loader
from ..ops.stats import CosineRandomFeatures, StandardScaler
from ..ops.util import ClassLabelIndicatorsFromIntLabels, MaxClassifier
from ..parallel.mesh import mask_pad_rows, padded_shard_rows, parse_mesh
from ..solvers.block import BlockLeastSquaresEstimator


@dataclass
class TimitConfig:
    """Flag-parity with the reference scopt config (:23-34)."""

    train_data_location: str = ""
    train_labels_location: str = ""
    test_data_location: str = ""
    test_labels_location: str = ""
    num_cosines: int = 50
    gamma: float = 0.05555
    rf_type: str = "gaussian"  # or "cauchy"
    lam: float = 0.0
    num_epochs: int = 5
    num_cosine_features: int = 4096
    seed: int = 123
    num_classes: int = TIMIT_NUM_CLASSES
    dimension: int = TIMIT_DIMENSION


class _Log(Logging):
    pass


def build_batch_featurizers(conf: TimitConfig, train_data, nvalid=None) -> list:
    """numCosines [CosineRandomFeatures -> StandardScaler] chains (:65-84).

    ``nvalid``: true row count when ``train_data`` carries zero pad rows —
    cos maps zero rows to nonzero ``cos(b)``, so pad rows are masked back to
    zero before the scaler's moment sums.
    """
    key = jax.random.PRNGKey(conf.seed)
    featurizers = []
    for _ in range(conf.num_cosines):
        key, sub = jax.random.split(key)
        rf = CosineRandomFeatures.create(
            conf.dimension,
            conf.num_cosine_features,
            conf.gamma,
            sub,
            w_dist=conf.rf_type,
        )
        feats = mask_pad_rows(rf(train_data), nvalid)
        scaler = StandardScaler().fit(feats, nvalid=nvalid)
        featurizers.append(rf.then(scaler))
    return featurizers


def run(conf: TimitConfig, data: TimitFeaturesData, mesh=None) -> dict:
    """With ``mesh``, features are row-sharded over the data axis and the
    multi-epoch BCD solver runs distributed — the reference runs this over
    partitioned RDDs end to end (TimitPipeline.scala:58-113)."""
    configure_logging()
    log = _Log()
    t0 = time.perf_counter()

    n_test = len(data.test.labels)
    if mesh is not None:
        train_data, nvalid = padded_shard_rows(data.train.data, mesh)
        test_data, _ = padded_shard_rows(data.test.data, mesh)
    else:
        train_data, nvalid = jnp.asarray(data.train.data), None
        test_data = jnp.asarray(data.test.data)

    batch_featurizer = build_batch_featurizers(conf, train_data, nvalid)
    training_batches = [
        mask_pad_rows(f(train_data), nvalid) for f in batch_featurizer
    ]

    labels = ClassLabelIndicatorsFromIntLabels(conf.num_classes)(data.train.labels)

    test_batches = [f(test_data) for f in batch_featurizer]

    solver = BlockLeastSquaresEstimator(
        conf.num_cosine_features, conf.num_epochs, conf.lam, mesh=mesh
    )
    model = solver.fit(training_batches, labels, nvalid=nvalid)
    log_fit_report(solver, label="timit cosine solve")

    results: dict = {}

    def evaluator(pred):
        predicted = MaxClassifier()(pred[:n_test])
        ev = MulticlassClassifierEvaluator(
            predicted, data.test.labels, conf.num_classes
        )
        results["test_error"] = 100.0 * ev.total_error
        log.log_info("TEST Error is %s%%", results["test_error"])

    model.apply_and_evaluate(test_batches, evaluator)
    results["seconds"] = time.perf_counter() - t0
    return results


def main(argv=None):
    p = argparse.ArgumentParser("Timit")
    p.add_argument("--trainDataLocation", required=True)
    p.add_argument("--trainLabelsLocation", required=True)
    p.add_argument("--testDataLocation", required=True)
    p.add_argument("--testLabelsLocation", required=True)
    p.add_argument("--numCosines", type=int, default=50)
    p.add_argument("--numEpochs", type=int, default=5)
    p.add_argument("--gamma", type=float, default=0.05555)
    p.add_argument("--lambda", dest="lam", type=float, default=0.0)
    p.add_argument("--rfType", choices=["gaussian", "cauchy"], default="gaussian")
    p.add_argument(
        "--mesh",
        default=None,
        help="device mesh, e.g. '8' (data) or '4x2' (data x model)",
    )
    a = p.parse_args(argv)
    conf = TimitConfig(
        train_data_location=a.trainDataLocation,
        train_labels_location=a.trainLabelsLocation,
        test_data_location=a.testDataLocation,
        test_labels_location=a.testLabelsLocation,
        num_cosines=a.numCosines,
        gamma=a.gamma,
        rf_type=a.rfType,
        lam=a.lam,
        num_epochs=a.numEpochs,
    )
    data = timit_features_loader(
        conf.train_data_location,
        conf.train_labels_location,
        conf.test_data_location,
        conf.test_labels_location,
    )
    return run(conf, data, mesh=parse_mesh(a.mesh))


if __name__ == "__main__":
    main()
