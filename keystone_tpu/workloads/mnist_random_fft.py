"""MnistRandomFFT — the first end-to-end workload
(reference src/main/scala/pipelines/images/mnist/MnistRandomFFT.scala:17-127).

Pipeline: CSV load -> per-FFT-batch [RandomSign -> PaddedFFT -> LinearRectifier]
-> ZipVectors -> BlockLeastSquares(blockSize, 1 iter, λ) -> MaxClassifier ->
MulticlassClassifierEvaluator.  784-pixel inputs give 512 PaddedFFT features
per FFT, so blockSize/512 FFTs land in each solver block, exactly as the
reference computes fftsPerBatch/numFFTBatches (:31-33).
"""

from __future__ import annotations

import argparse
from dataclasses import dataclass
import math
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..core import optimize, trace
from ..core.checkpoint import checkpoint_exists, load_pipeline, save_pipeline
from ..core.logging import Logging, configure_logging, stage_timer
from ..core.memory import log_fit_report
from ..core.pipeline import Pipeline
from ..core.resilience import assert_all_finite, numerics_guard_enabled
from ..evaluation.multiclass import MulticlassClassifierEvaluator
from ..loaders.csv_loader import LabeledData, csv_data_loader
from ..ops.stats import LinearRectifier, PaddedFFT, RandomSignNode
from ..ops.util import (
    ClassLabelIndicatorsFromIntLabels,
    GroupConcatFeaturizer,
    MaxClassifier,
    ZipVectors,
)
from ..parallel.mesh import padded_shard_rows, parse_mesh
from ..solvers.block import BlockLeastSquaresEstimator
from . import serve_common


@dataclass
class MnistRandomFFTConfig:
    """Flag-compatible with the reference scopt config (:94-101)."""

    train_location: str = ""
    test_location: str = ""
    num_ffts: int = 200
    block_size: int = 2048
    lam: float | None = None
    seed: int = 0
    mnist_image_size: int = 784
    num_classes: int = 10
    #: BCD solve fault tolerance (single-device fits only): a checkpoint
    #: path/callback (state persisted after every completed block) and an
    #: optional state to resume a preempted solve from — both forwarded to
    #: ``BlockLeastSquaresEstimator.fit(checkpoint=, resume_from=)``.
    solve_checkpoint: object = None
    solve_resume: object = None
    #: Cost-based auto-Cacher (core.optimize): decide from the MEASURED
    #: featurize cost whether the FFT feature batches stay resident through
    #: the train-split evaluation (reuse=2: solve + eval) or are freed
    #: after the solve and recomputed at eval — under a tight
    #: ``KEYSTONE_HBM_BUDGET`` the optimizer picks recompute instead of
    #: OOMing on residency.  Decision table in ``results["cache_plan"]``.
    auto_cache: bool = False
    #: Placement search (core.autoshard): force the cost-model-ranked
    #: candidate search for the block solve even when ``KEYSTONE_AUTOSHARD``
    #: disabled it process-wide.  The searched candidate table (scores,
    #: deny rationale, chosen plan's predicted-vs-actual cost) lands in
    #: ``results["placement"]`` whenever a search ran.
    auto_shard: bool = False
    #: Placement override forwarded verbatim to ``fit(plan=...)`` —
    #: ``False`` hand ladder, ``True`` force search, a PlacementPlan or
    #: candidate-name list replays/forces a ranking (the chaos harness
    #: forces a SPEC-assignment plan to the top through this).
    solve_plan: object = None
    #: Whole-fitted-SERVABLE-pipeline checkpoint stem (core.checkpoint):
    #: load-or-fit of ``GroupConcatFeaturizer >> model >> MaxClassifier``
    #: — the artifact the serving endpoint warm-loads.
    pipeline_file: str | None = None
    #: Serving modes (core.serve via serve_common): ``serve`` answers the
    #: test split through the warm endpoint and asserts bit-equality;
    #: ``serve_bench`` runs the concurrent-client SLO bench.  Both require
    #: ``pipeline_file``.
    serve: bool = False
    serve_bench: bool = False
    serve_clients: int = 4
    serve_requests: int = 256
    #: ``--serveMesh DxM``: serve on an explicit mesh — the checkpoint
    #: reshards onto it and buckets AOT-compile mesh-native (ISSUE 16).
    serve_mesh: str | None = None


def build_featurizer_batches(conf: MnistRandomFFTConfig):
    """The per-batch featurizers (:44-48): blockSize/512 FFT chains per batch."""
    ffts_per_batch = conf.block_size // 512
    num_fft_batches = math.ceil(conf.num_ffts / ffts_per_batch)
    key = jax.random.PRNGKey(conf.seed)
    batches = []
    for _ in range(num_fft_batches):
        chain = []
        for _ in range(ffts_per_batch):
            key, sub = jax.random.split(key)
            chain.append(
                Pipeline(
                    [
                        RandomSignNode.create(conf.mnist_image_size, sub),
                        PaddedFFT(),
                        LinearRectifier(0.0),
                    ]
                )
            )
        batches.append(chain)
    return batches


def run(
    conf: MnistRandomFFTConfig,
    train: LabeledData,
    test: LabeledData,
    mesh=None,
) -> dict:
    """With ``mesh``, train/test batches are row-sharded over the data axis
    and the block solver runs fully distributed (sharded grams + model-axis
    sharded solves) — the reference runs this pipeline over partitioned RDDs
    end to end (MnistRandomFFT.scala:36-88)."""
    configure_logging()
    log = _Log()
    t0 = time.perf_counter()

    if conf.pipeline_file is not None and checkpoint_exists(conf.pipeline_file):
        # Deploy-once/apply-many: the fitted servable chain restores whole
        # (featurize groups + model + classifier), training data is never
        # touched, and the run scores/serves with the restored pipeline.
        return _run_restored(conf, test, log, t0)

    labels = ClassLabelIndicatorsFromIntLabels(conf.num_classes)(train.labels)
    batch_featurizer = build_featurizer_batches(conf)

    n_train, n_test = len(train.labels), len(test.labels)
    if mesh is not None:
        # Featurization is elementwise per row: zero pad rows stay zero
        # through RandomSign/FFT/rectifier, so no masking is needed.
        train_data, nvalid = padded_shard_rows(train.data, mesh)
        test_data, _ = padded_shard_rows(test.data, mesh)
    else:
        train_data, nvalid = jnp.asarray(train.data), None
        test_data = jnp.asarray(test.data)

    def featurize_training():
        batches = [
            ZipVectors.apply([chain(train_data) for chain in chains])
            for chains in batch_featurizer
        ]
        # Sync inside the stage: jnp dispatch is async, and an unsynced
        # featurize span would read ~0 while the compute leaked into the
        # solve span's time.
        jax.block_until_ready(batches)
        return batches

    t_feat = time.perf_counter()
    with stage_timer("featurize"):
        training_batches = featurize_training()
    feat_secs = time.perf_counter() - t_feat

    cache_plan = None
    keep_features = True
    if conf.auto_cache:
        # Auto-Cacher decision on the featurized training batches: they are
        # consumed twice (the block solve, then the train-split streaming
        # eval).  Caching = the status-quo residency; a denial frees them
        # after the solve and recomputes at eval time — measured featurize
        # seconds vs materialized bytes, admitted per-chip under a mesh.
        cache_plan = optimize.plan_caches(
            [
                optimize.CacheCandidate(
                    index=0,
                    name="fft_features",
                    seconds=feat_secs,
                    output_bytes=sum(int(b.nbytes) for b in training_batches),
                    reuse=2,
                )
            ],
            mesh=mesh,
            dataset_rows=n_train,
        )
        keep_features = cache_plan.decisions[0].cached
        log.log_info("%s", cache_plan.summary())

    with stage_timer("solve"):
        solver = BlockLeastSquaresEstimator(
            conf.block_size, 1, conf.lam or 0.0, mesh=mesh
        )
        model = solver.fit(
            training_batches,
            labels,
            nvalid=nvalid,
            checkpoint=conf.solve_checkpoint,
            resume_from=conf.solve_resume,
            plan=(
                conf.solve_plan if conf.solve_plan is not None
                else (True if conf.auto_shard else None)
            ),
        )
        log_fit_report(solver, label="mnist random-fft solve")
        if numerics_guard_enabled():
            # Fail typed (FloatingPointError) instead of serving NaN
            # scores — a poisoned batch or diverged solve must never look
            # like a model.
            assert_all_finite(model, "mnist random-fft model")

    if not keep_features:
        # The plan priced residency above a recompute: release the feature
        # batches' memory through the solve->eval gap and rebuild them at
        # eval (bit-identical — the featurizers are deterministic).
        training_batches = None

    test_batches = [
        ZipVectors.apply([chain(test_data) for chain in chains])
        for chains in batch_featurizer
    ]

    results: dict = {}
    if cache_plan is not None:
        results["cache_plan"] = cache_plan.record()
    rep = solver.last_fit_report
    if rep is not None and rep.placement is not None:
        # The searched placement table — candidates, deny/score rationale,
        # chosen plan with predicted-vs-actual cost (tools/plan_view.py
        # pretty-prints it from this record).
        results["placement"] = rep.placement

    def train_eval(pred):
        predicted = MaxClassifier()(pred[:n_train])
        ev = MulticlassClassifierEvaluator(predicted, train.labels, conf.num_classes)
        results["train_error"] = 100.0 * ev.total_error
        log.log_info("Train Error is %s%%", results["train_error"])

    def test_eval(pred):
        predicted = MaxClassifier()(pred[:n_test])
        ev = MulticlassClassifierEvaluator(predicted, test.labels, conf.num_classes)
        results["test_error"] = 100.0 * ev.total_error
        # Full-model predicted labels (the streaming evaluator's last call
        # sees the complete model) — the chaos harness diffs these against
        # the fault-free run to rule out silent wrong models.
        results["test_predictions"] = np.asarray(predicted)
        log.log_info("TEST Error is %s%%", results["test_error"])

    # Streaming evaluation after each block, as the reference does (:70-86);
    # the last invocation sees the full-model prediction.
    with stage_timer("eval"):
        if training_batches is None:
            training_batches = featurize_training()
        model.apply_and_evaluate(training_batches, train_eval)
        model.apply_and_evaluate(test_batches, test_eval)

    # The fitted SERVABLE chain: the same featurize groups as one node,
    # whose concatenated output the model's VectorSplitter cuts back into
    # exactly the per-group blocks — served scores bit-equal the fit-path
    # apply.  Checkpointed whole for the serving endpoint to warm-load.
    servable = Pipeline(
        [GroupConcatFeaturizer(batch_featurizer), model, MaxClassifier()]
    )
    if conf.pipeline_file is not None:
        from ..core import numerics as knum

        # Fit-time output baseline (ISSUE 15): the predicted-class
        # distribution is persisted in the checkpoint manifest — the
        # reference the serving tier's output-drift monitor judges live
        # answers against once the engine warm-loads this artifact.
        save_pipeline(
            conf.pipeline_file,
            servable,
            numerics_baseline=knum.OutputSketch.for_outputs(
                results["test_predictions"]
            ).record(),
        )
        log.log_info("saved fitted servable pipeline to %s", conf.pipeline_file)
    _maybe_serve(conf, test, results, log)

    results["seconds"] = time.perf_counter() - t0
    log.log_info("Pipeline took %.3f s", results["seconds"])
    return results


def _run_restored(conf: MnistRandomFFTConfig, test, log, t0: float) -> dict:
    """Score (and serve) with the restored servable pipeline — no refit."""
    log.log_info(
        "restoring fitted servable pipeline from %s", conf.pipeline_file
    )
    servable = load_pipeline(conf.pipeline_file)
    predicted = servable(jnp.asarray(test.data))
    ev = MulticlassClassifierEvaluator(
        predicted, test.labels, conf.num_classes
    )
    results: dict = {
        "restored": True,
        "test_error": 100.0 * ev.total_error,
        "test_predictions": np.asarray(predicted),
    }
    log.log_info("TEST Error is %s%% (restored pipeline)", results["test_error"])
    _maybe_serve(conf, test, results, log)
    results["seconds"] = time.perf_counter() - t0
    return results


def _maybe_serve(conf: MnistRandomFFTConfig, test, results: dict, log) -> None:
    if not (conf.serve or conf.serve_bench):
        return
    if conf.pipeline_file is None:
        raise ValueError(
            "--serve/--serveBench need --pipelineFile — the endpoint "
            "warm-loads the fitted artifact, it never refits"
        )
    requests = np.asarray(test.data[: conf.serve_requests], np.float32)
    results["serving"] = serve_common.serve_fitted(
        conf.pipeline_file,
        jax.ShapeDtypeStruct((requests.shape[1],), np.float32),
        requests,
        label="mnist_random_fft",
        bench=conf.serve_bench,
        clients=conf.serve_clients,
        mesh=serve_common.resolve_serve_mesh(conf.serve_mesh),
    )


class _Log(Logging):
    pass


def main(argv=None):
    p = argparse.ArgumentParser("MnistRandomFFT")
    p.add_argument("--trainLocation", required=True)
    p.add_argument("--testLocation", required=True)
    p.add_argument("--numFFTs", type=int, default=200)
    p.add_argument("--blockSize", type=int, default=2048)
    p.add_argument("--lambda", dest="lam", type=float, default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--mesh",
        default=None,
        help="device mesh, e.g. '8' (data) or '4x2' (data x model)",
    )
    p.add_argument(
        "--solveCheckpoint",
        default=None,
        help="path for resumable per-block BCD solve state (single-device "
        "fits; state written atomically after every completed block)",
    )
    p.add_argument(
        "--resumeFrom",
        default=None,
        help="BCD solve state path to resume a preempted fit from",
    )
    p.add_argument(
        "--autoCache",
        action="store_true",
        help="cost-based auto-Cacher (core.optimize): decide feature-batch "
        "residency from measured featurize cost vs HBM budget "
        "(KEYSTONE_AUTOCACHE=1 equivalent)",
    )
    p.add_argument(
        "--autoShard",
        action="store_true",
        help="placement search (core.autoshard): force the cost-model "
        "ranked mesh/strategy candidate search for the block solve and "
        "record the searched plan in results['placement'] (the search is "
        "on by default; KEYSTONE_AUTOSHARD=0 disables it except here)",
    )
    p.add_argument(
        "--pipelineFile",
        default=None,
        help="fitted-SERVABLE-pipeline checkpoint stem: load-or-fit of "
        "featurize groups + model + classifier in one artifact (what "
        "--serve/--serveBench warm-load)",
    )
    serve_common.add_serve_args(p)
    p.add_argument(
        "--trace",
        default=None,
        metavar="PATH",
        help="write a Chrome-trace JSON (Perfetto-loadable; .jsonl for the "
        "JSONL event log) of the run — the KEYSTONE_TRACE env equivalent",
    )
    a = p.parse_args(argv)
    if a.trace:
        trace.enable(a.trace)
    # Before the load stage timer, so its log line has a handler to land on
    # (run() re-applies the same idempotent configuration).
    configure_logging()
    if a.blockSize <= 0 or a.blockSize % 512 != 0:
        p.error("--blockSize must be a positive multiple of 512")
    conf = MnistRandomFFTConfig(
        train_location=a.trainLocation,
        test_location=a.testLocation,
        num_ffts=a.numFFTs,
        block_size=a.blockSize,
        lam=a.lam,
        seed=a.seed,
        solve_checkpoint=a.solveCheckpoint,
        solve_resume=a.resumeFrom,
        auto_cache=a.autoCache or optimize.auto_cache_env(),
        auto_shard=a.autoShard,
        pipeline_file=a.pipelineFile,
        serve=a.serve,
        serve_bench=a.serveBench,
        serve_clients=a.serveClients,
        serve_requests=a.serveRequests,
        serve_mesh=a.serveMesh,
    )
    if (a.serve or a.serveBench) and not a.pipelineFile:
        p.error("--serve/--serveBench require --pipelineFile")
    # Labels in the files are 1-indexed (reference :40-42)
    with stage_timer("load"):
        train = LabeledData.from_rows(
            csv_data_loader(conf.train_location), one_indexed=True
        )
        test = LabeledData.from_rows(
            csv_data_loader(conf.test_location), one_indexed=True
        )
    # The reference hardcodes mnistImageSize=784 (:24); inferring the width
    # from the data keeps flag parity while admitting any pixel count
    # (e.g. the 64-pixel sklearn digits used for real-data accuracy runs).
    conf.mnist_image_size = train.data.shape[1]
    try:
        return run(conf, train, test, mesh=parse_mesh(a.mesh))
    finally:
        if a.trace:
            trace.flush()


if __name__ == "__main__":
    main()
