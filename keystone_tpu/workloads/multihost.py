"""Multi-host fit + serve workload (ISSUE 17): the `jax.distributed`
acceptance surface and the host-loss drill, as one module with three
faces.

* **Worker entries** (``python -m keystone_tpu.workloads.multihost ...``):
  ``fit-serve`` joins the process group, streams ITS tar shards through
  ``core.ingest``, fits a scaler by deterministic rank-ordered moment
  aggregation, checkpoints, cross-host-reshards the checkpoint back onto
  the process-spanning mesh, and serves the fit host-locally;
  ``serve-host`` is one fleet member — host-local ``ShapeRouter`` behind
  a ``WireServer``, driven over stdin by the fleet controller (the
  host-loss re-anchor path).
* **Drivers** (:func:`run_two_process_fit_serve`,
  :func:`run_host_loss_drill`): spawn the workers as REAL subprocesses
  with auto-picked ports and judge the results.  tests/test_multihost.py,
  the chaos ``host_loss`` family, ``bench.py``'s multihost section and
  the ``--hosts N`` tools all drive these two functions — one
  implementation, four consumers.

Bit-identity design: XLA's cross-process reductions are NOT bit-identical
to a single-process run, so nothing numerical crosses hosts through XLA.
Each host computes per-shard moment partials with the same local program,
partials are allgathered (exact byte transport) and summed host-side in
fixed rank order — and the single-process reference partitions the same
shard list into the same per-rank groups and sums the same partials in
the same order.  Same values, same op, same order: bit-identical by
construction (see ``parallel.distributed.deterministic_allreduce``).
"""

from __future__ import annotations

import argparse
import glob
import io
import json
import os
import subprocess
import sys
import tarfile
import time

import numpy as np

FEAT_DIM = 8
_TEST_ROWS = 12


# -- synthetic shard tars -----------------------------------------------------


def make_shard_tars(
    dirpath: str,
    shards: int,
    images_per_shard: int,
    seed: int = 0,
    h: int = 48,
    w: int = 48,
) -> list[str]:
    """Deterministic random-texture JPEG tar shards — the dataset every
    fit path (distributed and reference) reads.  One rng stream per
    member, keyed on (seed, shard, image), so the bytes do not depend on
    which host generates or reads them."""
    from PIL import Image as PILImage

    os.makedirs(dirpath, exist_ok=True)
    paths = []
    for s in range(shards):
        path = os.path.join(dirpath, f"shard_{s:03d}.tar")
        with tarfile.open(path, "w") as tf:
            for i in range(images_per_shard):
                rng = np.random.default_rng((seed, s, i))
                arr = rng.integers(0, 256, (h, w, 3), dtype=np.uint8)
                buf = io.BytesIO()
                PILImage.fromarray(arr).save(buf, format="JPEG", quality=90)
                data = buf.getvalue()
                info = tarfile.TarInfo(f"img_{s:03d}_{i:04d}.jpg")
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))
        paths.append(path)
    return paths


# -- deterministic fit --------------------------------------------------------


def _feat_fn():
    """[b, H, W, C] device batch -> [b, 8] features: per-channel means and
    maxes plus whole-image mean/max.  Elementwise + per-image reductions
    only — one fixed program per batch shape on every host."""
    import jax
    import jax.numpy as jnp

    def feats(x):
        return jnp.concatenate(
            [
                jnp.mean(x, axis=(1, 2)),
                jnp.max(x, axis=(1, 2)),
                jnp.mean(x, axis=(1, 2, 3), keepdims=False)[:, None],
                jnp.max(x, axis=(1, 2, 3), keepdims=False)[:, None],
            ],
            axis=1,
        )

    return jax.jit(feats)


def moments_for_shards(shard_paths, batch: int = 4) -> np.ndarray:
    """One host's (or one emulated rank's) moment partial over its shard
    list, packed ``[sum(8), sumsq(8), count]`` float32.  Shards are
    streamed through ``core.ingest`` in sorted order and accumulated
    host-side in that order — the partial is a pure function of the shard
    list, independent of which process computes it."""
    from keystone_tpu.core import ingest
    from keystone_tpu.workloads.fv_common import scatter_features_streaming

    feat = _feat_fn()
    s = np.zeros(FEAT_DIM, np.float32)
    sq = np.zeros(FEAT_DIM, np.float32)
    n = 0
    for tar in sorted(shard_paths):
        with ingest.stream_batches(tar, batch) as st:
            feats, _names = scatter_features_streaming(st, feat, FEAT_DIM)
        if not st.join(10.0):
            raise RuntimeError(f"{tar}: ingest threads did not exit")
        s += feats.sum(axis=0, dtype=np.float32)
        sq += (feats * feats).sum(axis=0, dtype=np.float32)
        n += feats.shape[0]
    return np.concatenate([s, sq, [np.float32(n)]]).astype(np.float32)


def fit_from_moments(packed: np.ndarray):
    """``(mean, std)`` float32 from the reduced moments — the
    ``StandardScaler`` math (sample variance, degenerate-std guard) in
    host numpy so every rank derives bitwise-identical parameters from
    the bitwise-identical reduced moments."""
    s = packed[:FEAT_DIM].astype(np.float32)
    sq = packed[FEAT_DIM : 2 * FEAT_DIM].astype(np.float32)
    n = np.float32(packed[-1])
    mean = (s / n).astype(np.float32)
    var = ((sq - n * mean * mean) / (n - np.float32(1.0))).astype(np.float32)
    with np.errstate(invalid="ignore"):
        std = np.sqrt(var).astype(np.float32)
    bad = ~np.isfinite(std) | (np.abs(std) < np.float32(1e-12))
    std = np.where(bad, np.float32(1.0), std).astype(np.float32)
    return mean, std


def test_rows(seed: int) -> np.ndarray:
    return np.asarray(
        np.random.default_rng((seed, 7)).normal(size=(_TEST_ROWS, FEAT_DIM)),
        np.float32,
    )


# -- worker: fit-serve --------------------------------------------------------


def fit_serve_main(argv) -> int:
    ap = argparse.ArgumentParser(prog="multihost fit-serve")
    ap.add_argument("--shards-dir", required=True)
    ap.add_argument("--out", required=True)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--ckpt", default=None)
    ap.add_argument(
        "--emulate-world", type=int, default=None,
        help="single-process reference: partition shards into this many "
        "rank groups and sum their partials in rank order",
    )
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from keystone_tpu.core import serve as kserve
    from keystone_tpu.core.checkpoint import load_pipeline, save_pipeline
    from keystone_tpu.core.resilience import counters
    from keystone_tpu.ops.stats import StandardScalerModel
    from keystone_tpu.parallel import distributed as kdist
    from keystone_tpu.parallel import mesh as kmesh

    t_start = time.monotonic()
    env_world = int(os.environ.get(kdist.PROCS_ENV, "1") or 1)
    if env_world > 1:
        st = kdist.init_process_group()
        world, rank = st.world, st.rank
    else:
        st, world, rank = None, 1, 0
    record: dict = {"world": world, "rank": rank, "pid": os.getpid()}

    shards = sorted(glob.glob(os.path.join(args.shards_dir, "*.tar")))
    if not shards:
        raise SystemExit(f"no tar shards under {args.shards_dir}")
    from keystone_tpu.core.ingest import host_shards

    t0 = time.monotonic()
    if st is not None and st.jax_initialized and world > 1:
        mine = host_shards(shards)
        partial = moments_for_shards(mine, args.batch)
        total = kdist.deterministic_allreduce(partial)
        record["my_shards"] = [os.path.basename(p) for p in mine]
    else:
        ew = max(1, args.emulate_world or 1)
        parts = [
            moments_for_shards(host_shards(shards, r, ew), args.batch)
            for r in range(ew)
        ]
        total = np.stack(parts, axis=0).sum(axis=0)
        record["emulated_world"] = ew
    mean, std = fit_from_moments(total)
    record["fit_wall_s"] = round(time.monotonic() - t0, 4)
    record["n_images"] = int(total[-1])
    record["mean"] = mean.tolist()
    record["std"] = std.tolist()

    model = StandardScalerModel(jnp.asarray(mean), jnp.asarray(std))
    rows = test_rows(args.seed)
    record["predictions"] = np.asarray(model(jnp.asarray(rows))).tolist()

    if args.ckpt:
        local = kmesh.host_local_mesh()
        if rank == 0:
            # Anchor the mean SHARDED so the manifest records a real
            # non-replicated spec the cross-host reshard must re-lower.
            anchored = StandardScalerModel(
                jax.device_put(
                    jnp.asarray(mean),
                    NamedSharding(local, P(kmesh.DATA_AXIS)),
                ),
                jnp.asarray(std),
            )
            with kmesh.use_mesh(local):
                save_pipeline(args.ckpt, anchored)
        kdist.barrier("ckpt_saved")
        if st is not None and st.jax_initialized and world > 1:
            gmesh = kmesh.make_mesh()  # global devices: the spanning mesh
            record["global_mesh"] = kmesh.mesh_desc(gmesh)
            record["mesh_spans"] = kmesh.mesh_spans_processes(gmesh)
            before = counters.get("ckpt_reshard_crosshost")
            t1 = time.monotonic()
            resumed = load_pipeline(args.ckpt, mesh=gmesh)
            record["reshard_wall_s"] = round(time.monotonic() - t1, 4)
            record["crosshost_reshard"] = (
                counters.get("ckpt_reshard_crosshost") - before
            )
            # Every shard addressable HERE must hold exactly the fit's
            # bytes — the redistribution is verified without any
            # cross-process compute.
            equal = True
            for shard in resumed.mean.addressable_shards:
                want = mean[shard.index]
                if not np.array_equal(np.asarray(shard.data), want):
                    equal = False
            record["crosshost_bit_equal"] = bool(equal)
            kdist.barrier("resumed")

    # Serve host-locally (engines never span hosts).
    t2 = time.monotonic()
    engine = kserve.ServingEngine(
        model,
        np.zeros(FEAT_DIM, np.float32),
        config=kserve.ServeConfig(buckets=(1, 2, 4), max_wait_ms=2.0),
        label=f"mh{rank}",
        mesh=kmesh.host_local_mesh(),
    )
    with kserve.Server(engine) as server:
        futures = [server.submit(r) for r in rows]
        served = np.stack([f.result(30.0) for f in futures])
    record["served"] = served.tolist()
    record["serve_wall_s"] = round(time.monotonic() - t2, 4)
    record["parity_ok"] = bool(engine.parity_ok)

    if st is not None and st.jax_initialized:
        record["leaked_threads"] = kdist.shutdown_process_group()
    record["wall_s"] = round(time.monotonic() - t_start, 4)
    record["counters"] = counters.snapshot()
    with open(args.out, "w") as fh:
        json.dump(record, fh)
    return 0


# -- worker: serve-host -------------------------------------------------------


def serve_host_main(argv) -> int:
    ap = argparse.ArgumentParser(prog="multihost serve-host")
    ap.add_argument("--ckpt", default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--buckets", default="1,2,4")
    args = ap.parse_args(argv)

    import jax.numpy as jnp

    from keystone_tpu.core import frontend as kfrontend
    from keystone_tpu.core import serve as kserve
    from keystone_tpu.core import trace
    from keystone_tpu.core import wire as kwire
    from keystone_tpu.core.checkpoint import load_pipeline
    from keystone_tpu.core.resilience import counters
    from keystone_tpu.ops.stats import StandardScalerModel
    from keystone_tpu.parallel import distributed as kdist
    from keystone_tpu.parallel import mesh as kmesh

    st = kdist.init_process_group(use_jax=False)  # fleet membership only
    rank = st.rank
    buckets = tuple(int(b) for b in args.buckets.split(","))
    state: dict = {}

    def load_model():
        if args.ckpt:
            return load_pipeline(args.ckpt, mesh=kmesh.host_local_mesh())
        rng = np.random.default_rng((args.seed, 11))
        return StandardScalerModel(
            jnp.asarray(rng.normal(size=FEAT_DIM).astype(np.float32)),
            jnp.asarray(
                (np.abs(rng.normal(size=FEAT_DIM)) + 0.5).astype(np.float32)
            ),
        )

    state["model"] = load_model()

    def build(shape, dtype, mesh_or_none):
        return kserve.ServingEngine(
            state["model"],
            np.zeros(shape, dtype),
            config=kserve.ServeConfig(buckets=buckets, max_wait_ms=2.0),
            label=f"host{rank}:{'x'.join(str(d) for d in shape)}",
            mesh=mesh_or_none,
        )

    factory = kfrontend.MeshEngineFactory(build, mesh=kmesh.host_local_mesh())
    router = kfrontend.ShapeRouter(factory, label=f"host{rank}")
    router.add_engine(factory((FEAT_DIM,), np.float32))
    server = kwire.WireServer(router, port=0, label=f"host{rank}")
    print(
        json.dumps({"rank": rank, "port": server.port, "pid": os.getpid()}),
        flush=True,
    )

    rc = 0
    try:
        for line in sys.stdin:
            parts = line.strip().split()
            if not parts:
                continue
            cmd = parts[0]
            if cmd == "quit":
                break
            if cmd == "peer_lost":
                # The controller (the front-end's liveness detection) says
                # these ORIGINAL ranks survive: re-form the reduced group,
                # redistribute the checkpointed state onto this host, and
                # hot-swap every engine — zero request loss, counted.
                survivors = [int(p) for p in parts[1:]]
                t0 = time.monotonic()
                new = kdist.reform_group(survivors)
                state["model"] = load_model()
                info = router.reanchor(
                    kmesh.host_local_mesh(),
                    why=f"host loss (group epoch {new.epoch}, "
                    f"lost {list(new.lost)})",
                )
                wall = round(time.monotonic() - t0, 4)
                counters.record(
                    "host_reanchor",
                    f"host{rank}: survivors={survivors} "
                    f"world={new.world} wall={wall}s",
                )
                print(
                    json.dumps(
                        {
                            "ack": "peer_lost",
                            "world": new.world,
                            "epoch": new.epoch,
                            "reanchor_wall_s": wall,
                            "swapped": len(info.get("swapped", [])),
                            "failed": len(info.get("failed", [])),
                        }
                    ),
                    flush=True,
                )
            elif cmd == "stats":
                print(
                    json.dumps({"stats": {"counters": counters.snapshot()}}),
                    flush=True,
                )
    except (BrokenPipeError, KeyboardInterrupt):  # controller died
        rc = 1
    finally:
        server.close()
        router.close()
        final = {
            "final": {
                "rank": rank,
                "counters": counters.snapshot(),
                "wire": dataclasses_asdict_safe(server.stats),
            }
        }
        print(json.dumps(final), flush=True)
        if trace.enabled():
            trace.flush()
    return rc


def dataclasses_asdict_safe(obj) -> dict:
    import dataclasses

    try:
        return dataclasses.asdict(obj)
    except TypeError:
        return {}


# -- drivers ------------------------------------------------------------------


def _worker_cmd(mode: str, extra) -> list[str]:
    return [sys.executable, "-m", "keystone_tpu.workloads.multihost", mode, *extra]


def _hermetic_env(env: dict, tmpdir: str, tag: str, *, trace_path=None) -> dict:
    """Spawned workers must not write the parent's trace or train the
    parent's plan log."""
    env = dict(env)
    env["KEYSTONE_PLAN_LOG"] = os.path.join(tmpdir, f"plan_{tag}.jsonl")
    if trace_path is None:
        env.pop("KEYSTONE_TRACE", None)
    else:
        env["KEYSTONE_TRACE"] = trace_path
    return env


def run_two_process_fit_serve(
    tmpdir: str,
    *,
    shards_per_host: int = 2,
    images_per_shard: int = 6,
    seed: int = 0,
    local_devices: int = 2,
    timeout_s: float = 300.0,
) -> dict:
    """The tentpole acceptance run: a REAL 2-process ``jax.distributed``
    CPU fit+serve (auto-picked coordinator port, per-host tar shards,
    cross-host checkpoint reshard) against the single-process reference on
    the same data — judged bit-identical.  Returns the judged record;
    raises on timeout or a worker that died."""
    from keystone_tpu.parallel import distributed as kdist

    world = 2
    shard_dir = os.path.join(tmpdir, "mh_shards")
    make_shard_tars(
        shard_dir, world * shards_per_host, images_per_shard, seed
    )
    ckpt = os.path.join(tmpdir, "mh_ckpt")
    outs = {
        "ref": os.path.join(tmpdir, "mh_ref.json"),
        0: os.path.join(tmpdir, "mh_rank0.json"),
        1: os.path.join(tmpdir, "mh_rank1.json"),
    }
    coord = kdist.pick_coordinator()
    t0 = time.monotonic()
    procs = {}
    common = ["--shards-dir", shard_dir, "--seed", str(seed)]
    procs["ref"] = subprocess.Popen(
        _worker_cmd(
            "fit-serve",
            [*common, "--out", outs["ref"], "--emulate-world", str(world)],
        ),
        env=_hermetic_env(
            kdist.worker_env(0, 1, "", local_devices=local_devices),
            tmpdir, "ref",
        ),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    for r in range(world):
        procs[r] = subprocess.Popen(
            _worker_cmd(
                "fit-serve", [*common, "--out", outs[r], "--ckpt", ckpt]
            ),
            env=_hermetic_env(
                kdist.worker_env(
                    r, world, coord, local_devices=local_devices
                ),
                tmpdir, f"rank{r}",
            ),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
    tails = {}
    for key, p in procs.items():
        left = max(5.0, timeout_s - (time.monotonic() - t0))
        try:
            out, err = p.communicate(timeout=left)
        except subprocess.TimeoutExpired:
            for q in procs.values():
                q.kill()
            raise TimeoutError(
                f"fit-serve worker {key} exceeded {timeout_s}s"
            ) from None
        tails[key] = (out or "")[-2000:] + (err or "")[-2000:]
        if p.returncode != 0:
            raise RuntimeError(
                f"fit-serve worker {key} died rc={p.returncode}: {tails[key]}"
            )
    records = {}
    for key, path in outs.items():
        with open(path) as fh:
            records[key] = json.load(fh)
    ref, r0, r1 = records["ref"], records[0], records[1]
    judged = {
        "world": world,
        "coordinator": coord,
        "wall_s": round(time.monotonic() - t0, 3),
        "fit_serve_wall_s": max(r0["wall_s"], r1["wall_s"]),
        "reshard_wall_s": max(
            r0.get("reshard_wall_s", 0.0), r1.get("reshard_wall_s", 0.0)
        ),
        "n_images": r0["n_images"],
        "bit_identical": (
            ref["predictions"] == r0["predictions"] == r1["predictions"]
            and ref["served"] == r0["served"] == r1["served"]
            and ref["mean"] == r0["mean"]
            and ref["std"] == r0["std"]
        ),
        "crosshost_reshard": min(
            r0.get("crosshost_reshard", 0), r1.get("crosshost_reshard", 0)
        ),
        "crosshost_bit_equal": bool(
            r0.get("crosshost_bit_equal") and r1.get("crosshost_bit_equal")
        ),
        "mesh_spans": bool(r0.get("mesh_spans") and r1.get("mesh_spans")),
        "leaked_threads": sorted(
            set(r0.get("leaked_threads", []) + r1.get("leaked_threads", []))
        ),
        "parity_ok": bool(
            ref["parity_ok"] and r0["parity_ok"] and r1["parity_ok"]
        ),
        "records": records,
    }
    return judged


# -- host-loss drill ----------------------------------------------------------


class _WorkerIO:
    """One serve-host subprocess with a draining stdout reader: every
    JSON line lands in a queue (a stalled parent can never deadlock the
    worker on a full pipe), stderr goes to a file for the postmortem."""

    def __init__(self, cmd, env, stderr_path: str):
        import queue
        import threading

        self.stderr_path = stderr_path
        self._err_fh = open(stderr_path, "w")
        self.proc = subprocess.Popen(
            cmd, env=env, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=self._err_fh, text=True, bufsize=1,
        )
        self.lines: "queue.Queue" = queue.Queue()
        self._reader = threading.Thread(
            target=self._drain, name="mh-worker-stdout", daemon=True
        )
        self._reader.start()

    def _drain(self) -> None:
        for line in self.proc.stdout:
            line = line.strip()
            if not line:
                continue
            try:
                self.lines.put(json.loads(line))
            except json.JSONDecodeError:
                pass  # stray library output: not protocol
        self.lines.put(None)  # EOF marker

    def expect(self, key: str, timeout_s: float) -> dict:
        import queue

        end = time.monotonic() + timeout_s
        while True:
            left = end - time.monotonic()
            if left <= 0:
                raise TimeoutError(
                    f"worker pid {self.proc.pid}: no {key!r} message within "
                    f"{timeout_s}s (see {self.stderr_path})"
                )
            try:
                msg = self.lines.get(timeout=min(left, 0.5))
            except queue.Empty:
                continue
            if msg is None:
                raise RuntimeError(
                    f"worker pid {self.proc.pid} exited before sending "
                    f"{key!r} (rc={self.proc.poll()}, "
                    f"see {self.stderr_path})"
                )
            if key in msg:
                return msg

    def send(self, line: str) -> None:
        self.proc.stdin.write(line + "\n")
        self.proc.stdin.flush()

    def kill(self) -> None:
        self.proc.kill()

    def finish(self, timeout_s: float = 20.0) -> int:
        try:
            self.proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait(timeout=5.0)
        self._err_fh.close()
        return self.proc.returncode


def _drill_model(seed: int):
    """The drill's deterministic scaler + its offline oracle answers."""
    import jax.numpy as jnp

    from keystone_tpu.ops.stats import StandardScalerModel

    rng = np.random.default_rng((seed, 11))
    mean = rng.normal(size=FEAT_DIM).astype(np.float32)
    std = (np.abs(rng.normal(size=FEAT_DIM)) + 0.5).astype(np.float32)
    model = StandardScalerModel(jnp.asarray(mean), jnp.asarray(std))
    return mean, std, model


def _drill_ckpt(tmpdir: str, seed: int, mean, std) -> str:
    """Checkpoint the scaler with its mean SHARDED under the controller's
    mesh, so every host's restore is a real reshard (and a naive load a
    typed refusal)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from keystone_tpu.core.checkpoint import save_pipeline
    from keystone_tpu.ops.stats import StandardScalerModel
    from keystone_tpu.parallel import mesh as kmesh

    devs = jax.devices()
    width = max(d for d in (4, 2, 1) if len(devs) >= d and FEAT_DIM % d == 0)
    pmesh = kmesh.make_mesh(data=width, model=1, devices=devs[:width])
    anchored = StandardScalerModel(
        jax.device_put(
            jnp.asarray(mean), NamedSharding(pmesh, P(kmesh.DATA_AXIS))
        ),
        jnp.asarray(std),
    )
    stem = os.path.join(tmpdir, "drill_ckpt")
    with kmesh.use_mesh(pmesh):
        save_pipeline(stem, anchored)
    return stem


def _drive_fleet(fleet, rows, results, errors, *, indices=None, threads=4):
    """Continuous concurrent traffic: a thread pool drains an index queue
    through ``fleet.predict`` so requests are ALWAYS in flight while the
    controller kills a host.  Returns the pool's join callable."""
    import queue
    import threading

    idx_q: "queue.Queue" = queue.Queue()
    for i in range(len(rows)) if indices is None else indices:
        idx_q.put(i)

    def work():
        while True:
            try:
                i = idx_q.get_nowait()
            except queue.Empty:
                return
            try:
                results[i] = np.asarray(fleet.predict(rows[i]))
            except Exception as e:  # noqa: BLE001 — judged by the oracle
                errors.append((i, f"{type(e).__name__}: {e}"))

    pool = [
        threading.Thread(target=work, name=f"drill-client-{t}", daemon=True)
        for t in range(threads)
    ]
    for t in pool:
        t.start()

    def join(timeout_s: float) -> bool:
        end = time.monotonic() + timeout_s
        for t in pool:
            t.join(max(0.1, end - time.monotonic()))
        return not any(t.is_alive() for t in pool)

    return join


def _answered(results) -> int:
    return sum(1 for r in results if r is not None)


def _wait_answered(results, target: int, timeout_s: float) -> None:
    end = time.monotonic() + timeout_s
    while _answered(results) < target:
        if time.monotonic() >= end:
            raise TimeoutError(
                f"only {_answered(results)}/{target} answers within "
                f"{timeout_s}s"
            )
        time.sleep(0.005)


def _stitch_worker_trace(path: str, host: int) -> int:
    """Re-emit a dead-or-done worker's counted-fault instants onto the
    controller's trace timeline (host-tagged) — the stitched trace shows
    the fleet's faults, not just the controller's."""
    from keystone_tpu.core import trace

    try:
        with open(path) as fh:
            doc = json.load(fh)
    except (OSError, json.JSONDecodeError):
        return 0
    events = doc.get("traceEvents", []) if isinstance(doc, dict) else doc
    n = 0
    for ev in events:
        if ev.get("ph") == "i" and ev.get("name") == "fault":
            kind = ev.get("args", {}).get("kind")
            if kind:
                trace.instant(
                    "fault", kind=kind, host=host, stitched=True,
                    detail=ev.get("args", {}).get("detail", ""),
                )
                n += 1
    return n


def run_host_loss_drill(
    tmpdir: str,
    *,
    hosts: int = 2,
    requests: int = 30,
    seed: int = 0,
    local_devices: int = 2,
    subprocess_mode: bool | None = None,
    timeout_s: float = 240.0,
) -> dict:
    """Kill one serving host mid-flight and judge the invariant: every
    request answered bit-equal to the offline oracle, the loss counted
    (``fleet_host_lost``), the survivors re-formed (``dist_reform``) and
    re-anchored (``host_reanchor``, postmortem-linked) — never a silent
    wrong answer, never a dropped request.

    ``subprocess_mode=True`` (default where :func:`spawn_available`) runs
    each host as a REAL subprocess serving over the wire and SIGKILLs
    one; ``False`` degrades to in-process wire servers with an abrupt
    socket close standing in for the death — the same fleet/failover/
    re-anchor code paths on hosts without spawn."""
    from keystone_tpu.parallel import distributed as kdist

    if subprocess_mode is None:
        subprocess_mode = kdist.spawn_available()
    if hosts < 2:
        raise ValueError("the drill needs >= 2 hosts (one must die)")

    import jax.numpy as jnp

    from keystone_tpu.core import frontend as kfrontend
    from keystone_tpu.core import trace
    from keystone_tpu.core.resilience import counters

    mean, std, model = _drill_model(seed)
    stem = _drill_ckpt(tmpdir, seed, mean, std)
    rows = np.asarray(
        np.random.default_rng((seed, 13)).normal(size=(requests, FEAT_DIM)),
        np.float32,
    )
    expected = np.asarray(model(jnp.asarray(rows)))

    pm_dir = os.path.join(tmpdir, "postmortems")
    os.makedirs(pm_dir, exist_ok=True)
    old_pm = os.environ.get("KEYSTONE_POSTMORTEM_DIR")
    os.environ["KEYSTONE_POSTMORTEM_DIR"] = pm_dir
    kill_rank = hosts - 1
    survivors = [r for r in range(hosts) if r != kill_rank]
    t_start = time.monotonic()
    record: dict = {
        "mode": "subprocess" if subprocess_mode else "inprocess",
        "hosts": hosts,
        "kill_rank": kill_rank,
        "requests": requests,
    }
    try:
        if subprocess_mode:
            _run_drill_subprocess(
                record, tmpdir, stem, seed, hosts, kill_rank, survivors,
                rows, expected, local_devices, timeout_s, kdist, kfrontend,
                counters,
            )
        else:
            _run_drill_inprocess(
                record, stem, seed, hosts, kill_rank, survivors, rows,
                expected, timeout_s, kdist, kfrontend, counters,
            )
    finally:
        if old_pm is None:
            os.environ.pop("KEYSTONE_POSTMORTEM_DIR", None)
        else:
            os.environ["KEYSTONE_POSTMORTEM_DIR"] = old_pm
    record["postmortems"] = sorted(os.listdir(pm_dir))
    record["wall_s"] = round(time.monotonic() - t_start, 3)
    trace.instant(
        "host_loss_drill", mode=record["mode"], hosts=hosts,
        dropped=record["dropped_requests"],
        mismatches=record["mismatches"],
    )
    return record


def _judge_answers(record, results, errors, expected) -> None:
    mismatches = [
        i
        for i, r in enumerate(results)
        if r is not None and not np.array_equal(r, expected[i])
    ]
    record["answered"] = _answered(results)
    record["dropped_requests"] = (
        len(results) - record["answered"]
    )
    record["errors"] = [e for _, e in errors][:8]
    record["mismatches"] = len(mismatches)


def _run_drill_subprocess(
    record, tmpdir, stem, seed, hosts, kill_rank, survivors, rows,
    expected, local_devices, timeout_s, kdist, kfrontend, counters,
) -> None:
    pm_dir = os.environ["KEYSTONE_POSTMORTEM_DIR"]
    workers: list[_WorkerIO] = []
    trace_paths = {}
    try:
        for r in range(hosts):
            trace_paths[r] = os.path.join(tmpdir, f"drill_host{r}.json")
            env = _hermetic_env(
                kdist.worker_env(
                    r, hosts, "controller", local_devices=local_devices
                ),
                tmpdir, f"host{r}", trace_path=trace_paths[r],
            )
            env["KEYSTONE_POSTMORTEM_DIR"] = pm_dir
            workers.append(
                _WorkerIO(
                    _worker_cmd(
                        "serve-host",
                        ["--ckpt", stem, "--seed", str(seed)],
                    ),
                    env,
                    os.path.join(tmpdir, f"drill_host{r}.err"),
                )
            )
        up = [w.expect("port", timeout_s / 2) for w in workers]
        endpoints = [("127.0.0.1", msg["port"]) for msg in up]

        n = len(rows)
        results: list = [None] * n
        errors: list = []
        with kfrontend.HostFleet(endpoints, label="drill") as fleet:
            join = _drive_fleet(fleet, rows, results, errors)
            # Mid-flight: requests are streaming when the host dies.
            _wait_answered(results, n // 3, timeout_s / 4)
            workers[kill_rank].kill()
            record["killed_at_answered"] = _answered(results)
            _wait_answered(results, (2 * n) // 3, timeout_s / 2)
            # The controller's liveness verdict reaches the survivors:
            # re-form the reduced group, reshard, re-anchor — under the
            # traffic that is still flowing.
            acks = {}
            for r in survivors:
                workers[r].send(
                    "peer_lost " + " ".join(str(s) for s in survivors)
                )
            for r in survivors:
                acks[r] = workers[r].expect("ack", timeout_s / 2)
                counters.record(
                    "host_reanchor",
                    f"controller: host{r} re-anchored after losing "
                    f"host{kill_rank} "
                    f"(wall {acks[r].get('reanchor_wall_s')}s, "
                    f"{acks[r].get('swapped')} engine(s))",
                )
            record["acks"] = acks
            if not join(timeout_s / 2):
                raise TimeoutError("drill clients did not drain")
            record["fleet"] = fleet.record()
        finals = {}
        for r in survivors:
            workers[r].send("quit")
            finals[r] = workers[r].expect("final", timeout_s / 4)["final"]
        record["survivor_counters"] = {
            r: finals[r]["counters"] for r in survivors
        }
        record["reanchor_wall_s"] = max(
            float(acks[r].get("reanchor_wall_s") or 0.0) for r in survivors
        )
    finally:
        rcs = [w.finish() for w in workers]
        record["worker_rcs"] = rcs
    record["stitched_events"] = sum(
        _stitch_worker_trace(trace_paths[r], r) for r in survivors
    )
    _judge_answers(record, results, errors, expected)


def _run_drill_inprocess(
    record, stem, seed, hosts, kill_rank, survivors, rows, expected,
    timeout_s, kdist, kfrontend, counters,
) -> None:
    import jax

    from keystone_tpu.core import serve as kserve
    from keystone_tpu.core import wire as kwire
    from keystone_tpu.core.checkpoint import load_pipeline
    from keystone_tpu.parallel import mesh as kmesh

    devs = jax.devices()
    per = max(1, min(2, len(devs) // hosts))
    fleet_group = kdist.is_initialized()
    if not fleet_group:
        kdist.init_process_group(
            coordinator="controller", world=hosts, rank=0, use_jax=False
        )
    routers, servers = [], []
    try:
        meshes = [
            kmesh.make_mesh(
                data=per, model=1, devices=devs[r * per : (r + 1) * per]
            )
            for r in range(hosts)
        ]
        for r in range(hosts):
            model_r = load_pipeline(stem, mesh=meshes[r])
            state = {"model": model_r}

            def build(shape, dtype, mesh_or_none, _state=state, _r=r):
                return kserve.ServingEngine(
                    _state["model"],
                    np.zeros(shape, dtype),
                    config=kserve.ServeConfig(buckets=(1, 2, 4), max_wait_ms=2.0),
                    label=f"inhost{_r}:{'x'.join(str(d) for d in shape)}",
                    mesh=mesh_or_none,
                )

            factory = kfrontend.MeshEngineFactory(build, mesh=meshes[r])
            router = kfrontend.ShapeRouter(factory, label=f"inhost{r}")
            router.add_engine(factory((FEAT_DIM,), np.float32))
            routers.append(router)
            servers.append(kwire.WireServer(router, port=0, label=f"inhost{r}"))
        endpoints = [("127.0.0.1", s.port) for s in servers]

        n = len(rows)
        results: list = [None] * n
        errors: list = []
        with kfrontend.HostFleet(endpoints, label="drill") as fleet:
            # Two waves: in-process serving is fast enough that a single
            # stream can fully drain before the close lands, so the
            # post-loss continuity is driven explicitly — wave 2 hits the
            # dead endpoint (round-robin), gets marked lost, reissues.
            join = _drive_fleet(fleet, rows, results, errors,
                                indices=range(n // 2))
            if not join(timeout_s / 4):
                raise TimeoutError("drill wave 1 did not drain")
            # The abrupt stand-in for SIGKILL: the dead host's sockets
            # close under its clients; its router is simply abandoned.
            servers[kill_rank].close()
            record["killed_at_answered"] = _answered(results)
            join = _drive_fleet(fleet, rows, results, errors,
                                indices=range(n // 2, n))
            new = kdist.reform_group([0])
            t0 = time.monotonic()
            for r in survivors:
                info = routers[r].reanchor(
                    meshes[r],
                    why=f"host loss (group epoch {new.epoch})",
                )
                counters.record(
                    "host_reanchor",
                    f"controller: inhost{r} re-anchored after losing "
                    f"inhost{kill_rank} ({len(info['swapped'])} engine(s))",
                )
            record["reanchor_wall_s"] = round(time.monotonic() - t0, 4)
            if not join(timeout_s / 2):
                raise TimeoutError("drill wave 2 did not drain")
            record["fleet"] = fleet.record()
        record["survivor_counters"] = {
            r: counters.snapshot() for r in survivors
        }
        record["stitched_events"] = 0
    finally:
        for r, s in enumerate(servers):
            if r != kill_rank:
                s.close()
        for r, router in enumerate(routers):
            router.close()
        kdist.shutdown_process_group()
    _judge_answers(record, results, errors, expected)


# -- obs-capture drill --------------------------------------------------------


def _pooled_p99_oracle(record, direct, fleet_hists) -> None:
    """Acceptance (b): the collector's fleet p99 must equal the pick-rule
    percentile of the POOLED per-member samples, and sit near the numpy
    linear-interpolation percentile of the same pool."""
    metric = None
    for cand in ("serve_latency_ms", "wire_request_ms"):
        if any(
            cand in (d.get("hist_windows") or {}) for d in direct.values()
        ):
            metric = cand
            break
    record["pooled_metric"] = metric
    if metric is None:
        record["p99_match"] = False
        return
    pool = [
        float(s)
        for d in direct.values()
        for s in d["hist_windows"].get(metric, {}).get("samples", ())
    ]
    pool.sort()
    record["p99_pool_n"] = len(pool)
    fleet_p99 = fleet_hists.get(metric, {}).get("p99")
    pick = pool[min(len(pool) - 1, int(0.99 * len(pool)))] if pool else None
    p99_np = float(np.percentile(pool, 99)) if pool else None
    record["p99_fleet"] = fleet_p99
    record["p99_oracle_pick"] = pick
    record["p99_oracle_np"] = p99_np
    record["p99_match"] = (
        fleet_p99 is not None
        and fleet_p99 == pick
        and abs(fleet_p99 - p99_np) <= max(0.25 * abs(p99_np), 1e-6)
    )


def _counter_sum_check(record, snap, direct) -> None:
    """Acceptance (a): fleet counters == the sum of per-member snapshots,
    key for key, both directions."""
    sums: dict = {}
    for d in direct.values():
        stz = d.get("statusz", {})
        for group in ("counters", "faults"):
            for k, v in (stz.get(group) or {}).items():
                sums[k] = sums.get(k, 0) + v
    fleet = dict(snap.get("counters", {}))
    for k, v in snap.get("faults", {}).items():
        fleet[k] = fleet.get(k, 0) + v
    mismatched = {
        k: (fleet.get(k), sums.get(k))
        for k in set(fleet) | set(sums)
        if fleet.get(k, 0) != sums.get(k, 0)
    }
    record["counter_sum_ok"] = not mismatched
    if mismatched:
        record["counter_sum_mismatch"] = {
            k: list(v) for k, v in sorted(mismatched.items())[:8]
        }


def _judge_incident(record, bundle_path, survivor_keys) -> None:
    """Acceptance (c): ONE bundle, every surviving member's ring present
    and non-empty, events on one monotone clock-aligned timeline."""
    with open(bundle_path) as fh:
        doc = json.load(fh)
    members = doc.get("members", {})
    ts = [
        ev["ts"]
        for ev in doc.get("events", [])
        if isinstance(ev.get("ts"), (int, float))
    ]
    record["incident"] = {
        "path": bundle_path,
        "schema": doc.get("schema"),
        "trigger": doc.get("trigger", {}).get("kind"),
        "capture_wall_s": doc.get("capture_wall_s"),
        "members": sorted(members),
        "missing": doc.get("missing", []),
        "n_events": len(doc.get("events", [])),
        "survivor_rings_ok": all(
            k in members and members[k].get("events", 0) > 0
            for k in survivor_keys
        ),
        "events_monotone": ts == sorted(ts),
    }


def run_obs_capture_drill(
    tmpdir: str,
    *,
    hosts: int = 2,
    requests: int = 18,
    seed: int = 0,
    local_devices: int = 2,
    subprocess_mode: bool | None = None,
    timeout_s: float = 240.0,
) -> dict:
    """The fleet-observability acceptance drill (ISSUE 20): serve across
    N members with a :class:`~..core.fleetobs.FleetCollector` attached,
    prove on a QUIET fleet that (a) fleet counters equal the sum of
    per-member snapshots and (b) fleet p99 comes from the pooled sample
    windows, then SIGKILL one member mid-scrape and prove (c) the
    collector degrades (``obs_member_lost``), stays monotone for the
    survivors, and writes ONE clock-aligned incident bundle holding
    every surviving member's flight ring — while every request still
    answers bit-equal to the offline oracle (collection must not touch
    the serving answers).

    ``subprocess_mode=False`` degrades to in-process wire servers (one
    process, N sockets) with an abrupt socket close standing in for the
    SIGKILL — the same collector/merge/incident code paths on hosts
    without spawn."""
    from keystone_tpu.parallel import distributed as kdist

    if subprocess_mode is None:
        subprocess_mode = kdist.spawn_available()
    if hosts < 2:
        raise ValueError("the drill needs >= 2 hosts (one must die)")

    import jax.numpy as jnp

    from keystone_tpu.core import frontend as kfrontend
    from keystone_tpu.core import trace
    from keystone_tpu.core.resilience import counters

    mean, std, model = _drill_model(seed)
    stem = _drill_ckpt(tmpdir, seed, mean, std)
    rows = np.asarray(
        np.random.default_rng((seed, 17)).normal(size=(requests, FEAT_DIM)),
        np.float32,
    )
    expected = np.asarray(model(jnp.asarray(rows)))

    pm_dir = os.path.join(tmpdir, "postmortems")
    os.makedirs(pm_dir, exist_ok=True)
    incident_dir = os.path.join(tmpdir, "incidents")
    old_pm = os.environ.get("KEYSTONE_POSTMORTEM_DIR")
    os.environ["KEYSTONE_POSTMORTEM_DIR"] = pm_dir
    kill_rank = hosts - 1
    survivors = [r for r in range(hosts) if r != kill_rank]
    t_start = time.monotonic()
    record: dict = {
        "mode": "subprocess" if subprocess_mode else "inprocess",
        "hosts": hosts,
        "kill_rank": kill_rank,
        "requests": requests,
        "incident_dir": incident_dir,
    }
    try:
        if subprocess_mode:
            _run_obs_drill_subprocess(
                record, tmpdir, stem, seed, hosts, kill_rank, survivors,
                rows, expected, local_devices, timeout_s, kdist, kfrontend,
                counters,
            )
        else:
            _run_obs_drill_inprocess(
                record, stem, seed, hosts, kill_rank, survivors, rows,
                expected, timeout_s, kdist, kfrontend, counters,
            )
    finally:
        if old_pm is None:
            os.environ.pop("KEYSTONE_POSTMORTEM_DIR", None)
        else:
            os.environ["KEYSTONE_POSTMORTEM_DIR"] = old_pm
    record["postmortems"] = sorted(os.listdir(pm_dir))
    record["wall_s"] = round(time.monotonic() - t_start, 3)
    trace.instant(
        "obs_capture_drill", mode=record["mode"], hosts=hosts,
        dropped=record["dropped_requests"],
        mismatches=record["mismatches"],
        incidents=len(record.get("incidents", [])),
    )
    return record


def _obs_drill_collector_phase(
    record, col, fleet, endpoints, kill, rows, expected, survivor_keys,
    timeout_s, counters, kwire,
):
    """The collector-side drill body shared by both modes: quiet-fleet
    merge checks, mid-scrape member death, incident + monotonicity
    judgement.  ``kill()`` is the mode's way of killing the chosen
    member."""
    from keystone_tpu.core import fleetobs  # noqa: F401 — drill subject

    n = len(rows)
    results: list = [None] * n
    errors: list = []
    fleet.attach_collector(col)
    col.start()
    # Wave 1: drive then DRAIN, so the merge checks compare a quiet fleet
    # (counters moving under the comparison would fake a mismatch).
    join = _drive_fleet(fleet, rows, results, errors, indices=range(n // 2))
    if not join(timeout_s / 4):
        raise TimeoutError("obs drill wave 1 did not drain")
    col.stop()
    # Quiet-fleet comparison discipline: one warm scrape FIRST (any
    # pending collector connect/clock handshake lands now), then the
    # direct pulls (each opens a fresh connection the member counts in
    # the very payload it returns), then the comparison scrape — which
    # reuses live connections and moves nothing, so both sides total the
    # same ``wire_connections``.
    col.scrape_once()
    direct = {}
    clients = [kwire.WireClient(ep[0], ep[1], timeout=10.0) for ep in endpoints]
    try:
        # All connections open BEFORE any payload is read: in-process
        # members share one registry, so a later connect would move the
        # counters an earlier payload already reported.
        for ep, c in zip(endpoints, clients):
            d = c.obs_snapshot()
            if d is not None:
                direct[f"{ep[0]}:{ep[1]}"] = d
    finally:
        for c in clients:
            c.close()
    t0 = time.monotonic()
    snap_before = col.scrape_once()
    record["scrape_wall_s"] = round(time.monotonic() - t0, 4)
    _counter_sum_check(record, snap_before, direct)
    _pooled_p99_oracle(record, direct, snap_before.get("histograms", {}))
    lost_before = counters.counts().get("obs_member_lost", 0)
    col.start()  # scraping again: the death below lands mid-cadence
    # Wave 2: the kill lands while requests AND scrapes are in flight.
    join = _drive_fleet(
        fleet, rows, results, errors, indices=range(n // 2, n)
    )
    kill()
    record["killed_at_answered"] = _answered(results)
    if not join(timeout_s / 2):
        raise TimeoutError("obs drill wave 2 did not drain")
    # The collector notices on its own cadence; force one pass if the
    # window closes first (alive->dead triggers exactly once either way).
    end = time.monotonic() + timeout_s / 4
    while (
        counters.counts().get("obs_member_lost", 0) <= lost_before
        and time.monotonic() < end
    ):
        time.sleep(0.05)
    col.stop()
    if counters.counts().get("obs_member_lost", 0) <= lost_before:
        col.scrape_once()
    snap_after = col.scrape_once()
    record["obs_member_lost"] = (
        counters.counts().get("obs_member_lost", 0) - lost_before
    )
    non_mono = {
        k: (v, snap_after["counters"].get(k, 0))
        for k, v in snap_before["counters"].items()
        if snap_after["counters"].get(k, 0) < v
    }
    record["monotone_ok"] = not non_mono
    if non_mono:
        record["monotone_violations"] = {
            k: list(v) for k, v in sorted(non_mono.items())[:8]
        }
    record["fleet_alive"] = snap_after["alive"]
    record["fleet_lost"] = snap_after["lost"]
    record["healthz"] = col.fleet_healthz()
    record["incidents"] = list(col.incident_paths)
    record["collector"] = col.record()
    record["fleet"] = fleet.record()
    _judge_answers(record, results, errors, expected)
    bundles = [
        p for p in col.incident_paths if "obs_member_lost" in p
    ]
    if len(bundles) == 1:
        _judge_incident(record, bundles[0], survivor_keys)
    else:
        record["incident"] = {"error": f"{len(bundles)} bundle(s)"}


def _run_obs_drill_subprocess(
    record, tmpdir, stem, seed, hosts, kill_rank, survivors, rows,
    expected, local_devices, timeout_s, kdist, kfrontend, counters,
) -> None:
    from keystone_tpu.core import fleetobs
    from keystone_tpu.core import wire as kwire

    pm_dir = os.environ["KEYSTONE_POSTMORTEM_DIR"]
    workers: list[_WorkerIO] = []
    try:
        for r in range(hosts):
            env = _hermetic_env(
                kdist.worker_env(
                    r, hosts, "controller", local_devices=local_devices
                ),
                tmpdir, f"obshost{r}",
            )
            env["KEYSTONE_POSTMORTEM_DIR"] = pm_dir
            workers.append(
                _WorkerIO(
                    _worker_cmd(
                        "serve-host", ["--ckpt", stem, "--seed", str(seed)]
                    ),
                    env,
                    os.path.join(tmpdir, f"obshost{r}.err"),
                )
            )
        up = [w.expect("port", timeout_s / 2) for w in workers]
        endpoints = [("127.0.0.1", msg["port"]) for msg in up]
        survivor_keys = [f"127.0.0.1:{up[r]['port']}" for r in survivors]
        with fleetobs.FleetCollector(
            interval_s=0.1, incident_dir=record["incident_dir"],
            window_s=5.0, label="obs-drill",
        ) as col, kfrontend.HostFleet(endpoints, label="obs-drill") as fleet:
            _obs_drill_collector_phase(
                record, col, fleet, endpoints,
                workers[kill_rank].kill, rows, expected, survivor_keys,
                timeout_s, counters, kwire,
            )
        finals = {}
        for r in survivors:
            workers[r].send("quit")
            finals[r] = workers[r].expect("final", timeout_s / 4)["final"]
        record["survivor_counters"] = {
            r: finals[r]["counters"] for r in survivors
        }
    finally:
        record["worker_rcs"] = [w.finish() for w in workers]


def _run_obs_drill_inprocess(
    record, stem, seed, hosts, kill_rank, survivors, rows, expected,
    timeout_s, kdist, kfrontend, counters,
) -> None:
    import jax

    from keystone_tpu.core import fleetobs
    from keystone_tpu.core import serve as kserve
    from keystone_tpu.core import wire as kwire
    from keystone_tpu.core.checkpoint import load_pipeline
    from keystone_tpu.parallel import mesh as kmesh

    devs = jax.devices()
    per = max(1, min(2, len(devs) // hosts))
    routers, servers = [], []
    try:
        meshes = [
            kmesh.make_mesh(
                data=per, model=1, devices=devs[r * per : (r + 1) * per]
            )
            for r in range(hosts)
        ]
        for r in range(hosts):
            model_r = load_pipeline(stem, mesh=meshes[r])

            def build(shape, dtype, mesh_or_none, _m=model_r, _r=r):
                return kserve.ServingEngine(
                    _m,
                    np.zeros(shape, dtype),
                    config=kserve.ServeConfig(
                        buckets=(1, 2, 4), max_wait_ms=2.0
                    ),
                    label=f"obshost{_r}:{'x'.join(str(d) for d in shape)}",
                    mesh=mesh_or_none,
                )

            factory = kfrontend.MeshEngineFactory(build, mesh=meshes[r])
            router = kfrontend.ShapeRouter(factory, label=f"obshost{r}")
            router.add_engine(factory((FEAT_DIM,), np.float32))
            routers.append(router)
            servers.append(
                kwire.WireServer(router, port=0, label=f"obshost{r}")
            )
        endpoints = [("127.0.0.1", s.port) for s in servers]
        survivor_keys = [f"127.0.0.1:{servers[r].port}" for r in survivors]
        with fleetobs.FleetCollector(
            interval_s=0.1, incident_dir=record["incident_dir"],
            window_s=5.0, label="obs-drill",
        ) as col, kfrontend.HostFleet(endpoints, label="obs-drill") as fleet:
            _obs_drill_collector_phase(
                record, col, fleet, endpoints,
                servers[kill_rank].close, rows, expected, survivor_keys,
                timeout_s, counters, kwire,
            )
        record["survivor_counters"] = {
            r: counters.snapshot() for r in survivors
        }
    finally:
        for r, s in enumerate(servers):
            if r != kill_rank:
                s.close()
        for router in routers:
            router.close()


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] not in ("fit-serve", "serve-host"):
        print(
            "usage: python -m keystone_tpu.workloads.multihost "
            "{fit-serve|serve-host} ...",
            file=sys.stderr,
        )
        return 2
    if argv[0] == "fit-serve":
        return fit_serve_main(argv[1:])
    return serve_host_main(argv[1:])


if __name__ == "__main__":
    sys.exit(main())
