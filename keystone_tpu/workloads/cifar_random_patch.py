"""RandomPatchCifar — the images/sec/chip benchmark workload
(reference src/main/scala/pipelines/images/cifar/RandomPatchCifar.scala:17-127).

Flow: CIFAR load -> random patch extraction (Windower -> ImageVectorizer ->
Sampler) -> normalizeRows -> ZCA whitener fit -> whitened+renormalized random
filters -> [Convolver -> SymmetricRectifier -> Pooler -> ImageVectorizer ->
StandardScaler] featurizer -> BlockLeastSquares(4096, 1, λ) -> MaxClassifier
-> MulticlassClassifierEvaluator.

TPU-native deviations from the reference (semantics preserved):

* The reference's Sampler sees every patch of every image lazily via the RDD;
  materializing all ~36M patches in HBM would be absurd, so we window a
  random subset of images large enough to oversample the requested patch
  count 4x, then sample patches from those (statistically equivalent).
* Featurization runs as one jitted chunk-batched program — by default the
  fused compact-activation form (ops/conv_fused.FusedConvFeaturizer: conv
  epilogue stores bf16, pos/neg pools fuse their rectifier reads —
  measured 2.4-2.8x the op-by-op chain, ROOFLINE.md); only the final
  [chunk, d] feature block leaves the device loop.
* The solve is ONE compiled program (solvers/block._fused_bcd_fit):
  centering, grams, Cholesky factors and the scanned BCD epochs fuse into
  a single XLA executable.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import time
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..core import optimize, trace
from ..core import snapshot as ksnap
from ..core.checkpoint import checkpoint_exists, load_pipeline, save_pipeline
from ..core.ingest import stream_batches
from ..core.logging import Logging, configure_logging, stage_timer
from ..core.memory import log_fit_report
from ..core.pipeline import FunctionTransformer, Pipeline
from ..core.resilience import (
    assert_all_finite,
    counters,
    numerics_guard_enabled,
)
from ..evaluation.multiclass import MulticlassClassifierEvaluator
from ..loaders.cifar import LabeledImageBatch, cifar_loader
from ..ops.conv_fused import FusedConvFeaturizer
from ..ops.images import (
    Convolver,
    ImageVectorizer,
    Pooler,
    SymmetricRectifier,
    Windower,
)
from ..ops.stats import Sampler, StandardScaler
from ..ops.util import ClassLabelIndicatorsFromIntLabels, MaxClassifier
from ..parallel.mesh import parse_mesh, row_sharding
from ..solvers.block import BlockLeastSquaresEstimator
from ..solvers.whitening import ZCAWhitenerEstimator
from ..utils.stats import normalize_rows
from . import serve_common
from .fv_common import stream_config_from_flags, stream_features_snapshot


@dataclass
class RandomCifarConfig:
    """Flag-parity with the reference scopt config (:88-99)."""

    train_location: str = ""
    test_location: str = ""
    num_filters: int = 100
    patch_size: int = 6
    patch_steps: int = 1
    pool_size: int = 14
    pool_stride: int = 13
    alpha: float = 0.25
    lam: float | None = None
    sample_frac: float | None = None
    seed: int = 42
    num_classes: int = 10
    image_size: int = 32
    num_channels: int = 3
    whitener_size: int = 100000
    featurize_chunk: int = 2048
    #: BCD solve fault tolerance (single-device fits only) — forwarded to
    #: ``BlockLeastSquaresEstimator.fit(checkpoint=, resume_from=)``.
    solve_checkpoint: object = None
    solve_resume: object = None
    #: Streaming ingest (core.ingest): when set, TEST scoring streams this
    #: JPEG tar — decode of chunk i+1 overlaps the conv featurize of chunk
    #: i — instead of using the eagerly-loaded ``test`` batch.  Member
    #: names carry the label as their leading directory ("<label>/x.jpg").
    stream_test_tar: str | None = None
    #: Cost-based auto-Cacher (core.optimize): profile the conv featurizer
    #: on a sample, measure its fit-path reuse, and insert a memoizing
    #: Cacher only where recompute x reuse beats the HBM cost — instead of
    #: the hand-placed always-materialize.  Decision table in
    #: ``results["cache_plan"]``.
    auto_cache: bool = False
    #: Placement search (core.autoshard): force the cost-model-ranked
    #: candidate search for the block solve (on by default via
    #: ``KEYSTONE_AUTOSHARD``); the searched table lands in
    #: ``results["placement"]`` whenever a search ran.
    auto_shard: bool = False
    #: Placement override forwarded verbatim to ``fit(plan=...)`` —
    #: ``False`` hand ladder, ``True`` force search, a PlacementPlan or
    #: candidate-name list replays/forces a ranking (the chaos harness
    #: forces a SPEC-assignment plan to the top through this).
    solve_plan: object = None
    #: Closed-loop ingest autotuner on the ``--streamTestTar`` path: retune
    #: decode width / ring depth / decode-ahead mid-stream from live stall
    #: metrics (results carry the knob trajectory).
    auto_tune: bool = False
    #: Decode backend for the streamed test tar: "thread" / "process"
    #: (true-parallel spawned decode workers + shared memory); None defers
    #: to ``KEYSTONE_DECODE_BACKEND``.
    decode_backend: str | None = None
    #: Snapshot cache root for the streamed test tar (core.snapshot): the
    #: first pass materializes decoded chunks — or, with
    #: ``KEYSTONE_SNAPSHOT_MODE=featurized``, the conv FEATURES keyed by
    #: the fitted featurizer's digest — and repeat runs stream the shards
    #: at IO speed.  None defers to ``KEYSTONE_SNAPSHOT_DIR``.
    snapshot_dir: str | None = None
    #: Device-resident decode for the streamed test tar (ops.jpeg_device):
    #: the host does the entropy pass only, pixels are born on-device and
    #: fused into the conv featurize.  False defers to
    #: ``KEYSTONE_DEVICE_DECODE``.
    device_decode: bool = False
    #: Whole-fitted-SERVABLE-pipeline checkpoint stem (core.checkpoint):
    #: load-or-fit of conv featurizer + scaler + model + classifier — the
    #: artifact the serving endpoint warm-loads.
    pipeline_file: str | None = None
    #: Serving modes (core.serve via serve_common); both need
    #: ``pipeline_file`` and an eager test split (requests are test images).
    serve: bool = False
    serve_bench: bool = False
    serve_clients: int = 4
    serve_requests: int = 256
    #: ``--serveMesh DxM``: serve on an explicit mesh — the checkpoint
    #: reshards onto it and buckets AOT-compile mesh-native (ISSUE 16).
    serve_mesh: str | None = None


class _Log(Logging):
    pass


def learn_filters(conf: RandomCifarConfig, train_images: np.ndarray):
    """Patch sampling + ZCA + filter construction (reference :38-51).

    Returns (filters [F, ps*ps*C], whitener).
    """
    n, h, w, c = train_images.shape
    ppi = ((h - conf.patch_size) // conf.patch_steps + 1) * (
        (w - conf.patch_size) // conf.patch_steps + 1
    )
    # Oversample 4x the requested patch count from a random image subset.
    need_imgs = min(n, max(1, -(-4 * conf.whitener_size // ppi)))
    rng = np.random.default_rng(conf.seed)
    img_idx = rng.permutation(n)[:need_imgs]
    subset = jnp.asarray(train_images[img_idx])

    patches = Windower(conf.patch_steps, conf.patch_size)(subset)
    patch_vecs = ImageVectorizer()(patches)
    sampled = Sampler(conf.whitener_size, conf.seed)(patch_vecs)

    base_filter_mat = normalize_rows(sampled, 10.0)
    whitener = ZCAWhitenerEstimator().fit_single(base_filter_mat)

    sample_filters = Sampler(conf.num_filters, conf.seed + 1)(base_filter_mat)
    unnorm = whitener(sample_filters)
    two_norms = jnp.linalg.norm(unnorm, axis=1, keepdims=True)
    filters = (unnorm / (two_norms + 1e-10)) @ whitener.whitener.T
    return filters, whitener


def build_conv_pipeline(
    conf: RandomCifarConfig, filters, whitener, fused: bool | None = None
) -> Pipeline:
    """Convolver -> SymmetricRectifier -> Pooler -> ImageVectorizer (:53-56).

    By default the chain is the fused compact-activation form
    (ops/conv_fused.FusedConvFeaturizer — measured 2.4-2.8x the op-by-op
    pipeline on v5e, see ROOFLINE.md; identical element order, ~9e-4
    relative difference from bf16 activation storage).  ``fused=False`` (or
    ``KEYSTONE_FUSED=0``) selects the op-by-op exact-f32 chain.
    """
    if fused is None:
        fused = os.environ.get("KEYSTONE_FUSED", "").strip() != "0"
    if fused:
        return Pipeline(
            [
                FusedConvFeaturizer(
                    filters,
                    whitener_means=whitener.means,
                    pool_stride=conf.pool_stride,
                    pool_size=conf.pool_size,
                    alpha=conf.alpha,
                    normalize_patches=True,
                    img_channels=conf.num_channels,
                )
            ]
        )
    return Pipeline(
        [
            Convolver(
                filters,
                whitener_means=whitener.means,
                normalize_patches=True,
                img_channels=conf.num_channels,
            ),
            SymmetricRectifier(alpha=conf.alpha),
            Pooler(conf.pool_stride, conf.pool_size, None, "sum"),
            ImageVectorizer(),
        ]
    )


def featurize_chunked(fn, images: np.ndarray, chunk: int, mesh=None) -> jnp.ndarray:
    """Run the jitted featurizer ``fn`` over fixed-size chunks (pad the tail)
    so the conv activations never exceed one chunk's footprint in HBM.

    With ``mesh``, each chunk is row-sharded over the data axis so the
    conv/rectify/pool program runs data-parallel across the mesh."""
    n = images.shape[0]
    sharding = None
    if mesh is not None:
        d = mesh.shape["data"]
        chunk = -(-chunk // d) * d  # chunk must split evenly across the axis
        sharding = row_sharding(mesh)
    outs = []
    for i in range(0, n, chunk):
        block = images[i : i + chunk]
        pad = chunk - block.shape[0]
        if pad:
            block = np.pad(block, ((0, pad), (0, 0), (0, 0), (0, 0)))
        dev_block = jnp.asarray(block)
        if sharding is not None:
            dev_block = jax.device_put(dev_block, sharding)
        feats = fn(dev_block)
        outs.append(feats[: chunk - pad] if pad else feats)
    return jnp.concatenate(outs, axis=0)


def cifar_tar_label(name: str) -> int:
    """Class id from a tar member's leading directory ("<label>/img.jpg" —
    the synset-style layout the streaming CIFAR tar uses)."""
    return int(name.split("/", 1)[0])


def cifar_tar_loader(path: str) -> LabeledImageBatch:
    """Eager CIFAR-from-JPEG-tar loader ("<label>/img.jpg" members, images
    >= 36 px — the loaders' MIN_DIM floor rules out true-32px JPEGs):
    threaded tar decode, labels parsed from member names.  The eager
    counterpart of ``--streamTestTar``, and the train-side loader when a
    CIFAR-style dataset ships as a JPEG tar (filter learning needs the
    images resident)."""
    from ..loaders.image_loaders import _iter_tar_images

    pairs = list(_iter_tar_images(path))
    if not pairs:
        return LabeledImageBatch(
            np.zeros((0, 1, 1, 3), np.float32), np.zeros(0, np.int32)
        )
    return LabeledImageBatch(
        np.stack([img for _, img in pairs]),
        np.asarray([cifar_tar_label(n) for n, _ in pairs], np.int32),
    )


def cifar_tar_stream_loader(
    path: str, *, batch: int = 256, config=None
) -> LabeledImageBatch:
    """Streamed counterpart of :func:`cifar_tar_loader` (ROADMAP
    carry-over: the streamed TRAIN path): the resident subset filter
    learning needs is decoded through ``core.ingest`` — overlapped decode
    pool, corrupt members skipped-and-counted, and with
    ``config.snapshot_dir`` set the decoded chunks tee into the
    materialized snapshot cache so repeat fits stream the images at IO
    speed — instead of the eager threaded decode.  Batches scatter back to
    stream-ordinal (tar member) order, so the result is BIT-IDENTICAL to
    the eager loader on a clean tar: same images array, same labels, same
    order (the tests pin it)."""
    if config is not None and config.decode_mode == "device":
        # This loader's CONTRACT is host-resident pixels bit-identical to
        # the eager loader (the filter-learning subset lives in host RAM);
        # device decode would hand back coefficient chunks with no host
        # batch and tolerance-level pixels.  Pin host decode, counted —
        # an env-seeded KEYSTONE_DEVICE_DECODE=1 must not crash the
        # streamed TRAIN path (the streamed TEST path honors it).
        counters.record(
            "device_decode_unsupported",
            f"{path}: cifar_tar_stream_loader needs host-resident pixels "
            "— decode_mode='device' ignored for the train stream",
        )
        config = dataclasses.replace(config, decode_mode="host")
    parts: list = []
    name_pairs: list = []
    n = 0
    with stream_batches(path, batch, config=config, transfer=False) as st:
        for b in st:
            parts.append((np.asarray(b.indices), np.asarray(b.host)))
            name_pairs.extend(zip(b.indices.tolist(), b.names))
            n += len(b)
    if not parts:
        return LabeledImageBatch(
            np.zeros((0, 1, 1, 3), np.float32), np.zeros(0, np.int32)
        )
    shape = parts[0][1].shape[1:]
    images = np.zeros((n,) + shape, np.float32)
    for idx, imgs in parts:
        images[idx] = imgs
    names = [None] * n
    for i, name in name_pairs:
        names[i] = name
    labels = np.asarray([cifar_tar_label(nm) for nm in names], np.int32)
    return LabeledImageBatch(images, labels)


def _pad_to_chunk(batch, chunk: int):
    """One streamed batch padded up to the compiled ``chunk`` rows (the
    jitted featurizer has exactly one shape) — THE single implementation
    of the compiled-chunk contract for the streaming paths.  Coefficient
    chunks (device decode, ``batch.host is None``) materialize their
    pixels on-device and pad THERE — the batch never round-trips through
    the host."""
    rows = len(batch)
    pad = chunk - rows
    if pad < 0:
        raise ValueError(
            f"streamed batch of {rows} rows exceeds the "
            f"compiled featurize chunk {chunk} — stream with "
            "batch_size == featurize_chunk"
        )
    if pad > 0:
        if batch.host is None:
            return jnp.pad(
                batch.dev(), ((0, pad), (0, 0), (0, 0), (0, 0))
            )
        return jnp.asarray(
            np.pad(batch.host, ((0, pad), (0, 0), (0, 0), (0, 0)))
        )
    return batch.dev()


def featurize_stream(fn, stream, chunk: int) -> tuple[np.ndarray, list]:
    """Streaming counterpart of :func:`featurize_chunked`: consume
    batch-assembled device chunks from ``core.ingest`` — the decode of
    chunk *i+1* runs on host threads (and its H2D is already dispatched)
    while the jitted featurizer runs chunk *i* — padding each chunk to the
    compiled ``chunk`` rows.  The host sync lands only on the consumed
    chunk's features.  Returns features scattered back to stream-ordinal
    order plus the member names in that order.

    Delegates to :func:`~.fv_common.stream_features_snapshot`'s live pass
    (no snapshot root), the same loop ``run()`` drives — the streamed
    compiled-chunk contract has exactly one implementation."""
    import contextlib

    feats, names, _ = stream_features_snapshot(
        lambda: contextlib.nullcontext(stream),
        lambda batch: np.asarray(fn(_pad_to_chunk(batch, chunk))),
    )
    return feats, names


def run(
    conf: RandomCifarConfig,
    train: LabeledImageBatch,
    test: LabeledImageBatch,
    mesh=None,
) -> dict:
    """With ``mesh``, featurization chunks are row-sharded over the data
    axis and the block solver runs fully distributed — the reference runs
    everything over partitioned RDDs (RandomPatchCifar.scala:20-85).
    Filter learning stays replicated: it is the analog of the reference's
    driver-local ZCA fit (:38-51)."""
    configure_logging()
    log = _Log()
    t0 = time.perf_counter()

    if conf.pipeline_file is not None and checkpoint_exists(conf.pipeline_file):
        # Deploy-once/apply-many: filter learning, featurize, and the solve
        # are all skipped — the servable chain restores whole and the run
        # scores/serves the eager test split with it.
        return _run_restored(conf, test, log, t0)

    if conf.sample_frac is not None:
        rng = np.random.default_rng(conf.seed)
        keep = rng.random(len(train)) < conf.sample_frac
        train = LabeledImageBatch(train.images[keep], train.labels[keep])

    with stage_timer("learn_filters"):
        filters, whitener = learn_filters(conf, train.images)
    conv_pipe = build_conv_pipeline(conf, filters, whitener)
    feat_fn = jax.jit(conv_pipe.__call__)

    # Warm the compile cache so the throughput number is steady-state — with
    # the same chunk shape AND sharding the real featurize pass will use.
    warm_chunk = conf.featurize_chunk
    warm = jnp.zeros((warm_chunk,) + train.images.shape[1:], jnp.float32)
    if mesh is not None:
        d = mesh.shape["data"]
        warm_chunk = -(-warm_chunk // d) * d
        warm = jax.device_put(
            jnp.zeros((warm_chunk,) + train.images.shape[1:], jnp.float32),
            row_sharding(mesh),
        )
    feat_fn(warm).block_until_ready()

    cache_plan = None
    if conf.auto_cache:
        # The KeystoneML optimizer pass: the conv featurizer is the
        # expensive upstream of the StandardScaler thenEstimator chain —
        # fitting pushes the images through it once and applying the
        # fitted pipeline pushes them through AGAIN (reuse=2, measured,
        # not assumed).  auto_cache_chain profiles a sample, scales to the
        # dataset, and inserts a memoizing Cacher only when the recompute
        # win beats the HBM cost (admitted per-chip under a mesh).
        feat_node = FunctionTransformer(
            lambda imgs: featurize_chunked(
                feat_fn, np.asarray(imgs), conf.featurize_chunk, mesh=mesh
            ),
            name="conv_featurize",
        )
        sample = train.images[: min(len(train.images), conf.featurize_chunk)]
        chain, cache_plan = optimize.auto_cache_chain(
            feat_node.then_estimator(StandardScaler()),
            sample,
            dataset_rows=len(train.images),
            mesh=mesh,
        )
        log.log_info("%s", cache_plan.summary())
        # Timed from AFTER the optimizer's sample profiling so
        # featurize_seconds measures the actual fit chain; note it covers
        # conv + scaler fit + scaled apply (they are one chain here),
        # whereas the manual path's figure is conv only.
        t_feat = time.perf_counter()
        with stage_timer("featurize"):
            fitted_feats = chain.fit(train.images)
            train_features = fitted_feats(train.images)
            train_features.block_until_ready()
        feat_secs = time.perf_counter() - t_feat
        # The scaler model is the chain's tail; the test path applies it to
        # freshly-featurized test data exactly like the manual path.
        scaler = fitted_feats.nodes[-1]
        # The memo held the conv intermediate alive for the replay above —
        # release it before the solve claims HBM.
        optimize.release_caches(fitted_feats)
    else:
        t_feat = time.perf_counter()
        with stage_timer("featurize"):
            train_conv = featurize_chunked(
                feat_fn, train.images, conf.featurize_chunk, mesh=mesh
            )
            train_conv.block_until_ready()
        feat_secs = time.perf_counter() - t_feat

        # StandardScaler fit on train features (thenEstimator, reference :58)
        scaler = StandardScaler().fit(train_conv)
        train_features = scaler(train_conv)

    labels = ClassLabelIndicatorsFromIntLabels(conf.num_classes)(train.labels)
    with stage_timer("solve"):
        solver = BlockLeastSquaresEstimator(4096, 1, conf.lam or 0.0, mesh=mesh)
        model = solver.fit(
            train_features,
            labels,
            checkpoint=conf.solve_checkpoint,
            resume_from=conf.solve_resume,
            plan=(
                conf.solve_plan if conf.solve_plan is not None
                else (True if conf.auto_shard else None)
            ),
        )
        log_fit_report(solver, label="cifar random-patch solve")
        if numerics_guard_enabled():
            # Typed failure (FloatingPointError) instead of NaN predictions.
            assert_all_finite(model, "cifar random-patch model")

    def predict(features):
        return MaxClassifier()(model(features))

    with stage_timer("eval"):
        train_pred = predict(train_features)
        train_eval = MulticlassClassifierEvaluator(
            train_pred, train.labels, conf.num_classes
        )

        if conf.stream_test_tar is not None:
            # Streaming ingest: JPEG decode of the next chunk overlaps the
            # conv featurize of the current one (core.ingest ring buffer +
            # double-buffered H2D); labels ride in the member names.  The
            # config carries the decode backend and snapshot knobs
            # (flags override the KEYSTONE_* env defaults).
            stream_cfg = stream_config_from_flags(
                autotune=conf.auto_tune,
                decode_backend=conf.decode_backend,
                snapshot_dir=conf.snapshot_dir,
                device_decode=conf.device_decode,
                # this path wraps the stream in stream_features_snapshot,
                # so mode=featurized is honored rather than degraded
                supports_featurized=True,
            )
            chunk = conf.featurize_chunk

            def conv_per_batch(batch):
                return np.asarray(
                    feat_fn(_pad_to_chunk(batch, chunk))
                )[: len(batch)]

            snap_root = snap_key = None
            if (
                stream_cfg.snapshot_dir
                and stream_cfg.snapshot_mode == "featurized"
            ):
                # Featurized snapshot: keyed by the fitted conv pipeline's
                # checkpoint digest — new filters/whitener = new key, so a
                # refit can never replay stale features.
                snap_root = stream_cfg.snapshot_dir
                snap_key = ksnap.snapshot_key(
                    conf.stream_test_tar,
                    batch_size=chunk,
                    mode="featurized",
                    featurizer=ksnap.featurizer_digest(conv_pipe),
                    # decode_mode changes the PIXELS the features were
                    # computed from (device decode differs within IDCT
                    # rounding) — fold it in so a host-decode run can
                    # never silently replay device-decoded features or
                    # vice versa.
                    extra=f"decode_mode={stream_cfg.decode_mode}",
                )
            test_feats, names, st = stream_features_snapshot(
                lambda: stream_batches(
                    conf.stream_test_tar, chunk, config=stream_cfg
                ),
                conv_per_batch,
                root=snap_root,
                key=snap_key,
                tar_path=conf.stream_test_tar,
                meta={"tar": ksnap.tar_identity(conf.stream_test_tar)},
            )
            if st is not None and st.tuner is not None:
                results_autotune = st.tuner.record()
                log.log_info(
                    "ingest autotune: %d retune(s), final config %s",
                    results_autotune["retunes"],
                    results_autotune["final_config"],
                )
            else:
                results_autotune = None
            test_labels = np.asarray(
                [cifar_tar_label(n) for n in names], np.int32
            )
            test_pred = predict(scaler(jnp.asarray(test_feats)))
        else:
            test_labels = test.labels
            test_conv = featurize_chunked(
                feat_fn, test.images, conf.featurize_chunk, mesh=mesh
            )
            test_pred = predict(scaler(test_conv))
        test_eval = MulticlassClassifierEvaluator(
            test_pred, test_labels, conf.num_classes
        )

    secs = time.perf_counter() - t0
    results = {
        "train_error": 100.0 * train_eval.total_error,
        "test_error": 100.0 * test_eval.total_error,
        # Predicted labels on the test split — the chaos harness diffs
        # these against the fault-free run to rule out silent wrong models.
        "test_predictions": np.asarray(test_pred),
        "seconds": secs,
        "featurize_seconds": feat_secs,
        "featurize_images_per_sec": len(train) / feat_secs,
    }
    if cache_plan is not None:
        results["cache_plan"] = cache_plan.record()
    rep = solver.last_fit_report
    if rep is not None and rep.placement is not None:
        # The searched placement table — candidates, deny/score rationale,
        # chosen plan with predicted-vs-actual cost.
        results["placement"] = rep.placement
    if conf.stream_test_tar is not None and results_autotune is not None:
        results["autotune"] = results_autotune
    # The fitted SERVABLE chain, checkpointed whole for the endpoint:
    # conv featurizer + fitted scaler + model + classifier as ONE pipeline
    # (model splits the features by its own fitted block widths).
    servable = Pipeline([*conv_pipe.nodes, scaler, model, MaxClassifier()])
    if conf.pipeline_file is not None:
        from ..core import numerics as knum

        # Fit-time output baseline (ISSUE 15): the predicted-class
        # distribution rides the checkpoint manifest, so the serving
        # tier's drift monitor has a reference to judge live answers
        # against from the moment the engine warm-loads.
        save_pipeline(
            conf.pipeline_file,
            servable,
            numerics_baseline=knum.OutputSketch.for_outputs(
                results["test_predictions"]
            ).record(),
        )
        log.log_info("saved fitted servable pipeline to %s", conf.pipeline_file)
    _maybe_serve(conf, test, results, log)
    log.log_info("Training error is: %s", train_eval.total_error)
    log.log_info("Test error is: %s", test_eval.total_error)
    log.log_info("Pipeline took %.3f s", secs)
    return results


def _apply_servable_chunked(servable, images: np.ndarray, chunk: int):
    """Apply the servable chain in fixed-size chunks (pad the tail) so the
    conv activations never exceed one chunk's HBM footprint — the restored
    path's analog of :func:`featurize_chunked`."""
    outs = []
    for i in range(0, images.shape[0], chunk):
        block = images[i : i + chunk]
        pad = chunk - block.shape[0]
        if pad:
            block = np.pad(block, ((0, pad), (0, 0), (0, 0), (0, 0)))
        pred = np.asarray(servable(jnp.asarray(block)))
        outs.append(pred[: chunk - pad] if pad else pred)
    return np.concatenate(outs, axis=0)


def _run_restored(conf: RandomCifarConfig, test, log, t0: float) -> dict:
    """Score (and serve) with the restored servable pipeline — no refit."""
    log.log_info(
        "restoring fitted servable pipeline from %s", conf.pipeline_file
    )
    servable = load_pipeline(conf.pipeline_file)
    if len(test.labels) == 0:
        raise ValueError(
            "restored servable runs score the EAGER test split — provide "
            "--testLocation (streamed test tars have no resident images "
            "to serve)"
        )
    test_pred = _apply_servable_chunked(
        servable, np.asarray(test.images, np.float32), conf.featurize_chunk
    )
    test_eval = MulticlassClassifierEvaluator(
        test_pred, test.labels, conf.num_classes
    )
    results: dict = {
        "restored": True,
        "test_error": 100.0 * test_eval.total_error,
        "test_predictions": np.asarray(test_pred),
    }
    log.log_info(
        "Test error is: %s (restored pipeline)", test_eval.total_error
    )
    _maybe_serve(conf, test, results, log)
    results["seconds"] = time.perf_counter() - t0
    return results


def _maybe_serve(conf: RandomCifarConfig, test, results: dict, log) -> None:
    if not (conf.serve or conf.serve_bench):
        return
    if conf.pipeline_file is None:
        raise ValueError(
            "--serve/--serveBench need --pipelineFile — the endpoint "
            "warm-loads the fitted artifact, it never refits"
        )
    if len(test.labels) == 0:
        raise ValueError(
            "serving draws its requests from the EAGER test split — "
            "provide --testLocation"
        )
    requests = np.asarray(test.images[: conf.serve_requests], np.float32)
    results["serving"] = serve_common.serve_fitted(
        conf.pipeline_file,
        jax.ShapeDtypeStruct(tuple(requests.shape[1:]), np.float32),
        requests,
        label="random_patch_cifar",
        bench=conf.serve_bench,
        clients=conf.serve_clients,
        mesh=serve_common.resolve_serve_mesh(conf.serve_mesh),
    )


def main(argv=None):
    p = argparse.ArgumentParser("RandomPatchCifar")
    p.add_argument(
        "--trainLocation",
        default=None,
        help="CIFAR binary (or JPEG tar); optional when --streamTrainTar "
        "supplies the train split",
    )
    p.add_argument(
        "--testLocation",
        default=None,
        help="CIFAR binary (or JPEG tar); optional when --streamTestTar "
        "supplies the test split",
    )
    p.add_argument("--numFilters", type=int, default=100)
    p.add_argument("--patchSize", type=int, default=6)
    p.add_argument("--patchSteps", type=int, default=1)
    p.add_argument("--poolSize", type=int, default=14)
    p.add_argument("--poolStride", type=int, default=13)
    p.add_argument("--alpha", type=float, default=0.25)
    p.add_argument("--lambda", dest="lam", type=float, default=None)
    p.add_argument("--sampleFrac", type=float, default=None)
    p.add_argument("--whitenerSize", type=int, default=100000)
    p.add_argument(
        "--streamTestTar",
        default=None,
        help="streaming ingest: score test from this JPEG tar "
        "('<label>/name.jpg' members) with decode/featurize overlap",
    )
    p.add_argument(
        "--streamTrainTar",
        default=None,
        help="streaming ingest for the TRAIN split: decode this JPEG tar "
        "('<label>/name.jpg' members) through core.ingest into the "
        "resident images filter learning needs — overlapped decode, "
        "snapshot-cache warm repeats via --snapshotDir, bit-identical to "
        "the eager loader (replaces --trainLocation)",
    )
    p.add_argument(
        "--decodeBackend",
        default=None,
        choices=("thread", "process"),
        help="decode backend for --streamTestTar: 'process' decodes on "
        "spawned worker processes (shared-memory return path, true "
        "parallel) instead of the GIL-bound thread pool "
        "(KEYSTONE_DECODE_BACKEND equivalent)",
    )
    p.add_argument(
        "--snapshotDir",
        default=None,
        help="snapshot cache root for --streamTestTar (core.snapshot): "
        "first pass materializes decoded chunks (or conv FEATURES under "
        "KEYSTONE_SNAPSHOT_MODE=featurized, keyed by the fitted "
        "featurizer's digest; or DEVICE-FORMAT shards under "
        "KEYSTONE_SNAPSHOT_MODE=device — warm epochs are pure DMA); "
        "repeat runs stream the shards at IO speed "
        "(KEYSTONE_SNAPSHOT_DIR equivalent)",
    )
    p.add_argument(
        "--deviceDecode",
        action="store_true",
        help="device-resident JPEG decode for --streamTestTar "
        "(ops.jpeg_device): the host runs the entropy pass only, pixels "
        "are born on-device fused into the conv featurize; unsupported "
        "JPEGs fall back to host decode counted per reason "
        "(KEYSTONE_DEVICE_DECODE=1 equivalent)",
    )
    p.add_argument(
        "--mesh",
        default=None,
        help="device mesh, e.g. '8' (data) or '4x2' (data x model)",
    )
    p.add_argument(
        "--autoCache",
        action="store_true",
        help="cost-based auto-Cacher (core.optimize): profile the conv "
        "featurizer on a sample and cache its output only where "
        "recompute x reuse beats the HBM cost (KEYSTONE_AUTOCACHE=1 "
        "equivalent)",
    )
    p.add_argument(
        "--autoShard",
        action="store_true",
        help="placement search (core.autoshard): force the cost-model "
        "ranked mesh/strategy candidate search for the block solve and "
        "record the searched plan in results['placement'] (on by "
        "default; KEYSTONE_AUTOSHARD=0 disables it except here)",
    )
    p.add_argument(
        "--autoTune",
        action="store_true",
        help="closed-loop ingest autotuner on --streamTestTar: retune "
        "decode width / ring depth / decode-ahead mid-stream from live "
        "stall metrics (KEYSTONE_AUTOTUNE=1 equivalent)",
    )
    p.add_argument(
        "--pipelineFile",
        default=None,
        help="fitted-SERVABLE-pipeline checkpoint stem: load-or-fit of "
        "conv featurizer + scaler + model + classifier in one artifact "
        "(what --serve/--serveBench warm-load)",
    )
    serve_common.add_serve_args(p)
    p.add_argument(
        "--trace",
        default=None,
        metavar="PATH",
        help="write a Chrome-trace JSON (Perfetto-loadable; .jsonl for the "
        "JSONL event log) of the run — the KEYSTONE_TRACE env equivalent",
    )
    a = p.parse_args(argv)
    if a.trace:
        trace.enable(a.trace)
    if (a.serve or a.serveBench) and not a.pipelineFile:
        p.error("--serve/--serveBench require --pipelineFile")
    if (a.serve or a.serveBench) and a.streamTestTar is not None:
        p.error(
            "--serve/--serveBench draw requests from the eager test split "
            "— use --testLocation, not --streamTestTar, for serving runs"
        )
    # Before the load stage timer, so its log line has a handler to land on
    # (run() re-applies the same idempotent configuration).
    configure_logging()
    if a.trainLocation is None and a.streamTrainTar is None:
        p.error("one of --trainLocation / --streamTrainTar is required")
    conf = RandomCifarConfig(
        train_location=a.trainLocation or a.streamTrainTar,
        test_location=a.testLocation,
        num_filters=a.numFilters,
        patch_size=a.patchSize,
        patch_steps=a.patchSteps,
        pool_size=a.poolSize,
        pool_stride=a.poolStride,
        alpha=a.alpha,
        lam=a.lam,
        sample_frac=a.sampleFrac,
        whitener_size=a.whitenerSize,
        stream_test_tar=a.streamTestTar,
        auto_cache=a.autoCache or optimize.auto_cache_env(),
        auto_shard=a.autoShard,
        auto_tune=a.autoTune,
        decode_backend=a.decodeBackend,
        snapshot_dir=a.snapshotDir,
        device_decode=a.deviceDecode,
        pipeline_file=a.pipelineFile,
        serve=a.serve,
        serve_bench=a.serveBench,
        serve_clients=a.serveClients,
        serve_requests=a.serveRequests,
        serve_mesh=a.serveMesh,
    )
    if a.testLocation is None and a.streamTestTar is None:
        p.error("one of --testLocation / --streamTestTar is required")

    def load_split(location):
        # JPEG tars ("<label>/img.jpg" members) load through the threaded
        # tar decoder; anything else is the CIFAR binary format.
        if location.endswith((".tar", ".tar.gz", ".tgz")):
            return cifar_tar_loader(location)
        return cifar_loader(location)

    with stage_timer("load"):
        if a.streamTrainTar is not None:
            # Streamed TRAIN path: the resident subset filter learning
            # needs arrives through core.ingest (+ the snapshot cache when
            # --snapshotDir is set) instead of eager threaded decode —
            # bit-identical images/labels, warm repeats at IO speed.
            train = cifar_tar_stream_loader(
                a.streamTrainTar,
                batch=conf.featurize_chunk,
                config=stream_config_from_flags(
                    decode_backend=conf.decode_backend,
                    snapshot_dir=conf.snapshot_dir,
                ),
            )
        else:
            train = load_split(conf.train_location)
        if a.streamTestTar is not None:
            # streamed test split: run() never touches the eager test
            # batch — loading --testLocation too would decode a tar just
            # to discard it
            test = LabeledImageBatch(
                np.zeros((0,) + train.images.shape[1:], np.float32),
                np.zeros(0, np.int32),
            )
        else:
            test = load_split(a.testLocation)
    try:
        return run(conf, train, test, mesh=parse_mesh(a.mesh))
    finally:
        if a.trace:
            trace.flush()


if __name__ == "__main__":
    main()
