"""NewsgroupsPipeline — 20 Newsgroups text classification
(reference src/main/scala/pipelines/text/NewsgroupsPipeline.scala:14-75).

Trim -> LowerCase -> Tokenizer -> NGrams(1..n) -> TermFrequency(x=>1) ->
CommonSparseFeatures(k) -> NaiveBayes -> MaxClassifier ->
MulticlassClassifierEvaluator (pretty summary per class).
"""

from __future__ import annotations

import argparse
import contextlib
import time

from dataclasses import dataclass

import numpy as np

from ..core.logging import Logging, configure_logging
from ..evaluation.multiclass import MulticlassClassifierEvaluator
from ..loaders.newsgroups import CLASSES, NewsgroupsData, newsgroups_loader
from ..ops.nlp import LowerCase, NGramsFeaturizer, TermFrequency, Tokenizer, Trim
from ..ops.sparse import CommonSparseFeatures
from ..ops.util import MaxClassifier
from ..parallel.mesh import parse_mesh, use_mesh
from ..solvers.naive_bayes import NaiveBayesEstimator


@dataclass
class NewsgroupsConfig:
    """Flag-parity with the reference scopt config (:46-50)."""

    train_location: str = ""
    test_location: str = ""
    n_grams: int = 2
    common_features: int = 100000
    classes: tuple = tuple(CLASSES)


class _Log(Logging):
    pass


def run(
    conf: NewsgroupsConfig,
    train: NewsgroupsData,
    test: NewsgroupsData,
    mesh=None,
) -> dict:
    """With ``mesh``: naive-Bayes scoring runs data-parallel over the mesh —
    per-device COO shards contracted against the replicated ``theta`` under
    ``shard_map`` (see NaiveBayesModel._apply_csr_mesh).  The text
    featurization and the NB count aggregation stay host-side, like the
    reference's per-executor text processing feeding MLlib
    (NewsgroupsPipeline.scala:14-75)."""
    configure_logging()
    log = _Log()
    t0 = time.perf_counter()
    num_classes = len(conf.classes)
    mesh_ctx = use_mesh(mesh) if mesh is not None else contextlib.nullcontext()

    log.log_info("Training classifier")
    text_pipe = (
        Trim()
        .then(LowerCase())
        .then(Tokenizer())
        .then(NGramsFeaturizer(range(1, conf.n_grams + 1)))
        .then(TermFrequency(lambda x: 1))
    )
    train_terms = text_pipe(train.data)
    vectorizer = CommonSparseFeatures(conf.common_features).fit(train_terms)
    train_feats = vectorizer(train_terms)
    model = NaiveBayesEstimator(num_classes).fit(train_feats, train.labels)

    log.log_info("Evaluating classifier")
    test_feats = vectorizer(text_pipe(test.data))
    with mesh_ctx:
        predictions = np.asarray(MaxClassifier()(model(test_feats)))
    ev = MulticlassClassifierEvaluator(predictions, test.labels, num_classes)
    results = {
        "test_error": 100.0 * ev.total_error,
        "seconds": time.perf_counter() - t0,
        "evaluator": ev,
    }
    log.log_info("\n%s", ev.summary(list(conf.classes)))
    return results


def main(argv=None):
    p = argparse.ArgumentParser("NewsgroupsPipeline")
    p.add_argument("--trainLocation", required=True)
    p.add_argument("--testLocation", required=True)
    p.add_argument("--nGrams", type=int, default=2)
    p.add_argument("--commonFeatures", type=int, default=100000)
    p.add_argument(
        "--mesh",
        default=None,
        help="device mesh, e.g. '8' (data) or '4x2' (data x model)",
    )
    a = p.parse_args(argv)
    conf = NewsgroupsConfig(
        train_location=a.trainLocation,
        test_location=a.testLocation,
        n_grams=a.nGrams,
        common_features=a.commonFeatures,
    )
    train = newsgroups_loader(conf.train_location)
    test = newsgroups_loader(conf.test_location)
    return run(conf, train, test, mesh=parse_mesh(a.mesh))


if __name__ == "__main__":
    main()
