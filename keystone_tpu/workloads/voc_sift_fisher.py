"""VOCSIFTFisher — multi-label VOC 2007 classification via SIFT + Fisher
vectors (reference src/main/scala/pipelines/images/voc/VOCSIFTFisher.scala:18-165).

Flow: VOC load -> grayscale -> dense SIFT -> [PCA fit or load] -> BatchPCA ->
[GMM fit or load] -> FisherVector -> vectorize/normalize/hellinger/normalize
-> BlockLeastSquares(4096, 1, λ) -> per-class scores -> 11-point MAP.

The pcaFile/gmm*File flags implement the reference's load-or-fit artifact
checkpoint pattern (SURVEY §5).
"""

from __future__ import annotations

import argparse
import os
import time
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..core import optimize, trace
from ..core.checkpoint import checkpoint_exists, load_pipeline, save_pipeline
from ..core.ingest import stream_batches
from ..core.logging import Logging, configure_logging, stage_timer
from ..core.memory import log_fit_report
from ..core.pipeline import FunctionTransformer, Pipeline
from ..core.resilience import assert_all_finite
from ..evaluation.map import MeanAveragePrecisionEvaluator
from ..loaders.image_loaders import (
    VOC_NUM_CLASSES,
    MultiLabeledImages,
    voc_labels_map,
    voc_loader,
)
from ..ops.sift import SIFTExtractor
from ..ops.util import ClassLabelIndicatorsFromIntArrayLabels
from ..parallel.mesh import parse_mesh
from ..solvers.block import BlockLeastSquaresEstimator
from ..solvers.gmm import GaussianMixtureModel, GaussianMixtureModelEstimator
from ..solvers.pca import BatchPCATransformer, compute_pca
from . import serve_common
from .fv_common import (
    bucket_by_shape,
    collect_autotune,
    fisher_feature_pipeline,
    grayscale,
    plan_pca_materialization,
    record_stream_autotune,
    sample_columns,
    scatter_features,
    searched_bucket_featurize,
    stream_config_from_flags,
    stream_descriptor_buckets,
)


@dataclass
class VOCStreamSource:
    """Streaming stand-in for :class:`MultiLabeledImages` (core.ingest):
    images are decoded from the tar WHILE the device featurizes — SIFT on
    batch *i* overlaps decode of batch *i+1* — instead of the eager
    decode-everything-first path.  ``labels``/``len`` become available
    after the descriptor pass records the decode-survival order."""

    data_path: str
    labels_path: str
    name_prefix: str = "VOCdevkit/VOC2007/JPEGImages/"
    batch_size: int = 64
    #: closed-loop ingest autotuner on this source's streams (--autoTune)
    autotune: bool = False
    #: decode backend (--decodeBackend): None defers to env
    decode_backend: str | None = None
    #: snapshot cache root (--snapshotDir): decoded chunks keyed by tar +
    #: decode config + this source's member filter (prefix + label file)
    snapshot_dir: str | None = None
    #: device-resident decode (--deviceDecode): entropy pass on the host,
    #: pixels born on-device fused into the SIFT featurize
    device_decode: bool = False

    def __post_init__(self):
        self._names: list | None = None
        self._labels_map: dict | None = None

    @property
    def images(self) -> "VOCStreamSource":
        # The workload passes ``data.images`` into the descriptor
        # extractors; for a stream source the "images" ARE the source.
        return self

    def labels_map(self) -> dict:
        if self._labels_map is None:
            self._labels_map = voc_labels_map(self.labels_path)
        return self._labels_map

    def record_names(self, names: list) -> None:
        self._names = names

    @property
    def labels(self) -> list:
        if self._names is None:
            raise RuntimeError(
                "VOCStreamSource.labels before the descriptor pass — the "
                "streaming extract must run first (it records image order)"
            )
        lm = self.labels_map()
        return [lm[n] for n in self._names]

    def __len__(self) -> int:
        if self._names is None:
            raise RuntimeError(
                "len(VOCStreamSource) before the descriptor pass"
            )
        return len(self._names)


@dataclass
class SIFTFisherConfig:
    """Flag-parity with the reference scopt config (:113-127)."""

    train_location: str = ""
    test_location: str = ""
    label_path: str = ""
    lam: float = 0.5
    desc_dim: int = 80
    vocab_size: int = 256
    scale_step: int = 0
    pca_file: str | None = None
    gmm_mean_file: str | None = None
    gmm_var_file: str | None = None
    gmm_wts_file: str | None = None
    num_pca_samples: int = int(1e6)
    num_gmm_samples: int = int(1e6)
    sift_step_size: int = 3
    seed: int = 42
    # Whole-fitted-pipeline checkpoint stem (core.checkpoint): load-or-fit of
    # PCA + GMM + linear model in one artifact — the generalization of the
    # per-node pcaFile/gmm*File CSV flags.
    pipeline_file: str | None = None
    # Resumable-solve state path: the BCD fit checkpoints after every block
    # and restarts from the last completed block if the state file exists.
    solve_checkpoint: str | None = None
    # Cost-based auto-Cacher (core.optimize): decide from a measured probe
    # whether the PCA-projected descriptors stay resident between GMM
    # sampling and Fisher featurization, or are re-projected per consumer
    # under a tight HBM budget.  Decision table in results["cache_plan"].
    auto_cache: bool = False
    # Placement search (core.autoshard): force the cost-model-ranked
    # candidate search for the block solve (on by default via
    # KEYSTONE_AUTOSHARD); the searched table lands in
    # results["placement"] whenever a search ran.
    auto_shard: bool = False
    # Serving modes (core.serve via serve_common): warm-load the
    # pipeline_file bundle, assemble the servable chain (grayscale ->
    # SIFT -> PCA -> Fisher features -> model), and answer/SLO-bench
    # requests drawn from the eager test split's modal image shape (one
    # engine per shape — the static-shape discipline).
    serve: bool = False
    serve_bench: bool = False
    serve_clients: int = 4
    serve_requests: int = 64
    #: ``--serveMesh DxM``: serve on an explicit mesh — the checkpoint
    #: reshards onto it and buckets AOT-compile mesh-native (ISSUE 16).
    serve_mesh: str | None = None


class _Log(Logging):
    pass


def extract_sift_buckets(
    conf: SIFTFisherConfig, images: list, mesh=None, placement_out=None
) -> dict:
    """Per shape bucket: grayscale + dense SIFT -> [n, 128, cols].  With a
    mesh the PLACEMENT (row-sharded over which factorization, or single
    device) is chosen by the same cost-model-ranked search as the solve
    (fv_common.searched_bucket_featurize; the hand row-sharded layout is
    the untrained head, pad rows are dropped downstream).  A caller-passed
    ``placement_out`` dict receives the searched record under
    ``"featurize"``."""
    # bf16 intermediates, the measured-throughput configuration; VOC
    # leave-2-out CV (tools/voc_leave2out_cv.py, mean MAP 0.85) validated
    # the accuracy surrogate under this dtype.  Op default stays f32.
    sift = SIFTExtractor(
        step_size=conf.sift_step_size,
        scale_step=conf.scale_step,
        compute_dtype=jnp.bfloat16,
    )
    if isinstance(images, VOCStreamSource):
        # Streaming ingest: decode of batch i+1 overlaps SIFT of batch i
        # (core.ingest ring buffer + double-buffered H2D).  Label-less and
        # non-JPEGImages members are filtered before decode.
        src = images
        lm = src.labels_map()

        def keep(name: str) -> bool:
            return name.startswith(src.name_prefix) and name in lm

        # The keep filter selects the member set, so it must be part of the
        # snapshot key: prefix + label-file identity (a changed labels CSV
        # changes the survivor set -> new snapshot).  Computed
        # unconditionally (one os.stat): inert when snapshots are off,
        # and an env-only KEYSTONE_SNAPSHOT_DIR is never silently inert
        # (the stream disables snapshots for unkeyed keep filters).
        from ..core import snapshot as ksnap

        extra = (
            f"voc:{src.name_prefix}:"
            f"{ksnap.file_identity(src.labels_path)}"
        )
        cfg = stream_config_from_flags(
            autotune=src.autotune,
            decode_backend=src.decode_backend,
            snapshot_dir=src.snapshot_dir,
            snapshot_extra=extra,
            device_decode=src.device_decode,
        )
        with stream_batches(
            src.data_path, src.batch_size, keep=keep, config=cfg
        ) as st:
            buckets, names = stream_descriptor_buckets(
                st, lambda dev: sift(grayscale(dev))
            )
        src.record_names(names)
        record_stream_autotune(src, st)
        return buckets
    out, placement = searched_bucket_featurize(
        "voc_sift_featurize", images, lambda dev: sift(grayscale(dev)), mesh
    )
    if placement_out is not None and placement is not None:
        placement_out["featurize"] = placement
    return out


def run(
    conf: SIFTFisherConfig,
    train: MultiLabeledImages,
    test: MultiLabeledImages,
    mesh=None,
) -> dict:
    """With ``mesh``: featurization buckets are row-sharded over the data
    axis and the block least-squares solve runs distributed ((data, model)
    shardings via the ambient mesh) — the analog of the reference running
    this pipeline over partitioned RDDs (VOCSIFTFisher.scala:18-111)."""
    configure_logging()
    log = _Log()
    t0 = time.perf_counter()

    feat_dim = 2 * conf.desc_dim * conf.vocab_size
    results_cache_plan = results_placement = None
    feat_placements: dict = {}

    # Load-or-fit of the WHOLE fitted pipeline (SURVEY §5 generalized): when
    # the checkpoint exists, training featurization and all fits are skipped
    # and the run scores test data with the restored PCA + GMM + model.
    if conf.pipeline_file is not None and checkpoint_exists(conf.pipeline_file):
        log.log_info("restoring fitted pipeline from %s", conf.pipeline_file)
        ck = load_pipeline(conf.pipeline_file)
        batch_pca, gmm, model = ck["pca"], ck["gmm"], ck["model"]
        fisher = fisher_feature_pipeline(gmm)
    else:
        # Part 1+2: SIFT descriptors per shape bucket (reference :36-57).
        # Runs BEFORE the label node: a streaming source only knows its
        # image order (and therefore labels) after the descriptor pass.
        with stage_timer("sift"):
            train_desc = extract_sift_buckets(
                conf, train.images, mesh, placement_out=feat_placements
            )

        label_node = ClassLabelIndicatorsFromIntArrayLabels(VOC_NUM_CLASSES)
        train_labels = label_node(train.labels)

        # Part 1a: PCA — fit on sampled descriptor columns, or load (:40-50)
        with stage_timer("pca"):
            if conf.pca_file is not None:
                pca_mat = jnp.asarray(
                    np.loadtxt(conf.pca_file, delimiter=",", ndmin=2).T,
                    jnp.float32,
                )
            else:
                samples = sample_columns(
                    train_desc, conf.num_pca_samples, conf.seed
                )
                pca_mat = compute_pca(samples.T, conf.desc_dim)
            batch_pca = BatchPCATransformer(pca_mat)

            def make_pca_desc() -> dict:
                return {
                    shape: (idx, batch_pca(descs))
                    for shape, (idx, descs) in train_desc.items()
                }

            materialize = True
            if conf.auto_cache:
                # Auto-Cacher decision: the projected set is consumed by
                # GMM sampling (when fitting one) and Fisher featurization.
                reuse = (0 if conf.gmm_mean_file is not None else 1) + 1
                cache_plan, materialize = plan_pca_materialization(
                    train_desc, batch_pca, reuse, mesh=mesh,
                    label="voc_pca_descriptors",
                )
                log.log_info("%s", cache_plan.summary())
                results_cache_plan = cache_plan.record()
            # Cached: one resident projection feeds both consumers (the
            # status quo).  Denied: each consumer projects on the fly —
            # deterministic, so samples and features are bit-identical.
            pca_desc = make_pca_desc() if materialize else None

        # Part 2a: GMM — fit on sampled PCA'd columns, or load (:59-70)
        with stage_timer("gmm"):
            if conf.gmm_mean_file is not None:
                gmm = GaussianMixtureModel.load(
                    conf.gmm_mean_file, conf.gmm_var_file, conf.gmm_wts_file
                )
            else:
                gmm_samples = sample_columns(
                    pca_desc if pca_desc is not None else make_pca_desc(),
                    conf.num_gmm_samples, conf.seed + 1,
                )
                gmm = GaussianMixtureModelEstimator(conf.vocab_size).fit(
                    gmm_samples.T
                )
            assert_all_finite(gmm, "VOC GMM fit")

        # Part 3: Fisher features (:72-82)
        with stage_timer("fisher_features"):
            fisher = fisher_feature_pipeline(gmm)
            train_features = jnp.asarray(
                scatter_features(
                    pca_desc if pca_desc is not None else make_pca_desc(),
                    fisher, len(train), feat_dim,
                )
            )

        # Part 4: linear model (:84-86) — mesh-distributed when given one;
        # with a solve checkpoint the BCD fit persists per-block state and
        # resumes from it after preemption.
        solve_kwargs = {}
        state_path = None
        if conf.solve_checkpoint is not None:
            from ..solvers.block import bcd_checkpoint_path

            solve_kwargs["checkpoint"] = conf.solve_checkpoint
            state_path = bcd_checkpoint_path(conf.solve_checkpoint)
            if os.path.exists(state_path):
                solve_kwargs["resume_from"] = conf.solve_checkpoint
        with stage_timer("solve"):
            solver = BlockLeastSquaresEstimator(4096, 1, conf.lam, mesh=mesh)
            model = solver.fit(
                train_features, train_labels, num_features=feat_dim,
                plan=True if conf.auto_shard else None,
                **solve_kwargs,
            )
            log_fit_report(solver, label="VOC SIFT-Fisher solve")
            assert_all_finite(model, "VOC block least-squares fit")
            rep = solver.last_fit_report
            results_placement = rep.placement if rep is not None else None
        if state_path is not None and os.path.exists(state_path):
            # The per-block state is a RESUME artifact, not a model cache:
            # leaving the completed state behind would make a later rerun
            # with different features silently resume into the stale model.
            os.unlink(state_path)

        if conf.pipeline_file is not None:
            save_pipeline(
                conf.pipeline_file,
                {"pca": batch_pca, "gmm": gmm, "model": model},
            )
            log.log_info("saved fitted pipeline to %s", conf.pipeline_file)

    # Test path (:92-106)
    with stage_timer("eval"):
        test_desc = extract_sift_buckets(conf, test.images, mesh)
        test_features = scatter_features(
            test_desc, lambda d: fisher(batch_pca(d)), len(test), feat_dim
        )

        predictions = np.asarray(model(jnp.asarray(test_features)))
    aps = MeanAveragePrecisionEvaluator(test.labels, predictions, VOC_NUM_CLASSES)
    results = {
        "aps": aps,
        "map": float(np.mean(aps)),
        "seconds": time.perf_counter() - t0,
    }
    if results_cache_plan is not None:
        results["cache_plan"] = results_cache_plan
    if results_placement is not None or feat_placements:
        # The searched placement tables — the block solve's candidates,
        # deny/score rationale, chosen plan's predicted-vs-actual cost,
        # and (under a mesh) the searched FEATURIZE placement: one audit
        # home for every ranked placement decision the run made.
        if feat_placements:
            results["placement"] = {
                "solver": results_placement, **feat_placements
            }
        else:
            results["placement"] = results_placement
    autotune = collect_autotune(train, test)
    if autotune:
        results["autotune"] = autotune
        log.log_info("ingest autotune: %s", autotune)
    _maybe_serve(conf, test, results, log)
    log.log_info("TEST APs are: %s", ",".join(str(a) for a in aps))
    log.log_info("TEST MAP is: %s", results["map"])
    return results


def servable_pipeline(conf: SIFTFisherConfig, bundle: dict) -> Pipeline:
    """Assemble the fitted apply-chain from a ``--pipelineFile`` bundle
    ({pca, gmm, model}) into ONE servable Transformer: grayscale -> dense
    SIFT -> BatchPCA -> Fisher features -> per-class scores.  The SIFT
    node is reconstructed from config (it holds no fitted state); the
    fitted arrays ride in the bundle's registered nodes, so the chain
    flows through jit as a pytree."""
    sift = SIFTExtractor(
        step_size=conf.sift_step_size,
        scale_step=conf.scale_step,
        compute_dtype=jnp.bfloat16,
    )
    fisher = fisher_feature_pipeline(bundle["gmm"])
    return Pipeline(
        [
            FunctionTransformer(grayscale, name="grayscale"),
            sift,
            bundle["pca"],
            FunctionTransformer(fisher, name="fisher_features"),
            bundle["model"],
        ]
    )


def _maybe_serve(conf: SIFTFisherConfig, test, results: dict, log) -> None:
    if not (conf.serve or conf.serve_bench):
        return
    if conf.pipeline_file is None:
        raise ValueError(
            "--serve/--serveBench need --pipelineFile — the endpoint "
            "warm-loads the fitted {pca, gmm, model} bundle, it never refits"
        )
    images = getattr(test, "images", None)
    if isinstance(images, VOCStreamSource) or not hasattr(images, "__len__"):
        raise ValueError(
            "serving draws requests from the EAGER test split — run "
            "--serve/--serveBench without --streamIngest"
        )
    # One engine serves ONE request shape (the static-shape discipline the
    # shape-bucketed featurize already follows): requests come from the
    # test split's most populous shape bucket.
    buckets = bucket_by_shape(images)
    shape, (idx, batch) = max(buckets.items(), key=lambda kv: len(kv[1][0]))
    requests = np.asarray(batch, np.float32)[: conf.serve_requests]
    record = serve_common.serve_fitted(
        conf.pipeline_file,
        jax.ShapeDtypeStruct(tuple(requests.shape[1:]), np.float32),
        requests,
        label="voc_sift_fisher",
        wrap=lambda bundle: servable_pipeline(conf, bundle),
        bench=conf.serve_bench,
        clients=conf.serve_clients,
        mesh=serve_common.resolve_serve_mesh(conf.serve_mesh),
    )
    record["request_shape"] = list(requests.shape[1:])
    record["shape_buckets_total"] = len(buckets)
    results["serving"] = record


def main(argv=None):
    p = argparse.ArgumentParser("VOCSIFTFisher")
    p.add_argument("--trainLocation", required=True)
    p.add_argument("--testLocation", required=True)
    p.add_argument("--labelPath", required=True)
    p.add_argument("--lambda", dest="lam", type=float, default=0.5)
    p.add_argument("--descDim", type=int, default=80)
    p.add_argument("--vocabSize", type=int, default=256)
    p.add_argument("--scaleStep", type=int, default=0)
    p.add_argument("--pcaFile", default=None)
    p.add_argument("--gmmMeanFile", default=None)
    p.add_argument("--gmmVarFile", default=None)
    p.add_argument("--gmmWtsFile", default=None)
    p.add_argument("--numPcaSamples", type=int, default=int(1e6))
    p.add_argument("--numGmmSamples", type=int, default=int(1e6))
    p.add_argument(
        "--pipelineFile",
        default=None,
        help="fitted-pipeline checkpoint stem: load-or-fit of PCA+GMM+model",
    )
    p.add_argument(
        "--solveCheckpoint",
        default=None,
        help="resumable BCD state path: per-block checkpoint + auto-resume",
    )
    p.add_argument(
        "--streamIngest",
        action="store_true",
        help="streaming ingest (core.ingest): decode the tar WHILE the "
        "device runs SIFT, instead of decoding everything first",
    )
    p.add_argument(
        "--streamBatchSize",
        type=int,
        default=64,
        help="images per streamed device batch (--streamIngest only)",
    )
    p.add_argument(
        "--autoCache",
        action="store_true",
        help="cost-based auto-Cacher (core.optimize): probe-measured "
        "decision on PCA-descriptor residency vs re-projection "
        "(KEYSTONE_AUTOCACHE=1 equivalent)",
    )
    p.add_argument(
        "--autoShard",
        action="store_true",
        help="placement search (core.autoshard): force the cost-model "
        "ranked mesh/strategy candidate search for the block solve and "
        "record the searched plan in results['placement'] (on by "
        "default; KEYSTONE_AUTOSHARD=0 disables it except here)",
    )
    p.add_argument(
        "--autoTune",
        action="store_true",
        help="closed-loop ingest autotuner on --streamIngest streams: "
        "retune decode width / ring depth / decode-ahead mid-stream "
        "(KEYSTONE_AUTOTUNE=1 equivalent)",
    )
    p.add_argument(
        "--decodeBackend",
        default=None,
        choices=("thread", "process"),
        help="decode backend for --streamIngest: 'process' decodes on "
        "spawned worker processes via shared memory "
        "(KEYSTONE_DECODE_BACKEND equivalent)",
    )
    p.add_argument(
        "--snapshotDir",
        default=None,
        help="snapshot cache root for --streamIngest streams "
        "(core.snapshot): first pass materializes decoded chunks, repeat "
        "runs stream the shards at IO speed "
        "(KEYSTONE_SNAPSHOT_DIR equivalent)",
    )
    p.add_argument(
        "--deviceDecode",
        action="store_true",
        help="device-resident JPEG decode for --streamIngest "
        "(ops.jpeg_device): host entropy pass only, pixels born on-device "
        "fused into the SIFT featurize; unsupported JPEGs fall back to "
        "host decode counted per reason (KEYSTONE_DEVICE_DECODE=1 "
        "equivalent)",
    )
    serve_common.add_serve_args(p)
    p.add_argument(
        "--mesh",
        default=None,
        help="device mesh, e.g. '8' (data) or '4x2' (data x model)",
    )
    p.add_argument(
        "--trace",
        default=None,
        metavar="PATH",
        help="write a Chrome-trace JSON (Perfetto-loadable; .jsonl for the "
        "JSONL event log) of the run — the KEYSTONE_TRACE env equivalent",
    )
    a = p.parse_args(argv)
    if a.trace:
        trace.enable(a.trace)
    if (a.serve or a.serveBench) and not a.pipelineFile:
        p.error("--serve/--serveBench require --pipelineFile")
    if (a.serve or a.serveBench) and a.streamIngest:
        p.error(
            "--serve/--serveBench draw requests from the eager test split "
            "— drop --streamIngest for serving runs"
        )
    conf = SIFTFisherConfig(
        train_location=a.trainLocation,
        test_location=a.testLocation,
        label_path=a.labelPath,
        lam=a.lam,
        desc_dim=a.descDim,
        vocab_size=a.vocabSize,
        scale_step=a.scaleStep,
        pca_file=a.pcaFile,
        gmm_mean_file=a.gmmMeanFile,
        gmm_var_file=a.gmmVarFile,
        gmm_wts_file=a.gmmWtsFile,
        num_pca_samples=a.numPcaSamples,
        num_gmm_samples=a.numGmmSamples,
        pipeline_file=a.pipelineFile,
        solve_checkpoint=a.solveCheckpoint,
        auto_cache=a.autoCache or optimize.auto_cache_env(),
        auto_shard=a.autoShard,
        serve=a.serve,
        serve_bench=a.serveBench,
        serve_clients=a.serveClients,
        serve_requests=a.serveRequests,
        serve_mesh=a.serveMesh,
    )
    if conf.pipeline_file is not None and checkpoint_exists(conf.pipeline_file):
        # Restored runs never touch training data — skip decoding the
        # entire training tar (the dominant reload-path cost).
        train = MultiLabeledImages([], [], [])
    elif a.streamIngest:
        train = VOCStreamSource(
            conf.train_location, conf.label_path,
            batch_size=a.streamBatchSize, autotune=a.autoTune,
            decode_backend=a.decodeBackend, snapshot_dir=a.snapshotDir,
            device_decode=a.deviceDecode,
        )
    else:
        train = voc_loader(conf.train_location, conf.label_path)
    if a.streamIngest:
        test = VOCStreamSource(
            conf.test_location, conf.label_path,
            batch_size=a.streamBatchSize, autotune=a.autoTune,
            decode_backend=a.decodeBackend, snapshot_dir=a.snapshotDir,
            device_decode=a.deviceDecode,
        )
    else:
        test = voc_loader(conf.test_location, conf.label_path)
    try:
        return run(conf, train, test, mesh=parse_mesh(a.mesh))
    finally:
        if a.trace:
            trace.flush()


if __name__ == "__main__":
    main()
