"""Shared machinery for the Fisher-vector workloads (VOCSIFTFisher,
ImageNetSiftLcsFV — reference pipelines/images/voc/VOCSIFTFisher.scala and
pipelines/images/imagenet/ImageNetSiftLcsFV.scala).

The reference maps per-image JNI featurizers over RDDs of arbitrarily-sized
images.  XLA wants static shapes, so images are grouped into same-shape
buckets, each bucket is featurized by one jitted program, and the resulting
fixed-dimension feature rows are scattered back to original order.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.fisher import FisherVector
from ..ops.images import GrayScaler, PixelScaler
from ..ops.stats import NormalizeRows, SignedHellingerMapper
from ..ops.util import MatrixVectorizer
from ..parallel.mesh import padded_shard_rows
from ..solvers.gmm import GaussianMixtureModel


def bucket_by_shape(images: list) -> dict:
    """Group per-image arrays by (H, W): shape -> (orig_indices, [n,H,W,C])."""
    groups: dict = {}
    for i, img in enumerate(images):
        groups.setdefault(img.shape[:2], []).append(i)
    return {
        shape: (np.asarray(idx), np.stack([images[i] for i in idx]))
        for shape, idx in groups.items()
    }


def shard_batch(batch, mesh):
    """Row-shard one bucket's [n, H, W, C] batch over the mesh's data axis
    (zero-padding n up to an axis multiple), or plain device_put without a
    mesh.  Pad rows ride through the per-image featurizers as garbage rows
    and are dropped at scatter time (``scatter_features`` slices to the
    bucket's true image count) and at sampling time (``sample_columns``
    samples only valid images) — the bucket featurize program itself is
    purely data-parallel, so no masking is needed in between."""
    dev, _n = padded_shard_rows(np.asarray(batch), mesh)
    return dev


def grayscale(batch) -> jnp.ndarray:
    """PixelScaler then GrayScaler -> [n, H, W] in [0, 1]."""
    return GrayScaler()(PixelScaler()(jnp.asarray(batch)))[..., 0]


def searched_bucket_featurize(label: str, images: list, per_batch, mesh,
                              *, plan=None):
    """Eager bucket featurize with the PLACEMENT chosen by the same
    cost-model-ranked search the solvers use (core.autoshard, ISSUE 10) —
    the hand-written ``shard_batch(batch, mesh)`` layout stops being the
    only option and becomes the prior head of a ranked candidate list:

    * ``row_sharded[mesh DxM]`` for the given mesh (the hand placement,
      rank 0 on an untrained model — bit-identical default), and for
      every other (data, model) factorization of the same devices;
    * the ``single_device`` floor (plain ``device_put``), pinned last.

    The chosen candidate runs the WHOLE bucket featurize through the
    unchanged ``run_ladder`` contract, so a sharded featurize that dies
    RESOURCE_EXHAUSTED at runtime steps down the ranking counted
    (``autoshard_stepdown``) instead of killing the workload, and the
    measured outcome trains the cross-program calibration like any solve
    plan.  Returns ``(buckets, placement_record_or_None)`` — the record
    lands next to the solver's in ``results["placement"]``, so featurize
    and solve placements are chosen by one ranking machinery and audited
    in one table.  ``mesh=None`` (or a disabled search) is the plain
    hand path."""
    from ..core import autoshard
    from ..core import memory as kmem
    from ..parallel.mesh import DATA_AXIS, enumerate_meshes, mesh_desc

    raw = bucket_by_shape(images)

    def featurize_with(m):
        return {
            shape: (idx, per_batch(shard_batch(batch, m)))
            for shape, (idx, batch) in raw.items()
        }

    if mesh is None or not autoshard.will_search(plan):
        return featurize_with(mesh), None

    total_bytes = sum(int(b.nbytes) for _i, b in raw.values())
    # The featurize consumes uint8 pixels but computes in float32 — the
    # roofline prior charges the device-resident working set.
    f32_bytes = total_bytes * 4

    def tier(m, prior_rank, hand):
        d_sz = m.shape[DATA_AXIS]

        def run(_mplan, m=m):
            return featurize_with(m)

        return autoshard.Candidate(
            f"row_sharded[mesh {mesh_desc(m)}]",
            "featurize_mesh",
            plan=lambda m=m, d_sz=d_sz: kmem.plan_bytes(
                f"{label}:row_sharded[{mesh_desc(m)}]",
                argument_bytes=total_bytes // d_sz,
                temp_bytes=f32_bytes // d_sz,
                mesh=m,
            ),
            run=run,
            hints={
                "arg_bytes": total_bytes // d_sz,
                "temp_bytes": f32_bytes // d_sz,
                "h2d_bytes": total_bytes // d_sz,
                "dispatches": len(raw),
            },
            mesh_axes=dict(m.shape),
            prior_rank=prior_rank,
            hand=hand,
            specs={"batch": "data@dim0"},
        )

    cands = [tier(mesh, 0, True)]
    for extra in enumerate_meshes(list(mesh.devices.flat)):
        if mesh_desc(extra) != mesh_desc(mesh):
            cands.append(tier(extra, len(cands), False))
    cands.append(autoshard.Candidate(
        "single_device",
        "featurize",
        plan=lambda: kmem.plan_bytes(
            f"{label}:single_device",
            argument_bytes=total_bytes,
            temp_bytes=f32_bytes,
        ),
        run=lambda _mplan: featurize_with(None),
        hints={
            "arg_bytes": total_bytes,
            "temp_bytes": f32_bytes,
            "h2d_bytes": total_bytes,
            "dispatches": len(raw),
        },
        prior_rank=len(cands),
        floor=True,
        specs={"batch": "replicated"},
    ))
    report = kmem.FitReport(label=label)
    out = autoshard.run_search(
        label, cands, report,
        fingerprint=autoshard.fingerprint(
            label,
            sorted((shape, len(idx)) for shape, (idx, _b) in raw.items()),
            dict(mesh.shape),
            autoshard.device_fingerprint(),
        ),
        plan=plan,
    )
    return out, report.placement


def sample_columns(desc_buckets: dict, num_samples: int, seed: int = 42) -> jnp.ndarray:
    """ColumnSampler analog over per-bucket [n, d, cols] descriptor arrays:
    uniform sample of descriptor columns -> [d, <= num_samples].

    Each bucket contributes its proportional quota and only the sampled
    columns are materialized — never the full descriptor set (the reference
    ColumnSampler likewise samples per image, Sampling.scala:12-22)."""
    rng = np.random.default_rng(seed)
    # valid image count is len(idx) — descriptor arrays may carry sharding
    # pad rows past it (see shard_batch) which must never be sampled
    totals = {
        shape: len(idx) * descs.shape[2]
        for shape, (idx, descs) in desc_buckets.items()
    }
    grand_total = sum(totals.values())
    picks = []
    for shape, (idx_arr, descs) in desc_buckets.items():
        n, d, c = len(idx_arr), descs.shape[1], descs.shape[2]
        total = totals[shape]
        if grand_total <= num_samples:
            quota = total
            idx = np.arange(total)
        else:
            quota = min(total, max(1, int(num_samples * total / grand_total)))
            idx = np.sort(rng.choice(total, quota, replace=False))
        # gather the quota columns directly — no transposed full copy
        im, col = np.divmod(idx, c)
        picks.append(descs[jnp.asarray(im), :, jnp.asarray(col)].T)  # [d, quota]
    return jnp.concatenate(picks, axis=1)


def fisher_feature_pipeline(gmm: GaussianMixtureModel):
    """FisherVector -> vectorize (col-major) -> L2 norm -> signed sqrt ->
    L2 norm (reference constructFisherFeaturizer / VOCSIFTFisher.scala:73-80).
    Returns a callable [n, d, cols]-descriptors -> [n, 2·d·K] features."""
    fv = FisherVector(gmm)
    vec = MatrixVectorizer()
    norm = NormalizeRows()
    hell = SignedHellingerMapper()

    def featurize(descs):
        return norm(hell(norm(vec(fv(descs)))))

    return featurize


def scatter_features(buckets: dict, transform, n_total: int, feature_dim: int) -> np.ndarray:
    """Apply ``transform`` ([n, d, cols] descriptors -> [n, D] features) per
    bucket and scatter rows back to original image order."""
    out = np.zeros((n_total, feature_dim), np.float32)
    for _shape, (idx, descs) in buckets.items():
        # slice off sharding pad rows (see shard_batch): only the bucket's
        # true images scatter back
        out[np.asarray(idx)] = np.asarray(transform(descs))[: len(idx)]
    return out


def plan_pca_materialization(
    desc_buckets: dict, batch_pca, reuse: int, *, mesh=None,
    label: str = "pca_descriptors",
):
    """Auto-Cacher decision for the PCA-projected descriptor buckets
    (core.optimize): the FV workloads consume them up to twice — GMM
    sampling, then Fisher featurization — and today always hold the whole
    projected set resident between the two.  Profile the projection on the
    smallest bucket, scale seconds/bytes to the full set, and run the
    caching inequality through the HBM admission gate.  Returns
    ``(CachePlan, materialize)``: ``materialize=False`` means each consumer
    projects on the fly (bit-identical — the projection is deterministic)
    instead of pinning the set through the GMM EM fit."""
    from ..core import optimize

    shape, (_idx, probe) = min(
        desc_buckets.items(), key=lambda kv: kv[1][1].size
    )
    # Warm the projection's compile before timing: a cold first call would
    # fold one-off JIT time into probe_secs and then SCALE it by the
    # dataset ratio, overpricing recompute and biasing every decision
    # toward materialize.
    jax.block_until_ready(batch_pca(probe))
    t0 = time.perf_counter()
    out = jax.block_until_ready(batch_pca(probe))
    probe_secs = time.perf_counter() - t0
    probe_cols = int(probe.shape[0]) * int(probe.shape[2])
    total_cols = sum(
        int(d.shape[0]) * int(d.shape[2]) for _, d in desc_buckets.values()
    )
    scale = total_cols / max(1, probe_cols)
    plan = optimize.plan_caches(
        [
            optimize.CacheCandidate(
                index=0,
                name=label,
                seconds=probe_secs * scale,
                output_bytes=int(out.nbytes * scale),
                reuse=reuse,
            )
        ],
        mesh=mesh,
    )
    return plan, plan.decisions[0].cached


# -- streaming ingest (core.ingest) -------------------------------------------


def stream_config_from_flags(
    *, autotune: bool = False, decode_backend: str | None = None,
    snapshot_dir: str | None = None, snapshot_extra: str | None = None,
    supports_featurized: bool = False, device_decode: bool | None = None,
):
    """One ``StreamConfig`` builder for every streaming workload: env-seeded
    (``KEYSTONE_*``), with the workload's ``--autoTune`` / ``--decodeBackend``
    / ``--snapshotDir`` / ``--deviceDecode`` flags overriding the env
    defaults.  ``snapshot_extra`` keys the stream's member-selection inputs
    (keep filters, label files) into the snapshot content hash.
    ``device_decode=True`` selects ``decode_mode="device"`` (pixels born
    on-device, ops.jpeg_device; env ``KEYSTONE_DEVICE_DECODE``).

    ``supports_featurized``: set by callers that wrap the stream in
    :func:`stream_features_snapshot`.  Everywhere else a
    ``KEYSTONE_SNAPSHOT_MODE=featurized`` request degrades to DECODED
    caching — counted (``snapshot_mode_unsupported``), never a silently
    inert cache dir."""
    from ..core.ingest import StreamConfig
    from ..core.resilience import counters

    cfg = StreamConfig.from_env(
        autotune=True if autotune else None,
        decode_backend=decode_backend,
        snapshot_dir=snapshot_dir,
        snapshot_extra=snapshot_extra,
        decode_mode="device" if device_decode else None,
    )
    if (
        cfg.snapshot_dir
        and cfg.snapshot_mode == "featurized"
        and not supports_featurized
    ):
        counters.record(
            "snapshot_mode_unsupported",
            "featurized snapshots are not implemented on this stream — "
            "caching decoded chunks instead",
        )
        cfg.snapshot_mode = "decoded"
    return cfg


def stream_features_snapshot(
    make_stream, per_batch, *, root=None, key=None, tar_path=None, meta=None
):
    """Featurized-snapshot wrapper around a streaming featurize pass.

    ``per_batch``: ``StreamBatch -> np.ndarray [b, D]`` feature rows.
    With ``root``/``key`` set and a committed FEATURIZED snapshot present,
    the features stream straight from the shards — no tar read, no decode,
    no device featurize (``key`` must fold in the fitted featurizer's
    digest, ``core.snapshot.featurizer_digest``, so refits never replay
    stale features).  Otherwise the live pass runs (decode of chunk *i+1*
    overlapping featurize of chunk *i*) and its per-batch features are teed
    into a fresh snapshot, committed only on clean completion.  A corrupt
    shard mid-read is a counted ``snapshot_fallback`` to the live pass.

    Returns ``(features [n, D] f32, names, stream_or_None)`` — the stream
    is None when the snapshot served the pass (nothing streamed, so there
    is no autotune record)."""
    from ..core import snapshot as ksnap
    from ..core.resilience import counters

    if root is not None and key is not None:
        # tar_path (when given) powers the staleness classification: a
        # committed FEATURIZED snapshot for the same tar under another key
        # means the featurizer or input moved — counted, not silent.
        snap, reason = ksnap.lookup(
            root, key, tar_path=tar_path, mode="featurized"
        )
        if reason == "stale":
            counters.record(
                "snapshot_stale",
                f"{root}: featurized snapshot keyed differently "
                "(featurizer or input moved) — recomputing",
            )
        if snap is not None:
            parts, name_pairs, n = [], [], 0
            try:
                for _entry, arrays in snap.iter_chunks():
                    idx = np.asarray(arrays["indices"], np.int64)
                    parts.append((idx, np.asarray(arrays["payload"], np.float32)))
                    name_pairs.extend(
                        zip(idx.tolist(), [str(x) for x in arrays["names"]])
                    )
                    n += len(idx)
                feats, names = _scatter_parts(parts, name_pairs, n)
                return feats, names, None
            except ksnap.SnapshotCorrupt as e:
                counters.record(
                    "snapshot_fallback",
                    f"{snap.path}: {e} — recomputing features live",
                )

    writer = None
    if root is not None and key is not None:
        meta = dict(meta or {})
        if tar_path is not None:
            # The manifest's tar identity is what classifies a later
            # different-key lookup as STALE rather than a plain miss.
            meta.setdefault("tar", ksnap.tar_identity(tar_path))
        try:
            writer = ksnap.SnapshotWriter(
                root, key, mode="featurized", meta=meta
            )
        except (OSError, ksnap.SnapshotError) as e:
            # An unusable snapshot root never kills the featurize pass —
            # same counted-degrade contract as a failed shard write.
            counters.record(
                "snapshot_write_failed",
                f"cannot open featurized snapshot writer: {e}",
            )
    parts, name_pairs, n = [], [], 0
    try:
        with make_stream() as st:
            for batch in st:
                feats = np.asarray(per_batch(batch), np.float32)[: len(batch)]
                parts.append((batch.indices, feats))
                name_pairs.extend(zip(batch.indices.tolist(), batch.names))
                n += len(batch)
                if writer is not None:
                    try:
                        writer.add_chunk(
                            batch.index, batch.indices, batch.names, feats
                        )
                    except (OSError, ksnap.SnapshotError) as e:
                        # Same contract as the ingest tee: the cache is an
                        # optimization — a full disk drops the WRITER,
                        # counted, never the featurize pass.
                        counters.record("snapshot_write_failed", str(e))
                        writer.abort()
                        writer = None
        if writer is not None:
            try:
                writer.commit()
            except (OSError, ksnap.SnapshotError) as e:
                counters.record(
                    "snapshot_write_failed", f"commit failed: {e}"
                )
    finally:
        if writer is not None:
            writer.abort()  # no-op after commit; drops partials on error
    feats, names = _scatter_parts(parts, name_pairs, n)
    return feats, names, st


def record_stream_autotune(src, stream) -> None:
    """Append a finished stream's autotuner record to its source (one
    record per streaming pass — ImageNet streams a source once per
    descriptor branch).  No-op without a tuner."""
    if stream.tuner is not None:
        records = getattr(src, "last_autotune", None) or []
        records.append(stream.tuner.record())
        src.last_autotune = records


def collect_autotune(train, test) -> dict:
    """The ``results["autotune"]`` section: per-split knob-trajectory
    record lists accumulated by :func:`record_stream_autotune` (empty dict
    when nothing streamed with a tuner)."""
    return {
        split: getattr(src, "last_autotune", None)
        for split, src in (("train", train), ("test", test))
        if getattr(src, "last_autotune", None)
    }


def _ordered_names(pairs: list, n: int) -> list:
    names = [None] * n
    for i, name in pairs:
        names[i] = name
    return names


def _scatter_parts(
    parts: list, name_pairs: list, n: int, feature_dim: int | None = None
) -> tuple[np.ndarray, list]:
    """Scatter accumulated ``(indices, [b, D] features)`` parts back to
    stream-ordinal (decode-survival) order — the one copy of the
    scatter-to-ordinal contract every streaming feature pass shares
    (``feats[: len(idx)]`` drops sharding pad rows, see shard_batch).
    ``feature_dim`` is inferred from the first part when omitted."""
    if feature_dim is None:
        feature_dim = parts[0][1].shape[1] if parts else 0
    out = np.zeros((n, feature_dim), np.float32)
    for idx, feats in parts:
        out[np.asarray(idx)] = feats[: len(idx)]
    return out, _ordered_names(name_pairs, n)


def stream_descriptor_buckets(stream, per_batch) -> tuple[dict, list]:
    """Build the ``bucket_by_shape``-shaped descriptor dict by consuming a
    ``core.ingest`` stream: ``per_batch`` ([b, H, W, C] device batch ->
    per-image descriptor array) runs on chunk *i* while chunk *i+1* decodes
    on the host and transfers (the decode/featurize overlap the eager path
    lacks — it decoded the whole tar before the first device batch).

    Per-batch results stay on device (async dispatch — no sync until a
    downstream consumer pulls), and are concatenated per shape at
    end-of-stream, so ``{shape: (idx, descs)}`` is element-identical to the
    eager ``bucket_by_shape`` + per-bucket featurize.  Returns the buckets
    plus member names in stream-ordinal order (the loaders' filename
    order)."""
    parts: dict = {}
    name_pairs: list = []
    n = 0
    for batch in stream:
        # batch.apply fuses the device decode into the featurize program
        # for coefficient chunks (decode_mode="device"); for pixel chunks
        # it is exactly per_batch(batch.dev())
        descs = batch.apply(per_batch)
        parts.setdefault(batch.shape, []).append((batch.indices, descs))
        name_pairs.extend(zip(batch.indices.tolist(), batch.names))
        n += len(batch)
    buckets = {}
    # Insertion order = each shape's FIRST image ordinal, matching eager
    # bucket_by_shape's first-occurrence order exactly: downstream seeded
    # column sampling (sample_columns) iterates the dict sequentially from
    # one rng, so a chunk-emission order (first FULL batch first) would
    # silently pick different PCA/GMM samples than the eager path.
    for shape, chunks in sorted(
        parts.items(), key=lambda kv: kv[1][0][0][0]
    ):
        idx = np.concatenate([c[0] for c in chunks])
        descs = (
            chunks[0][1]
            if len(chunks) == 1
            else jnp.concatenate([c[1] for c in chunks], axis=0)
        )
        buckets[shape] = (idx, descs)
    return buckets, _ordered_names(name_pairs, n)


def scatter_features_streaming(stream, transform, feature_dim: int) -> tuple[np.ndarray, list]:
    """Streaming variant of :func:`scatter_features`: consume shape-bucketed
    device batches from ``core.ingest``, apply ``transform`` ([b, H, W, C]
    device batch -> [b, D] features) per batch, and scatter rows back to
    stream-ordinal (decode-survival) order.

    The host sync (``np.asarray``) lands only on the CONSUMED batch —
    decode threads keep filling the ring and the next batch's H2D is
    already in flight while this batch's features are pulled.  Returns
    ``(features [n, D] f32, names)``."""
    parts: list = []
    name_pairs: list = []
    n = 0
    for batch in stream:
        # fused decode+featurize for coefficient chunks (device decode),
        # plain transform(batch.dev()) for pixel chunks
        feats = batch.apply(transform)
        # sync on the consumed batch only; later batches decode/transfer on
        parts.append((batch.indices, np.asarray(feats, np.float32)))
        name_pairs.extend(zip(batch.indices.tolist(), batch.names))
        n += len(batch)
    return _scatter_parts(parts, name_pairs, n, feature_dim)
