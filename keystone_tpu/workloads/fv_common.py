"""Shared machinery for the Fisher-vector workloads (VOCSIFTFisher,
ImageNetSiftLcsFV — reference pipelines/images/voc/VOCSIFTFisher.scala and
pipelines/images/imagenet/ImageNetSiftLcsFV.scala).

The reference maps per-image JNI featurizers over RDDs of arbitrarily-sized
images.  XLA wants static shapes, so images are grouped into same-shape
buckets, each bucket is featurized by one jitted program, and the resulting
fixed-dimension feature rows are scattered back to original order.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..ops.fisher import FisherVector
from ..ops.images import GrayScaler, PixelScaler
from ..ops.stats import NormalizeRows, SignedHellingerMapper
from ..ops.util import MatrixVectorizer
from ..solvers.gmm import GaussianMixtureModel


def bucket_by_shape(images: list) -> dict:
    """Group per-image arrays by (H, W): shape -> (orig_indices, [n,H,W,C])."""
    groups: dict = {}
    for i, img in enumerate(images):
        groups.setdefault(img.shape[:2], []).append(i)
    return {
        shape: (np.asarray(idx), np.stack([images[i] for i in idx]))
        for shape, idx in groups.items()
    }


def grayscale(batch) -> jnp.ndarray:
    """PixelScaler then GrayScaler -> [n, H, W] in [0, 1]."""
    return GrayScaler()(PixelScaler()(jnp.asarray(batch)))[..., 0]


def sample_columns(desc_buckets: dict, num_samples: int, seed: int = 42) -> jnp.ndarray:
    """ColumnSampler analog over per-bucket [n, d, cols] descriptor arrays:
    uniform sample of descriptor columns -> [d, <= num_samples].

    Each bucket contributes its proportional quota and only the sampled
    columns are materialized — never the full descriptor set (the reference
    ColumnSampler likewise samples per image, Sampling.scala:12-22)."""
    rng = np.random.default_rng(seed)
    totals = {
        shape: descs.shape[0] * descs.shape[2]
        for shape, (_, descs) in desc_buckets.items()
    }
    grand_total = sum(totals.values())
    picks = []
    for shape, (_, descs) in desc_buckets.items():
        n, d, c = descs.shape
        total = totals[shape]
        if grand_total <= num_samples:
            quota = total
            idx = np.arange(total)
        else:
            quota = min(total, max(1, int(num_samples * total / grand_total)))
            idx = np.sort(rng.choice(total, quota, replace=False))
        # gather the quota columns directly — no transposed full copy
        im, col = np.divmod(idx, c)
        picks.append(descs[jnp.asarray(im), :, jnp.asarray(col)].T)  # [d, quota]
    return jnp.concatenate(picks, axis=1)


def fisher_feature_pipeline(gmm: GaussianMixtureModel):
    """FisherVector -> vectorize (col-major) -> L2 norm -> signed sqrt ->
    L2 norm (reference constructFisherFeaturizer / VOCSIFTFisher.scala:73-80).
    Returns a callable [n, d, cols]-descriptors -> [n, 2·d·K] features."""
    fv = FisherVector(gmm)
    vec = MatrixVectorizer()
    norm = NormalizeRows()
    hell = SignedHellingerMapper()

    def featurize(descs):
        return norm(hell(norm(vec(fv(descs)))))

    return featurize


def scatter_features(buckets: dict, transform, n_total: int, feature_dim: int) -> np.ndarray:
    """Apply ``transform`` ([n, d, cols] descriptors -> [n, D] features) per
    bucket and scatter rows back to original image order."""
    out = np.zeros((n_total, feature_dim), np.float32)
    for _shape, (idx, descs) in buckets.items():
        out[np.asarray(idx)] = np.asarray(transform(descs))
    return out
