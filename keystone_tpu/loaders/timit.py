"""Pre-featurized TIMIT loader
(reference src/main/scala/loaders/TimitFeaturesDataLoader.scala:15-71).

Features: CSV of numbers; labels: "row# label" lines, 1-indexed rows and
labels.  (The reference passes ``testLabelsLocation`` when building the
*train* labels — TimitFeaturesDataLoader.scala:64 — an evident copy-paste
bug we do not reproduce.)
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

TIMIT_DIMENSION = 440
TIMIT_NUM_CLASSES = 147


@dataclass
class TimitSplit:
    data: np.ndarray  # [N, 440] f32
    labels: np.ndarray  # [N] int32 (0-indexed)


@dataclass
class TimitFeaturesData:
    train: TimitSplit
    test: TimitSplit


def _parse_sparse_labels(path: str) -> dict[int, int]:
    out: dict[int, int] = {}
    with open(path) as fh:
        for line in fh:
            parts = line.split()
            if len(parts) >= 2:
                out[int(parts[0]) - 1] = int(parts[1])
    return out


def _load_split(data_path: str, labels_path: str) -> TimitSplit:
    data = np.loadtxt(data_path, delimiter=",", ndmin=2).astype(np.float32)
    labels_map = _parse_sparse_labels(labels_path)
    labels = np.asarray(
        [labels_map[i] - 1 for i in range(data.shape[0])], np.int32
    )
    return TimitSplit(data, labels)


def timit_features_loader(
    train_data: str, train_labels: str, test_data: str, test_labels: str
) -> TimitFeaturesData:
    return TimitFeaturesData(
        train=_load_split(train_data, train_labels),
        test=_load_split(test_data, test_labels),
    )
