"""20 Newsgroups loader (reference src/main/scala/loaders/NewsgroupsDataLoader.scala:9-58).

Expects ``dir/class_label/docs_as_separate_plaintext_files``; class ids are
indices into the fixed 20-class list.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np

CLASSES = [
    "comp.graphics",
    "comp.os.ms-windows.misc",
    "comp.sys.ibm.pc.hardware",
    "comp.sys.mac.hardware",
    "comp.windows.x",
    "rec.autos",
    "rec.motorcycles",
    "rec.sport.baseball",
    "rec.sport.hockey",
    "sci.crypt",
    "sci.electronics",
    "sci.med",
    "sci.space",
    "misc.forsale",
    "talk.politics.misc",
    "talk.politics.guns",
    "talk.politics.mideast",
    "talk.religion.misc",
    "alt.atheism",
    "soc.religion.christian",
]


@dataclass
class NewsgroupsData:
    data: list  # of document strings
    labels: np.ndarray  # [N] int32


def newsgroups_loader(data_dir: str, classes: list[str] | None = None) -> NewsgroupsData:
    classes = classes if classes is not None else CLASSES
    docs, labels = [], []
    for idx, cls in enumerate(classes):
        cls_dir = os.path.join(data_dir, cls)
        if not os.path.isdir(cls_dir):
            continue
        for fname in sorted(os.listdir(cls_dir)):
            path = os.path.join(cls_dir, fname)
            if not os.path.isfile(path):
                continue
            with open(path, errors="replace") as fh:
                docs.append(fh.read())
            labels.append(idx)
    return NewsgroupsData(docs, np.asarray(labels, np.int32))


NewsgroupsDataLoader = newsgroups_loader
