"""ctypes binding for the native C++ JPEG decoder (native/ingest.cpp).

The shared library is built lazily with the system toolchain on first use
(g++ + libjpeg, both baked into the image) and cached next to the source.
ctypes releases the GIL for the duration of each decode call, so the
thread-pool loader in image_loaders.py parallelizes across host cores with
no Python image library on the hot path.  ``KEYSTONE_NATIVE_DECODE=0``
disables the native path; anything unbuildable or undecodable falls back
to PIL transparently.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

_logger = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "native")
_SRC = os.path.join(_NATIVE_DIR, "ingest.cpp")
_LIB = os.path.join(_NATIVE_DIR, "libkstingest.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _build() -> bool:
    from ..core.resilience import retry

    cmd = [
        "g++", "-O2", "-shared", "-fPIC", _SRC, "-o", _LIB, "-ljpeg",
    ]

    # The one-time g++ invocation is plain file IO + a subprocess — fork
    # failures and filesystem hiccups on busy hosts are transient, so the
    # build retries with backoff before the loader settles for PIL.  A
    # compile that blows the 120 s timeout is NOT transient (each retry
    # would stall startup another two minutes): it fails straight to PIL.
    @retry(retry_on=(OSError,), name="native_decode_build")
    def _run():
        return subprocess.run(cmd, capture_output=True, timeout=120)

    try:
        res = _run()
    except (OSError, subprocess.TimeoutExpired):
        return False
    return res.returncode == 0 and os.path.exists(_LIB)


def _load() -> ctypes.CDLL | None:
    """Build (first use only) + dlopen the native decoder.

    Call this (via :func:`available`) BEFORE entering a decode hot path:
    the one-time g++ build runs under the module lock, so a lazy first call
    from inside a thread-pool loader would stall every worker behind it.
    The loaders do so (image_loaders._iter_tar_images); fallback to PIL is
    logged once so a silent slow path is attributable."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if os.environ.get("KEYSTONE_NATIVE_DECODE", "").strip() == "0":
            return None
        try:
            if not os.path.exists(_LIB) or os.path.getmtime(
                _LIB
            ) < os.path.getmtime(_SRC):
                if not _build():
                    _logger.warning(
                        "native JPEG decoder build failed; falling back to PIL"
                    )
                    return None
            lib = ctypes.CDLL(_LIB)
        except OSError:
            _logger.warning(
                "native JPEG decoder unavailable; falling back to PIL"
            )
            return None
        lib.kst_decode_jpeg.argtypes = [
            ctypes.c_char_p,
            ctypes.c_long,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int),
        ]
        lib.kst_decode_jpeg.restype = ctypes.c_int
        lib.kst_free.argtypes = [ctypes.POINTER(ctypes.c_float)]
        lib.kst_free.restype = None
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def reset() -> None:
    """Forget the cached build/load outcome (under the module lock) so the
    next decode re-evaluates the ``KEYSTONE_NATIVE_DECODE`` gate and the
    library state.  Public hook for benchmarks/tests that toggle the env
    var to compare native-vs-PIL paths — poking ``_tried``/``_lib``
    directly would race any live decode thread."""
    global _lib, _tried
    with _lock:
        _tried = False
        _lib = None


def decode_jpeg_native(data: bytes) -> np.ndarray | None:
    """JPEG bytes -> f32[H, W, 3] BGR in [0, 255], or None when the stream
    is corrupt, rejected (<36 px), or the native library is unavailable.
    Matches image_loaders.decode_image semantics bit-for-... well, within
    libjpeg-version IDCT differences of PIL (see tests)."""
    lib = _load()
    if lib is None:
        return None
    out = ctypes.POINTER(ctypes.c_float)()
    h = ctypes.c_int()
    w = ctypes.c_int()
    rc = lib.kst_decode_jpeg(data, len(data), ctypes.byref(out), ctypes.byref(h), ctypes.byref(w))
    if rc != 0:
        return None
    try:
        arr = np.ctypeslib.as_array(out, shape=(h.value, w.value, 3)).copy()
    finally:
        lib.kst_free(out)
    return arr
