"""CIFAR-10 binary loader (reference src/main/scala/loaders/CifarLoader.scala:13-50).

Record format: 1 label byte + 32*32*3 pixel bytes (R, G, B planes, row-major
within a plane).  The reference wraps the raw bytes as a
``RowColumnMajorByteArrayVectorizedImage`` (utils/images/Image.scala:263-286)
— its (x, y) axes are the transpose of the usual (row, col) convention, which
is irrelevant to the CIFAR pipeline (every downstream op is spatially
symmetric).  Here images load as ``f32[N, 32, 32, 3]`` (row, col, RGB) with
values in [0, 255], matching the reference's unsigned-byte reads.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

NROW = 32
NCOL = 32
NCHAN = 3
RECORD_BYTES = 1 + NROW * NCOL * NCHAN


@dataclass
class LabeledImageBatch:
    """Batch analog of the reference's RDD[LabeledImage]."""

    images: np.ndarray  # [N, H, W, C] f32
    labels: np.ndarray  # [N] int32

    def __len__(self):
        return self.images.shape[0]


def cifar_loader(path: str) -> LabeledImageBatch:
    raw = np.fromfile(path, dtype=np.uint8)
    if raw.size % RECORD_BYTES != 0:
        raise ValueError(
            f"{path}: size {raw.size} not a multiple of CIFAR record "
            f"({RECORD_BYTES} bytes)"
        )
    records = raw.reshape(-1, RECORD_BYTES)
    labels = records[:, 0].astype(np.int32)
    images = (
        records[:, 1:]
        .reshape(-1, NCHAN, NROW, NCOL)
        .transpose(0, 2, 3, 1)
        .astype(np.float32)
    )
    return LabeledImageBatch(images=images, labels=labels)


CifarLoader = cifar_loader
