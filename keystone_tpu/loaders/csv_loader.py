"""CSV loading (reference src/main/scala/loaders/CsvDataLoader.scala,
LabeledData.scala).

The reference parallelizes CSV lines into an RDD of DenseVectors; here the
host loads into numpy and the array is committed row-sharded to the mesh.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass
class LabeledData:
    """(labels, data) pair (reference loaders/LabeledData.scala)."""

    labels: np.ndarray
    data: np.ndarray

    @staticmethod
    def from_rows(rows: np.ndarray, label_col: int = 0, one_indexed: bool = False):
        labels = rows[:, label_col].astype(np.int32)
        if one_indexed:
            labels = labels - 1
        data = np.delete(rows, label_col, axis=1)
        return LabeledData(labels=labels, data=data)


def csv_data_loader(path: str, dtype=np.float32) -> np.ndarray:
    """Load a comma-separated numeric file into [N, d]
    (reference loaders/CsvDataLoader.scala)."""
    return np.loadtxt(path, delimiter=",", dtype=dtype, ndmin=2)
