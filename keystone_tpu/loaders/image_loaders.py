"""Tar-archive image loaders for VOC / ImageNet
(reference src/main/scala/loaders/VOCLoader.scala:28-64,
ImageNetLoader.scala:11-41, ImageLoaderUtils.scala:32-100).

The reference streams tars from HDFS and decodes JPEGs with javax ImageIO
per executor (synchronized — ImageUtils.scala:17).  Here the host-side
Python path decodes with PIL into ``f32[H, W, 3]`` BGR arrays in [0, 255]
(the reference's ByteArrayVectorizedImage is BGR; GrayScaler assumes it);
the native C++ ingest library (keystone_tpu/native) replaces this path for
throughput when built.

Images of differing sizes are kept as per-image arrays; workloads bucket
them by shape before featurizing (XLA wants static shapes).
"""

from __future__ import annotations

import io
import os
import tarfile
from dataclasses import dataclass

import numpy as np

VOC_NUM_CLASSES = 20  # constant of the VOC 2007 dataset
IMAGENET_NUM_CLASSES = 1000

MIN_DIM = 36  # reference ImageUtils.loadImage rejects images < 36px (:23-27)


@dataclass
class MultiLabeledImages:
    """Batch analog of RDD[MultiLabeledImage]."""

    images: list  # of f32[H, W, 3] BGR arrays
    labels: list  # of list[int]
    filenames: list

    def __len__(self):
        return len(self.images)


@dataclass
class LabeledImages:
    images: list
    labels: np.ndarray  # [N] int32
    filenames: list

    def __len__(self):
        return len(self.images)


def decode_image(data: bytes) -> np.ndarray | None:
    """JPEG/PNG bytes -> f32[H, W, 3] BGR in [0, 255]; None when rejected
    (the reference logs and skips undecodable/small/odd-channel images,
    ImageLoaderUtils.scala:78-96)."""
    from PIL import Image as PILImage

    try:
        img = PILImage.open(io.BytesIO(data))
        if img.mode not in ("RGB", "L"):
            img = img.convert("RGB")
        arr = np.asarray(img, np.float32)
    except Exception:
        return None
    if arr.ndim == 2:  # grayscale triplicated (ImageConversions.scala:26-37)
        arr = np.stack([arr] * 3, axis=-1)
    if arr.shape[0] < MIN_DIM or arr.shape[1] < MIN_DIM:
        return None
    return arr[:, :, ::-1].copy()  # RGB -> BGR


def _tar_files(path: str) -> list[str]:
    if os.path.isdir(path):
        return sorted(
            os.path.join(path, f)
            for f in os.listdir(path)
            if f.endswith((".tar", ".tar.gz", ".tgz"))
        )
    return [path]


def _iter_tar_images(path: str):
    """Yield (member_name, image) for each decodable image in the tar(s)."""
    for tar_path in _tar_files(path):
        with tarfile.open(tar_path) as tf:
            for member in tf:
                if not member.isfile():
                    continue
                f = tf.extractfile(member)
                if f is None:
                    continue
                img = decode_image(f.read())
                if img is not None:
                    yield member.name.lstrip("./"), img


def voc_loader(data_path: str, labels_path: str, name_prefix: str = "VOCdevkit/VOC2007/JPEGImages/") -> MultiLabeledImages:
    """VOC 2007 loader (reference VOCLoader.scala:42-64): labels CSV has
    columns (id, class, classname, traintesteval, filename); class ids are
    1-indexed in the file."""
    labels_map: dict[str, list[int]] = {}
    with open(labels_path) as fh:
        next(fh, None)  # header (empty file -> no rows)
        for line in fh:
            if not line.strip():
                continue
            parts = line.strip().split(",")
            fname = parts[4].replace('"', "")
            labels_map.setdefault(fname, []).append(int(parts[1]) - 1)

    images, labels, filenames = [], [], []
    for name, img in _iter_tar_images(data_path):
        # namePrefix acts as a filter (reference ImageLoaderUtils.loadFiles
        # with Some(namePrefix)): only JPEGImages entries are kept.
        if not name.startswith(name_prefix):
            continue
        if name in labels_map:
            images.append(img)
            labels.append(labels_map[name])
            filenames.append(name)
    return MultiLabeledImages(images, labels, filenames)


def imagenet_loader(data_path: str, labels_path: str) -> LabeledImages:
    """ImageNet loader (reference ImageNetLoader.scala:25-41): each tar holds
    one synset directory whose name maps to a class id via the
    space-separated labels file."""
    labels_map: dict[str, int] = {}
    with open(labels_path) as fh:
        for line in fh:
            parts = line.split()
            if len(parts) >= 2:
                labels_map[parts[0]] = int(parts[1])

    images, labels, filenames = [], [], []
    for name, img in _iter_tar_images(data_path):
        synset = name.split("/")[0]
        if synset in labels_map:
            images.append(img)
            labels.append(labels_map[synset])
            filenames.append(name)
    return LabeledImages(images, np.asarray(labels, np.int32), filenames)
