"""Tar-archive image loaders for VOC / ImageNet
(reference src/main/scala/loaders/VOCLoader.scala:28-64,
ImageNetLoader.scala:11-41, ImageLoaderUtils.scala:32-100).

The reference streams tars from HDFS and decodes JPEGs with javax ImageIO
per executor (synchronized — ImageUtils.scala:17).  Here the host-side
path decodes into ``f32[H, W, 3]`` BGR arrays in [0, 255] (the reference's
ByteArrayVectorizedImage is BGR; GrayScaler assumes it), using a
thread-pool decoder (PIL releases the GIL during JPEG decode) so ingest
scales with host cores the way the reference's per-executor decode does.

Images of differing sizes are kept as per-image arrays; workloads bucket
them by shape before featurizing (XLA wants static shapes).
"""

from __future__ import annotations

import collections
import io
import os
import tarfile
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass

import numpy as np

from ..core.resilience import counters, retry

# Extra decode-ahead slots beyond the pool width.  The in-order window holds
# DECODED f32 images (~12x the JPEG bytes), so it must cover decode latency
# without scaling multiplicatively with cores: threads + _DECODE_AHEAD total
# in-flight entries keeps every core busy with a small constant of completed
# results buffered behind a slow head-of-line decode.  Env-tunable via
# ``KEYSTONE_DECODE_AHEAD`` (see :func:`decode_ahead`).
_DECODE_AHEAD = 8

VOC_NUM_CLASSES = 20  # constant of the VOC 2007 dataset
IMAGENET_NUM_CLASSES = 1000

MIN_DIM = 36  # reference ImageUtils.loadImage rejects images < 36px (:23-27)


@dataclass
class MultiLabeledImages:
    """Batch analog of RDD[MultiLabeledImage]."""

    images: list  # of f32[H, W, 3] BGR arrays
    labels: list  # of list[int]
    filenames: list

    def __len__(self):
        return len(self.images)


@dataclass
class LabeledImages:
    images: list
    labels: np.ndarray  # [N] int32
    filenames: list

    def __len__(self):
        return len(self.images)


def decode_image(data: bytes) -> np.ndarray | None:
    """JPEG/PNG bytes -> f32[H, W, 3] BGR in [0, 255]; None when rejected
    (the reference logs and skips undecodable/small/odd-channel images,
    ImageLoaderUtils.scala:78-96).

    JPEG streams decode through the native C++ libjpeg binding
    (native/ingest.cpp via loaders/native_decode.py — identical to PIL up
    to libjpeg IDCT version differences, no Python image library on the
    hot path); PNG and anything the native decoder declines falls back to
    PIL."""
    if data[:2] == b"\xff\xd8":
        from .native_decode import decode_jpeg_native

        arr = decode_jpeg_native(data)
        if arr is not None:
            return arr
        # fall through: native unavailable, stream corrupt, or image
        # rejected — the PIL path reproduces the same accept/reject rules

    from PIL import Image as PILImage

    try:
        img = PILImage.open(io.BytesIO(data))
        if img.mode not in ("RGB", "L"):
            img = img.convert("RGB")
        arr = np.asarray(img, np.float32)
    except Exception:
        return None
    if arr.ndim == 2:  # grayscale triplicated (ImageConversions.scala:26-37)
        arr = np.stack([arr] * 3, axis=-1)
    if arr.shape[0] < MIN_DIM or arr.shape[1] < MIN_DIM:
        return None
    return arr[:, :, ::-1].copy()  # RGB -> BGR


def _tar_files(path: str) -> list[str]:
    if os.path.isdir(path):
        return sorted(
            os.path.join(path, f)
            for f in os.listdir(path)
            if f.endswith((".tar", ".tar.gz", ".tgz"))
        )
    return [path]


def _iter_tar_members(path: str):
    """Yield (member_name, raw_bytes) for each file entry in the tar(s).

    Fault behavior (the reference gets per-record skip + task retry from
    Spark; here it is explicit): opening each tar retries transient IO
    errors with backoff (core.resilience.retry); a member whose payload
    cannot be read (truncated/corrupt entry) is counted under
    ``tar_member_error`` and skipped; a corrupt member *header* ends that
    tar (tar framing is unrecoverable past it) with a counted
    ``tar_stream_error`` but does not abort the remaining tars."""
    for tar_path in _tar_files(path):
        with retry(tarfile.open, name=f"tarfile.open({tar_path})")(tar_path) as tf:
            it = iter(tf)
            while True:
                try:
                    member = next(it)
                except StopIteration:
                    break
                except (tarfile.TarError, OSError, EOFError) as e:
                    counters.record("tar_stream_error", f"{tar_path}: {e}")
                    break
                if not member.isfile():
                    continue
                try:
                    f = tf.extractfile(member)
                    if f is None:
                        continue
                    data = f.read()
                except (tarfile.TarError, OSError, EOFError) as e:
                    counters.record(
                        "tar_member_error", f"{tar_path}:{member.name}: {e}"
                    )
                    continue
                yield member.name.lstrip("./"), data


def decode_threads() -> int:
    """Decoder pool width: ``KEYSTONE_DECODE_THREADS`` env or host cores."""
    raw = os.environ.get("KEYSTONE_DECODE_THREADS", "").strip()
    if raw:
        try:
            val = int(raw)
        except ValueError:
            raise ValueError(
                f"KEYSTONE_DECODE_THREADS={raw!r} is not an integer"
            ) from None
        if val < 1:
            raise ValueError(
                f"KEYSTONE_DECODE_THREADS={raw!r} must be >= 1"
            )
        return val
    try:  # affinity-aware (cgroup/container limits), not raw core count
        return len(os.sched_getaffinity(0)) or 1
    except AttributeError:  # platforms without sched_getaffinity
        return os.cpu_count() or 1


def decode_ahead() -> int:
    """Decode-ahead slots beyond the pool width: ``KEYSTONE_DECODE_AHEAD``
    env or the :data:`_DECODE_AHEAD` default.  Total in-flight decodes per
    stream = ``decode_threads() + decode_ahead()``."""
    raw = os.environ.get("KEYSTONE_DECODE_AHEAD", "").strip()
    if raw:
        try:
            val = int(raw)
        except ValueError:
            raise ValueError(
                f"KEYSTONE_DECODE_AHEAD={raw!r} is not an integer"
            ) from None
        if val < 0:
            raise ValueError(f"KEYSTONE_DECODE_AHEAD={raw!r} must be >= 0")
        return val
    return _DECODE_AHEAD


def _iter_tar_images(path: str, num_threads: int | None = None):
    """Yield (member_name, image) for each decodable image in the tar(s).

    The tar stream is read serially (it is a sequential format) but JPEG
    decode — the hot part, reference ImageLoaderUtils.scala:60-100 decodes
    per executor in parallel — runs on a thread pool: PIL releases the GIL
    inside the libjpeg decode loop, so decode scales with host cores.  A
    bounded in-order window of in-flight futures gives decode-ahead
    double-buffering without unbounded memory.
    """
    num_threads = num_threads or decode_threads()
    # Build/load the native decoder BEFORE the pool spins up: the one-time
    # g++ build runs under native_decode's module lock, and paying it lazily
    # inside the first decode call would stall every worker behind it.
    from .native_decode import available as _native_available

    _native_available()
    if num_threads <= 1:
        for name, data in _iter_tar_members(path):
            img = decode_image(data)
            if img is not None:
                yield name, img
            else:
                counters.record("corrupt_image", name)
        return

    ahead = decode_ahead()
    with ThreadPoolExecutor(max_workers=num_threads) as pool:
        window: collections.deque = collections.deque()
        for name, data in _iter_tar_members(path):
            window.append((name, pool.submit(decode_image, data)))
            if len(window) >= num_threads + ahead:
                done_name, fut = window.popleft()
                img = fut.result()
                if img is not None:
                    yield done_name, img
                else:
                    counters.record("corrupt_image", done_name)
        while window:
            done_name, fut = window.popleft()
            img = fut.result()
            if img is not None:
                yield done_name, img
            else:
                counters.record("corrupt_image", done_name)


def voc_labels_map(labels_path: str) -> dict[str, list[int]]:
    """Parse the VOC labels CSV (columns id, class, classname,
    traintesteval, filename; class ids 1-indexed) into filename ->
    class-id-list — shared by the eager loader and the streaming source."""
    labels_map: dict[str, list[int]] = {}
    with retry(open, name=f"open({labels_path})")(labels_path) as fh:
        next(fh, None)  # header (empty file -> no rows)
        for line in fh:
            if not line.strip():
                continue
            parts = line.strip().split(",")
            fname = parts[4].replace('"', "")
            labels_map.setdefault(fname, []).append(int(parts[1]) - 1)
    return labels_map


def imagenet_labels_map(labels_path: str) -> dict[str, int]:
    """Parse the space-separated synset -> class-id labels file — shared by
    the eager loader and the streaming source."""
    labels_map: dict[str, int] = {}
    with retry(open, name=f"open({labels_path})")(labels_path) as fh:
        for line in fh:
            parts = line.split()
            if len(parts) >= 2:
                labels_map[parts[0]] = int(parts[1])
    return labels_map


def voc_loader(data_path: str, labels_path: str, name_prefix: str = "VOCdevkit/VOC2007/JPEGImages/") -> MultiLabeledImages:
    """VOC 2007 loader (reference VOCLoader.scala:42-64): labels CSV has
    columns (id, class, classname, traintesteval, filename); class ids are
    1-indexed in the file."""
    labels_map = voc_labels_map(labels_path)

    images, labels, filenames = [], [], []
    for name, img in _iter_tar_images(data_path):
        # namePrefix acts as a filter (reference ImageLoaderUtils.loadFiles
        # with Some(namePrefix)): only JPEGImages entries are kept.
        if not name.startswith(name_prefix):
            continue
        if name in labels_map:
            images.append(img)
            labels.append(labels_map[name])
            filenames.append(name)
    return MultiLabeledImages(images, labels, filenames)


def imagenet_loader(data_path: str, labels_path: str) -> LabeledImages:
    """ImageNet loader (reference ImageNetLoader.scala:25-41): each tar holds
    one synset directory whose name maps to a class id via the
    space-separated labels file."""
    labels_map = imagenet_labels_map(labels_path)

    images, labels, filenames = [], [], []
    for name, img in _iter_tar_images(data_path):
        synset = name.split("/")[0]
        if synset in labels_map:
            images.append(img)
            labels.append(labels_map[synset])
            filenames.append(name)
    return LabeledImages(images, np.asarray(labels, np.int32), filenames)
