"""PCA + multi-class LDA (reference src/main/scala/nodes/learning/PCA.scala:16-106,
LinearDiscriminantAnalysis.scala:17-67).

The reference collects samples to the driver and runs LAPACK ``sgesvd`` /
Breeze ``eig`` there.  Here both run on-device: the SVD in float32 (as the
reference's sgesvd) on an HBM-resident sample matrix, and LDA via the
symmetric whitening trick (Cholesky of S_W + ``eigh``) instead of the
non-symmetric ``eig(inv(S_W) S_B)`` — same eigenvalues, same projection
subspace, but a TPU-friendly symmetric eigensolve.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

import numpy as np

from ..core.pipeline import Estimator, LabelEstimator, Transformer, node
from .linear import LinearMapper


@node(data_fields=("pca_mat",))
class PCATransformer(Transformer):
    """Project vectors: ``in @ pcaMat`` (reference PCA.scala:16-27 computes
    ``pcaMat.t * in`` per item — identical for batched rows)."""

    def __init__(self, pca_mat):
        self.pca_mat = pca_mat

    def __call__(self, batch):
        return batch @ self.pca_mat


@node(data_fields=("pca_mat",))
class BatchPCATransformer(Transformer):
    """Project descriptor matrices with descriptors as *columns*
    (reference PCA.scala:35-40: ``pcaMat.t * in``).  Batch input is
    ``[N, d, cols]`` -> ``[N, dims, cols]``."""

    def __init__(self, pca_mat):
        self.pca_mat = pca_mat

    def __call__(self, batch):
        return jnp.einsum("dk,ndc->nkc", self.pca_mat, batch)


def compute_pca(data_mat, dims: int):
    """The reference's computePCA (PCA.scala:63-106): mean-center, f32 SVD,
    MATLAB sign convention (largest-|element| of each column positive), first
    ``dims`` columns of V."""
    data_mat = jnp.asarray(data_mat, jnp.float32)
    means = jnp.mean(data_mat, axis=0)
    data = data_mat - means
    # full VT only when n < d; for n >= d the reduced VT is the same [d, d]
    # and full_matrices=True would materialize an [n, n] U (the reference
    # passes jobu="N" because samples are O(1e6) rows, PCA.scala:57,80-86)
    n, d = data.shape
    _, _, vt = jnp.linalg.svd(data, full_matrices=n < d)
    pca = vt.T  # [d, d], columns = components, descending singular value
    col_max = jnp.max(pca, axis=0)
    abs_col_max = jnp.max(jnp.abs(pca), axis=0)
    signs = jnp.where(col_max == abs_col_max, 1.0, -1.0).astype(pca.dtype)
    pca = pca * signs
    return pca[:, :dims]


class PCAEstimator(Estimator):
    """Fit PCA from a sample matrix (reference PCA.scala:46-61; the
    driver-collect disappears — the sample stays on device)."""

    def __init__(self, dims: int):
        self.dims = dims

    def fit(self, samples) -> PCATransformer:
        return PCATransformer(compute_pca(jnp.asarray(samples), self.dims))


class LinearDiscriminantAnalysis(LabelEstimator):
    """Multi-class LDA -> LinearMapper
    (reference LinearDiscriminantAnalysis.scala:17-67).

    S_W = Σ_c Σ_{x∈c} (x-μ_c)(x-μ_c)ᵀ,  S_B = Σ_c n_c (μ_c-μ)(μ_c-μ)ᵀ.
    Solved as the symmetric problem ``eigh(L⁻¹ S_B L⁻ᵀ)`` with
    ``S_W = L Lᵀ`` — eigenvalues match ``eig(inv(S_W) S_B)``; eigenvectors
    are ``W = L⁻ᵀ Y`` (differ from the reference only by per-vector scale,
    which is irrelevant to the projection)."""

    def __init__(self, num_dimensions: int):
        self.num_dimensions = num_dimensions

    def fit(self, data, labels) -> LinearMapper:
        data = jnp.asarray(data, jnp.float64 if jax.config.jax_enable_x64 else jnp.float32)
        labels_np = np.asarray(labels)
        classes = np.unique(labels_np)
        total_mean = jnp.mean(data, axis=0)
        n = data.shape[0]

        # One-hot gemms instead of per-class gathers (no data-dependent
        # shapes; a few gemms total regardless of class count).  S_W is
        # accumulated directly from class-mean-centered rows — no
        # S_total − S_B subtraction, which cancels catastrophically in f32
        # when between-class scatter dominates.
        class_of_row = np.searchsorted(classes, labels_np)
        onehot = jnp.asarray(
            (classes[:, None] == labels_np[None, :]).astype(np.float32), data.dtype
        )  # [C, n]
        counts = jnp.sum(onehot, axis=1)  # [C]
        class_means = (onehot @ data) / counts[:, None]  # [C, d]
        centered = data - class_means[jnp.asarray(class_of_row)]
        sw = centered.T @ centered
        dm = (class_means - total_mean) * jnp.sqrt(counts)[:, None]
        sb = dm.T @ dm

        l = jnp.linalg.cholesky(sw)
        if not bool(jnp.all(jnp.isfinite(l))):
            raise ValueError(
                "S_W is singular (need n_samples - n_classes >= n_features); "
                "LDA projection would be NaN"
            )
        linv_sb = jax.scipy.linalg.solve_triangular(l, sb, lower=True)
        m = jax.scipy.linalg.solve_triangular(l, linv_sb.T, lower=True).T
        m = 0.5 * (m + m.T)  # symmetrize fp error
        eigvals, y = jnp.linalg.eigh(m)
        order = jnp.argsort(-jnp.abs(eigvals))[: self.num_dimensions]
        w = jax.scipy.linalg.solve_triangular(
            l.T, y[:, order], lower=False
        )
        # Breeze's eig returns unit eigenvectors; normalize so the projection
        # matrix matches the reference's (up to per-column sign).
        w = w / jnp.linalg.norm(w, axis=0, keepdims=True)
        return LinearMapper(w)
