"""ZCA whitening (reference src/main/scala/nodes/learning/ZCAWhitener.scala:11-64).

The reference collects one local matrix, runs LAPACK ``sgesvd`` in float32,
and forms ``V diag((s²/(n-1) + 0.1)^-0.5) Vᵀ``.  Here the SVD runs on-device
(`jnp.linalg.svd`, f32 — the reference also downcasts to Float before the
SVD), so the whitener can be fit from an HBM-resident sample matrix with no
host round-trip.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.pipeline import Estimator, Transformer, node


@node(data_fields=("whitener", "means"))
class ZCAWhitener(Transformer):
    """Apply ``(x - means) @ whitener`` (reference ZCAWhitener.scala:11-17)."""

    def __init__(self, whitener, means):
        self.whitener = whitener
        self.means = means

    def __call__(self, batch):
        return (batch - self.means) @ self.whitener


class ZCAWhitenerEstimator(Estimator):
    """Fit the ZCA transform from a single [n, d] sample matrix
    (reference ZCAWhitener.scala:19-64).

    Note the reference's ``eps`` constructor arg is *unused* — the shrinkage
    added to the squared singular values is the hard-coded ``0.1f``
    (ZCAWhitener.scala:52); we reproduce that (keeping ``eps`` for API
    parity).
    """

    def __init__(self, eps: float = 1e-12):
        self.eps = eps

    def fit(self, data) -> ZCAWhitener:
        return self.fit_single(jnp.asarray(data))

    def fit_single(self, mat) -> ZCAWhitener:
        mat = jnp.asarray(mat)
        means = jnp.mean(mat, axis=0)
        centered = (mat - means).astype(jnp.float32)
        n, d = centered.shape
        # Full VT (as the reference's sgesvd jobvt="A"): when n < d the
        # null-space components have s=0 and still get the 0.1 shrinkage,
        # i.e. a 0.1^-0.5 gain — dropping them would change the transform.
        # full_matrices only when n < d: otherwise the reduced VT is already
        # [d, d] and full_matrices=True would materialize an [n, n] U
        # (the reference avoids U entirely via sgesvd jobu="N").
        _, s, vt = jnp.linalg.svd(centered, full_matrices=n < d)
        s2 = jnp.zeros((d,), s.dtype).at[: s.shape[0]].set((s * s) / (n - 1.0))
        scale = (s2 + 0.1) ** -0.5
        whitener = (vt.T * scale) @ vt
        return ZCAWhitener(whitener.astype(mat.dtype), means)
