"""Gaussian mixture model with diagonal covariances, fit by EM
(reference src/main/scala/nodes/learning/GaussianMixtureModel.scala:18-91,
which delegates to the vendored enceval C++ EM — src/main/cpp/EncEval.cxx:122-193).

The reference collects samples to the driver and runs single-threaded C++ EM.
Here the E-step is one [n, k] batched log-density + softmax on the MXU and
the M-step a handful of gemms — chunked over samples so 1e7-descriptor fits
stream through HBM.  Init follows EncEval.cxx:146-148: seed-42 random samples
as means (the exact enceval RNG is not reproduced; parity target is
distribution recovery, per the reference suite EncEvalSuite.scala:42-64).

Model layout matches the reference: ``means``/``variances`` are [d, k]
(centroid-major columns), ``weights`` [k].
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core.pipeline import Estimator, Transformer, node


@node(data_fields=("means", "variances", "weights"))
class GaussianMixtureModel(Transformer):
    """Diagonal-covariance GMM (reference GaussianMixtureModel.scala:18-36).

    ``__call__`` returns the soft cluster assignments (posteriors) — the
    reference declares this surface but leaves it unimplemented (:32-36).
    """

    def __init__(self, means, variances, weights):
        means = jnp.asarray(means)
        variances = jnp.asarray(variances)
        weights = jnp.asarray(weights)
        if means.shape != variances.shape:
            raise ValueError("GMM means and variances must be the same size.")
        if weights.shape[0] != means.shape[1]:
            raise ValueError("Every GMM center must have a weight.")
        self.means = means
        self.variances = variances
        self.weights = weights

    @property
    def k(self) -> int:
        return self.means.shape[1]

    @property
    def dim(self) -> int:
        return self.means.shape[0]

    def log_responsibilities(self, x):
        """[n, d] -> [n, k] log posteriors under the mixture."""
        return _log_resp(x, self.means, self.variances, self.weights)

    def __call__(self, batch):
        return jax.nn.softmax(self.log_responsibilities(batch), axis=-1)

    @staticmethod
    def load(mean_file: str, vars_file: str, weights_file: str) -> "GaussianMixtureModel":
        """CSV artifact loading (reference GaussianMixtureModel.scala:83-90) —
        the load-or-fit checkpoint pattern."""
        means = np.loadtxt(mean_file, delimiter=",", ndmin=2)
        variances = np.loadtxt(vars_file, delimiter=",", ndmin=2)
        weights = np.loadtxt(weights_file, delimiter=",").ravel()
        return GaussianMixtureModel(means, variances, weights)


@jax.jit
def _log_resp(x, means, variances, weights):
    # log N(x; mu_k, diag sigma2_k) + log pi_k, via one gemm per moment
    inv_var = 1.0 / variances  # [d, k]
    x2 = x * x
    quad = x2 @ inv_var - 2.0 * (x @ (means * inv_var)) + jnp.sum(
        means * means * inv_var, axis=0
    )
    log_det = jnp.sum(jnp.log(variances), axis=0)
    d = x.shape[1]
    log_pdf = -0.5 * (quad + log_det + d * jnp.log(2.0 * jnp.pi))
    return log_pdf + jnp.log(weights)


@jax.jit
def _e_stats(x, means, variances, weights):
    """Sufficient statistics (s0, s1, s2, Σ log-norm) for one sample chunk."""
    logr = _log_resp(x, means, variances, weights)
    log_norm = jax.scipy.special.logsumexp(logr, axis=1, keepdims=True)
    q = jnp.exp(logr - log_norm)  # [n, k]
    s0 = jnp.sum(q, axis=0)  # [k]
    s1 = x.T @ q  # [d, k]
    s2 = (x * x).T @ q  # [d, k]
    return s0, s1, s2, jnp.sum(log_norm)


def _em_step(x, means, variances, weights, var_floor, chunk: int):
    """One EM iteration, E-step chunked over samples so the [n, k] posterior
    matrix never exceeds one chunk's footprint."""
    n = x.shape[0]
    d, k = means.shape
    s0 = jnp.zeros((k,), x.dtype)
    s1 = jnp.zeros((d, k), x.dtype)
    s2 = jnp.zeros((d, k), x.dtype)
    llh_sum = jnp.zeros((), x.dtype)
    for i in range(0, n, chunk):
        c0, c1, c2, cl = _e_stats(x[i : i + chunk], means, variances, weights)
        s0, s1, s2, llh_sum = s0 + c0, s1 + c1, s2 + c2, llh_sum + cl
    # Floor the responsibility mass: an empty/collapsed component would give
    # 0/0 = NaN means and poison the whole fit.
    s0_safe = jnp.maximum(s0, 1e-10)
    new_means = s1 / s0_safe
    new_vars = jnp.maximum(s2 / s0_safe - new_means * new_means, var_floor)
    new_weights = s0 / n
    return new_means, new_vars, new_weights, llh_sum / n


@functools.partial(jax.jit, static_argnames=("max_iter", "chunk"))
def _em_fit(x, means, variances, weights, var_floor, tol, max_iter: int, chunk: int):
    """The ENTIRE EM fit as one compiled program: a lax.while_loop runs EM
    steps until the device-side convergence test fires (same test as the
    reference's enceval loop) or ``max_iter`` is hit.  The eager form
    host-pulled the log-likelihood every iteration — up to ``max_iter``
    transport round-trips per fit (~13 s of pure latency at 100 iters on a
    tunneled chip) for a loop whose compute is milliseconds."""

    def cond(state):
        i, _, _, _, llh, prev = state
        return (i < max_iter) & (
            jnp.abs(llh - prev) >= tol * jnp.maximum(1.0, jnp.abs(llh))
        )

    def body(state):
        i, m, v, w, llh, _ = state
        m2, v2, w2, llh2 = _em_step(x, m, v, w, var_floor, chunk)
        return (i + 1, m2, v2, w2, llh2, llh)

    # +/-inf sentinels make the first two conditions unconditionally true,
    # reproducing the eager loop's "first comparison at iteration 2".
    init = (0, means, variances, weights, jnp.inf, -jnp.inf)
    iters, m, v, w, _, _ = jax.lax.while_loop(cond, body, init)
    return m, v, w, iters


class GaussianMixtureModelEstimator(Estimator):
    """Fit a ``k``-center GMM by EM (reference GaussianMixtureModel.scala:44-80;
    EM semantics from the vendored enceval gaussian_mixture<float>)."""

    def __init__(
        self,
        k: int,
        max_iter: int = 100,
        tol: float = 1e-4,
        seed: int = 42,
        var_floor_factor: float = 1e-3,
        chunk: int = 1 << 18,
    ):
        self.k = k
        self.max_iter = max_iter
        self.tol = tol
        self.seed = seed
        self.var_floor_factor = var_floor_factor
        self.chunk = chunk

    def fit(self, samples) -> GaussianMixtureModel:
        x = jnp.asarray(samples, jnp.float32)
        n, d = x.shape
        if n < self.k:
            raise ValueError(f"need at least k={self.k} samples, got {n}")

        rng = np.random.default_rng(self.seed)  # seed 42 per EncEval.cxx:146
        idx = rng.choice(n, self.k, replace=False)
        means = x[jnp.asarray(idx)].T  # [d, k]
        global_var = jnp.var(x, axis=0)[:, None]  # [d, 1]
        variances = jnp.broadcast_to(global_var, (d, self.k))
        weights = jnp.full((self.k,), 1.0 / self.k, x.dtype)
        var_floor = self.var_floor_factor * jnp.mean(global_var)

        means, variances, weights, iters = _em_fit(
            x, means, variances, weights, var_floor,
            jnp.asarray(self.tol, x.dtype), self.max_iter, self.chunk,
        )
        # EM iterations actually run (device-resident until read; a host
        # pull of this one scalar is the only extra sync a caller pays).
        self.last_iterations = iters
        return GaussianMixtureModel(means, variances, weights)
