"""Class-weighted block coordinate descent least squares
(reference src/main/scala/nodes/learning/BlockWeightedLeastSquares.scala:35-362).

The reference re-shuffles the data so each Spark partition holds exactly one
class (HashPartitioner on the argmax class index, :324-361), then per pass per
block: tree-reduces population gram/XᵀR statistics, broadcasts them, runs a
per-class local solve on each partition, collects the per-class weight
columns, and updates a cached residual RDD.

TPU-native re-design:

* the class shuffle becomes a host-side stable sort by class (one-time);
* population statistics are plain gemms over the sorted [N, d] block — under
  ``jit`` with row-sharded inputs XLA lowers them to local gram + ICI
  all-reduce (the treeReduce replacement);
* the per-class solves run as a ``lax.scan`` over *chunks* of classes with a
  ``vmap`` inside each chunk — ``class_chunk`` classes are gathered, built
  into mixture-weighted normal equations, and solved concurrently as one
  batched ``linalg.solve`` (the reference solves all classes concurrently
  across partitions, :228-263); only a [chunk, n_max, d] slab is ever
  materialized, never the full [C, n_max, d] tensor;
* with a mesh, features are row-sharded over the data axis (population
  grams lower to local gram + ICI all-reduce) and each class chunk is
  sharded over the model axis — the class-partitioned parallelism of the
  reference's one-partition-per-class layout;
* broadcasts/collects disappear (single-controller, arrays stay in HBM).

Semantics (update order, statistics caching across passes, the λ-shifted
solve, and the joint-means intercept) follow the reference exactly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
try:
    from jax import shard_map
except ImportError:  # pre-0.5 jax exports it under experimental only
    from jax.experimental.shard_map import shard_map

from jax.sharding import NamedSharding, PartitionSpec as P

import dataclasses

from ..core import autoshard
from ..core import memory as kmem
from ..core import numerics as knum
from ..core import profiler as kprof
from ..core import trace
from ..core.pipeline import LabelEstimator
from ..core.resilience import counters
from ..parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    current_mesh,
    enumerate_meshes,
    mesh_desc,
    reduced_mesh,
)
from .block import BlockLinearMapper, _blocked_design_matrix, _design_matrix_owned


def _bwls_spec_variants(m, n_classes: int) -> list[dict]:
    """Per-operand spec assignments the BWLS placement search enumerates
    for one mesh shape beyond the default layout: model-axis-sharded
    label/residual columns (the wide-class layout — per-class residual
    columns are independent, so class columns shard cleanly over the
    model axis when the class count divides it) and fully-replicated
    labels.  Deterministic, and legal by construction."""
    d_sz, m_sz = m.shape[DATA_AXIS], m.shape[MODEL_AXIS]
    out: list[dict] = []
    if m_sz > 1 and n_classes % m_sz == 0:
        out.append({"labels": "model@dim1"})
    if d_sz * m_sz > 1:
        out.append({"labels": "replicated"})
    return out


@dataclasses.dataclass
class _SolveCtx:
    """Mesh-dependent BWLS solve layout for ONE ladder tier: the padded
    row count, the class-chunk rounding, and the sort/pad/shard closures
    all follow the tier's mesh axis sizes, so each rung of the mesh
    degradation ladder builds its own (see ``fit``'s ``prep``)."""

    mesh: object
    p_tot: int
    chunk: int
    sort_pad: object
    sort_labels: object
    valid_d: object
    seg_ids: object
    starts: object
    counts: object
    counts_f: object
    joint_label_mean: object

# Per-row byte budget for the column-chunked device gather in the class
# shuffle: each chunk transiently materializes [p_tot, chunk_bytes] un-sharded
# per device (e.g. 2 KB/row x 1.25M rows = 2.5 GB slab at ImageNet scale).
# Fallback path only — see _RegroupPlan for the all_to_all fast path.
_GATHER_COL_CHUNK = 2048


class _RegroupPlan:
    """Host-precomputed routing for the TRAFFIC-OPTIMAL class shuffle: a
    device-side all_to_all permutation in which each row crosses the ICI
    exactly once (reference BlockWeightedLeastSquares.scala:324-361 — its
    HashPartitioner shuffle likewise moves each row once between executors).

    Traffic model (the reason this path exists): the fallback chunked
    replicated-index gather below makes GSPMD all-gather every column slab,
    so the matrix crosses the interconnect D times (once per device).  At
    the 1.25M x 256k f32 north star that is D x 1.28 TB (41 TB on a
    32-chip pod) versus 1.28 TB moved once here — a D x reduction, worth
    minutes of pod time at ~100 GB/s per-link ICI.

    Construction: rows are grouped by (source shard, destination shard);
    each device locally gathers its send buckets (padded to the max bucket
    ``m_pad``), one lax.all_to_all exchanges them, and a local gather (with
    out-of-range fill) places received rows and zeroes the tail.  The only
    overhead vs optimal is bucket padding (m_pad * D^2 / n rows).
    """

    def __init__(self, order: np.ndarray, n_src: int, p_tot: int, d: int):
        n = order.shape[0]
        rows_in, rows_out = n_src // d, p_tot // d
        r = np.arange(n)
        src = order // rows_in
        dst = r // rows_out
        # occurrence rank of each row within its (src, dst) bucket,
        # preserving destination order
        key = src * d + dst
        by_key = np.argsort(key, kind="stable")
        ks = key[by_key]
        change = np.r_[True, ks[1:] != ks[:-1]]
        grp_start = np.maximum.accumulate(np.where(change, np.arange(n), 0))
        j = np.empty(n, np.int64)
        j[by_key] = np.arange(n) - grp_start
        m_pad = int(j.max()) + 1 if n else 1

        send = np.zeros((d, d, m_pad), np.int32)
        send[src, dst, j] = (order % rows_in).astype(np.int32)
        # received layout on dst: [src bucket, j] -> flat src*m_pad + j;
        # out-of-range index for the zero tail (jnp.take mode="fill")
        recv = np.full((d, rows_out), d * m_pad, np.int32)
        recv[dst, r % rows_out] = (src * m_pad + j).astype(np.int32)

        self.d = d
        self.m_pad = m_pad
        self.rows_out = rows_out
        # Skew guard: buckets pad to the GLOBAL max m_pad, so a
        # class-correlated input order (near-identity permutation) would
        # make the per-device exchange buffer [d*m_pad, cols] approach the
        # full unsharded block — exactly the slab the chunked fallback
        # exists to bound.  Usable only while padding stays within 2x of
        # optimal; an unusable plan allocates NO device buffers.
        self.usable = d * m_pad <= 2 * rows_out
        if self.usable:
            self.send_idx = jnp.asarray(send)
            self.recv_idx = jnp.asarray(recv)
        self._jitted = {}  # mesh -> compiled regroup program

    def apply(self, mesh, x):
        """Sorted + zero-tail-padded copy of row-sharded ``x`` via one
        all_to_all; output row-sharded over the data axis."""
        d, m_pad = self.d, self.m_pad

        if mesh not in self._jitted:

            def f(x_l, s_l, r_l):
                cols = x_l.shape[1]
                buf = jnp.take(x_l, s_l[0].reshape(-1), axis=0)
                buf = buf.reshape(d, m_pad, cols)
                recv = jax.lax.all_to_all(buf, DATA_AXIS, 0, 0)
                flat = recv.reshape(d * m_pad, cols)
                return jnp.take(flat, r_l[0], axis=0, mode="fill", fill_value=0)

            self._jitted[mesh] = jax.jit(
                shard_map(
                    f,
                    mesh=mesh,
                    in_specs=(
                        P(DATA_AXIS, None),
                        P(DATA_AXIS, None, None),
                        P(DATA_AXIS, None),
                    ),
                    out_specs=P(DATA_AXIS, None),
                )
            )
        return self._jitted[mesh](x, self.send_idx, self.recv_idx)


@functools.partial(jax.jit, static_argnames=("n_max", "chunk", "mesh"))
def _class_solves(
    xb_pad,  # [N + pad, d] sorted block features, zero tail
    res_pad,  # [N + pad, C] sorted residual, zero tail
    starts,  # [C]
    counts,  # [C]
    pop_cov,  # [d, d]
    pop_mean,  # [d]
    pop_xtr,  # [d, C]
    joint_means,  # [C, d]
    residual_mean,  # [C]
    model_block,  # [d, C]
    lam,
    mixture_weight,
    n_max: int,
    chunk: int,
    mesh=None,
):
    """Per-class solve sweep (reference :228-263): scan over class chunks,
    ``chunk`` concurrent batched solves per step — returns ΔW [d, C]."""
    d = xb_pad.shape[1]
    c_total = starts.shape[0]
    w = mixture_weight
    eye = jnp.eye(d, dtype=xb_pad.dtype)
    row_ids = jnp.arange(n_max)

    def one_class(start, cnt, c, xtr_c, jm_c, rm_c, m_c):
        xc = jax.lax.dynamic_slice(xb_pad, (start, 0), (n_max, d))
        mask = (row_ids < cnt).astype(xb_pad.dtype)
        xc = xc * mask[:, None]
        # this class's own residual column (:231)
        r_c = jax.lax.dynamic_slice(res_pad, (start, c), (n_max, 1))[:, 0] * mask
        n_c = cnt.astype(xb_pad.dtype)

        class_mean = jnp.sum(xc, axis=0) / n_c
        zm = (xc - class_mean) * mask[:, None]
        class_cov = zm.T @ zm / n_c
        class_xtr = xc.T @ r_c / n_c

        mean_diff = class_mean - pop_mean
        joint_xtx = (
            pop_cov * (1.0 - w)
            + class_cov * w
            + jnp.outer(mean_diff, mean_diff) * ((1.0 - w) * w)
        )
        mean_mixture_wt = rm_c * (1.0 - w) + w * (jnp.sum(r_c) / n_c)
        joint_xtr = xtr_c * (1.0 - w) + class_xtr * w - jm_c * mean_mixture_wt
        # λ-shifted solve (reference :259-260)
        return jnp.linalg.solve(joint_xtx + lam * eye, joint_xtr - m_c * lam)

    solve_chunk = jax.vmap(one_class)

    # Pad the class axis to a chunk multiple by repeating class 0 (results
    # for the repeats are discarded; repeating a real class keeps every
    # batched solve well-conditioned).
    n_chunks = -(-c_total // chunk)
    cls = jnp.arange(c_total)
    cls_pad = jnp.concatenate(
        [cls, jnp.zeros(n_chunks * chunk - c_total, cls.dtype)]
    )

    def chunked(x):
        return x.reshape((n_chunks, chunk) + x.shape[1:])

    xs = (
        chunked(starts[cls_pad]),
        chunked(counts[cls_pad]),
        chunked(cls_pad),
        chunked(pop_xtr.T[cls_pad]),
        chunked(joint_means[cls_pad]),
        chunked(residual_mean[cls_pad]),
        chunked(model_block.T[cls_pad]),
    )

    model_spec = None
    if mesh is not None and chunk % mesh.shape[MODEL_AXIS] == 0:
        model_spec = NamedSharding(mesh, P(MODEL_AXIS, None))

    def step(carry, inp):
        dws = solve_chunk(*inp)  # [chunk, d]
        if model_spec is not None:
            # Class-partitioned parallelism: each device in the model axis
            # owns chunk/model_size of the concurrent class solves.
            dws = jax.lax.with_sharding_constraint(dws, model_spec)
        return carry, dws

    _, dws = jax.lax.scan(step, None, xs)  # [n_chunks, chunk, d]
    return dws.reshape(n_chunks * chunk, d)[:c_total].T  # [d, C]


def _fused_bwls_impl(
    x, labels_sorted, valid, seg_ids, starts, counts, counts_f,
    joint_label_mean, nvalid, lam, w,
    num_iter: int, n_max: int, chunk: int, num_classes: int, widths, mesh,
    specs=None,
):
    """The ENTIRE BWLS solve as one compiled program (the
    BlockLeastSquares treatment, solvers/block._fused_bcd_fit): residual
    init, per-block population statistics (computed once, cached across
    passes like the reference's persisted grams), ``num_iter`` passes of a
    lax.scan over blocks (population XᵀR gram + class-solve sweep + model
    and residual updates + residual class means), and the joint-means
    intercept — round 3 ran ~5 eager dispatches per block per pass over a
    ~126 ms-round-trip transport.  (reference :134-311.)

    x: ONE sorted, zero-tail-padded [P, B*bs] design matrix (bs =
    max(widths)); block i occupies columns [i*bs, i*bs + widths[i]) with
    zero pad columns.  Scan steps dynamic-slice their block out of ``x``,
    so peak HBM is one design matrix plus a single [P, bs] block slice —
    the round-4 form stacked blocks into a [B, P, bs] tensor, transiently
    doubling the footprint.  Pad columns get a unit diagonal shift on the
    population covariance (scaled by (1-w) > 0 in the joint normal
    equations), so their solutions are exactly zero and every batched solve
    stays nonsingular even at lam=0.

    ``specs`` (static; sorted tuple of ``(operand, spec)`` pairs from a
    searched spec assignment, core.autoshard ISSUE 10): overrides the
    per-operand layout — ``"x"`` defaults to ``data@dim0``, ``"labels"``
    (the sorted labels, and through them the residual carries) to the
    caller's placement.  ``specs=None`` is bit-for-bit the PR 9 program.

    Returns (models [B, bs, C], intercept [C]).
    """
    bs = max(widths)
    nb = len(widths)
    dtype = labels_sorted.dtype
    n = nvalid.astype(dtype)

    if mesh is not None:
        sp = dict(specs) if specs else {}
        x = jax.lax.with_sharding_constraint(
            x, autoshard.spec_sharding(sp.get("x", "data@dim0"), mesh, 2)
        )
        lspec = sp.get("labels")
        if lspec is not None:
            labels_sorted = jax.lax.with_sharding_constraint(
                labels_sorted, autoshard.spec_sharding(lspec, mesh, 2)
            )

    res = (labels_sorted - joint_label_mean) * valid
    rmean = _residual_class_means(res, seg_ids, counts_f, num_classes)

    pad_diag = jnp.stack(
        [(jnp.arange(bs) >= wd).astype(dtype) for wd in widths]
    )  # [B, bs] — 1.0 on pad columns

    def slice_block(i):
        return jax.lax.dynamic_slice_in_dim(x, i * bs, bs, axis=1)

    def stats_one(carry, inp):
        i, pd = inp
        xb = slice_block(i)
        pop_mean = jnp.sum(xb, axis=0) / n
        pop_cov = xb.T @ xb / n - jnp.outer(pop_mean, pop_mean) + jnp.diag(pd)
        class_means = _class_sums(xb, seg_ids, num_classes) / counts_f[:, None]
        joint_means = w * class_means + (1.0 - w) * pop_mean
        return carry, (pop_cov, pop_mean, joint_means)

    _, (pop_covs, pop_means, joint_means_all) = jax.lax.scan(
        stats_one, None, (jnp.arange(nb), pad_diag)
    )

    models = jnp.zeros((nb, bs, num_classes), dtype)

    def block_step(carry, inp):
        res, rmean = carry
        i, pop_cov, pop_mean, jm, model = inp
        xb = slice_block(i)
        pop_xtr = xb.T @ res / n
        dw = _class_solves(
            xb, res, starts, counts, pop_cov, pop_mean, pop_xtr,
            jm, rmean, model, lam, w, n_max, chunk, mesh,
        )
        model_new = model + dw
        res_new = res - xb @ dw
        rmean_new = _residual_class_means(res_new, seg_ids, counts_f, num_classes)
        return (res_new, rmean_new), model_new

    def one_pass(carry, _):
        models, res, rmean = carry
        (res, rmean), models = jax.lax.scan(
            block_step,
            (res, rmean),
            (jnp.arange(nb), pop_covs, pop_means, joint_means_all, models),
        )
        return (models, res, rmean), None

    (models, res, rmean), _ = jax.lax.scan(
        one_pass, (models, res, rmean), None, length=num_iter
    )

    # Intercept from joint means (reference :307-311):
    # b = jointLabelMean − Σ_d jointMeans[c, d] · W[d, c]
    intercept = joint_label_mean - jnp.einsum(
        "bcd,bdc->c", joint_means_all, models
    )
    return models, intercept


_BWLS_STATICS = (
    "num_iter", "n_max", "chunk", "num_classes", "widths", "mesh", "specs",
)


@functools.lru_cache(maxsize=None)
def _fused_bwls_fit_variant(donate_argnums: tuple = ()):
    """jit of the fused BWLS solve with a chosen donation set.  ``(0, 1)``
    donates the sorted design matrix and sorted labels — both are copies
    the fit itself created in ``sort_pad``, never caller-visible arrays, so
    the single-device fit donates them unconditionally and XLA reuses their
    HBM for the residual/block temps."""
    return jax.jit(
        _fused_bwls_impl,
        static_argnames=_BWLS_STATICS,
        donate_argnums=donate_argnums,
    )


#: Historical non-donating entry point (the mesh path and AOT benches).
_fused_bwls_fit = _fused_bwls_fit_variant(())


def _execute_fused_bwls(plan, args, statics):
    """Dispatch the fused BWLS program: the planned AOT executable when
    admission ran, else the donating jitted variant (also the resilient
    fallback when the sorted inputs are sharded — a single-device plan
    baked single-device placements).  Module level so benches capture the
    exact solve arguments here and the fault harness injects
    RESOURCE_EXHAUSTED to exercise the ladder step-down."""
    from .block import _single_device_arrays

    if (
        plan is not None
        and plan.compiled is not None
        and _single_device_arrays(*args)
    ):
        return plan.compiled(*args)
    return _fused_bwls_fit_variant((0, 1))(*args, *statics)


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_cols(out, g, c0):
    """One column-chunk landing in the preallocated gather output.  The
    donated ``out`` buffer is updated in place (TPU aliases it), so the
    chunked sort_pad gather peaks at source + output + ONE chunk — the
    round-5 form accumulated every chunk in a list and concatenated,
    transiently holding ~3x the design matrix (ADVICE r5)."""
    return jax.lax.dynamic_update_slice(out, g, (jnp.int32(0), c0))


@functools.partial(jax.jit, static_argnames=("num_classes",))
def _bwls_block_stats(xb, seg_ids, counts_f, n, w, pad_diag_i, num_classes: int):
    """Population/per-class statistics of ONE block — the per-block body of
    the fused program's stats scan, exposed as its own program for the
    stepwise/host-staged ladder tiers (identical math, one dispatch per
    block)."""
    pop_mean = jnp.sum(xb, axis=0) / n
    pop_cov = xb.T @ xb / n - jnp.outer(pop_mean, pop_mean) + jnp.diag(pad_diag_i)
    class_means = _class_sums(xb, seg_ids, num_classes) / counts_f[:, None]
    return pop_cov, pop_mean, w * class_means + (1.0 - w) * pop_mean


@jax.jit
def _bwls_block_xtr(xb, res, n):
    return xb.T @ res / n


@jax.jit
def _bwls_block_apply(xb, res, model, dw):
    return model + dw, res - xb @ dw


def _stepwise_bwls_fit(
    get_block, labels_sorted, valid, seg_ids, starts, counts, counts_f,
    joint_label_mean, nvalid, lam, w,
    num_iter: int, n_max: int, chunk: int, num_classes: int, widths,
    class_solves=None,
):
    """The BWLS solve driven from the host one block at a time — the
    stepwise/host-staged rungs of the degradation ladder.  ``get_block(i)``
    returns block i as a device [P, bs] array: a device-side slice of the
    sorted design matrix (stepwise — bounds per-dispatch temps) or an H2D
    upload from a host-resident sorted matrix (host-staged — the design
    matrix never fully occupies HBM; peak device residency is one block +
    the residual + the per-block statistics caches).  Statistics are
    computed once and cached across passes, and the update order matches
    ``_fused_bwls_fit`` exactly, so results are numerically identical.

    ``class_solves``: the preflight's AOT-compiled class-solve executable
    (``plan.compiled`` — statics baked, same avals), so the degraded tier
    executes the very program admission planned instead of recompiling
    ``_class_solves`` at first jit dispatch; ``None`` → the jitted entry.
    """
    bs = max(widths)
    nb = len(widths)
    dtype = labels_sorted.dtype
    n = jnp.asarray(nvalid, dtype)
    w_arr = jnp.asarray(w, dtype)
    lam_arr = jnp.asarray(lam, dtype)

    def jit_class_solves(*a):
        return _class_solves(*a, n_max, chunk, None)

    solves = class_solves if class_solves is not None else jit_class_solves

    res = (labels_sorted - joint_label_mean) * valid
    rmean = _residual_class_means(res, seg_ids, counts_f, num_classes)
    pad_diag = np.stack(
        [(np.arange(bs) >= wd).astype(np.float64) for wd in widths]
    )

    stats = []
    for i in range(nb):
        xb = get_block(i)
        stats.append(
            _bwls_block_stats(
                xb, seg_ids, counts_f, n, w_arr,
                jnp.asarray(pad_diag[i], dtype), num_classes,
            )
        )
        del xb

    models = [jnp.zeros((bs, num_classes), dtype) for _ in range(nb)]
    for _ in range(num_iter):
        for i in range(nb):
            xb = get_block(i)
            pop_cov, pop_mean, jm = stats[i]
            pop_xtr = _bwls_block_xtr(xb, res, n)
            dw = solves(
                xb, res, starts, counts, pop_cov, pop_mean, pop_xtr,
                jm, rmean, models[i], lam_arr, w_arr,
            )
            models[i], res = _bwls_block_apply(xb, res, models[i], dw)
            rmean = _residual_class_means(res, seg_ids, counts_f, num_classes)
            del xb

    joint_means_all = jnp.stack([s[2] for s in stats])
    models_st = jnp.stack(models)
    intercept = joint_label_mean - jnp.einsum(
        "bcd,bdc->c", joint_means_all, models_st
    )
    return models_st, intercept


@functools.partial(jax.jit, static_argnames=("num_classes",))
def _class_sums(x_pad, seg_ids, num_classes: int):
    """Per-class row sums of a (sorted, padded) block via segment sum.

    ``seg_ids`` maps each row to its class, with pad rows mapped to segment
    ``num_classes`` which is dropped — a segment sum replaces round 2's
    [C, N] one-hot matmul (O(N) index memory instead of O(N·C))."""
    sums = jax.ops.segment_sum(
        x_pad, seg_ids, num_segments=num_classes + 1, indices_are_sorted=True
    )
    return sums[:num_classes]


@functools.partial(jax.jit, static_argnames=("num_classes",))
def _residual_class_means(res_pad, seg_ids, counts, num_classes: int):
    """Per-class column means of the residual, averaged over classes with
    equal class weight (reference :165-167, :283-287)."""
    means = _class_sums(res_pad, seg_ids, num_classes) / counts[:, None]
    return jnp.mean(means, axis=0)


class BlockWeightedLeastSquaresEstimator(LabelEstimator):
    """Weighted BCD least squares (reference :35-88).

    ``mixture_weight`` ∈ (0, 1): how much each class's own examples are
    up-weighted relative to the population (per-class effective weights are
    ``(1-w)/n + w/n_c`` on the true-class column, ``(1-w)/n`` elsewhere).
    """

    def __init__(
        self,
        block_size: int,
        num_iter: int,
        lam: float,
        mixture_weight: float,
        class_chunk: int = 16,
        mesh=None,
    ):
        self.block_size = block_size
        self.num_iter = num_iter
        self.lam = lam
        self.mixture_weight = mixture_weight
        self.class_chunk = class_chunk
        self.mesh = mesh
        #: core.memory.FitReport of the most recent fit (tier plans, chosen
        #: tier, denials, OOM retries) — the bench emits it verbatim.
        self.last_fit_report = None

    def fit(
        self,
        features,
        labels,
        num_features: int | None = None,
        nvalid: int | None = None,
        donate: bool | None = None,
        plan=None,
    ) -> BlockLinearMapper:
        """``features``/``labels`` may be host arrays OR device-resident
        (row-sharded) ``jax.Array``s — the full design matrix is never
        materialized on host.  ``nvalid``: true global row count when the
        inputs carry zero pad rows from ``padded_shard_rows``; pad rows are
        excluded from the class grouping.

        Memory resilience: the solve runs a degradation ladder.  Without a
        mesh: fused one-program → stepwise per-block → host-staged block
        streaming, each tier preflighted against the HBM budget
        (core.memory; ``KEYSTONE_HBM_BUDGET`` overrides) and a runtime
        RESOURCE_EXHAUSTED steps down one tier.  With a mesh, mesh tiers
        sit above those — full ``(data, model)`` mesh → model-axis-
        collapsed mesh → the single-device ladder — each admitted PER CHIP
        against the minimum free HBM across the mesh's devices, with
        ``last_fit_report.mesh_shape`` recording which mesh actually ran.  The fused program
        always donates the SORTED design-matrix/label copies (they are
        fit-private).  ``donate=True`` additionally frees the CALLER's
        device-resident inputs as soon as their sorted copies exist —
        halving the peak across the class-sort gather — at the price that
        an exec-level OOM can no longer rebuild them for the step-down.
        The decision trail is ``self.last_fit_report``.

        Placement search (core.autoshard, on by default): the ladders are
        the HAND enumeration — the fit runs the cost-model RANKED candidate
        list (every (data, model) mesh factorization x strategy), pruned by
        the zero-cost batch preflight, hand order as the untrained
        tie-break, floor pinned last, runtime OOM stepping down the ranked
        list (counted ``autoshard_stepdown``).  ``plan``: ``None`` honors
        ``KEYSTONE_AUTOSHARD``, ``False`` hand ladder, ``True`` forces the
        search, a ``PlacementPlan``/name list replays a ranking; the table
        lands in ``last_fit_report.placement``."""
        mesh = self.mesh if self.mesh is not None else current_mesh()
        n = nvalid if nvalid is not None else int(np.shape(labels)[0])
        n_classes = int(np.shape(labels)[1])
        # Class of each valid row: device argmax for device labels, so only
        # the [n] int vector crosses to host (round 2 pulled the whole
        # design matrix); plain numpy argmax for host labels.
        with trace.span("bwls.class_sort", cat="solve", n=n, classes=n_classes):
            if isinstance(labels, jax.Array):
                class_idx = np.asarray(jnp.argmax(labels[:n], axis=1))
            else:
                class_idx = np.argmax(np.asarray(labels)[:n], axis=1)
            counts_np = np.bincount(class_idx, minlength=n_classes)
            if np.any(counts_np == 0):
                missing = np.nonzero(counts_np == 0)[0]
                raise ValueError(
                    f"classes with no examples: {missing.tolist()}"
                )

            # Class grouping (the reference's HashPartitioner shuffle +
            # per-partition id sort, :324-361): a host argsort of the [n]
            # class vector gives the permutation; rows move device-side via
            # one regroup of the whole design matrix below.
            order = np.argsort(class_idx, kind="stable")
        starts_np = np.concatenate([[0], np.cumsum(counts_np)[:-1]])
        n_max = int(counts_np.max())

        x, widths = _blocked_design_matrix(
            features, self.block_size, num_features
        )
        # Conditioning monitor (ISSUE 15): per-block κ estimates on the
        # blocked design matrix this fit already formed (row-capped probe;
        # one flag check when the observatory is off).
        cond_rows = (
            knum.design_conditioning(
                x, widths, float(self.lam), label="bwls_fit"
            )
            if knum.active()
            else None
        )
        dtype = jnp.asarray(x[:1, :1]).dtype
        w = self.mixture_weight

        def prep(m, labels_src):
            """Mesh-dependent solve context for one ladder tier.

            Padded row layout: sorted valid rows, then a zero tail of >=
            n_max rows (so every dynamic_slice in the class sweep stays in
            bounds).  The zero tail contributes nothing to gemms/sums, so
            population statistics use xb_pad directly with the true count
            n.  With a mesh the tail additionally rounds the row count up
            to a data-axis multiple and the padded blocks are row-sharded:
            population gram/XᵀR gemms lower to local gram + ICI
            all-reduce.  Every quantity that depends on the mesh's axis
            sizes (p_tot, the gather index, seg ids, the class chunk, the
            sort/regroup closures) lives in the returned context, so each
            rung of the mesh degradation ladder rebuilds its own layout.
            """
            pad_total = n_max
            row_shard = None
            if m is not None:
                d_size = m.shape[DATA_AXIS]
                pad_total += (-(n + n_max)) % d_size
                row_shard = NamedSharding(m, P(DATA_AXIS, None))
            p_tot = n + pad_total

            # gather index: order for valid rows, then an out-of-range
            # index so ``mode="fill"`` writes exact zero rows for the tail
            # — the sort and the padding are a single device gather, no
            # host round-trip.
            gather_np = np.concatenate(
                [order, np.full(pad_total, n, dtype=order.dtype)]
            )
            gather_idx = jnp.asarray(gather_np)
            valid = jnp.asarray((gather_np < n).astype(np.float32))[:, None]

            regroup_plans: dict[int, _RegroupPlan] = {}

            def sort_pad(x):
                """Sorted, zero-tail-padded, (re-)sharded copy of ``x``.

                Host arrays are permuted host-side (no device gather at
                all).  Device-resident arrays under a mesh regroup via the
                traffic-optimal all_to_all plan (each row crosses the ICI
                once — see _RegroupPlan for the D-times-less-traffic
                model).  The fallback for shapes the plan cannot take (row
                count not a data-axis multiple) is a feature-column-chunked
                gather: a replicated-index gather over a row-sharded
                operand makes GSPMD all-gather the operand, so chunking
                bounds the transient unsharded slab to [p_tot, chunk].  The
                tail is exact zero in every path (``mode="fill"`` covers
                sources with exactly n rows; sources carrying their own pad
                rows at >= n need the mask).
                """
                if not isinstance(x, jax.Array):
                    xh = np.asarray(x)
                    out_h = np.zeros((p_tot,) + xh.shape[1:], xh.dtype)
                    out_h[:n] = xh[order]
                    out = jnp.asarray(out_h)
                    if row_shard is not None:
                        out = jax.device_put(out, row_shard)
                    return out

                if m is not None and x.shape[0] % m.shape[DATA_AXIS] == 0:
                    n_src = x.shape[0]
                    if n_src not in regroup_plans:
                        regroup_plans[n_src] = _RegroupPlan(
                            order, n_src, p_tot, m.shape[DATA_AXIS]
                        )
                    plan = regroup_plans[n_src]
                    if plan.usable:  # else: skew guard — fallback below
                        return plan.apply(m, jax.device_put(x, row_shard))
                    # A survivable degradation, counted so operators (and
                    # the multichip dryrun) can see which regroup path ran.
                    counters.record(
                        "bwls_regroup_skew_fallback",
                        f"d*m_pad {plan.d * plan.m_pad} > 2*rows_out "
                        f"{2 * plan.rows_out}: bucket padding beyond 2x "
                        "optimal — taking the chunked-gather fallback",
                    )

                chunk_cols = max(1, _GATHER_COL_CHUNK // max(1, x.itemsize))
                if x.shape[1] <= chunk_cols:
                    g = jnp.take(
                        x, gather_idx, axis=0, mode="fill", fill_value=0
                    )
                    g = g * valid.astype(x.dtype)
                    return (
                        g if row_shard is None else jax.device_put(g, row_shard)
                    )
                # Chunks land in a PREALLOCATED output via a donating
                # dynamic-update-slice, so peak HBM is source + output +
                # one chunk (~2x the design matrix).  The round-5 form
                # accumulated all chunks in a list and concatenated —
                # source + chunks + concat output, ~3x transient (ADVICE
                # r5 medium).
                out = jnp.zeros((p_tot, x.shape[1]), x.dtype)
                if row_shard is not None:
                    out = jax.device_put(out, row_shard)
                for c0 in range(0, x.shape[1], chunk_cols):
                    sl = jax.lax.slice_in_dim(
                        x, c0, min(c0 + chunk_cols, x.shape[1]), axis=1
                    )
                    g = jnp.take(
                        sl, gather_idx, axis=0, mode="fill", fill_value=0
                    )
                    g = g * valid.astype(x.dtype)
                    if row_shard is not None:
                        # Reshard each slab as it lands so at most one
                        # unsharded chunk is transient at a time.
                        g = jax.device_put(g, row_shard)
                    out = _scatter_cols(out, g, jnp.int32(c0))
                return out

            counts = jnp.asarray(counts_np)
            starts = jnp.asarray(starts_np)
            # Segment ids: class of each sorted row, pad rows -> segment C.
            seg_np = np.full(p_tot, n_classes, np.int32)
            seg_np[:n] = class_idx[order]
            seg_ids = jnp.asarray(seg_np)
            counts_f = counts.astype(dtype)

            # jointLabelMean[c] = 2w + 2(1-w)·n_c/n − 1 (reference :147-149)
            joint_label_mean = jnp.asarray(
                2.0 * w + 2.0 * (1.0 - w) * counts_np / n - 1.0, dtype
            )
            valid_d = valid.astype(dtype)

            chunk = max(1, min(self.class_chunk, n_classes))
            if m is not None:
                # Round the chunk up to a model-axis multiple so the
                # batched class solves always shard over the model axis
                # (pad classes in a partial chunk are repeats of class 0,
                # discarded afterwards).
                m_size = m.shape[MODEL_AXIS]
                chunk = -(-chunk // m_size) * m_size

            def sort_labels():
                if isinstance(labels_src, jax.Array):
                    return sort_pad(labels_src.astype(dtype))
                return sort_pad(np.asarray(labels_src, dtype))

            return _SolveCtx(
                mesh=m,
                p_tot=p_tot,
                chunk=chunk,
                sort_pad=sort_pad,
                sort_labels=sort_labels,
                valid_d=valid_d,
                seg_ids=seg_ids,
                starts=starts,
                counts=counts,
                counts_f=counts_f,
                joint_label_mean=joint_label_mean,
            )

        if mesh is not None:
            # Multi-chip path: the mesh degradation ladder — full
            # (data, model) mesh with per-chip admission, then the
            # model-axis-collapsed mesh, then the single-device ladder —
            # searched/ranked by core.autoshard unless plan=False.
            models_st, b = self._fit_mesh_ladder(
                features, x, labels, prep, mesh, order, n, n_max,
                n_classes, widths, dtype, donate, plan_arg=plan,
            )
        else:
            models_st, b = self._fit_ladder(
                features, x, labels, prep(None, labels), order, n, n_max,
                n_classes, widths, dtype, donate, plan_arg=plan,
            )
        if cond_rows and self.last_fit_report is not None:
            self.last_fit_report.conditioning = cond_rows
        model_list = [models_st[i, :wd] for i, wd in enumerate(widths)]
        return BlockLinearMapper(model_list, self.block_size, b)

    def _fit_mesh_ladder(
        self, features, x, labels, prep, mesh, order, n, n_max, n_classes,
        widths, dtype, donate, plan_arg=None,
    ):
        """Distributed BWLS through the MESH degradation ladder: full
        ``(data, model)`` mesh → model-axis-collapsed mesh (row-sharded
        operands halve per chip, model state replicates) → the
        single-device ladder on host-pulled inputs.  Each mesh tier builds
        its own sort/pad layout (``prep(m, ...)``), is admitted PER CHIP
        against the minimum free HBM across the mesh's devices, and a
        runtime ``RESOURCE_EXHAUSTED`` from any chip steps down one tier.
        ``report.mesh_shape`` records which mesh actually ran."""
        bs, nb = max(widths), len(widths)
        d_tot = nb * bs
        it = np.dtype(dtype).itemsize
        xdt = jax.dtypes.canonicalize_dtype(x.dtype)
        report = kmem.FitReport(label="bwls_fit")
        self.last_fit_report = report

        itx = np.dtype(xdt).itemsize

        def mesh_tier(m, prior_rank, hand, specs=None):
            """One fused-mesh BWLS candidate: ``specs=None`` is the
            default layout (the PR 9 hand rung, bit-for-bit); a spec
            assignment EXECUTES that per-operand layout — e.g.
            model-axis-sharded label columns for wide-class solves — with
            the hints charging the chosen specs' actual per-chip bytes."""
            name = f"fused[mesh {mesh_desc(m)}]"
            if specs:
                name = f"fused[mesh {mesh_desc(m)}|{autoshard.spec_tag(specs)}]"
            d_sz, m_sz = m.shape[DATA_AXIS], m.shape[MODEL_AXIS]
            mdict = dict(m.shape)
            lspec = (specs or {}).get("labels", "data@dim0")
            # The tier's padded layout, computed WITHOUT building the ctx
            # (the search scores every enumerated mesh shape; the O(p_tot)
            # gather/seg/mask buffers stay lazy below).
            p_tot_a = n + n_max + ((-(n + n_max)) % d_sz)
            chunk_a = max(1, min(self.class_chunk, n_classes))
            chunk_a = -(-chunk_a // m_sz) * m_sz
            # Residual carries inherit the labels layout (default: row
            # sharded over the data axis).
            res_b = autoshard.spec_chip_bytes(
                (p_tot_a, n_classes), dtype, lspec, mdict
            )
            # Analytic per-chip transient floor (CPU backends report
            # temp 0): two residual carries, one row-sharded block slice,
            # the model-axis-sharded class-solve slab, the replicated
            # stats/models stacks.  Also the cost model's temp term and
            # the zero-cost prune's figure — one formula.
            floor = 2 * res_b + it * (
                p_tot_a * bs // d_sz
                + chunk_a * n_max * bs // m_sz
                + nb * (bs * bs + bs + n_classes * bs)
                + nb * bs * n_classes
            )
            if specs:
                # A spec candidate charges the layout it will execute.
                arg_bytes = (
                    autoshard.spec_chip_bytes(
                        (p_tot_a, d_tot), xdt,
                        (specs or {}).get("x", "data@dim0"), mdict,
                    )
                    + autoshard.spec_chip_bytes(
                        (p_tot_a, n_classes), dtype, lspec, mdict
                    )
                    + it * p_tot_a  # replicated valid/seg vectors
                )
            else:
                # Hand accounting: per-operand bytes through the spec
                # enumeration's minimum (the best sharding this mesh
                # shape can achieve) — a lower bound of any layout the
                # compiled admission will charge; the valid/seg vectors
                # the program truly replicates are charged replicated.
                arg_bytes = sum(
                    autoshard.best_spec(a, mdict)["per_chip_bytes"]
                    for a in (
                        jax.ShapeDtypeStruct((p_tot_a, d_tot), xdt),
                        jax.ShapeDtypeStruct((p_tot_a, n_classes), dtype),
                    )
                ) + it * p_tot_a
            hints = {
                "arg_bytes": arg_bytes,
                "temp_bytes": floor,
                "out_bytes": it * (nb * bs * n_classes + n_classes),
                "flops": (
                    self.num_iter * nb * (
                        2.0 * p_tot_a * bs * (bs + 2 * n_classes)
                        + n_classes * n_max * bs * (bs + 2)
                    )
                ) / (d_sz * m_sz),
                "dispatches": 1,
                "hbm_passes": self.num_iter + 1,
                "coll_bytes": (
                    it * self.num_iter * nb
                    * (bs * bs + bs * n_classes)
                    if d_sz > 1 else 0
                ),
            }
            spec_t = tuple(sorted(specs.items())) if specs else None
            # Lazy, memoized: a tier's O(p_tot) gather/seg/mask buffers are
            # only built once the ladder actually CONSIDERS the tier (the
            # common admitted-first-tier fit never pays for the rungs
            # below it — same laziness run_ladder gives the plans).
            ctx_box: list = []

            def ctx():
                if not ctx_box:
                    ctx_box.append(prep(m, labels))
                return ctx_box[0]

            def plan():
                ctx_ = ctx()
                budget, _worst = kmem.min_chip_budget(m)
                sds = jax.ShapeDtypeStruct
                i32 = jnp.int32
                row = NamedSharding(m, P(DATA_AXIS, None))
                x_s = sds((ctx_.p_tot, d_tot), xdt, sharding=row)
                y_s = sds(
                    (ctx_.p_tot, n_classes), dtype,
                    sharding=(
                        row if lspec == "data@dim0"
                        else autoshard.spec_sharding(lspec, m, 2)
                    ),
                )
                # valid/seg/stat vectors are replicated — charged whole.
                v_s = sds((ctx_.p_tot, 1), dtype)
                seg_s = sds((ctx_.p_tot,), i32)
                c_i32, c_f = sds((n_classes,), i32), sds((n_classes,), dtype)
                sc_s, nv_s = sds((), dtype), sds((), i32)
                return kmem.plan_program(
                    _fused_bwls_fit_variant((0, 1)),
                    x_s, y_s, v_s, seg_s, c_i32, c_i32, c_f, c_f, nv_s,
                    sc_s, sc_s, self.num_iter, n_max, ctx_.chunk, n_classes,
                    widths, m, spec_t,
                    label=f"bwls_{name}", budget=budget,
                    min_temp_bytes=floor, mesh=m,
                )

            def run(plan):
                ctx_ = ctx()
                report.mesh_shape = dict(m.shape)
                ls = ctx_.sort_labels()
                if lspec != "data@dim0":
                    # The searched labels layout, placed for real — the
                    # program's constraint reads the same spec string.
                    ls = jax.device_put(
                        ls, autoshard.spec_sharding(lspec, m, 2)
                    )
                args = (
                    ctx_.sort_pad(x), ls, ctx_.valid_d,
                    ctx_.seg_ids, ctx_.starts, ctx_.counts, ctx_.counts_f,
                    ctx_.joint_label_mean, jnp.asarray(n),
                    jnp.asarray(self.lam, dtype),
                    jnp.asarray(self.mixture_weight, dtype),
                )
                statics = (
                    self.num_iter, n_max, ctx_.chunk, n_classes, widths, m,
                    spec_t,
                )
                # plan=None: the jitted sharded program, not the AOT plan
                # executable (committed-sharding pitfalls — see
                # block._execute_fused_bcd_mesh); same injection point.
                return _execute_fused_bwls(None, args, statics)

            return autoshard.Candidate(
                name, "fused_mesh", plan, run, hints=hints,
                mesh_axes=mdict, prior_rank=prior_rank, hand=hand,
                specs=dict(specs) if specs else None,
            )

        def plan_single():
            return kmem.MemoryPlan(
                label="single_device",
                admitted=True,
                reason=(
                    "mesh ladder floor: single-device degradation ladder "
                    "(its own per-tier admission runs inside)"
                ),
            )

        inner_chosen = []

        def run_single(_plan):
            report.mesh_shape = None
            x_h = (
                np.asarray(jax.device_get(x)) if isinstance(x, jax.Array) else x
            )
            y_h = (
                np.asarray(jax.device_get(labels))
                if isinstance(labels, jax.Array)
                else labels
            )
            out = self._fit_ladder(
                x_h, x_h, y_h, prep(None, y_h), order, n, n_max,
                n_classes, widths, dtype, None,
                # The mesh-level search already ranked this floor; the
                # nested single-device ladder walks its hand order.
                plan_arg=False,
                report=report,
            )
            inner_chosen.append(report.chosen)
            return out

        cands = [mesh_tier(mesh, 0, True)]
        rm = reduced_mesh(mesh)
        if rm is not None:
            cands.append(mesh_tier(rm, 1, True))
        # Searched candidate set: the remaining (data, model)
        # factorizations of the same devices, then the per-operand SPEC
        # assignments of every mesh shape (KEYSTONE_AUTOSHARD_SPECS) —
        # model-axis-sharded label columns for wide-class solves, or fully
        # replicated labels — ranked after the hand rungs on an untrained
        # prior.  Only enumerated when the search will run — a hand-ladder
        # walk would discard them, and each costs a jax Mesh construction.
        if autoshard.will_search(plan_arg):
            hand_shapes = {
                mesh_desc(c_mesh) for c_mesh in (mesh, rm) if c_mesh
            }
            searched_meshes = [mesh] + ([rm] if rm is not None else [])
            for extra in enumerate_meshes(list(mesh.devices.flat)):
                if mesh_desc(extra) not in hand_shapes:
                    searched_meshes.append(extra)
                    cands.append(mesh_tier(extra, len(cands), False))
            if autoshard.specs_enabled():
                for sm in searched_meshes:
                    for sp in _bwls_spec_variants(sm, n_classes):
                        cands.append(
                            mesh_tier(sm, len(cands), False, specs=sp)
                        )
        p_tot_s = n + n_max
        cands.append(autoshard.Candidate(
            "single_device", "single_device", plan_single, run_single,
            hints={
                "arg_bytes": itx * p_tot_s * d_tot + it * p_tot_s * n_classes,
                "h2d_bytes": itx * p_tot_s * d_tot + it * p_tot_s * n_classes,
                "flops": self.num_iter * nb * (
                    2.0 * p_tot_s * bs * (bs + 2 * n_classes)
                    + n_classes * n_max * bs * (bs + 2)
                ),
                "dispatches": 3,
            },
            prior_rank=len(cands), floor=True,
        ))
        # Profiler phase (core.profiler): the watermark sampler attributes
        # this solve's HBM high-water mark to "bwls_fit".  No-op when off.
        with kprof.phase("bwls_fit"):
            out = autoshard.run_search(
                "bwls_fit", cands, report,
                fingerprint=autoshard.fingerprint(
                    "bwls_fit", n, n_classes, n_max, widths, self.num_iter,
                    self.class_chunk, str(xdt), str(dtype), dict(mesh.shape),
                    autoshard.device_fingerprint(),
                ),
                plan=plan_arg,
            )
        if inner_chosen and report.chosen == "single_device":
            report.chosen = f"single_device/{inner_chosen[0]}"
        return out

    def _fit_ladder(
        self, features, x, labels, ctx, order, n, n_max, n_classes, widths,
        dtype, donate, plan_arg=None, report=None,
    ):
        """Single-device BWLS through the degradation ladder (preflight
        admission per tier; runtime RESOURCE_EXHAUSTED steps down one tier).

        The SORTED design matrix / labels are fit-private copies, so the
        fused program always donates them; ``donate=True`` additionally
        frees the caller's device inputs once sorted copies exist."""
        sort_pad, sort_labels = ctx.sort_pad, ctx.sort_labels
        valid_d, seg_ids = ctx.valid_d, ctx.seg_ids
        starts, counts, counts_f = ctx.starts, ctx.counts, ctx.counts_f
        joint_label_mean = ctx.joint_label_mean
        chunk, p_tot = ctx.chunk, ctx.p_tot
        bs, nb = max(widths), len(widths)
        d_tot = nb * bs
        it = np.dtype(dtype).itemsize
        xdt = jax.dtypes.canonicalize_dtype(x.dtype)
        budget = kmem.hbm_budget()
        donate_input = bool(donate)

        lam_arr = jnp.asarray(self.lam, dtype)
        w_arr = jnp.asarray(self.mixture_weight, dtype)
        nv_arr = jnp.asarray(n, jnp.int32)
        statics = (self.num_iter, n_max, chunk, n_classes, widths, None, None)

        sds = jax.ShapeDtypeStruct
        i32 = jnp.int32
        x_s = sds((p_tot, d_tot), xdt)
        y_s = sds((p_tot, n_classes), dtype)
        v_s = sds((p_tot, 1), dtype)
        seg_s = sds((p_tot,), i32)
        c_i32 = sds((n_classes,), i32)
        c_f = sds((n_classes,), dtype)
        sc_s, nv_s = sds((), dtype), sds((), i32)
        xb_s = sds((p_tot, bs), xdt)
        cov_s, mean_s = sds((bs, bs), dtype), sds((bs,), dtype)
        xtr_s, jm_s = sds((bs, n_classes), dtype), sds((n_classes, bs), dtype)
        m_s = sds((bs, n_classes), dtype)

        # Resident-set accounting the per-program argument lists do not
        # see: per-block statistics caches, the models stack, the sorted
        # labels, and (unless donated) the caller's device inputs.
        stats_bytes = it * nb * (bs * bs + bs + n_classes * bs)
        models_bytes = it * nb * bs * n_classes
        labels_bytes = it * p_tot * n_classes
        # Device-resident caller inputs stay alive at least through the
        # class-sort gather (source + sorted output coexist) even under
        # donate=True, so they count against every tier's charged total —
        # including host-staged, which pulls the source to RAM but cannot
        # free a non-donated caller buffer.  When the budget is LIVE free
        # bytes they are credited back (free already excludes them).
        src_bytes = (
            (x.nbytes if isinstance(x, jax.Array) else 0)
            + (labels.nbytes if isinstance(labels, jax.Array) else 0)
        )
        # Analytic transient floor of the fused program (CPU backends
        # report temp 0): two residual carries, one block slice, the stats
        # stacks, the models carry, and the per-chunk class-solve slab.
        fused_floor = it * (
            2 * p_tot * n_classes + p_tot * bs + chunk * n_max * bs
        ) + stats_bytes + models_bytes
        slab_floor = it * chunk * n_max * bs

        def plan_fused():
            return kmem.plan_program(
                _fused_bwls_fit_variant((0, 1)),
                x_s, y_s, v_s, seg_s, c_i32, c_i32, c_f, c_f, nv_s, sc_s,
                sc_s, *statics,
                label="bwls_fused", budget=budget,
                min_temp_bytes=fused_floor, extra_bytes=src_bytes,
                resident_bytes=src_bytes,
            )

        def plan_stepwise():
            return kmem.plan_program(
                _class_solves, xb_s, y_s, c_i32, c_i32, cov_s, mean_s,
                xtr_s, jm_s, c_f, m_s, sc_s, sc_s, n_max, chunk, None,
                label="bwls_stepwise", budget=budget,
                min_temp_bytes=slab_floor,
                extra_bytes=(
                    it * p_tot * d_tot  # the sorted design matrix
                    + labels_bytes + stats_bytes + models_bytes + src_bytes
                ),
                resident_bytes=src_bytes,
            )

        def plan_host():
            return kmem.plan_program(
                _class_solves, xb_s, y_s, c_i32, c_i32, cov_s, mean_s,
                xtr_s, jm_s, c_f, m_s, sc_s, sc_s, n_max, chunk, None,
                label="bwls_host_staged", budget=budget,
                min_temp_bytes=slab_floor,
                extra_bytes=(
                    labels_bytes + stats_bytes + models_bytes + src_bytes
                ),
                resident_bytes=src_bytes,
            )

        def src_x():
            if isinstance(x, jax.Array) and x.is_deleted():
                raise kmem.LadderSourceLost(
                    "BWLS design matrix was donated (donate=True) and is "
                    "gone — cannot step the ladder down; refit with "
                    "donate=False to keep OOM recovery possible"
                )
            return x

        def free_sources():
            if donate_input:
                kmem.free_buffers(
                    x if isinstance(x, jax.Array) else None,
                    labels if isinstance(labels, jax.Array) else None,
                )

        def sorted_device_inputs():
            xs = sort_pad(src_x())
            ls = sort_labels()
            free_sources()
            return xs, ls

        def run_fused(plan):
            xs, ls = sorted_device_inputs()
            args = (xs, ls, valid_d, seg_ids, starts, counts, counts_f,
                    joint_label_mean, nv_arr, lam_arr, w_arr)
            del xs, ls  # the args tuple holds the only refs; donation eats them
            return _execute_fused_bwls(plan, args, statics)

        def run_stepwise(plan):
            from .block import _single_device_arrays

            xs, ls = sorted_device_inputs()
            reusable = plan is not None and _single_device_arrays(xs, ls)

            def get_block(i):
                return jax.lax.slice_in_dim(xs, i * bs, (i + 1) * bs, axis=1)

            return _stepwise_bwls_fit(
                get_block, ls, valid_d, seg_ids, starts, counts, counts_f,
                joint_label_mean, n, self.lam, self.mixture_weight,
                self.num_iter, n_max, chunk, n_classes, widths,
                # Reuse the preflight's AOT executable: the class-solve
                # program compiled exactly once, at admission.  (Sharded
                # inputs fall back to the jitted entry.)
                class_solves=plan.compiled if reusable else None,
            )

        def run_host(plan):
            xh = src_x()
            x_np = (
                np.asarray(jax.device_get(xh))
                if isinstance(xh, jax.Array) else np.asarray(xh)
            )
            if isinstance(xh, jax.Array) and _design_matrix_owned(xh, features):
                # Fit-owned device copy (concat/pad product): once pulled to
                # host it must not keep the full matrix resident in HBM —
                # that residency is exactly what this tier exists to avoid.
                kmem.free_buffers(xh)
            ls = sort_labels()
            free_sources()
            # Host-side class sort + zero tail: the device never holds more
            # than one [P, bs] block of the design matrix.
            x_sorted_h = np.zeros((p_tot, x_np.shape[1]), x_np.dtype)
            x_sorted_h[:n] = x_np[order]
            del x_np

            def get_block(i):
                return jnp.asarray(
                    np.ascontiguousarray(x_sorted_h[:, i * bs : (i + 1) * bs])
                )

            from .block import _single_device_arrays

            return _stepwise_bwls_fit(
                get_block, ls, valid_d, seg_ids, starts, counts, counts_f,
                joint_label_mean, n, self.lam, self.mixture_weight,
                self.num_iter, n_max, chunk, n_classes, widths,
                class_solves=(
                    plan.compiled
                    if plan is not None and _single_device_arrays(ls)
                    else None
                ),
            )

        if report is None:
            report = kmem.FitReport(label="bwls_fit", budget_bytes=budget)
            self.last_fit_report = report
        itx = np.dtype(xdt).itemsize
        sorted_x_bytes = itx * p_tot * d_tot
        sorted_y_bytes = it * p_tot * n_classes
        flops = self.num_iter * nb * (
            2.0 * p_tot * bs * (bs + 2 * n_classes)
            + n_classes * n_max * bs * (bs + 2)
        )
        per_block_dispatches = nb * (3 * self.num_iter + 1) + 2
        cands = [
            autoshard.Candidate(
                "fused", "fused", plan_fused, run_fused,
                hints={
                    "arg_bytes": (
                        sorted_x_bytes + sorted_y_bytes + it * p_tot
                    ),
                    # The fused program always donates the fit-private
                    # sorted copies — credited out of the prune's lower
                    # bound exactly as the compiled admission's alias is.
                    "alias_bytes": sorted_x_bytes + sorted_y_bytes,
                    "temp_bytes": fused_floor,
                    "out_bytes": it * (nb * bs * n_classes + n_classes),
                    "extra_bytes": src_bytes,
                    "resident_bytes": src_bytes,
                    "flops": flops,
                    "dispatches": 1,
                    "hbm_passes": self.num_iter + 1,
                },
                prior_rank=0,
            ),
            autoshard.Candidate(
                "stepwise", "stepwise", plan_stepwise, run_stepwise,
                hints={
                    "arg_bytes": itx * p_tot * bs + sorted_y_bytes,
                    "temp_bytes": slab_floor,
                    "out_bytes": it * bs * n_classes,
                    "extra_bytes": (
                        sorted_x_bytes + labels_bytes + stats_bytes
                        + models_bytes + src_bytes
                    ),
                    "resident_bytes": src_bytes,
                    "flops": flops,
                    "dispatches": per_block_dispatches,
                    "hbm_passes": self.num_iter + 1,
                },
                prior_rank=1,
            ),
            autoshard.Candidate(
                "host_staged", "host_staged", plan_host, run_host,
                hints={
                    "arg_bytes": itx * p_tot * bs + sorted_y_bytes,
                    "temp_bytes": slab_floor,
                    "out_bytes": it * bs * n_classes,
                    "extra_bytes": (
                        labels_bytes + stats_bytes + models_bytes + src_bytes
                    ),
                    "resident_bytes": src_bytes,
                    "flops": flops,
                    "dispatches": per_block_dispatches,
                    # Every pass re-streams each sorted block over PCIe.
                    "h2d_bytes": (self.num_iter + 1) * sorted_x_bytes,
                },
                prior_rank=2, floor=True,
            ),
        ]
        with kprof.phase("bwls_fit"):
            return autoshard.run_search(
                "bwls_fit", cands, report,
                fingerprint=autoshard.fingerprint(
                    "bwls_fit", n, n_classes, n_max, widths, self.num_iter,
                    self.class_chunk, str(xdt), str(dtype), None,
                    autoshard.device_fingerprint(),
                ),
                plan=plan_arg,
                budget=budget,
            )
