"""Class-weighted block coordinate descent least squares
(reference src/main/scala/nodes/learning/BlockWeightedLeastSquares.scala:35-362).

The reference re-shuffles the data so each Spark partition holds exactly one
class (HashPartitioner on the argmax class index, :324-361), then per pass per
block: tree-reduces population gram/XᵀR statistics, broadcasts them, runs a
per-class local solve on each partition, collects the per-class weight
columns, and updates a cached residual RDD.

TPU-native re-design:

* the class shuffle becomes a host-side stable sort by class (one-time);
* population statistics are plain gemms over the sorted [N, d] block — under
  ``jit`` with row-sharded inputs XLA lowers them to local gram + ICI
  all-reduce (the treeReduce replacement);
* the per-class solves run inside one jitted ``lax.scan`` over classes — each
  step dynamic-slices the class's rows (padded to the max class size) out of
  the sorted array, builds the mixture-weighted normal equations, and does a
  dense solve; no padded [C, n_max, d] tensor is ever materialized;
* broadcasts/collects disappear (single-controller, arrays stay in HBM).

Semantics (update order, statistics caching across passes, the λ-shifted
solve, and the joint-means intercept) follow the reference exactly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core.pipeline import LabelEstimator
from ..ops.util import VectorSplitter
from .block import BlockLinearMapper


@functools.partial(jax.jit, static_argnames=("n_max",))
def _class_solves(
    xb_pad,  # [N + n_max, d] sorted block features, zero tail
    res_pad,  # [N + n_max, C] sorted residual, zero tail
    starts,  # [C]
    counts,  # [C]
    pop_cov,  # [d, d]
    pop_mean,  # [d]
    pop_xtr,  # [d, C]
    joint_means,  # [C, d]
    residual_mean,  # [C]
    model_block,  # [d, C]
    lam,
    mixture_weight,
    n_max: int,
):
    """One per-class solve sweep (reference :228-263) via sequential
    lax.scan — returns ΔW [d, C]."""
    d = xb_pad.shape[1]
    c_total = starts.shape[0]
    w = mixture_weight
    eye = jnp.eye(d, dtype=xb_pad.dtype)

    def one_class(carry, c):
        start, cnt = starts[c], counts[c]
        xc = jax.lax.dynamic_slice(xb_pad, (start, 0), (n_max, d))
        rc = jax.lax.dynamic_slice(res_pad, (start, 0), (n_max, c_total))
        mask = (jnp.arange(n_max) < cnt).astype(xb_pad.dtype)
        xc = xc * mask[:, None]
        r_c = rc[:, c] * mask  # this class's own residual column (:231)
        n_c = cnt.astype(xb_pad.dtype)

        class_mean = jnp.sum(xc, axis=0) / n_c
        zm = (xc - class_mean) * mask[:, None]
        class_cov = zm.T @ zm / n_c
        class_xtr = xc.T @ r_c / n_c

        mean_diff = class_mean - pop_mean
        joint_xtx = (
            pop_cov * (1.0 - w)
            + class_cov * w
            + jnp.outer(mean_diff, mean_diff) * ((1.0 - w) * w)
        )
        mean_mixture_wt = residual_mean[c] * (1.0 - w) + w * (jnp.sum(r_c) / n_c)
        joint_xtr = (
            pop_xtr[:, c] * (1.0 - w)
            + class_xtr * w
            - joint_means[c] * mean_mixture_wt
        )
        # λ-shifted solve (reference :259-260)
        dw = jnp.linalg.solve(
            joint_xtx + lam * eye, joint_xtr - model_block[:, c] * lam
        )
        return carry, dw

    _, dws = jax.lax.scan(one_class, None, jnp.arange(c_total))
    return dws.T  # [d, C]


@jax.jit
def _residual_class_means(res, class_onehot, counts):
    """Per-class column means of the residual, averaged over classes with
    equal class weight (reference :165-167, :283-287)."""
    sums = class_onehot @ res  # [C, C]
    means = sums / counts[:, None]
    return jnp.mean(means, axis=0)


class BlockWeightedLeastSquaresEstimator(LabelEstimator):
    """Weighted BCD least squares (reference :35-88).

    ``mixture_weight`` ∈ (0, 1): how much each class's own examples are
    up-weighted relative to the population (per-class effective weights are
    ``(1-w)/n + w/n_c`` on the true-class column, ``(1-w)/n`` elsewhere).
    """

    def __init__(
        self,
        block_size: int,
        num_iter: int,
        lam: float,
        mixture_weight: float,
    ):
        self.block_size = block_size
        self.num_iter = num_iter
        self.lam = lam
        self.mixture_weight = mixture_weight

    def fit(self, features, labels, num_features: int | None = None) -> BlockLinearMapper:
        labels_np = np.asarray(labels)
        n, n_classes = labels_np.shape
        class_idx = np.argmax(labels_np, axis=1)
        counts_np = np.bincount(class_idx, minlength=n_classes)
        if np.any(counts_np == 0):
            missing = np.nonzero(counts_np == 0)[0]
            raise ValueError(f"classes with no examples: {missing.tolist()}")

        # Host-side class grouping: stable sort by class (the reference's
        # HashPartitioner shuffle + per-partition id sort, :324-361).
        order = np.argsort(class_idx, kind="stable")
        starts_np = np.concatenate([[0], np.cumsum(counts_np)[:-1]])
        n_max = int(counts_np.max())

        if isinstance(features, (list, tuple)):
            blocks = [jnp.asarray(np.asarray(b)[order]) for b in features]
        else:
            feats_sorted = np.asarray(features)[order]
            blocks = VectorSplitter(self.block_size, num_features)(feats_sorted)
            blocks = [jnp.asarray(b) for b in blocks]

        dtype = blocks[0].dtype
        w = self.mixture_weight
        labels_sorted = jnp.asarray(labels_np[order], dtype)
        counts = jnp.asarray(counts_np)
        starts = jnp.asarray(starts_np)
        class_onehot = jnp.asarray(
            (np.arange(n_classes)[:, None] == class_idx[order][None, :]).astype(
                labels_np.dtype
            ),
            dtype,
        )  # [C, N]

        # jointLabelMean[c] = 2w + 2(1-w)·n_c/n − 1  (reference :147-149)
        joint_label_mean = jnp.asarray(
            2.0 * w + 2.0 * (1.0 - w) * counts_np / n - 1.0, dtype
        )

        residual = labels_sorted - joint_label_mean
        residual_mean = _residual_class_means(
            residual, class_onehot, counts.astype(dtype)
        )

        models = [jnp.zeros((b.shape[1], n_classes), dtype) for b in blocks]
        # Keep ONLY the padded copy of each block (zero tail of n_max rows):
        # the zero tail contributes nothing to gemms/sums, so population
        # statistics use xb_pad directly with the true count n — no second
        # full copy of the design matrix stays resident.
        blocks_padded = []
        for b in blocks:
            blocks_padded.append(
                jnp.concatenate([b, jnp.zeros((n_max, b.shape[1]), dtype)], axis=0)
            )
        del blocks
        onehot_pad = jnp.concatenate(
            [class_onehot, jnp.zeros((n_classes, n_max), dtype)], axis=1
        )
        tail = jnp.zeros((n_max, n_classes), dtype)
        block_stats: list[tuple | None] = [None] * len(blocks_padded)
        lam_arr = jnp.asarray(self.lam, dtype)
        w_arr = jnp.asarray(w, dtype)

        for _pass in range(self.num_iter):
            for bi, xb_pad in enumerate(blocks_padded):
                res_pad = jnp.concatenate([residual, tail], axis=0)
                if block_stats[bi] is None:
                    pop_mean = jnp.sum(xb_pad, axis=0) / n
                    ata = xb_pad.T @ xb_pad
                    pop_cov = ata / n - jnp.outer(pop_mean, pop_mean)
                    class_means = (onehot_pad @ xb_pad) / counts.astype(dtype)[:, None]
                    joint_means = w * class_means + (1.0 - w) * pop_mean
                    block_stats[bi] = (pop_cov, pop_mean, joint_means)
                else:
                    pop_cov, pop_mean, joint_means = block_stats[bi]
                pop_xtr = xb_pad.T @ res_pad / n
                dw = _class_solves(
                    xb_pad,
                    res_pad,
                    starts,
                    counts,
                    pop_cov,
                    pop_mean,
                    pop_xtr,
                    joint_means,
                    residual_mean,
                    models[bi],
                    lam_arr,
                    w_arr,
                    n_max,
                )
                models[bi] = models[bi] + dw
                residual = residual - (xb_pad @ dw)[: residual.shape[0]]
                residual_mean = _residual_class_means(
                    residual, class_onehot, counts.astype(dtype)
                )

        # Intercept from joint means (reference :307-311):
        # b = jointLabelMean − Σ_d jointMeans[c, d] · W[d, c]
        full_model = jnp.concatenate(models, axis=0)
        joint_means_combined = jnp.concatenate(
            [s[2] for s in block_stats], axis=1
        )  # [C, D]
        b = joint_label_mean - jnp.einsum(
            "cd,dc->c", joint_means_combined, full_model
        )
        return BlockLinearMapper(models, self.block_size, b)
