"""Linear models + least-squares estimators
(reference src/main/scala/nodes/learning/LinearMapper.scala:18-93)."""

from __future__ import annotations


from ..core.pipeline import LabelEstimator, Transformer, node
from ..ops.stats import StandardScaler, StandardScalerModel
from ..parallel.mesh import current_mesh, mask_pad_rows, pad_shard_inputs
from .normal_equations import solve_least_squares


@node(data_fields=("x", "b", "feature_scaler"))
class LinearMapper(Transformer):
    """``out = (scale(in)) @ x + b`` (reference LinearMapper.scala:18-56).

    ``x`` is [d, k]; the reference stores the same matrix and computes
    ``x.t * in`` per item / ``rowsToMatrix(rows) * x`` per partition — here a
    single [N,d]x[d,k] MXU gemm.
    """

    def __init__(self, x, b=None, feature_scaler: StandardScalerModel | None = None):
        self.x = x
        self.b = b
        self.feature_scaler = feature_scaler

    def __call__(self, batch):
        if self.feature_scaler is not None:
            batch = self.feature_scaler(batch)
        out = batch @ self.x
        if self.b is not None:
            out = out + self.b
        return out


class LinearMapEstimator(LabelEstimator):
    """OLS / ridge via sharded normal equations
    (reference LinearMapper.scala:63-93): mean-center features and labels
    (mean-only StandardScaler), solve, intercept = label mean."""

    def __init__(self, lam: float | None = None, mesh=None):
        self.lam = lam
        self.mesh = mesh

    def fit(self, features, labels, nvalid: int | None = None) -> LinearMapper:
        """``nvalid``: true global row count when ``features``/``labels`` were
        zero-padded for sharding (see parallel.mesh.padded_shard_rows) —
        centering turns pad rows into ``-mean``, so they are masked back to
        zero before the gram.

        With a mesh (explicit or ambient), inputs are row-sharded and the
        normal equations run as a shard_map gram + model-axis-sharded solve.
        """
        mesh = self.mesh if self.mesh is not None else current_mesh()
        if mesh is not None:
            (features, labels), nvalid = pad_shard_inputs(
                mesh, nvalid, features, labels
            )
        feature_scaler = StandardScaler(normalize_std_dev=False).fit(
            features, nvalid=nvalid
        )
        label_scaler = StandardScaler(normalize_std_dev=False).fit(
            labels, nvalid=nvalid
        )
        a = mask_pad_rows(feature_scaler(features), nvalid)
        b = mask_pad_rows(label_scaler(labels), nvalid)
        x = solve_least_squares(a, b, float(self.lam or 0.0), mesh=mesh)
        return LinearMapper(x, label_scaler.mean, feature_scaler)


@node(data_fields=("weights", "intercept"))
class LeastSquaresModel(Transformer):
    """Bare ``X @ W + b`` head used by generic model application."""

    def __init__(self, weights, intercept=None):
        self.weights = weights
        self.intercept = intercept

    def __call__(self, batch):
        out = batch @ self.weights
        if self.intercept is not None:
            out = out + self.intercept
        return out
