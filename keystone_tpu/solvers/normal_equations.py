"""Sharded normal-equations least squares — re-owns the external ml-matrix
library (`edu.berkeley.cs.amplab.mlmatrix.NormalEquations`, SURVEY §2.2: the
jar imported at reference nodes/learning/BlockLinearMapper.scala:4).

The reference accumulates per-partition ``AᵀA``/``Aᵀb`` grams with a
configurable tree-reduce to the driver, then solves there.  Here: local grams
on each data shard hit the MXU, one psum over ICI reduces them, and the
λ-shifted Cholesky solve runs replicated on-device.  No driver round-trip.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import jax.scipy.linalg as jsl


@jax.jit
def gram(a, b):
    """(AᵀA, AᵀB).  With row-sharded inputs under jit XLA emits
    local-gram + all-reduce (the treeReduce replacement)."""
    return a.T @ a, a.T @ b


@functools.partial(jax.jit, static_argnames=())
def solve_gram_l2(ata, atb, lam):
    """Solve ``(AᵀA + λI) X = AᵀB`` via Cholesky."""
    d = ata.shape[0]
    reg = ata + lam * jnp.eye(d, dtype=ata.dtype)
    c, low = jsl.cho_factor(reg)
    return jsl.cho_solve((c, low), atb)


def solve_least_squares(a, b, lam: float = 0.0):
    """One-shot (regularized) least squares ``min ‖AX - B‖² + λ‖X‖²``."""
    ata, atb = gram(a, b)
    return solve_gram_l2(ata, atb, jnp.asarray(lam, ata.dtype))


class NormalEquations:
    """Class-shaped facade matching the ml-matrix API surface."""

    def solve_least_squares(self, a, b):
        return solve_least_squares(a, b, 0.0)

    def solve_least_squares_with_l2(self, a, b, lam):
        return solve_least_squares(a, b, lam)


@jax.jit
def _bcd_residual_init(blocks_t, models_t, labels_t):
    r = labels_t
    for blk, m in zip(blocks_t, models_t):
        r = r - blk @ m
    return r


@jax.jit
def _bcd_block_update(blk, ata, m_old, r, lam_):
    r_i = r + blk @ m_old
    atb = blk.T @ r_i
    m_new = solve_gram_l2(ata, atb, lam_)
    r_new = r_i - blk @ m_new
    return m_new, r_new


def bcd_least_squares_l2(
    blocks,
    labels,
    lam: float,
    num_iter: int,
    models_init=None,
):
    """Block coordinate descent for ``min ‖Σ_i A_i X_i - B‖² + λΣ‖X_i‖²`` —
    re-owns ml-matrix ``BlockCoordinateDescent.solveLeastSquaresWithL2``
    (SURVEY §2.2, called at reference BlockLinearMapper.scala:196-198).

    Per epoch, per block i:  solve
    ``(A_iᵀA_i + λI) X_i' = A_iᵀ (R + A_i X_i)`` where ``R = B - Σ_j A_j X_j``
    is the running residual, then update R.  Block grams are computed once and
    reused across epochs (they are constant), so epochs>1 cost only the
    ``A_i X_i`` matmuls and the solve.

    blocks: list of [N, d_i] arrays (row-sharded ok);  labels: [N, k].
    Returns list of [d_i, k] model blocks.
    """
    lam = jnp.asarray(lam, labels.dtype)
    nblocks = len(blocks)
    if models_init is None:
        models = [
            jnp.zeros((blk.shape[1], labels.shape[1]), labels.dtype) for blk in blocks
        ]
    else:
        models = list(models_init)

    if nblocks == 1 and models_init is None:
        # Degenerate case = plain normal equations; skip the residual machinery.
        return [solve_least_squares(blocks[0], labels, lam)]

    grams = []
    for blk in blocks:
        ata, _ = gram(blk, labels[:, :0])
        grams.append(ata)

    residual = _bcd_residual_init(tuple(blocks), tuple(models), labels)
    for _ in range(num_iter):
        for i in range(nblocks):
            models[i], residual = _bcd_block_update(
                blocks[i], grams[i], models[i], residual, lam
            )
    return models
