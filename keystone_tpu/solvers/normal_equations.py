"""Sharded normal-equations least squares — re-owns the external ml-matrix
library (`edu.berkeley.cs.amplab.mlmatrix.NormalEquations`, SURVEY §2.2: the
jar imported at reference nodes/learning/BlockLinearMapper.scala:4).

The reference accumulates per-partition ``AᵀA``/``Aᵀb`` grams with a
configurable tree-reduce to the driver, then solves there.  Here: local grams
on each data shard hit the MXU, one psum over ICI reduces them, and the
λ-shifted Cholesky solve runs replicated on-device.  No driver round-trip.
"""

from __future__ import annotations

import functools
import logging

import jax
import jax.numpy as jnp
import jax.scipy.linalg as jsl
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core import numerics as knum
from ..core.resilience import numerics_guard_enabled
from ..parallel.collectives import sharded_gram
from ..parallel.mesh import DATA_AXIS, MODEL_AXIS, padded_shard_rows

_logger = logging.getLogger("keystone_tpu.solvers.normal_equations")

# Jitter-retry escalation depth: regularizer grows λ·10^k for k=1..3 before
# the solve gives up (reference ml-matrix has no recovery at all — a
# rank-deficient gram NaNs the model silently).
_MAX_JITTER_ESCALATIONS = 3


@jax.jit
def gram(a, b):
    """(AᵀA, AᵀB).  With row-sharded inputs under jit XLA emits
    local-gram + all-reduce (the treeReduce replacement)."""
    return a.T @ a, a.T @ b


def _solve_gram_l2(ata, atb, lam):
    d = ata.shape[0]
    reg = ata + lam * jnp.eye(d, dtype=ata.dtype)
    c, low = jsl.cho_factor(reg)
    return jsl.cho_solve((c, low), atb)


_solve_gram_l2_jit = jax.jit(_solve_gram_l2)


def _all_finite(x) -> bool:
    return bool(jnp.all(jnp.isfinite(x)))


def _guarded_solve(solve_fn, ata, atb, lam):
    """Run ``solve_fn(ata, atb, lam)`` with non-finite input checks and
    Cholesky jitter-retry: an indefinite/rank-deficient gram NaNs the f32
    Cholesky, so the regularizer escalates λ·10^k (k ≤ 3, each step logged)
    before erroring.  λ=0 escalates from a floor of ~f32-eps times the mean
    gram diagonal, the standard relative-jitter scale.

    The checks cost one host sync per solve; ``KEYSTONE_NUMERICS_GUARD=0``
    restores the unguarded single-dispatch path.
    """
    lam_arr = jnp.asarray(lam, ata.dtype)
    if knum.active():
        # Conditioning monitor (ISSUE 15): a few-step power-iteration κ
        # estimate on the very gram this Cholesky is about to factor,
        # recorded into the active fit's FitReport.conditioning and
        # counted as a predictive ``cond_warn`` BEFORE the jitter-retry
        # ladder below ever trips — the ACCURACY.md §6 sweep live.
        knum.estimate_gram_condition(ata, float(lam), label="solve_gram_l2")
    if not numerics_guard_enabled():
        return solve_fn(ata, atb, lam_arr)
    if not _all_finite(ata) or not _all_finite(atb):
        raise FloatingPointError(
            "solve_gram_l2: non-finite entries in the gram/right-hand side "
            "— a NaN/Inf batch reached the solver (inject upstream guards, "
            "see core.resilience)"
        )
    x = solve_fn(ata, atb, lam_arr)
    if _all_finite(x):
        return x
    lam0 = float(lam)
    base = lam0
    if base <= 0.0:
        mean_diag = float(jnp.mean(jnp.diagonal(ata)))
        base = 1.2e-7 * abs(mean_diag) if mean_diag != 0.0 else 1.2e-7
    for k in range(1, _MAX_JITTER_ESCALATIONS + 1):
        lam_k = base * (10.0 ** k)
        _logger.warning(
            "solve_gram_l2: Cholesky produced non-finite solution at "
            "lam=%.3g; retrying with jitter lam=%.3g (escalation %d/%d)",
            lam0 if k == 1 else base * (10.0 ** (k - 1)),
            lam_k,
            k,
            _MAX_JITTER_ESCALATIONS,
        )
        x = solve_fn(ata, atb, jnp.asarray(lam_k, ata.dtype))
        if _all_finite(x):
            return x
    raise FloatingPointError(
        f"solve_gram_l2: solution still non-finite after "
        f"{_MAX_JITTER_ESCALATIONS} jitter escalations "
        f"(final lam={base * 10.0 ** _MAX_JITTER_ESCALATIONS:.3g}) — the "
        "gram is numerically broken beyond regularization"
    )


def solve_gram_l2(ata, atb, lam):
    """Solve ``(AᵀA + λI) X = AᵀB`` via Cholesky, guarded: non-finite
    inputs raise, and a failed factorization retries with escalating
    jitter (λ·10^k, k ≤ 3, logged) before erroring."""
    return _guarded_solve(_solve_gram_l2_jit, ata, atb, lam)


@functools.lru_cache(maxsize=None)
def _mesh_solver_fns(mesh):
    """jit-compiled solver steps with explicit (data, model) shardings.

    The Cholesky factorization of the regularized gram is replicated (it is
    tiny relative to the data) while the solve's right-hand-side columns —
    the class axis — are sharded over the model axis of the mesh: the
    TPU-native form of the reference's per-class column partitioning
    (reference nodes/learning/BlockWeightedLeastSquares.scala:228-263) and
    the model-parallel analog of ml-matrix's driver-side solve.
    """
    cols = NamedSharding(mesh, P(None, MODEL_AXIS))
    rows = NamedSharding(mesh, P(DATA_AXIS, None))

    solve = jax.jit(_solve_gram_l2, out_shardings=cols)
    block_update_jit = jax.jit(
        _bcd_block_update_impl, out_shardings=(cols, rows)
    )
    return solve, block_update_jit, rows


def _pad_cols(x, mult: int):
    """Zero-pad trailing columns to a multiple of ``mult`` (exact for the
    solvers: zero label columns produce zero weight columns)."""
    pad = (-x.shape[1]) % mult
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad)))
    return x, pad


def solve_least_squares(a, b, lam: float = 0.0, mesh=None):
    """One-shot (regularized) least squares ``min ‖AX - B‖² + λ‖X‖²``.

    With ``mesh``: grams run as an explicit shard_map (local MXU gram + one
    psum over the data axis — parallel.collectives.sharded_gram) and the
    triangular solve is model-axis sharded over the class columns.  Row
    counts not divisible by the data axis are zero-padded (exact: zero rows
    contribute nothing to the grams).
    """
    if mesh is None:
        ata, atb = gram(a, b)
        return solve_gram_l2(ata, atb, jnp.asarray(lam, ata.dtype))
    solve, _, _ = _mesh_solver_fns(mesh)
    a, _ = padded_shard_rows(a, mesh)
    b, _ = padded_shard_rows(b, mesh)
    b, col_pad = _pad_cols(b, mesh.shape[MODEL_AXIS])
    ata, atb = sharded_gram(mesh, a, b)
    x = _guarded_solve(solve, ata, atb, lam)
    return x[:, : x.shape[1] - col_pad] if col_pad else x


class NormalEquations:
    """Class-shaped facade matching the ml-matrix API surface."""

    def solve_least_squares(self, a, b):
        return solve_least_squares(a, b, 0.0)

    def solve_least_squares_with_l2(self, a, b, lam):
        return solve_least_squares(a, b, lam)


@jax.jit
def _bcd_residual_init(blocks_t, models_t, labels_t):
    r = labels_t
    for blk, m in zip(blocks_t, models_t):
        r = r - blk @ m
    return r


def _bcd_block_update_impl(blk, ata, m_old, r, lam_):
    r_i = r + blk @ m_old
    atb = blk.T @ r_i  # rows contracted over the data axis -> one psum
    m_new = _solve_gram_l2(ata, atb, lam_)
    r_new = r_i - blk @ m_new
    return m_new, r_new


# One BCD update body, two compiled forms: the local path below and the
# (data, model)-sharded path built in _mesh_solver_fns.
_bcd_block_update = jax.jit(_bcd_block_update_impl)


def bcd_least_squares_l2(
    blocks,
    labels,
    lam: float,
    num_iter: int,
    models_init=None,
    mesh=None,
):
    """Block coordinate descent for ``min ‖Σ_i A_i X_i - B‖² + λΣ‖X_i‖²`` —
    re-owns ml-matrix ``BlockCoordinateDescent.solveLeastSquaresWithL2``
    (SURVEY §2.2, called at reference BlockLinearMapper.scala:196-198).

    NOTE: the production fit path is ``solvers.block._fused_bcd_fit`` (one
    compiled program per fit).  This step-at-a-time form is kept as the
    REFERENCE ORACLE the fused path is tested against
    (tests/test_solvers.py::test_fused_fit_matches_stepwise_oracle) and as
    the BCD entry point for callers holding pre-centered blocks.

    Per epoch, per block i:  solve
    ``(A_iᵀA_i + λI) X_i' = A_iᵀ (R + A_i X_i)`` where ``R = B - Σ_j A_j X_j``
    is the running residual, then update R.  Block grams are computed once and
    reused across epochs (they are constant), so epochs>1 cost only the
    ``A_i X_i`` matmuls and the solve.

    blocks: list of [N, d_i] arrays (row-sharded ok);  labels: [N, k].
    Returns list of [d_i, k] model blocks.

    With ``mesh``: block grams run via the explicit shard_map collective and
    every block update is compiled with (data, model) shardings — features
    row-sharded, model columns sharded over the model axis.  Uneven row
    counts are zero-padded (exact: zero rows are zero in both the blocks and
    the labels, so grams and residual updates are unchanged).
    """
    lam = jnp.asarray(lam, labels.dtype)
    nblocks = len(blocks)

    if nblocks == 1 and models_init is None:
        # Degenerate case = plain normal equations; skip the residual machinery.
        return [solve_least_squares(blocks[0], labels, lam, mesh=mesh)]

    col_pad = 0
    if mesh is not None:
        _, block_update, _ = _mesh_solver_fns(mesh)
        blocks = [padded_shard_rows(blk, mesh)[0] for blk in blocks]
        labels, _ = padded_shard_rows(labels, mesh)
        # Class columns shard over the model axis; pad to a multiple (zero
        # label columns stay zero through every BCD update).
        labels, col_pad = _pad_cols(labels, mesh.shape[MODEL_AXIS])
        if models_init is not None and col_pad:
            models_init = [_pad_cols(m, mesh.shape[MODEL_AXIS])[0] for m in models_init]
        grams = [sharded_gram(mesh, blk, blk[:, :0])[0] for blk in blocks]
    else:
        block_update = _bcd_block_update
        grams = [gram(blk, labels[:, :0])[0] for blk in blocks]

    if models_init is None:
        models = [
            jnp.zeros((blk.shape[1], labels.shape[1]), labels.dtype) for blk in blocks
        ]
    else:
        models = list(models_init)

    residual = _bcd_residual_init(tuple(blocks), tuple(models), labels)
    for _ in range(num_iter):
        for i in range(nblocks):
            models[i], residual = block_update(
                blocks[i], grams[i], models[i], residual, lam
            )
    if col_pad:
        models = [m[:, : m.shape[1] - col_pad] for m in models]
    return models
