"""Block linear models: feature-dimension model parallelism
(reference src/main/scala/nodes/learning/BlockLinearMapper.scala:21-204).

The reference splits the feature axis into blocks (VectorSplitter), solves
block coordinate descent over them, and applies the model block-by-block with
a partial-sum reduce over zipped RDDs.  Here blocks are slices of an HBM
array; block application is a sum of MXU gemms; the streaming
``applyAndEvaluate`` form is preserved for models wider than memory.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp

from ..core.pipeline import Identity, LabelEstimator, Transformer
from ..ops.stats import StandardScaler
from ..ops.util import VectorSplitter
from ..parallel.mesh import current_mesh, mask_pad_rows, pad_shard_inputs
from .normal_equations import bcd_least_squares_l2


class BlockLinearMapper(Transformer):
    """Linear model stored as feature blocks
    (reference BlockLinearMapper.scala:21-137).

    xs: list of [d_i, k] weight blocks; b: optional [k] intercept;
    feature_scalers: per-block transformers applied before the gemm.
    """

    def __init__(
        self,
        xs: Sequence,
        block_size: int,
        b=None,
        feature_scalers: Sequence[Transformer] | None = None,
    ):
        self.xs = list(xs)
        self.block_size = block_size
        self.b = b
        self.feature_scalers = (
            list(feature_scalers)
            if feature_scalers is not None
            else [Identity() for _ in self.xs]
        )
        self.vector_splitter = VectorSplitter(block_size)

    def apply_blocks(self, blocks: Sequence):
        """Apply to pre-split feature blocks (reference :47-74)."""
        if len(blocks) != len(self.xs):
            raise ValueError(
                f"{len(blocks)} feature blocks vs {len(self.xs)} model blocks"
            )
        out = None
        for blk, x, scaler in zip(blocks, self.xs, self.feature_scalers):
            part = scaler(blk) @ x
            out = part if out is None else out + part
        if self.b is not None:
            out = out + self.b
        return out

    def __call__(self, batch):
        if isinstance(batch, (list, tuple)):
            return self.apply_blocks(batch)
        return self.apply_blocks(self.vector_splitter(batch))

    def apply_and_evaluate(
        self, batch_or_blocks, evaluator: Callable[[jnp.ndarray], None]
    ):
        """Invoke ``evaluator`` on the running prediction after each block —
        streaming evaluation without materializing all block products
        (reference BlockLinearMapper.scala:104-137)."""
        blocks = (
            batch_or_blocks
            if isinstance(batch_or_blocks, (list, tuple))
            else self.vector_splitter(batch_or_blocks)
        )
        if len(blocks) != len(self.xs):
            raise ValueError(
                f"{len(blocks)} feature blocks vs {len(self.xs)} model blocks"
            )
        running = None
        for blk, x, scaler in zip(blocks, self.xs, self.feature_scalers):
            part = scaler(blk) @ x
            running = part if running is None else running + part
            with_intercept = running if self.b is None else running + self.b
            evaluator(with_intercept)


jax.tree_util.register_pytree_node(
    BlockLinearMapper,
    lambda m: ((m.xs, m.b, m.feature_scalers), m.block_size),
    lambda block_size, kids: BlockLinearMapper(
        kids[0], block_size, kids[1], kids[2]
    ),
)


class BlockLeastSquaresEstimator(LabelEstimator):
    """Block coordinate descent least squares with L2
    (reference BlockLinearMapper.scala:147-204).

    Semantics matched to the reference: labels are mean-centered (mean-only
    StandardScaler), each feature block is mean-centered with its own scaler,
    BCD runs ``num_iter`` epochs over blocks, and the intercept is the label
    mean.
    """

    def __init__(
        self,
        block_size: int,
        num_iter: int = 1,
        lam: float = 0.0,
        mesh=None,
    ):
        self.block_size = block_size
        self.num_iter = num_iter
        self.lam = lam
        self.mesh = mesh

    def fit(
        self,
        features,
        labels,
        num_features: int | None = None,
        nvalid: int | None = None,
    ) -> BlockLinearMapper:
        """``nvalid``: true global row count when inputs were zero-padded for
        sharding — pad rows are masked back to zero after centering so grams
        stay exact (see parallel.mesh.padded_shard_rows).

        With a mesh (explicit or ambient via ``parallel.mesh.use_mesh``) the
        inputs are row-sharded over the data axis (zero-padding rows to a
        multiple of the axis size) and the BCD solve runs with (data, model)
        shardings — the distributed execution of reference
        BlockLinearMapper.scala:147-204.
        """
        mesh = self.mesh if self.mesh is not None else current_mesh()
        if isinstance(features, (list, tuple)):
            blocks = list(features)
        else:
            blocks = VectorSplitter(self.block_size, num_features)(features)

        if mesh is not None:
            (*blocks, labels), nvalid = pad_shard_inputs(
                mesh, nvalid, *blocks, labels
            )

        label_scaler = StandardScaler(normalize_std_dev=False).fit(
            labels, nvalid=nvalid
        )
        b = label_scaler(labels)

        feature_scalers = [
            StandardScaler(normalize_std_dev=False).fit(blk, nvalid=nvalid)
            for blk in blocks
        ]
        a_blocks = [scaler(blk) for scaler, blk in zip(feature_scalers, blocks)]

        b = mask_pad_rows(b, nvalid)
        a_blocks = [mask_pad_rows(a, nvalid) for a in a_blocks]

        models = bcd_least_squares_l2(
            a_blocks, b, self.lam, self.num_iter, mesh=mesh
        )
        return BlockLinearMapper(
            models, self.block_size, label_scaler.mean, feature_scalers
        )
