"""Block linear models: feature-dimension model parallelism
(reference src/main/scala/nodes/learning/BlockLinearMapper.scala:21-204).

The reference splits the feature axis into blocks (VectorSplitter), solves
block coordinate descent over them, and applies the model block-by-block with
a partial-sum reduce over zipped RDDs.  Here blocks are slices of an HBM
array; block application is a sum of MXU gemms; the streaming
``applyAndEvaluate`` form is preserved for models wider than memory.
"""

from __future__ import annotations

import functools
import io
import logging
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import jax.scipy.linalg as jsl
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core import autoshard
from ..core import memory as kmem
from ..core import numerics as knum
from ..core import profiler as kprof
from ..core import trace
from ..core.checkpoint import CheckpointError, _atomic_write_bytes
from ..core.pipeline import Identity, LabelEstimator, Transformer
from ..ops.stats import StandardScalerModel
from ..ops.util import VectorSplitter
from ..parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    current_mesh,
    enumerate_meshes,
    mesh_desc,
    pad_shard_inputs,
    reduced_mesh,
    row_sharding,
)

_logger = logging.getLogger("keystone_tpu.solvers.block")


class BlockLinearMapper(Transformer):
    """Linear model stored as feature blocks
    (reference BlockLinearMapper.scala:21-137).

    xs: list of [d_i, k] weight blocks; b: optional [k] intercept;
    feature_scalers: per-block transformers applied before the gemm.
    """

    def __init__(
        self,
        xs: Sequence,
        block_size: int,
        b=None,
        feature_scalers: Sequence[Transformer] | None = None,
    ):
        self.xs = list(xs)
        self.block_size = block_size
        self.b = b
        self.feature_scalers = (
            list(feature_scalers)
            if feature_scalers is not None
            else [Identity() for _ in self.xs]
        )
        self.vector_splitter = VectorSplitter(block_size)

    def apply_blocks(self, blocks: Sequence):
        """Apply to pre-split feature blocks (reference :47-74)."""
        if len(blocks) != len(self.xs):
            raise ValueError(
                f"{len(blocks)} feature blocks vs {len(self.xs)} model blocks"
            )
        out = None
        for blk, x, scaler in zip(blocks, self.xs, self.feature_scalers):
            part = scaler(blk) @ x
            out = part if out is None else out + part
        if self.b is not None:
            out = out + self.b
        return out

    def _split_features(self, batch):
        """Cut a concatenated [n, D] feature matrix into this model's OWN
        fitted block widths.  The nominal ``vector_splitter`` (block_size
        cuts) only agrees with the fitted blocks when every block except
        the last is exactly block_size wide; a model fit on pre-split
        batches narrower than block_size (MnistRandomFFT's per-FFT-group
        batches) needs the true widths — the serving path applies the model
        to ``GroupConcatFeaturizer``'s concatenation and must recover the
        fit-path blocks bit-exactly."""
        widths = [int(x.shape[0]) for x in self.xs]
        if int(batch.shape[-1]) != sum(widths):
            raise ValueError(
                f"feature matrix is {int(batch.shape[-1])} wide but the "
                f"model's blocks sum to {sum(widths)} ({widths})"
            )
        out = []
        i = 0
        for w in widths:
            out.append(batch[..., i : i + w])
            i += w
        return out

    def __call__(self, batch):
        if isinstance(batch, (list, tuple)):
            return self.apply_blocks(batch)
        return self.apply_blocks(self._split_features(batch))

    def apply_and_evaluate(
        self, batch_or_blocks, evaluator: Callable[[jnp.ndarray], None]
    ):
        """Invoke ``evaluator`` on the running prediction after each block —
        streaming evaluation without materializing all block products
        (reference BlockLinearMapper.scala:104-137)."""
        blocks = (
            batch_or_blocks
            if isinstance(batch_or_blocks, (list, tuple))
            else self._split_features(batch_or_blocks)
        )
        if len(blocks) != len(self.xs):
            raise ValueError(
                f"{len(blocks)} feature blocks vs {len(self.xs)} model blocks"
            )
        running = None
        for blk, x, scaler in zip(blocks, self.xs, self.feature_scalers):
            part = scaler(blk) @ x
            running = part if running is None else running + part
            with_intercept = running if self.b is None else running + self.b
            evaluator(with_intercept)


jax.tree_util.register_pytree_node(
    BlockLinearMapper,
    lambda m: ((m.xs, m.b, m.feature_scalers), m.block_size),
    lambda block_size, kids: BlockLinearMapper(
        kids[0], block_size, kids[1], kids[2]
    ),
)


def _fused_bcd_impl(x, labels, lam, nvalid, num_iter: int, widths, mesh,
                    specs=None):
    """The ENTIRE block-least-squares fit as one compiled program.

    Centering (label + per-block feature means over the ``nvalid`` true
    rows), pad-row masking, the per-block grams, the Cholesky factors, and
    ``num_iter`` BCD epochs (a lax.scan over epochs around a lax.scan over
    blocks) all fuse into a single XLA executable — the round-3 fit ran
    these as dozens of eager dispatches and was wall-clock-bound by
    per-dispatch transport latency (~126 ms each on a tunneled chip), not
    device compute.  The reference's analog is one Spark job per block
    (BlockLinearMapper.scala:147-204); ours is one program per fit.

    x: ONE [N, B*bs] design matrix with bs = max(widths); feature block i
    occupies columns [i*bs, i*bs + widths[i]) and everything else — pad
    columns of short blocks AND rows at index >= nvalid — must be zero
    (``fit`` and ``pad_shard_inputs`` guarantee both).  Each scan step
    dynamic-slices its block out of ``x`` and materializes the centered
    masked copy of THAT block only, so peak HBM is one design matrix plus a
    single [N, bs] block — the round-4 form stacked all blocks into a
    [B, N, bs] tensor plus a centered copy, transiently TRIPLING the
    design-matrix footprint, which capped the largest fittable solve at a
    third of HBM.  Pad columns get a unit diagonal shift (their gram rows
    are zero, so their solutions are exactly zero and the factorization
    stays positive-definite even at lam=0).

    With ``mesh``: rows shard over the data axis (grams lower to local
    MXU gram + ICI all-reduce), models/labels' class columns shard over the
    model axis.  ``specs`` (static; a sorted tuple of
    ``(operand, spec-string)`` pairs from a searched spec assignment —
    core.autoshard ISSUE 10) overrides the per-operand layout: ``"x"``
    defaults to ``data@dim0``, ``"labels"`` to the caller's placement,
    ``"models"`` to ``model@dim2``; each chosen spec lowers through
    ``autoshard.spec_sharding`` into the very ``NamedSharding`` constraint
    executed here, so a searched layout is REAL, not just byte accounting.
    ``specs=None`` is bit-for-bit the PR 9 program.

    Returns (models [B, bs, k], label_mean [k], means [B, bs]).
    """
    bs = max(widths)
    nb = len(widths)
    dtype = labels.dtype
    n = labels.shape[0]

    col_spec = None
    mrow_spec = None
    if mesh is not None:
        sp = dict(specs) if specs else {}
        x = jax.lax.with_sharding_constraint(
            x, autoshard.spec_sharding(sp.get("x", "data@dim0"), mesh, 2)
        )
        lspec = sp.get("labels")
        if lspec is not None:
            labels = jax.lax.with_sharding_constraint(
                labels, autoshard.spec_sharding(lspec, mesh, 2)
            )
        mspec = sp.get("models", "model@dim2")
        if mspec == "model@dim2":
            col_spec = NamedSharding(mesh, P(None, None, MODEL_AXIS))
            mrow_spec = NamedSharding(mesh, P(None, MODEL_AXIS))
        elif mspec != "replicated":  # replicated: no constraint at all
            raise ValueError(f"unsupported models spec {mspec!r}")

    mask = (jnp.arange(n) < nvalid).astype(dtype)[:, None]
    nv = jnp.asarray(nvalid, dtype)
    label_mean = jnp.sum(labels * mask, axis=0) / nv
    residual = (labels - label_mean) * mask
    # All block means in one gemv (pad rows are zero by contract).
    mu = (mask[:, 0] @ x) / nv  # [B*bs]
    means = mu.reshape(nb, bs)

    def centered_block(i):
        """(x_block_i - mean_i) * row_mask — the per-step [N, bs] transient
        (identical numerics to centering the whole matrix, without ever
        materializing more than one centered block)."""
        xi = jax.lax.dynamic_slice_in_dim(x, i * bs, bs, axis=1)
        mu_i = jax.lax.dynamic_slice_in_dim(mu, i * bs, bs, axis=0)
        return (xi - mu_i) * mask, mu_i

    pad_diag = jnp.stack(
        [
            (jnp.arange(bs) >= w).astype(dtype)  # 1.0 on pad columns
            for w in widths
        ]
    )

    # Regularized grams, factored once (they are constant across epochs —
    # the reference caches them the same way via its gram RDD persist).
    def gram_one(_, inp):
        i, pd = inp
        a_i, _ = centered_block(i)
        reg = a_i.T @ a_i + jnp.diag(lam + pd)
        return None, jsl.cho_factor(reg)[0]

    _, chol = jax.lax.scan(gram_one, None, (jnp.arange(nb), pad_diag))

    models = jnp.zeros((nb, bs, labels.shape[1]), dtype)
    if col_spec is not None:
        models = jax.lax.with_sharding_constraint(models, col_spec)

    def block_step(res, inp):
        i, c_i, m_i = inp
        a_i, _ = centered_block(i)
        r_i = res + a_i @ m_i
        atb = a_i.T @ r_i  # rows contract over the data axis -> one psum
        m_new = jsl.cho_solve((c_i, False), atb)
        if mrow_spec is not None:
            m_new = jax.lax.with_sharding_constraint(m_new, mrow_spec)
        return r_i - a_i @ m_new, m_new

    def epoch(carry, _):
        models, residual = carry
        residual, models = jax.lax.scan(
            block_step, residual, (jnp.arange(nb), chol, models)
        )
        return (models, residual), None

    (models, residual), _ = jax.lax.scan(
        epoch, (models, residual), None, length=num_iter
    )
    return models, label_mean, means


@functools.lru_cache(maxsize=None)
def _fused_bcd_fit_variant(donate_argnums: tuple = ()):
    """jit of the fused fit with a chosen donation set.  ``(0, 1)`` donates
    the design matrix and labels, letting XLA reuse their HBM for the
    residual/centered-block temps instead of doubling the footprint —
    callers donate only buffers THEY own (host-uploaded or padded copies),
    never a caller-visible passthrough array (VERDICT r5 weak #1)."""
    return jax.jit(
        _fused_bcd_impl,
        static_argnames=("num_iter", "widths", "mesh", "specs"),
        donate_argnums=donate_argnums,
    )


#: The historical non-donating entry point (benches AOT-lower this one).
_fused_bcd_fit = _fused_bcd_fit_variant(())


def _single_device_arrays(*arrays) -> bool:
    """True when no argument is a multi-device (sharded) jax.Array — the
    precondition for executing an AOT program planned on unsharded avals
    (its baked SingleDeviceSharding would reject sharded inputs)."""
    for a in arrays:
        if isinstance(a, jax.Array):
            try:
                if len(a.sharding.device_set) > 1:
                    return False
            except Exception:  # noqa: BLE001 — unknown sharding: be safe
                return False
    return True


def _execute_fused_bcd(plan, donate_argnums, x, labels, lam, nvalid,
                       num_iter: int, widths):
    """Dispatch the fused program: the planned AOT executable when admission
    ran (so the very program that was planned is the one executed), else the
    jitted variant (jit-cache-friendly when no budget is known, and the
    resilient fallback when a caller hands SHARDED arrays to a mesh-less
    fit — the planned executable baked single-device placements).  Module
    level so the fault harness can intercept it (tests inject
    RESOURCE_EXHAUSTED here to exercise the ladder's step-down)."""
    if (
        plan is not None
        and plan.compiled is not None
        and _single_device_arrays(x, labels)
    ):
        return plan.compiled(x, labels, lam, nvalid)
    return _fused_bcd_fit_variant(donate_argnums)(
        x, labels, lam, nvalid, num_iter, widths, None
    )


def _execute_fused_bcd_mesh(plan, x, labels, lam, nvalid, num_iter: int,
                            widths, mesh, specs=None):
    """Dispatch the GSPMD fused program for one mesh-ladder tier (``specs``:
    the tier's searched per-operand layout assignment, hashable, or None
    for the default layout).  The jitted entry — not ``plan.compiled`` —
    is used deliberately: an AOT executable bakes committed input
    shardings and scalar placements that a later call's padded inputs need
    not match exactly, while the jit cache keys on the same
    (aval, sharding) signature and reuses its own compilation.  Module
    level so the chaos harness can inject RESOURCE_EXHAUSTED here to drive
    the mesh ladder's step-down (the ``spec_mispredict`` family kills the
    top-ranked spec-sharded plan at this very dispatch)."""
    del plan
    return _fused_bcd_fit(x, labels, lam, nvalid, num_iter, widths, mesh,
                          specs)


def _bcd_spec_variants(m) -> list[dict]:
    """Per-operand spec assignments the BCD placement search enumerates
    for one mesh shape, beyond the strategy's default layout (row-sharded
    inputs, model-axis-sharded model columns): model-axis-sharded label
    columns (the wide-class layout), fully-replicated model blocks, and
    fully-replicated small operands.  Every entry is legal by
    construction — the class axis is padded to a model-axis multiple
    before execution — and deterministic, so two searches over one device
    set enumerate identical candidates."""
    d_sz, m_sz = m.shape[DATA_AXIS], m.shape[MODEL_AXIS]
    out: list[dict] = []
    if m_sz > 1:
        out.append({"labels": "model@dim1"})
        out.append({"models": "replicated"})
    if d_sz * m_sz > 1:
        out.append({"labels": "replicated", "models": "replicated"})
    return out


def _blocked_design_matrix(features, block_size: int, num_features=None):
    """(x, widths): the [N, B*bs] zero-padded blocked layout _fused_bcd_fit
    consumes, from either a monolithic [N, d] array or a list of pre-split
    feature blocks (the reference's fit(Seq[RDD]) form).

    Monolithic input with d a block_size multiple is passed through with NO
    copy — the common production shape (d = 2·2·descDim·vocabSize etc.) pays
    zero extra HBM.  Anything needing column padding costs one copy (np.pad
    host-side for host arrays, so nothing transient lands on device).
    """
    if isinstance(features, (list, tuple)):
        widths = tuple(int(b.shape[1]) for b in features)
        bs = max(widths)
        host = not any(isinstance(b, jax.Array) for b in features)
        xp = np if host else jnp
        parts = [
            xp.pad(xp.asarray(b), ((0, 0), (0, bs - w))) if w < bs else xp.asarray(b)
            for b, w in zip(features, widths)
        ]
        return xp.concatenate(parts, axis=1), widths
    d = num_features or features.shape[1]
    if d > features.shape[1]:
        # Silent clamping here once produced wrong models with no error:
        # widths were computed from d while the matrix stayed narrower, so
        # dynamic_slice re-read the previous block's columns (ADVICE r5).
        raise ValueError(
            f"num_features={d} exceeds the actual feature count "
            f"{features.shape[1]} — the blocked-design contract requires "
            "num_features <= features.shape[1]"
        )
    widths = tuple(
        min(block_size, d - i) for i in range(0, d, block_size)
    )
    bs = max(widths)
    features = features[:, :d]
    col_pad = len(widths) * bs - d
    if col_pad:
        xp = jnp if isinstance(features, jax.Array) else np
        features = xp.pad(xp.asarray(features), ((0, 0), (0, col_pad)))
    return features, widths


def _design_matrix_owned(x, features) -> bool:
    """True when the blocked design matrix ``x`` is a buffer this fit
    created (a host array whose device upload will be ours, or a fresh
    padded/concatenated device copy) — the precondition for donating it.
    A trivial full slice of a monolithic device input returns the SAME
    array object (jnp aliases it), so identity checks are exact."""
    if not isinstance(x, jax.Array):
        return True  # host: the jnp.asarray device copy belongs to the fit
    if x is features:
        return False
    if isinstance(features, (list, tuple)) and any(x is b for b in features):
        return False
    return True


@functools.partial(jax.jit, static_argnames=("bs",))
def _bcd_block_factor(x, mu, mask, lam, pad_diag_i, i, bs: int):
    """Cholesky factor of block i's regularized gram — computed once per
    block and reused across epochs (the factors are constant, exactly as
    the fused path caches them in its first scan)."""
    xi = jax.lax.dynamic_slice_in_dim(x, i * bs, bs, axis=1)
    mu_i = jax.lax.dynamic_slice_in_dim(mu, i * bs, bs, axis=0)
    a_i = (xi - mu_i) * mask
    return jsl.cho_factor(a_i.T @ a_i + jnp.diag(lam + pad_diag_i))[0]


@functools.partial(jax.jit, static_argnames=("bs",))
def _bcd_block_solve(x, mu, mask, residual, m_old, c_i, i, bs: int):
    """One BCD block update given the cached factor — identical math to
    one ``block_step`` of ``_fused_bcd_fit``."""
    xi = jax.lax.dynamic_slice_in_dim(x, i * bs, bs, axis=1)
    mu_i = jax.lax.dynamic_slice_in_dim(mu, i * bs, bs, axis=0)
    a_i = (xi - mu_i) * mask
    r_i = residual + a_i @ m_old
    m_new = jsl.cho_solve((c_i, False), a_i.T @ r_i)
    return m_new, r_i - a_i @ m_new


@jax.jit
def _hs_block_mean(xi, mask, nv):
    """Per-block feature means over the valid rows — identical per-column
    numerics to the fused path's one-gemv ``(mask @ x) / nv`` (each output
    column is an independent dot product, so blockwise evaluation changes
    nothing)."""
    return (mask[:, 0] @ xi) / nv


@jax.jit
def _hs_block_factor(xi, mu_i, mask, lam, pad_diag_i):
    """Cholesky factor of one HOST-STAGED block's regularized gram: the
    block arrives as its own [N, bs] argument (streamed H2D by the caller)
    instead of being sliced out of a device-resident design matrix."""
    a_i = (xi - mu_i) * mask
    return jsl.cho_factor(a_i.T @ a_i + jnp.diag(lam + pad_diag_i))[0]


@jax.jit
def _hs_block_solve(xi, mu_i, mask, residual, m_old, c_i):
    """One BCD block update on a host-staged block — same math as
    ``_bcd_block_solve`` minus the device-side slice."""
    a_i = (xi - mu_i) * mask
    r_i = residual + a_i @ m_old
    m_new = jsl.cho_solve((c_i, False), a_i.T @ r_i)
    return m_new, r_i - a_i @ m_new


def _host_staged_bcd_fit(x_host, labels, lam, nvalid, num_iter: int, widths):
    """The floor of the degradation ladder: the blocked design matrix lives
    in HOST RAM and exactly one [N, bs] block is on-device at a time (the
    H2D stream re-uploads each block once per epoch).  Device residency is
    one block + the [N, k] residual + the cached per-block factors/means —
    models far bigger than HBM fit, at H2D-bandwidth cost.  This is
    ml-matrix's "models bigger than memory" property (SURVEY L1'), which
    the fused one-program design had lost.  Numerics are identical to
    ``_fused_bcd_fit``: same centering, masking, pad-column shift, and
    update order.
    """
    bs = max(widths)
    nb = len(widths)
    x_host = np.asarray(x_host)
    labels = jnp.asarray(labels)
    dtype = labels.dtype
    n = labels.shape[0]

    mask = (jnp.arange(n) < nvalid).astype(dtype)[:, None]
    nv = jnp.asarray(nvalid, dtype)
    lam_arr = jnp.asarray(lam, dtype)
    label_mean = jnp.sum(labels * mask, axis=0) / nv
    residual = (labels - label_mean) * mask
    pad_diag = np.stack(
        [(np.arange(bs) >= w).astype(np.float64) for w in widths]
    )

    # Per-block means and Cholesky factors are constant across epochs; the
    # caches cost nb*(bs + bs^2) device floats — for production shapes
    # (bs=4096, nb<=8) ~0.5 GB, far below the matrix this tier is avoiding.
    mus: dict[int, jax.Array] = {}
    chols: dict[int, jax.Array] = {}
    models = [jnp.zeros((bs, labels.shape[1]), dtype) for _ in range(nb)]

    for _ in range(num_iter):
        for i in range(nb):
            xi = jnp.asarray(
                np.ascontiguousarray(x_host[:, i * bs : (i + 1) * bs])
            ).astype(dtype)
            if i not in mus:
                mus[i] = _hs_block_mean(xi, mask, nv)
                chols[i] = _hs_block_factor(
                    xi, mus[i], mask, lam_arr, jnp.asarray(pad_diag[i], dtype)
                )
            m_new, residual = _hs_block_solve(
                xi, mus[i], mask, residual, models[i], chols[i]
            )
            models[i] = m_new
            del xi  # the one big device buffer — released before the next H2D
    means = jnp.stack([mus[i] for i in range(nb)])
    return jnp.stack(models), label_mean, means


BCD_STATE_VERSION = 1


def bcd_checkpoint_path(path: str) -> str:
    """Canonical on-disk location of a BCD state for a stem or path — the
    ONE place the ``.npz`` suffix rule lives (save/load and the workload
    existence/cleanup checks all go through it)."""
    return path if path.endswith(".npz") else path + ".npz"


def save_bcd_checkpoint(path: str, state: dict) -> str:
    """Write a resumable BCD state (one ``.npz``, atomic) — the default
    sink for the per-block checkpoint callback."""
    buf = io.BytesIO()
    np.savez(
        buf,
        version=np.int64(state.get("version", BCD_STATE_VERSION)),
        epoch=np.int64(state["epoch"]),
        block=np.int64(state["block"]),
        models=np.asarray(jax.device_get(state["models"])),
        residual=np.asarray(jax.device_get(state["residual"])),
        widths=np.asarray(state["widths"], np.int64),
        num_iter=np.int64(state["num_iter"]),
        lam=np.float64(state["lam"]),
        nvalid=np.int64(state["nvalid"]),
        data_sum=np.asarray(state["data_sum"], np.float64),
    )
    path = bcd_checkpoint_path(path)
    _atomic_write_bytes(path, buf.getvalue())
    return path


def load_bcd_checkpoint(path: str) -> dict:
    """Read a state written by :func:`save_bcd_checkpoint`."""
    path = bcd_checkpoint_path(path)
    try:
        with np.load(path) as zf:
            state = {k: zf[k] for k in zf.files}
    except (OSError, ValueError) as e:
        raise CheckpointError(f"cannot read BCD checkpoint {path}: {e}") from e
    version = int(state.get("version", -1))
    if version != BCD_STATE_VERSION:
        raise CheckpointError(
            f"{path}: BCD state version {version} (this build reads "
            f"{BCD_STATE_VERSION})"
        )
    return {
        "version": version,
        "epoch": int(state["epoch"]),
        "block": int(state["block"]),
        "models": state["models"],
        "residual": state["residual"],
        "widths": tuple(int(w) for w in state["widths"]),
        "num_iter": int(state["num_iter"]),
        "lam": float(state["lam"]),
        "nvalid": int(state["nvalid"]),
        "data_sum": tuple(float(v) for v in state["data_sum"]),
    }


def bcd_checkpoint_writer(path: str) -> Callable[[dict], None]:
    """Per-block callback persisting each completed block's state to
    ``path`` (atomically, so preemption mid-write loses at most one block
    of progress)."""

    def write(state: dict) -> None:
        save_bcd_checkpoint(path, state)

    return write


def _stepwise_bcd_fit(
    x,
    labels,
    lam,
    nvalid,
    num_iter: int,
    widths,
    checkpoint_cb: Callable[[dict], None] | None = None,
    resume_state: dict | None = None,
    block_solve=None,
):
    """The resumable form of ``_fused_bcd_fit``: same centering, masking,
    pad-column shift, and per-block update, but driven from the host one
    block at a time so ``checkpoint_cb`` fires after every completed block
    and a preempted fit restarts at the last completed block via
    ``resume_state`` instead of from scratch.

    Trades the fused path's single-dispatch latency for preemptibility —
    the per-block program is still one compiled step (``_bcd_block_step``),
    so the extra cost is one dispatch round-trip per block plus whatever
    the callback spends persisting state.

    ``block_solve``: the preflight's AOT-compiled per-block solve executable
    (``plan.compiled`` from the stepwise tier's admission plan — statics
    baked, same avals).  When given, the degraded path executes the very
    program that was planned instead of re-compiling ``_bcd_block_solve``
    at first jit dispatch; ``None`` falls back to the jitted entry.
    """
    bs = max(widths)
    nb = len(widths)
    x = jnp.asarray(x)
    labels = jnp.asarray(labels)
    dtype = labels.dtype
    n = labels.shape[0]

    mask = (jnp.arange(n) < nvalid).astype(dtype)[:, None]
    nv = jnp.asarray(nvalid, dtype)
    label_mean = jnp.sum(labels * mask, axis=0) / nv
    mu = (mask[:, 0] @ x) / nv
    means = mu.reshape(nb, bs)
    pad_diag = np.stack(
        [(np.arange(bs) >= w).astype(np.float64) for w in widths]
    )
    # Cheap content fingerprint of the inputs: shape checks alone cannot
    # tell "same fit, resumed" from "different data, same shape" (e.g. a
    # re-featurized train set after a seed change) — resuming across that
    # line would silently mix two models.
    data_sum = (float(jnp.sum(x)), float(jnp.sum(labels)))

    if resume_state is not None:
        for field, want in (
            ("widths", tuple(widths)),
            ("num_iter", int(num_iter)),
            ("nvalid", int(nvalid)),
            ("lam", float(lam)),
        ):
            got = resume_state.get(field)
            if got != want:
                raise CheckpointError(
                    f"resume_from state disagrees with this fit: {field} is "
                    f"{got!r} in the checkpoint, {want!r} here"
                )
        got_sum = resume_state.get("data_sum")
        if got_sum is not None and not np.allclose(
            got_sum, data_sum, rtol=1e-5, atol=1e-6
        ):
            raise CheckpointError(
                "resume_from state was written for DIFFERENT data (input "
                f"fingerprint {tuple(got_sum)} vs {data_sum}) — refusing to "
                "resume a fit against features it was not computing on"
            )
        models = jnp.asarray(resume_state["models"], dtype)
        residual = jnp.asarray(resume_state["residual"], dtype)
        if models.shape != (nb, bs, labels.shape[1]) or residual.shape != (
            n,
            labels.shape[1],
        ):
            raise CheckpointError(
                "resume_from state shapes do not match this fit "
                f"(models {models.shape}, residual {residual.shape})"
            )
        e0 = int(resume_state["epoch"])
        b0 = int(resume_state["block"]) + 1  # block index last COMPLETED
        if b0 >= nb:
            e0, b0 = e0 + 1, 0
        _logger.info(
            "resuming BCD fit at epoch %d block %d (of %d epochs x %d blocks)",
            e0, b0, num_iter, nb,
        )
    else:
        models = jnp.zeros((nb, bs, labels.shape[1]), dtype)
        residual = (labels - label_mean) * mask
        e0, b0 = 0, 0

    lam_arr = jnp.asarray(lam, dtype)

    def jit_block_solve(*a):
        return _bcd_block_solve(*a, bs)

    solve = block_solve if block_solve is not None else jit_block_solve
    chol_cache: dict[int, jax.Array] = {}  # factors are constant across epochs
    for e in range(e0, num_iter):
        for i in range(b0 if e == e0 else 0, nb):
            c_i = chol_cache.get(i)
            if c_i is None:
                c_i = chol_cache[i] = _bcd_block_factor(
                    x,
                    mu,
                    mask,
                    lam_arr,
                    jnp.asarray(pad_diag[i], dtype),
                    jnp.asarray(i, jnp.int32),
                    bs,
                )
            m_new, residual = solve(
                x,
                mu,
                mask,
                residual,
                models[i],
                c_i,
                jnp.asarray(i, jnp.int32),
            )
            models = models.at[i].set(m_new)
            if checkpoint_cb is not None:
                checkpoint_cb(
                    {
                        "version": BCD_STATE_VERSION,
                        "epoch": e,
                        "block": i,
                        "models": models,
                        "residual": residual,
                        "widths": tuple(widths),
                        "num_iter": int(num_iter),
                        "lam": float(lam),
                        "nvalid": int(nvalid),
                        "data_sum": data_sum,
                    }
                )
    return models, label_mean, means


class BlockLeastSquaresEstimator(LabelEstimator):
    """Block coordinate descent least squares with L2
    (reference BlockLinearMapper.scala:147-204).

    Semantics matched to the reference: labels are mean-centered (mean-only
    StandardScaler), each feature block is mean-centered with its own scaler,
    BCD runs ``num_iter`` epochs over blocks, and the intercept is the label
    mean.  The whole fit compiles to ONE device program (_fused_bcd_fit).
    """

    def __init__(
        self,
        block_size: int,
        num_iter: int = 1,
        lam: float = 0.0,
        mesh=None,
    ):
        self.block_size = block_size
        self.num_iter = num_iter
        self.lam = lam
        self.mesh = mesh
        #: core.memory.FitReport of the most recent fit (tier plans, chosen
        #: tier, denials, OOM retries) — the bench emits it verbatim.
        self.last_fit_report = None

    def fit(
        self,
        features,
        labels,
        num_features: int | None = None,
        nvalid: int | None = None,
        checkpoint=None,
        resume_from=None,
        donate: bool | None = None,
        plan=None,
    ) -> BlockLinearMapper:
        """``nvalid``: true global row count when inputs were zero-padded for
        sharding — pad rows are masked back to zero after centering so grams
        stay exact (see parallel.mesh.padded_shard_rows).

        With a mesh (explicit or ambient via ``parallel.mesh.use_mesh``) the
        inputs are row-sharded over the data axis (zero-padding rows to a
        multiple of the axis size) and the BCD solve runs with (data, model)
        shardings — the distributed execution of reference
        BlockLinearMapper.scala:147-204.

        Fault tolerance: ``checkpoint`` is a path (state written atomically
        after every completed block — :func:`bcd_checkpoint_writer`) or a
        callback receiving the state dict; ``resume_from`` is a path or a
        state dict from a previous interrupted fit, which restarts at the
        last completed block.  Either switches the solve from the fused
        single-program path to the stepwise per-block path (same math,
        one dispatch per block); both are single-host (mesh unsupported —
        preempted multi-chip fits restart whole).

        Memory resilience: the solve runs a degradation ladder.  Without a
        mesh: fused one-program → stepwise per-block → host-staged block
        streaming, each tier preflighted against the HBM budget
        (core.memory.plan_program; ``KEYSTONE_HBM_BUDGET`` overrides for
        testing) and a runtime ``RESOURCE_EXHAUSTED`` steps down one tier
        instead of killing the fit.  With a mesh the ladder grows mesh
        tiers above those: full ``(data, model)`` mesh → model-axis-
        collapsed mesh → the single-device ladder, with each mesh tier
        admitted PER CHIP against the minimum free HBM across the mesh's
        devices and ``last_fit_report.mesh_shape`` recording which mesh
        actually ran.  ``donate``: tri-state — ``None``
        (default) donates the design matrix/labels into the fused program
        only when they are buffers this fit created (host uploads, padded
        copies), ``True`` forces donation of caller-owned device arrays
        (the caller must not reuse them; an exec-level OOM then cannot
        rebuild them for the step-down), ``False`` never donates.  The
        decision trail is ``self.last_fit_report``.

        Placement search (core.autoshard, on by default): the ladders above
        are the HAND enumeration — the fit actually runs the cost-model
        RANKED candidate list (every (data, model) mesh factorization of
        the live devices x fused/stepwise/host-staged strategy), pruned by
        the zero-cost batch preflight, with the hand order as the
        untrained-model tie-break and the host-staged/single-device floor
        pinned last; runtime RESOURCE_EXHAUSTED steps down the ranked list
        (counted ``autoshard_stepdown``) exactly as the hand ladder did.
        ``plan``: ``None`` honors ``KEYSTONE_AUTOSHARD``, ``False`` forces
        the hand ladder, ``True`` forces the search, a ``PlacementPlan``
        (or candidate-name list) replays a previous ranking.  The searched
        table lands in ``last_fit_report.placement``.
        """
        mesh = self.mesh if self.mesh is not None else current_mesh()
        resumable = checkpoint is not None or resume_from is not None
        if resumable and mesh is not None:
            raise ValueError(
                "checkpoint/resume_from use the stepwise BCD path, which "
                "does not run under a mesh — fit without a mesh or without "
                "checkpointing"
            )
        x, widths = _blocked_design_matrix(
            features, self.block_size, num_features
        )
        # Conditioning monitor (ISSUE 15): per-block κ estimates riding
        # the blocked design matrix this fit already formed (row-capped,
        # so the probe never re-uploads a host-staged matrix).  One flag
        # check when the observatory is off.
        cond_rows = (
            knum.design_conditioning(
                x, widths, float(self.lam), label="bcd_fit"
            )
            if knum.active()
            else None
        )
        # Any per-solve κ estimate emitted DURING the fit (the
        # _guarded_solve hook in normal_equations) joins the design-block
        # probes in the report.
        cond_ctx = knum.collect_conditioning()
        solve_cond = cond_ctx.__enter__()
        try:
            return self._fit_dispatch(
                features, x, labels, num_features, nvalid, widths,
                checkpoint, resume_from, donate, plan, mesh, resumable,
                cond_rows, solve_cond,
            )
        finally:
            cond_ctx.__exit__(None, None, None)

    def _fit_dispatch(
        self, features, x, labels, num_features, nvalid, widths,
        checkpoint, resume_from, donate, plan, mesh, resumable,
        cond_rows, solve_cond,
    ):
        if resumable:
            if nvalid is None:
                nvalid = int(jnp.shape(labels)[0])
            self.last_fit_report = kmem.FitReport(
                label="bcd_fit", chosen="stepwise[checkpoint]"
            )
            cb = checkpoint if callable(checkpoint) or checkpoint is None else (
                bcd_checkpoint_writer(checkpoint)
            )
            state = (
                load_bcd_checkpoint(resume_from)
                if isinstance(resume_from, str)
                else resume_from
            )
            # The checkpoint/resume path bypasses run_ladder (its tier is
            # forced), so it emits its own tier span with the report linked.
            with trace.span(
                "tier:stepwise[checkpoint]", cat="solve", solve="bcd_fit",
                resuming=state is not None,
            ):
                models, label_mean, means = _stepwise_bcd_fit(
                    jnp.asarray(x),
                    jnp.asarray(labels),
                    self.lam,
                    nvalid,
                    self.num_iter,
                    widths,
                    checkpoint_cb=cb,
                    resume_state=state,
                )
        elif mesh is not None:
            # Multi-chip path: the MESH degradation ladder — full
            # (data, model) mesh with per-chip admission, then the
            # model-axis-collapsed mesh, then the single-device ladder —
            # searched/ranked by core.autoshard unless plan=False.
            models, label_mean, means = self._fit_mesh_ladder(
                features, x, labels, num_features, nvalid, widths, mesh,
                plan_arg=plan,
            )
        else:
            if nvalid is None:
                nvalid = int(jnp.shape(labels)[0])
            models, label_mean, means = self._fit_ladder(
                features, x, labels, num_features, nvalid, widths, donate,
                plan_arg=plan,
            )
        all_cond = (cond_rows or []) + list(solve_cond)
        if all_cond and self.last_fit_report is not None:
            self.last_fit_report.conditioning = all_cond
        model_list = [models[i, :w] for i, w in enumerate(widths)]
        feature_scalers = [
            StandardScalerModel(means[i, :w]) for i, w in enumerate(widths)
        ]
        return BlockLinearMapper(
            model_list, self.block_size, label_mean, feature_scalers
        )

    def _fit_mesh_ladder(
        self, features, x, labels, num_features, nvalid, widths, mesh,
        plan_arg=None,
    ):
        """Distributed solve through the MESH degradation ladder.

        Tiers: the full ``(data, model)`` mesh → the model-axis-collapsed
        mesh (same chips, pure data-parallel: row-sharded operands halve
        per chip while model blocks replicate) → the single-device ladder
        (fused → stepwise → host-staged) on host-pulled inputs.  Each mesh
        tier is preflighted PER CHIP (``plan_program(mesh=...)`` against
        the minimum free HBM across participating chips) and a runtime
        ``RESOURCE_EXHAUSTED`` from any chip steps down exactly one tier —
        the Spark-executor admission/retry discipline, rebuilt for GSPMD.
        ``report.mesh_shape`` records which mesh actually ran the solve.
        """
        bs, nb = max(widths), len(widths)
        n0 = int(np.shape(labels)[0])
        k = int(np.shape(labels)[1])
        nvalid0 = nvalid if nvalid is not None else n0
        dtype = jax.dtypes.canonicalize_dtype(
            getattr(labels, "dtype", np.float32)
        )
        xdt = jax.dtypes.canonicalize_dtype(x.dtype)
        it = np.dtype(dtype).itemsize
        lam_arr = jnp.asarray(self.lam, dtype)

        report = kmem.FitReport(label="bcd_fit")
        self.last_fit_report = report

        itx = np.dtype(xdt).itemsize

        def mesh_tier(m, prior_rank, hand, specs=None):
            """One fused-mesh candidate: ``specs=None`` is the strategy's
            default layout (row-sharded inputs, model-axis-sharded model
            columns — the PR 9 hand rung, bit-for-bit); a spec assignment
            makes the candidate EXECUTE that per-operand layout, with the
            hints charging the chosen specs' actual per-chip bytes instead
            of the best-spec lower bound."""
            name = f"fused[mesh {mesh_desc(m)}]"
            if specs:
                name = f"fused[mesh {mesh_desc(m)}|{autoshard.spec_tag(specs)}]"
            d_sz, m_sz = m.shape[DATA_AXIS], m.shape[MODEL_AXIS]
            n_pad = n0 + (-n0) % d_sz
            k_pad = k + (-k) % m_sz
            mdict = dict(m.shape)
            lspec = (specs or {}).get("labels", "data@dim0")
            mspec = (specs or {}).get("models", "model@dim2")
            # The residual carries inherit the labels layout; the models
            # carry follows the models spec.  One byte helper feeds the
            # transient floor, the prune figure, and the cost model alike.
            res_b = autoshard.spec_chip_bytes(
                (n_pad, k_pad), dtype, lspec, mdict
            )
            models_b = autoshard.spec_chip_bytes(
                (nb, bs, k_pad), dtype,
                "model@dim2" if mspec == "model@dim2" else "replicated",
                mdict,
            )
            # Analytic per-chip transient floor (CPU backends report
            # temp 0): one centered row-sharded block, the replicated
            # Cholesky stack, two residual carries, the models carry.
            floor = (
                it * (n_pad * bs // d_sz + nb * bs * bs)
                + 2 * res_b + models_b
            )
            if specs:
                # A spec candidate charges the bytes of the layout it
                # will actually execute — the spec dimension is real.
                arg_bytes = (
                    autoshard.spec_chip_bytes(
                        (n_pad, nb * bs), xdt,
                        (specs or {}).get("x", "data@dim0"), mdict,
                    )
                    + autoshard.spec_chip_bytes(
                        (n_pad, k_pad), dtype, lspec, mdict
                    )
                )
            else:
                # Hand accounting: per-operand bytes through the spec
                # enumeration's minimum (the best sharding this mesh shape
                # can achieve) — a lower bound of any layout the compiled
                # admission will charge.
                arg_bytes = sum(
                    autoshard.best_spec(a, mdict)["per_chip_bytes"]
                    for a in (
                        jax.ShapeDtypeStruct((n_pad, nb * bs), xdt),
                        jax.ShapeDtypeStruct((n_pad, k_pad), dtype),
                    )
                )
            hints = {
                "arg_bytes": arg_bytes,
                "temp_bytes": floor,
                "out_bytes": it * (k_pad + nb * bs) + models_b,
                "flops": (
                    2.0 * n_pad * bs * bs * nb
                    + self.num_iter * 4.0 * n_pad * bs * k_pad * nb
                ) / (d_sz * m_sz),
                "dispatches": 1,
                "hbm_passes": self.num_iter + 1,
                "coll_bytes": (
                    it * nb * (bs * bs + self.num_iter * bs * k_pad)
                    if d_sz > 1 else 0
                ),
            }
            spec_t = tuple(sorted(specs.items())) if specs else None

            def plan():
                budget, _worst = kmem.min_chip_budget(m)
                sds = jax.ShapeDtypeStruct
                row = row_sharding(m)
                x_s = sds((n_pad, nb * bs), xdt, sharding=row)
                y_s = sds(
                    (n_pad, k_pad), dtype,
                    sharding=(
                        row if lspec == "data@dim0"
                        else autoshard.spec_sharding(lspec, m, 2)
                    ),
                )
                lam_s, i32_s = sds((), dtype), sds((), jnp.int32)
                return kmem.plan_program(
                    _fused_bcd_fit, x_s, y_s, lam_s, i32_s,
                    self.num_iter, widths, m, spec_t,
                    label=f"bcd_{name}", budget=budget,
                    min_temp_bytes=floor, mesh=m,
                )

            def run(plan):
                report.mesh_shape = dict(m.shape)
                if spec_t is None or lspec == "data@dim0":
                    (x_p, y_p), nv = pad_shard_inputs(m, nvalid0, x, labels)
                    # Class columns shard over the model axis; zero label
                    # columns stay zero through every BCD update — exact
                    # pad.
                    col_pad = (-int(jnp.shape(y_p)[1])) % m_sz
                    if col_pad:
                        y_p = jnp.pad(y_p, ((0, 0), (0, col_pad)))
                    nv = nv if nv is not None else int(jnp.shape(y_p)[0])
                else:
                    # Non-default labels layout: pad rows to the sharded
                    # design matrix's count and columns to a model-axis
                    # multiple, then PLACE per the chosen spec — the
                    # program's constraint and this placement read the
                    # same spec string, so they cannot drift.
                    (x_p,), nv = pad_shard_inputs(m, nvalid0, x)
                    nv = nv if nv is not None else n0
                    row_pad = int(jnp.shape(x_p)[0]) - n0
                    col_pad = (-k) % m_sz
                    if isinstance(labels, jax.Array):
                        y_p = (
                            jnp.pad(labels, ((0, row_pad), (0, col_pad)))
                            if row_pad or col_pad else labels
                        )
                    else:
                        y_p = np.pad(
                            np.asarray(labels),
                            ((0, row_pad), (0, col_pad)),
                        )
                    y_p = jax.device_put(
                        jnp.asarray(y_p), autoshard.spec_sharding(lspec, m, 2)
                    )
                models, label_mean, means = _execute_fused_bcd_mesh(
                    plan, jnp.asarray(x_p), jnp.asarray(y_p), lam_arr,
                    nv, self.num_iter, widths, m, spec_t,
                )
                if k_pad != k:
                    models = models[:, :, :k]
                    label_mean = label_mean[:k]
                return models, label_mean, means

            return autoshard.Candidate(
                name, "fused_mesh", plan, run, hints=hints,
                mesh_axes=mdict, prior_rank=prior_rank, hand=hand,
                specs=dict(specs) if specs else None,
            )

        def plan_single():
            return kmem.MemoryPlan(
                label="single_device",
                admitted=True,
                reason=(
                    "mesh ladder floor: single-device degradation ladder "
                    "(its own per-tier admission runs inside)"
                ),
            )

        inner_chosen = []

        def run_single(_plan):
            report.mesh_shape = None
            x_h = (
                np.asarray(jax.device_get(x)) if isinstance(x, jax.Array) else x
            )
            y_h = (
                np.asarray(jax.device_get(labels))
                if isinstance(labels, jax.Array)
                else labels
            )
            out = self._fit_ladder(
                x_h, x_h, y_h, num_features, nvalid0, widths, None,
                # The mesh-level search already ranked this floor; the
                # nested single-device ladder walks its hand order (a
                # nested search would overwrite the report's placement).
                plan_arg=False,
                report=report,
            )
            inner_chosen.append(report.chosen)
            return out

        cands = [mesh_tier(mesh, 0, True)]
        rm = reduced_mesh(mesh)
        if rm is not None:
            cands.append(mesh_tier(rm, 1, True))
        # The searched candidate set: every remaining (data, model)
        # factorization of the SAME devices, then (KEYSTONE_AUTOSHARD_SPECS)
        # the per-operand SPEC assignments of every mesh shape — e.g.
        # model-axis-sharded label columns, or fully-replicated model
        # blocks — each an executable layout, ranked by the cost model but
        # never promoted past the hand rungs on an untrained prior.  Only
        # enumerated when the search will run — a hand-ladder walk would
        # discard them, and each costs a jax Mesh construction.
        if autoshard.will_search(plan_arg):
            hand_shapes = {
                mesh_desc(c_mesh) for c_mesh in (mesh, rm) if c_mesh
            }
            searched_meshes = [mesh] + ([rm] if rm is not None else [])
            for extra in enumerate_meshes(list(mesh.devices.flat)):
                if mesh_desc(extra) not in hand_shapes:
                    searched_meshes.append(extra)
                    cands.append(mesh_tier(extra, len(cands), False))
            if autoshard.specs_enabled():
                for sm in searched_meshes:
                    for sp in _bcd_spec_variants(sm):
                        cands.append(
                            mesh_tier(sm, len(cands), False, specs=sp)
                        )
        cands.append(autoshard.Candidate(
            "single_device", "single_device", plan_single, run_single,
            hints={
                # Host pull + refit on one chip: the whole design matrix
                # crosses back over PCIe and nothing divides — the floor's
                # predicted cost is honest about why it is the floor.
                "arg_bytes": itx * n0 * nb * bs + it * n0 * k,
                "h2d_bytes": itx * n0 * nb * bs + it * n0 * k,
                "flops": 2.0 * n0 * bs * bs * nb
                + self.num_iter * 4.0 * n0 * bs * k * nb,
                "dispatches": 3,
            },
            prior_rank=len(cands), floor=True,
        ))
        # The solver declares its fit as a profiler PHASE (core.profiler):
        # the HBM watermark sampler attributes this solve's high-water
        # mark to "bcd_fit", separable from serving/ingest residency in
        # the same process.  A no-op when the profiler is off.
        with kprof.phase("bcd_fit"):
            out = autoshard.run_search(
                "bcd_fit", cands, report,
                fingerprint=autoshard.fingerprint(
                    "bcd_fit", n0, k, widths, self.num_iter, str(xdt),
                    str(dtype), dict(mesh.shape),
                    autoshard.device_fingerprint(),
                ),
                plan=plan_arg,
            )
        if inner_chosen and report.chosen == "single_device":
            # Keep the inner rung visible: "single_device/host_staged".
            report.chosen = f"single_device/{inner_chosen[0]}"
        return out

    def _fit_ladder(
        self, features, x, labels, num_features, nvalid, widths, donate,
        plan_arg=None, report=None,
    ):
        """Single-device solve through the degradation ladder.

        Preflights each tier on ShapeDtypeStructs (nothing allocated to
        decide), runs the first admitted tier, and steps down one tier on a
        runtime RESOURCE_EXHAUSTED.  Rebuild closures re-derive device
        buffers from the ORIGINAL ``features``/``labels`` — which a default
        (``donate=None``) fit never donates — so a failed donating attempt
        still leaves the next tier a data source.
        """
        bs, nb = max(widths), len(widths)
        n, k = int(np.shape(labels)[0]), int(np.shape(labels)[1])
        dtype = jax.dtypes.canonicalize_dtype(labels.dtype)
        xdt = jax.dtypes.canonicalize_dtype(x.dtype)
        it = np.dtype(dtype).itemsize
        budget = kmem.hbm_budget()

        donate_x = donate if donate is not None else _design_matrix_owned(x, features)
        donate_y = donate if donate is not None else not isinstance(labels, jax.Array)
        dn = tuple(i for i, d in ((0, donate_x), (1, donate_y)) if d)

        lam_arr = jnp.asarray(self.lam, dtype)
        nv_arr = jnp.asarray(nvalid, jnp.int32)
        sds = jax.ShapeDtypeStruct
        x_s, y_s = sds((n, nb * bs), xdt), sds((n, k), dtype)
        lam_s, i32_s = sds((), dtype), sds((), jnp.int32)
        mu_s, mask_s = sds((nb * bs,), xdt), sds((n, 1), dtype)
        res_s, m_s, c_s = sds((n, k), dtype), sds((bs, k), dtype), sds((bs, bs), dtype)
        # Caller inputs already on device: charged by every tier's plan
        # (they stay resident through the fit — run_host cannot free a
        # caller-owned buffer) and credited back when the budget is live
        # free bytes, which already excludes them.
        res_dev = (x.nbytes if isinstance(x, jax.Array) else 0) + (
            labels.nbytes if isinstance(labels, jax.Array) else 0
        )
        # Persistent device buffers the per-block programs' argument lists
        # do not see: labels + the models stack + the cached Cholesky
        # factors (and, host-staged, the cached block means).
        persist = it * (n * k + nb * bs * k + nb * bs * bs)
        # Analytic transient floor of the fused program — one centered
        # block, the chol stack, two residual carries, the models carry.
        # CPU backends report temp_size 0, which would otherwise rank the
        # fused program cheaper than its own stepwise decomposition.
        fused_floor = it * (n * bs + nb * bs * bs + 2 * n * k + nb * bs * k)

        def plan_fused():
            return kmem.plan_program(
                _fused_bcd_fit_variant(dn), x_s, y_s, lam_s, i32_s,
                self.num_iter, widths, None,
                label="bcd_fused", budget=budget, min_temp_bytes=fused_floor,
                resident_bytes=res_dev,
            )

        def plan_stepwise():
            return kmem.plan_program(
                _bcd_block_solve, x_s, mu_s, mask_s, res_s, m_s, c_s, i32_s,
                bs, label="bcd_stepwise", budget=budget, extra_bytes=persist,
                resident_bytes=res_dev,
            )

        def plan_host():
            return kmem.plan_program(
                _hs_block_solve, sds((n, bs), xdt), sds((bs,), xdt), mask_s,
                res_s, m_s, c_s,
                label="bcd_host_staged", budget=budget,
                extra_bytes=persist + it * nb * bs + res_dev,
                resident_bytes=res_dev,
            )

        def rebuild_x():
            xx, _ = _blocked_design_matrix(features, self.block_size, num_features)
            if isinstance(xx, jax.Array) and xx.is_deleted():
                raise kmem.LadderSourceLost(
                    "design matrix was donated (donate=True) and the source "
                    "features are gone — cannot step the ladder down; refit "
                    "with donate=False to keep OOM recovery possible"
                )
            return xx

        def get_x():
            return rebuild_x() if isinstance(x, jax.Array) and x.is_deleted() else x

        def get_y_dev():
            if isinstance(labels, jax.Array) and labels.is_deleted():
                raise kmem.LadderSourceLost(
                    "labels were donated (donate=True) and cannot be rebuilt "
                    "for the ladder step-down"
                )
            return jnp.asarray(labels)

        def run_fused(plan):
            return _execute_fused_bcd(
                plan, dn, jnp.asarray(get_x()), get_y_dev(), lam_arr, nv_arr,
                self.num_iter, widths,
            )

        def run_stepwise(plan):
            x_dev, y_dev = jnp.asarray(get_x()), get_y_dev()
            reusable = (
                plan is not None and _single_device_arrays(x_dev, y_dev)
            )
            return _stepwise_bcd_fit(
                x_dev, y_dev, self.lam, nvalid, self.num_iter, widths,
                # The preflight already compiled the per-block solve on
                # these very avals — execute that executable instead of
                # paying a second compile at first jit dispatch.  (Sharded
                # caller inputs fall back to the jitted entry: the planned
                # program baked single-device placements.)
                block_solve=plan.compiled if reusable else None,
            )

        def run_host(plan):
            xx = get_x()
            x_h = (
                np.asarray(jax.device_get(xx))
                if isinstance(xx, jax.Array) else np.asarray(xx)
            )
            if isinstance(xx, jax.Array) and _design_matrix_owned(xx, features):
                # Fit-owned device copy (initial or rebuilt): the host tier
                # must not keep the full matrix resident in HBM while
                # streaming blocks — that residency is what it exists to
                # avoid.  Caller-owned arrays are left alone.
                kmem.free_buffers(xx)
            return _host_staged_bcd_fit(
                x_h, get_y_dev(), self.lam, nvalid, self.num_iter, widths
            )

        if report is None:
            report = kmem.FitReport(label="bcd_fit", budget_bytes=budget)
            self.last_fit_report = report
        itx = np.dtype(xdt).itemsize
        x_bytes, y_bytes = itx * n * nb * bs, it * n * k
        flops = (
            2.0 * n * bs * bs * nb + self.num_iter * 4.0 * n * bs * k * nb
        )
        per_block_dispatches = nb * (self.num_iter + 1) + 2
        cands = [
            autoshard.Candidate(
                "fused", "fused", plan_fused, run_fused,
                hints={
                    "arg_bytes": x_bytes + y_bytes,
                    # The donating variant aliases its donated args — the
                    # zero-cost prune must stay a lower bound of the
                    # compiled admission, which credits them back.
                    "alias_bytes": (
                        (x_bytes if 0 in dn else 0)
                        + (y_bytes if 1 in dn else 0)
                    ),
                    "temp_bytes": fused_floor,
                    "out_bytes": it * (nb * bs * k + k + nb * bs),
                    "resident_bytes": res_dev,
                    "flops": flops,
                    "dispatches": 1,
                    "hbm_passes": self.num_iter + 1,
                },
                prior_rank=0,
            ),
            autoshard.Candidate(
                "stepwise", "stepwise", plan_stepwise, run_stepwise,
                hints={
                    "arg_bytes": x_bytes + y_bytes,
                    "temp_bytes": it * (n * bs + n * k),
                    "out_bytes": it * nb * bs * k,
                    "extra_bytes": persist,
                    "resident_bytes": res_dev,
                    "flops": flops,
                    "dispatches": per_block_dispatches,
                    "hbm_passes": self.num_iter + 1,
                },
                prior_rank=1,
            ),
            autoshard.Candidate(
                "host_staged", "host_staged", plan_host, run_host,
                hints={
                    "arg_bytes": itx * n * bs + y_bytes,
                    "temp_bytes": it * n * k,
                    "extra_bytes": persist + it * nb * bs,
                    "resident_bytes": res_dev,
                    "flops": flops,
                    "dispatches": per_block_dispatches,
                    # Each epoch re-streams every block over PCIe — the
                    # term that keeps the floor at the bottom of every
                    # untrained ranking.
                    "h2d_bytes": self.num_iter * x_bytes,
                },
                prior_rank=2, floor=True,
            ),
        ]
        with kprof.phase("bcd_fit"):
            return autoshard.run_search(
                "bcd_fit", cands, report,
                fingerprint=autoshard.fingerprint(
                    "bcd_fit", n, k, widths, self.num_iter, str(xdt),
                    str(dtype), None, autoshard.device_fingerprint(),
                ),
                plan=plan_arg,
                budget=budget,
            )
