"""Multinomial naive Bayes — own implementation replacing the reference's
Spark-MLlib delegation (reference src/main/scala/nodes/learning/NaiveBayesModel.scala:22-71,
which calls mllib.classification.NaiveBayes.train).

MLlib's multinomial NB semantics (reproduced here):
    pi[c]       = log(n_c + λ) − log(n + C·λ)
    theta[c, d] = log(count_{c,d} + λ) − log(Σ_d count_{c,d} + D·λ)
    score(x)    = pi + theta @ x   (log-posterior up to a constant)

Fitting aggregates per-class feature sums from CSR features with one
host-side scatter-add (the data is already host-resident text); scoring runs
on device — dense inputs hit the MXU directly, CSR inputs use
gather + segment-sum, the TPU-friendly sparse contraction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
try:
    from jax import shard_map
except ImportError:  # pre-0.5 jax exports it under experimental only
    from jax.experimental.shard_map import shard_map

from jax.sharding import PartitionSpec as P

from ..core.pipeline import LabelEstimator, Transformer, node
from ..ops.sparse import CSRFeatures
from ..parallel.mesh import DATA_AXIS, current_mesh


@node(data_fields=("pi", "theta"))
class NaiveBayesModel(Transformer):
    """Log-posterior scores ``pi + theta @ x``
    (reference NaiveBayesModel.scala:49-55)."""

    def __init__(self, pi, theta):
        self.pi = pi  # [C]
        self.theta = theta  # [C, D]

    def __call__(self, batch):
        if isinstance(batch, CSRFeatures):
            mesh = current_mesh()
            if mesh is not None and mesh.shape[DATA_AXIS] > 1:
                return self._apply_csr_mesh(batch, mesh)
            return self._apply_csr(batch)
        return batch @ self.theta.T + self.pi

    # Chunk the nnz axis so the [chunk, C] gather intermediate stays bounded
    # even for corpora whose nnz dwarfs the dense input.
    NNZ_CHUNK = 1 << 22

    def _apply_csr(self, csr: CSRFeatures):
        # gather theta columns at the nonzeros, scale, segment-sum by row
        n = len(csr)
        # int64 on host: nnz can exceed int32 for large corpora
        row_ids = np.repeat(
            np.arange(n, dtype=np.int64), np.diff(csr.indptr).astype(np.int64)
        )
        nnz = row_ids.shape[0]
        scores = jnp.zeros((n, self.theta.shape[0]), self.theta.dtype)
        for lo in range(0, max(nnz, 1), self.NNZ_CHUNK):
            hi = min(lo + self.NNZ_CHUNK, nnz)
            cols = jnp.asarray(csr.indices[lo:hi])
            vals = jnp.asarray(csr.values[lo:hi])
            contrib = self.theta.T[cols] * vals[:, None]  # [chunk, C]
            scores = scores + jax.ops.segment_sum(
                contrib, jnp.asarray(row_ids[lo:hi]), num_segments=n
            )
        return scores + self.pi

    def _apply_csr_mesh(self, csr: CSRFeatures, mesh):
        """Data-parallel CSR scoring over the mesh: documents are split into
        one contiguous row group per data-axis device; each device runs the
        gather + sorted-segment-sum contraction on its own COO shard against
        the replicated ``theta`` — no cross-device communication at all (the
        shuffle-free analog of the reference scoring an RDD partition per
        executor).  Per-shard COO buffers are zero-padded to the max shard
        nnz (value 0 contributes nothing)."""
        k = mesh.shape[DATA_AXIS]
        n = len(csr)
        rows_per = -(-n // k)
        indptr = csr.indptr.astype(np.int64)
        bounds = [int(indptr[min(j * rows_per, n)]) for j in range(k + 1)]
        nnz_max = max(bounds[j + 1] - bounds[j] for j in range(k))
        cols = np.zeros((k, max(nnz_max, 1)), np.int32)
        vals = np.zeros((k, max(nnz_max, 1)), np.float32)
        # pad entries point at the LAST local row (zero value, so they add
        # nothing) keeping row ids non-decreasing for indices_are_sorted
        rows = np.full((k, max(nnz_max, 1)), rows_per - 1, np.int32)
        for j in range(k):
            lo, hi = bounds[j], bounds[j + 1]
            r0, r1 = j * rows_per, min((j + 1) * rows_per, n)
            m = hi - lo
            cols[j, :m] = csr.indices[lo:hi]
            vals[j, :m] = csr.values[lo:hi]
            rows[j, :m] = (
                np.repeat(np.arange(r0, r1), np.diff(indptr[r0 : r1 + 1])) - r0
            )

        def shard_scores(cols_s, vals_s, rows_s, theta_t, pi):
            contrib = theta_t[cols_s[0]] * vals_s[0][:, None]  # [nnz, C]
            s = jax.ops.segment_sum(
                contrib,
                rows_s[0],
                num_segments=rows_per,
                indices_are_sorted=True,
            )
            return (s + pi)[None]

        fn = shard_map(
            shard_scores,
            mesh=mesh,
            in_specs=(
                P(DATA_AXIS, None),
                P(DATA_AXIS, None),
                P(DATA_AXIS, None),
                P(None, None),
                P(None),
            ),
            out_specs=P(DATA_AXIS, None, None),
        )
        out = jax.jit(fn)(
            jnp.asarray(cols),
            jnp.asarray(vals),
            jnp.asarray(rows),
            self.theta.T,
            self.pi,
        )
        return out.reshape(k * rows_per, -1)[:n]


class NaiveBayesEstimator(LabelEstimator):
    """Fit multinomial NB (reference NaiveBayesEstimator:63-71)."""

    def __init__(self, num_classes: int, lam: float = 1.0):
        self.num_classes = num_classes
        self.lam = lam

    def fit(self, features, labels) -> NaiveBayesModel:
        labels = np.asarray(labels)
        n = labels.shape[0]
        c = self.num_classes
        n_c = np.bincount(labels, minlength=c).astype(np.float64)

        if isinstance(features, CSRFeatures):
            d = features.num_features
            counts = np.zeros((c, d), np.float64)
            row_ids = np.repeat(np.arange(len(features)), np.diff(features.indptr))
            np.add.at(
                counts, (labels[row_ids], features.indices), features.values
            )
        else:
            dense = np.asarray(features, np.float64)
            d = dense.shape[1]
            counts = np.zeros((c, d), np.float64)
            np.add.at(counts, labels, dense)

        lam = self.lam
        pi = np.log(n_c + lam) - np.log(n + c * lam)
        theta = np.log(counts + lam) - np.log(
            counts.sum(axis=1, keepdims=True) + d * lam
        )
        return NaiveBayesModel(
            jnp.asarray(pi, jnp.float32), jnp.asarray(theta, jnp.float32)
        )
