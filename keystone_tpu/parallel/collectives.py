"""Communication backend: XLA collectives over ICI/DCN.

The reference's entire comm surface is Spark primitives (SURVEY §2.9):
``treeAggregate``/``treeReduce`` (reference nodes/stats/StandardScaler.scala:46-48,
nodes/learning/BlockWeightedLeastSquares.scala:186-216), ``broadcast``
(BlockLinearMapper.scala:51), ``partitionBy`` shuffles
(BlockWeightedLeastSquares.scala:335-357) and ``collect``.  Here each maps to
one XLA collective over the ICI fabric:

  treeReduce/treeAggregate  ->  psum            (one fused all-reduce)
  broadcast                 ->  replication / all_gather
  partitionBy shuffle       ->  all_to_all / ppermute
  collect                   ->  device->host transfer of an already-reduced array

These wrappers are thin on purpose — the win is that under ``jit`` with
sharded inputs XLA already inserts the right collective; the explicit
``shard_map`` forms below exist for kernels that want manual control (e.g.
streaming gram accumulation) and for multi-host DCN layouts.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from jax import shard_map

from .mesh import DATA_AXIS


def psum_gram(x_block, y_block, axis_name: str = DATA_AXIS):
    """Per-shard gram + cross-shard reduce: the treeReduce replacement.

    Inside ``shard_map``: computes local ``XᵀX`` and ``XᵀY`` on the MXU and
    all-reduces over the data axis — one ICI collective replaces the
    reference's multi-hop executor->driver tree
    (BlockWeightedLeastSquares.scala:186-216).
    """
    ata = jax.lax.psum(x_block.T @ x_block, axis_name)
    atb = jax.lax.psum(x_block.T @ y_block, axis_name)
    return ata, atb


@functools.lru_cache(maxsize=None)
def _sharded_gram_fn(mesh):
    fn = shard_map(
        functools.partial(psum_gram, axis_name=DATA_AXIS),
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS, None)),
        out_specs=(P(None, None), P(None, None)),
    )
    return jax.jit(fn)


def sharded_gram(mesh, x, y):
    """``(XᵀX, XᵀY)`` for row-sharded ``x``/``y`` via an explicit shard_map.
    Compiled once per (mesh, shape) — the wrapper is cached per mesh so
    repeated fits hit the jit cache."""
    return _sharded_gram_fn(mesh)(x, y)


def psum_moments(x_block, axis_name: str = DATA_AXIS, nvalid=None):
    """Sharded (count, sum, sumsq): the MultivariateOnlineSummarizer analog.

    Zero-padded rows contribute zero to the sums; ``nvalid`` (global true row
    count) overrides the padded count when provided.
    """
    cnt = jax.lax.psum(jnp.asarray(x_block.shape[0], x_block.dtype), axis_name)
    if nvalid is not None:
        cnt = jnp.asarray(nvalid, x_block.dtype)
    s = jax.lax.psum(jnp.sum(x_block, axis=0), axis_name)
    sq = jax.lax.psum(jnp.sum(x_block * x_block, axis=0), axis_name)
    return cnt, s, sq


@jax.jit
def sharded_moments_jit(x):
    """(count, Σx, Σx²) over rows.  Under jit with a row-sharded input XLA
    lowers the sums to local reductions + one psum over ICI — the
    treeAggregate(MultivariateOnlineSummarizer) replacement
    (reference nodes/stats/StandardScaler.scala:46-48)."""
    cnt = jnp.asarray(x.shape[0], x.dtype)
    s = jnp.sum(x, axis=0)
    sq = jnp.sum(x * x, axis=0)
    return cnt, s, sq


@functools.lru_cache(maxsize=None)
def _all_to_all_fn(mesh, ndim: int, axis_name: str):
    def body(xs):
        return jax.lax.all_to_all(xs, axis_name, 0, 0, tiled=True)

    spec = P(DATA_AXIS, *([None] * (ndim - 1)))
    return jax.jit(shard_map(body, mesh=mesh, in_specs=(spec,), out_specs=spec))


def all_to_all_rows(mesh, x, axis_name: str = DATA_AXIS):
    """Reshard rows across the data axis — the partitionBy/shuffle analog.

    Each shard's rows are split into axis_size equal groups and group j is
    delivered to device j (tiled all_to_all), so row i of the global array
    lands on device ``(i mod per_shard) // (per_shard / k)`` — a deterministic
    round-robin redistribution.  Requires per-shard row count divisible by the
    axis size.
    """
    return _all_to_all_fn(mesh, x.ndim, axis_name)(x)


def replicate_to(mesh, x):
    """Broadcast analog: commit an array replicated across the mesh."""
    return jax.device_put(x, NamedSharding(mesh, P()))
