"""Communication backend: XLA collectives over ICI/DCN.

The reference's entire comm surface is Spark primitives (SURVEY §2.9):
``treeAggregate``/``treeReduce`` (reference nodes/stats/StandardScaler.scala:46-48,
nodes/learning/BlockWeightedLeastSquares.scala:186-216), ``broadcast``
(BlockLinearMapper.scala:51), ``partitionBy`` shuffles
(BlockWeightedLeastSquares.scala:335-357) and ``collect``.  Here each maps to
one XLA collective over the ICI fabric:

  treeReduce/treeAggregate  ->  psum (one fused all-reduce): ``sharded_gram``
                                below, wired into the solvers
  broadcast                 ->  implicit XLA replication of unsharded
                                operands under jit / explicit P() shardings
  partitionBy shuffle       ->  host sort of the small key vector + one
                                device gather per block (the BWLS class
                                shuffle, solvers/weighted.py) — measured
                                simpler and no worse than a ragged
                                all_to_all for the one-time preamble; the
                                per-shard COO layout in
                                solvers/naive_bayes.py is the
                                shuffle-free scoring analog
  collect                   ->  device->host transfer of an already-reduced
                                array

These wrappers are thin on purpose — the win is that under ``jit`` with
sharded inputs XLA already inserts the right collective; the explicit
``shard_map`` forms below exist for kernels that want manual control (e.g.
streaming gram accumulation) and for multi-host DCN layouts.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
try:
    from jax import shard_map
except ImportError:  # pre-0.5 jax exports it under experimental only
    from jax.experimental.shard_map import shard_map


from .mesh import DATA_AXIS


def psum_gram(x_block, y_block, axis_name: str = DATA_AXIS):
    """Per-shard gram + cross-shard reduce: the treeReduce replacement.

    Inside ``shard_map``: computes local ``XᵀX`` and ``XᵀY`` on the MXU and
    all-reduces over the data axis — one ICI collective replaces the
    reference's multi-hop executor->driver tree
    (BlockWeightedLeastSquares.scala:186-216).
    """
    ata = jax.lax.psum(x_block.T @ x_block, axis_name)
    atb = jax.lax.psum(x_block.T @ y_block, axis_name)
    return ata, atb


@functools.lru_cache(maxsize=None)
def _sharded_gram_fn(mesh):
    fn = shard_map(
        functools.partial(psum_gram, axis_name=DATA_AXIS),
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS, None)),
        out_specs=(P(None, None), P(None, None)),
    )
    return jax.jit(fn)


def sharded_gram(mesh, x, y):
    """``(XᵀX, XᵀY)`` for row-sharded ``x``/``y`` via an explicit shard_map.
    Compiled once per (mesh, shape) — the wrapper is cached per mesh so
    repeated fits hit the jit cache."""
    return _sharded_gram_fn(mesh)(x, y)


@jax.jit
def sharded_moments_jit(x):
    """(count, Σx, Σx²) over rows.  Under jit with a row-sharded input XLA
    lowers the sums to local reductions + one psum over ICI — the
    treeAggregate(MultivariateOnlineSummarizer) replacement
    (reference nodes/stats/StandardScaler.scala:46-48)."""
    cnt = jnp.asarray(x.shape[0], x.dtype)
    s = jnp.sum(x, axis=0)
    sq = jnp.sum(x * x, axis=0)
    return cnt, s, sq
