"""Multi-host process-group bring-up: ``jax.distributed`` behind the
``make_mesh`` API, with membership the serving fleet can shrink.

Two layers, deliberately separate:

* **``jax.distributed`` bring-up** (:func:`init_process_group`) for the
  fit path, where cross-host collectives are worth their coupling: gloo
  CPU collectives are enabled so multi-process CPU computations work at
  all, the coordinator port is auto-picked (:func:`pick_coordinator`)
  with bounded retry on ``EADDRINUSE`` (counted ``dist_port_retry``),
  and the join is deadline-guarded — a slow or dead peer becomes a typed
  :class:`~keystone_tpu.core.resilience.DeadlineExceeded` (counted
  ``dist_join_timeout``), never a hang.  Once initialised,
  ``jax.devices()`` is GLOBAL, so the existing ``make_mesh()`` /
  ``enumerate_meshes()`` calls build a data axis spanning hosts with no
  new API.
* **Fleet membership** (:class:`GroupState`, :func:`reform_group`) for
  the serving path.  jax's coordination client cannot survive peer death
  in-process (a lost peer's heartbeat failure poisons the client and a
  later ``jax.distributed.shutdown()`` fatally aborts the process), so
  serving hosts keep jax HOST-LOCAL — no collectives on the serve hot
  path — and track world/rank in keystone's own group record, which
  :func:`reform_group` reduces in place when the front-end declares a
  peer dead (counted ``dist_reform``).  This is the production-fleet
  shape: inference hosts share routing and checkpoints, not an XLA
  communicator.

Single-process discipline: with nothing configured (no
``KEYSTONE_DIST_*`` env, no explicit ``world``), every entry point here
is inert — :func:`process_count` answers 1 and :func:`process_index` 0
WITHOUT importing jax, so decode workers and the serve hot path pay
nothing.  jax is imported lazily inside the functions that need it.

Env knobs (README ``KEYSTONE_*`` table):

* ``KEYSTONE_DIST_COORD`` — coordinator ``host:port``.
* ``KEYSTONE_DIST_PROCS`` / ``KEYSTONE_DIST_RANK`` — world size and this
  process's rank.
* ``KEYSTONE_DIST_JOIN_TIMEOUT_S`` — per-peer join deadline (default 60).
* ``KEYSTONE_DIST_PORT_RETRIES`` — coordinator bind retries on
  ``EADDRINUSE`` (default 4).
* ``KEYSTONE_DIST_DISABLE`` — force :func:`spawn_available` False (CI
  hosts without spawn/ports).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import socket
import sys
import threading
import time

from ..core.resilience import (
    DeadlineExceeded,
    counters,
    is_addr_in_use,
)

COORD_ENV = "KEYSTONE_DIST_COORD"
PROCS_ENV = "KEYSTONE_DIST_PROCS"
RANK_ENV = "KEYSTONE_DIST_RANK"
JOIN_TIMEOUT_ENV = "KEYSTONE_DIST_JOIN_TIMEOUT_S"
PORT_RETRIES_ENV = "KEYSTONE_DIST_PORT_RETRIES"
DISABLE_ENV = "KEYSTONE_DIST_DISABLE"

DEFAULT_JOIN_TIMEOUT_S = 60.0
DEFAULT_PORT_RETRIES = 4

_logger = logging.getLogger("keystone_tpu.distributed")

_lock = threading.Lock()


@dataclasses.dataclass
class GroupState:
    """The live process-group record.  ``jax_initialized`` says whether a
    real ``jax.distributed`` communicator backs it (fit path) or the
    group is keystone-managed membership only (serving fleet)."""

    world: int
    rank: int
    coordinator: str
    jax_initialized: bool = False
    epoch: int = 0  #: bumped by every :func:`reform_group`
    lost: tuple = ()  #: original ranks declared dead across reforms

    def record(self) -> dict:
        return {
            "world": self.world,
            "rank": self.rank,
            "coordinator": self.coordinator,
            "jax": self.jax_initialized,
            "epoch": self.epoch,
            "lost": list(self.lost),
        }


_state: GroupState | None = None
_threads_before_init: frozenset[int] | None = None


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


def _env_int(name: str, default: int | None) -> int | None:
    raw = os.environ.get(name, "").strip()
    try:
        return int(raw) if raw else default
    except ValueError:
        return default


# -- ports and availability ---------------------------------------------------


def pick_port(host: str = "127.0.0.1") -> int:
    """An OS-assigned free TCP port (bind to 0, read, release).  The
    release-to-bind window is racy by nature; the consumer
    (:func:`init_process_group`) retries ``EADDRINUSE`` rather than
    trusting the pick."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, 0))
        return s.getsockname()[1]


def pick_coordinator(host: str = "127.0.0.1") -> str:
    """Auto-picked ``host:port`` coordinator address for a launcher to
    hand every worker."""
    return f"{host}:{pick_port(host)}"


def spawn_available() -> bool:
    """Can this host run the multi-process path at all: POSIX, a usable
    ``sys.executable``, and the loopback port space open.  The ``dist``
    pytest marker and every ``--hosts N`` tool degrade to the
    single-process path when this is False (or ``KEYSTONE_DIST_DISABLE``
    is set) — multi-process is a capability, never a requirement."""
    if os.environ.get(DISABLE_ENV, "").strip() in ("1", "true", "yes"):
        return False
    if os.name != "posix":
        return False
    if not sys.executable or not os.path.exists(sys.executable):
        return False
    try:
        pick_port()
    except OSError:
        return False
    return True


# -- group state --------------------------------------------------------------


def is_initialized() -> bool:
    return _state is not None


def group_state() -> GroupState | None:
    return _state


def process_count() -> int:
    """World size — 1 when no group is configured (no jax import on the
    inert path)."""
    return _state.world if _state is not None else 1


def process_index() -> int:
    """This process's rank — 0 when no group is configured."""
    return _state.rank if _state is not None else 0


# -- bring-up -----------------------------------------------------------------


def _enable_cpu_collectives() -> None:
    """The default CPU backend refuses multi-process computations
    outright; gloo is the collectives implementation that works.  Must
    run before ``jax.distributed.initialize``."""
    import jax

    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception as e:  # non-CPU backends / renamed flag: not fatal
        _logger.debug("cpu collectives config not applied: %s", e)


def init_process_group(
    coordinator: str | None = None,
    world: int | None = None,
    rank: int | None = None,
    *,
    join_timeout_s: float | None = None,
    port_retries: int | None = None,
    use_jax: bool = True,
) -> GroupState:
    """Join (or create) the process group.  Arguments default from the
    ``KEYSTONE_DIST_*`` env; with nothing configured this is an inert
    no-op returning a solo :class:`GroupState` WITHOUT importing jax.

    ``use_jax=True`` runs the real ``jax.distributed.initialize`` under
    the join deadline: the coordinator (rank 0) retries ``EADDRINUSE``
    up to ``port_retries`` times (counted ``dist_port_retry``), and a
    join that outlives ``join_timeout_s`` — a dead coordinator, a peer
    that never arrives — raises typed :class:`DeadlineExceeded` counted
    ``dist_join_timeout``.  ``use_jax=False`` records keystone-level
    membership only (the serving-fleet mode; jax stays host-local)."""
    global _state, _threads_before_init
    with _lock:
        if _state is not None:
            raise RuntimeError(
                f"process group already initialised: {_state.record()} — "
                "shutdown_process_group() first"
            )
        world = world if world is not None else _env_int(PROCS_ENV, None)
        if world is None or world <= 0:
            # Nothing configured: the single-process inert path.
            _state = GroupState(world=1, rank=0, coordinator="", epoch=0)
            return _state
        rank = rank if rank is not None else (_env_int(RANK_ENV, 0) or 0)
        coordinator = coordinator or os.environ.get(COORD_ENV, "").strip()
        if not (0 <= rank < world):
            raise ValueError(f"rank {rank} outside world {world}")
        if world > 1 and not coordinator:
            raise ValueError(
                f"world={world} needs a coordinator address "
                f"({COORD_ENV} or coordinator=)"
            )
        if not coordinator:
            coordinator = pick_coordinator()
        if not use_jax:
            _state = GroupState(world=world, rank=rank, coordinator=coordinator)
            _logger.info("fleet group joined: %s", _state.record())
            return _state

        budget = (
            join_timeout_s
            if join_timeout_s is not None
            else _env_float(JOIN_TIMEOUT_ENV, DEFAULT_JOIN_TIMEOUT_S)
        )
        retries = (
            port_retries
            if port_retries is not None
            else (_env_int(PORT_RETRIES_ENV, DEFAULT_PORT_RETRIES) or 0)
        )
        import jax

        _enable_cpu_collectives()
        _threads_before_init = frozenset(
            id(t) for t in threading.enumerate()
        )
        t0 = time.monotonic()
        attempt = 0
        while True:
            try:
                _join_with_deadline(jax, coordinator, world, rank, budget)
                break
            except DeadlineExceeded:
                counters.record(
                    "dist_join_timeout",
                    f"rank {rank}/{world} join via {coordinator} "
                    f"exceeded {budget:g}s",
                )
                raise
            except Exception as e:
                if is_addr_in_use(e) and rank == 0 and attempt < retries:
                    attempt += 1
                    counters.record(
                        "dist_port_retry",
                        f"coordinator {coordinator} in use "
                        f"(attempt {attempt}/{retries})",
                    )
                    time.sleep(0.05 * attempt)
                    continue
                if _looks_like_timeout(e):
                    counters.record(
                        "dist_join_timeout",
                        f"rank {rank}/{world} join via {coordinator}: {e}",
                    )
                    raise DeadlineExceeded(
                        f"dist_join[{rank}/{world}]", budget
                    ) from e
                raise
        _state = GroupState(
            world=world, rank=rank, coordinator=coordinator,
            jax_initialized=True,
        )
        _logger.info(
            "process group up in %.2fs: %s (%d global devices)",
            time.monotonic() - t0, _state.record(), len(jax.devices()),
        )
        return _state


def _join_with_deadline(jax, coordinator, world, rank, budget) -> None:
    """Run ``jax.distributed.initialize`` under a REAL deadline.

    ``jax.distributed.initialize`` blocks inside ``client.connect()``,
    in C++ where neither SIGALRM nor KeyboardInterrupt can reach, and
    its own deadlines are the wrong shape: the coordinator waiting for a
    peer that never arrives sits under XLA's cluster-register timeout
    (~an hour), and where ``initialization_timeout`` DOES fire (the
    joiner's register RPC) client.h treats it as fatal and terminates
    the process.  So the join runs on a helper thread and THIS thread
    owns the clock: past the budget the caller gets a typed
    :class:`DeadlineExceeded` and the stuck join thread is abandoned
    (daemon — it dies with the process, and a bring-up failure means the
    launcher replaces the process anyway)."""
    box: dict = {}

    def run():
        try:
            # jax's own timeout is pushed PAST ours on purpose: when the
            # C++ RegisterTask deadline fires first, client.h declares it
            # fatal and TERMINATES the process — no Python frame ever
            # sees it.  With the keystone clock in front, the caller gets
            # the typed fault, records it, and decides; a process that
            # lingers with the poisoned client may still be aborted by
            # the late C++ deadline, so a failed bring-up means REPLACE
            # the process, not retry in it.
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=world,
                process_id=rank,
                initialization_timeout=max(1, int(budget * 2)) + 5,
            )
        except BaseException as e:  # noqa: BLE001 — re-raised by caller
            box["error"] = e

    t = threading.Thread(target=run, name=f"dist-join-{rank}", daemon=True)
    t.start()
    t.join(budget)
    if t.is_alive():
        raise DeadlineExceeded(f"dist_join[{rank}/{world}]", budget)
    if "error" in box:
        raise box["error"]


def _looks_like_timeout(e: BaseException) -> bool:
    msg = str(e).lower()
    return any(
        tok in msg
        for tok in ("timed out", "timeout", "deadline exceeded", "unavailable")
    )


def shutdown_process_group(join_timeout_s: float = 5.0) -> list[str]:
    """Leave the group and tear the coordinator/client service down.
    Returns the names of any service threads still alive after
    ``join_timeout_s`` — callers assert ``== []`` the way a stream's
    ``join()`` is asserted, so a leak is a test failure, not a slow
    accumulation.  Idempotent; inert when no group was initialised."""
    global _state, _threads_before_init
    with _lock:
        st, _state = _state, None
        before, _threads_before_init = _threads_before_init, None
    if st is None or not st.jax_initialized:
        return []
    import jax

    try:
        jax.distributed.shutdown()
    except Exception as e:
        counters.record("dist_shutdown_error", str(e))
        raise
    leaked: list[str] = []
    end = time.monotonic() + max(0.0, join_timeout_s)
    while True:
        leaked = [
            t.name
            for t in threading.enumerate()
            if t.is_alive()
            and (before is None or id(t) not in before)
            and t is not threading.current_thread()
        ]
        if not leaked or time.monotonic() >= end:
            break
        time.sleep(0.05)
    if leaked:
        counters.record("dist_thread_leak", ",".join(leaked))
    return leaked


def reform_group(survivors) -> GroupState:
    """Re-form the group as the ``survivors`` (original ranks, order
    fixed across hosts so every survivor derives the same new world).
    Counted ``dist_reform``.  A ``jax.distributed`` communicator is NOT
    re-formed in place — a dead peer has already poisoned the
    coordination client, and touching it (even ``shutdown``) fatally
    aborts the process — so the group downgrades to keystone-managed
    membership and jax work continues HOST-LOCAL; the caller reshards
    state via ``load_pipeline(mesh=)`` and re-anchors its routers."""
    global _state
    with _lock:
        if _state is None:
            raise RuntimeError("no process group to re-form")
        survivors = sorted(int(s) for s in survivors)
        if _state.rank not in survivors:
            raise ValueError(
                f"rank {_state.rank} is not among survivors {survivors}"
            )
        if not all(0 <= s < _state.world for s in survivors):
            raise ValueError(
                f"survivors {survivors} outside world {_state.world}"
            )
        lost = tuple(
            sorted(
                set(range(_state.world)) - set(survivors)
                | set(_state.lost)
            )
        )
        new = GroupState(
            world=len(survivors),
            rank=survivors.index(_state.rank),
            coordinator=_state.coordinator,
            jax_initialized=False,
            epoch=_state.epoch + 1,
            lost=lost,
        )
        counters.record(
            "dist_reform",
            f"world {_state.world}->{new.world} "
            f"rank {_state.rank}->{new.rank} lost={list(lost)}",
        )
        if _state.jax_initialized:
            _logger.warning(
                "leaving poisoned jax.distributed client behind "
                "(peer death; shutdown would abort) — jax is host-local "
                "from here"
            )
        _state = new
        return new


# -- deterministic cross-host reduction ---------------------------------------


def deterministic_allreduce(partial):
    """Sum per-host partials in FIXED rank order — the bit-identity
    primitive.  XLA's cross-process reductions are not bit-identical to
    a single-process run (reduction order differs with topology), so the
    fit path reduces HOST-SIDE: every rank's partial is allgathered
    (exact byte transport, no arithmetic) into a ``(world, ...)`` stack
    and summed by the same ``np.sum(axis=0)`` the single-process
    reference applies to its per-group partials.  Same values, same op,
    same order → bit-identical by construction.  World 1 returns the
    partial unchanged."""
    import numpy as np

    x = np.asarray(partial)
    if _state is None or _state.world <= 1 or not _state.jax_initialized:
        return x
    from jax.experimental import multihost_utils

    stacked = np.asarray(multihost_utils.process_allgather(x))
    if stacked.shape[0] != _state.world:  # pragma: no cover - invariant
        raise RuntimeError(
            f"allgather returned {stacked.shape[0]} parts for world "
            f"{_state.world}"
        )
    return stacked.sum(axis=0)


def barrier(name: str = "keystone") -> None:
    """Cross-host sync point (jax-backed groups only; solo is a no-op)."""
    if _state is None or _state.world <= 1 or not _state.jax_initialized:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


# -- launcher helpers ---------------------------------------------------------


def _xla_flags_with_device_count(flags: str, n: int) -> str:
    """Rewrite ``--xla_force_host_platform_device_count`` in an XLA_FLAGS
    string (workers must not inherit the parent's virtual device count)."""
    kept = [
        tok
        for tok in (flags or "").split()
        if not tok.startswith("--xla_force_host_platform_device_count")
    ]
    kept.append(f"--xla_force_host_platform_device_count={int(n)}")
    return " ".join(kept)


def worker_env(
    rank: int,
    world: int,
    coordinator: str,
    *,
    local_devices: int = 2,
    base: dict | None = None,
) -> dict:
    """Environment for one spawned worker host: CPU platform pinned,
    ``local_devices`` virtual CPU devices (replacing any inherited
    count), and the ``KEYSTONE_DIST_*`` triple set."""
    env = dict(os.environ if base is None else base)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = _xla_flags_with_device_count(
        env.get("XLA_FLAGS", ""), local_devices
    )
    env[COORD_ENV] = coordinator
    env[PROCS_ENV] = str(int(world))
    env[RANK_ENV] = str(int(rank))
    return env
