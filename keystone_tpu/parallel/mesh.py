"""Device mesh + sharding helpers — the execution substrate.

Replaces the reference's Spark-RDD substrate (SURVEY §1 L1): an RDD partition
becomes a shard of a ``jax.Array`` over the mesh's ``data`` axis; the feature
/ model-block dimension (reference nodes/util/VectorSplitter.scala:10-36)
maps to the ``model`` axis.  All cross-device communication is XLA
collectives over ICI — there is no driver/executor split; host Python is the
single controller and device arrays persist in HBM between stages.
"""

from __future__ import annotations

import contextlib
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(data: int | None = None, model: int = 1, devices=None) -> Mesh:
    """Build a (data, model) mesh.  ``data=None`` uses all remaining devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if data is None:
        if n % model != 0:
            raise ValueError(f"{n} devices not divisible by model={model}")
        data = n // model
    if data * model > n:
        raise ValueError(f"mesh {data}x{model} needs {data * model} devices, have {n}")
    arr = np.array(devices[: data * model]).reshape(data, model)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def reduced_mesh(mesh: Mesh) -> Mesh | None:
    """The next rung of the mesh degradation ladder: the SAME devices with
    the ``model`` axis collapsed into ``data`` — ``(data=4, model=2)`` →
    ``(data=8, model=1)``.  Model blocks replicate instead of sharding, and
    in exchange every row-sharded operand (the design matrix, labels, the
    residual — the terms that dominate a solve's per-chip footprint) holds
    half as many rows per chip.  ``None`` when the mesh is already pure
    data-parallel (nothing left to collapse; the ladder's next rung is the
    single-device floor)."""
    if mesh.shape[MODEL_AXIS] <= 1:
        return None
    devices = list(mesh.devices.flat)
    return make_mesh(data=len(devices), model=1, devices=devices)


def mesh_desc(mesh: Mesh) -> str:
    """``'4x2'`` — the (data, model) shape tag used in tier names."""
    return f"{mesh.shape[DATA_AXIS]}x{mesh.shape[MODEL_AXIS]}"


@functools.lru_cache(maxsize=None)
def _mesh_shapes(n_devices: int) -> tuple[tuple[int, int], ...]:
    """Memoized factorization body of :func:`enumerate_mesh_shapes` — the
    device count never changes within a process, yet the placement search
    re-enumerates on every ``fit()``; computing the divisor walk once per
    count keeps that recurring call a dict hit."""
    if n_devices < 1:
        raise ValueError(f"need >= 1 device, got {n_devices}")
    return tuple(
        (d, n_devices // d)
        for d in range(n_devices, 0, -1)
        if n_devices % d == 0
    )


def enumerate_mesh_shapes(n_devices: int) -> list[tuple[int, int]]:
    """Every (data, model) factorization of ``n_devices``, data-major
    descending — the candidate set the placement search (core.autoshard)
    scores instead of the hand ladder's two fixed rungs.  All devices
    participate in every candidate (a smaller mesh never beats a larger one
    on the cost model's axes, and the single-device strategies are their
    own candidates); ``n_devices=1`` is the one-shape list ``[(1, 1)]``,
    and a prime count yields exactly its two degenerate factorizations.
    Memoized per device count (a fresh list is returned per call; the
    cached tuple is never handed out mutable)."""
    return list(_mesh_shapes(n_devices))


#: device tuple -> materialized candidate meshes; a Mesh wraps the device
#: objects themselves, so caching on the exact device identity (same
#: devices, same order) is both safe and the determinism contract.
_mesh_cache: dict[tuple, tuple[Mesh, ...]] = {}


def enumerate_meshes(devices) -> list[Mesh]:
    """:func:`enumerate_mesh_shapes` materialized over a fixed device
    list — the same devices in the same order for every candidate, so two
    searches over one device set enumerate identical meshes (searched-plan
    determinism).  Memoized per device tuple: every ``fit()`` under a mesh
    re-enumerates candidates, and each uncached enumeration costs one jax
    ``Mesh`` construction per factorization."""
    key = tuple(devices)
    cached = _mesh_cache.get(key)
    if cached is None:
        cached = _mesh_cache[key] = tuple(
            make_mesh(data=d, model=m, devices=list(key))
            for d, m in _mesh_shapes(len(key))
        )
    return list(cached)


def mesh_spans_processes(mesh: Mesh) -> bool:
    """Does this mesh place shards on devices owned by OTHER processes?
    After ``jax.distributed`` bring-up ``jax.devices()`` is global, so the
    existing ``make_mesh()`` transparently builds a data axis spanning
    hosts — and every consumer that stages host memory, reads
    ``memory_stats()``, or serves requests must know whether all of the
    mesh is addressable from here.  Always False single-process."""
    me = jax.process_index()
    return any(d.process_index != me for d in mesh.devices.flat)


def host_local_mesh(mesh: Mesh | None = None) -> Mesh:
    """The largest pure-data mesh over THIS process's addressable devices.
    ``mesh`` given: its local sub-mesh (the serving anchor for a host in a
    fleet — engines never span hosts); omitted: all local devices.  Device
    order follows ``jax.local_devices()`` so every host derives the same
    shape for a symmetric fleet."""
    if mesh is None:
        local = list(jax.local_devices())
    else:
        me = jax.process_index()
        local = [d for d in mesh.devices.flat if d.process_index == me]
        if not local:
            raise ValueError(
                f"mesh {mesh_desc(mesh)} has no devices on process {me}"
            )
    return make_mesh(data=len(local), model=1, devices=local)


_current_mesh: list[Mesh] = []


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    """Set the ambient mesh used by estimators when sharding inputs."""
    _current_mesh.append(mesh)
    try:
        yield mesh
    finally:
        _current_mesh.pop()


def current_mesh() -> Mesh | None:
    return _current_mesh[-1] if _current_mesh else None


def row_sharding(mesh: Mesh) -> NamedSharding:
    """Examples sharded over the data axis; features replicated (the RDD analog)."""
    return NamedSharding(mesh, P(DATA_AXIS))


def padded_shard_rows(x, mesh: Mesh | None = None):
    """Pad N up to a multiple of the data-axis size with zero rows, shard,
    return (x, nvalid).

    Zero rows contribute nothing to raw sums, but any estimator that
    *centers* data must be told ``nvalid`` (pad rows become ``-mean`` after
    centering and would pollute grams) — the solvers' ``fit(..., nvalid=)``
    parameter masks pad rows back to zero after centering.
    """
    mesh = mesh or current_mesh()
    n = x.shape[0]
    if mesh is None:
        return jax.device_put(x), n
    d = mesh.shape[DATA_AXIS]
    pad = (-n) % d
    if pad:
        if isinstance(x, jax.Array):
            # Device-resident: pad on device, no host round trip.
            x = jnp.concatenate(
                [x, jnp.zeros((pad,) + tuple(x.shape[1:]), x.dtype)], axis=0
            )
        else:
            # Host input: pad on host so the single device_put below
            # transfers straight into the sharded layout.
            widths = [(0, pad)] + [(0, 0)] * (np.ndim(x) - 1)
            x = np.pad(np.asarray(x), widths)
    return jax.device_put(x, row_sharding(mesh)), n


def parse_mesh(spec: str | None) -> Mesh | None:
    """Parse a ``--mesh`` flag: ``"8"`` -> 8-way data mesh, ``"4x2"`` ->
    (data=4, model=2).  None/empty -> no mesh (single device)."""
    if not spec:
        return None
    parts = spec.lower().split("x")
    if (
        len(parts) > 2
        or not all(p.strip().isdigit() for p in parts)
        or any(int(p) == 0 for p in parts)
    ):
        raise ValueError(
            f"bad --mesh spec {spec!r}: expected 'DATA' or 'DATAxMODEL' "
            "with positive sizes (e.g. '8' or '4x2')"
        )
    data = int(parts[0])
    model = int(parts[1]) if len(parts) > 1 else 1
    return make_mesh(data=data, model=model)


def mask_pad_rows(x, nvalid: int | None):
    """Zero out rows at index >= ``nvalid``.

    Needed after a featurizer that maps zero pad rows to nonzero outputs
    (e.g. ``cos(0·W + b)`` in CosineRandomFeatures) so downstream moment
    sums over the padded batch stay exact."""
    if nvalid is None or x.shape[0] == nvalid:
        return x
    mask = (jnp.arange(x.shape[0]) < nvalid).astype(x.dtype)
    return x * mask.reshape((-1,) + (1,) * (x.ndim - 1))


def pad_shard_inputs(mesh, nvalid: int | None, *arrays):
    """Row-shard ``arrays`` over the data axis with shared zero padding.

    Returns ``(list_of_sharded_arrays, nvalid)`` where ``nvalid`` is the true
    global row count whenever padding was added (callers mask pad rows after
    centering).  The shared fit preamble of the mesh-aware estimators.
    """
    n_true = nvalid if nvalid is not None else arrays[0].shape[0]
    out = [padded_shard_rows(a, mesh)[0] for a in arrays]
    if out and out[0].shape[0] != n_true:
        nvalid = n_true
    return out, nvalid
