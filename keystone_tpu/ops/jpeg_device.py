"""Device-resident JPEG decode: split baseline JPEG at the entropy boundary.

The decode wall (BENCH_r05: ~900 images/sec host decode vs 15-17k
images/sec device featurize) has been attacked three times — threaded
overlap (PR 4), the process pool and the snapshot cache (PR 7) — but the
host still performed ALL pixel work: Huffman entropy decode, dequant,
IDCT, chroma upsample, colorspace.  Only the first of those is inherently
serial bit-twiddling; everything after the entropy decoder is dense
batched linear algebra — exactly what the accelerator is for.  This
module splits the decoder at that boundary:

* **host entropy pass** (:func:`entropy_decode`, numpy + a table-driven
  bit reader): parse markers, Huffman-decode the entropy-coded scan into
  per-component quantized DCT coefficient blocks (`int16`, natural
  order), and emit a :class:`CoeffImage` — coefficients plus a geometry
  descriptor and the image's quantization tables.  No IDCT, no upsample,
  no colorspace: the heavy O(pixels) math never runs on the host.
* **device batch pass** (:func:`decode_batch`, one jitted program per
  geometry): dequantize, 8x8 IDCT (Pallas kernel on TPU,
  interpret-mode/jnp fallback so tier-1 runs on CPU — bit-equal, see
  :func:`idct_blocks`), libjpeg-style *fancy* (triangular) chroma
  upsampling, YCbCr->RGB, clamp/round — pixels are born on device, in
  the same BGR f32 layout :func:`~..loaders.image_loaders.decode_image`
  produces, and can be FUSED straight into a featurize program
  (:func:`fused_apply`) so coefficient batches turn into features in one
  dispatch.

Scope is deliberately the baseline subset (sequential DCT, Huffman, 8-bit,
grayscale or YCbCr with 4:4:4 / 4:2:2 / 4:2:0 sampling, restart markers):
everything else raises a typed :class:`JpegDecodeUnsupported` carrying a
``reason`` so ``core.ingest`` routes it to the host decode path as a
COUNTED ``device_decode_fallback_<reason>`` — never a silent wrong pixel.
Corrupt entropy data (truncated scan, invalid Huffman code, early marker)
raises :class:`JpegEntropyCorrupt` — a typed, counted skip upstream.

Parity contract: device output matches the native libjpeg decoder within
IDCT-rounding tolerance (:data:`GOLDEN_MAX_ABS` / :data:`GOLDEN_MEAN_ABS`)
— the same class of difference ``core.snapshot`` already keys snapshots by
(native-vs-PIL decoders differ in IDCT rounding, so the snapshot key folds
the decoder in; device decode is a third decoder in that sense and the
device-format snapshot tier stores its OWN pixels, see core/snapshot.py).
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import os

import numpy as np

from . import native_entropy

_logger = logging.getLogger(__name__)

#: Golden-parity tolerance vs the host (libjpeg/PIL) decoder, in 8-bit
#: sample levels.  Budget: libjpeg's fixed-point ``jpeg_idct_islow`` is
#: IEEE-1180-accurate (~±1) on conforming blocks, fancy upsampling and the
#: fixed-point color conversion each round within ±1 — but heavily
#: quantized noise blocks whose IDCT overshoots [0, 255] sit outside the
#: 1180 test range, where the fixed-point path drifts a few more levels
#: from the exact float IDCT (measured max 6 over the bench corpus at
#: quality 85).  The MEAN bound is the tight one; the max bound budgets
#: the clamp-corner outliers.
GOLDEN_MAX_ABS = 8.0
GOLDEN_MEAN_ABS = 1.0

#: ``KEYSTONE_PALLAS_IDCT``: ``1`` forces the Pallas IDCT kernel (interpret
#: mode off-TPU), ``0`` forces the jnp einsum path; unset = Pallas on TPU
#: backends, jnp elsewhere (interpret mode is a correctness oracle, not a
#: fast path — tier-1 asserts the two bit-equal).
PALLAS_IDCT_ENV = "KEYSTONE_PALLAS_IDCT"

#: ``KEYSTONE_NATIVE_ENTROPY``: ``0`` forces the pure-Python entropy pass;
#: unset/anything else lazy-builds the native loop (ops/native_entropy)
#: and degrades to Python counted when the toolchain is absent.  Both
#: passes are bit-identical over the supported subset (tier-1 asserts it
#: whenever the toolchain is available).
NATIVE_ENTROPY_ENV = native_entropy.NATIVE_ENTROPY_ENV

def _zigzag_order() -> np.ndarray:
    """zigzag scan position -> natural (row-major) position within the
    8x8 (built by walking the pattern — a 64-entry literal is unreadable
    and unverifiable by eye)."""
    order = np.empty(64, np.int32)
    row = col = 0
    for k in range(64):
        order[k] = row * 8 + col
        if (row + col) % 2 == 0:  # moving up-right
            if col == 7:
                row += 1
            elif row == 0:
                col += 1
            else:
                row -= 1
                col += 1
        else:  # moving down-left
            if row == 7:
                col += 1
            elif col == 0:
                row += 1
            else:
                row += 1
                col -= 1
    return order


ZIGZAG = _zigzag_order()


class JpegDecodeUnsupported(ValueError):
    """The stream is a JPEG the device path does not claim (progressive,
    arithmetic-coded, CMYK, exotic subsampling, 12-bit, multi-scan...).
    Carries ``reason`` — a short slug the ingest fallback counter is keyed
    by (``device_decode_fallback_<reason>``)."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(f"{reason}: {detail}" if detail else reason)
        self.reason = reason


class JpegEntropyCorrupt(ValueError):
    """The entropy-coded scan is damaged (truncated data, invalid Huffman
    code, a marker where MCUs should be, coefficient overrun).  The caller
    must skip-and-count — decoding further would fabricate pixels."""


@dataclasses.dataclass(frozen=True)
class JpegGeometry:
    """Everything the DEVICE stage needs that is shape-static: images with
    equal geometry batch into one jitted decode program (quant tables ride
    as per-image data — quality may vary within a batch)."""

    height: int
    width: int
    #: per-component (h, v) sampling factors, e.g. ((2, 2), (1, 1), (1, 1))
    sampling: tuple
    #: per-component padded block-grid shape (blocks_y, blocks_x)
    block_shape: tuple

    @property
    def n_components(self) -> int:
        return len(self.sampling)

    def coeff_shapes(self) -> tuple:
        """Per-component coefficient array shapes [by, bx, 8, 8]."""
        return tuple((by, bx, 8, 8) for by, bx in self.block_shape)

    def coeff_bytes(self) -> int:
        """int16 coefficient payload bytes for ONE image — the wire cost
        of the entropy-boundary split (telemetry: ``ingest_coeff_bytes``)."""
        return sum(by * bx * 64 * 2 for by, bx in self.block_shape)


@dataclasses.dataclass
class CoeffImage:
    """One entropy-decoded image: quantized coefficients + geometry."""

    geom: JpegGeometry
    #: per-component [by, bx, 8, 8] int16, natural (row-major) order
    coeffs: tuple
    #: [ncomp, 8, 8] float32 dequant tables (natural order)
    qt: np.ndarray


# -- host entropy pass ---------------------------------------------------------


class _HuffLUT:
    """Canonical Huffman table compiled to a 16-bit-peek lookup: one index
    decodes (symbol, code length) — the classic libjpeg fast path, built
    once per table per image.  Stored as ``bytes`` (not ndarrays): the
    scan loop indexes them per symbol, and ``bytes[i]`` is a plain int at
    a fraction of a numpy scalar's cost."""

    __slots__ = ("length_b", "symbol_b")

    def __init__(self, counts: np.ndarray, symbols: np.ndarray):
        length = np.zeros(1 << 16, np.uint8)
        symbol = np.zeros(1 << 16, np.uint8)
        code = 0
        k = 0
        for bits in range(1, 17):
            n = int(counts[bits - 1])
            for _ in range(n):
                if code >= (1 << bits):
                    raise JpegEntropyCorrupt(
                        f"overfull Huffman table at code length {bits}"
                    )
                lo = code << (16 - bits)
                hi = lo + (1 << (16 - bits))
                length[lo:hi] = bits
                symbol[lo:hi] = symbols[k]
                code += 1
                k += 1
            code <<= 1
        self.length_b = length.tobytes()
        self.symbol_b = symbol.tobytes()


@functools.lru_cache(maxsize=64)
def _huff_lut(counts: bytes, symbols: bytes) -> _HuffLUT:
    """LUT compilation cached by table content: most encoders emit the
    Annex-K standard tables, so a tar of thousands of JPEGs compiles four
    LUTs once instead of four per image."""
    return _HuffLUT(
        np.frombuffer(counts, np.uint8), np.frombuffer(symbols, np.uint8)
    )


def _decode_scan(
    segments, planes, mcu_blocks, ncomp, mcus_x, total_mcus, interval
):
    """The hot loop: Huffman-decode every MCU of the (already unstuffed,
    restart-split) scan into the per-component coefficient planes.

    Deliberately ONE function with the bit reader inlined as plain locals
    (acc/accbits/pos) and the Huffman LUTs indexed as ``bytes`` — this is
    the only O(compressed-bytes) Python in the device-decode path, and
    attribute access per symbol costs more than the decode itself.  Running
    out of bits or hitting an invalid code raises
    :class:`JpegEntropyCorrupt` (libjpeg pads with 1s and warns; this
    path's contract is typed-or-correct, so a truncated scan is an error,
    not a grey image)."""
    zz = ZIGZAG.tolist()
    flat = [p.reshape(-1, 64) for p in planes]
    row_width = [p.shape[1] for p in planes]
    from_bytes = int.from_bytes
    mcu = 0
    for seg_bytes in segments:
        acc = 0
        accbits = 0
        pos = 0
        nbytes = len(seg_bytes)
        preds = [0] * ncomp
        seg_end = min(mcu + interval, total_mcus)
        while mcu < seg_end:
            my, mx = divmod(mcu, mcus_x)
            for ci, v, h, by, bx, dc_lut, ac_lut in mcu_blocks:
                row = flat[ci][
                    (my * v + by) * row_width[ci] + mx * h + bx
                ]
                pred = preds[ci]
                lenb, symb = dc_lut.length_b, dc_lut.symbol_b
                ac = False
                k = 0
                while True:
                    # -- decode one Huffman symbol ------------------------
                    if accbits < 16 and pos < nbytes:
                        take = seg_bytes[pos : pos + 6]
                        acc = (acc << (8 * len(take))) | from_bytes(
                            take, "big"
                        )
                        accbits += 8 * len(take)
                        pos += len(take)
                    peek = (
                        (acc << (16 - accbits))
                        if accbits < 16
                        else (acc >> (accbits - 16))
                    ) & 0xFFFF
                    nb = lenb[peek]
                    if nb == 0 or nb > accbits:
                        raise JpegEntropyCorrupt(
                            "invalid Huffman code or truncated scan "
                            f"(mcu {mcu}/{total_mcus})"
                        )
                    accbits -= nb
                    acc &= (1 << accbits) - 1
                    sym = symb[peek]
                    # -- interpret it ------------------------------------
                    if ac:
                        run, size = sym >> 4, sym & 0xF
                        if size == 0:
                            if run == 15:
                                k += 16
                                if k > 63:
                                    raise JpegEntropyCorrupt(
                                        "ZRL overflows the block"
                                    )
                                continue
                            break  # EOB
                        k += run + 1
                        if k > 63:
                            raise JpegEntropyCorrupt(
                                "AC run overflows the block"
                            )
                    else:
                        size = sym
                        if size > 15:
                            raise JpegEntropyCorrupt(
                                f"DC category {size} out of range"
                            )
                    # -- receive the value bits --------------------------
                    val = 0
                    if size:
                        if accbits < size:
                            take = seg_bytes[pos : pos + 6]
                            acc = (acc << (8 * len(take))) | from_bytes(
                                take, "big"
                            )
                            accbits += 8 * len(take)
                            pos += len(take)
                            if accbits < size:
                                raise JpegEntropyCorrupt(
                                    "truncated scan mid-coefficient"
                                )
                        accbits -= size
                        val = (acc >> accbits) & ((1 << size) - 1)
                        acc &= (1 << accbits) - 1
                        if val < (1 << (size - 1)):  # EXTEND
                            val = val - (1 << size) + 1
                    if ac:
                        row[zz[k]] = val
                        if k == 63:
                            break
                    else:
                        pred += val
                        if not -32768 <= pred <= 32767:
                            # only reachable on a damaged stream: a valid
                            # baseline DC predictor is 11-bit — raise
                            # typed instead of numpy's OverflowError
                            raise JpegEntropyCorrupt(
                                "DC predictor out of int16 range"
                            )
                        row[0] = pred
                        ac = True
                        lenb, symb = ac_lut.length_b, ac_lut.symbol_b
                preds[ci] = pred
            mcu += 1
    if mcu != total_mcus:
        raise JpegEntropyCorrupt(
            f"decoded {mcu} of {total_mcus} MCUs (truncated scan)"
        )


_native_fallback_logged = False


def _run_scan(
    segments, planes, mcu_blocks, ncomp, mcus_x, total_mcus, interval,
    backend,
):
    """Backend dispatch for the scan hot loop — returns the backend that
    actually ran (``"native"`` / ``"python"``).

    ``backend=None`` (production) prefers the native loop when the
    ``KEYSTONE_NATIVE_ENTROPY`` gate allows it and the library builds,
    and otherwise runs the pure-Python pass — bit-equal by contract.  An
    UNEXPECTED native failure (not a typed corrupt-stream error) degrades
    this one image to the Python pass, counted ``native_entropy_fallback``
    — never a crash, never a silent difference.  Explicit ``"native"`` /
    ``"python"`` pin a backend for tests and benches; a pinned native
    backend raises rather than degrade, so parity harnesses cannot
    silently compare Python against itself.

    ``native_entropy.decode_scan`` is resolved as a module attribute at
    call time so the chaos harness can inject failures at the boundary.
    """
    if backend == "python":
        _decode_scan(
            segments, planes, mcu_blocks, ncomp, mcus_x, total_mcus,
            interval,
        )
        return "python"
    if backend == "native":
        if not native_entropy.decode_scan(
            segments, planes, mcu_blocks, ncomp, mcus_x, total_mcus,
            interval,
        ):
            raise RuntimeError(
                "entropy backend pinned to 'native' but the native "
                "library is unavailable (check g++ / "
                f"{NATIVE_ENTROPY_ENV})"
            )
        return "native"
    if backend is not None:
        raise ValueError(f"unknown entropy backend {backend!r}")
    if native_entropy.enabled():
        try:
            if native_entropy.decode_scan(
                segments, planes, mcu_blocks, ncomp, mcus_x, total_mcus,
                interval,
            ):
                return "native"
        except JpegEntropyCorrupt:
            raise  # typed classification — identical to the Python pass
        except Exception as exc:  # noqa: BLE001 — degrade, never crash
            global _native_fallback_logged
            if not _native_fallback_logged:
                _native_fallback_logged = True
                _logger.warning(
                    "native entropy decode failed (%s: %s); this image "
                    "degrades to the pure-Python pass (counted "
                    "native_entropy_fallback; logged once)",
                    type(exc).__name__, exc,
                )
            try:
                from ..core.resilience import counters

                counters.record(
                    "native_entropy_fallback",
                    f"{type(exc).__name__}: {exc}",
                )
            except Exception:  # noqa: BLE001
                pass
            # the native call may have written a partial image before
            # failing — re-zero so the Python re-decode starts clean
            for p in planes:
                p[...] = 0
    _decode_scan(
        segments, planes, mcu_blocks, ncomp, mcus_x, total_mcus, interval
    )
    return "python"


def entropy_backend() -> str:
    """The backend the auto dispatch would pick right now (``"native"`` /
    ``"python"``) — for bench records and ingest telemetry.  Triggers the
    lazy native build, so call it from setup paths, not per image."""
    return "native" if native_entropy.available() else "python"


def _u16(data: bytes, i: int) -> int:
    return (data[i] << 8) | data[i + 1]


@dataclasses.dataclass
class _Frame:
    height: int = 0
    width: int = 0
    comps: list = dataclasses.field(default_factory=list)  # (id, h, v, tq)
    restart_interval: int = 0
    qt: dict = dataclasses.field(default_factory=dict)  # tq -> [64] u16 zigzag
    huff_dc: dict = dataclasses.field(default_factory=dict)
    huff_ac: dict = dataclasses.field(default_factory=dict)
    scan_comps: list = dataclasses.field(default_factory=list)  # (ci, td, ta)
    scan_at: int = 0  # offset of first entropy-coded byte
    adobe_transform: int | None = None  # APP14 color transform, if present


_SUPPORTED_LUMA = {(1, 1), (2, 1), (2, 2)}


def _parse_headers(data: bytes) -> _Frame:
    if len(data) < 4 or data[0] != 0xFF or data[1] != 0xD8:
        raise JpegDecodeUnsupported("not_jpeg", "missing SOI marker")
    f = _Frame()
    i = 2
    n = len(data)
    while True:
        # seek the next marker (fill bytes 0xFF may repeat)
        while i < n and data[i] != 0xFF:
            i += 1
        while i < n and data[i] == 0xFF:
            i += 1
        if i >= n:
            raise JpegEntropyCorrupt("ran out of data before SOS")
        marker = data[i]
        i += 1
        if marker in (0x01,) or 0xD0 <= marker <= 0xD8:
            continue  # standalone markers
        if marker == 0xD9:
            raise JpegEntropyCorrupt("EOI before any scan data")
        if i + 2 > n:
            raise JpegEntropyCorrupt("truncated marker segment header")
        seg_len = _u16(data, i)
        if seg_len < 2 or i + seg_len > n:
            raise JpegEntropyCorrupt(f"truncated segment FF{marker:02X}")
        seg = data[i + 2 : i + seg_len]
        i += seg_len
        if marker == 0xC2:
            raise JpegDecodeUnsupported("progressive")
        if marker in (0xC9, 0xCA, 0xCB, 0xCD, 0xCE, 0xCF):
            raise JpegDecodeUnsupported("arithmetic")
        if marker in (0xC3, 0xC5, 0xC6, 0xC7):
            raise JpegDecodeUnsupported(
                "sof_unsupported", f"SOF marker FF{marker:02X}"
            )
        if marker in (0xC0, 0xC1):  # baseline / extended sequential Huffman
            if seg[0] != 8:
                raise JpegDecodeUnsupported(
                    "precision", f"{seg[0]}-bit samples"
                )
            f.height = _u16(seg, 1)
            f.width = _u16(seg, 3)
            ncomp = seg[5]
            if ncomp == 4:
                raise JpegDecodeUnsupported("cmyk", "4-component frame")
            if ncomp not in (1, 3):
                raise JpegDecodeUnsupported(
                    "components", f"{ncomp}-component frame"
                )
            for c in range(ncomp):
                cid, hv, tq = seg[6 + 3 * c : 9 + 3 * c]
                f.comps.append((cid, hv >> 4, hv & 0xF, tq))
            if ncomp == 3 and tuple(c[0] for c in f.comps) == (
                0x52, 0x47, 0x42,
            ):
                # component ids spell "RGB": channels are stored RGB, and
                # the YCbCr matrix below would hue-shift them silently
                raise JpegDecodeUnsupported(
                    "rgb_colorspace", "RGB component ids"
                )
            if ncomp == 3:
                (_, h0, v0, _), (_, h1, v1, _), (_, h2, v2, _) = f.comps
                if (
                    (h0, v0) not in _SUPPORTED_LUMA
                    or (h1, v1) != (1, 1)
                    or (h2, v2) != (1, 1)
                ):
                    raise JpegDecodeUnsupported(
                        "subsampling",
                        f"Y={h0}x{v0} Cb={h1}x{v1} Cr={h2}x{v2}",
                    )
            continue
        if marker == 0xDB:  # DQT — possibly several tables per segment
            j = 0
            while j < len(seg):
                pq, tq = seg[j] >> 4, seg[j] & 0xF
                j += 1
                if pq == 0:
                    f.qt[tq] = np.frombuffer(
                        seg, np.uint8, 64, j
                    ).astype(np.uint16)
                    j += 64
                else:
                    f.qt[tq] = np.frombuffer(
                        seg[j : j + 128], ">u2", 64
                    ).astype(np.uint16)
                    j += 128
            continue
        if marker == 0xC4:  # DHT
            j = 0
            while j < len(seg):
                tc, th = seg[j] >> 4, seg[j] & 0xF
                counts = np.frombuffer(seg, np.uint8, 16, j + 1)
                total = int(counts.sum())
                table = _huff_lut(
                    bytes(counts), seg[j + 17 : j + 17 + total]
                )
                (f.huff_dc if tc == 0 else f.huff_ac)[th] = table
                j += 17 + total
            continue
        if marker == 0xDD:  # DRI
            f.restart_interval = _u16(seg, 0)
            continue
        if marker == 0xEE and seg[:5] == b"Adobe" and len(seg) >= 12:
            f.adobe_transform = seg[11]
            continue
        if marker == 0xDA:  # SOS
            ns = seg[0]
            if not f.comps:
                raise JpegEntropyCorrupt("SOS before SOF")
            if len(f.comps) == 3 and f.adobe_transform == 0:
                # Adobe APP14 transform=0: three components stored RGB —
                # the YCbCr conversion would silently hue-shift them
                raise JpegDecodeUnsupported(
                    "rgb_colorspace", "Adobe APP14 transform=0"
                )
            if ns != len(f.comps):
                raise JpegDecodeUnsupported(
                    "multi_scan", f"{ns} of {len(f.comps)} components in scan"
                )
            for s in range(ns):
                cs, tdta = seg[1 + 2 * s : 3 + 2 * s]
                ci = next(
                    (k for k, c in enumerate(f.comps) if c[0] == cs), None
                )
                if ci is None:
                    raise JpegEntropyCorrupt(
                        f"scan names unknown component {cs}"
                    )
                f.scan_comps.append((ci, tdta >> 4, tdta & 0xF))
            ss, se = seg[1 + 2 * ns], seg[2 + 2 * ns]
            if (ss, se) != (0, 63):
                raise JpegDecodeUnsupported(
                    "spectral_selection", f"Ss={ss} Se={se}"
                )
            f.scan_at = i
            return f
        # APPn / COM / anything else: skipped


def _split_scan(data: bytes, start: int) -> list[bytes]:
    """Slice the entropy-coded data into UNSTUFFED restart segments.
    ``0xFF00`` is byte stuffing (kept as a data ``0xFF``), ``0xFFD0-D7``
    are restart markers (segment boundaries), any other marker ends the
    scan."""
    arr = np.frombuffer(data, np.uint8, len(data) - start, start)
    ff = np.flatnonzero(arr[:-1] == 0xFF)
    nxt = arr[ff + 1]
    segments: list[bytes] = []
    raw = arr.tobytes()
    seg_start = 0
    end = len(raw)
    cut_points: list[int] = []
    for pos, code in zip(ff.tolist(), nxt.tolist()):
        if pos < seg_start:
            continue  # inside an already-consumed marker pair
        if code == 0x00:
            continue  # stuffing, handled by the replace below
        if code == 0xFF:
            continue  # fill byte; the NEXT 0xFF position classifies it
        if 0xD0 <= code <= 0xD7:
            cut_points.append(pos)
            seg_start = pos + 2
            continue
        end = pos  # real marker: scan ends here
        break
    out = []
    prev = 0
    for cut in cut_points:
        if cut >= end:
            break
        out.append(raw[prev:cut].replace(b"\xff\x00", b"\xff"))
        prev = cut + 2
    out.append(raw[prev:end].replace(b"\xff\x00", b"\xff"))
    return out


def entropy_decode(data: bytes, *, backend: str | None = None) -> CoeffImage:
    """Baseline-JPEG bytes -> :class:`CoeffImage` (host entropy pass only).

    Raises :class:`JpegDecodeUnsupported` (typed fallback routing) for
    streams outside the claimed subset and :class:`JpegEntropyCorrupt`
    (typed counted skip) for damaged scans.

    ``backend`` pins the scan hot loop: ``"native"`` (the lazily-built C
    loop, raises if unbuildable), ``"python"`` (the portable pass), or
    ``None`` — native when available, Python otherwise, bit-identical
    output either way (see :func:`_run_scan`)."""
    f = _parse_headers(data)
    ncomp = len(f.comps)
    hmax = max(c[1] for c in f.comps)
    vmax = max(c[2] for c in f.comps)
    mcus_x = -(-f.width // (8 * hmax))
    mcus_y = -(-f.height // (8 * vmax))
    if f.height == 0 or f.width == 0:
        raise JpegEntropyCorrupt("zero-sized frame")

    # per-component coefficient planes, MCU-padded, zigzag written flat
    planes = []
    qts = np.zeros((ncomp, 8, 8), np.float32)
    for k, (_cid, h, v, tq) in enumerate(f.comps):
        planes.append(np.zeros((mcus_y * v, mcus_x * h, 64), np.int16))
        if tq not in f.qt:
            raise JpegEntropyCorrupt(f"missing quant table {tq}")
        nat = np.zeros(64, np.float32)
        nat[ZIGZAG] = f.qt[tq].astype(np.float32)
        qts[k] = nat.reshape(8, 8)

    for ci, td, ta in f.scan_comps:
        if td not in f.huff_dc or ta not in f.huff_ac:
            raise JpegEntropyCorrupt(
                f"scan references missing Huffman table dc={td} ac={ta}"
            )

    segments = _split_scan(data, f.scan_at)
    total_mcus = mcus_x * mcus_y
    interval = f.restart_interval or total_mcus
    expected_segments = -(-total_mcus // interval)
    if len(segments) < expected_segments:
        raise JpegEntropyCorrupt(
            f"scan holds {len(segments)} restart segment(s), geometry "
            f"needs {expected_segments}"
        )

    # per-MCU (component, block-row, block-col, dc_lut, ac_lut) unrolled
    # once so the hot loop below carries no per-block geometry arithmetic
    mcu_blocks = []
    for ci, td, ta in f.scan_comps:
        _cid, h, v, _tq = f.comps[ci]
        for by in range(v):
            for bx in range(h):
                mcu_blocks.append(
                    (ci, v, h, by, bx, f.huff_dc[td], f.huff_ac[ta])
                )
    _run_scan(
        segments[:expected_segments], planes, mcu_blocks, ncomp,
        mcus_x, total_mcus, interval, backend,
    )

    geom = JpegGeometry(
        height=f.height,
        width=f.width,
        sampling=tuple((h, v) for _cid, h, v, _tq in f.comps),
        block_shape=tuple(p.shape[:2] for p in planes),
    )
    coeffs = tuple(
        p.reshape(p.shape[0], p.shape[1], 8, 8) for p in planes
    )
    return CoeffImage(geom=geom, coeffs=coeffs, qt=qts)


# -- device batch pass ---------------------------------------------------------


@functools.lru_cache(maxsize=1)
def _idct_basis() -> np.ndarray:
    """Orthonormal 8-point DCT-II basis A (A @ A.T = I): spatial samples
    x = A.T @ X @ A for coefficient block X."""
    k = np.arange(8)[:, None].astype(np.float64)
    n = np.arange(8)[None, :].astype(np.float64)
    a = np.cos((2 * n + 1) * k * np.pi / 16.0) * 0.5
    a[0] *= 1.0 / np.sqrt(2.0)
    return a.astype(np.float32)


def _pallas_wanted() -> bool:
    raw = os.environ.get(PALLAS_IDCT_ENV, "").strip()
    if raw == "1":
        return True
    if raw == "0":
        return False
    import jax

    return jax.default_backend() == "tpu"


def idct_blocks_jnp(blocks):
    """[..., 8, 8] dequantized coefficients -> spatial samples (no level
    shift) — the reference path the Pallas kernel must bit-match."""
    import jax.numpy as jnp

    a = jnp.asarray(_idct_basis())
    return jnp.einsum(
        "ij,...jk,kl->...il", a.T, blocks, a,
        preferred_element_type=jnp.float32,
    )


def _idct_kernel(a_ref, x_ref, o_ref):
    import jax.numpy as jnp

    a = a_ref[...]
    o_ref[...] = jnp.einsum(
        "ij,bjk,kl->bil", a.T, x_ref[...], a,
        preferred_element_type=jnp.float32,
    )


def idct_blocks_pallas(blocks, *, blocks_per_step: int = 256,
                       interpret: bool | None = None):
    """Pallas IDCT over [..., 8, 8] blocks: grid over tiles of
    ``blocks_per_step`` 8x8 blocks, same einsum as :func:`idct_blocks_jnp`
    inside the kernel (bit-equal in interpret mode by construction).
    ``interpret=None`` resolves to interpret off-TPU."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    lead = blocks.shape[:-2]
    nb = int(np.prod(lead)) if lead else 1
    x = blocks.reshape(nb, 8, 8)
    b = min(blocks_per_step, nb) or 1
    pad = (-nb) % b
    if pad:
        x = jnp.concatenate(
            [x, jnp.zeros((pad, 8, 8), x.dtype)], axis=0
        )
    out = pl.pallas_call(
        _idct_kernel,
        grid=((nb + pad) // b,),
        in_specs=[
            pl.BlockSpec((8, 8), lambda i: (0, 0)),
            pl.BlockSpec((b, 8, 8), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((b, 8, 8), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((nb + pad, 8, 8), jnp.float32),
        interpret=interpret,
    )(jnp.asarray(_idct_basis()), x)
    return out[:nb].reshape(*lead, 8, 8)


def idct_blocks(blocks):
    """The production chooser: Pallas on TPU (or ``KEYSTONE_PALLAS_IDCT=1``
    anywhere, interpret mode off-TPU), jnp einsum otherwise."""
    if _pallas_wanted():
        return idct_blocks_pallas(blocks)
    return idct_blocks_jnp(blocks)


def _upsample2_h(plane):
    """libjpeg ``h2v1`` fancy (triangular) upsample along the last axis:
    out[2i] = (3*s[i] + s[i-1]) / 4, out[2i+1] = (3*s[i] + s[i+1]) / 4,
    edges replicated."""
    import jax.numpy as jnp

    left = jnp.concatenate([plane[..., :1], plane[..., :-1]], axis=-1)
    right = jnp.concatenate([plane[..., 1:], plane[..., -1:]], axis=-1)
    even = (3.0 * plane + left) * 0.25
    odd = (3.0 * plane + right) * 0.25
    out = jnp.stack([even, odd], axis=-1)
    return out.reshape(*plane.shape[:-1], plane.shape[-1] * 2)


def _upsample2_v(plane):
    import jax.numpy as jnp

    up = jnp.swapaxes(_upsample2_h(jnp.swapaxes(plane, -1, -2)), -1, -2)
    return up


def _blocks_to_plane(x):
    """[B, by, bx, 8, 8] -> [B, by*8, bx*8]."""
    b, by, bx = x.shape[:3]
    return x.transpose(0, 1, 3, 2, 4).reshape(b, by * 8, bx * 8)


def _decode_pixels(geom: JpegGeometry, coeffs, qt):
    """The jitted body: coefficient arrays (+ per-image quant tables) ->
    [B, H, W, 3] BGR f32 pixel batch, integral values in [0, 255]."""
    import jax.numpy as jnp

    h_img, w_img = geom.height, geom.width
    hmax = max(h for h, _v in geom.sampling)
    vmax = max(v for _h, v in geom.sampling)
    planes = []
    for c in range(geom.n_components):
        x = coeffs[c].astype(jnp.float32) * qt[:, c][:, None, None]
        x = idct_blocks(x) + 128.0
        plane = _blocks_to_plane(x)
        ch, cv = geom.sampling[c]
        # crop to the component's true sample grid BEFORE upsampling: the
        # MCU pad region holds encoder filler whose values must not bleed
        # into real pixels through the triangular filter
        comp_h = -(-h_img * cv // vmax)
        comp_w = -(-w_img * ch // hmax)
        plane = plane[:, :comp_h, :comp_w]
        if ch < hmax:
            plane = _upsample2_h(plane)
        if cv < vmax:
            plane = _upsample2_v(plane)
        planes.append(plane[:, :h_img, :w_img])
    y = planes[0]
    if geom.n_components == 1:
        rgb = (y, y, y)
    else:
        cb = planes[1] - 128.0
        cr = planes[2] - 128.0
        rgb = (
            y + 1.40200 * cr,
            y - 0.344136 * cb - 0.714136 * cr,
            y + 1.77200 * cb,
        )
    # BGR channel order + round-to-integral — the decode_image contract
    bgr = jnp.stack([rgb[2], rgb[1], rgb[0]], axis=-1)
    return jnp.clip(jnp.round(bgr), 0.0, 255.0).astype(jnp.float32)


@functools.lru_cache(maxsize=256)
def _decode_jit(geom: JpegGeometry):
    import jax

    return jax.jit(functools.partial(_decode_pixels, geom))


def decode_batch(geom: JpegGeometry, coeffs, qt):
    """Batched device decode: per-component coefficient arrays
    ([B, by, bx, 8, 8], int16 or f32, host or device) + [B, ncomp, 8, 8]
    quant tables -> [B, H, W, 3] BGR f32 pixels.  One compiled program per
    geometry (cached)."""
    return _decode_jit(geom)(tuple(coeffs), qt)


def stack_coeff_images(images: list) -> tuple:
    """Stack same-geometry :class:`CoeffImage`s into the batched arrays
    ``decode_batch`` consumes: ``(coeffs_tuple, qt)``."""
    geom = images[0].geom
    coeffs = tuple(
        np.stack([img.coeffs[c] for img in images])
        for c in range(geom.n_components)
    )
    qt = np.stack([img.qt for img in images])
    return coeffs, qt


# -- fused decode+featurize ----------------------------------------------------


#: transform -> {geometry -> (fused_jit, admitted)}.  Keyed on the
#: transform OBJECT (not id(): a dead transform's id can be reissued to a
#: new callable, which would silently serve the old fused program) with
#: STRONG references and oldest-first eviction at a small cap — weak
#: keying cannot work here because the cached fused jit closes over the
#: transform, so the value would keep its own key alive forever (an
#: unbounded leak across short-lived transforms).
_fused_cache: dict = {}
_FUSED_CACHE_MAX = 64


def fused_apply(transform, geom: JpegGeometry, coeffs, qt, *,
                label: str = "stream"):
    """Run ``transform(pixels)`` with the device decode FUSED in: one
    jitted program turns coefficient arrays into features — XLA sees
    dequant, IDCT, upsample, colorspace, and the featurize as a single
    module, so pixels never round-trip through HBM-resident f32 batches
    between two dispatches.

    The fused program is HBM-admitted once per (transform, geometry)
    through ``core.memory.plan_program`` (the fused decode+featurize is
    what actually resides during a device-decode epoch); a denial is
    counted (``device_decode_admission_denied``) and degrades to the
    two-dispatch path — decode, then featurize — whose peak is smaller
    because the coefficient buffers die before the featurize runs."""
    import jax

    try:
        per_transform = _fused_cache.get(transform)
        if per_transform is None:
            while len(_fused_cache) >= _FUSED_CACHE_MAX:
                _fused_cache.pop(next(iter(_fused_cache)))
            per_transform = _fused_cache[transform] = {}
    except TypeError:
        # unhashable transform: fuse without caching (recompiles per
        # chunk — correct, just slower)
        per_transform = {}
    entry = per_transform.get(geom)
    if entry is None:
        fused = jax.jit(
            lambda c, q: transform(_decode_pixels(geom, c, q))
        )
        from ..core import memory as kmem
        from ..core.resilience import counters

        sds = (
            tuple(
                jax.ShapeDtypeStruct(
                    (qt.shape[0],) + s, np.dtype(np.int16)
                )
                for s in geom.coeff_shapes()
            ),
            jax.ShapeDtypeStruct(tuple(qt.shape), np.dtype(np.float32)),
        )
        try:
            plan = kmem.plan_program(
                fused, *sds,
                label=f"device_decode+featurize:{label}",
            )
            admitted = plan.admitted
        except Exception:  # noqa: BLE001 — planning must never kill decode
            admitted = True
        if not admitted:
            counters.record(
                "device_decode_admission_denied",
                f"{label}: fused decode+featurize denied at "
                f"{geom.height}x{geom.width} — running unfused",
            )
        entry = (fused, admitted)
        per_transform[geom] = entry
    fused, admitted = entry
    if not admitted:
        return transform(decode_batch(geom, coeffs, qt))
    from ..core import profiler as kprof

    if not kprof.enabled():
        return fused(tuple(coeffs), qt)
    # Device cost attribution (ISSUE 14): the fused decode+featurize
    # dispatch lands in the per-program MFU ledger with a synced wall
    # (cost memoized per (fused jit, geometry)).  Syncing serializes the
    # consumer's double buffer for this chunk — profiling costs
    # pipelining, never correctness (values unchanged; the
    # profiler_crash chaos family pins bit-equality).
    return kprof.attributed_call(
        f"fused_decode:{label}:{geom.height}x{geom.width}",
        geom, fused, tuple(coeffs), qt,
    )
