"""Fisher vector encoding (reference
src/main/scala/nodes/images/external/FisherVector.scala:14-35, delegating to
the vendored enceval ``fisher<float>`` with alpha=1.0, pnorm=0 —
src/main/cpp/EncEval.cxx:67-69,97).

Improved-FV formulation (Perronnin et al.), mean and variance gradients only
(the enceval output length is exactly ``2·d·K``, EncEval.cxx:41):

    G_μk = (1/(N√π_k)) Σ_n q_nk (x_n − μ_k)/σ_k
    G_σk = (1/(N√(2π_k))) Σ_n q_nk [((x_n − μ_k)/σ_k)² − 1]

alpha=1 / pnorm=0 mean *no* power- or L2-normalization inside the encoder —
the pipelines apply SignedHellinger + NormalizeRows as separate nodes
(reference ImageNetSiftLcsFV.scala:29-39), exactly as here.

Output layout matches the reference wrapper: ``[d, 2K]`` per image — columns
0..K-1 the mean gradients, K..2K-1 the variance gradients
(FisherVector.scala:33-34 wraps the flat enceval buffer as
DenseMatrix(numDims, numCentroids*2)).

TPU-native: posteriors are one [n, k] gemm + softmax; the sufficient
statistics (s0, s1, s2) are three gemms; everything vmaps over the image
axis, with an optional validity mask for ragged descriptor counts (XLA needs
static shapes, SURVEY §7 "hard parts").
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.pipeline import Transformer, node
from ..solvers.gmm import GaussianMixtureModel, _log_resp
from ..utils.platform import use_pallas_kernels


def _fv_from_stats(s0, s1, s2, means, variances, weights, n_valid):
    """Assemble mean/variance gradients from sufficient statistics.
    Batched: s0 [..., k], s1/s2 [..., d, k], n_valid [...]."""
    sigma = jnp.sqrt(variances)
    n_safe = jnp.maximum(n_valid, 1.0)[..., None, None]
    s0e = s0[..., None, :]
    g_mean = (s1 - means * s0e) / (sigma * jnp.sqrt(weights) * n_safe)
    g_var = (
        (s2 - 2.0 * means * s1 + (means * means - variances) * s0e)
        / (variances * jnp.sqrt(2.0 * weights) * n_safe)
    )
    return jnp.concatenate([g_mean, g_var], axis=-1)  # [..., d, 2K]


def _use_pallas() -> bool:
    """Opt-in (KEYSTONE_PALLAS=1, shared gate utils/platform.py): the
    hand-written fused kernel MEASURED SLOWER than XLA's own fusion on the
    production shape (0.95 vs 1.61 ms — see ops/fv_pallas.py docstring), so
    the XLA path is the default by evidence, and the kernel remains
    available for shapes where the balance tips (much larger vocab K)."""
    return use_pallas_kernels()


def fisher_vector(descriptors, means, variances, weights, mask=None):
    """FV of one descriptor matrix ``[cols, d]`` (descriptors as rows here;
    callers with column-major descriptor matrices transpose first).

    ``mask``: optional [cols] 0/1 validity mask for padded descriptors —
    padded columns contribute nothing and N counts only valid ones.
    """
    x = descriptors
    logr = _log_resp(x, means, variances, weights)
    q = jax.nn.softmax(logr, axis=-1)  # [n, k]
    if mask is not None:
        q = q * mask[:, None]
        n_valid = jnp.sum(mask)
    else:
        n_valid = jnp.asarray(x.shape[0], x.dtype)

    s0 = jnp.sum(q, axis=0)  # [k]
    s1 = x.T @ q  # [d, k]
    s2 = (x * x).T @ q  # [d, k]
    return _fv_from_stats(s0, s1, s2, means, variances, weights, n_valid)


@node(data_fields=("gmm",))
class FisherVector(Transformer):
    """Batched FV node: ``[N, d, cols]`` descriptor matrices (the
    BatchPCATransformer output convention, descriptors as columns) ->
    ``[N, d, 2K]``."""

    def __init__(self, gmm: GaussianMixtureModel):
        self.gmm = gmm

    @property
    def num_dims(self) -> int:
        return self.gmm.dim

    @property
    def num_centroids(self) -> int:
        return self.gmm.k

    @property
    def num_features(self) -> int:
        return self.num_dims * self.num_centroids * 2

    def __call__(self, batch, mask=None):
        """``mask``: optional [N, cols] validity for ragged descriptor counts.

        Under KEYSTONE_PALLAS=1 on TPU the sufficient statistics run as the
        fused single-pass Pallas kernel (ops/fv_pallas.py) — measured slower
        than XLA's fusion at the production shape, kept opt-in; see the
        kernel docstring.  Masked calls always take the XLA path (the kernel
        encodes raggedness as prefix counts, not arbitrary masks)."""
        gmm = self.gmm
        if mask is None and _use_pallas():
            from .fv_pallas import fv_stats_pallas

            s0, s1, s2 = fv_stats_pallas(
                batch, None, gmm.means, gmm.variances, gmm.weights
            )
            n_valid = jnp.full((batch.shape[0],), batch.shape[2], jnp.float32)
            return _fv_from_stats(
                s0, s1, s2, gmm.means, gmm.variances, gmm.weights, n_valid
            )

        def one(mat, m):
            return fisher_vector(mat.T, gmm.means, gmm.variances, gmm.weights, m)

        if mask is None:
            return jax.vmap(lambda mat: one(mat, None))(batch)
        return jax.vmap(one)(batch, mask)
