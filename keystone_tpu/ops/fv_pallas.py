"""Pallas TPU kernel: fused Fisher-vector sufficient statistics.

The XLA formulation of FV encoding (ops/fisher.py) materializes the [n, k]
responsibilities to HBM and then runs three separate contractions (s0, s1,
s2) over the descriptors.  This kernel makes ONE pass: each descriptor chunk
is loaded to VMEM once; posterior logits, the softmax, the validity mask and
all three statistics accumulate before the next chunk streams in.  The
per-image [d, k] accumulators stay VMEM-resident across the chunk loop
(their output block index is constant in the inner grid axis).  Descriptors
are processed as COLUMNS ([d, chunk] blocks) so the long chunk axis is the
lane axis — the row-major variant wastes 7/8 of the lanes on the [*, k]
tensors and measured 2.3x slower.

This is the TPU-native re-own of the enceval FV accumulation loop the
reference calls through JNI (src/main/cpp/EncEval.cxx:19-120, whose
fisher<float> encoder likewise accumulates statistics descriptor-by-
descriptor in cache) — SURVEY §2.8's "native-quality kernel" for the FV op.

MEASURED VERDICT (v5e, 64 images x 13165 descriptors, d=64, K=16, serial
in-graph chain timing): XLA fused path 0.95 ms/batch, this kernel (best
chunk=2048) 1.61 ms/batch.  XLA's own fusion of the softmax + three gemms
beats the hand-written kernel by 1.7x on the production shape, so the
XLA path is the DEFAULT and this kernel is opt-in (KEYSTONE_PALLAS=1) —
kept as the measured proof behind that design choice and as the template
for shapes where the balance tips (e.g. much larger K, where the [n, k]
posterior spill that XLA materializes grows linearly).

Parameterization: with inv_var = 1/variances,

    logit^T = A^T x^T - 0.5 * B^T (x*x)^T + c         [k, C]
    A = means * inv_var [d, k];  B = inv_var [d, k]
    c = log w - 0.5*(sum_d means^2*inv_var + sum_d log var + d*log 2pi) [k]

then q = softmax_k(logit) masked to the first ``counts[i]`` descriptors,
s0 = sum_n q, s1 = x^T q, s2 = (x*x)^T q — identical math to
ops/fisher.fisher_vector, reassociated only.

Ragged descriptor counts enter as per-image COUNTS (an SMEM operand read
scalar-wise by program id), not a dense [N, D] mask: Mosaic requires block
last-two-dims of (8k, 128m), which a mask row violates, and an in-kernel
``iota < count`` compare is free.  Arbitrary (non-prefix) masks take the
XLA path in FisherVector.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# s0 is [k] per image, but a (1, k) output block violates Mosaic's
# (sublane, lane) divisibility; the accumulator is padded to 8 sublanes and
# row 0 sliced out at the end.
_S0_PAD = 8


def _fv_stats_kernel(
    cnt_ref, x_ref, at_ref, bt_ref, c_ref, s0_ref, s1_ref, s2_ref, *, chunk: int
):
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        s0_ref[...] = jnp.zeros_like(s0_ref)
        s1_ref[...] = jnp.zeros_like(s1_ref)
        s2_ref[...] = jnp.zeros_like(s2_ref)

    x = x_ref[0]  # [d, C] — descriptors as columns
    x2 = x * x
    logit = (
        jnp.dot(at_ref[...], x, preferred_element_type=jnp.float32)
        - 0.5 * jnp.dot(bt_ref[...], x2, preferred_element_type=jnp.float32)
        + c_ref[...]
    )  # [k, C]
    m = jnp.max(logit, axis=0, keepdims=True)
    e = jnp.exp(logit - m)
    q = e / jnp.sum(e, axis=0, keepdims=True)  # [k, C]

    # validity: global column index < count for this image (scalar SMEM read)
    col = j * chunk + jax.lax.broadcasted_iota(jnp.int32, (1, chunk), 1)
    q = q * (col < cnt_ref[0, i]).astype(jnp.float32)

    s0_ref[0, 0, :] += jnp.sum(q, axis=1)
    # contract over the chunk axis: [d, C] x [k, C] -> [d, k]
    dims = (((1,), (1,)), ((), ()))
    s1_ref[0] += jax.lax.dot_general(x, q, dims, preferred_element_type=jnp.float32)
    s2_ref[0] += jax.lax.dot_general(x2, q, dims, preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnames=("chunk", "interpret"))
def fv_stats_pallas(
    x, counts, means, variances, weights, *, chunk: int = 2048, interpret: bool = False
):
    """Batched FV sufficient statistics in one fused pass.

    x: [N, d, D] descriptor matrices (descriptors as columns — the
    FisherVector node's native layout); counts: [N] int32 valid-descriptor
    counts (prefix-valid ragged batches) or None for all-valid;
    means/variances: [d, k]; weights: [k].
    Returns (s0 [N, k], s1 [N, d, k], s2 [N, d, k]).
    """
    n, d, d_count = x.shape
    k = means.shape[1]
    # short descriptor batches: don't pad a ~700-column image up to a 2048
    # chunk of mostly-zero gemm work — clamp to the lane-aligned column count
    chunk = min(chunk, max(128, -(-d_count // 128) * 128))
    if counts is None:
        counts = jnp.full((n,), d_count, jnp.int32)
    counts = counts.astype(jnp.int32).reshape(1, n)  # one full SMEM block

    inv_var = 1.0 / variances
    at = (means * inv_var).T.astype(jnp.float32)  # [k, d]
    bt = inv_var.T.astype(jnp.float32)  # [k, d]
    c = (
        jnp.log(weights)
        - 0.5
        * (
            jnp.sum(means * means * inv_var, axis=0)
            + jnp.sum(jnp.log(variances), axis=0)
            + d * jnp.log(2.0 * jnp.pi)
        )
    ).astype(jnp.float32)[:, None]  # [k, 1]

    # pad the descriptor axis to a chunk multiple; counts exclude pad columns
    pad = (-d_count) % chunk
    if pad:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, pad)))
    n_chunks = (d_count + pad) // chunk

    kernel = functools.partial(_fv_stats_kernel, chunk=chunk)
    s0, s1, s2 = pl.pallas_call(
        kernel,
        grid=(n, n_chunks),
        in_specs=[
            pl.BlockSpec((1, n), lambda i, j: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, d, chunk), lambda i, j: (i, 0, j)),
            pl.BlockSpec((k, d), lambda i, j: (0, 0)),
            pl.BlockSpec((k, d), lambda i, j: (0, 0)),
            pl.BlockSpec((k, 1), lambda i, j: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, _S0_PAD, k), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, d, k), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, d, k), lambda i, j: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, _S0_PAD, k), jnp.float32),
            jax.ShapeDtypeStruct((n, d, k), jnp.float32),
            jax.ShapeDtypeStruct((n, d, k), jnp.float32),
        ],
        interpret=interpret,
    )(counts, x.astype(jnp.float32), at, bt, c)
    return s0[:, 0, :], s1, s2
