"""Fused conv -> rectify -> pool featurizer with compact activations.

TPU-native re-design of the RandomPatchCifar featurization chain
(reference src/main/scala/pipelines/images/cifar/RandomPatchCifar.scala:53-56:
Convolver -> SymmetricRectifier -> Pooler -> ImageVectorizer, with
Convolver's im2col+gemm at nodes/images/Convolver.scala:93-136).

Why this exists (measured on v5e, 1024 CIFAR images, 100 6x6x3 filters,
14/13 sum-pool — full table in ROOFLINE.md): the op-by-op pipeline moves
~1.35 MB/image of HBM traffic for ~17 MFLOP/image (arithmetic intensity
12.6 FLOP/B vs the chip's ~240 ridge point) and its measured 8.5 TFLOP/s
was already 82% of that formulation's own memory-bound ceiling — the
featurizer is bandwidth-limited, so the only lever is traffic, not
scheduling.  Hand-written Pallas kernels with an HBM im2col stage were
measured SLOWER (the patch tensor costs a write+read that exceeds what the
kernel saves, and TPU tiled HBM layouts make every reshape of it a full
retile copy).  What wins is letting XLA's conv emitter stream patches
through the MXU (no HBM im2col exists at all) and cutting the remaining
traffic instead:

- the [oh, ow, F] normalized activations are stored BF16 (half the bytes of
  the dominant stream);
- pos/neg pooling run as two separate reduce_windows so the rectifier fuses
  into each pool read and the [oh, ow, 2F] concatenated rectified tensor —
  the single largest stream of the unfused chain — never exists;
- per-patch normalization uses Convolver's algebraic identity
  (f.(p-mu)/sigma - f.m = (f.p - mu*sum f)/sigma - f.m) with box-filter
  sums, all fused by XLA into the conv epilogue.

Measured result: ~0.59 MB/image, 1.18-1.36M images/sec, 20-23 TFLOP/s
(~10-12% MFU) — 2.4-2.8x the unfused chain at ~85% of HBM peak bandwidth.
``activation_dtype=float32`` reproduces the unfused pipeline to ~3e-7
relative (still 1.6x faster: pooling pos/neg separately avoids the 2F
concat); the default bf16 storage differs by ~9e-4 relative — the same
order as the bf16 MXU passes every TPU matmul already takes under JAX's
default precision.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.pipeline import Transformer, node
from ..utils.platform import use_pallas_kernels
from .images import Convolver, Pooler


@node(
    data_fields=("conv",),
    meta_fields=(
        "alpha", "max_val", "pool_stride", "pool_size", "activation_dtype"
    ),
)
class FusedConvFeaturizer(Transformer):
    """Convolver -> SymmetricRectifier -> Pooler('sum') -> ImageVectorizer
    as one fused XLA program with compact (bf16 by default) activations.

    Construction mirrors :class:`~keystone_tpu.ops.images.Convolver`
    (filters [F, ws, ws, C] or flat, optional whitener means, per-patch
    normalization) plus the rectifier/pooler parameters; ``__call__`` maps
    [N, H, W, C] images to the [N, npy*npx*2F] vectorized features of the
    unfused chain, element order identical.
    """

    def __init__(
        self,
        filters,
        whitener_means=None,
        *,
        pool_stride: int,
        pool_size: int,
        alpha: float = 0.0,
        max_val: float = 0.0,
        normalize_patches: bool = True,
        var_constant: float = 10.0,
        img_channels: int | None = None,
        activation_dtype=jnp.bfloat16,
    ):
        # Reuse Convolver's filter canonicalization + normalization terms.
        self.conv = Convolver(
            filters,
            whitener_means=whitener_means,
            normalize_patches=normalize_patches,
            var_constant=var_constant,
            img_channels=img_channels,
        )
        self.alpha = alpha
        self.max_val = max_val
        self.pool_stride = pool_stride
        self.pool_size = pool_size
        self.activation_dtype = activation_dtype

    def __call__(self, batch):
        # Normalized conv activations, stored compact.  The cast fuses into
        # the conv epilogue; everything downstream reads half the bytes.
        z = self.conv(batch).astype(self.activation_dtype)

        if use_pallas_kernels():
            # Opt-in hand-written kernel — measured 3.7x SLOWER than the
            # XLA form below at the production shape (custom-call layout
            # constraints force relayout copies of z); see
            # ops/rect_pool_pallas.py for the measured verdict.
            from .rect_pool_pallas import rect_pool_pallas

            return rect_pool_pallas(
                z, pool_stride=self.pool_stride, pool_size=self.pool_size,
                alpha=self.alpha, max_val=self.max_val,
            )

        pooler = Pooler(self.pool_stride, self.pool_size, None, "sum")
        a = jnp.asarray(self.alpha, jnp.float32)
        mv = jnp.asarray(self.max_val, jnp.float32)
        zf = z.astype(jnp.float32)
        # Two reduce_windows instead of pool(concat(pos, neg)): the
        # rectifier fuses into each pool's read and the [oh, ow, 2F] concat
        # never materializes.  Pool accumulation stays f32.
        pos = pooler(jnp.maximum(mv, zf - a))
        neg = pooler(jnp.maximum(mv, -zf - a))
        out = jnp.concatenate([pos, neg], axis=-1)  # [N, npy, npx, 2F]
        return out.reshape(out.shape[0], -1)
