"""Sparse feature vectorization
(reference src/main/scala/nodes/util/CommonSparseFeatures.scala:16-30,
AllSparseFeatures.scala:13-19, SparseFeatureVectorizer.scala:7-19).

The reference produces Breeze SparseVectors consumed by MLlib NaiveBayes.
TPU-native representation: a batch of sparse vectors is a CSR triple
(values, col_indices, row_ptr) of numpy arrays — downstream consumers
(solvers.naive_bayes) compute with gathers + segment sums on device, which is
how 100k-dim sparse text features stay MXU/HBM-friendly (SURVEY §7 "sparse
features on TPU").
"""

from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass

import numpy as np

from ..core.pipeline import Estimator, Transformer


@dataclass
class CSRFeatures:
    """Batch of sparse feature vectors in CSR form."""

    values: np.ndarray  # [nnz] f32
    indices: np.ndarray  # [nnz] int32 column ids
    indptr: np.ndarray  # [N+1] int64 row boundaries
    num_features: int

    def __len__(self):
        return len(self.indptr) - 1

    @property
    def shape(self):
        return (len(self), self.num_features)

    def to_dense(self) -> np.ndarray:
        out = np.zeros(self.shape, np.float32)
        for i in range(len(self)):
            s, e = self.indptr[i], self.indptr[i + 1]
            out[i, self.indices[s:e]] += self.values[s:e]
        return out


class SparseFeatureVectorizer(Transformer):
    """Map term-value pairs into CSR rows given a fitted feature space
    (reference SparseFeatureVectorizer.scala:7-19; unseen terms dropped)."""

    def __init__(self, feature_space: dict):
        self.feature_space = feature_space

    def __call__(self, batch) -> CSRFeatures:
        fs = self.feature_space
        values, indices, indptr = [], [], [0]
        for terms in batch:
            for t, v in terms:
                j = fs.get(t)
                if j is not None:
                    indices.append(j)
                    values.append(v)
            indptr.append(len(indices))
        return CSRFeatures(
            np.asarray(values, np.float32),
            np.asarray(indices, np.int32),
            np.asarray(indptr, np.int64),
            len(fs),
        )


class CommonSparseFeatures(Estimator):
    """Keep the ``num_features`` most document-frequent features
    (reference CommonSparseFeatures.scala:16-30: presence counts via
    mapValues(_ => 1) + reduceByKey, then top-k)."""

    def __init__(self, num_features: int):
        self.num_features = num_features

    def fit(self, data) -> SparseFeatureVectorizer:
        freq: dict = defaultdict(int)
        for terms in data:
            for t, _v in terms:
                freq[t] += 1
        top = sorted(freq.items(), key=lambda kv: -kv[1])[: self.num_features]
        return SparseFeatureVectorizer({t: i for i, (t, _) in enumerate(top)})


class AllSparseFeatures(Estimator):
    """Keep every observed feature (reference AllSparseFeatures.scala:13-19)."""

    def fit(self, data) -> SparseFeatureVectorizer:
        space: dict = {}
        for terms in data:
            for t, _v in terms:
                if t not in space:
                    space[t] = len(space)
        return SparseFeatureVectorizer(space)
