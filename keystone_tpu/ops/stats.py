"""Statistical feature nodes (reference src/main/scala/nodes/stats/).

All nodes operate on batches ``[N, d]``; per-partition ``rowsToMatrix`` gemm
batching in the reference (e.g. CosineRandomFeatures.scala:24-32) disappears —
arrays are already dense and HBM-resident, and the matmul hits the MXU
directly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.pipeline import Estimator, Transformer, node
from ..parallel.collectives import sharded_moments_jit


@node(data_fields=("mean", "std"))
class StandardScalerModel(Transformer):
    """Subtract column means, optionally divide by column std
    (reference nodes/stats/StandardScaler.scala:16-35)."""

    def __init__(self, mean, std=None):
        self.mean = mean
        self.std = std

    def __call__(self, batch):
        out = batch - self.mean
        if self.std is not None:
            out = out / self.std
        return out


class StandardScaler(Estimator):
    """Distributed column mean/std via one fused reduction
    (reference nodes/stats/StandardScaler.scala:39-60: treeAggregate of a
    MultivariateOnlineSummarizer -> here a single psum of (count, Σx, Σx²)).

    Matches the reference's guards: sample (n-1) variance; any std that is
    NaN/Inf/<eps becomes 1.0.
    """

    def __init__(self, normalize_std_dev: bool = True, eps: float = 1e-12):
        self.normalize_std_dev = normalize_std_dev
        self.eps = eps

    def fit(self, data, nvalid: int | None = None) -> StandardScalerModel:
        n = nvalid if nvalid is not None else data.shape[0]
        _, s, sq = sharded_moments_jit(data)
        cnt = jnp.asarray(n, data.dtype)  # true row count (excludes pad rows)
        mean = s / cnt
        if not self.normalize_std_dev:
            return StandardScalerModel(mean, None)
        var = (sq - cnt * mean * mean) / (cnt - 1.0)
        std = jnp.sqrt(var)
        bad = jnp.isnan(std) | jnp.isinf(std) | (jnp.abs(std) < self.eps)
        std = jnp.where(bad, 1.0, std)
        return StandardScalerModel(mean, std)


@node(data_fields=("W", "b"))
class CosineRandomFeatures(Transformer):
    """Random Fourier features ``cos(x Wᵀ + b)``
    (reference nodes/stats/CosineRandomFeatures.scala:18-57).  One [N,d]x[d,D]
    gemm on the MXU replaces the per-partition batching."""

    def __init__(self, W, b):
        if b.shape[0] != W.shape[0]:
            raise ValueError("# rows of W must match size of b")
        self.W = W
        self.b = b

    def __call__(self, batch):
        return jnp.cos(batch @ self.W.T + self.b)

    @staticmethod
    def create(
        num_input_features: int,
        num_output_features: int,
        gamma: float,
        key,
        w_dist: str = "gaussian",
        dtype=jnp.float32,
    ) -> "CosineRandomFeatures":
        """Gaussian (RBF kernel) or Cauchy (Laplacian kernel) W, uniform b
        (reference CosineRandomFeatures.scala:46-57)."""
        kw, kb = jax.random.split(key)
        shape = (num_output_features, num_input_features)
        if w_dist == "gaussian":
            W = jax.random.normal(kw, shape, dtype)
        elif w_dist == "cauchy":
            W = jax.random.cauchy(kw, shape, dtype)
        else:
            raise ValueError(f"unknown w_dist {w_dist!r}")
        b = jax.random.uniform(kb, (num_output_features,), dtype) * (2.0 * jnp.pi)
        return CosineRandomFeatures(W * gamma, b)


def next_power_of_two(i: int) -> int:
    return 1 << (i - 1).bit_length()


@node(data_fields=(), meta_fields=())
class PaddedFFT(Transformer):
    """Zero-pad to the next power of two; return the real part of the first
    half of the FFT (reference nodes/stats/PaddedFFT.scala:13-21).
    d -> next_pow2(d)/2."""

    def __call__(self, batch):
        padded = next_power_of_two(batch.shape[-1])
        return jnp.fft.rfft(batch, n=padded, axis=-1).real[..., : padded // 2]


@node(data_fields=("signs",))
class RandomSignNode(Transformer):
    """Elementwise random ±1 mask (reference nodes/stats/RandomSignNode.scala:11-25)."""

    def __init__(self, signs):
        self.signs = signs

    def __call__(self, batch):
        return batch * self.signs

    @staticmethod
    def create(size: int, key, dtype=jnp.float32) -> "RandomSignNode":
        signs = jax.random.bernoulli(key, 0.5, (size,)).astype(dtype) * 2.0 - 1.0
        return RandomSignNode(signs)


@node(data_fields=(), meta_fields=("max_val", "alpha"))
class LinearRectifier(Transformer):
    """``max(maxVal, x - alpha)`` (reference nodes/stats/LinearRectifier.scala:11-16)."""

    def __init__(self, max_val: float = 0.0, alpha: float = 0.0):
        self.max_val = max_val
        self.alpha = alpha

    def __call__(self, batch):
        return jnp.maximum(self.max_val, batch - self.alpha)


@node(data_fields=(), meta_fields=())
class NormalizeRows(Transformer):
    """L2-normalize each row, norm floored at machine epsilon
    (reference nodes/stats/NormalizeRows.scala:10-15)."""

    def __call__(self, batch):
        norm = jnp.linalg.norm(batch, axis=-1, keepdims=True)
        return batch / jnp.maximum(norm, 2.2e-16)


@node(data_fields=(), meta_fields=())
class SignedHellingerMapper(Transformer):
    """Signed square-root power normalization ``sign(x)·sqrt(|x|)``
    (reference nodes/stats/SignedHellingerMapper.scala:12-22).  Applies
    elementwise, so the batch form doubles as BatchSignedHellingerMapper."""

    def __call__(self, batch):
        return jnp.sign(batch) * jnp.sqrt(jnp.abs(batch))


# Batch alias matching the reference's separate matrix node.
BatchSignedHellingerMapper = SignedHellingerMapper


class Sampler:
    """``takeSample``-style row sampler (reference nodes/stats/Sampling.scala:25-37)."""

    def __init__(self, size: int, seed: int = 42):
        self.size = size
        self.seed = seed

    def __call__(self, data):
        n = data.shape[0]
        if n <= self.size:
            return data
        idx = jax.random.choice(
            jax.random.PRNGKey(self.seed), n, (self.size,), replace=False
        )
        return jnp.take(data, idx, axis=0)


class ColumnSampler:
    """Sample columns from a batch of descriptor matrices
    (reference nodes/stats/Sampling.scala:12-22).  Input [N, d, cols] or a
    list of [d, cols_i]; output [d, num_samples]."""

    def __init__(self, num_samples: int, seed: int = 42):
        self.num_samples = num_samples
        self.seed = seed

    def __call__(self, mats):
        if isinstance(mats, (list, tuple)):
            cols = jnp.concatenate([m for m in mats], axis=1)
        else:
            n, d, c = mats.shape
            cols = jnp.moveaxis(mats, 1, 0).reshape(d, n * c)
        total = cols.shape[1]
        if total <= self.num_samples:
            return cols
        idx = jax.random.choice(
            jax.random.PRNGKey(self.seed), total, (self.num_samples,), replace=False
        )
        return jnp.take(cols, idx, axis=1)
