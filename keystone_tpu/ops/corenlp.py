"""CoreNLP-equivalent featurizer — rule-based, dependency-free
(reference src/main/scala/nodes/nlp/CoreNLPFeatureExtractor.scala:18-45).

The reference delegates to the sista FastNLPProcessor (an external CoreNLP
wrapper jar) to tokenize, lemmatize, tag named entities, and emit n-grams
respecting sentence boundaries; tokens that are part of an entity are
replaced by their entity type, everything else by its normalized lemma.

This environment has no CoreNLP models, so the same contract is implemented
host-side with deterministic rules:

* sentence splitting on terminal punctuation;
* an English suffix lemmatizer (irregular table + -ies/-ied/-oes/-es/-s,
  -ing, -ed with consonant-doubling and silent-e restoration), with
  Porter-style vowel-measure guards on every strip so the rules stay safe
  on open vocabulary — covers the reference suite's cases
  (jumping->jump, snakes->snake, hunted->hunt, ...);
* gazetteer + shape NER: PERSON (common given names), LOCATION (countries,
  US states, major cities), ORGANIZATION (Corp/Inc/University ... suffix
  patterns), NUMBER for numeric tokens — matching the entity-type tokens
  the reference emits (PERSON/LOCATION/ORGANIZATION per CoreNLP's tag set);
* n-grams of the requested orders within each sentence, space-joined.

Like the reference (a host-side JVM/NLP step, not a compute kernel), this
runs on the host, not the TPU.
"""

from __future__ import annotations

import re
from typing import Sequence

from ..core.pipeline import Transformer

# Terminal punctuation only at a whitespace/end boundary — "3.14" is one
# token, not a sentence break.
_SENT_SPLIT = re.compile(r"[.!?]+(?=\s|$)")
# Numbers keep internal , and . only between digits ("4,200", "3.14" — but
# "2026,Google" is two tokens); word tokens start with a letter (a bare "'''"
# must not become an empty token after normalization).  Digit-led
# alphanumerics ("3d", "90s", "4k") stay ONE token — neither split ("3","d")
# nor tagged NUMBER.
_TOKEN = re.compile(
    r"[0-9](?:[0-9]|[.,](?=[0-9]))*(?:[A-Za-z][A-Za-z0-9']*)?|[A-Za-z][A-Za-z0-9']*"
)
_NON_ALNUM = re.compile(r"[^a-zA-Z0-9\s+]")
_NUMERIC = re.compile(r"^[0-9][0-9,.]*$")

_VOWELS = set("aeiou")

# Irregular lemmas (the high-frequency closed class; suffix rules handle the
# regular inflections).
_IRREGULAR = {
    "ran": "run", "ate": "eat", "went": "go", "gone": "go", "saw": "see",
    "seen": "see", "took": "take", "taken": "take", "came": "come",
    "made": "make", "said": "say", "got": "get", "gotten": "get",
    "found": "find", "gave": "give", "given": "give", "told": "tell",
    "felt": "feel", "kept": "keep", "left": "leave", "meant": "mean",
    "met": "meet", "paid": "pay", "sat": "sit", "spoke": "speak",
    "spoken": "speak", "stood": "stand", "thought": "think", "wrote": "write",
    "written": "write", "knew": "know", "known": "know", "grew": "grow",
    "grown": "grow", "drew": "draw", "drawn": "draw", "flew": "fly",
    "flown": "fly", "threw": "throw", "thrown": "throw", "broke": "break",
    "broken": "break", "chose": "choose", "chosen": "choose", "drove": "drive",
    "driven": "drive", "fell": "fall", "fallen": "fall", "held": "hold",
    "lost": "lose", "sold": "sell", "sent": "send",
    "was": "be", "were": "be", "is": "be", "are": "be", "am": "be",
    "been": "be", "being": "be", "has": "have", "had": "have",
    "does": "do", "did": "do", "done": "do", "goes": "go",
    "men": "man", "women": "woman", "children": "child", "people": "person",
    "mice": "mouse", "geese": "goose", "feet": "foot", "teeth": "tooth",
    "better": "good", "best": "good", "worse": "bad", "worst": "bad",
}

# Words whose surface form ends like an inflection but is not one.
_NO_STRIP = {
    "this", "his", "its", "thus", "us", "bus", "gas", "yes", "news",
    "lens", "species", "series", "analysis", "basis", "crisis",
    "ring", "king", "thing", "spring", "string", "sing", "bring",
    "during", "morning", "evening", "nothing", "something", "anything",
    "everything", "red", "bed", "wed", "ted", "led", "fed", "need",
    "seed", "feed", "speed", "indeed",
}


# Words ending consonant+"oes" that are o+"es" plurals of -oe nouns, not
# -o nouns ("shoes" = shoe+s, not sho+es).
_OE_PLURALS = {
    "shoes", "canoes", "oboes", "tiptoes", "mistletoes", "throes", "floes",
}


def _has_vowel(stem: str) -> bool:
    """Porter's *v* condition: a stem with no vowel ("bl" from "bling",
    "z" from "zings") is not a word, so the suffix was not an inflection."""
    return any(c in _VOWELS or c == "y" for c in stem)


def lemmatize(word: str) -> str:
    """Suffix-rule English lemmatizer (the FastNLPProcessor.lemmatize
    analog): irregular table first, then suffix rules guarded by
    Porter-style conditions — every strip requires the remaining stem to
    contain a vowel (Porter's *v* measure guard), which is what keeps the
    rules safe on OPEN vocabulary where a closed exception list cannot
    anticipate every "bling"/"zings"-shaped token."""
    w = word.lower()
    if w in _IRREGULAR:
        return _IRREGULAR[w]
    if w in _NO_STRIP or len(w) <= 3:
        return w

    def _restore(stem: str) -> str:
        # doubled final consonant: "hopped" -> "hopp" -> "hop"
        if (
            len(stem) >= 3
            and stem[-1] == stem[-2]
            and stem[-1] not in _VOWELS
            and stem[-1] not in "ls"
        ):
            return stem[:-1]
        # silent-e restoration: "making" -> "mak" -> "make"
        if (
            len(stem) >= 3
            and stem[-1] not in _VOWELS | {"w", "x", "y"}
            and stem[-2] in _VOWELS
            and stem[-3] not in _VOWELS
            and _needs_e(stem)
        ):
            return stem + "e"
        return stem

    if w.endswith("ies") and len(w) > 4:
        return w[:-3] + "y"
    if w.endswith("ied") and len(w) > 4:  # carried -> carry, studied -> study
        return w[:-3] + "y"
    if w.endswith("sses"):
        return w[:-2]
    if w.endswith(("ches", "shes", "xes", "zes")):
        return w[:-2]
    if (
        w.endswith("oes")
        and len(w) > 4
        and w[-4] not in _VOWELS
        and w not in _OE_PLURALS
    ):
        return w[:-2]  # consonant+o takes -es: heroes/echoes/potatoes
    if w.endswith("s") and not w.endswith(("ss", "us", "is")):
        return w[:-1] if _has_vowel(w[:-1]) else w
    if w.endswith("ing") and len(w) > 5 and _has_vowel(w[:-3]):
        return _restore(w[:-3])
    if w.endswith("ed") and len(w) > 4 and _has_vowel(w[:-2]):
        return _restore(w[:-2])
    return w


def _needs_e(stem: str) -> bool:
    """Heuristic: restore silent e after stripping -ing/-ed for stems like
    mak-, writ-, driv-, tak-, encod- (single vowel + single final consonant
    that commonly ends an e-final base)."""
    return stem[-1] in set("kvztcgud") or stem.endswith(("at", "it", "ot", "ut"))


# Compact gazetteers — the reference resolves these through CoreNLP's models.
_PERSON_NAMES = {
    "john", "mary", "james", "robert", "michael", "william", "david",
    "richard", "joseph", "thomas", "charles", "chris", "daniel", "matthew",
    "anthony", "mark", "donald", "steven", "paul", "andrew", "joshua",
    "kenneth", "kevin", "brian", "george", "timothy", "ronald", "jason",
    "edward", "jeffrey", "ryan", "jacob", "gary", "nicholas", "eric",
    "jonathan", "stephen", "larry", "justin", "scott", "brandon", "benjamin",
    "samuel", "gregory", "alexander", "patrick", "frank", "raymond", "jack",
    "dennis", "jerry", "tyler", "aaron", "jose", "adam", "nathan", "henry",
    "peter", "zachary", "kyle", "noah", "alan", "ethan", "jeremy", "walter",
    "christian", "keith", "roger", "terry", "austin", "sean", "gerald",
    "carl", "harold", "dylan", "arthur", "lawrence", "jordan", "jesse",
    "bryan", "billy", "bruce", "gabriel", "joe", "logan", "alex", "juan",
    "albert", "willie", "elijah", "wayne", "randy", "vincent", "mason",
    "roy", "ralph", "bobby", "russell", "bradley", "philip", "eugene",
    "patricia", "jennifer", "linda", "elizabeth", "barbara", "susan",
    "jessica", "sarah", "karen", "lisa", "nancy", "betty", "sandra",
    "margaret", "ashley", "kimberly", "emily", "donna", "michelle", "carol",
    "amanda", "dorothy", "melissa", "deborah", "stephanie", "rebecca",
    "sharon", "laura", "cynthia", "kathleen", "amy", "angela", "shirley",
    "anna", "brenda", "pamela", "emma", "nicole", "helen", "samantha",
    "katherine", "christine", "debra", "rachel", "carolyn", "janet",
    "catherine", "maria", "heather", "diane", "ruth", "julie", "olivia",
    "joyce", "virginia", "victoria", "kelly", "lauren", "christina", "joan",
    "evelyn", "judith", "megan", "andrea", "cheryl", "hannah", "jacqueline",
    "martha", "gloria", "teresa", "ann", "sara", "madison", "frances",
    "kathryn", "janice", "jean", "abigail", "alice", "judy", "sophia",
    "grace", "denise", "amber", "doris", "marilyn", "danielle", "beverly",
    "isabella", "theresa", "diana", "natalie", "brittany", "charlotte",
}
_LOCATIONS = {
    # US states
    "alabama", "alaska", "arizona", "arkansas", "california", "colorado",
    "connecticut", "delaware", "florida", "georgia", "hawaii", "idaho",
    "illinois", "indiana", "iowa", "kansas", "kentucky", "louisiana",
    "maine", "maryland", "massachusetts", "michigan", "minnesota",
    "mississippi", "missouri", "montana", "nebraska", "nevada", "ohio",
    "oklahoma", "oregon", "pennsylvania", "tennessee", "texas", "utah",
    "vermont", "virginia", "washington", "wisconsin", "wyoming",
    # countries
    "america", "canada", "mexico", "brazil", "argentina", "england",
    "britain", "france", "germany", "spain", "italy", "portugal", "ireland",
    "scotland", "russia", "china", "japan", "korea", "india", "australia",
    "egypt", "israel", "turkey", "greece", "poland", "sweden", "norway",
    "denmark", "finland", "netherlands", "belgium", "switzerland", "austria",
    "ukraine", "iran", "iraq", "afghanistan", "pakistan", "vietnam",
    "thailand", "indonesia", "philippines", "nigeria", "kenya", "ethiopia",
    # major cities
    "london", "paris", "berlin", "madrid", "rome", "moscow", "beijing",
    "shanghai", "tokyo", "seoul", "delhi", "mumbai", "sydney", "toronto",
    "chicago", "boston", "seattle", "houston", "dallas", "denver", "miami",
    "atlanta", "philadelphia", "phoenix", "detroit", "baltimore",
}
_ORG_SUFFIXES = {
    "inc", "corp", "corporation", "company", "co", "ltd", "llc", "group",
    "university", "college", "institute", "association", "committee",
    "department", "agency", "bureau", "ministry", "bank", "press",
}
_ORG_NAMES = {
    "google", "microsoft", "apple", "amazon", "facebook", "ibm", "intel",
    "oracle", "netflix", "tesla", "boeing", "toyota", "honda", "sony",
    "samsung", "nasa", "fbi", "cia", "nato", "congress", "senate", "nyse",
}


def _entity_type(token: str, capitalized: bool, next_lower: str | None) -> str | None:
    """NER analog: entity type or None (CoreNLP tags 'O' for non-entities)."""
    low = token.lower()
    if _NUMERIC.match(token):
        return "NUMBER"
    if low in _ORG_NAMES:
        return "ORGANIZATION"
    if capitalized:
        if next_lower in _ORG_SUFFIXES:
            return "ORGANIZATION"
        if low in _PERSON_NAMES:
            return "PERSON"
        if low in _LOCATIONS:
            return "LOCATION"
        if low in _ORG_SUFFIXES:
            return "ORGANIZATION"
    return None


def normalize(s: str) -> str:
    """Strip non-alphanumerics and lowercase (reference :41-44)."""
    return _NON_ALNUM.sub("", s).lower()


class CoreNLPFeatureExtractor(Transformer):
    """Tokenize -> lemmatize -> entity-replace -> sentence-bounded n-grams
    (reference CoreNLPFeatureExtractor.scala:18-45).  Input: a batch of
    document strings; output: per document, the list of space-joined n-gram
    strings for every requested order."""

    def __init__(self, orders: Sequence[int]):
        self.orders = list(orders)

    def apply_item(self, doc: str) -> list:
        sentences = []
        for sent in _SENT_SPLIT.split(doc):
            raw = _TOKEN.findall(sent)
            if not raw:
                continue
            out = []
            for i, tok in enumerate(raw):
                nxt = raw[i + 1].lower() if i + 1 < len(raw) else None
                ent = _entity_type(tok, tok[:1].isupper(), nxt)
                if ent is not None:
                    out.append(ent)
                elif tok[:1].isdigit():
                    # digit-led mixed token ("90s", "3d"): unit/decade
                    # notation, not an English inflection — don't let the
                    # suffix lemmatizer strip it ("90s" -> "90")
                    out.append(normalize(tok))
                else:
                    out.append(normalize(lemmatize(tok)))
            sentences.append(out)
        grams = []
        for n in self.orders:
            for s in sentences:
                for i in range(len(s) - n + 1):
                    grams.append(" ".join(s[i : i + n]))
        return grams

    def __call__(self, batch: Sequence[str]):
        return [self.apply_item(doc) for doc in batch]
