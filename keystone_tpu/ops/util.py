"""Utility nodes (reference src/main/scala/nodes/util/)."""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.pipeline import FunctionNode, Transformer, node


@node(data_fields=(), meta_fields=("num_classes",))
class ClassLabelIndicatorsFromIntLabels(Transformer):
    """Int label -> ±1 one-hot indicator vector
    (reference nodes/util/ClassLabelIndicators.scala:11-21): -1 everywhere,
    +1 at the class index."""

    def __init__(self, num_classes: int):
        if num_classes < 2:
            raise ValueError("Must have at least two classes")
        self.num_classes = num_classes

    def __call__(self, labels):
        labels = jnp.asarray(labels)
        eye = jnp.eye(self.num_classes, dtype=jnp.float32)
        return 2.0 * eye[labels] - 1.0


@node(data_fields=(), meta_fields=("num_classes",))
class ClassLabelIndicatorsFromIntArrayLabels(Transformer):
    """Multi-label variant (reference ClassLabelIndicators.scala:24-38):
    takes a ±1 multi-hot from a padded [N, max_labels] int array (pad = -1)."""

    def __init__(self, num_classes: int):
        if num_classes < 2:
            raise ValueError("Must have at least two classes")
        self.num_classes = num_classes

    def __call__(self, label_arrays):
        out = []
        for labels in label_arrays:
            v = np.full(self.num_classes, -1.0, dtype=np.float32)
            for l in np.asarray(labels).ravel():
                if l >= 0:
                    v[int(l)] = 1.0
            out.append(v)
        return jnp.asarray(np.stack(out))


@node(data_fields=(), meta_fields=())
class MaxClassifier(Transformer):
    """argmax over the score vector (reference nodes/util/MaxClassifier.scala:9-11)."""

    def __call__(self, batch):
        return jnp.argmax(batch, axis=-1)


@node(data_fields=(), meta_fields=("k",))
class TopKClassifier(Transformer):
    """Top-k class indices, best first (reference nodes/util/TopKClassifier.scala:9-12)."""

    def __init__(self, k: int):
        self.k = k

    def __call__(self, batch):
        _, idx = jax.lax.top_k(batch, self.k)
        return idx


@node(data_fields=(), meta_fields=("dtype",))
class Cast(Transformer):
    """dtype cast; the reference's FloatToDouble
    (nodes/util/FloatToDouble.scala:9-11) generalized."""

    def __init__(self, dtype):
        self.dtype = dtype

    def __call__(self, batch):
        return batch.astype(self.dtype)


FloatToDouble = Cast  # alias; pass jnp.float64 (requires x64) or keep f32


@node(data_fields=(), meta_fields=())
class MatrixVectorizer(Transformer):
    """Flatten each per-example matrix to a vector
    (reference nodes/util/MatrixVectorizer.scala:9-11).  Column-major order to
    match Breeze's DenseMatrix.toDenseVector layout."""

    def __call__(self, batch):
        n = batch.shape[0]
        return jnp.swapaxes(batch, -1, -2).reshape(n, -1)


class ZipVectors(FunctionNode):
    """Concatenate a sequence of feature batches along the feature axis
    (reference nodes/util/ZipVectors.scala:10-15).  Co-sharded arrays concat
    with zero communication."""

    def __call__(self, batches: Sequence):
        return jnp.concatenate(list(batches), axis=-1)

    @staticmethod
    def apply(batches):
        return jnp.concatenate(list(batches), axis=-1)


@node(data_fields=("groups",), meta_fields=())
class GroupConcatFeaturizer(Transformer):
    """The MnistRandomFFT featurize phase as ONE chainable (and
    checkpointable) node: each GROUP of per-FFT chains runs on the same
    input batch, ZipVectors concatenates within the group, and the groups
    concatenate along the feature axis — ``[n, d] -> [n, G * group_width]``.

    This exists for the serving path (ISSUE 8): the fit loop keeps feeding
    :class:`~..solvers.block.BlockLinearMapper` the per-group batches
    directly (streaming evaluation wants blocks), but a *fitted* pipeline
    shipped to an endpoint must be one Transformer chain —
    ``GroupConcatFeaturizer >> model >> MaxClassifier`` — whose concatenated
    output the model's ``VectorSplitter`` cuts back into exactly the
    per-group blocks (each group is ``block_size`` wide by construction),
    so served scores are bit-equal to the fit-path apply.  ``groups`` is a
    data field: the chains are registered-node Pipelines, so the whole
    thing checkpoints through ``core.checkpoint`` and flows through jit as
    a pytree (fitted arrays stay program arguments, not baked constants).
    """

    def __init__(self, groups: Sequence[Sequence[Transformer]]):
        self.groups = tuple(tuple(g) for g in groups)

    def __call__(self, batch):
        return jnp.concatenate(
            [
                ZipVectors.apply([chain(batch) for chain in group])
                for group in self.groups
            ],
            axis=-1,
        )

    def __repr__(self):
        return (
            f"GroupConcatFeaturizer({len(self.groups)} groups x "
            f"{len(self.groups[0]) if self.groups else 0} chains)"
        )


class VectorSplitter(FunctionNode):
    """Split [N, d] features into ⌈d/block_size⌉ feature blocks — the
    model-parallel decomposition primitive
    (reference nodes/util/VectorSplitter.scala:10-36).  The last block may be
    short, matching the reference's slice semantics."""

    def __init__(self, block_size: int, num_features: int | None = None):
        self.block_size = block_size
        self.num_features = num_features

    def __call__(self, data):
        d = self.num_features or data.shape[-1]
        return [
            data[..., i : min(i + self.block_size, d)]
            for i in range(0, d, self.block_size)
        ]

    def split_vector(self, vec):
        return self(vec)

    def num_blocks(self, d: int | None = None) -> int:
        d = d or self.num_features
        return -(-d // self.block_size)
