"""DAISY dense descriptors (reference src/main/scala/nodes/images/DaisyExtractor.scala:28-201;
Tola, Lepetit, Fua — PAMI 2010).

Oriented gradient maps via separable [1,0,-1]x[1,2,1] convolutions, a cascade
of Gaussian blur layers, ring sampling of histograms, per-histogram L2
normalization with a zero threshold.  All convolutions/orientation maps are
batched XLA ops; the ring sampling is one static gather.

Output per image: ``[num_keypoints, daisyH*(daisyT*daisyQ + 1)]`` — DAISY
descriptors are ROWS (the reference's DenseMatrix layout), unlike the
SIFT/LCS column convention.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from ..core.pipeline import Transformer, node
from .lcs import _same_conv2d_zero

FEATURE_THRESHOLD = 1e-8  # zero histograms below this norm
CONV_THRESHOLD = 1e-6  # where to truncate the Gaussian blurs


@node(
    meta_fields=(
        "daisy_t", "daisy_q", "daisy_r", "daisy_h",
        "pixel_border", "stride", "patch_size",
    )
)
class DaisyExtractor(Transformer):
    """Batched DAISY: ``[N, H, W, 1]`` (or [N,H,W]) -> ``[N, K, featSize]``."""

    def __init__(
        self,
        daisy_t: int = 8,
        daisy_q: int = 3,
        daisy_r: int = 7,
        daisy_h: int = 8,
        pixel_border: int = 16,
        stride: int = 4,
        patch_size: int = 24,
    ):
        self.daisy_t = daisy_t
        self.daisy_q = daisy_q
        self.daisy_r = daisy_r
        self.daisy_h = daisy_h
        self.pixel_border = pixel_border
        self.stride = stride
        self.patch_size = patch_size

    @property
    def feature_size(self) -> int:
        return self.daisy_h * (self.daisy_t * self.daisy_q + 1)

    def _gaussians(self):
        """Blur kernels g[q] from the sigma-difference cascade (:50-64)."""
        q_range = np.arange(self.daisy_q + 1)
        sigma_sq = (self.daisy_r * q_range / (2.0 * self.daisy_q)) ** 2
        diff = sigma_sq[1:] - sigma_sq[:-1]
        kernels = []
        for t in diff:
            rad = int(
                math.ceil(
                    math.sqrt(-2 * t * math.log(CONV_THRESHOLD) - t * math.log(2 * math.pi * t))
                )
            )
            n = np.arange(-rad, rad + 1, dtype=np.float64)
            k = np.exp(-(n**2) / (2.0 * t)) / math.sqrt(2 * math.pi * t)
            kernels.append(k.astype(np.float32))
        return kernels

    def _keypoints(self, dim: int) -> np.ndarray:
        return np.arange(self.pixel_border, dim - self.pixel_border, self.stride)

    def __call__(self, batch):
        if batch.ndim == 3:
            batch = batch[..., None]
        n, h, w, _ = batch.shape
        f1 = np.array([1.0, 0.0, -1.0], np.float32)
        f2 = np.array([1.0, 2.0, 1.0], np.float32)
        # gradients (:111-113): conv2D(in, filter1, filter2) = d/dx smoothed
        ix = _same_conv2d_zero(batch, f1, f2)[..., 0]
        iy = _same_conv2d_zero(batch, f2, f1)[..., 0]

        kernels = self._gaussians()
        # orientation maps: max(cos(a)·ix + sin(a)·iy, 0), blur cascade (:116-137)
        angles = 2.0 * np.pi * np.arange(self.daisy_h) / self.daisy_h
        layers = []  # layers[q] : [N, daisyH, H, W]
        per_angle = []
        for a in angles:
            m = jnp.maximum(math.cos(a) * ix + math.sin(a) * iy, 0.0)
            per_angle.append(m)
        current = jnp.stack(per_angle, axis=1)  # [N, daisyH, H, W]
        for q in range(self.daisy_q):
            g = kernels[q]
            flat = current.reshape(n * self.daisy_h, h, w)[..., None]
            blurred = _same_conv2d_zero(flat, g, g)[..., 0]
            current = blurred.reshape(n, self.daisy_h, h, w)
            layers.append(current)

        xs = self._keypoints(w)
        ys = self._keypoints(h)
        n_x, n_y = len(xs), len(ys)
        # keypoint grid flattened as x*numY + y (:151-199)
        kp_x = np.repeat(xs, n_y)
        kp_y = np.tile(ys, n_x)

        def normalize(hists):
            # [..., daisyH] L2 normalize; zero when norm <= threshold (:193-200)
            norm = jnp.linalg.norm(hists, axis=-1, keepdims=True)
            return jnp.where(norm > FEATURE_THRESHOLD, hists / norm, 0.0)

        # center histogram from layer 0 at the keypoint (:96-103)
        center = layers[0][:, :, jnp.asarray(kp_y), jnp.asarray(kp_x)]  # [N, daisyH, K]
        center = normalize(jnp.moveaxis(center, 1, 2))  # [N, K, daisyH]

        out = jnp.zeros((n, n_x * n_y, self.feature_size), center.dtype)
        out = out.at[:, :, : self.daisy_h].set(center)

        # ring histograms (:73-94, :165-186): layout column
        # daisyH + angle*Q*H + level*H + off
        for level in range(self.daisy_q):
            cur_rad = self.daisy_r * (1.0 + level) / self.daisy_q
            for angle_count in range(self.daisy_t):
                cur_theta = 2.0 * math.pi * (angle_count - 1) / self.daisy_t
                off_x = int(round(cur_rad * math.sin(cur_theta)))
                off_y = int(round(cur_rad * math.cos(cur_theta)))
                sx = np.clip(kp_x + off_x, 0, w - 1)
                sy = np.clip(kp_y + off_y, 0, h - 1)
                hist = layers[level][:, :, jnp.asarray(sy), jnp.asarray(sx)]
                hist = normalize(jnp.moveaxis(hist, 1, 2))  # [N, K, daisyH]
                col0 = self.daisy_h + angle_count * self.daisy_q * self.daisy_h + level * self.daisy_h
                out = out.at[:, :, col0 : col0 + self.daisy_h].set(hist)
        return out
