"""Pallas TPU kernel: fused symmetric-rectify + sum-pool, single read.

The shipped fused featurizer (ops/conv_fused.py) stores the normalized conv
activations ``z`` once (bf16) and pools pos/neg with two reduce_windows —
each fusing its rectifier but each READING z: ~0.44 MB/image of the
0.59 MB/image total is that one write + two reads.  This kernel computes
BOTH pooled signs from one pass over z: read once, write [2*npools, F]
per image — projected ~0.41 MB/image total for the featurizer.

Why this kernel avoids the traps that sank the im2col kernels (ROOFLINE.md):
it contains NO matmuls and NO reshapes — rectification is elementwise on
the native [b, oh, ow, F] conv layout, row-pooling sums over an OUTER dim
(plain tile adds), and column-pooling sums a sublane range.  All VPU work
on tiles the conv already emits.

MEASURED VERDICT (v5e, 1024 CIFAR images, production shape): the XLA
two-reduce_window form runs 1.16M img/s at 594 KB/img; this kernel runs
311k img/s at 1,896 KB/img — 3.7x SLOWER with 3x MORE traffic.  The
projection failed at the program boundary, not in the kernel: a Pallas
call is an XLA custom call with operand layout constraints, so (a) the
conv can no longer fuse its bf16 epilogue cast into the consumer, and (b)
XLA inserts relayout copies of the full [N, oh, ow, F] activation tensor
to satisfy the constrained tiled layout — the copies cost more than the
saved second read.  Same boundary economics as the im2col kernels in
ROOFLINE.md: beating XLA's fusion pipeline requires removing streams it
is FORCED to keep, and a custom-call boundary adds streams instead.
Kept opt-in (KEYSTONE_PALLAS=1 in FusedConvFeaturizer) as the measured
proof and as the template for shapes where a producer emits the layout
natively.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _num_pools(dim: int, stride: int, pool_size: int) -> int:
    return math.ceil((dim - pool_size // 2) / stride)


def _windows(dim: int, stride: int, pool_size: int):
    """(start, length) per pool — Pooler coverage (truncated high edge)."""
    half = pool_size // 2
    span = 2 * half if pool_size % 2 == 1 else pool_size
    return [
        (p * stride, min(p * stride + span, dim) - p * stride)
        for p in range(_num_pools(dim, stride, pool_size))
    ]

def _kernel(z_ref, o_ref, *, wy, wx, alpha: float, max_val: float):
    z = z_ref[...].astype(jnp.float32)  # [b, oh, ow, F]
    pos = jnp.maximum(max_val, z - alpha)
    neg = jnp.maximum(max_val, -z - alpha)
    outs = []
    for t in (pos, neg):
        for y0, ylen in wy:
            # row pool: sum over the outer spatial dim — tile adds
            u = jnp.sum(t[:, y0 : y0 + ylen], axis=1)  # [b, ow, F]
            for x0, xlen in wx:
                # col pool: sublane-range sum
                outs.append(jnp.sum(u[:, x0 : x0 + xlen], axis=1))  # [b, F]
    # [b, 2*npools, F]: sign-major, then (py, px) — epilogue reorders
    o_ref[...] = jnp.stack(outs, axis=1)


@functools.partial(
    jax.jit,
    static_argnames=(
        "pool_stride", "pool_size", "alpha", "max_val", "images_per_step",
        "interpret",
    ),
)
def rect_pool_pallas(
    z,
    *,
    pool_stride: int,
    pool_size: int,
    alpha: float = 0.0,
    max_val: float = 0.0,
    images_per_step: int = 8,
    interpret: bool = False,
):
    """[N, oh, ow, F] activations -> [N, npools*2F] pooled features in the
    unfused element order (position-major, pos block then neg block)."""
    n, oh, ow, f = z.shape
    wy = tuple(_windows(oh, pool_stride, pool_size))
    wx = tuple(_windows(ow, pool_stride, pool_size))
    npools = len(wy) * len(wx)

    b = images_per_step
    n_pad = (-n) % b
    if n_pad:
        z = jnp.pad(z, ((0, n_pad), (0, 0), (0, 0), (0, 0)))

    kern = functools.partial(
        _kernel, wy=wy, wx=wx, alpha=alpha, max_val=max_val
    )
    out = pl.pallas_call(
        kern,
        grid=((n + n_pad) // b,),
        in_specs=[pl.BlockSpec((b, oh, ow, f), lambda i: (i, 0, 0, 0))],
        out_specs=pl.BlockSpec((b, 2 * npools, f), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n + n_pad, 2 * npools, f), jnp.float32),
        interpret=interpret,
    )(z)

    # [N, 2, npools, F] -> [N, npools, 2, F] -> [N, npools*2F]
    out = out[:n].reshape(n, 2, npools, f).transpose(0, 2, 1, 3)
    return out.reshape(n, npools * 2 * f)
