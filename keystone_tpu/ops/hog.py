"""Histogram of Oriented Gradients, Felzenszwalb/voc-dpm variant
(reference src/main/scala/nodes/images/HogExtractor.scala:33-296, itself a
port of voc-dpm features.cc).

31-dim cell features: 18 contrast-sensitive + 9 contrast-insensitive
orientation channels (block-normalized by 4 neighborhoods, clamped at 0.2),
4 texture-energy features, 1 truncation feature (always 0).

The reference walks pixels in Scala while-loops; here the per-pixel work
(channel selection, orientation snapping, bilinear cell weights) is batched
array ops and the histogram is built with 4 scatter-adds.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.pipeline import Transformer, node

EPSILON = 0.0001
UU = np.array(
    [1.0, 0.9397, 0.7660, 0.5, 0.1736, -0.1736, -0.5, -0.7660, -0.9397]
)
VV = np.array(
    [0.0, 0.3420, 0.6428, 0.8660, 0.9848, 0.9848, 0.8660, 0.6428, 0.3420]
)


@node(meta_fields=("bin_size",))
class HogExtractor(Transformer):
    """Batched HOG: ``[N, H, W, C]`` -> ``[N, cells, 32]`` where
    cells = max(numXCells-2,0)·max(numYCells-2,0) and the 32nd column is the
    truncation feature (reference computeFeaturesFromHist :196-296)."""

    def __init__(self, bin_size: int):
        self.bin_size = bin_size

    def __call__(self, batch):
        n, h, w, c = batch.shape
        bs = self.bin_size
        # reference x = column axis (xDim), y = row axis; the reference's
        # math.round rounds half AWAY from zero (Python's round() would
        # banker-round 0.5 down and change the cell grid).
        nx = int(np.floor(w / bs + 0.5))
        ny = int(np.floor(h / bs + 0.5))
        vis_x = nx * bs
        vis_y = ny * bs

        # interior visible pixels [1, vis-1).  When a dimension rounds UP
        # (dim mod bin_size > bin_size/2) the visible region exceeds the
        # image; voc-dpm clamps gradient reads to the image interior
        # (features.cc: min(x, dims-2)) while bin positions use the
        # unclamped coordinate — the Scala port would crash there.
        px = np.arange(1, vis_x - 1)
        py = np.arange(1, vis_y - 1)
        # central differences over the full interior, then gather at clamped
        # coordinates
        dxi = (batch[:, :, 2:, :] - batch[:, :, :-2, :])[:, 1:-1, :, :]  # [N,h-2,w-2,C]
        dyi = (batch[:, 2:, :, :] - batch[:, :-2, :, :])[:, :, 1:-1, :]
        px_r = np.minimum(px, w - 2) - 1
        py_r = np.minimum(py, h - 2) - 1
        dx_all = dxi[:, py_r][:, :, px_r]  # [N,py,px,C]
        dy_all = dyi[:, py_r][:, :, px_r]
        mag2 = dx_all * dx_all + dy_all * dy_all
        # channel loop runs 2,1,0 with strict '>': ties keep the HIGHEST
        # channel index; argmax on the reversed axis replicates that
        best_rev = jnp.argmax(mag2[..., ::-1], axis=-1)
        best_c = (c - 1) - best_rev
        dx = jnp.take_along_axis(dx_all, best_c[..., None], axis=-1)[..., 0]
        dy = jnp.take_along_axis(dy_all, best_c[..., None], axis=-1)[..., 0]
        mag = jnp.sqrt(jnp.take_along_axis(mag2, best_c[..., None], axis=-1)[..., 0])

        # orientation snap (:118-133): candidates interleaved (+d0,-d0,+d1,..)
        uu = jnp.asarray(UU, batch.dtype)
        vv = jnp.asarray(VV, batch.dtype)
        dots = dy[..., None] * uu + dx[..., None] * vv  # [N,py,px,9]
        cand = jnp.stack([dots, -dots], axis=-1).reshape(*dots.shape[:-1], 18)
        best_i = jnp.argmax(cand, axis=-1)
        orient = jnp.where(best_i % 2 == 0, best_i // 2, best_i // 2 + 9)
        # initial best dot is 0.0: all-zero gradients give orientation 0
        orient = jnp.where(jnp.max(cand, axis=-1) > 0.0, orient, 0)

        # bilinear cell weights — functions of pixel coords only (:136-160)
        xp = (px + 0.5) / bs - 0.5
        yp = (py + 0.5) / bs - 0.5
        ixp = np.floor(xp).astype(np.int64)
        iyp = np.floor(yp).astype(np.int64)
        vx0 = xp - ixp
        vy0 = yp - iyp

        hist = jnp.zeros((n, 18 * nx * ny), batch.dtype)
        flat_o = orient * (nx * ny)
        iyp_g, ixp_g = np.meshgrid(iyp, ixp, indexing="ij")
        vy0_g, vx0_g = np.meshgrid(vy0, vx0, indexing="ij")
        for dy_c, dx_c, wgt in (
            (0, 0, (1 - vy0_g) * (1 - vx0_g)),
            (1, 0, vy0_g * (1 - vx0_g)),
            (0, 1, (1 - vy0_g) * vx0_g),
            (1, 1, vy0_g * vx0_g),
        ):
            cx = ixp_g + dx_c
            cy = iyp_g + dy_c
            valid = (cx >= 0) & (cy >= 0) & (cx < nx) & (cy < ny)
            cell = np.clip(cx, 0, nx - 1) + np.clip(cy, 0, ny - 1) * nx
            idx = flat_o + jnp.asarray(cell)
            contrib = mag * jnp.asarray(wgt * valid, batch.dtype)
            hist = hist.at[
                jnp.arange(n)[:, None, None], idx
            ].add(contrib)
        hist = hist.reshape(n, 18, ny, nx)

        # block energies (:167-193): opposite orientations combined
        norm = jnp.sum(
            (hist[:, :9] + hist[:, 9:]) ** 2, axis=1
        )  # [N, ny, nx]

        nxf, nyf = max(nx - 2, 0), max(ny - 2, 0)
        if nxf == 0 or nyf == 0:
            return jnp.zeros((n, 0, 32), batch.dtype)

        def block_norm(y0, x0):
            # 1/sqrt of 2x2 neighborhood energy starting at (y0, x0)
            s = (
                norm[:, y0 : y0 + nyf, x0 : x0 + nxf]
                + norm[:, y0 : y0 + nyf, x0 + 1 : x0 + 1 + nxf]
                + norm[:, y0 + 1 : y0 + 1 + nyf, x0 : x0 + nxf]
                + norm[:, y0 + 1 : y0 + 1 + nyf, x0 + 1 : x0 + 1 + nxf]
            )
            return 1.0 / jnp.sqrt(s + EPSILON)

        n1 = block_norm(1, 1)
        n2 = block_norm(1, 0)
        n3 = block_norm(0, 1)
        n4 = block_norm(0, 0)  # each [N, nyf, nxf]

        center = hist[:, :, 1 : 1 + nyf, 1 : 1 + nxf]  # [N, 18, nyf, nxf]
        feats = []
        tsum = [jnp.zeros_like(n1) for _ in range(4)]
        for o in range(18):
            hs = [
                jnp.minimum(center[:, o] * nk, 0.2) for nk in (n1, n2, n3, n4)
            ]
            for i in range(4):
                tsum[i] = tsum[i] + hs[i]
            feats.append(0.5 * (hs[0] + hs[1] + hs[2] + hs[3]))
        for o in range(9):
            s = center[:, o] + center[:, o + 9]
            hs = [jnp.minimum(s * nk, 0.2) for nk in (n1, n2, n3, n4)]
            feats.append(0.5 * (hs[0] + hs[1] + hs[2] + hs[3]))
        for i in range(4):
            feats.append(0.2357 * tsum[i])
        feats.append(jnp.zeros_like(n1))  # truncation feature
        stacked = jnp.stack(feats, axis=-1)  # [N, nyf, nxf, 32]
        # row index = y + x*numYCellsWithFeatures (:210) -> x-major flatten
        stacked = jnp.swapaxes(stacked, 1, 2)  # [N, nxf, nyf, 32]
        return stacked.reshape(n, nxf * nyf, 32)
