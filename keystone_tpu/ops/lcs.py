"""Local Color Statistics extractor
(reference src/main/scala/nodes/images/LCSExtractor.scala:25-130).

Per channel: box-filter means and standard deviations (via E[x²]−E[x]²) over
``subPatchSize`` windows, sampled at a 4×4 neighborhood around each keypoint
of a regular grid — 96-dim descriptors for RGB (4·4·3·2).

The reference runs per-image Scala while-loops over a conv2D helper
(utils/images/ImageUtils.scala:162-274: zero-padded 'same' separable
convolution); here both convolutions are batched XLA depthwise convs and the
neighborhood sampling is one static gather — whole batches stay in HBM.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.pipeline import Transformer, node


def _same_conv2d_zero(batch, xfilt, yfilt):
    """The reference conv2D: zero padding of filter_len−1 split
    floor/ceil (low/high), true convolution (filter reversed), output same
    size.  ``batch`` [N, H, W, C]; filters 1-D."""
    xk = jnp.asarray(xfilt[::-1].copy())
    yk = jnp.asarray(yfilt[::-1].copy())
    n, h, w, c = batch.shape
    xlen, ylen = xk.shape[0], yk.shape[0]
    # reference pads (len-1) total: low = floor((len-1)/2), high = rest
    pads = {
        1: ((ylen - 1) // 2, (ylen - 1) - (ylen - 1) // 2),
        2: ((xlen - 1) // 2, (xlen - 1) - (xlen - 1) // 2),
    }
    x = jnp.pad(
        batch, ((0, 0), pads[1], pads[2], (0, 0)), mode="constant"
    )
    x = jnp.moveaxis(x, -1, 1).reshape(n * c, 1, h + ylen - 1, w + xlen - 1)
    out = jax.lax.conv_general_dilated(
        x,
        yk.reshape(1, 1, ylen, 1),
        (1, 1),
        "VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    out = jax.lax.conv_general_dilated(
        out,
        xk.reshape(1, 1, 1, xlen),
        (1, 1),
        "VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    return jnp.moveaxis(out.reshape(n, c, h, w), 1, -1)


@node(meta_fields=("stride", "stride_start", "sub_patch_size"))
class LCSExtractor(Transformer):
    """Batched LCS: ``[N, H, W, C]`` -> ``[N, descDim, numKeypoints]``
    (descriptors as columns, the SIFT/BatchPCA convention).

    Keypoints: ``strideStart until dim−strideStart by stride`` in x and y,
    columns ordered x-major (reference :99-125); descriptor entries ordered
    channel-major, then (nx, ny) neighborhood, interleaving (mean, std)
    (reference :108-122).
    """

    def __init__(self, stride: int, stride_start: int, sub_patch_size: int):
        self.stride = stride
        self.stride_start = stride_start
        self.sub_patch_size = sub_patch_size

    def _keypoints(self, dim: int) -> np.ndarray:
        return np.arange(self.stride_start, dim - self.stride_start, self.stride)

    def _neighborhood(self) -> np.ndarray:
        s = self.sub_patch_size
        # reference :66-71: -2s + s/2 - 1  to  s + s/2 - 1  by s
        nbr = np.arange(-2 * s + s // 2 - 1, s + s // 2 - 1 + 1, s)
        # JAX would silently wrap negative sample coordinates to the far
        # edge (the Scala reference throws); fail loudly instead.
        if self.stride_start + nbr.min() < 0:
            raise ValueError(
                f"stride_start={self.stride_start} too small for "
                f"sub_patch_size={s}: sample offset {nbr.min()} would index "
                "before the image edge"
            )
        return nbr

    def num_keypoints(self, h: int, w: int) -> int:
        return len(self._keypoints(w)) * len(self._keypoints(h))

    def __call__(self, batch):
        n, h, w, c = batch.shape
        s = self.sub_patch_size
        box = np.full(s, 1.0 / s, np.float32)
        means = _same_conv2d_zero(batch, box, box)
        sq = _same_conv2d_zero(batch * batch, box, box)
        stds = jnp.sqrt(jnp.maximum(sq - means * means, 0.0))

        xs = self._keypoints(w)
        ys = self._keypoints(h)
        nbr = self._neighborhood()
        # all sampled positions: keypoint + neighbor offset
        sx = (xs[:, None] + nbr[None, :]).ravel()  # [Kx*4]
        sy = (ys[:, None] + nbr[None, :]).ravel()  # [Ky*4]

        def sample(img):  # [N, H, W, C] -> [N, Kx, 4, Ky, 4, C]
            g = img[:, jnp.asarray(sy), :, :][:, :, jnp.asarray(sx), :]
            g = g.reshape(n, len(ys), nbr.size, len(xs), nbr.size, c)
            # a = y-neighbor (ny), b = x-neighbor (nx); reference order is
            # nx outer, ny inner (:108-113)
            return jnp.einsum("nyaxbc->nxycba", g)  # [N,Kx,Ky,C,nx,ny]

        m = sample(means)
        sd = sample(stds)
        # interleave mean/std on a trailing axis -> [N,Kx,Ky,C,nx,ny,2]
        pairs = jnp.stack([m, sd], axis=-1)
        k_total = len(xs) * len(ys)
        desc = pairs.reshape(n, k_total, c * nbr.size * nbr.size * 2)
        return jnp.swapaxes(desc, 1, 2)  # [N, descDim, K]
