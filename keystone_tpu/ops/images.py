"""Image operator nodes (reference src/main/scala/nodes/images/).

Representation: a batch of images is a dense ``f32[N, H, W, C]`` array
(H = yDim rows, W = xDim cols).  The reference's
``ChannelMajorArrayVectorizedImage`` stores pixel (x, y, c) at index
``c + x*numChannels + y*numChannels*xDim`` (utils/images/Image.scala:19-317),
i.e. exactly the row-major flattening of ``[H, W, C]`` — so
:class:`ImageVectorizer` here is a plain reshape and produces bit-identical
vector layouts.

The big design change is :class:`Convolver`: the reference materializes an
im2col patch matrix per image and does one gemm
(nodes/images/Convolver.scala:93-136, :62).  On TPU the convolution maps
straight onto the MXU via ``lax.conv_general_dilated`` and the per-patch
normalization is recovered *algebraically* from box-filter sums (see
Convolver docstring) — no patch matrix ever exists in HBM.
"""

from __future__ import annotations

import math
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from ..core.pipeline import FunctionNode, Transformer, node


# ---------------------------------------------------------------------------
# Simple per-pixel nodes
# ---------------------------------------------------------------------------


@node(data_fields=(), meta_fields=())
class PixelScaler(Transformer):
    """Rescale [0..255] -> [0..1] (reference nodes/images/PixelScaler.scala:10-14)."""

    def __call__(self, batch):
        return batch / 255.0


@node(data_fields=(), meta_fields=())
class GrayScaler(Transformer):
    """NTSC grayscale (reference nodes/images/GrayScaler.scala:9-11,
    utils/images/ImageUtils.scala:55-87).  3-channel input is assumed BGR
    (as the reference assumes): ``0.2989*R + 0.5870*G + 0.1140*B``; any other
    channel count uses sqrt of the mean of squares.  Output keeps a trailing
    singleton channel axis."""

    def __call__(self, batch):
        c = batch.shape[-1]
        if c == 3:
            w = jnp.array([0.1140, 0.5870, 0.2989], batch.dtype)  # B, G, R
            out = jnp.einsum("...c,c->...", batch, w)
        else:
            out = jnp.sqrt(jnp.mean(batch * batch, axis=-1))
        return out[..., None]


@node(data_fields=(), meta_fields=())
class ImageVectorizer(Transformer):
    """Flatten [N,H,W,C] -> [N, H*W*C]; identical element order to the
    reference's channel-major ``Image.toArray``
    (nodes/images/ImageVectorizer.scala:11-15)."""

    def __call__(self, batch):
        return batch.reshape(batch.shape[0], -1)


@node(data_fields=(), meta_fields=("max_val", "alpha"))
class SymmetricRectifier(Transformer):
    """Two-sided ReLU; channels double: ``[max(v, x-a), max(v, -x-a)]``
    (reference nodes/images/SymmetricRectifier.scala:6-32).  Positive parts
    occupy channels [0, C), negative parts [C, 2C), as in the reference."""

    def __init__(self, max_val: float = 0.0, alpha: float = 0.0):
        self.max_val = max_val
        self.alpha = alpha

    def __call__(self, batch):
        pos = jnp.maximum(self.max_val, batch - self.alpha)
        neg = jnp.maximum(self.max_val, -batch - self.alpha)
        return jnp.concatenate([pos, neg], axis=-1)


# ---------------------------------------------------------------------------
# Windower — strided patch extraction
# ---------------------------------------------------------------------------


class Windower(FunctionNode):
    """All strided square patches of each image
    (reference nodes/images/Windower.scala:13-58).

    [N,H,W,C] -> [N * nWin, ws, ws, C].  Patch order matches the reference's
    flatMap order: x (column) outer, y (row) inner.
    """

    def __init__(self, stride: int, window_size: int):
        self.stride = stride
        self.window_size = window_size

    def __call__(self, batch):
        n, h, w, c = batch.shape
        ws, st = self.window_size, self.stride
        xs = jnp.arange(0, w - ws + 1, st)
        ys = jnp.arange(0, h - ws + 1, st)
        # grid ordered x-outer, y-inner (reference :27-28)
        gx = jnp.repeat(xs, ys.shape[0])
        gy = jnp.tile(ys, xs.shape[0])

        def one_window(img, x, y):
            return lax.dynamic_slice(img, (y, x, 0), (ws, ws, c))

        per_image = jax.vmap(one_window, in_axes=(None, 0, 0))
        wins = jax.vmap(lambda img: per_image(img, gx, gy))(batch)
        return wins.reshape(n * gx.shape[0], ws, ws, c)


# ---------------------------------------------------------------------------
# Pooler
# ---------------------------------------------------------------------------


@node(data_fields=(), meta_fields=("stride", "pool_size", "pixel_function", "pool_function"))
class Pooler(Transformer):
    """Strided pooling over square regions
    (reference nodes/images/Pooler.scala:20-68).

    Pool centers start at ``strideStart = poolSize/2`` and step by ``stride``;
    each pool covers ``[x - ps//2, min(x + ps//2, dim))`` — edge pools are
    truncated, and (as in the reference, where the pool buffer is a fixed
    ``poolSize²`` zero-filled vector) truncated regions contribute zeros.

    ``pixel_function`` maps each pixel first (e.g. ``jnp.abs``);
    ``pool_function`` is ``'sum'``, ``'mean'`` or ``'max'`` — mean divides by
    the fixed ``poolSize²`` and max sees the pad zeros in truncated edge
    pools, exactly like the reference's zero-filled pool vector.
    """

    def __init__(
        self,
        stride: int,
        pool_size: int,
        pixel_function: Callable | None = None,
        pool_function: str = "sum",
    ):
        if pool_function not in ("sum", "mean", "max"):
            raise ValueError("pool_function must be 'sum', 'mean' or 'max'")
        self.stride = stride
        self.pool_size = pool_size
        self.pixel_function = pixel_function
        self.pool_function = pool_function

    def _num_pools(self, dim: int) -> int:
        stride_start = self.pool_size // 2
        return math.ceil((dim - stride_start) / self.stride)

    def __call__(self, batch):
        n, h, w, c = batch.shape
        ps, st = self.pool_size, self.stride
        half = ps // 2
        stride_start = half
        np_x = self._num_pools(w)
        np_y = self._num_pools(h)

        x = batch if self.pixel_function is None else self.pixel_function(batch)

        # Window origins: strideStart + i*stride - ps//2 = i*stride; windows
        # span ps pixels (even ps) or 2*(ps//2) pixels (odd ps, matching the
        # reference's [x-ps/2, x+ps/2) bound), truncated at the high edge.
        span = 2 * half if ps % 2 == 1 else ps
        # Pad the high edge with zeros so every window is full-size.
        pad_h = max(0, (np_y - 1) * st + span - h)
        pad_w = max(0, (np_x - 1) * st + span - w)
        x = jnp.pad(x, ((0, 0), (0, pad_h), (0, pad_w), (0, 0)))

        if self.pool_function == "max":
            init, op = -jnp.inf, lax.max
        else:
            init, op = 0.0, lax.add
        pooled = lax.reduce_window(
            x,
            jnp.asarray(init, x.dtype),
            op,
            window_dimensions=(1, span, span, 1),
            window_strides=(1, st, st, 1),
            padding="VALID",
        )
        pooled = pooled[:, :np_y, :np_x, :]
        if self.pool_function == "mean":
            pooled = pooled / float(ps * ps)
        elif self.pool_function == "max" and span < ps:
            # Odd pool_size: the reference's fixed poolSize² zero-filled pool
            # buffer (Pooler.scala:43) is never fully overwritten (the window
            # spans only (ps-1)² pixels), so its max always sees zeros.
            pooled = jnp.maximum(pooled, 0.0)
        return pooled


# ---------------------------------------------------------------------------
# Convolver
# ---------------------------------------------------------------------------


@node(
    data_fields=("filters", "whitener_means", "filter_means_dot"),
    meta_fields=("normalize_patches", "var_constant"),
)
class Convolver(Transformer):
    """Convolve a filter bank over images with optional per-patch
    normalization (reference nodes/images/Convolver.scala:19-154).

    The reference builds an explicit im2col patch matrix, normalizes each
    patch row (``Stats.normalizeRows`` with additive ``varConstant``,
    Convolver.scala:128), subtracts ZCA means, and gemms with the filter bank
    (:62).  TPU-native formulation: for a patch ``p`` (d = ws·ws·C elements),
    normalized ``p' = (p - μ·1)/σ  - m`` with ``μ = Σp/d``,
    ``σ = sqrt((Σp² - d μ²)/(d-1) + varConstant)``, so for filter ``f``:

        f·p' = (f·p − μ·Σf) / σ − f·m

    ``f·p`` is one conv with the filter bank; ``Σp`` and ``Σp²`` come from a
    channel-summed box filter over the image and its square — three
    MXU convolutions replace the patch matrix entirely.

    ``filters``: [F, ws, ws, C] (HWC patch layout, matching the reference's
    ``c + x*C + y*C*ws`` row-major order) or [F, ws*ws*C] flat.
    """

    def __init__(
        self,
        filters,
        whitener_means=None,
        normalize_patches: bool = True,
        var_constant: float = 10.0,
        img_channels: int | None = None,
    ):
        filters = jnp.asarray(filters)
        if filters.ndim == 2:
            if img_channels is None:
                raise ValueError("img_channels required for flat filters")
            ws = int(math.isqrt(filters.shape[1] // img_channels))
            filters = filters.reshape(filters.shape[0], ws, ws, img_channels)
        self.filters = filters
        self.normalize_patches = normalize_patches
        self.var_constant = var_constant
        self.whitener_means = (
            None if whitener_means is None else jnp.asarray(whitener_means)
        )
        # f·m per filter, folded into the output as a bias (reference
        # subtracts means from every patch row; dotting with filters is
        # equivalent and free).
        if self.whitener_means is not None:
            flat = self.filters.reshape(self.filters.shape[0], -1)
            self.filter_means_dot = flat @ self.whitener_means
        else:
            self.filter_means_dot = None

    @property
    def conv_size(self) -> int:
        return self.filters.shape[1]

    def __call__(self, batch):
        f, ws, _, c = self.filters.shape
        if batch.shape[-1] != c:
            raise ValueError(
                f"image channels {batch.shape[-1]} != filter channels {c}"
            )
        dn = lax.conv_dimension_numbers(
            batch.shape, (ws, ws, c, f), ("NHWC", "HWIO", "NHWC")
        )
        kernel = jnp.moveaxis(self.filters, 0, -1)  # [ws, ws, C, F]
        conv_fp = lax.conv_general_dilated(
            batch, kernel, (1, 1), "VALID", dimension_numbers=dn
        )

        if self.normalize_patches:
            d = ws * ws * c
            ones = jnp.ones((ws, ws, c, 1), batch.dtype)
            dn1 = lax.conv_dimension_numbers(
                batch.shape, (ws, ws, c, 1), ("NHWC", "HWIO", "NHWC")
            )
            psum = lax.conv_general_dilated(
                batch, ones, (1, 1), "VALID", dimension_numbers=dn1
            )
            psumsq = lax.conv_general_dilated(
                batch * batch, ones, (1, 1), "VALID", dimension_numbers=dn1
            )
            mu = psum / d
            var = (psumsq - d * mu * mu) / (d - 1.0)
            sigma = jnp.sqrt(var + self.var_constant)
            fsum = jnp.sum(self.filters, axis=(1, 2, 3))  # Σf per filter
            out = (conv_fp - mu * fsum) / sigma
        else:
            out = conv_fp

        if self.filter_means_dot is not None:
            out = out - self.filter_means_dot
        return out


# ---------------------------------------------------------------------------
# Label extractors (reference nodes/images/LabeledImageExtractors.scala:8-32)
# ---------------------------------------------------------------------------


@node(data_fields=(), meta_fields=())
class ImageExtractor(Transformer):
    """LabeledImage batch -> images (field extractor)."""

    def __call__(self, labeled):
        return labeled.images


@node(data_fields=(), meta_fields=())
class LabelExtractor(Transformer):
    """LabeledImage batch -> labels."""

    def __call__(self, labeled):
        return labeled.labels


MultiLabelExtractor = LabelExtractor
MultiLabeledImageExtractor = ImageExtractor
