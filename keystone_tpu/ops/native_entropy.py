"""ctypes binding for the native entropy-decode hot loop (native/entropy.cpp).

The device-resident decode path keeps header parsing, Huffman LUT
compilation, and restart-segment splitting in Python
(ops/jpeg_device.entropy_decode) and hands ONLY the O(compressed-bytes)
symbol loop to this library — the same split libjpeg draws between its
marker reader and ``decode_mcu``.  The shared library is built lazily with
the system g++ on first use (no libjpeg or any other dependency) and
cached next to the source, mirroring loaders/native_decode.py's contract:
a transient build failure retries with backoff, a real one degrades to
the pure-Python loop counted ``native_entropy_unavailable`` and logged
once per process — the stream stays bit-equal either way, because both
loops implement the identical algorithm (tier-1 asserts it).

ctypes releases the GIL for the duration of each ``decode_scan`` call, so
the ingest thread pool finally scales the entropy pass across host cores
— the pure-Python loop serialized every producer behind the GIL.

``KEYSTONE_NATIVE_ENTROPY=0`` forces the Python pass; the gate lives in
:func:`enabled` (re-read per call, NOT latched at first load) so tests and
benchmarks can toggle backends without :func:`reset`.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

_logger = logging.getLogger(__name__)

#: Env knob: ``0`` forces the pure-Python entropy pass (portable
#: fallback); anything else builds/loads the native loop on first use.
NATIVE_ENTROPY_ENV = "KEYSTONE_NATIVE_ENTROPY"

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "native")
_SRC = os.path.join(_NATIVE_DIR, "entropy.cpp")
_LIB = os.path.join(_NATIVE_DIR, "libkstentropy.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False
_reported = False  # degradation counted/logged once per process

#: C return code -> the EXACT JpegEntropyCorrupt message the Python loop
#: raises (keep in sync with the KST_E* enum in native/entropy.cpp).
#: Formatted with err_info[0] (mcu), err_info[1] (DC category) and
#: total_mcus.
_ERR_MESSAGES = {
    1: "invalid Huffman code or truncated scan (mcu {e0}/{total})",
    2: "ZRL overflows the block",
    3: "AC run overflows the block",
    4: "DC category {e1} out of range",
    5: "truncated scan mid-coefficient",
    6: "DC predictor out of int16 range",
    7: "decoded {e0} of {total} MCUs (truncated scan)",
}


def _build() -> bool:
    from ..core.resilience import retry

    cmd = ["g++", "-O2", "-shared", "-fPIC", _SRC, "-o", _LIB]

    # Same build contract as native_decode: fork failures / filesystem
    # hiccups retry with backoff; a compile blowing the 120 s timeout is
    # not transient and fails straight to the Python pass.
    @retry(retry_on=(OSError,), name="native_entropy_build")
    def _run():
        return subprocess.run(cmd, capture_output=True, timeout=120)

    try:
        res = _run()
    except (OSError, subprocess.TimeoutExpired):
        return False
    return res.returncode == 0 and os.path.exists(_LIB)


def _report_unavailable(why: str) -> None:
    """Count + log the native->Python degradation ONCE per process — a
    silently slow entropy pass would look exactly like a regression."""
    global _reported
    if _reported:
        return
    _reported = True
    _logger.warning(
        "native entropy decoder unavailable (%s); using the pure-Python "
        "pass — streams stay bit-equal, throughput drops", why,
    )
    try:
        from ..core.resilience import counters

        counters.record(
            "native_entropy_unavailable",
            f"{why}: entropy decode degraded to the pure-Python pass",
        )
    except Exception:  # noqa: BLE001 — accounting must never block decode
        pass


def _load() -> ctypes.CDLL | None:
    """Build (first use only) + dlopen the native entropy loop.

    Call this (via :func:`available`) BEFORE entering a decode hot path:
    the one-time g++ build runs under the module lock, so a lazy first
    call from inside the ingest thread pool would stall every producer
    behind it (core.ingest prewarms in the device-mode producer).  The
    env gate is deliberately NOT consulted here — callers check
    :func:`enabled` per call so toggling the knob needs no reset."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        try:
            if not os.path.exists(_LIB) or os.path.getmtime(
                _LIB
            ) < os.path.getmtime(_SRC):
                if not _build():
                    _report_unavailable("build failed")
                    return None
            lib = ctypes.CDLL(_LIB)
        except OSError:
            _report_unavailable("load failed")
            return None
        u8pp = ctypes.POINTER(ctypes.c_char_p)
        lib.kst_entropy_decode.argtypes = [
            u8pp,                                    # segs
            ctypes.POINTER(ctypes.c_longlong),       # seg_lens
            ctypes.c_int,                            # nseg
            ctypes.POINTER(ctypes.POINTER(ctypes.c_short)),  # planes
            ctypes.POINTER(ctypes.c_int),            # row_width
            ctypes.POINTER(ctypes.c_int),            # mcu_blocks
            ctypes.c_int,                            # n_mcu_blocks
            u8pp,                                    # lut_len
            u8pp,                                    # lut_sym
            ctypes.c_char_p,                         # zigzag
            ctypes.c_int,                            # ncomp
            ctypes.c_longlong,                       # mcus_x
            ctypes.c_longlong,                       # total_mcus
            ctypes.c_longlong,                       # interval
            ctypes.POINTER(ctypes.c_longlong),       # err_info
        ]
        lib.kst_entropy_decode.restype = ctypes.c_int
        _lib = lib
        return _lib


def enabled() -> bool:
    """The env gate, re-read on every call: ``KEYSTONE_NATIVE_ENTROPY=0``
    forces the Python pass without touching the cached build state."""
    return os.environ.get(NATIVE_ENTROPY_ENV, "").strip() != "0"


def available() -> bool:
    """True when the native loop is enabled AND built/loadable.  Triggers
    the lazy build, so call it from setup code (not per image) where the
    one-time g++ cost is acceptable."""
    return enabled() and _load() is not None


def reset() -> None:
    """Forget the cached build/load outcome (under the module lock) so the
    next call re-evaluates the library state, and re-arm the once-per-
    process degradation report.  Public hook for tests that simulate
    build failure — poking ``_tried``/``_lib`` directly would race any
    live decode thread."""
    global _lib, _tried, _reported
    with _lock:
        _tried = False
        _lib = None
        _reported = False


def _zigzag_bytes() -> bytes:
    from .jpeg_device import ZIGZAG

    return ZIGZAG.astype(np.uint8).tobytes()


_zz_cache: bytes | None = None


def decode_scan(
    segments, planes, mcu_blocks, ncomp, mcus_x, total_mcus, interval
) -> bool:
    """Native drop-in for ops/jpeg_device._decode_scan — identical
    arguments, identical plane writes, identical typed errors.

    Returns False (planes untouched) when the library is unavailable so
    the caller runs the Python loop; True after a successful native
    decode.  A damaged scan raises :class:`JpegEntropyCorrupt` with the
    same message the Python loop produces for the same stream."""
    global _zz_cache
    lib = _load()
    if lib is None:
        return False

    nseg = len(segments)
    seg_arr = (ctypes.c_char_p * nseg)(*segments)
    len_arr = (ctypes.c_longlong * nseg)(*(len(s) for s in segments))

    plane_ptrs = (ctypes.POINTER(ctypes.c_short) * len(planes))(
        *(p.ctypes.data_as(ctypes.POINTER(ctypes.c_short)) for p in planes)
    )
    widths = (ctypes.c_int * len(planes))(*(p.shape[1] for p in planes))

    # Dedup the _HuffLUT objects (the LUT byte tables are 64 KiB each and
    # shared across blocks/components) and flatten mcu_blocks to the 7-int
    # rows the C loop indexes.
    lut_index: dict[int, int] = {}
    lut_len: list[bytes] = []
    lut_sym: list[bytes] = []

    def _lut(lut) -> int:
        idx = lut_index.get(id(lut))
        if idx is None:
            idx = len(lut_len)
            lut_index[id(lut)] = idx
            lut_len.append(lut.length_b)
            lut_sym.append(lut.symbol_b)
        return idx

    flat = []
    for ci, v, h, by, bx, dc_lut, ac_lut in mcu_blocks:
        flat.extend((ci, v, h, by, bx, _lut(dc_lut), _lut(ac_lut)))
    mb_arr = (ctypes.c_int * len(flat))(*flat)
    len_ptrs = (ctypes.c_char_p * len(lut_len))(*lut_len)
    sym_ptrs = (ctypes.c_char_p * len(lut_sym))(*lut_sym)

    if _zz_cache is None:
        _zz_cache = _zigzag_bytes()
    err = (ctypes.c_longlong * 2)(0, 0)

    rc = lib.kst_entropy_decode(
        seg_arr, len_arr, nseg,
        plane_ptrs, widths,
        mb_arr, len(mcu_blocks),
        len_ptrs, sym_ptrs, _zz_cache,
        ncomp, mcus_x, total_mcus, interval, err,
    )
    if rc == 0:
        return True
    from .jpeg_device import JpegEntropyCorrupt

    msg = _ERR_MESSAGES.get(rc, "native entropy decode error {e0}")
    raise JpegEntropyCorrupt(
        msg.format(e0=int(err[0]), e1=int(err[1]), total=total_mcus)
    )
