"""N-gram language modeling: indexers, counts, Stupid Backoff
(reference src/main/scala/nodes/nlp/indexers.scala:5-135, ngrams.scala:98-183,
StupidBackoff.scala:25-182).

N-grams are plain tuples (hashable, ordered — the NGram wrapper class exists
in the reference only to give Scala Seqs sane hashCode/equals).

The reference's ``InitialBigramPartitioner`` co-locates every ngram with its
backoff context by hash-partitioning on the first two context words; in the
single-controller design the whole count table lives in one host dict, and
:func:`shard_by_initial_bigram` provides the same sharding function for the
multi-host layout (each shard then scores its ngrams purely locally, as the
reference's partitions do).
"""

from __future__ import annotations

import zlib
from collections import defaultdict
from typing import Sequence

from ..core.pipeline import Estimator, FunctionNode

MAX_WORD = 1 << 20


class NGramIndexerImpl:
    """Tuple-backed indexer (reference indexers.scala:113-135)."""

    min_ngram_order = 1
    max_ngram_order = 5

    def pack(self, ngram: Sequence) -> tuple:
        return tuple(ngram)

    def unpack(self, ngram: tuple, pos: int):
        return ngram[pos]

    def remove_farthest_word(self, ngram: tuple) -> tuple:
        return ngram[1:]

    def remove_current_word(self, ngram: tuple) -> tuple:
        return ngram[:-1]

    def ngram_order(self, ngram: tuple) -> int:
        return len(ngram)


class NaiveBitPackIndexer:
    """Pack <=3 word ids (each < 2^20) into one 64-bit int
    (reference indexers.scala:42-111).  Layout, most significant first:
    [4 control bits][farthest word][middle][current]; left-aligned.
    Control bits: 0=unigram, 1=bigram, 2=trigram."""

    min_ngram_order = 1
    max_ngram_order = 3

    @staticmethod
    def pack(ngram: Sequence[int]) -> int:
        for w in ngram:
            if w >= MAX_WORD:
                raise ValueError(f"word id {w} >= 2^20")
        n = len(ngram)
        if n == 1:
            return ngram[0] << 40
        if n == 2:
            return (ngram[1] << 20) | (ngram[0] << 40) | (1 << 60)
        if n == 3:
            return ngram[2] | (ngram[1] << 20) | (ngram[0] << 40) | (1 << 61)
        raise ValueError("ngram order need to be in { 1, 2, 3 } for now")

    @staticmethod
    def unpack(ngram: int, pos: int) -> int:
        if pos == 0:
            return (ngram >> 40) & (MAX_WORD - 1)
        if pos == 1:
            return (ngram >> 20) & (MAX_WORD - 1)
        if pos == 2:
            return ngram & (MAX_WORD - 1)
        raise ValueError("position must be in { 0, 1, 2 }")

    @classmethod
    def ngram_order(cls, ngram: int) -> int:
        order = (ngram >> 60) & 0xF
        if not (cls.min_ngram_order <= order + 1 <= cls.max_ngram_order):
            raise ValueError(f"raw control bits {order} are invalid")
        return order + 1

    @classmethod
    def remove_farthest_word(cls, ngram: int) -> int:
        order = cls.ngram_order(ngram)
        if order == 2:
            return (ngram & ((1 << 40) - 1)) << 20
        if order == 3:
            return ((ngram & ((1 << 40) - 1)) << 20) | (1 << 60)
        raise ValueError(f"ngram order is either invalid or not supported: {order}")

    @classmethod
    def remove_current_word(cls, ngram: int) -> int:
        order = cls.ngram_order(ngram)
        if order == 2:
            return ngram & ~((1 << 40) - 1) & ~(0xF << 60)
        if order == 3:
            return (ngram & ~((1 << 20) - 1) & ~(0xF << 60)) | (1 << 60)
        raise ValueError(f"ngram order is either invalid or not supported: {order}")


class NGramsCounts(FunctionNode):
    """Count ngram tuples over a corpus of per-line ngram lists
    (reference ngrams.scala:140-183).  'Default' mode returns counts sorted
    by frequency descending; 'noAdd' returns the unsorted dict."""

    def __init__(self, mode: str = "default"):
        if mode not in ("default", "noAdd"):
            raise ValueError("`mode` must be `default` or `noAdd`")
        self.mode = mode

    def __call__(self, lines):
        counts: dict = defaultdict(int)
        for line in lines:
            for gram in line:
                counts[tuple(gram)] += 1
        if self.mode == "default":
            return sorted(counts.items(), key=lambda kv: -kv[1])
        return list(counts.items())


def shard_by_initial_bigram(ngram: tuple, num_shards: int, indexer=None) -> int:
    """The InitialBigramPartitioner function (reference StupidBackoff.scala:25-58):
    ngrams sharing their first two context words land on the same shard, so
    backoff scoring is shard-local."""
    indexer = indexer or NGramIndexerImpl()
    if indexer.ngram_order(ngram) > 1:
        first = indexer.unpack(ngram, 0)
        second = indexer.unpack(ngram, 1)
        # stable across processes (builtin hash() is salted per process —
        # a multi-host layout needs every host to agree on the shard)
        key = repr((first, second)).encode()
        return zlib.crc32(key) % num_shards
    return 0


class StupidBackoffModel:
    """Stupid Backoff LM scores (Brants et al. 2007; reference
    StupidBackoff.scala:97-127).

    S(w | context) = freq(ngram)/freq(context) when seen, else
    α·S(w | shorter context);  S(w) = freq(w)/N.
    """

    def __init__(
        self,
        ngram_counts: dict,
        unigram_counts: dict,
        num_tokens: int,
        alpha: float = 0.4,
        indexer: NGramIndexerImpl | None = None,
    ):
        self.ngram_counts = ngram_counts
        self.unigram_counts = unigram_counts
        self.num_tokens = num_tokens
        self.alpha = alpha
        self.indexer = indexer or NGramIndexerImpl()

    def _count(self, ngram: tuple) -> int:
        return self.ngram_counts.get(ngram, 0)

    def score(self, ngram: Sequence) -> float:
        """Recursive backoff scoring (reference scoreLocally :63-95)."""
        ngram = tuple(ngram)
        ix = self.indexer
        accum = 1.0
        freq = self._count(ngram)
        while True:
            order = ix.ngram_order(ngram)
            if order == 1:
                return accum * freq / self.num_tokens
            if freq != 0:
                context = ix.remove_current_word(ngram)
                if order != 2:
                    context_freq = self._count(context)
                else:
                    context_freq = self.unigram_counts.get(ix.unpack(context, 0), 0)
                if context_freq == 0:
                    raise ValueError(
                        f"ngram {ngram} has count {freq} but its context "
                        f"{context} has zero count — fit with consecutive "
                        "orders (including the context order)"
                    )
                return accum * freq / context_freq
            # out-of-corpus ngram: back off
            ngram = ix.remove_farthest_word(ngram)
            order = ix.ngram_order(ngram)
            if order != 1:
                freq = self._count(ngram)
            else:
                freq = self.unigram_counts.get(ix.unpack(ngram, 0), 0)
            accum *= self.alpha

    def scores(self) -> dict:
        """Score every counted ngram (the reference's scoresRDD)."""
        out = {}
        for ngram, _freq in self.ngram_counts.items():
            s = self.score(ngram)
            if not (0.0 <= s <= 1.0):
                raise AssertionError(f"score = {s:.4f} not in [0,1], ngram = {ngram}")
            out[ngram] = s
        return out


def sharded_scores(
    ngram_counts: dict,
    unigram_counts: dict,
    num_shards: int,
    alpha: float = 0.4,
    indexer: NGramIndexerImpl | None = None,
    queries=None,
) -> tuple[dict, dict]:
    """Score every counted ngram through the SHARDED path the reference's
    InitialBigramPartitioner implies (StupidBackoff.scala:25-58): the count
    table is partitioned by :func:`shard_by_initial_bigram`, each shard
    scores its ngrams against ONLY its local counts (plus the broadcast
    unigram table, which the reference also replicates), and an ngram whose
    backoff shortens past its shard's key — removing the farthest word
    changes the first two words, i.e. the shard — is re-routed to the
    owning shard for the next round with its accumulated alpha, exactly the
    shuffle a multi-host run would perform.  At most ``max_order - 1``
    rounds.

    ``queries``: the ngrams to score — default every counted ngram (the
    reference's scoresRDD).  Counted ngrams score shard-locally in one
    round; UNSEEN queries exercise the backoff re-route.

    Returns ``(scores, shard_sizes)``; the scores are identical to the
    single-table :meth:`StupidBackoffModel.score` (asserted by the
    workload), because each lookup happens on the shard that owns the
    ngram — the co-location invariant made executable."""
    ix = indexer or NGramIndexerImpl()
    num_tokens = sum(unigram_counts.values())
    shards: dict[int, dict] = defaultdict(dict)
    for ngram, cnt in ngram_counts.items():
        shards[shard_by_initial_bigram(ngram, num_shards, ix)][ngram] = cnt
    shard_sizes = {s: len(tab) for s, tab in shards.items()}

    scores: dict = {}
    # Work item: (original ngram, current backoff form, accumulated alpha,
    # backed_off), grouped by the shard owning the CURRENT form.
    work: dict[int, list] = defaultdict(list)
    for ngram in (queries if queries is not None else ngram_counts):
        ngram = ix.pack(ngram) if isinstance(ngram, (list, tuple)) else ngram
        work[shard_by_initial_bigram(ngram, num_shards, ix)].append(
            (ngram, ngram, 1.0, False)
        )
    while work:
        next_work: dict[int, list] = defaultdict(list)
        for shard_id, items in work.items():
            local = shards.get(shard_id, {})
            for orig, ngram, accum, backed_off in items:
                order = ix.ngram_order(ngram)
                if order == 1:
                    # Parity with StupidBackoffModel.score: a DIRECT
                    # order-1 query reads the ngram table (orders 2..n, so
                    # usually 0); only a BACKED-OFF unigram reads the
                    # broadcast unigram table.
                    freq = (
                        unigram_counts.get(ix.unpack(ngram, 0), 0)
                        if backed_off
                        else local.get(ngram, 0)
                    )
                    scores[orig] = accum * freq / num_tokens
                    continue
                freq = local.get(ngram, 0)
                if freq != 0:
                    context = ix.remove_current_word(ngram)
                    if order != 2:
                        # same first two words as the ngram: SHARD-LOCAL by
                        # the co-location invariant
                        context_freq = local.get(context, 0)
                    else:
                        context_freq = unigram_counts.get(
                            ix.unpack(context, 0), 0
                        )
                    if context_freq == 0:
                        raise ValueError(
                            f"ngram {ngram} has count {freq} but its "
                            f"context {context} has zero count on shard "
                            f"{shard_id} — fit with consecutive orders"
                        )
                    scores[orig] = accum * freq / context_freq
                    continue
                # Back off: the shortened form may live on another shard —
                # the re-route is the multi-host shuffle.
                shorter = ix.remove_farthest_word(ngram)
                next_work[
                    shard_by_initial_bigram(shorter, num_shards, ix)
                ].append((orig, shorter, accum * alpha, True))
        work = next_work
    return scores, shard_sizes


class StupidBackoffEstimator(Estimator):
    """Fit from (ngram, count) pairs (reference StupidBackoffEstimator:149-182)."""

    def __init__(self, unigram_counts: dict, alpha: float = 0.4):
        self.unigram_counts = unigram_counts
        self.alpha = alpha

    def fit(self, data) -> StupidBackoffModel:
        counts: dict = defaultdict(int)
        for ngram, cnt in data:
            counts[tuple(ngram)] += cnt
        num_tokens = sum(self.unigram_counts.values())
        return StupidBackoffModel(
            dict(counts), self.unigram_counts, num_tokens, self.alpha
        )
