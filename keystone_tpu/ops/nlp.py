"""NLP string/ngram nodes (reference src/main/scala/nodes/nlp/StringUtils.scala:13-31,
ngrams.scala:18-183, TermFrequency at nodes/stats/TermFrequency.scala:18-20).

These are host-side (strings never touch the TPU); batches are Python lists.
The TPU enters downstream, once sparse features are vectorized (ops.sparse).
"""

from __future__ import annotations

import re
from collections import defaultdict
from typing import Callable, Sequence

from ..core.pipeline import Transformer


class Tokenizer(Transformer):
    """Split on a regex, default all punctuation+whitespace
    (reference StringUtils.scala:13-16).  Matches Scala ``String.split``
    semantics: leading empty strings are kept, trailing removed."""

    def __init__(self, sep: str = r"[^\w]+" "|_"):
        # \p{Punct}+whitespace ~ non-word chars plus underscore in Python re
        self.sep = sep
        self._re = re.compile(sep)

    def __call__(self, batch: Sequence[str]):
        out = []
        for line in batch:
            toks = self._re.split(line)
            while toks and toks[-1] == "":
                toks.pop()
            out.append(toks)
        return out


class Trim(Transformer):
    """Strip leading/trailing whitespace (reference StringUtils.scala:21-23)."""

    def __call__(self, batch: Sequence[str]):
        return [s.strip() for s in batch]


class LowerCase(Transformer):
    """Lowercase (reference StringUtils.scala:29-31)."""

    def __call__(self, batch: Sequence[str]):
        return [s.lower() for s in batch]


class NGramsFeaturizer(Transformer):
    """All n-grams of consecutive orders [min..max]
    (reference ngrams.scala:18-89).  Tokens -> list of tuples, emitted in the
    reference's order: at each position, the min-order gram then its
    extensions to max order."""

    def __init__(self, orders: Sequence[int]):
        orders = list(orders)
        if min(orders) < 1:
            raise ValueError(f"minimum order is not >= 1, found {min(orders)}")
        for a, b in zip(orders, orders[1:]):
            if b != a + 1:
                raise ValueError(f"orders are not consecutive; contains {a} and {b}")
        self.min_order = orders[0]
        self.max_order = orders[-1]

    def __call__(self, batch):
        out = []
        for tokens in batch:
            grams = []
            n = len(tokens)
            for i in range(n - self.min_order + 1):
                for order in range(
                    self.min_order, min(self.max_order, n - i) + 1
                ):
                    grams.append(tuple(tokens[i : i + order]))
            out.append(grams)
        return out


class TermFrequency(Transformer):
    """Term counts with a weighting function applied to the raw count
    (reference nodes/stats/TermFrequency.scala:18-20) — e.g. ``lambda x: 1``
    for binary presence, identity for raw TF."""

    def __init__(self, fn: Callable = lambda x: x):
        self.fn = fn

    def __call__(self, batch):
        out = []
        for terms in batch:
            counts: dict = defaultdict(int)
            for t in terms:
                counts[t] += 1
            out.append([(t, self.fn(c)) for t, c in counts.items()])
        return out


class WordFrequencyEncoder(Transformer):
    """Fitted via :func:`word_frequency_encoder`: maps words to their
    frequency rank (0 = most frequent), OOV -> -1
    (reference nodes/nlp/WordFrequencyEncoder.scala:8-63)."""

    def __init__(self, word_index: dict, unigram_counts: dict):
        self.word_index = word_index
        self.unigram_counts = unigram_counts

    def __call__(self, batch):
        wi = self.word_index
        return [[wi.get(tok, -1) for tok in tokens] for tokens in batch]


def fit_word_frequency_encoder(corpus) -> WordFrequencyEncoder:
    """Rank words by corpus frequency (reference WordFrequencyEncoder:16-40)."""
    counts: dict = defaultdict(int)
    for tokens in corpus:
        for t in tokens:
            counts[t] += 1
    ranked = sorted(counts.items(), key=lambda kv: -kv[1])
    word_index = {w: i for i, (w, _) in enumerate(ranked)}
    unigram_counts = {word_index[w]: c for w, c in counts.items()}
    return WordFrequencyEncoder(word_index, unigram_counts)
