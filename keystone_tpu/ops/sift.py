"""Multi-scale dense SIFT — TPU-native replacement for the reference's
VLFeat JNI kernel (src/main/cpp/VLFeat.cxx:37-292, wrapping vlfeat-0.9.20
``vl_dsift``; Scala surface src/main/scala/nodes/images/external/SIFTExtractor.scala:16-40).

Per scale ``s`` (reference VLFeat.cxx:68-123):
  * bin size ``b = bin + 2s``; sampling step ``step + s*scaleStep``;
  * Gaussian smooth with σ = b/magnif, magnif = 6.0 (:85-90);
  * bounds offset ``off = (1+2S) - 3s`` so scale grids share their origin
    when steps coincide (:93-95);
  * flat-window mode, windowSize 1.5 (:98-102) — uniform descriptor
    weighting, which cancels under L2 normalization;
  * descriptors: 4x4 spatial bins × 8 orientations; gradient magnitudes
    split bilinearly between adjacent orientation bins; each orientation
    plane convolved with a triangular kernel of half-width ``b`` (the
    bilinear spatial interpolation, vl_imconvcoltri) and sampled at bin
    centers ``origin + bin_idx*b``;
  * L2 normalize → clamp 0.2 → renormalize; descriptors with pre-norm
    below contrastthreshold=0.005 are zeroed (:62,167-169);
  * quantize ``min(floor(512·v), 255)`` (:249-263).

Everything is batched ``[N, H, W]`` XLA ops — conv, gather, vmap — so whole
image batches stay in HBM (the reference pays a JVM→C JNI crossing per
image).  Descriptor count per image is static given (H, W, params), which
keeps shapes XLA-friendly; variable-size image sets bucket by shape upstream.

Descriptor layout note: the reference transposes each descriptor
(vl_dsift_transpose_descriptor, VLFeat.cxx:256) to undo its x/y-swapped
image layout; we compute directly in (row=y, col=x) convention so no
transpose is needed — the 128 dims are a fixed permutation of the
reference's, which is irrelevant to downstream PCA/GMM/FV learning.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.pipeline import Transformer, node

MAGNIF = 6.0
CONTRAST_THRESHOLD = 0.005
NUM_BIN_T = 8
NUM_BIN_XY = 4
DESC_DIM = NUM_BIN_T * NUM_BIN_XY * NUM_BIN_XY  # 128


def _gaussian_kernel(sigma: float) -> np.ndarray:
    radius = max(1, int(math.ceil(4.0 * sigma)))
    x = np.arange(-radius, radius + 1, dtype=np.float64)
    k = np.exp(-0.5 * (x / sigma) ** 2)
    return (k / k.sum()).astype(np.float32)


def _triangular_kernel(bin_size: int) -> np.ndarray:
    # vl_imconvcoltri: triangle of half-width bin_size, unit integral
    t = np.concatenate(
        [np.arange(1, bin_size + 1), np.arange(bin_size - 1, 0, -1)]
    ).astype(np.float32)
    return t / bin_size  # peak 1, integral bin_size (scale cancels in L2)


def _binned_sampling_matrix(
    length: int, positions: np.ndarray, kernel: np.ndarray
) -> np.ndarray:
    """[P, length] matrix S with S @ x == (edge-padded conv of x with
    ``kernel``) evaluated at ``positions``.

    The spatial binning of dsift is a triangular convolution sampled only at
    the 4 bin centers per frame — a tiny fraction of the plane.  Expressing
    "convolve then sample" as one banded matmul turns VPU-bound depthwise
    convs plus TPU-hostile gathers into MXU gemms (the einsums in
    ``__call__``); numerics are identical up to f32 summation order."""
    klen = len(kernel)
    r = (klen - 1) // 2
    s = np.zeros((len(positions), length), np.float32)
    for i, p in enumerate(positions):
        for t, kv in enumerate(kernel):
            h = min(max(p + t - r, 0), length - 1)  # edge padding
            s[i, h] += kv
    return s


def _conv1d_axis(batch, kernel, axis):
    """Convolve [N, H, W] along ``axis`` (1=rows/y, 2=cols/x) with edge pad."""
    k = jnp.asarray(kernel, batch.dtype)
    klen = k.shape[0]
    r = (klen - 1) // 2
    pad = [(0, 0), (0, 0), (0, 0)]
    pad[axis] = (r, klen - 1 - r)
    x = jnp.pad(batch, pad, mode="edge")
    # depthwise conv via conv_general_dilated on a singleton channel
    x4 = x[:, None, :, :]  # [N, 1, H, W]
    if axis == 1:
        kern = k[::-1].reshape(1, 1, klen, 1)
    else:
        kern = k[::-1].reshape(1, 1, 1, klen)
    out = jax.lax.conv_general_dilated(
        x4, kern, (1, 1), "VALID", dimension_numbers=("NCHW", "OIHW", "NCHW")
    )
    return out[:, 0]


def _smooth(batch, sigma: float):
    k = _gaussian_kernel(sigma)
    return _conv1d_axis(_conv1d_axis(batch, k, 1), k, 2)


def _gradients(batch):
    """np.gradient-style derivatives on [N, H, W]: central differences in the
    interior, one-sided at the edges (vlfeat dsift gradient convention)."""
    gy = (jnp.roll(batch, -1, 1) - jnp.roll(batch, 1, 1)) * 0.5
    gy = gy.at[:, 0, :].set(batch[:, 1, :] - batch[:, 0, :])
    gy = gy.at[:, -1, :].set(batch[:, -1, :] - batch[:, -2, :])
    gx = (jnp.roll(batch, -1, 2) - jnp.roll(batch, 1, 2)) * 0.5
    gx = gx.at[:, :, 0].set(batch[:, :, 1] - batch[:, :, 0])
    gx = gx.at[:, :, -1].set(batch[:, :, -1] - batch[:, :, -2])
    return gy, gx


def _orientation_planes(gy, gx):
    """[N, H, W] -> [N, 8, H, W]: magnitude split bilinearly between the two
    adjacent orientation bins.  Angle math runs f32 regardless of input
    dtype (a low-precision arctan2 would shift bin-split weights); the
    caller chooses the storage dtype of the result and XLA fuses the casts
    into this elementwise chain."""
    gy = gy.astype(jnp.float32)
    gx = gx.astype(jnp.float32)
    mag = jnp.sqrt(gx * gx + gy * gy)
    angle = jnp.arctan2(gy, gx)  # [-pi, pi]
    a = angle * (NUM_BIN_T / (2.0 * jnp.pi))  # bin units
    t = jnp.arange(NUM_BIN_T, dtype=a.dtype)
    # circular distance in bin units; tent weight
    d = jnp.abs(((a[..., None] - t + NUM_BIN_T / 2) % NUM_BIN_T) - NUM_BIN_T / 2)
    w = jnp.maximum(0.0, 1.0 - d)  # [N, H, W, 8]
    return jnp.moveaxis(mag[..., None] * w, -1, 1)


def _scale_geometry(h: int, w: int, step: int, bin_size: int, num_scales: int, scale: int):
    """Frame-origin grids per reference VLFeat.cxx:93-95 and vl_dsift bounds:
    origins from ``off`` while origin + 3b <= dim-1."""
    off = (1 + 2 * num_scales) - 3 * scale
    if off < 0:
        # vl_dsift never starts before the frame; a negative origin would
        # silently wrap under JAX indexing — fail loudly for scale counts
        # outside the reference envelope (VLFeat.cxx:93-95).
        raise ValueError(
            f"scale={scale} with num_scales={num_scales} yields negative "
            f"grid origin {off}; use scales <= {(1 + 2 * num_scales) // 3}"
        )
    span = NUM_BIN_XY - 1  # bin centers at origin + {0,1,2,3}*b
    xs = np.arange(off, w - 1 - span * bin_size + 1, step)
    ys = np.arange(off, h - 1 - span * bin_size + 1, step)
    return ys, xs


@node(meta_fields=("step_size", "bin_size", "scales", "scale_step", "compute_dtype"))
class SIFTExtractor(Transformer):
    """Batched dense SIFT: ``[N, H, W]`` (or [N,H,W,1]) grayscale in [0,1]
    -> ``[N, 128, num_desc]`` quantized descriptors as float32
    (reference SIFTExtractor.scala:27-34 returns DenseMatrix(128, numCols)).

    ``compute_dtype`` (default f32): storage dtype of the large per-scale
    intermediates — the [N, 8, H, W] orientation planes and the banded-gemm
    sampling tensors, the dominant HBM streams of this op (measured ~197
    MB/image of traffic in f32 at 256x256x4-scales; the op is memory-bound
    at ~11 FLOP/byte, BENCH_r04 roofline).  Passing ``jnp.bfloat16`` (the
    throughput workloads do — imagenet_sift_lcs_fv, voc_sift_fisher,
    bench.py) halves that traffic: gemms accumulate f32 and the
    normalize/clamp/quantize tail runs f32, so the only effect is one
    rounding of intermediate values.  MEASURED vs the f32 chain (v5e,
    random-noise 256x256 images — the worst case for near-threshold bins):
    99.5% of quantized entries within +/-1 — the reference's own MATLAB
    acceptance envelope (VLFeatSuite.scala:48-51) — with rare tail
    outliers up to ~13/255; throughput 4.3k -> 5.9k img/s (+35%) on the
    SIFT->PCA->FV chain, traffic 197 -> 126 MB/image.  One known whole-
    descriptor failure mode under bf16: a descriptor whose
    pre-normalization norm lands within bf16 rounding (~0.4%) of
    CONTRAST_THRESHOLD can flip the zeroing decision vs the f32 chain,
    changing its entire 128-dim column — such near-threshold (i.e.
    near-contrastless) descriptors carry negligible signal, which is why
    the throughput workloads opt in; the OP default stays f32 so
    parity-critical callers get bit-level agreement without asking.
    """

    def __init__(
        self,
        step_size: int = 3,
        bin_size: int = 4,
        scales: int = 4,
        scale_step: int = 1,
        compute_dtype=jnp.float32,
    ):
        self.step_size = step_size
        self.bin_size = bin_size
        self.scales = scales
        self.scale_step = scale_step
        self.compute_dtype = compute_dtype

    def num_descriptors(self, h: int, w: int) -> int:
        total = 0
        for s in range(self.scales):
            b = self.bin_size + 2 * s
            step = self.step_size + s * self.scale_step
            ys, xs = _scale_geometry(h, w, step, b, self.scales, s)
            total += len(ys) * len(xs)
        return total

    def __call__(self, batch):
        if batch.ndim == 4:
            batch = batch[..., 0]
        n, h, w = batch.shape
        cdt = self.compute_dtype
        batch = batch.astype(cdt)
        per_scale = []
        for s in range(self.scales):
            b = self.bin_size + 2 * s
            step = self.step_size + s * self.scale_step
            ys, xs = _scale_geometry(h, w, step, b, self.scales, s)
            if len(ys) == 0 or len(xs) == 0:
                continue
            sigma = b / MAGNIF
            smoothed = _smooth(batch, sigma)
            gy, gx = _gradients(smoothed)
            planes = _orientation_planes(gy, gx).astype(cdt)  # [N, 8, H, W]
            tri = _triangular_kernel(b)

            # spatial binning as banded matmuls: triangular conv + bin-center
            # sampling in one MXU gemm per axis (see _binned_sampling_matrix)
            bin_off = np.arange(NUM_BIN_XY) * b
            yy = (ys[:, None] + bin_off[None, :]).ravel()  # [Fy*4]
            xx = (xs[:, None] + bin_off[None, :]).ravel()  # [Fx*4]
            s_y = jnp.asarray(_binned_sampling_matrix(h, yy, tri), cdt)
            s_x = jnp.asarray(_binned_sampling_matrix(w, xx, tri), cdt)
            # Two explicit gemms (not one opt-einsum) so the [N, 8, P, W]
            # intermediate is stored in compute_dtype — at the production
            # shape it is the single largest tensor of the whole op.
            part = jnp.einsum(
                "ph,nthw->ntpw", s_y, planes,
                preferred_element_type=jnp.float32,
            ).astype(cdt)
            sampled = jnp.einsum(
                "ntpw,qw->ntpq", part, s_x,
                preferred_element_type=jnp.float32,
            ).astype(cdt)  # [N, 8, Fy*4, Fx*4]
            fy, fx = len(ys), len(xs)
            sampled = sampled.reshape(n, NUM_BIN_T, fy, NUM_BIN_XY, fx, NUM_BIN_XY)
            # descriptor dims ordered [by, bx, t]; frames ordered y-major
            desc = jnp.einsum("ntybxc->nyxbct", sampled).reshape(
                n, fy * fx, NUM_BIN_XY * NUM_BIN_XY * NUM_BIN_T
            )
            per_scale.append(desc)

        descs = jnp.concatenate(per_scale, axis=1)  # [N, D, 128]
        # Normalization tail in f32: reductions/divisions read the compact
        # descriptors and accumulate full-precision (XLA fuses the upcast).
        norms = jnp.sqrt(
            jnp.sum(jnp.square(descs.astype(jnp.float32)), axis=-1, keepdims=True)
        )
        normed = descs.astype(jnp.float32) / jnp.maximum(norms, 1e-12)
        clamped = jnp.minimum(normed, 0.2)
        norms2 = jnp.linalg.norm(clamped, axis=-1, keepdims=True)
        final = clamped / jnp.maximum(norms2, 1e-12)
        # contrast threshold on the pre-normalization norm (:167-169)
        final = jnp.where(norms > CONTRAST_THRESHOLD, final, 0.0)
        quant = jnp.minimum(jnp.floor(512.0 * final), 255.0)
        return jnp.swapaxes(quant, 1, 2)  # [N, 128, D]
