"""Numeric test/eval helpers (reference utils/Stats.scala:25-123)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def about_eq(a, b, thresh: float = 1e-8) -> bool:
    """Tolerance comparison for scalars/vectors/matrices
    (reference utils/Stats.scala:25-66: elementwise |a-b| < thresh)."""
    a = np.asarray(a)
    b = np.asarray(b)
    if a.shape != b.shape:
        raise ValueError(f"shape mismatch {a.shape} vs {b.shape}")
    return bool(np.all(np.abs(a - b) < thresh))


def classification_error(predicted, actual, k: int = 1) -> float:
    """Fraction of examples whose true label is NOT in the top-k prediction
    (reference utils/Stats.scala:76-102).  ``predicted`` is [N, k] of label
    indices (or [N] for k=1); ``actual`` is [N] int labels."""
    predicted = np.asarray(predicted)
    actual = np.asarray(actual)
    if predicted.ndim == 1:
        predicted = predicted[:, None]
    hits = (predicted[:, :k] == actual[:, None]).any(axis=1)
    return float(1.0 - hits.mean())


def get_err_percent(predicted, actual, k: int = 1) -> float:
    return 100.0 * classification_error(predicted, actual, k)


def normalize_rows(mat, alpha: float = 1.0):
    """Row-normalize to zero mean / unit-ish variance with additive smoothing
    (reference utils/Stats.scala:105-123): per row,
    ``(x - mean) / sqrt(var + alpha)``."""
    mat = jnp.asarray(mat)
    mean = jnp.mean(mat, axis=1, keepdims=True)
    var = jnp.var(mat, axis=1, keepdims=True, ddof=1)  # sample variance (n-1)
    return (mat - mean) / jnp.sqrt(var + alpha)
