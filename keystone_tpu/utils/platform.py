"""Platform/runtime gates shared across ops."""

from __future__ import annotations

import os

import jax


def use_pallas_kernels() -> bool:
    """Opt-in gate (KEYSTONE_PALLAS=1, TPU backend only) for the
    hand-written Pallas kernels that MEASURED SLOWER than XLA's own fusion
    on their production shapes and are therefore not the defaults — see
    ops/fv_pallas.py and ops/rect_pool_pallas.py for the measured verdicts.
    One shared gate so every opt-in kernel engages under the same
    condition."""
    return os.environ.get("KEYSTONE_PALLAS", "").strip() == "1" and (
        jax.default_backend() == "tpu"
    )
