"""VOC-style mean average precision
(reference src/main/scala/evaluation/MeanAveragePrecisionEvaluator.scala:23-85).

11-point interpolated AP per class (precision maxima at recall levels
0, 0.1, ..., 1.0), averaged over classes by the caller.
"""

from __future__ import annotations

import numpy as np


def mean_average_precision(test_actual, test_predicted, num_classes: int) -> np.ndarray:
    """``test_actual``: per-example list/array of true class ids;
    ``test_predicted``: [N, num_classes] scores.  Returns per-class AP [C]."""
    scores = np.asarray(test_predicted, np.float64)
    n = scores.shape[0]
    gt = np.zeros((n, num_classes), np.float64)
    for i, labels in enumerate(test_actual):
        for l in np.atleast_1d(np.asarray(labels)):
            if l >= 0:
                gt[i, int(l)] = 1.0

    aps = np.zeros(num_classes)
    for cl in range(num_classes):
        # sort by descending score (reference sorts ascending then reverses)
        order = np.argsort(-scores[:, cl], kind="stable")
        g = gt[order, cl]
        tps = np.cumsum(g)
        fps = np.cumsum(1.0 - g)
        total = gt[:, cl].sum()
        if total == 0:
            aps[cl] = 0.0
            continue
        recalls = tps / total
        precisions = tps / (tps + fps)
        ap = 0.0
        # exact levels x/10 (reference :72); arange accumulation would give
        # 0.30000000000000004 etc. and wrongly exclude exact-recall hits
        for t in np.arange(11) / 10.0:
            px = precisions[recalls >= t]
            ap += (px.max() if px.size else 0.0) / 11.0
        aps[cl] = ap
    return aps


# Name-parity alias for the reference's evaluator object.
MeanAveragePrecisionEvaluator = mean_average_precision
