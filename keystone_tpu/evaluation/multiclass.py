"""Multiclass / binary evaluation
(reference src/main/scala/evaluation/MulticlassClassifierEvaluator.scala:21-152,
BinaryClassifierEvaluator.scala:17-65).

The confusion matrix is computed in one fused device pass (scatter-add /
segment-sum) — the reference's single ``aggregate`` pass over the zipped RDD.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class BinaryClassificationMetrics:
    """Contingency-table metrics (reference BinaryClassifierEvaluator.scala:17-47)."""

    tp: float
    fp: float
    tn: float
    fn: float

    def merge(self, o: "BinaryClassificationMetrics"):
        return BinaryClassificationMetrics(
            self.tp + o.tp, self.fp + o.fp, self.tn + o.tn, self.fn + o.fn
        )

    @property
    def accuracy(self):
        return (self.tp + self.tn) / (self.tp + self.fp + self.tn + self.fn)

    @property
    def error(self):
        return (self.fp + self.fn) / (self.tp + self.fp + self.tn + self.fn)

    @property
    def recall(self):
        return self.tp / (self.tp + self.fn)

    @property
    def precision(self):
        return self.tp / (self.tp + self.fp)

    @property
    def specificity(self):
        return self.tn / (self.fp + self.tn)

    def f_score(self, beta: float = 1.0) -> float:
        num = (1.0 + beta * beta) * self.tp
        denom = (1.0 + beta * beta) * self.tp + beta * beta * self.fn + self.fp
        return num / denom


class MulticlassMetrics:
    """Confusion-matrix metrics; rows = true labels, cols = predicted
    (reference MulticlassClassifierEvaluator.scala:21-152)."""

    def __init__(self, confusion_matrix):
        cm = np.asarray(confusion_matrix, dtype=np.float64)
        if cm.shape[0] != cm.shape[1]:
            raise ValueError("Confusion matrix must be square")
        self.confusion_matrix = cm
        self.num_classes = cm.shape[0]
        total = cm.sum()
        actual_sums = cm.sum(axis=1)
        predicted_sums = cm.sum(axis=0)
        self.class_metrics = []
        for c in range(self.num_classes):
            tp = cm[c, c]
            fp = predicted_sums[c] - tp
            tn = total - actual_sums[c] - fp
            fn = total - tp - fp - tn
            self.class_metrics.append(BinaryClassificationMetrics(tp, fp, tn, fn))

    def _class_avg(self, f) -> float:
        return sum(f(m) for m in self.class_metrics) / self.num_classes

    def _micro(self, f) -> float:
        merged = self.class_metrics[0]
        for m in self.class_metrics[1:]:
            merged = merged.merge(m)
        return f(merged)

    @property
    def avg_accuracy(self):
        return self._class_avg(lambda m: m.accuracy)

    @property
    def avg_error(self):
        return self._class_avg(lambda m: m.error)

    @property
    def macro_precision(self):
        return self._class_avg(lambda m: m.precision)

    @property
    def macro_recall(self):
        return self._class_avg(lambda m: m.recall)

    def macro_f_score(self, beta: float = 1.0):
        return self._class_avg(lambda m: m.f_score(beta))

    @property
    def total_accuracy(self):
        return self._micro(lambda m: m.precision)

    @property
    def total_error(self):
        return self._micro(lambda m: m.fn / (m.fn + m.tp))

    @property
    def micro_precision(self):
        return self._micro(lambda m: m.precision)

    @property
    def micro_recall(self):
        return self._micro(lambda m: m.recall)

    def micro_f_score(self, beta: float = 1.0):
        return self._micro(lambda m: m.f_score(beta))

    def pprint_confusion_matrix(self, classes) -> str:
        """Mahout-style pretty print (reference :62-81)."""
        labels = [_small_label(i) for i in range(self.num_classes)]
        width = max(6, max(len(l) for l in labels) + 1)
        lines = ["".join(l.rjust(width) for l in labels) + "   <-- Classified As"]
        for r in range(self.num_classes):
            row = "".join(
                str(int(self.confusion_matrix[r, c])).rjust(width)
                for c in range(self.num_classes)
            )
            lines.append(f"{row}   {labels[r]} = {classes[r]}")
        return "\n".join(lines)

    def summary(self, classes) -> str:
        return (
            f"{self.pprint_confusion_matrix(classes)}\n"
            f"Avg Accuracy:\t{self.avg_accuracy:2.3f}\n"
            f"Macro Precision:\t{self.macro_precision:2.3f}\n"
            f"Macro Recall:\t{self.macro_recall:2.3f}\n"
            f"Macro F1:\t{self.macro_f_score():2.3f}\n"
            f"Total Accuracy:\t{self.total_accuracy:2.3f}\n"
            f"Micro Precision:\t{self.micro_precision:2.3f}\n"
            f"Micro Recall:\t{self.micro_recall:2.3f}\n"
            f"Micro F1:\t{self.micro_f_score():2.3f}\n"
        )


def _small_label(i: int) -> str:
    """Base-26 column header (reference :108-123, bug-for-bug: digit order and
    the off-by-one 'a'+n are reproduced so printed headers match)."""
    if i == 0:
        return "a"
    out = ""
    while i > 0:
        out = out + chr(ord("a") + (i % 26))
        i //= 26
    return out


def confusion_matrix(predictions, actuals, num_classes: int):
    """One-pass confusion matrix on device: rows=actual, cols=predicted."""
    predictions = jnp.asarray(predictions).astype(jnp.int32)
    actuals = jnp.asarray(actuals).astype(jnp.int32)
    flat = actuals * num_classes + predictions
    counts = jnp.bincount(flat, length=num_classes * num_classes)
    return counts.reshape(num_classes, num_classes)


class MulticlassClassifierEvaluator:
    """Callable matching the reference companion object
    (MulticlassClassifierEvaluator.scala:126-163)."""

    @staticmethod
    def apply(predictions, actuals, num_classes: int) -> MulticlassMetrics:
        return MulticlassMetrics(confusion_matrix(predictions, actuals, num_classes))

    def __new__(cls, predictions, actuals, num_classes: int) -> MulticlassMetrics:  # type: ignore[misc]
        return cls.apply(predictions, actuals, num_classes)


class BinaryClassifierEvaluator:
    """Contingency table from boolean predictions/actuals
    (reference BinaryClassifierEvaluator.scala:50-65)."""

    @staticmethod
    def apply(predictions, actuals) -> BinaryClassificationMetrics:
        p = np.asarray(predictions, dtype=bool)
        a = np.asarray(actuals, dtype=bool)
        tp = float(np.sum(p & a))
        fp = float(np.sum(p & ~a))
        tn = float(np.sum(~p & ~a))
        fn = float(np.sum(~p & a))
        return BinaryClassificationMetrics(tp, fp, tn, fn)

    def __new__(cls, predictions, actuals) -> BinaryClassificationMetrics:  # type: ignore[misc]
        return cls.apply(predictions, actuals)
