"""Production telemetry tier: the live SLO surface, Prometheus exposition
of the metrics registry, and the flight-recorder postmortem dump.

The TensorFlow system paper (PAPERS.md: 1605.08695) is blunt about what
keeps production ML alive: the serving substrate is monitored continuously,
faults leave evidence, and regressions are caught by comparing rounds — the
model math is the easy part.  ``core.trace`` already unifies spans and the
metrics registry; this module is the OPERATOR-FACING layer on top:

* :class:`SLOTracker` — rolling-window p50/p99/QPS and **error-budget burn
  rate** per serving engine, judged against configurable targets
  (``KEYSTONE_SERVE_SLO_MS`` — one number, or ``label=ms`` pairs;
  ``KEYSTONE_SERVE_SLO_BUDGET`` — the allowed violation fraction).  A burn
  rate of 1.0 means the endpoint is spending its error budget exactly as
  fast as the budget allows; > 1.0 is an SLO page.  Trackers register into
  ``trace.metrics`` as the adopted ``slo`` group, so ONE
  ``metrics.snapshot()`` carries perf counters, the fault ledger, AND the
  SLO surface.
* :func:`prometheus_text` — the full registry snapshot rendered in
  Prometheus text exposition format (counters, gauges, histograms as
  summaries with quantile labels, adopted groups flattened).  Exported by
  a periodic atomic file writer (``KEYSTONE_METRICS_FILE``, interval
  ``KEYSTONE_METRICS_INTERVAL_S``) and/or a tiny in-process HTTP endpoint
  (``KEYSTONE_METRICS_PORT``; ``/metrics``) — both env-activated at
  import, both daemon threads, neither touching jax.
* :func:`maybe_postmortem` — the flight-recorder dump: when a typed fault
  of a :data:`POSTMORTEM_KINDS` family is counted
  (``resilience.counters.record`` calls through here) and
  ``KEYSTONE_POSTMORTEM_DIR`` is set, the recent-event ring
  (``trace.flight_events()`` — running even with tracing disabled), an
  atomic metrics snapshot, and the triggering fault are dumped as ONE
  schema-tagged JSON file, atomically.  Capped per kind per process so a
  fault storm cannot fill a disk.  ``postmortem_paths()`` links the dumps
  from ``FitReport``/``ServerStats`` records.

Never on the fit/serve hot path: the SLO observe is one deque append under
a lock, the postmortem check is one env read + set lookup, and everything
heavier runs on exporter threads or at fault time (when latency is already
the least of the operator's problems).
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import re
import threading
import time
from collections import deque

from . import trace

_logger = logging.getLogger("keystone_tpu.telemetry")

SLO_MS_ENV = "KEYSTONE_SERVE_SLO_MS"
SLO_BUDGET_ENV = "KEYSTONE_SERVE_SLO_BUDGET"
SLO_WINDOW_ENV = "KEYSTONE_SERVE_SLO_WINDOW_S"
METRICS_FILE_ENV = "KEYSTONE_METRICS_FILE"
METRICS_PORT_ENV = "KEYSTONE_METRICS_PORT"
METRICS_INTERVAL_ENV = "KEYSTONE_METRICS_INTERVAL_S"
POSTMORTEM_DIR_ENV = "KEYSTONE_POSTMORTEM_DIR"

DEFAULT_SLO_MS = 50.0
DEFAULT_SLO_BUDGET = 0.01  # 1% of requests may violate the SLO
DEFAULT_SLO_WINDOW_S = 60.0
DEFAULT_METRICS_INTERVAL_S = 10.0

#: Fault families that trigger a flight-recorder postmortem dump (the
#: typed faults an operator wants last-moments evidence for): OOM
#: step-downs on both the fit ladders and the serving buckets, watchdog
#: trips, parity failures, and snapshot divergence.
POSTMORTEM_KINDS = frozenset(
    {
        "solver_oom_retry",
        "autoshard_stepdown",
        "deadline_exceeded",
        "serve_burst_oom",
        "serve_batch_failed",
        "serve_parity_unverified",
        "serve_bucket_parity_dropped",
        "snapshot_fallback",
        "nonfinite_model",
        # Numerics observatory (ISSUE 15): a probe catching non-finite
        # values in a streamed/served batch, and a serving engine's output
        # distribution diverging from its fit-time baseline — both carry
        # their provenance/divergence evidence in the dumped metrics
        # snapshot's "numerics" group (and maybe_postmortem's capture hook
        # opens the bounded xprof window the ISSUE asks for).
        "numerics_nonfinite",
        "serve_output_drift",
        # Elastic serving (ISSUE 16): a surviving-mesh re-anchor is a
        # topology-loss event — the postmortem captures which engines were
        # hot-swapped, the mesh they landed on, and the in-flight counters
        # at the moment the substrate shrank.
        "mesh_reanchor",
        # Multi-host serving (ISSUE 17): losing a HOST is the
        # topology-loss event one tier up — the survivor's re-anchor onto
        # its host-local mesh ("host_reanchor"), the front-end declaring a
        # fleet member dead ("fleet_host_lost"), and a peer that never
        # joined the process group ("dist_join_timeout") all warrant
        # last-moments evidence.
        "host_reanchor",
        "fleet_host_lost",
        "dist_join_timeout",
        # Model lifecycle (ISSUE 18): the closed drift→refit→swap loop's
        # decision points are postmortem-worthy — a refit landing
        # ("lifecycle_refit", the swap evidence: generations, walls, the
        # new baseline), a candidate judged WORSE than the incumbent and
        # refused ("refit_rejected", the no-unvalidated-model invariant
        # firing), and a refit cycle dying typed mid-flight
        # ("refit_failed", the incumbent keeps serving).
        "lifecycle_refit",
        "refit_rejected",
        "refit_failed",
        # Fleet observability (ISSUE 20): the collector declaring a fleet
        # member unreachable mid-scrape is itself a topology-evidence
        # event — the postmortem (and the cross-host incident bundle the
        # collector writes alongside it) captures the last merged fleet
        # view and every surviving member's flight ring.
        "obs_member_lost",
    }
)

POSTMORTEM_SCHEMA = "keystone.postmortem/1"

#: Per-kind dump cap per process: the FIRST occurrences carry the
#: information; a fault storm repeating one kind must not fill a disk.
MAX_DUMPS_PER_KIND = 3


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        _logger.error("%s=%r is not a number — using %g", name, raw, default)
        return default


def slo_target_ms(label: str) -> float:
    """The latency SLO for ``label`` from ``KEYSTONE_SERVE_SLO_MS``: a bare
    number applies to every engine; ``label=ms`` pairs (comma-separated,
    optional ``default=ms`` entry) set per-engine targets."""
    raw = os.environ.get(SLO_MS_ENV, "").strip()
    if not raw:
        return DEFAULT_SLO_MS
    if "=" not in raw:
        try:
            return float(raw)
        except ValueError:
            _logger.error(
                "%s=%r is not a number — using %g",
                SLO_MS_ENV, raw, DEFAULT_SLO_MS,
            )
            return DEFAULT_SLO_MS
    default = DEFAULT_SLO_MS
    for tok in raw.split(","):
        if "=" not in tok:
            continue
        key, _, val = tok.partition("=")
        try:
            ms = float(val)
        except ValueError:
            _logger.error("%s: ignoring malformed entry %r", SLO_MS_ENV, tok)
            continue
        if key.strip() == label:
            return ms
        if key.strip() == "default":
            default = ms
    return default


class SLOTracker:
    """Rolling-window SLO accounting for one serving engine.

    ``observe(latency_ms, ok)`` is called once per answered (or typed-
    failed) request; :meth:`summary` reports window p50/p99/QPS, the
    violation rate (over-SLO latency or error), and the error-budget burn
    rate (violation rate / budget — 1.0 = burning exactly at budget).
    """

    def __init__(
        self,
        label: str,
        slo_ms: float | None = None,
        budget: float | None = None,
        window_s: float | None = None,
        clock=time.monotonic,
    ):
        self.label = label
        self.slo_ms = slo_ms if slo_ms is not None else slo_target_ms(label)
        self.budget = (
            budget
            if budget is not None
            else _env_float(SLO_BUDGET_ENV, DEFAULT_SLO_BUDGET)
        )
        self.window_s = (
            window_s
            if window_s is not None
            else _env_float(SLO_WINDOW_ENV, DEFAULT_SLO_WINDOW_S)
        )
        self._clock = clock
        self._lock = threading.Lock()
        self._window: deque = deque()  # (t, latency_ms, violation)
        self._window_violations = 0  # running count over the live window
        self.total_requests = 0
        self.total_errors = 0
        self.total_violations = 0

    #: window observations required before a burn-rate breach can fire a
    #: capture — one early violation over a 3-request window is noise,
    #: not a page.
    BURN_CAPTURE_MIN_COUNT = 20

    def observe(self, latency_ms: float, ok: bool = True) -> None:
        if _suspended:
            return
        now = self._clock()
        violation = (not ok) or latency_ms > self.slo_ms
        breach = False
        with self._lock:
            self.total_requests += 1
            if not ok:
                self.total_errors += 1
            if violation:
                self.total_violations += 1
                self._window_violations += 1
            self._window.append((now, float(latency_ms), violation))
            self._prune(now)
            if violation and self.budget > 0:
                count = len(self._window)
                breach = (
                    count >= self.BURN_CAPTURE_MIN_COUNT
                    and (self._window_violations / count) / self.budget > 1.0
                )
        if breach:
            # SLO burn-rate breach: the endpoint is spending its error
            # budget faster than the budget allows — open one bounded
            # device capture window (core.profiler; rate-limited per kind
            # per process, a no-op without KEYSTONE_XPROF_DIR).  The
            # integer bookkeeping above keeps the per-observe cost flat.
            from . import profiler

            profiler.maybe_capture(
                "slo_burn", reason=f"engine {self.label} burning error budget"
            )

    def _prune(self, now: float) -> None:
        cutoff = now - self.window_s
        w = self._window
        while w and w[0][0] < cutoff:
            if w.popleft()[2]:
                self._window_violations -= 1

    def summary(self) -> dict:
        """JSON-able SLO surface: rolling-window percentiles/QPS/burn rate
        plus process-lifetime totals."""
        now = self._clock()
        with self._lock:
            self._prune(now)
            window = list(self._window)
            totals = (
                self.total_requests, self.total_errors, self.total_violations
            )
        lat = sorted(v for _, v, _ in window)
        violations = sum(1 for _, _, viol in window if viol)
        count = len(window)

        def pick(q: float) -> float:
            if not lat:
                return 0.0
            return round(lat[min(len(lat) - 1, int(q * len(lat)))], 3)

        span_s = (now - window[0][0]) if count else 0.0
        violation_rate = violations / count if count else 0.0
        total_rate = totals[2] / totals[0] if totals[0] else 0.0
        return {
            "label": self.label,
            "slo_ms": self.slo_ms,
            "budget": self.budget,
            "window_seconds": self.window_s,
            "window": {
                "count": count,
                "qps": round(count / span_s, 2) if span_s > 0 else 0.0,
                "p50_ms": pick(0.50),
                "p99_ms": pick(0.99),
                "max_ms": round(lat[-1], 3) if lat else 0.0,
                "violations": violations,
                "violation_rate": round(violation_rate, 6),
                "burn_rate": round(violation_rate / self.budget, 4)
                if self.budget > 0
                else 0.0,
            },
            "total": {
                "requests": totals[0],
                "errors": totals[1],
                "violations": totals[2],
                "burn_rate": round(total_rate / self.budget, 4)
                if self.budget > 0
                else 0.0,
            },
        }


# -- the per-engine tracker registry (the adopted "slo" metrics group) --------

_slo_lock = threading.Lock()
_slo_trackers: dict[str, SLOTracker] = {}
_suspended = False  # telemetry_disabled(): the bench's off-mode control


def register_slo(label: str, **kwargs) -> SLOTracker:
    """Create a fresh tracker for ``label`` and register it as the live SLO
    surface for that engine (a new Server replaces its predecessor's — the
    exporter shows the CURRENT endpoint, not a dead one's history)."""
    tracker = SLOTracker(label, **kwargs)
    with _slo_lock:
        _slo_trackers[label] = tracker
    return tracker


def unregister_slo(label: str) -> None:
    """Drop ``label``'s tracker from the live SLO surface (a retired
    serving engine must stop being exported — its history belongs to the
    records that captured it, not to every future snapshot)."""
    with _slo_lock:
        _slo_trackers.pop(label, None)


def slo_summaries() -> dict:
    with _slo_lock:
        trackers = list(_slo_trackers.values())
    return {t.label: t.summary() for t in trackers}


class _SLOGroup:
    """Adopted-group adapter: ``metrics.snapshot()`` carries the live SLO
    surface under the ``slo`` key (reset is a no-op — SLO state belongs to
    the trackers, not the registry)."""

    def snapshot(self, reset: bool = False) -> dict:
        return slo_summaries()


trace.metrics.adopt("slo", _SLOGroup())


@contextlib.contextmanager
def telemetry_disabled():
    """Everything this tier adds, OFF: flight ring depth 0 and SLO
    observation suspended — the control arm of the bench's telemetry-
    overhead measurement."""
    global _suspended
    prev_depth = trace.flight_depth()
    prev_susp = _suspended
    trace.set_flight_depth(0)
    _suspended = True
    try:
        yield
    finally:
        trace.set_flight_depth(prev_depth)
        _suspended = prev_susp


# -- the /statusz debug surface ------------------------------------------------

_statusz_lock = threading.Lock()
_statusz_providers: dict[str, object] = {}


def register_statusz(name: str, provider) -> None:
    """Register a live-state provider (a zero-arg callable returning a
    JSON-able dict) under ``name`` on the ``/statusz`` debug page —
    routers register their engine tables, streams their ring state.  A
    new registration under the same name replaces the old (the page shows
    the CURRENT object, not a dead one's history)."""
    with _statusz_lock:
        _statusz_providers[name] = provider


def unregister_statusz(name: str, provider=None) -> None:
    """Drop ``name``'s provider.  Pass the registered ``provider`` back to
    make the removal identity-guarded: if a NEWER object has since
    registered under the same name, the old owner's unregister is a no-op
    instead of evicting the live provider."""
    with _statusz_lock:
        if provider is None or _statusz_providers.get(name) is provider:
            _statusz_providers.pop(name, None)


def statusz_snapshot() -> dict:
    """One JSON snapshot of the process's live operational state: every
    registered provider (router engine tables, ring/stream state), the
    rolling SLO windows, the numerics observatory surface, and the
    metrics registry (fault ledger included).  Served at ``/statusz`` on
    the ``KEYSTONE_METRICS_PORT`` endpoint; also directly callable (the
    golden tests pin the schema).  A provider that raises is reported as
    its error string — one sick subsystem must not blank the page."""
    providers: dict = {}
    with _statusz_lock:
        items = list(_statusz_providers.items())
    for name, provider in items:
        try:
            providers[name] = provider()
        except Exception as e:  # noqa: BLE001 — the page must render
            providers[name] = {"error": f"{type(e).__name__}: {e}"[:200]}
    # Importing numerics (jax-free) ensures its adopted metrics group
    # exists, so ONE registry snapshot carries the whole surface — no
    # second numerics.snapshot() pass per GET.
    from . import numerics

    snap = trace.metrics.snapshot()
    return {
        "schema": "keystone.statusz/1",
        "time_unix": time.time(),
        "pid": os.getpid(),
        "providers": providers,
        "slo": snap.get("slo", {}),
        "numerics": snap.get("numerics") or numerics.snapshot(),
        "faults": snap.get("faults", {}),
        "counters": snap.get("counters", {}),
        "gauges": snap.get("gauges", {}),
    }


# -- Prometheus text exposition -----------------------------------------------

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _metric_name(*parts: str) -> str:
    return "keystone_" + "_".join(
        _NAME_RE.sub("_", str(p)) for p in parts if str(p)
    )


def _fmt(value) -> str:
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, int):
        return str(value)
    return repr(float(value))


_LABEL_VALUE_RE = re.compile(r'["\\\n]')


def render_labels(labels: dict | None, extra: str = "") -> str:
    """Prometheus label block: ``{host="h0",rank="0"}`` — keys sorted and
    sanitized like metric names, values escaped per the exposition format.
    ``extra`` is a pre-rendered ``key="value"`` pair appended last (the
    histogram quantile label).  Empty labels and empty extra render ``""``."""
    pairs = []
    for k in sorted(labels or {}):
        v = labels[k]
        if v is None:
            continue
        val = _LABEL_VALUE_RE.sub(
            lambda m: {"\\": "\\\\", '"': '\\"', "\n": "\\n"}[m.group()],
            str(v),
        )
        pairs.append(f'{_NAME_RE.sub("_", str(k))}="{val}"')
    if extra:
        pairs.append(extra)
    return "{" + ",".join(pairs) + "}" if pairs else ""


def _flatten(prefix: tuple, obj, out: list) -> None:
    """Numeric leaves of an adopted group's nested snapshot, depth-first,
    as (name_parts, value) — non-numeric leaves are skipped (labels and
    notes have no Prometheus representation)."""
    if isinstance(obj, dict):
        for k in sorted(obj):
            _flatten(prefix + (k,), obj[k], out)
    elif isinstance(obj, (int, float)) and not isinstance(obj, bool):
        out.append((prefix, obj))


def prometheus_text(
    snapshot: dict | None = None, labels: dict | None = None
) -> str:
    """Render a ``trace.metrics`` snapshot (default: a fresh one) in the
    Prometheus text exposition format, deterministically ordered.
    Counters/gauges map 1:1; histograms render as summaries (quantile
    labels + ``_sum``/``_count``); adopted groups flatten to gauges
    (``faults`` to counters) prefixed with the group name.

    ``labels`` (e.g. ``{"host": "h0", "rank": 0}``) attaches the same
    label set to EVERY sample line — the multi-process scrape story
    (core.fleetobs labels each member's exposition ``host=``/``rank=``
    so one fleet page carries N processes without name collisions).
    ``labels=None`` renders byte-identically to the pre-label format
    (golden-pinned)."""
    snap = snapshot if snapshot is not None else trace.metrics.snapshot()
    lbl = render_labels(labels)
    lines: list[str] = []
    for name in sorted(snap.get("counters", {})):
        m = _metric_name(name)
        lines.append(f"# TYPE {m} counter")
        lines.append(f"{m}{lbl} {_fmt(snap['counters'][name])}")
    for name in sorted(snap.get("gauges", {})):
        m = _metric_name(name)
        lines.append(f"# TYPE {m} gauge")
        lines.append(f"{m}{lbl} {_fmt(snap['gauges'][name])}")
    for name in sorted(snap.get("histograms", {})):
        h = snap["histograms"][name]
        m = _metric_name(name)
        lines.append(f"# TYPE {m} summary")
        for q in ("p50", "p90", "p99"):
            if q in h:
                qlbl = render_labels(labels, extra=f'quantile="0.{q[1:]}"')
                lines.append(f"{m}{qlbl} {_fmt(h[q])}")
        count = h.get("count", 0)
        mean = h.get("mean", 0.0)
        lines.append(f"{m}_sum{lbl} {_fmt(mean * count)}")
        lines.append(f"{m}_count{lbl} {_fmt(count)}")
    for group in sorted(snap):
        if group in ("counters", "gauges", "histograms"):
            continue
        flat: list = []
        _flatten((group,), snap[group], flat)
        kind = "counter" if group == "faults" else "gauge"
        for parts, value in flat:
            m = _metric_name(*parts)
            lines.append(f"# TYPE {m} {kind}")
            lines.append(f"{m}{lbl} {_fmt(value)}")
    return "\n".join(lines) + "\n"


def _atomic_write_text(path: str, text: str) -> None:
    trace.atomic_write(path, lambda f: f.write(text))


class MetricsWriter:
    """Periodic atomic writer of :func:`prometheus_text` to a file — the
    node-exporter-textfile-collector integration path (a scraper tails the
    file; no port to open, works inside any sandbox)."""

    def __init__(self, path: str, interval_s: float = DEFAULT_METRICS_INTERVAL_S):
        self.path = path
        self.interval_s = max(0.05, float(interval_s))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="keystone-metrics-writer", daemon=True
        )

    def start(self) -> "MetricsWriter":
        self.write()  # fail fast on an unwritable destination
        self._thread.start()
        return self

    def write(self) -> None:
        _atomic_write_text(self.path, prometheus_text())

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.write()
            except Exception:  # noqa: BLE001 — the exporter must not die
                _logger.exception("metrics file write failed")

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(self.interval_s + 1.0)
        with contextlib.suppress(Exception):
            self.write()  # final snapshot so the file ends current


def start_metrics_server(port: int):
    """Tiny in-process HTTP endpoint on 127.0.0.1: :func:`prometheus_text`
    at ``/metrics`` (and ``/``), the :func:`statusz_snapshot` JSON debug
    page at ``/statusz``, and a ``/healthz`` liveness probe.  ``port=0``
    binds an ephemeral port (``server.server_address[1]``).  Returns the
    live ``ThreadingHTTPServer`` — call ``.shutdown()`` to stop."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 — http.server API
            route = self.path.split("?")[0]
            if route == "/healthz":
                body = b'{"ok": true}\n'
                ctype = "application/json"
            elif route == "/statusz":
                try:
                    body = json.dumps(statusz_snapshot()).encode()
                except Exception as e:  # noqa: BLE001 — a debug page
                    self.send_error(500, f"{type(e).__name__}: {e}"[:200])
                    return
                ctype = "application/json"
            elif route in ("/", "/metrics"):
                body = prometheus_text().encode()
                ctype = "text/plain; version=0.0.4"
            else:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):  # noqa: A002
            _logger.debug("metrics http: " + fmt, *args)

    server = ThreadingHTTPServer(("127.0.0.1", int(port)), Handler)
    thread = threading.Thread(
        target=server.serve_forever, name="keystone-metrics-http", daemon=True
    )
    thread.start()
    _logger.info(
        "metrics endpoint on http://127.0.0.1:%d/metrics",
        server.server_address[1],
    )
    return server


# -- flight-recorder postmortem dumps -----------------------------------------

_pm_lock = threading.Lock()
_pm_counts: dict[str, int] = {}
_pm_paths: list[str] = []


def postmortem_paths() -> list[str]:
    """Paths of every postmortem dump this process has written (linked
    from ``FitReport``/``ServerStats`` records)."""
    with _pm_lock:
        return list(_pm_paths)


def maybe_postmortem(kind: str, detail: str | None = None, total: int = 0):
    """Dump a flight-recorder postmortem for fault ``kind`` if it is a
    :data:`POSTMORTEM_KINDS` family, ``KEYSTONE_POSTMORTEM_DIR`` is set,
    and the per-kind cap has room.  Returns the written path or None.

    Called by ``resilience.counters.record`` AFTER its lock is released
    (the metrics snapshot below re-enters the fault ledger's own snapshot);
    never raises — a failing dump must not break the fault path it is
    documenting."""
    if kind not in POSTMORTEM_KINDS:
        return None
    # Any postmortem-family fault also triggers a bounded XLA capture
    # window (core.profiler; no-op without KEYSTONE_XPROF_DIR, capped per
    # kind per process, never raises) — the device-side evidence next to
    # the flight ring's host-side last moments.
    from . import profiler

    profiler.maybe_capture(kind, reason=(detail or "")[:200])
    dump_dir = os.environ.get(POSTMORTEM_DIR_ENV, "").strip()
    if not dump_dir:
        return None
    try:
        with _pm_lock:
            n = _pm_counts.get(kind, 0)
            if n >= MAX_DUMPS_PER_KIND:
                return None
            _pm_counts[kind] = n + 1
        dump = {
            "schema": POSTMORTEM_SCHEMA,
            "time_unix": time.time(),
            "pid": os.getpid(),
            "fault": {"kind": kind, "detail": detail, "total": total},
            "trace_enabled": trace.enabled(),
            "flight_depth": trace.flight_depth(),
            # The ring: the process's last moments, captured even when
            # tracing was never enabled.
            "flight": trace.flight_events(),
            # One atomic registry snapshot: counters, gauges, histograms,
            # the fault ledger, and the live SLO surface.
            "metrics": trace.metrics.snapshot(),
            # Triggered device capture windows this process opened
            # (core.profiler) — the postmortem links the xprof evidence.
            "xprof_captures": profiler.capture_paths(),
        }
        os.makedirs(dump_dir, exist_ok=True)
        path = os.path.join(
            dump_dir, f"postmortem_{_NAME_RE.sub('_', kind)}_{os.getpid()}_{n}.json"
        )
        _atomic_write_text(path, json.dumps(dump))
        with _pm_lock:
            _pm_paths.append(path)
        _logger.warning("postmortem dumped -> %s (fault %s)", path, kind)
        return path
    except Exception:  # noqa: BLE001 — never break the fault path
        _logger.exception("postmortem dump for %r failed", kind)
        return None


def _reset_state() -> None:
    """Test isolation: forget dump caps/paths, SLO trackers, and statusz
    providers."""
    with _pm_lock:
        _pm_counts.clear()
        _pm_paths.clear()
    with _slo_lock:
        _slo_trackers.clear()
    with _statusz_lock:
        _statusz_providers.clear()


# -- env activation -----------------------------------------------------------

_env_writer: MetricsWriter | None = None
_env_server = None


def _is_worker_process() -> bool:
    """Spawned helper processes (the decode workers) inherit the parent's
    env, so without this guard every worker would start its own writer and
    atomically clobber the shared metrics file with a near-empty registry
    (and race to bind the metrics port).  Only the MAIN process exports.
    The process NAME is checked as well as the parent handle because a
    spawn child unpickles its target (importing this module) BEFORE the
    bootstrap sets the parent handle — the name is already set by then."""
    import multiprocessing

    return (
        multiprocessing.parent_process() is not None
        or multiprocessing.current_process().name != "MainProcess"
    )


_raw_file = os.environ.get(METRICS_FILE_ENV, "").strip()
if _raw_file and _is_worker_process():
    _raw_file = ""
if _raw_file:
    try:
        _env_writer = MetricsWriter(
            _raw_file,
            _env_float(METRICS_INTERVAL_ENV, DEFAULT_METRICS_INTERVAL_S),
        ).start()
        import atexit as _atexit

        _atexit.register(_env_writer.stop)
    except OSError as e:
        import sys as _sys

        _sys.stderr.write(
            f"keystone_tpu: {METRICS_FILE_ENV}={_raw_file!r} is unusable "
            f"({e}) — metrics file writer disabled\n"
        )
        _logger.error(
            "%s=%r unusable (%s) — metrics file writer disabled",
            METRICS_FILE_ENV, _raw_file, e,
        )

_raw_port = os.environ.get(METRICS_PORT_ENV, "").strip()
if _raw_port and _is_worker_process():
    _raw_port = ""
if _raw_port:
    try:
        _env_server = start_metrics_server(int(_raw_port))
    except (OSError, ValueError) as e:
        import sys as _sys

        _sys.stderr.write(
            f"keystone_tpu: {METRICS_PORT_ENV}={_raw_port!r} is unusable "
            f"({e}) — metrics endpoint disabled\n"
        )
        _logger.error(
            "%s=%r unusable (%s) — metrics endpoint disabled",
            METRICS_PORT_ENV, _raw_port, e,
        )
