"""Cost-based pipeline optimization: the auto-Cacher and the closed-loop
ingest autotuner.

KeystoneML's defining contribution is the whole-pipeline optimizer
(reference PipelineRuntimeEstimator / the Cacher materialization pass):
profile every node on a data sample, count how often each intermediate is
recomputed across the fit DAG, and greedily insert ``Cacher`` nodes where
recompute-cost x reuse beats the memory cost of keeping the output
resident.  This module reproduces that pass on the measurement substrate
PR 5 landed — ``Pipeline.profile`` -> :class:`PipelineProfile` plus
``core.pipeline.track_reuse`` — and goes one step beyond the reference
with a tf.data-style closed-loop autotuner (PAPERS.md, arxiv 2101.12127)
that retunes the streaming-ingest knobs mid-run from live trace metrics.

**Auto-Cacher** (static, KeystoneML-faithful):

* :func:`plan_caches` — the greedy decision pass over
  :class:`CacheCandidate` rows (node name, full-dataset recompute seconds,
  full-dataset output bytes, measured reuse): a node is WORTH caching when
  ``recompute_seconds x (reuse - 1)`` exceeds the amortized cost of
  holding ``output_bytes`` resident (bytes / :func:`cache_gbps`, the
  materialization-bandwidth exchange rate); every insertion is admitted
  through ``core.memory``'s HBM budget (``plan_cache_bytes``; the minimum
  per-chip budget under a mesh), and on denial the CHEAPEST-win caches are
  dropped first (admission walks biggest win first).  The full decision
  table — cached and rejected rows, each with its reason — lands in a
  :class:`CachePlan`, the audit-trail analog of ``FitReport``.
* :func:`apply_cache_plan` — rewrite a pipeline with memoizing
  ``Cacher(name, sharding)`` nodes after each cached node.
* :func:`auto_cache_chain` — the whole pass for a
  ``ChainedEstimator``/``ChainedLabelEstimator``: profile the upstream
  transformer on a sample, measure reuse by executing the fit pattern on
  that sample under ``track_reuse``, scale costs to the full dataset size,
  plan, and return the chain rebuilt around the cached pipeline.

**Closed-loop ingest autotuner**:

* :class:`IngestAutotuner` — attached to a ``core.ingest`` stream
  (``StreamConfig.autotune`` / ``KEYSTONE_AUTOTUNE=1``), it reads the live
  metrics published at every chunk boundary (ring stall counters, ring
  depth, knob gauges) and retunes decode-pool width, ring capacity, and
  the decode-ahead window through the mutable ``StreamConfig``:
  consumer-starved intervals (decode-bound) widen decode; producer-blocked
  intervals (device-bound) narrow decode to free host cores and deepen the
  ring.  Retuning changes concurrency and buffering only — the stream's
  output is bit-identical at any knob trajectory (the ``autotune_thrash``
  chaos family enforces it).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os

from . import memory as kmem
from . import trace
from .pipeline import (
    Cacher,
    ChainedEstimator,
    ChainedLabelEstimator,
    Pipeline,
    PipelineProfile,
    track_reuse,
)

_logger = logging.getLogger("keystone_tpu.optimize")

#: env var: the materialization-bandwidth exchange rate (GB/s) pricing the
#: amortized cost of holding a cached intermediate resident.
CACHE_GBPS_ENV = "KEYSTONE_CACHE_GBPS"
_DEFAULT_CACHE_GBPS = 1.0


def auto_cache_env() -> bool:
    """``KEYSTONE_AUTOCACHE=1``: opt a workload into the auto-Cacher
    without its ``--autoCache`` flag (the env form of the opt-in)."""
    # Same flag grammar as KEYSTONE_AUTOTUNE (one parser, no drift).
    from .ingest import _env_flag

    return _env_flag("KEYSTONE_AUTOCACHE")


def cache_gbps() -> float:
    """GB/s rate converting cached bytes into amortized seconds — the
    exchange rate between the two sides of the caching inequality.  The
    default (1 GB/s) approximates one host<->device round trip of the
    materialized value; raise it to cache more aggressively, lower it to
    price HBM residency higher (``KEYSTONE_CACHE_GBPS``)."""
    raw = os.environ.get(CACHE_GBPS_ENV, "").strip()
    if not raw:
        return _DEFAULT_CACHE_GBPS
    try:
        val = float(raw)
    except ValueError:
        raise ValueError(f"{CACHE_GBPS_ENV}={raw!r} is not a number") from None
    if val <= 0:
        raise ValueError(f"{CACHE_GBPS_ENV}={raw!r} must be > 0")
    return val


@dataclasses.dataclass
class CacheCandidate:
    """One node's caching economics, scaled to the FULL dataset."""

    index: int  #: node position in the pipeline (-1 for non-pipeline sites)
    name: str
    seconds: float  #: one full-dataset recompute of this node
    output_bytes: int  #: full-dataset materialized output
    reuse: int  #: times the fit path computes this intermediate


@dataclasses.dataclass
class CacheDecision:
    """One row of the optimizer's decision table."""

    index: int
    name: str
    reuse: int
    recompute_seconds: float
    output_bytes: int
    win_seconds: float  #: recompute_seconds x (reuse - 1)
    amortized_seconds: float  #: output_bytes / cache_gbps
    cached: bool
    reason: str

    def record(self) -> dict:
        out = dataclasses.asdict(self)
        out["recompute_seconds"] = round(self.recompute_seconds, 6)
        out["win_seconds"] = round(self.win_seconds, 6)
        out["amortized_seconds"] = round(self.amortized_seconds, 6)
        return out


@dataclasses.dataclass
class CachePlan:
    """The auto-Cacher's audit trail (the ``FitReport`` analog): every
    considered node's decision with the evidence, the admission verdicts,
    and what the budget degradation dropped."""

    decisions: list  #: list[CacheDecision], pipeline order
    budget_bytes: int | None = None
    cached_bytes: int = 0
    dataset_rows: int | None = None
    sample_rows: int | None = None
    gbps: float = _DEFAULT_CACHE_GBPS
    denials: list = dataclasses.field(default_factory=list)
    #: names dropped by the budget degradation path, cheapest win first
    dropped: list = dataclasses.field(default_factory=list)

    def cached(self) -> list:
        return [d for d in self.decisions if d.cached]

    def record(self) -> dict:
        return {
            "cached": [d.name for d in self.cached()],
            "cached_bytes": self.cached_bytes,
            "budget_bytes": self.budget_bytes,
            "dataset_rows": self.dataset_rows,
            "sample_rows": self.sample_rows,
            "gbps": self.gbps,
            "denials": list(self.denials),
            "dropped": list(self.dropped),
            "decisions": [d.record() for d in self.decisions],
        }

    def to_json(self) -> str:
        """The plan as one JSON document, embeddable in bench/chaos
        records (the decision table would otherwise die with the
        process)."""
        return json.dumps(self.record())

    def summary(self) -> str:
        cached = ", ".join(d.name for d in self.cached()) or "nothing"
        s = f"auto-cache: caching {cached} ({kmem.fmt_bytes(self.cached_bytes)})"
        if self.dropped:
            s += f"; budget dropped {self.dropped}"
        return s


def plan_caches(
    candidates,
    *,
    budget=kmem._UNSET,
    mesh=None,
    headroom: float = 0.5,
    gbps: float | None = None,
    dataset_rows: int | None = None,
    sample_rows: int | None = None,
) -> CachePlan:
    """The greedy caching decision over :class:`CacheCandidate` rows.

    Eligibility is KeystoneML's inequality: cache a node iff its win —
    ``recompute_seconds x (reuse - 1)`` — exceeds the amortized residency
    cost ``output_bytes / gbps``.  ``reuse <= 1`` is never cached (nothing
    is saved).  Eligible nodes are then admitted through
    ``core.memory.plan_cache_bytes`` cumulatively, BIGGEST win first, so a
    denial drops the cheapest-win caches: the degradation path under a
    tight ``KEYSTONE_HBM_BUDGET`` is fewer (or no) caches, never a
    caching-induced OOM.  Under a ``mesh`` a row-sharded cache charges its
    per-chip shard (bytes / data-axis size) against the minimum per-chip
    budget."""
    rate = gbps if gbps is not None else cache_gbps()
    per_chip = 1
    if mesh is not None:
        per_chip = max(1, int(mesh.shape.get("data", 1)))
    decisions: list[CacheDecision] = []
    eligible: list[CacheDecision] = []
    for c in candidates:
        win = c.seconds * max(0, c.reuse - 1)
        amortized = c.output_bytes / (rate * 2**30)
        d = CacheDecision(
            index=c.index,
            name=c.name,
            reuse=c.reuse,
            recompute_seconds=c.seconds,
            output_bytes=c.output_bytes,
            win_seconds=win,
            amortized_seconds=amortized,
            cached=False,
            reason="",
        )
        if c.reuse <= 1:
            d.reason = "reuse <= 1: nothing recomputed, nothing to save"
        elif win <= amortized:
            d.reason = (
                f"win {win:.4f}s <= amortized residency cost "
                f"{amortized:.4f}s ({kmem.fmt_bytes(c.output_bytes)} @ "
                f"{rate}GB/s)"
            )
        else:
            eligible.append(d)
        decisions.append(d)

    plan = CachePlan(
        decisions=decisions,
        dataset_rows=dataset_rows,
        sample_rows=sample_rows,
        gbps=rate,
    )
    # Admission walks the eligible set biggest win first: under a tight
    # budget the caches given up are the cheapest wins.  Each candidate is
    # admitted independently against the REMAINING budget — a denied big
    # win does not abandon smaller ones that still fit (greedy knapsack,
    # not first-failure abort).
    eligible.sort(key=lambda d: d.win_seconds, reverse=True)
    cum = 0
    for d in eligible:
        mp = kmem.plan_cache_bytes(
            f"cache:{d.name}",
            (cum + d.output_bytes) // per_chip,
            mesh=mesh,
            budget=budget,
            headroom=headroom,
        )
        plan.budget_bytes = mp.budget_bytes
        if mp.admitted:
            d.cached = True
            d.reason = (
                f"cached: win {d.win_seconds:.4f}s > amortized "
                f"{d.amortized_seconds:.4f}s; {mp.reason}"
            )
            cum += d.output_bytes
        else:
            d.reason = f"budget denied: {mp.reason}"
            plan.denials.append(d.name)
            plan.dropped.append(d.name)
    plan.cached_bytes = cum
    trace.instant(
        "auto_cache_plan",
        cached=[d.name for d in plan.cached()],
        cached_bytes=cum,
        dropped=list(plan.dropped),
    )
    return plan


def candidates_from_profile(
    profile: PipelineProfile,
    reuse_by_index: dict,
    *,
    dataset_rows: int | None = None,
    sample_rows: int | None = None,
) -> list:
    """Turn a sample-batch :class:`PipelineProfile` into full-dataset
    :class:`CacheCandidate` rows: each node's measured seconds and output
    bytes scale linearly by ``dataset_rows / sample_rows`` (KeystoneML's
    sampling profiler made the same linear extrapolation)."""
    scale = 1.0
    if dataset_rows and sample_rows:
        scale = dataset_rows / float(sample_rows)
    return [
        CacheCandidate(
            index=n.index,
            name=n.name,
            seconds=n.seconds * scale,
            output_bytes=int(n.output_bytes * scale),
            reuse=int(reuse_by_index.get(n.index, 1)),
        )
        for n in profile.nodes
    ]


def apply_cache_plan(pipeline: Pipeline, plan: CachePlan, sharding=None) -> Pipeline:
    """Insert a memoizing ``Cacher(name, sharding)`` after every cached
    node.  Existing Cachers are never doubled.  Returns a new Pipeline
    (the input is untouched); with nothing cached it is an equal-node
    rebuild."""
    cached_at = {d.index for d in plan.cached()}
    nodes = []
    for i, n in enumerate(pipeline.nodes):
        nodes.append(n)
        if i in cached_at and not isinstance(n, Cacher):
            nodes.append(
                Cacher(
                    name=f"auto:{_plan_name(plan, i)}",
                    sharding=sharding,
                    memoize=True,
                )
            )
    return Pipeline(nodes)


def _plan_name(plan: CachePlan, index: int) -> str:
    for d in plan.decisions:
        if d.index == index:
            return d.name
    return str(index)


def measure_chain_reuse(chain, sample, labels=None) -> dict:
    """Execute the workload fit pattern — ``chain.fit(sample)`` followed by
    one application of the fitted pipeline to the same sample — on a SAMPLE
    under ``track_reuse``, and return ``{node_index_in_xform: count}``.
    This is the fit-path reuse measurement: an upstream node counted twice
    is recomputed once per extra count when the real fit runs."""
    xform = chain.xform
    pipe = xform if isinstance(xform, Pipeline) else Pipeline([xform])
    with track_reuse() as counts:
        if isinstance(chain, ChainedLabelEstimator):
            fitted = chain.fit(sample, labels)
        else:
            fitted = chain.fit(sample)
        fitted(sample)
    return {i: counts.get(id(n), 0) for i, n in enumerate(pipe.nodes)}


def auto_cache_chain(
    chain,
    sample,
    dataset_rows: int,
    *,
    labels=None,
    mesh=None,
    sharding=None,
    budget=kmem._UNSET,
    headroom: float = 0.5,
    gbps: float | None = None,
):
    """The whole KeystoneML optimizer pass for one chained estimator.

    1. profile the upstream transformer node-by-node on ``sample``
       (``Pipeline.profile``: wall seconds + output bytes per node);
    2. measure per-node REUSE by running the fit pattern on the sample
       under ``track_reuse`` (fit + one fitted application — the workload
       usage that recomputes upstream intermediates);
    3. scale costs to ``dataset_rows`` and run :func:`plan_caches` through
       the HBM admission gate;
    4. rebuild the chain around the Cacher-annotated pipeline.

    Returns ``(optimized_chain, CachePlan)``.  With every cache denied the
    optimized chain is behaviorally identical to the input (and produces
    bit-identical results either way — the memo replays the very arrays
    the fit computed)."""
    if not isinstance(chain, (ChainedEstimator, ChainedLabelEstimator)):
        raise TypeError(
            f"auto_cache_chain wants a ChainedEstimator/ChainedLabelEstimator, "
            f"got {type(chain).__name__}"
        )
    xform = chain.xform
    pipe = xform if isinstance(xform, Pipeline) else Pipeline([xform])
    sample_rows = int(getattr(sample, "shape", [len(sample)])[0])
    with trace.span("optimize.auto_cache", nodes=len(pipe.nodes)):
        profile = pipe.profile(sample)
        reuse = measure_chain_reuse(chain, sample, labels)
        plan = plan_caches(
            candidates_from_profile(
                profile,
                reuse,
                dataset_rows=dataset_rows,
                sample_rows=sample_rows,
            ),
            budget=budget,
            mesh=mesh,
            headroom=headroom,
            gbps=gbps,
            dataset_rows=dataset_rows,
            sample_rows=sample_rows,
        )
    cached_pipe = apply_cache_plan(pipe, plan, sharding=sharding)
    _logger.info("%s", plan.summary())
    rebuilt = type(chain)(cached_pipe, chain.est)
    return rebuilt, plan


def release_caches(pipeline: Pipeline) -> None:
    """Drop every memoized intermediate a cached pipeline holds (frees the
    device memory once the fit path no longer needs the replay)."""
    for n in getattr(pipeline, "nodes", ()):
        if isinstance(n, Cacher):
            n.clear_memo()


# -- the placement cost model (shared with core.autoshard) --------------------

#: Per-chip bf16/f32 peak FLOP/s and HBM GB/s by device kind — the roofline
#: rates the analytic placement prior divides by.  Unknown kinds (the CPU
#: test platform included) fall back to :data:`_DEFAULT_RATES`; only the
#: RELATIVE ranking of candidate plans matters to the search, and the
#: learned calibration (core.autoshard's plan-outcome log) absorbs the
#: absolute error across runs.
DEVICE_RATES: dict[str, dict] = {
    "TPU v4": {"peak_flops": 275e12, "hbm_gbps": 1228.0, "ici_gbps": 50.0},
    "TPU v5e": {"peak_flops": 197e12, "hbm_gbps": 819.0, "ici_gbps": 50.0},
    "TPU v5 lite": {"peak_flops": 197e12, "hbm_gbps": 819.0, "ici_gbps": 50.0},
    "TPU v5p": {"peak_flops": 459e12, "hbm_gbps": 2765.0, "ici_gbps": 100.0},
    "TPU v6e": {"peak_flops": 918e12, "hbm_gbps": 1640.0, "ici_gbps": 100.0},
}

_DEFAULT_RATES = {"peak_flops": 50e9, "hbm_gbps": 20.0, "ici_gbps": 5.0}


@dataclasses.dataclass
class CostModel:
    """Analytic roofline prior for one candidate placement's solve wall.

    The Learned-Cost-Model placement paper's structure (PAPERS.md): an
    analytic prior over the quantities a plan determines — per-chip bytes
    moved through HBM, per-chip FLOPs, host<->device dispatch round trips,
    H2D streaming traffic, cross-chip collective volume — refined by a
    learned per-(program, candidate) calibration factor fitted to measured
    outcomes (core.autoshard reads them from the persistent plan-outcome
    log and multiplies :meth:`predict_seconds` by the measured/predicted
    ratio).  The prior only has to RANK candidates sanely on a cold start;
    the calibration makes the absolute numbers honest across runs.
    """

    peak_flops: float = _DEFAULT_RATES["peak_flops"]
    hbm_gbps: float = _DEFAULT_RATES["hbm_gbps"]
    ici_gbps: float = _DEFAULT_RATES["ici_gbps"]
    h2d_gbps: float = 8.0  #: PCIe-class host->device streaming rate
    dispatch_seconds: float = 1e-3  #: one host->device dispatch round trip

    @classmethod
    def for_devices(cls, devices=None) -> "CostModel":
        """Rates for the live platform (:data:`DEVICE_RATES` by
        ``device_kind``, default rates for unknown kinds)."""
        try:
            if devices is None:
                import jax

                devices = jax.devices()
            kind = devices[0].device_kind
        except Exception:  # noqa: BLE001 — no backend: relative ranking only
            kind = ""
        rates = DEVICE_RATES.get(kind, _DEFAULT_RATES)
        return cls(
            peak_flops=rates["peak_flops"],
            hbm_gbps=rates["hbm_gbps"],
            ici_gbps=rates["ici_gbps"],
        )

    def predict_seconds(self, hints: dict) -> float:
        """Prior wall seconds for one candidate from its cost hints.

        ``hints`` keys (all optional, per chip): ``arg_bytes`` /
        ``out_bytes`` / ``temp_bytes`` (HBM traffic, charged once),
        ``hbm_passes`` (how many times the solve streams that working set;
        default 1), ``flops``, ``dispatches``, ``h2d_bytes``,
        ``coll_bytes``.  The roofline term takes the MAX of the HBM and
        FLOP times (they overlap on the MXU); dispatches, H2D streaming,
        and collectives are serial adders."""
        touched = (
            hints.get("arg_bytes", 0)
            + hints.get("out_bytes", 0)
            + hints.get("temp_bytes", 0)
        ) * max(1.0, float(hints.get("hbm_passes", 1)))
        hbm_s = touched / (self.hbm_gbps * 2**30)
        flop_s = float(hints.get("flops", 0.0)) / self.peak_flops
        return (
            max(hbm_s, flop_s)
            + float(hints.get("dispatches", 1)) * self.dispatch_seconds
            + float(hints.get("h2d_bytes", 0)) / (self.h2d_gbps * 2**30)
            + float(hints.get("coll_bytes", 0)) / (self.ici_gbps * 2**30)
        )


# -- the cross-program calibration model (ISSUE 10) ---------------------------

#: featurized outcomes required before a cross-program fit is attempted
#: (core.autoshard additionally requires >= 2 distinct program
#: fingerprints — transfer between programs is the model's entire point).
MIN_MODEL_ROWS = 8

#: one-sided bound on the learned factor: a regression extrapolating onto
#: a feature vector far outside its training hull must not predict a
#: thousandfold slowdown/speedup and blow a candidate past every margin.
_FACTOR_CLIP = 32.0


@dataclasses.dataclass
class CalibrationModel:
    """Cross-program calibration: ridge regression of
    ``log(measured / analytic-prior)`` on candidate FEATURES (operand
    bytes, mesh factorization, strategy kind, arithmetic intensity — see
    ``core.autoshard.plan_features``), fitted over every program's logged
    outcomes.

    This replaces PR 9's per-(fingerprint, candidate) memorization as the
    below-:data:`~keystone_tpu.core.autoshard.MIN_TRAIN` fallback: a
    median keyed on the program fingerprint cannot say anything about a
    shape it never ran, while a feature-space fit transfers — train on a
    16k x 2k solve, predict the ratio for an 8k x 4k one (the Learned
    Cost Model placement direction, PAPERS.md).  Direct per-pair medians
    still win once they exist, and only THEY tighten the ranking margin;
    the model only shifts absolute predictions toward honesty, bounded by
    :data:`_FACTOR_CLIP`.
    """

    feature_names: list
    kinds: list  #: strategy one-hot vocabulary seen at fit time
    weights: "np.ndarray"  #: [1 + features + kinds] — bias first
    n_rows: int
    n_programs: int

    @classmethod
    def fit_rows(cls, rows, l2: float = 1.0) -> "CalibrationModel | None":
        """Fit from ``[(fingerprint, features_dict, ratio)]`` rows (the
        shape ``core.autoshard.model_rows`` yields).  Returns ``None``
        for degenerate inputs (no rows / no positive ratios)."""
        import numpy as np

        rows = [
            (fp, f, r) for fp, f, r in rows
            if isinstance(f, dict) and r and r > 0
        ]
        if not rows:
            return None
        names = sorted({
            k for _fp, f, _r in rows
            for k, v in f.items()
            if isinstance(v, (int, float))
        })
        kinds = sorted({f.get("kind") for _fp, f, _r in rows} - {None})
        xs, ys = [], []
        for _fp, f, r in rows:
            xs.append(cls._vector(f, names, kinds))
            ys.append(np.log(r))
        x = np.asarray(xs, np.float64)
        y = np.asarray(ys, np.float64)
        reg = l2 * np.eye(x.shape[1])
        reg[0, 0] = 0.0  # the bias absorbs the global mean unpenalized
        w = np.linalg.solve(x.T @ x + reg, x.T @ y)
        return cls(
            feature_names=names,
            kinds=kinds,
            weights=w,
            n_rows=len(rows),
            n_programs=len({fp for fp, _f, _r in rows}),
        )

    @staticmethod
    def _vector(features: dict, names, kinds):
        import numpy as np

        v = [1.0]
        v.extend(float(features.get(k, 0.0) or 0.0) for k in names)
        kind = features.get("kind")
        v.extend(1.0 if kind == k else 0.0 for k in kinds)
        return np.asarray(v, np.float64)

    def predict_factor(self, features: dict) -> float:
        """The calibration factor (measured/prior ratio) this model
        predicts for one candidate's feature vector, clipped to
        ``[1/32, 32]``."""
        import numpy as np

        pred = float(
            self._vector(features, self.feature_names, self.kinds)
            @ self.weights
        )
        lim = float(np.log(_FACTOR_CLIP))
        return float(np.exp(np.clip(pred, -lim, lim)))

    def record(self) -> dict:
        return {
            "n_rows": self.n_rows,
            "n_programs": self.n_programs,
            "features": list(self.feature_names),
            "kinds": list(self.kinds),
        }


# -- the snapshot advisor -----------------------------------------------------

#: env var: assumed snapshot-disk sequential bandwidth (GB/s) used by the
#: advisor when no measured rate is supplied.
SNAPSHOT_GBPS_ENV = "KEYSTONE_SNAPSHOT_GBPS"
_DEFAULT_SNAPSHOT_GBPS = 0.5


def snapshot_gbps() -> float:
    raw = os.environ.get(SNAPSHOT_GBPS_ENV, "").strip()
    if not raw:
        return _DEFAULT_SNAPSHOT_GBPS
    try:
        val = float(raw)
    except ValueError:
        raise ValueError(
            f"{SNAPSHOT_GBPS_ENV}={raw!r} is not a number"
        ) from None
    if val <= 0:
        raise ValueError(f"{SNAPSHOT_GBPS_ENV}={raw!r} must be > 0")
    return val


@dataclasses.dataclass
class SnapshotAdvice:
    """The snapshot advisor's decision row (CachePlan's sibling): should a
    repeat-epoch workload materialize decoded chunks instead of re-decoding
    every epoch?  Same cost-model shape as the caching inequality — decode
    seconds saved across repeat epochs vs the IO cost of writing once and
    reading per epoch."""

    images: int
    epochs: int
    bytes_per_image: int
    decode_images_per_sec: float
    gbps: float
    live_seconds: float  #: epochs x one full decode
    snapshot_seconds: float  #: decode once + write once + read (epochs-1)x
    advise: bool
    reason: str

    def record(self) -> dict:
        out = dataclasses.asdict(self)
        out["live_seconds"] = round(self.live_seconds, 3)
        out["snapshot_seconds"] = round(self.snapshot_seconds, 3)
        return out


def advise_snapshot(
    *,
    images: int,
    bytes_per_image: int,
    decode_images_per_sec: float,
    epochs: int,
    gbps: float | None = None,
) -> SnapshotAdvice:
    """Cost-based snapshot decision: a snapshot pays when the decode time
    it removes from epochs 2..N exceeds the one-time shard write plus the
    per-epoch shard read.  ``decode_images_per_sec`` is the MEASURED live
    decode rate (bench's decode ceiling, or the stream's own stats);
    ``gbps`` prices shard IO (``KEYSTONE_SNAPSHOT_GBPS``)."""
    if images < 0 or epochs < 1 or decode_images_per_sec <= 0:
        raise ValueError(
            "advise_snapshot wants images >= 0, epochs >= 1, "
            "decode_images_per_sec > 0"
        )
    rate = gbps if gbps is not None else snapshot_gbps()
    decode_secs = images / decode_images_per_sec
    io_secs = images * bytes_per_image / (rate * 2**30)
    live = epochs * decode_secs
    snap = decode_secs + io_secs + (epochs - 1) * io_secs
    advise = epochs > 1 and snap < live
    if epochs <= 1:
        reason = "single pass: nothing to amortize"
    elif advise:
        reason = (
            f"snapshot {snap:.2f}s < live {live:.2f}s over {epochs} epochs "
            f"(decode {decode_secs:.2f}s/epoch, shard IO {io_secs:.2f}s @ "
            f"{rate}GB/s)"
        )
    else:
        reason = (
            f"live {live:.2f}s <= snapshot {snap:.2f}s — shard IO would "
            "cost more than the decode it saves"
        )
    out = SnapshotAdvice(
        images=images,
        epochs=epochs,
        bytes_per_image=bytes_per_image,
        decode_images_per_sec=decode_images_per_sec,
        gbps=rate,
        live_seconds=live,
        snapshot_seconds=snap,
        advise=advise,
        reason=reason,
    )
    trace.instant(
        "snapshot_advice", advise=advise, live_seconds=round(live, 3),
        snapshot_seconds=round(snap, 3), epochs=epochs,
    )
    return out


# -- the closed-loop ingest autotuner -----------------------------------------


class IngestAutotuner:
    """Closed-loop controller over one ingest stream's :class:`StreamConfig`.

    Attached by ``core.ingest`` (``config.autotune`` / explicit ``tuner=``),
    it is invoked at every chunk boundary on the consumer thread and, every
    ``autotune_interval`` chunks, reads the interval's stall deltas from the
    stream's stats (the same numbers published as ``ingest_*`` gauges in
    ``trace.metrics``):

    * ``consumer_stalls`` grew, ``producer_stalls`` didn't -> the ring ran
      dry: DECODE-BOUND.  Double the decode width (up to the pool cap) and
      keep the decode-ahead window at least as wide, so the extra lanes can
      actually fill.
    * ``producer_stalls`` grew, ``consumer_stalls`` didn't -> the ring ran
      full: DEVICE/CONSUMER-BOUND.  Narrow decode one step (on a CPU
      backend the decode pool and the featurize share cores — idle decode
      width is stolen featurize time) and deepen the ring (up to the cap)
      to absorb burstiness.
    * both (or neither) moved -> mixed/converged: leave the knobs alone.
    * decode-bound AND the last decode-width doubling bought <
      :attr:`SCALING_FLOOR` (1.3x) chunk throughput -> the pool is
      GIL-bound, not core-bound: promote ``decode_backend`` to
      ``process`` (the stream spins up the spawned shared-memory decode
      pool at its next member), counted ``ingest_backend_promotions``.

    Every retune is appended to :attr:`trajectory`, counted
    (``ingest_retunes``), and emitted as an ``ingest_autotune`` trace
    instant — the knob path is auditable next to the span timeline.
    Retunes touch concurrency/buffering knobs only; output identity is the
    stream's own invariant.
    """

    #: Threaded decode scaling below this after a width doubling reads as
    #: "the GIL is the wall, not core count" — the knob that helps is the
    #: BACKEND, not more width (ISSUE 7: BENCH_r05 measured 1.04x).
    SCALING_FLOOR = 1.3

    def __init__(
        self,
        *,
        interval: int | None = None,
        min_threads: int = 1,
        max_ring: int = 64,
        max_ahead: int = 64,
        allow_backend_switch: bool = True,
    ):
        self._interval = interval
        self._min_threads = min_threads
        self._max_ring = max_ring
        self._max_ahead = max_ahead
        self._allow_backend_switch = allow_backend_switch
        self.trajectory: list = []
        self._chunks = 0
        self._last_prod = 0
        self._last_cons = 0
        self._warmed = False
        self._cfg = None
        self._stats = None
        self._last_decide_t: float | None = None
        self._last_interval_chunks = 0
        #: rate (chunks/sec) measured over the interval BEFORE the last
        #: decode-width doubling — the denominator of the scaling check.
        self._widen_rate: float | None = None
        #: actual width ratio of the widen behind _widen_rate (a widen
        #: capped by max_decode_threads may be far less than a doubling —
        #: the promotion floor must scale with what was really promised)
        self._widen_ratio: float | None = None

    def _now(self) -> float:  # seam for tests
        import time

        return time.monotonic()

    def attach(self, stream) -> None:
        self._cfg = stream.config
        self._stats = stream.stats
        self._last_prod = stream.stats.producer_stalls
        self._last_cons = stream.stats.consumer_stalls

    def on_chunk(self, stream) -> None:
        self._chunks += 1
        interval = self._interval or self._cfg.autotune_interval
        if self._chunks % max(1, interval):
            return
        self._decide()

    def _decide(self) -> None:
        cfg, st = self._cfg, self._stats
        dp = st.producer_stalls - self._last_prod
        dc = st.consumer_stalls - self._last_cons
        self._last_prod = st.producer_stalls
        self._last_cons = st.consumer_stalls
        now = self._now()
        rate = None
        if self._last_decide_t is not None and now > self._last_decide_t:
            rate = (self._chunks - self._last_interval_chunks) / (
                now - self._last_decide_t
            )
        self._last_decide_t = now
        self._last_interval_chunks = self._chunks
        if not self._warmed:
            # The first interval always contains the warm-up stall: the
            # consumer's first ring.get precedes any decoded chunk, so a
            # consumer_stall of 1 here says NOTHING about the steady state
            # — acting on it would widen decode on perfectly converged (or
            # consumer-bound) streams.  Discard it and measure from here.
            self._warmed = True
            return
        changes: dict = {}

        def move(knob: str, new) -> None:
            old = getattr(cfg, knob)
            if new != old:
                setattr(cfg, knob, new)
                changes[knob] = [old, new]

        if dc > 0 and dp == 0:
            # Decode-bound: the consumer found the ring empty this interval.
            # The floor scales with the width ratio actually widened: a
            # full doubling promises SCALING_FLOOR (1.3x); a ceiling-capped
            # 7->8 widen only promises ~1.13x even core-bound — holding it
            # to 1.3x would misread linear scaling as GIL-bound.
            floor = (
                1.0 + (self.SCALING_FLOOR - 1.0) * (self._widen_ratio - 1.0)
                if self._widen_ratio is not None
                else None
            )
            if (
                self._widen_rate is not None
                and floor is not None
                and rate is not None
                and rate / self._widen_rate < floor
                and cfg.decode_backend == "thread"
                and self._allow_backend_switch
            ):
                # A doubling of decode width bought <1.3x — the thread pool
                # is GIL-bound, not core-bound.  Promote the BACKEND: the
                # stream lazily spins up the spawned-process pool at its
                # next member submit.  The pool width must track the TUNED
                # decode width, not the (possibly starved) initial
                # decode_procs resolution — and it must land BEFORE the
                # backend flip: the producer thread polls the config per
                # member, and flipping first could race it into spawning
                # a 1-worker "parallel" pool that is never resized.
                move(
                    "decode_procs",
                    max(cfg.decode_procs, cfg.decode_threads),
                )
                move("decode_backend", "process")
                trace.metrics.inc("ingest_backend_promotions")
                self._widen_rate = None
                self._widen_ratio = None
            else:
                old_width = cfg.decode_threads
                move(
                    "decode_threads",
                    min(cfg.max_decode_threads, cfg.decode_threads * 2),
                )
                move(
                    "decode_ahead",
                    min(
                        self._max_ahead,
                        max(cfg.decode_ahead, cfg.decode_threads),
                    ),
                )
                if "decode_threads" in changes:
                    # Remember the pre-widen rate AND how much wider the
                    # pool really got: the NEXT decode-bound interval's
                    # rate over it is the measured scaling.
                    self._widen_rate = rate
                    self._widen_ratio = cfg.decode_threads / old_width
                elif cfg.decode_threads == cfg.max_decode_threads == old_width:
                    # Already at the width ceiling and still starved: treat
                    # the flatline as scaling evidence too (a capped pool
                    # can never demonstrate a doubling — hold it to the
                    # full-doubling floor so a flat rate reads GIL-bound).
                    self._widen_rate = self._widen_rate or rate
                    self._widen_ratio = self._widen_ratio or 2.0
        elif dp > 0 and dc == 0:
            # Consumer-bound: the producer blocked on a full ring.
            move(
                "decode_threads",
                max(self._min_threads, cfg.decode_threads - 1),
            )
            move("ring_capacity", min(self._max_ring, cfg.ring_capacity * 2))
            self._widen_rate = None
            self._widen_ratio = None
        else:
            # Converged or mixed interval: the pre-widen rate is no longer
            # comparable evidence (chunk mix and load drift between
            # decode-bound episodes) — a promotion must be argued from
            # CONSECUTIVE decode-bound intervals, never a rate measured
            # many intervals ago.
            self._widen_rate = None
            self._widen_ratio = None
        if not changes:
            return
        entry = {
            "chunk": self._chunks,
            "producer_stalls_delta": dp,
            "consumer_stalls_delta": dc,
            "changes": changes,
        }
        self.trajectory.append(entry)
        trace.metrics.inc("ingest_retunes")
        trace.instant("ingest_autotune", **entry)
        _logger.info(
            "ingest autotune @chunk %d: %s (producer_stalls+%d, "
            "consumer_stalls+%d)",
            self._chunks, changes, dp, dc,
        )

    def record(self) -> dict:
        return {
            "retunes": len(self.trajectory),
            "trajectory": list(self.trajectory),
            "final_config": self._cfg.record() if self._cfg else None,
        }
