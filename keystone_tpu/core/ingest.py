"""Streaming ingest: decode/featurize overlap with a host ring buffer and
double-buffered H2D transfers.

The reference hides decode latency behind per-executor parallelism
(ImageLoaderUtils.scala decodes per executor while other executors
featurize); the eager port decoded every tar member into host RAM before
the first device batch ran, leaving the accelerator idle for the whole
decode phase.  This module turns tar -> decode -> featurize into a
bounded-capacity pipeline (the tf.data "prefetch to device" pattern):

* **producer thread** — reads the tar serially (tar is a sequential
  format; opens retry via ``core.resilience.retry``), decodes JPEGs on a
  thread pool (``loaders.image_loaders.decode_threads()`` wide, with a
  bounded in-order window of ``decode_threads() + decode_ahead()``
  in-flight decodes), assembles decoded images into **shape buckets**
  (XLA wants static shapes), and pushes batch-assembled ``np.ndarray``
  chunks into a host **ring buffer**.  A full ring blocks the producer —
  backpressure, so decode never runs unboundedly ahead of the device.
* **transfer stage** — the consumer generator starts each chunk's H2D
  (``jax.device_put``, dispatched asynchronously) as soon as it leaves the
  ring and keeps **two** device-resident batches in flight: batch *i+1*
  transfers while the consumer featurizes batch *i*.  The consumer
  synchronizes (``np.asarray`` / ``block_until_ready``) only on the batch
  it is consuming.
* **consumer API** — ``stream_batches(path, batch_size, ...)`` yields
  :class:`StreamBatch` in assembly order; each carries the global image
  ordinals (``indices``) and member ``names`` so features scatter back to
  decode-survival order exactly like the eager path.

Resilience invariants preserved from the eager loaders:

* tar opens retry transient IO (``io_retry`` counted); corrupt members
  are counted skips (``corrupt_image``/``tar_member_error``) — never
  silent, never fatal.
* every ring wait is a short poll, so a ``resilience.deadline`` armed
  around the consumer interrupts a hung decoder thread as a typed
  ``DeadlineExceeded`` instead of deadlocking the pipeline.
* consumer exceptions (or early exit) stop the producer and release the
  decode pool; producer exceptions surface on the consumer's next
  ``__next__``.  ``join()`` lets tests assert every thread exited.

Two attacks on the decode wall itself (BENCH_r05: threaded decode speedup
1.04x — the pool is GIL-bound — while device featurize runs 15-17k
images/sec):

* **process decode backend** (``KEYSTONE_DECODE_BACKEND=process``) — a
  pool of SPAWNED worker processes decodes members truly in parallel; raw
  tar member bytes go in over per-worker queues, decoded pixels come back
  in ``multiprocessing.shared_memory`` blocks the chunk assembly stacks
  straight out of.  Worker crashes respawn (counted
  ``decode_worker_respawn``; a task that keeps killing workers becomes a
  counted ``decode_worker_lost`` skip), hangs fall to the same
  ``resilience.deadline`` contract as a hung decode thread, and every
  worker is joined — and every shm block released — on stream exit.
* **snapshot cache** (``KEYSTONE_SNAPSHOT_DIR``, core.snapshot) — the
  first pass over a tar tees its decoded chunks to disk; later passes
  stream the shards through the same ring at IO speed.  Staleness and
  shard corruption are counted fallbacks to live decode
  (``snapshot_stale`` / ``snapshot_fallback``), never silently wrong
  pixels — the fallback re-decode cross-checks the chunk prefix the
  consumer already received and dies typed
  (:class:`SnapshotFallbackDivergence`) if the survivor sequences
  diverged rather than scramble ordinals.

The THIRD decode-wall attack (ISSUE 13) moves the pixel math off the host
entirely: ``decode_mode="device"`` (``KEYSTONE_DEVICE_DECODE=1``) has the
producer threads run an ENTROPY-ONLY pass (ops.jpeg_device: markers +
Huffman -> quantized DCT coefficients), the ring carries
:class:`CoeffChunk` coefficient chunks bucketed by JPEG geometry, the
transfer stage double-buffers H2D of coefficients (~1/4 of pixel bytes),
and ``StreamBatch.apply`` fuses dequant/IDCT/upsample/colorspace INTO the
featurize program — pixels are born on device.  JPEGs outside the
baseline subset fall back to the host decode path counted per reason
(``device_decode_fallback_<reason>``); damaged scans are typed counted
skips (``jpeg_corrupt_entropy``, chaos family of the same name).  The
decoded-pixel snapshot cache does not compose with device decode
(different IDCT rounding — disabled counted); the DEVICE-FORMAT snapshot
tier (``snapshot_mode="device"``) stores dtype-final padded shards on the
(host-decoded) cold pass so warm epochs are pure DMA with zero host
transform.

Every sizing knob lives in a mutable :class:`StreamConfig` (env-seeded:
the ``KEYSTONE_DECODE_THREADS`` / ``KEYSTONE_DECODE_AHEAD`` /
``KEYSTONE_RING_CAPACITY`` values are INITIAL settings, no longer frozen
at construction) consulted at every decision point, so the closed-loop
autotuner (core.optimize.IngestAutotuner, ``KEYSTONE_AUTOTUNE=1``) can
retune decode width, ring depth, decode-ahead — and now the decode
BACKEND (promoted to ``process`` when it observes threaded scaling
flatline) — mid-stream.  Knobs change concurrency and buffering only —
never ordering or content.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import logging
import multiprocessing
import os
import queue as _queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Callable

import numpy as np

from ..loaders import image_loaders
from . import numerics as knum
from . import snapshot as ksnap
from . import trace
from .resilience import counters

# NO module-level jax import: every spawned decode worker re-imports THIS
# module (its target function _decode_worker_main lives here), and the only
# jax consumer is the consumer-side H2D transfer — which a worker never
# runs.  jax loads lazily at the first device_put instead of costing every
# worker spawn multi-second interpreter startup (the bench_decode
# total-vs-steady gap).  tests/test_lazy_import.py enforces this.


def _device_put(host):
    import jax

    return jax.device_put(host)

_logger = logging.getLogger("keystone_tpu.ingest")

#: Process-unique sequence for /statusz stream-provider names.
_stream_seq = itertools.count()

#: Assembled chunks the host ring holds before the producer blocks.  Each
#: slot is a decoded f32 batch (batch_size * H * W * 3 * 4 bytes), so the
#: default bounds host RAM at ~4 batches beyond the decode window.
DEFAULT_RING_CAPACITY = 4

#: Device batches the transfer stage keeps in flight: the consumed batch
#: plus the next one whose H2D overlaps the consumer's featurize.
DEVICE_BUFFERS = 2

#: Every blocking wait in the pipeline is a poll at this period so signals
#: (the resilience.deadline SIGALRM) and stop flags are always observed.
_POLL_SECONDS = 0.05


def ring_capacity() -> int:
    """Ring depth: ``KEYSTONE_RING_CAPACITY`` env or the default."""
    raw = os.environ.get("KEYSTONE_RING_CAPACITY", "").strip()
    if raw:
        try:
            val = int(raw)
        except ValueError:
            raise ValueError(
                f"KEYSTONE_RING_CAPACITY={raw!r} is not an integer"
            ) from None
        if val < 1:
            raise ValueError(f"KEYSTONE_RING_CAPACITY={raw!r} must be >= 1")
        return val
    return DEFAULT_RING_CAPACITY


def _env_flag(name: str) -> bool:
    return os.environ.get(name, "").strip() in ("1", "true", "on", "yes")


def _host_cores() -> int:
    """Physical decode ceiling: the host's schedulable cores — deliberately
    NOT ``image_loaders.decode_threads()``, whose env override sets the
    INITIAL width; capping at the override too would pin the autotuner to
    it and make widening impossible."""
    try:
        return len(os.sched_getaffinity(0)) or 1
    except AttributeError:  # platforms without sched_getaffinity
        return os.cpu_count() or 1


def _env_int(name: str, default: int, minimum: int) -> int:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        val = int(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r} is not an integer") from None
    if val < minimum:
        raise ValueError(f"{name}={raw!r} must be >= {minimum}")
    return val


#: Decode backends a stream can run: GIL-bound thread pool (PIL/native
#: decode release the GIL, but entropy decode + colorspace still serialize
#: badly — BENCH_r05 measured 1.04x threaded "speedup") or true parallel
#: spawned worker processes returning pixels via shared memory.
DECODE_BACKENDS = ("thread", "process")

#: Where pixels are born: ``host`` (full decode on the host, the classic
#: path) or ``device`` (host does the entropy pass only, the ring carries
#: quantized DCT coefficient chunks, and dequant/IDCT/upsample/colorspace
#: run batched on the accelerator — ops.jpeg_device).
DECODE_MODES = ("host", "device")


def decode_backend_env() -> str:
    """``KEYSTONE_DECODE_BACKEND``: ``thread`` (default) or ``process``."""
    raw = os.environ.get("KEYSTONE_DECODE_BACKEND", "").strip() or "thread"
    if raw not in DECODE_BACKENDS:
        raise ValueError(
            f"KEYSTONE_DECODE_BACKEND={raw!r} must be one of {DECODE_BACKENDS}"
        )
    return raw


def decode_mode_env() -> str:
    """``KEYSTONE_DEVICE_DECODE``: ``1`` (or ``device``) turns on
    device-resident decode; default ``host``."""
    raw = os.environ.get("KEYSTONE_DEVICE_DECODE", "").strip().lower()
    if raw in ("", "0", "off", "false", "host"):
        return "host"
    if raw in ("1", "on", "true", "device", "yes"):
        return "device"
    raise ValueError(
        f"KEYSTONE_DEVICE_DECODE={raw!r} must be 0/1 (or host/device)"
    )


@dataclasses.dataclass
class StreamConfig:
    """The LIVE knob set of one ingest stream.

    The env knobs (``KEYSTONE_DECODE_THREADS`` / ``KEYSTONE_DECODE_AHEAD`` /
    ``KEYSTONE_RING_CAPACITY``) used to be read once at stream construction
    and frozen; they are now only the INITIAL values of this mutable config
    (:meth:`from_env`).  The stream consults the config at every decision
    point — each tar member for the decode window, each ring put for the
    capacity — so mutating a field retunes the stream mid-run.  That is the
    closed-loop autotuner's mutation surface (core.optimize.IngestAutotuner),
    and a programmatic configuration API in its own right.

    The knobs control CONCURRENCY AND BUFFERING only — never ordering or
    content: decodes complete through an in-order FIFO window and chunks
    assemble identically at any width/depth, so retuning may change speed,
    never results (the ``autotune_thrash`` chaos family holds it to that).

    ``decode_threads`` is the number of decodes kept in flight (the
    effective pool width); ``max_decode_threads`` caps how far a tuner may
    raise it — the thread pool is created at the cap, width is governed by
    the in-flight window.
    """

    decode_threads: int
    decode_ahead: int
    ring_capacity: int
    max_decode_threads: int = 0  # 0 -> resolved to >= decode_threads in __post_init__
    autotune: bool = False  #: create an IngestAutotuner for this stream
    autotune_interval: int = 4  #: chunks between controller evaluations
    #: Decode backend: "thread" (GIL-bound pool) or "process" (spawned
    #: workers + shared-memory return path).  Consulted PER MEMBER, so the
    #: autotuner can promote a running stream to process decode when it
    #: observes threaded scaling flatline (core.optimize.IngestAutotuner).
    decode_backend: str = "thread"
    #: Process-backend worker count; 0 -> resolved to decode_threads.
    decode_procs: int = 0
    #: ``host`` = full pixel decode on the host (thread/process backend);
    #: ``device`` = entropy-only host pass, coefficient chunks in the
    #: ring, batched dequant+IDCT+upsample+colorspace on the accelerator
    #: (ops.jpeg_device).  JPEGs outside the device path's baseline
    #: subset fall back to host decode COUNTED per reason
    #: (``device_decode_fallback_<reason>``); the entropy pass runs on
    #: the thread pool regardless of ``decode_backend`` (it is the light
    #: pass — the heavy math moved on-device).
    decode_mode: str = "host"
    #: Snapshot cache root (None = off): first pass over the tar writes
    #: decoded chunks here, later passes stream them at IO speed
    #: (core.snapshot).  ``snapshot_mode="featurized"`` is handled ABOVE
    #: the ring by the workload helpers (fv_common) — the ingest stream
    #: itself only materializes decoded chunks.
    snapshot_dir: str | None = None
    snapshot_mode: str = "decoded"
    #: Extra key material for the snapshot content hash — REQUIRED when the
    #: stream uses a ``keep`` member filter (the filter selects the member
    #: set, so an unkeyed filter would alias different survivor sets).
    snapshot_extra: str | None = None

    def __post_init__(self):
        if self.decode_threads < 1:
            raise ValueError(f"decode_threads must be >= 1, got {self.decode_threads}")
        if self.decode_ahead < 0:
            raise ValueError(f"decode_ahead must be >= 0, got {self.decode_ahead}")
        if self.ring_capacity < 1:
            raise ValueError(f"ring_capacity must be >= 1, got {self.ring_capacity}")
        if self.autotune_interval < 1:
            raise ValueError(
                f"autotune_interval must be >= 1, got {self.autotune_interval}"
            )
        if self.decode_backend not in DECODE_BACKENDS:
            raise ValueError(
                f"decode_backend={self.decode_backend!r} must be one of "
                f"{DECODE_BACKENDS}"
            )
        if self.decode_procs < 0:
            raise ValueError(
                f"decode_procs must be >= 0, got {self.decode_procs}"
            )
        if self.decode_procs == 0:
            self.decode_procs = self.decode_threads
        if self.decode_mode not in DECODE_MODES:
            raise ValueError(
                f"decode_mode={self.decode_mode!r} must be one of "
                f"{DECODE_MODES}"
            )
        if self.snapshot_mode not in ksnap.MODES:
            raise ValueError(
                f"snapshot_mode={self.snapshot_mode!r} must be one of "
                f"{ksnap.MODES}"
            )
        if self.max_decode_threads == 0:
            self.max_decode_threads = max(self.decode_threads, _host_cores())
        elif self.max_decode_threads < self.decode_threads:
            # An EXPLICIT cap below the width is a contradiction, not a
            # sentinel — silently widening it would let the tuner exceed a
            # bound the caller set to protect host CPU.
            raise ValueError(
                f"max_decode_threads={self.max_decode_threads} is below "
                f"decode_threads={self.decode_threads}"
            )

    @classmethod
    def from_env(cls, **overrides) -> "StreamConfig":
        """Env-seeded defaults (``KEYSTONE_DECODE_THREADS`` /
        ``KEYSTONE_DECODE_AHEAD`` / ``KEYSTONE_RING_CAPACITY`` /
        ``KEYSTONE_AUTOTUNE`` / ``KEYSTONE_AUTOTUNE_INTERVAL`` /
        ``KEYSTONE_DECODE_BACKEND`` / ``KEYSTONE_DECODE_PROCS`` /
        ``KEYSTONE_SNAPSHOT_DIR`` / ``KEYSTONE_SNAPSHOT_MODE``), any field
        overridable by keyword."""
        cfg = {
            "decode_threads": image_loaders.decode_threads(),
            "decode_ahead": image_loaders.decode_ahead(),
            "ring_capacity": ring_capacity(),
            "autotune": _env_flag("KEYSTONE_AUTOTUNE"),
            "autotune_interval": _env_int("KEYSTONE_AUTOTUNE_INTERVAL", 4, 1),
            "decode_backend": decode_backend_env(),
            "decode_mode": decode_mode_env(),
            "decode_procs": _env_int("KEYSTONE_DECODE_PROCS", 0, 0),
            "snapshot_dir": ksnap.snapshot_dir_env(),
            "snapshot_mode": ksnap.snapshot_mode_env(),
        }
        cfg.update({k: v for k, v in overrides.items() if v is not None})
        return cls(**cfg)

    def window(self) -> int:
        """In-flight decode window: effective pool width + decode-ahead."""
        return max(1, self.decode_threads) + max(0, self.decode_ahead)

    def record(self) -> dict:
        return dataclasses.asdict(self)


class _Cancelled(Exception):
    """Internal: the consumer stopped the stream — unwind the producer."""


class _FallbackPixels:
    """Device-decode task result: the JPEG is outside the device path's
    baseline subset (``reason``) and was decoded on the host instead —
    the producer counts the fallback per reason."""

    __slots__ = ("reason", "img")

    def __init__(self, reason: str, img):
        self.reason = reason
        self.img = img


class _CorruptEntropy:
    """Device-decode task result: the entropy-coded scan is damaged — a
    typed, counted skip (``jpeg_corrupt_entropy``), never silent wrong
    pixels."""

    __slots__ = ("detail",)

    def __init__(self, detail: str):
        self.detail = detail


def _entropy_decode_task(data: bytes):
    """One member's DEVICE-mode decode task (thread pool): entropy-only
    decode into a ``CoeffImage``; JPEGs the device path cannot claim fall
    back to the full host decode TYPED (``_FallbackPixels``), damaged
    scans come back as ``_CorruptEntropy``.  The device path reproduces
    ``decode_image``'s reject rules (min dimension) so host and device
    streams keep identical survivor sets."""
    from ..ops import jpeg_device as jdev

    try:
        ci = jdev.entropy_decode(data)
    except jdev.JpegEntropyCorrupt as e:
        return _CorruptEntropy(str(e))
    except jdev.JpegDecodeUnsupported as e:
        return _FallbackPixels(e.reason, image_loaders.decode_image(data))
    if (
        ci.geom.height < image_loaders.MIN_DIM
        or ci.geom.width < image_loaders.MIN_DIM
    ):
        return None  # the decode_image reject floor, same counted skip
    return ci


class SnapshotFallbackDivergence(RuntimeError):
    """The live re-decode behind a corrupt-shard snapshot fallback stopped
    matching the chunk prefix the consumer already received from the
    snapshot (a transient counted skip — e.g. ``decode_worker_lost`` —
    shifted the survivor sequence between the two passes).  The served
    prefix is valid original data, but continuing would assign the same
    stream ordinals to different images, silently scrambling the
    consumer's scatter — so the stream dies TYPED (and counted,
    ``snapshot_fallback_divergence``) instead."""


# -- the multiprocess decode backend ------------------------------------------


def _decode_worker_main(task_q, result_q):
    """Entry point of one SPAWNED decode worker process.

    Receives ``(task_id, raw_member_bytes)``, decodes with the same
    ``image_loaders.decode_image`` the thread path runs (bit-identity by
    construction), and publishes the pixels through a
    ``multiprocessing.shared_memory`` block sized to the decoded array —
    the parent maps the block and stacks STRAIGHT from it into the chunk
    assembly, so no pickled array ever crosses the result queue.  A
    ``None`` task is the shutdown sentinel; a corrupt member answers
    ``(task_id, None, None, None)`` (the parent counts the skip)."""
    from multiprocessing import shared_memory

    from ..loaders import image_loaders as _loaders
    from ..loaders.native_decode import available as _native_available

    _native_available()  # one-time build/load before the decode loop
    while True:
        item = task_q.get()
        if item is None:
            break
        tid, data = item
        try:
            img = _loaders.decode_image(data)
        except Exception:  # noqa: BLE001 — a crash here is a counted skip
            img = None
        if img is None:
            result_q.put((tid, None, None, None))
            continue
        shm = shared_memory.SharedMemory(create=True, size=img.nbytes)
        np.ndarray(img.shape, img.dtype, buffer=shm.buf)[:] = img
        # The block stays REGISTERED with the resource tracker (shared
        # with the parent via the spawn tracker_fd): the tracker reaps
        # only when main + every worker have exited, so worker exit or
        # respawn can never unlink a block the parent is assembling from,
        # and a SIGKILL landing anywhere around this put — even before
        # the queue's feeder thread flushes the name to the pipe — leaves
        # the block tracker-known and reclaimed at interpreter exit.  The
        # parent's unlink() unregisters on the normal path.
        result_q.put((tid, shm.name, img.shape, img.dtype.str))
        shm.close()


class _ShmArray:
    """Parent-side view of one worker-decoded image living in shared
    memory.  ``arr`` is a zero-copy ndarray over the block; ``release()``
    (after chunk assembly copies the pixels out) closes and unlinks it."""

    __slots__ = ("_pool", "shm", "arr")

    def __init__(self, pool, shm, shape, dtype):
        self._pool = pool
        self.shm = shm
        self.arr = np.ndarray(shape, dtype, buffer=shm.buf)

    @property
    def shape(self):
        return self.arr.shape

    def release(self) -> None:
        self._pool._release(self.shm)


class _ProcTask:
    """Future-like handle for one member's process decode (same
    ``result(timeout)`` surface as a thread-pool future, so the in-order
    FIFO window holds either kind)."""

    __slots__ = (
        "id", "name", "data", "worker", "img", "done", "skip_reason",
        "resubmits", "_pool",
    )

    def __init__(self, pool, tid: int, name: str, data: bytes):
        self._pool = pool
        self.id = tid
        self.name = name
        self.data = data  # retained until done: a dead worker's tasks resubmit
        self.worker = None
        self.img = None
        self.done = False
        self.skip_reason: str | None = None
        self.resubmits = 0

    def result(self, timeout: float):
        return self._pool._wait(self, timeout)


class _PoolWorker:
    __slots__ = ("proc", "task_q", "pending")

    def __init__(self, proc, task_q):
        self.proc = proc
        self.task_q = task_q
        self.pending: dict = {}  # task_id -> _ProcTask


class _ProcessDecodePool:
    """True parallel decode: ``procs`` SPAWNED worker processes (no fork —
    jax-unsafe), raw tar member bytes in over per-worker task queues,
    decoded pixels back via shared memory.

    Crash containment: a worker that dies (OOM-killed, SIGKILL chaos) is
    detected on the next result wait — its pending tasks are resubmitted to
    a freshly spawned replacement (counted ``decode_worker_respawn``); a
    task that kills workers repeatedly becomes a counted skip
    (``decode_worker_lost``) instead of a respawn storm.  A HUNG worker is
    the consumer deadline's problem, exactly like a hung decode thread:
    ``result()`` keeps timing out, the armed ``resilience.deadline`` fires
    typed, and :meth:`shutdown` terminates the stragglers — the ring never
    deadlocks and workers are always joined on stream exit.

    Every live shared-memory block is registered in ``_live_shm`` until the
    chunk assembly releases it, and :meth:`shutdown` force-releases the
    registry — no ``/dev/shm`` segment outlives the stream (asserted by the
    tier-1 suite)."""

    MAX_RESUBMITS = 2

    def __init__(self, procs: int, stats: StreamStats | None = None):
        self._ctx = multiprocessing.get_context("spawn")
        self._result_q = self._ctx.Queue()
        self._workers: list[_PoolWorker] = []
        self._inflight: dict = {}  # task_id -> _ProcTask
        self._live_shm: dict = {}  # shm name -> SharedMemory
        self._ids = itertools.count()
        self._stats = stats
        self._down = False
        for _ in range(max(1, procs)):
            self._spawn_worker()

    # -- worker lifecycle ------------------------------------------------------

    def _spawn_worker(self) -> _PoolWorker:
        task_q = self._ctx.Queue()
        proc = self._ctx.Process(
            target=_decode_worker_main,
            args=(task_q, self._result_q),
            name="keystone-decode-proc",
            daemon=True,
        )
        proc.start()
        w = _PoolWorker(proc, task_q)
        self._workers.append(w)
        return w

    def _reap_dead_workers(self) -> None:
        for w in list(self._workers):
            if w.proc.is_alive():
                continue
            self._workers.remove(w)
            lost = list(w.pending.values())
            w.pending.clear()
            w.task_q.cancel_join_thread()
            w.task_q.close()
            counters.record(
                "decode_worker_respawn",
                f"pid {w.proc.pid} exited {w.proc.exitcode} with "
                f"{len(lost)} task(s) pending — respawned",
            )
            trace.instant(
                "decode_worker_respawn",
                pid=w.proc.pid, exitcode=w.proc.exitcode, lost=len(lost),
            )
            if self._stats is not None:
                self._stats.worker_respawns += 1
            self._spawn_worker()
            # Blame the crash on the worker's OLDEST pending task only —
            # the FIFO worker was decoding it when it died (pending is
            # insertion-ordered; later entries were still queued).
            # Charging every co-pending task would let one poison member
            # exhaust healthy members' resubmit budgets, skipping images
            # the thread path keeps (breaking process-vs-thread
            # bit-identity).
            if lost:
                lost[0].resubmits += 1
            for t in lost:
                if t.resubmits > self.MAX_RESUBMITS:
                    # The task itself keeps killing workers: a counted
                    # skip, never an infinite respawn loop.
                    self._inflight.pop(t.id, None)
                    t.img = None
                    t.skip_reason = "decode_worker_lost"
                    t.done = True
                    t.data = None
                else:
                    self._dispatch(t)

    # -- task flow -------------------------------------------------------------

    def submit(self, name: str, data: bytes) -> _ProcTask:
        if self._down:
            raise RuntimeError("decode pool is shut down")
        t = _ProcTask(self, next(self._ids), name, data)
        self._inflight[t.id] = t
        self._dispatch(t)
        return t

    def _dispatch(self, task: _ProcTask) -> None:
        w = min(self._workers, key=lambda w: len(w.pending))
        w.pending[task.id] = task
        task.worker = w
        w.task_q.put((task.id, task.data))

    def _handle(self, item) -> None:
        tid, shm_name, shape, dtype = item
        task = self._inflight.pop(tid, None)
        if shm_name is None:
            if task is not None:
                task.img = None
                task.skip_reason = task.skip_reason or "corrupt_image"
                task.done = True
                task.data = None
                if task.worker is not None:
                    task.worker.pending.pop(tid, None)
            return
        from multiprocessing import shared_memory

        shm = shared_memory.SharedMemory(name=shm_name)
        if task is None or task.done:
            # A resubmit raced the original worker's queued result: the
            # duplicate block is surplus — release it immediately.
            shm.close()
            try:
                shm.unlink()
            except FileNotFoundError:
                pass
            return
        nbytes = int(np.prod(shape)) * np.dtype(dtype).itemsize
        # An instant, not an io_span: attaching the block is a zero-copy
        # mmap (the pixels move later, in _emit's np.stack), so a derived
        # mb_per_s here would report dict-insert latency as IPC bandwidth.
        trace.instant(
            "ingest.shm_recv", bytes=nbytes, member=task.name
        )
        self._live_shm[shm.name] = shm
        task.img = _ShmArray(self, shm, shape, np.dtype(dtype))
        task.done = True
        task.data = None
        if task.worker is not None:
            task.worker.pending.pop(tid, None)

    def _wait(self, task: _ProcTask, timeout: float):
        end = time.monotonic() + timeout
        while True:
            drained = False
            try:
                item = self._result_q.get(timeout=_POLL_SECONDS / 5)
                drained = True
            except _queue.Empty:
                item = None
            while item is not None:
                self._handle(item)
                try:
                    item = self._result_q.get_nowait()
                except _queue.Empty:
                    item = None
            if task.done:
                return task.img
            if not drained:
                self._reap_dead_workers()
            if task.done:
                return task.img
            if time.monotonic() >= end:
                raise _FutureTimeout()

    def _release(self, shm) -> None:
        self._live_shm.pop(shm.name, None)
        shm.close()
        try:
            shm.unlink()
        except FileNotFoundError:
            pass

    # -- shutdown --------------------------------------------------------------

    def shutdown(self, clean: bool) -> None:
        """Stop every worker (sentinel, then terminate/kill stragglers),
        drain undelivered results, and force-release every live
        shared-memory block.  Idempotent."""
        if self._down:
            return
        self._down = True
        for w in self._workers:
            try:
                w.task_q.put_nowait(None)
            except (ValueError, OSError):
                pass
        end = time.monotonic() + (5.0 if clean else 1.0)
        for w in self._workers:
            w.proc.join(max(0.0, end - time.monotonic()))
        for w in self._workers:
            if w.proc.is_alive():
                w.proc.terminate()
                w.proc.join(1.0)
            if w.proc.is_alive():
                w.proc.kill()
                w.proc.join(1.0)
            w.task_q.cancel_join_thread()
            w.task_q.close()
        # Undelivered results hold blocks the parent never attached: attach
        # and unlink each so nothing leaks in /dev/shm.
        while True:
            try:
                item = self._result_q.get_nowait()
            except (_queue.Empty, OSError, ValueError):
                break
            if item[1] is not None:
                from multiprocessing import shared_memory

                try:
                    s = shared_memory.SharedMemory(name=item[1])
                    s.close()
                    s.unlink()
                except FileNotFoundError:
                    pass
        self._result_q.cancel_join_thread()
        self._result_q.close()
        for shm in list(self._live_shm.values()):
            self._release(shm)
        self._inflight.clear()

    def joined(self) -> bool:
        return self._down and not any(
            w.proc.is_alive() for w in self._workers
        )


@dataclasses.dataclass
class CoeffChunk:
    """Device-decode payload of one chunk: quantized DCT coefficients for
    a batch of same-geometry JPEGs (what the ring carries instead of
    pixels under ``decode_mode="device"``)."""

    geom: object  #: ops.jpeg_device.JpegGeometry (hashable, shape-static)
    coeffs: tuple  #: per-component [b, by, bx, 8, 8] int16 host arrays
    qt: np.ndarray  #: [b, ncomp, 8, 8] f32 per-image dequant tables
    #: (coeffs_on_device, qt_on_device) once the transfer stage ran —
    #: the double-buffered H2D moves COEFFICIENTS, not pixels
    device: tuple | None = None

    def arrays(self) -> tuple:
        return self.device if self.device is not None else (
            self.coeffs, self.qt
        )

    def nbytes(self) -> int:
        return sum(int(c.nbytes) for c in self.coeffs) + int(self.qt.nbytes)


@dataclasses.dataclass
class StreamBatch:
    """One shape-bucketed, batch-assembled chunk of decoded images.

    Under ``decode_mode="device"`` a chunk may carry COEFFICIENTS instead
    of pixels (``coeff`` set, ``host`` None): ``dev()`` then runs the
    batched device decode, and :meth:`apply` fuses decode+featurize into
    one jitted dispatch (ops.jpeg_device.fused_apply)."""

    index: int  #: chunk ordinal (FIFO yield order)
    indices: np.ndarray  #: [b] global image ordinals in decode-survival order
    names: list  #: [b] tar member names
    host: np.ndarray | None  #: [b, H, W, C] f32 host batch (None for coeff)
    device: object | None = None  #: jax.Array once the transfer stage ran
    coeff: CoeffChunk | None = None  #: device-decode payload (host is None)

    @property
    def shape(self) -> tuple:
        """The bucket key: per-image (H, W)."""
        if self.coeff is not None:
            return (self.coeff.geom.height, self.coeff.geom.width)
        return tuple(self.host.shape[1:3])

    def __len__(self) -> int:
        return len(self.names)

    def dev(self):
        """The device-resident PIXEL batch (transferring — and for
        coefficient chunks, device-decoding — on demand when the stream
        ran with ``transfer=False``)."""
        if self.device is None:
            self.device = (
                _decode_coeffs(self.coeff)
                if self.coeff is not None
                else _device_put(self.host)
            )
        return self.device

    def apply(self, transform):
        """``transform(pixels)`` for this chunk — FUSED with the device
        decode into one jitted program for coefficient chunks (pixels are
        never materialized between two dispatches), a plain call on the
        device pixel batch otherwise."""
        if self.coeff is None:
            from . import profiler as kprof

            if not kprof.enabled():
                return self._probed(transform(self.dev()))
            # Per-program MFU attribution of the featurize dispatch
            # (ISSUE 14).  Values unchanged; pipelining traded for
            # measurement only while the profiler is ON.
            dev = self.dev()
            return self._probed(kprof.attributed_call(
                f"featurize:{self.shape[0]}x{self.shape[1]}",
                tuple(np.shape(dev)), transform, dev,
            ))
        from ..ops import jpeg_device as jdev

        coeffs, qt = self.coeff.arrays()
        return self._probed(
            jdev.fused_apply(transform, self.coeff.geom, coeffs, qt)
        )

    def _probed(self, out):
        """Numerics observatory hook (KEYSTONE_NUMERICS=1): the featurize
        output of every streamed chunk is a tensor-stat probe site, with
        this chunk's tar member ``names`` as the NaN-provenance map — a
        non-finite featurize row is counted naming the member that
        produced it, not just the chunk that carried it.  One flag check
        when off; the value passes through bit-unchanged either way."""
        if knum.active():
            knum.probe(
                f"stream.featurize.{self.shape[0]}x{self.shape[1]}",
                out, names=self.names,
            )
        return out


def _decode_coeffs(chunk: CoeffChunk):
    from ..ops import jpeg_device as jdev

    coeffs, qt = chunk.arrays()
    return jdev.decode_batch(chunk.geom, coeffs, qt)


@dataclasses.dataclass
class StreamStats:
    """Per-stream ingest counters (ring depth/stall accounting for the
    bench ``e2e`` section and the backpressure tests)."""

    decoded: int = 0  #: images decoded successfully
    skipped: int = 0  #: corrupt members skipped (also counted globally)
    batches: int = 0  #: chunks emitted into the ring
    ring_capacity: int = 0
    ring_max_depth: int = 0  #: high-water mark of assembled chunks queued
    producer_stalls: int = 0  #: puts that blocked on a full ring (backpressure)
    consumer_stalls: int = 0  #: gets that found the ring empty (decode-bound)
    snapshot_chunks_read: int = 0  #: chunks served from the snapshot cache
    snapshot_chunks_written: int = 0  #: chunks teed into a snapshot writer
    worker_respawns: int = 0  #: process-backend decode workers respawned
    entropy_decoded: int = 0  #: images entropy-decoded (device decode mode)
    entropy_backend: str = ""  #: scan hot-loop backend ("native"/"python")
    entropy_corrupt: int = 0  #: typed+counted corrupt-scan skips
    device_fallbacks: int = 0  #: JPEGs routed to host decode (counted per reason)
    coeff_bytes: int = 0  #: coefficient payload bytes carried by the ring
    snapshot_dma_bytes: int = 0  #: device-format shard bytes served straight to H2D

    def record(self) -> dict:
        return dataclasses.asdict(self)


class _Ring:
    """Bounded FIFO between the producer thread and the consumer.

    All waits poll at ``_POLL_SECONDS`` so the main thread stays
    interruptible (resilience.deadline's SIGALRM) and the producer always
    observes ``stop()``.  A producer error is stored and re-raised on the
    consumer side; ``close()`` marks end-of-stream."""

    _END = object()

    def __init__(self, config: StreamConfig, stats: StreamStats):
        self._q: collections.deque = collections.deque()
        self._cond = threading.Condition()
        # Capacity is read from the LIVE config on every put: a mid-stream
        # retune takes effect at the next enqueue (shrinking below the
        # current depth just blocks the producer until the consumer drains).
        self._config = config
        self._stats = stats
        self._closed = False
        self._stopped = False
        self._error: BaseException | None = None

    @property
    def stopped(self) -> bool:
        return self._stopped

    def depth(self) -> int:
        with self._cond:
            return len(self._q)

    def put(self, item) -> bool:
        """Producer side; blocks while full (backpressure).  Returns False
        when the consumer stopped the stream."""
        with self._cond:
            stalled = False
            while len(self._q) >= max(1, self._config.ring_capacity) and not self._stopped:
                if not stalled:
                    self._stats.producer_stalls += 1
                    stalled = True
                self._cond.wait(_POLL_SECONDS)
            if self._stopped:
                return False
            self._q.append(item)
            self._stats.ring_max_depth = max(
                self._stats.ring_max_depth, len(self._q)
            )
            self._cond.notify_all()
            return True

    def get(self):
        """Consumer side; blocks while empty.  Returns ``_Ring._END`` at
        end-of-stream, re-raises a producer failure."""
        with self._cond:
            stalled = False
            while True:
                if self._q:
                    item = self._q.popleft()
                    self._cond.notify_all()
                    return item
                if self._error is not None:
                    err, self._error = self._error, None
                    raise err
                if self._closed or self._stopped:
                    return self._END
                if not stalled:
                    self._stats.consumer_stalls += 1
                    stalled = True
                self._cond.wait(_POLL_SECONDS)

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def fail(self, error: BaseException) -> None:
        with self._cond:
            self._error = error
            self._closed = True
            self._cond.notify_all()

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()


class IngestStream:
    """The streaming pipeline: iterate to consume, ``with`` (or ``close``)
    to guarantee shutdown, ``join()`` to assert no thread leaked."""

    def __init__(
        self,
        path: str,
        batch_size: int,
        *,
        keep: Callable[[str], bool] | None = None,
        num_threads: int | None = None,
        decode_ahead_slots: int | None = None,
        capacity: int | None = None,
        transfer: bool = True,
        config: StreamConfig | None = None,
        tuner=None,
    ):
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        self._path = path
        self._batch_size = batch_size
        self._keep = keep
        # The stream's live knob set: an explicit StreamConfig, or an
        # env-seeded one; the legacy per-stream kwargs override its initial
        # values.  The config object is SHARED with the caller/tuner —
        # mutations retune the running stream.
        if config is None:
            config = StreamConfig.from_env(
                decode_threads=num_threads,
                decode_ahead=decode_ahead_slots,
                ring_capacity=capacity,
            )
        else:
            if num_threads is not None:
                config.decode_threads = num_threads
                config.max_decode_threads = max(
                    config.max_decode_threads, num_threads
                )
            if decode_ahead_slots is not None:
                config.decode_ahead = decode_ahead_slots
            if capacity is not None:
                config.ring_capacity = capacity
            if num_threads is not None or decode_ahead_slots is not None or capacity is not None:
                # Legacy overrides must pass the same validation the
                # constructor enforces (num_threads=0 etc. raise, never
                # silently configure a dead stream).
                config.__post_init__()
        self.config = config
        self._transfer = transfer
        self.stats = StreamStats(ring_capacity=config.ring_capacity)
        self._ring = _Ring(config, self.stats)
        self._workers: list[threading.Thread] = []
        self._pool: ThreadPoolExecutor | None = None
        self._proc_pool: _ProcessDecodePool | None = None
        #: resolved per produce pass (_produce_live): device decode is
        #: forced OFF while a snapshot writer needs host pixels
        self._device_decode = config.decode_mode == "device"
        self._writer = None  #: core.snapshot.SnapshotWriter while teeing
        self._skip_chunks = 0
        #: (names, indices) per chunk already served from a snapshot when a
        #: corrupt shard forced the live fallback — the oracle the
        #: suppressed re-decode prefix must reproduce exactly.
        self._served_prefix: list = []
        self._chunk_counter = 0
        self.tuner = tuner
        if self.tuner is None and config.autotune:
            # Lazy import: optimize imports ingest at module level; the
            # reverse edge resolves only when a stream actually autotunes.
            from .optimize import IngestAutotuner

            self.tuner = IngestAutotuner()
        if self.tuner is not None:
            self.tuner.attach(self)
        # One line per stream so operators can see the effective ingest
        # configuration (the env knobs resolved) without env spelunking.
        _logger.info(
            "streaming ingest %s: batch=%d threads=%d ahead=%d ring=%d "
            "transfer=%s autotune=%s",
            path,
            batch_size,
            config.decode_threads,
            config.decode_ahead,
            config.ring_capacity,
            transfer,
            bool(self.tuner),
        )
        # Live ring/stream state on the /statusz debug page (ISSUE 15) —
        # jax-free: telemetry is already on the resilience import path.
        # The name carries a process-unique sequence so two concurrent
        # streams over the SAME tar each get their own row (and the
        # identity-guarded unregister means an old stream's close can
        # never evict a newer one's entry).
        from . import telemetry as _telemetry

        self._statusz_name = (
            f"stream:{os.path.basename(path)}#{next(_stream_seq)}"
        )
        self._statusz_provider = lambda: {
            "path": path,
            "batch_size": batch_size,
            "decode_threads": self.config.decode_threads,
            "decode_ahead": self.config.decode_ahead,
            "ring_capacity": self.config.ring_capacity,
            "decode_backend": self.config.decode_backend,
            **self.stats.record(),
        }
        _telemetry.register_statusz(
            self._statusz_name, self._statusz_provider
        )
        self._iter = self._drain()
        self._thread = threading.Thread(
            target=self._produce, name="keystone-ingest-producer", daemon=True
        )
        self._thread.start()

    # -- producer side --------------------------------------------------------

    def _register_worker(self):
        self._workers.append(threading.current_thread())

    def _await_decode(self, fut):
        """Poll a decode future so a stopped stream abandons a hung decoder
        instead of joining it forever."""
        while True:
            if self._ring.stopped:
                raise _Cancelled()
            try:
                return fut.result(timeout=_POLL_SECONDS)
            except _FutureTimeout:
                continue

    def _ensure_thread_pool(self) -> ThreadPoolExecutor:
        # The pool is sized at the retune CEILING; the effective width is
        # the in-flight window (config.decode_threads), consulted per
        # member — so the tuner can widen/narrow decode mid-stream without
        # rebuilding the pool.
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=self.config.max_decode_threads,
                thread_name_prefix="keystone-decode",
                initializer=self._register_worker,
            )
        return self._pool

    def _ensure_proc_pool(self) -> _ProcessDecodePool:
        if self._proc_pool is None:
            with trace.span(
                "ingest.spawn_decode_procs", cat="ingest",
                procs=self.config.decode_procs,
            ):
                self._proc_pool = _ProcessDecodePool(
                    self.config.decode_procs, self.stats
                )
            _logger.info(
                "process decode backend: %d spawned worker(s)",
                self.config.decode_procs,
            )
        return self._proc_pool

    def _submit_decode(self, name: str, data: bytes):
        """Submit one member's decode on the CURRENTLY configured backend
        (consulted per member: the autotuner may promote a running stream
        from thread to process decode; mixed futures drain through the same
        in-order FIFO window).  On the thread backend, when tracing is
        enabled each decode becomes an ``ingest.decode`` span on ITS worker
        thread's timeline — the parallel decode lanes are visible next to
        the consumer lane, so decode/featurize overlap is a picture, not an
        inference.  The module attribute is resolved at call time (the
        chaos harness patches ``image_loaders.decode_image``)."""
        if self._device_decode:
            # Entropy-only pass: always the thread pool (the native scan
            # loop releases the GIL per call so the pool scales across
            # cores; the pure-Python fallback stays the LIGHT half of the
            # decode — the heavy math runs on-device).  A process backend
            # setting governs the host-pixel path only.
            pool = self._ensure_thread_pool()
            if not trace.enabled():
                return pool.submit(_entropy_decode_task, data)

            def traced_entropy(data=data, name=name):
                with trace.span(
                    "ingest.entropy_decode", cat="ingest", member=name
                ):
                    return _entropy_decode_task(data)

            return pool.submit(traced_entropy)
        if self.config.decode_backend == "process":
            return self._ensure_proc_pool().submit(name, data)
        pool = self._ensure_thread_pool()
        if not trace.enabled():
            return pool.submit(image_loaders.decode_image, data)

        def traced(data=data, name=name):
            with trace.span("ingest.decode", cat="ingest", member=name):
                return image_loaders.decode_image(data)

        return pool.submit(traced)

    def _produce(self):
        clean = False
        try:
            clean = self._run_producer()
        except BaseException as e:  # noqa: BLE001 — surfaces on the consumer
            self._ring.fail(e)
        finally:
            self._ring.close()
            if self._writer is not None:
                # No-op after a successful commit; a cancelled/failed pass
                # must never leave a partial snapshot behind.
                self._writer.abort()
            # A stopped stream may hold a hung decode future: abandon it
            # (workers are daemon threads) instead of blocking shutdown.
            if self._pool is not None:
                self._pool.shutdown(wait=clean, cancel_futures=not clean)
            if self._proc_pool is not None:
                self._proc_pool.shutdown(clean)

    def _snapshot_plan(self):
        """``(root, key, mode)`` when an ingest-level snapshot tier applies
        to this stream — ``decoded`` (f32 pixel chunks, exactly what the
        ring carried) or ``device`` (pre-laid-out device-format shards:
        padded/bucketed, dtype-final, read back as pure DMA).
        ``snapshot_mode="featurized"`` is the workload helpers' business —
        the ring never carries feature rows.

        ``decode_mode="device"`` + a DECODED snapshot is a contradiction
        (device streams decode pixels on the accelerator, host-decoded
        cached pixels differ within IDCT rounding — serving them would
        silently change the stream's bits): the cache is disabled COUNTED
        rather than silently served."""
        cfg = self.config
        if not cfg.snapshot_dir or cfg.snapshot_mode not in (
            "decoded", "device",
        ):
            return None
        if cfg.decode_mode == "device" and cfg.snapshot_mode == "decoded":
            counters.record(
                "snapshot_mode_unsupported",
                f"{self._path}: decoded-pixel snapshots do not compose "
                "with device decode (different IDCT rounding) — use "
                "snapshot_mode='device' for a DMA-format cache",
            )
            return None
        if self._keep is not None and cfg.snapshot_extra is None:
            _logger.warning(
                "snapshot cache disabled for %s: the stream has a keep "
                "filter but no snapshot_extra key material — an unkeyed "
                "filter would alias different member subsets",
                self._path,
            )
            return None
        key = ksnap.snapshot_key(
            self._path,
            batch_size=self._batch_size,
            mode=cfg.snapshot_mode,
            extra=cfg.snapshot_extra,
        )
        return cfg.snapshot_dir, key, cfg.snapshot_mode

    def _run_producer(self) -> bool:
        """Produce chunks — from the snapshot cache when a valid one
        exists, else by live decode (teeing a fresh snapshot when caching
        is on).  Returns True on clean end-of-stream, False when the
        consumer cancelled."""
        plan = self._snapshot_plan()
        skip = 0
        if plan is not None:
            root, key, snap_mode = plan
            snap, reason = ksnap.lookup(
                root, key, tar_path=self._path, mode=snap_mode
            )
            if reason == "stale":
                counters.record(
                    "snapshot_stale",
                    f"{self._path}: committed snapshot exists under a "
                    "different key (input or decode config moved) — live "
                    "decode, fresh snapshot written",
                )
            if snap is not None:
                try:
                    emitted = self._emit_from_snapshot(snap)
                except _Cancelled:
                    return False
                if emitted is True:
                    return True
                # Corrupt shard mid-read: the chunks already emitted were
                # hash-validated (bit-equal to live decode by construction);
                # re-decode from the top, suppressing re-emission of that
                # prefix, and REWRITE the snapshot (self-healing).
                skip = emitted
                counters.record(
                    "snapshot_fallback",
                    f"{snap.path}: corrupt shard after {skip} chunk(s) — "
                    "falling back to live decode (bit-equal), rewriting",
                )
                trace.instant(
                    "snapshot_fallback", path=snap.path, emitted=skip
                )
            try:
                self._writer = ksnap.SnapshotWriter(
                    root,
                    key,
                    mode=snap_mode,
                    meta={
                        "tar": ksnap.tar_identity(self._path),
                        "path": self._path,
                        "batch_size": self._batch_size,
                        "extra": self.config.snapshot_extra,
                    },
                )
            except (OSError, ksnap.SnapshotError) as e:
                # Same contract as the add_chunk tee: an unusable snapshot
                # root (unwritable, component is a file) must never kill a
                # healthy live-decode stream — counted, cache skipped.
                counters.record(
                    "snapshot_write_failed",
                    f"{self._path}: cannot open snapshot writer: {e}",
                )
        try:
            self._produce_live(skip)
        except _Cancelled:
            return False
        if self._writer is not None:
            try:
                self._writer.commit()
            except (OSError, ksnap.SnapshotError) as e:
                # Every chunk already reached the consumer — a failed
                # commit (ENOSPC, a concurrent writer racing os.replace)
                # loses only the CACHE, never the stream.
                counters.record(
                    "snapshot_write_failed",
                    f"{self._path}: commit failed: {e}",
                )
                self._writer.abort()
        return True

    def _emit_from_snapshot(self, snap) -> bool | int:
        """Stream a committed snapshot's chunks into the ring.  Returns
        True when the whole snapshot streamed, or the count of chunks
        already emitted when a corrupt shard forces the live-decode
        fallback."""
        emitted = 0
        images = 0
        served: list = []
        with trace.span(
            "ingest.snapshot_read", cat="ingest",
            path=snap.path, chunks=len(snap.manifest["chunks"]),
        ) as sp:
            try:
                for _entry, arrays in snap.iter_chunks():
                    if self._ring.stopped:
                        raise _Cancelled()
                    payload = arrays["payload"]
                    if snap.mode == "device":
                        # Pre-laid-out shard: dtype-final f32, batch dim
                        # padded to a sharding quantum.  The slice to
                        # the valid rows is a zero-copy view — the shard
                        # bytes flow straight into the consumer's
                        # device_put with NO host transform (the warm
                        # "pure DMA" epoch the tier exists for).
                        self.stats.snapshot_dma_bytes += int(
                            payload.nbytes
                        )
                        valid = int(arrays.get("valid", len(payload)))
                        if valid < len(payload):
                            payload = payload[:valid]
                    chunk = StreamBatch(
                        index=self._chunk_counter,
                        indices=np.asarray(arrays["indices"], np.int64),
                        names=[str(n) for n in arrays["names"].tolist()],
                        host=payload,
                    )
                    self._chunk_counter += 1
                    with trace.span(
                        "ingest.ring_put", cat="ingest",
                        index=chunk.index, images=len(chunk),
                    ):
                        ok = self._ring.put(chunk)
                    if not ok:
                        raise _Cancelled()
                    self.stats.batches += 1
                    self.stats.decoded += len(chunk)
                    self.stats.snapshot_chunks_read += 1
                    emitted += 1
                    images += len(chunk)
                    served.append((chunk.names, chunk.indices))
            except ksnap.SnapshotCorrupt as e:
                sp.set(fallback_after=emitted, corrupt=str(e)[:200])
                # The live fallback re-decodes (and re-counts) everything
                # from the top; un-count the snapshot prefix so stats stay
                # one-pass truthful.  Chunk numbering restarts with it.
                self.stats.decoded -= images
                self._chunk_counter = 0
                self._served_prefix = served
                return emitted
            sp.set(chunks_read=emitted, images=images)
        return True

    def _produce_live(self, skip_chunks: int = 0):
        self._skip_chunks = skip_chunks
        # Build/load the native decoder before any pool spins up (the
        # one-time g++ build runs under native_decode's module lock and
        # would otherwise stall every worker behind the first decode).
        from ..loaders.native_decode import available as _native_available

        _native_available()
        # Frozen per pass: a snapshot tee needs host pixels (the writer
        # materializes what the ring carried), and a corrupt-shard
        # FALLBACK re-decode (skip_chunks > 0) must reproduce the pixel
        # chunks the consumer already received — _emit's prefix
        # suppression and divergence guard only exist on the pixel path,
        # so the fallback pins host decode even when the rewrite writer
        # failed to open.  Mid-stream decode_mode mutation would mix
        # chunk kinds inconsistently, so the mode is not a live retune
        # surface.
        self._device_decode = (
            self.config.decode_mode == "device"
            and self._writer is None
            and skip_chunks == 0
        )
        if self._device_decode:
            # Same prewarm contract for the entropy hot loop: build/load
            # the native scan decoder (ops/native_entropy) before the
            # entropy pool spins up, and record which backend this pass
            # will run.  Unavailability degrades to the pure-Python pass
            # counted native_entropy_unavailable — bit-equal stream,
            # lower throughput, never a crash.
            from ..ops import jpeg_device as _jd

            self.stats.entropy_backend = _jd.entropy_backend()
        # shape -> (ordinals, names, images); insertion-ordered so the
        # end-of-stream flush of partial buckets is deterministic.
        buckets: dict = {}
        # geometry -> (ordinals, names, CoeffImages) for device decode
        coeff_buckets: dict = {}
        window: collections.deque = collections.deque()
        ordinal = 0

        def keep_image(name, img):
            nonlocal ordinal
            self.stats.decoded += 1
            key = img.shape[:2]
            idx, names, imgs = buckets.setdefault(key, ([], [], []))
            idx.append(ordinal)
            names.append(name)
            imgs.append(img)
            ordinal += 1
            if len(imgs) >= self._batch_size:
                self._emit(buckets.pop(key))

        def keep_coeff(name, ci):
            nonlocal ordinal
            self.stats.decoded += 1
            self.stats.entropy_decoded += 1
            self.stats.coeff_bytes += ci.geom.coeff_bytes()
            idx, names, imgs = coeff_buckets.setdefault(
                ci.geom, ([], [], [])
            )
            idx.append(ordinal)
            names.append(name)
            imgs.append(ci)
            ordinal += 1
            if len(imgs) >= self._batch_size:
                self._emit_coeff(ci.geom, coeff_buckets.pop(ci.geom))

        def drain_one():
            name, fut = window.popleft()
            img = self._await_decode(fut)
            if isinstance(img, _CorruptEntropy):
                # Damaged entropy-coded scan under device decode: a TYPED,
                # COUNTED skip — the rest of the batch survives, and the
                # member never becomes silent wrong pixels.
                counters.record(
                    "jpeg_corrupt_entropy", f"{name}: {img.detail}"
                )
                self.stats.skipped += 1
                self.stats.entropy_corrupt += 1
                return
            if isinstance(img, _FallbackPixels):
                # Outside the device path's baseline subset: decoded on
                # the host instead, counted PER REASON so a tar full of
                # (say) progressive JPEGs is visible as exactly that.
                counters.record(
                    "device_decode_fallback", f"{name}: {img.reason}"
                )
                counters.record(
                    f"device_decode_fallback_{img.reason}", name
                )
                self.stats.device_fallbacks += 1
                img = img.img
            if img is None:
                # "corrupt_image" for an undecodable member; the process
                # backend may instead report "decode_worker_lost" (a task
                # that kept killing its workers) — either way a COUNTED
                # skip, never a silent drop.
                counters.record(
                    getattr(fut, "skip_reason", None) or "corrupt_image",
                    name,
                )
                self.stats.skipped += 1
                return
            from ..ops.jpeg_device import CoeffImage

            if isinstance(img, CoeffImage):
                keep_coeff(name, img)
            else:
                keep_image(name, img)

        with trace.span(
            "ingest.produce", cat="ingest", path=self._path
        ) as prod_sp:
            try:
                for name, data in image_loaders._iter_tar_members(
                    self._path
                ):
                    if self._ring.stopped:
                        raise _Cancelled()
                    if self._keep is not None and not self._keep(name):
                        continue
                    window.append((name, self._submit_decode(name, data)))
                    # Live window limit: a retune takes effect at the
                    # next member ("while" drains DOWN to a narrowed
                    # window; completion order through the FIFO window
                    # is unchanged by any width).
                    while len(window) >= self.config.window():
                        drain_one()
                while window:
                    drain_one()
                # Flush the batch-size remainders (partial last batch
                # per shape/geometry), oldest bucket first for a
                # deterministic tail order across BOTH bucket kinds.
                tails = [
                    (b[0][0], None, b) for b in buckets.values()
                ] + [
                    (b[0][0], geom, b)
                    for geom, b in coeff_buckets.items()
                ]
                for _first, geom, bucket in sorted(
                    tails, key=lambda t: t[0]
                ):
                    if geom is None:
                        self._emit(bucket)
                    else:
                        self._emit_coeff(geom, bucket)
            except _Cancelled:
                # Consumer stopped the stream early — routine shutdown
                # (a supported path), not a producer failure: the span
                # marks it aborted rather than errored.
                prod_sp.set(aborted=True)
                raise
            finally:
                prod_sp.set(
                    decoded=self.stats.decoded,
                    skipped=self.stats.skipped,
                    batches=self.stats.batches,
                )

    def _emit(self, bucket):
        idx, names, imgs = bucket
        # np.stack copies straight out of any shared-memory views (the
        # process backend's zero-extra-copy path into chunk assembly);
        # the blocks are released the moment the chunk owns the pixels.
        host = np.stack(
            [i.arr if isinstance(i, _ShmArray) else i for i in imgs]
        )
        for i in imgs:
            if isinstance(i, _ShmArray):
                i.release()
        chunk = StreamBatch(
            index=self._chunk_counter,
            indices=np.asarray(idx, np.int64),
            names=names,
            host=host,
        )
        self._chunk_counter += 1
        if self._writer is not None:
            try:
                # pad_to only applies to device-format shards (the writer
                # pads the batch dim so warm epochs stream fixed-shape,
                # sharding-ready buffers); decoded shards store exactly
                # the chunk.
                self._writer.add_chunk(
                    chunk.index, chunk.indices, chunk.names, chunk.host,
                    pad_to=self._batch_size,
                )
                self.stats.snapshot_chunks_written += 1
            except (OSError, ksnap.SnapshotError) as e:
                # The cache is an optimization: a full disk (or any shard
                # write failure) must never kill a healthy live-decode
                # stream — counted, writer dropped, pass continues.
                counters.record(
                    "snapshot_write_failed", f"{self._path}: {e}"
                )
                self._writer.abort()
                self._writer = None
        if chunk.index < self._skip_chunks:
            # Fallback re-decode: this prefix already streamed from the
            # snapshot (hash-validated) — rewritten above, not re-emitted.
            # Suppression is only sound while the re-decode reproduces the
            # served chunks EXACTLY; a transient counted skip in either
            # pass shifts every later chunk boundary, so verify before
            # dropping (the consumer scatters rows by these ordinals —
            # a divergence here would silently scramble them).
            names, indices = self._served_prefix[chunk.index]
            if chunk.names != names or not np.array_equal(
                chunk.indices, indices
            ):
                counters.record(
                    "snapshot_fallback_divergence",
                    f"{self._path}: live re-decode chunk {chunk.index} != "
                    "snapshot prefix already served",
                )
                raise SnapshotFallbackDivergence(
                    f"{self._path}: chunk {chunk.index} of the fallback "
                    "re-decode does not match the snapshot prefix the "
                    "consumer already received (survivor sequences "
                    "diverged — see the counted skip that shifted them)"
                )
            return
        # The put span's duration IS the backpressure stall: a full ring
        # blocks here, and the trace shows the producer lane waiting.
        with trace.span(
            "ingest.ring_put", cat="ingest",
            index=chunk.index, images=len(chunk),
        ):
            ok = self._ring.put(chunk)
        if not ok:
            raise _Cancelled()
        self.stats.batches += 1

    def _emit_coeff(self, geom, bucket):
        """Assemble one same-geometry coefficient bucket into a
        :class:`CoeffChunk`-carrying :class:`StreamBatch` (device decode
        mode: the ring carries coefficients, never pixels).  Device-mode
        passes never tee a snapshot (``_device_decode`` is forced off
        while a writer is live), so no shard/suppression path exists
        here."""
        from ..ops.jpeg_device import stack_coeff_images

        idx, names, imgs = bucket
        coeffs, qt = stack_coeff_images(imgs)
        chunk = StreamBatch(
            index=self._chunk_counter,
            indices=np.asarray(idx, np.int64),
            names=names,
            host=None,
            coeff=CoeffChunk(geom=geom, coeffs=coeffs, qt=qt),
        )
        self._chunk_counter += 1
        with trace.span(
            "ingest.ring_put", cat="ingest",
            index=chunk.index, images=len(chunk),
            coeff_bytes=chunk.coeff.nbytes(),
        ):
            ok = self._ring.put(chunk)
        if not ok:
            raise _Cancelled()
        self.stats.batches += 1

    # -- consumer side --------------------------------------------------------

    def _yield_consumed(self, item):
        """Yield one chunk under an ``ingest.consume`` span: the span runs
        from the moment the consumer receives the chunk until it asks for
        the next one — i.e. the consumer's featurize time for THAT chunk,
        on the consumer thread's lane.  Decode spans on the worker lanes
        running inside a consume span's interval ARE the overlap."""
        with trace.span(
            "ingest.consume", cat="ingest",
            index=item.index, images=len(item),
        ):
            yield item

    def _publish_metrics(self) -> None:
        """Chunk-boundary gauges: the live trace-metrics the autotuner (and
        any operator dashboard) reads — ring depth plus the current knob
        values, alongside the stats counters."""
        m = trace.metrics
        # A retune may have moved the capacity: keep the stats record (the
        # bench/chaos artifact) consistent with the ring's live bound.
        self.stats.ring_capacity = self.config.ring_capacity
        m.gauge("ingest_ring_depth", self._ring.depth())
        m.gauge("ingest_decode_threads", self.config.decode_threads)
        m.gauge("ingest_decode_ahead", self.config.decode_ahead)
        m.gauge("ingest_ring_capacity", self.config.ring_capacity)
        m.gauge("ingest_producer_stalls", self.stats.producer_stalls)
        m.gauge("ingest_consumer_stalls", self.stats.consumer_stalls)
        m.gauge("ingest_decoded", self.stats.decoded)
        m.gauge("ingest_snapshot_chunks_read", self.stats.snapshot_chunks_read)
        m.gauge("ingest_worker_respawns", self.stats.worker_respawns)
        # Device-decode surface: entropy-decode progress, coefficient
        # bytes the ring carried, fallbacks to host decode, and
        # device-format shard bytes served straight to H2D — the warm
        # device-snapshot acceptance check reads these (all zero on a
        # pure-DMA epoch except the dma gauge).
        m.gauge("ingest_entropy_decoded", self.stats.entropy_decoded)
        m.gauge(
            "ingest_entropy_native",
            1 if self.stats.entropy_backend == "native" else 0,
        )
        m.gauge("ingest_coeff_bytes", self.stats.coeff_bytes)
        m.gauge("ingest_device_fallbacks", self.stats.device_fallbacks)
        m.gauge("ingest_snapshot_dma_bytes", self.stats.snapshot_dma_bytes)

    def _drain(self):
        pending: collections.deque = collections.deque()
        try:
            while True:
                with trace.span("ingest.ring_get", cat="ingest"):
                    item = self._ring.get()
                if item is _Ring._END:
                    break
                if self._transfer:
                    # Async dispatch: the H2D for this chunk starts now and
                    # overlaps the consumer's work on the PREVIOUS chunk
                    # still being featurized.  Coefficient chunks transfer
                    # their (much lighter) coefficient arrays — the pixel
                    # batch is only ever born on device.
                    if item.coeff is not None:
                        item.coeff.device = (
                            tuple(
                                _device_put(c) for c in item.coeff.coeffs
                            ),
                            _device_put(item.coeff.qt),
                        )
                    else:
                        item.device = _device_put(item.host)
                self._publish_metrics()
                if self.tuner is not None:
                    # Chunk boundary: the closed-loop controller reads the
                    # stall counters/gauges and may retune the config.
                    self.tuner.on_chunk(self)
                pending.append(item)
                if len(pending) >= DEVICE_BUFFERS:
                    yield from self._yield_consumed(pending.popleft())
            while pending:
                yield from self._yield_consumed(pending.popleft())
        finally:
            self.close()

    def __iter__(self):
        return self

    def __next__(self) -> StreamBatch:
        return next(self._iter)

    def __enter__(self) -> "IngestStream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        """Stop the producer and release the ring.  Idempotent; called
        automatically on stream exhaustion, consumer exception, or context
        exit."""
        from . import telemetry as _telemetry

        _telemetry.unregister_statusz(
            self._statusz_name, self._statusz_provider
        )
        self._ring.stop()
        # Close the drain generator too: a consumer that stopped early
        # leaves it SUSPENDED at the yield inside an open ingest.consume
        # span, and a suspended span sits on this thread's span stack
        # corrupting every later span's depth/parent (and the flight
        # recorder's view) until the generator is garbage-collected.
        # Closing delivers GeneratorExit at the yield — the span exits as
        # aborted and pops.  ValueError = close() reached from INSIDE the
        # running generator (the exhaustion path's own finally); it is
        # already unwinding, nothing to do.
        try:
            self._iter.close()
        except ValueError:
            pass

    def join(self, timeout: float = 10.0) -> bool:
        """Wait for the producer, every decoder thread, AND every decode
        worker process to exit; returns True when no ingest thread or
        process remains alive (the no-leak assertion the tier-1 suite runs
        under pytest)."""
        end = time.monotonic() + timeout
        self._thread.join(max(0.0, end - time.monotonic()))
        for t in list(self._workers):
            t.join(max(0.0, end - time.monotonic()))
        procs_ok = True
        if self._proc_pool is not None:
            while (
                not self._proc_pool.joined() and time.monotonic() < end
            ):
                time.sleep(_POLL_SECONDS / 5)
            procs_ok = self._proc_pool.joined()
        return procs_ok and not (
            self._thread.is_alive()
            or any(t.is_alive() for t in self._workers)
        )


def stream_batches(
    path: str,
    batch_size: int,
    *,
    keep: Callable[[str], bool] | None = None,
    num_threads: int | None = None,
    decode_ahead_slots: int | None = None,
    capacity: int | None = None,
    transfer: bool = True,
    config: StreamConfig | None = None,
    tuner=None,
) -> IngestStream:
    """Stream shape-bucketed device batches from a tar (or directory of
    tars) of images.

    ``keep``: member-name predicate (label filtering before decode).
    ``config``: a :class:`StreamConfig` — the stream's LIVE knob set
    (env-seeded via :meth:`StreamConfig.from_env` when omitted); mutate it
    mid-stream to retune, or set ``config.autotune`` (env
    ``KEYSTONE_AUTOTUNE=1``) for the closed-loop controller.
    ``num_threads`` / ``decode_ahead_slots`` / ``capacity``: legacy
    per-stream overrides of the config's initial values.
    ``transfer=False`` skips the H2D stage (host-only consumers, decode
    benchmarking).  ``tuner``: an explicit controller (anything with
    ``attach(stream)`` / ``on_chunk(stream)``) instead of the default.

    Yields :class:`StreamBatch` in assembly order; use as a context
    manager (or iterate to exhaustion) so the decode threads are released,
    and ``stream.join()`` to assert they exited."""
    return IngestStream(
        path,
        batch_size,
        keep=keep,
        num_threads=num_threads,
        decode_ahead_slots=decode_ahead_slots,
        capacity=capacity,
        transfer=transfer,
        config=config,
        tuner=tuner,
    )


def host_shards(paths, rank: int | None = None, world: int | None = None):
    """This host's slice of a tar-shard list: deterministic round-robin
    (``paths[rank::world]``) over the SORTED names, so every member of a
    process group derives a disjoint cover of the dataset from the same
    listing with no coordination.  ``rank``/``world`` default from the
    live process group (``parallel.distributed``) and collapse to
    "everything" single-process — the multi-host data axis costs the
    single-process path nothing.  Each host then streams its own shards
    through :func:`stream_batches`; no bytes cross hosts at ingest."""
    paths = sorted(str(p) for p in paths)
    if rank is None or world is None:
        from ..parallel import distributed as kdist

        rank = kdist.process_index() if rank is None else rank
        world = kdist.process_count() if world is None else world
    if world <= 1:
        return paths
    if not (0 <= rank < world):
        raise ValueError(f"rank {rank} outside world {world}")
    return paths[rank::world]
