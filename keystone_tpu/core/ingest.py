"""Streaming ingest: decode/featurize overlap with a host ring buffer and
double-buffered H2D transfers.

The reference hides decode latency behind per-executor parallelism
(ImageLoaderUtils.scala decodes per executor while other executors
featurize); the eager port decoded every tar member into host RAM before
the first device batch ran, leaving the accelerator idle for the whole
decode phase.  This module turns tar -> decode -> featurize into a
bounded-capacity pipeline (the tf.data "prefetch to device" pattern):

* **producer thread** — reads the tar serially (tar is a sequential
  format; opens retry via ``core.resilience.retry``), decodes JPEGs on a
  thread pool (``loaders.image_loaders.decode_threads()`` wide, with a
  bounded in-order window of ``decode_threads() + decode_ahead()``
  in-flight decodes), assembles decoded images into **shape buckets**
  (XLA wants static shapes), and pushes batch-assembled ``np.ndarray``
  chunks into a host **ring buffer**.  A full ring blocks the producer —
  backpressure, so decode never runs unboundedly ahead of the device.
* **transfer stage** — the consumer generator starts each chunk's H2D
  (``jax.device_put``, dispatched asynchronously) as soon as it leaves the
  ring and keeps **two** device-resident batches in flight: batch *i+1*
  transfers while the consumer featurizes batch *i*.  The consumer
  synchronizes (``np.asarray`` / ``block_until_ready``) only on the batch
  it is consuming.
* **consumer API** — ``stream_batches(path, batch_size, ...)`` yields
  :class:`StreamBatch` in assembly order; each carries the global image
  ordinals (``indices``) and member ``names`` so features scatter back to
  decode-survival order exactly like the eager path.

Resilience invariants preserved from the eager loaders:

* tar opens retry transient IO (``io_retry`` counted); corrupt members
  are counted skips (``corrupt_image``/``tar_member_error``) — never
  silent, never fatal.
* every ring wait is a short poll, so a ``resilience.deadline`` armed
  around the consumer interrupts a hung decoder thread as a typed
  ``DeadlineExceeded`` instead of deadlocking the pipeline.
* consumer exceptions (or early exit) stop the producer and release the
  decode pool; producer exceptions surface on the consumer's next
  ``__next__``.  ``join()`` lets tests assert every thread exited.

Every sizing knob lives in a mutable :class:`StreamConfig` (env-seeded:
the ``KEYSTONE_DECODE_THREADS`` / ``KEYSTONE_DECODE_AHEAD`` /
``KEYSTONE_RING_CAPACITY`` values are INITIAL settings, no longer frozen
at construction) consulted at every decision point, so the closed-loop
autotuner (core.optimize.IngestAutotuner, ``KEYSTONE_AUTOTUNE=1``) can
retune decode width, ring depth, and decode-ahead mid-stream.  Knobs
change concurrency and buffering only — never ordering or content.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Callable

import jax
import numpy as np

from ..loaders import image_loaders
from . import trace
from .resilience import counters

_logger = logging.getLogger("keystone_tpu.ingest")

#: Assembled chunks the host ring holds before the producer blocks.  Each
#: slot is a decoded f32 batch (batch_size * H * W * 3 * 4 bytes), so the
#: default bounds host RAM at ~4 batches beyond the decode window.
DEFAULT_RING_CAPACITY = 4

#: Device batches the transfer stage keeps in flight: the consumed batch
#: plus the next one whose H2D overlaps the consumer's featurize.
DEVICE_BUFFERS = 2

#: Every blocking wait in the pipeline is a poll at this period so signals
#: (the resilience.deadline SIGALRM) and stop flags are always observed.
_POLL_SECONDS = 0.05


def ring_capacity() -> int:
    """Ring depth: ``KEYSTONE_RING_CAPACITY`` env or the default."""
    raw = os.environ.get("KEYSTONE_RING_CAPACITY", "").strip()
    if raw:
        try:
            val = int(raw)
        except ValueError:
            raise ValueError(
                f"KEYSTONE_RING_CAPACITY={raw!r} is not an integer"
            ) from None
        if val < 1:
            raise ValueError(f"KEYSTONE_RING_CAPACITY={raw!r} must be >= 1")
        return val
    return DEFAULT_RING_CAPACITY


def _env_flag(name: str) -> bool:
    return os.environ.get(name, "").strip() in ("1", "true", "on", "yes")


def _host_cores() -> int:
    """Physical decode ceiling: the host's schedulable cores — deliberately
    NOT ``image_loaders.decode_threads()``, whose env override sets the
    INITIAL width; capping at the override too would pin the autotuner to
    it and make widening impossible."""
    try:
        return len(os.sched_getaffinity(0)) or 1
    except AttributeError:  # platforms without sched_getaffinity
        return os.cpu_count() or 1


def _env_int(name: str, default: int, minimum: int) -> int:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        val = int(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r} is not an integer") from None
    if val < minimum:
        raise ValueError(f"{name}={raw!r} must be >= {minimum}")
    return val


@dataclasses.dataclass
class StreamConfig:
    """The LIVE knob set of one ingest stream.

    The env knobs (``KEYSTONE_DECODE_THREADS`` / ``KEYSTONE_DECODE_AHEAD`` /
    ``KEYSTONE_RING_CAPACITY``) used to be read once at stream construction
    and frozen; they are now only the INITIAL values of this mutable config
    (:meth:`from_env`).  The stream consults the config at every decision
    point — each tar member for the decode window, each ring put for the
    capacity — so mutating a field retunes the stream mid-run.  That is the
    closed-loop autotuner's mutation surface (core.optimize.IngestAutotuner),
    and a programmatic configuration API in its own right.

    The knobs control CONCURRENCY AND BUFFERING only — never ordering or
    content: decodes complete through an in-order FIFO window and chunks
    assemble identically at any width/depth, so retuning may change speed,
    never results (the ``autotune_thrash`` chaos family holds it to that).

    ``decode_threads`` is the number of decodes kept in flight (the
    effective pool width); ``max_decode_threads`` caps how far a tuner may
    raise it — the thread pool is created at the cap, width is governed by
    the in-flight window.
    """

    decode_threads: int
    decode_ahead: int
    ring_capacity: int
    max_decode_threads: int = 0  # 0 -> resolved to >= decode_threads in __post_init__
    autotune: bool = False  #: create an IngestAutotuner for this stream
    autotune_interval: int = 4  #: chunks between controller evaluations

    def __post_init__(self):
        if self.decode_threads < 1:
            raise ValueError(f"decode_threads must be >= 1, got {self.decode_threads}")
        if self.decode_ahead < 0:
            raise ValueError(f"decode_ahead must be >= 0, got {self.decode_ahead}")
        if self.ring_capacity < 1:
            raise ValueError(f"ring_capacity must be >= 1, got {self.ring_capacity}")
        if self.autotune_interval < 1:
            raise ValueError(
                f"autotune_interval must be >= 1, got {self.autotune_interval}"
            )
        if self.max_decode_threads == 0:
            self.max_decode_threads = max(self.decode_threads, _host_cores())
        elif self.max_decode_threads < self.decode_threads:
            # An EXPLICIT cap below the width is a contradiction, not a
            # sentinel — silently widening it would let the tuner exceed a
            # bound the caller set to protect host CPU.
            raise ValueError(
                f"max_decode_threads={self.max_decode_threads} is below "
                f"decode_threads={self.decode_threads}"
            )

    @classmethod
    def from_env(cls, **overrides) -> "StreamConfig":
        """Env-seeded defaults (``KEYSTONE_DECODE_THREADS`` /
        ``KEYSTONE_DECODE_AHEAD`` / ``KEYSTONE_RING_CAPACITY`` /
        ``KEYSTONE_AUTOTUNE`` / ``KEYSTONE_AUTOTUNE_INTERVAL``), any field
        overridable by keyword."""
        cfg = {
            "decode_threads": image_loaders.decode_threads(),
            "decode_ahead": image_loaders.decode_ahead(),
            "ring_capacity": ring_capacity(),
            "autotune": _env_flag("KEYSTONE_AUTOTUNE"),
            "autotune_interval": _env_int("KEYSTONE_AUTOTUNE_INTERVAL", 4, 1),
        }
        cfg.update({k: v for k, v in overrides.items() if v is not None})
        return cls(**cfg)

    def window(self) -> int:
        """In-flight decode window: effective pool width + decode-ahead."""
        return max(1, self.decode_threads) + max(0, self.decode_ahead)

    def record(self) -> dict:
        return dataclasses.asdict(self)


class _Cancelled(Exception):
    """Internal: the consumer stopped the stream — unwind the producer."""


@dataclasses.dataclass
class StreamBatch:
    """One shape-bucketed, batch-assembled chunk of decoded images."""

    index: int  #: chunk ordinal (FIFO yield order)
    indices: np.ndarray  #: [b] global image ordinals in decode-survival order
    names: list  #: [b] tar member names
    host: np.ndarray  #: [b, H, W, C] f32 host batch
    device: object | None = None  #: jax.Array once the transfer stage ran

    @property
    def shape(self) -> tuple:
        """The bucket key: per-image (H, W)."""
        return tuple(self.host.shape[1:3])

    def __len__(self) -> int:
        return len(self.names)

    def dev(self):
        """The device-resident batch (transferring on demand when the
        stream ran with ``transfer=False``)."""
        if self.device is None:
            self.device = jax.device_put(self.host)
        return self.device


@dataclasses.dataclass
class StreamStats:
    """Per-stream ingest counters (ring depth/stall accounting for the
    bench ``e2e`` section and the backpressure tests)."""

    decoded: int = 0  #: images decoded successfully
    skipped: int = 0  #: corrupt members skipped (also counted globally)
    batches: int = 0  #: chunks emitted into the ring
    ring_capacity: int = 0
    ring_max_depth: int = 0  #: high-water mark of assembled chunks queued
    producer_stalls: int = 0  #: puts that blocked on a full ring (backpressure)
    consumer_stalls: int = 0  #: gets that found the ring empty (decode-bound)

    def record(self) -> dict:
        return dataclasses.asdict(self)


class _Ring:
    """Bounded FIFO between the producer thread and the consumer.

    All waits poll at ``_POLL_SECONDS`` so the main thread stays
    interruptible (resilience.deadline's SIGALRM) and the producer always
    observes ``stop()``.  A producer error is stored and re-raised on the
    consumer side; ``close()`` marks end-of-stream."""

    _END = object()

    def __init__(self, config: StreamConfig, stats: StreamStats):
        self._q: collections.deque = collections.deque()
        self._cond = threading.Condition()
        # Capacity is read from the LIVE config on every put: a mid-stream
        # retune takes effect at the next enqueue (shrinking below the
        # current depth just blocks the producer until the consumer drains).
        self._config = config
        self._stats = stats
        self._closed = False
        self._stopped = False
        self._error: BaseException | None = None

    @property
    def stopped(self) -> bool:
        return self._stopped

    def depth(self) -> int:
        with self._cond:
            return len(self._q)

    def put(self, item) -> bool:
        """Producer side; blocks while full (backpressure).  Returns False
        when the consumer stopped the stream."""
        with self._cond:
            stalled = False
            while len(self._q) >= max(1, self._config.ring_capacity) and not self._stopped:
                if not stalled:
                    self._stats.producer_stalls += 1
                    stalled = True
                self._cond.wait(_POLL_SECONDS)
            if self._stopped:
                return False
            self._q.append(item)
            self._stats.ring_max_depth = max(
                self._stats.ring_max_depth, len(self._q)
            )
            self._cond.notify_all()
            return True

    def get(self):
        """Consumer side; blocks while empty.  Returns ``_Ring._END`` at
        end-of-stream, re-raises a producer failure."""
        with self._cond:
            stalled = False
            while True:
                if self._q:
                    item = self._q.popleft()
                    self._cond.notify_all()
                    return item
                if self._error is not None:
                    err, self._error = self._error, None
                    raise err
                if self._closed or self._stopped:
                    return self._END
                if not stalled:
                    self._stats.consumer_stalls += 1
                    stalled = True
                self._cond.wait(_POLL_SECONDS)

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def fail(self, error: BaseException) -> None:
        with self._cond:
            self._error = error
            self._closed = True
            self._cond.notify_all()

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()


class IngestStream:
    """The streaming pipeline: iterate to consume, ``with`` (or ``close``)
    to guarantee shutdown, ``join()`` to assert no thread leaked."""

    def __init__(
        self,
        path: str,
        batch_size: int,
        *,
        keep: Callable[[str], bool] | None = None,
        num_threads: int | None = None,
        decode_ahead_slots: int | None = None,
        capacity: int | None = None,
        transfer: bool = True,
        config: StreamConfig | None = None,
        tuner=None,
    ):
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        self._path = path
        self._batch_size = batch_size
        self._keep = keep
        # The stream's live knob set: an explicit StreamConfig, or an
        # env-seeded one; the legacy per-stream kwargs override its initial
        # values.  The config object is SHARED with the caller/tuner —
        # mutations retune the running stream.
        if config is None:
            config = StreamConfig.from_env(
                decode_threads=num_threads,
                decode_ahead=decode_ahead_slots,
                ring_capacity=capacity,
            )
        else:
            if num_threads is not None:
                config.decode_threads = num_threads
                config.max_decode_threads = max(
                    config.max_decode_threads, num_threads
                )
            if decode_ahead_slots is not None:
                config.decode_ahead = decode_ahead_slots
            if capacity is not None:
                config.ring_capacity = capacity
            if num_threads is not None or decode_ahead_slots is not None or capacity is not None:
                # Legacy overrides must pass the same validation the
                # constructor enforces (num_threads=0 etc. raise, never
                # silently configure a dead stream).
                config.__post_init__()
        self.config = config
        self._transfer = transfer
        self.stats = StreamStats(ring_capacity=config.ring_capacity)
        self._ring = _Ring(config, self.stats)
        self._workers: list[threading.Thread] = []
        self._chunk_counter = 0
        self.tuner = tuner
        if self.tuner is None and config.autotune:
            # Lazy import: optimize imports ingest at module level; the
            # reverse edge resolves only when a stream actually autotunes.
            from .optimize import IngestAutotuner

            self.tuner = IngestAutotuner()
        if self.tuner is not None:
            self.tuner.attach(self)
        # One line per stream so operators can see the effective ingest
        # configuration (the env knobs resolved) without env spelunking.
        _logger.info(
            "streaming ingest %s: batch=%d threads=%d ahead=%d ring=%d "
            "transfer=%s autotune=%s",
            path,
            batch_size,
            config.decode_threads,
            config.decode_ahead,
            config.ring_capacity,
            transfer,
            bool(self.tuner),
        )
        self._iter = self._drain()
        self._thread = threading.Thread(
            target=self._produce, name="keystone-ingest-producer", daemon=True
        )
        self._thread.start()

    # -- producer side --------------------------------------------------------

    def _register_worker(self):
        self._workers.append(threading.current_thread())

    def _await_decode(self, fut):
        """Poll a decode future so a stopped stream abandons a hung decoder
        instead of joining it forever."""
        while True:
            if self._ring.stopped:
                raise _Cancelled()
            try:
                return fut.result(timeout=_POLL_SECONDS)
            except _FutureTimeout:
                continue

    def _submit_decode(self, pool, name: str, data: bytes):
        """Submit one member's decode; when tracing is enabled each decode
        becomes an ``ingest.decode`` span on ITS worker thread's timeline —
        the parallel decode lanes are visible next to the consumer lane,
        so decode/featurize overlap is a picture, not an inference.  The
        module attribute is resolved at call time (the chaos harness
        patches ``image_loaders.decode_image``)."""
        if not trace.enabled():
            return pool.submit(image_loaders.decode_image, data)

        def traced(data=data, name=name):
            with trace.span("ingest.decode", cat="ingest", member=name):
                return image_loaders.decode_image(data)

        return pool.submit(traced)

    def _produce(self):
        # The pool is sized at the retune CEILING; the effective width is
        # the in-flight window (config.decode_threads), consulted per
        # member — so the tuner can widen/narrow decode mid-stream without
        # rebuilding the pool.
        pool = ThreadPoolExecutor(
            max_workers=self.config.max_decode_threads,
            thread_name_prefix="keystone-decode",
            initializer=self._register_worker,
        )
        clean = False
        try:
            # Build/load the native decoder before the pool spins up (the
            # one-time g++ build runs under native_decode's module lock and
            # would otherwise stall every worker behind the first decode).
            from ..loaders.native_decode import available as _native_available

            _native_available()
            # shape -> (ordinals, names, images); insertion-ordered so the
            # end-of-stream flush of partial buckets is deterministic.
            buckets: dict = {}
            window: collections.deque = collections.deque()
            ordinal = 0

            def drain_one():
                nonlocal ordinal
                name, fut = window.popleft()
                img = self._await_decode(fut)
                if img is None:
                    counters.record("corrupt_image", name)
                    self.stats.skipped += 1
                    return
                self.stats.decoded += 1
                key = img.shape[:2]
                idx, names, imgs = buckets.setdefault(key, ([], [], []))
                idx.append(ordinal)
                names.append(name)
                imgs.append(img)
                ordinal += 1
                if len(imgs) >= self._batch_size:
                    self._emit(buckets.pop(key))

            with trace.span(
                "ingest.produce", cat="ingest", path=self._path
            ) as prod_sp:
                try:
                    for name, data in image_loaders._iter_tar_members(
                        self._path
                    ):
                        if self._ring.stopped:
                            raise _Cancelled()
                        if self._keep is not None and not self._keep(name):
                            continue
                        window.append(
                            (name, self._submit_decode(pool, name, data))
                        )
                        # Live window limit: a retune takes effect at the
                        # next member ("while" drains DOWN to a narrowed
                        # window; completion order through the FIFO window
                        # is unchanged by any width).
                        while len(window) >= self.config.window():
                            drain_one()
                    while window:
                        drain_one()
                    # Flush the batch-size remainders (partial last batch
                    # per shape), oldest bucket first for a deterministic
                    # tail order.
                    for bucket in sorted(
                        buckets.values(), key=lambda b: b[0][0]
                    ):
                        self._emit(bucket)
                    clean = True
                except _Cancelled:
                    # Consumer stopped the stream early — routine shutdown
                    # (a supported path), not a producer failure: the span
                    # marks it aborted rather than errored.
                    prod_sp.set(aborted=True)
                prod_sp.set(
                    decoded=self.stats.decoded,
                    skipped=self.stats.skipped,
                    batches=self.stats.batches,
                )
        except BaseException as e:  # noqa: BLE001 — surfaces on the consumer
            self._ring.fail(e)
        finally:
            self._ring.close()
            # A stopped stream may hold a hung decode future: abandon it
            # (workers are daemon threads) instead of blocking shutdown.
            pool.shutdown(wait=clean, cancel_futures=not clean)

    def _emit(self, bucket):
        idx, names, imgs = bucket
        chunk = StreamBatch(
            index=self._chunk_counter,
            indices=np.asarray(idx, np.int64),
            names=names,
            host=np.stack(imgs),
        )
        self._chunk_counter += 1
        # The put span's duration IS the backpressure stall: a full ring
        # blocks here, and the trace shows the producer lane waiting.
        with trace.span(
            "ingest.ring_put", cat="ingest",
            index=chunk.index, images=len(chunk),
        ):
            ok = self._ring.put(chunk)
        if not ok:
            raise _Cancelled()
        self.stats.batches += 1

    # -- consumer side --------------------------------------------------------

    def _yield_consumed(self, item):
        """Yield one chunk under an ``ingest.consume`` span: the span runs
        from the moment the consumer receives the chunk until it asks for
        the next one — i.e. the consumer's featurize time for THAT chunk,
        on the consumer thread's lane.  Decode spans on the worker lanes
        running inside a consume span's interval ARE the overlap."""
        with trace.span(
            "ingest.consume", cat="ingest",
            index=item.index, images=len(item),
        ):
            yield item

    def _publish_metrics(self) -> None:
        """Chunk-boundary gauges: the live trace-metrics the autotuner (and
        any operator dashboard) reads — ring depth plus the current knob
        values, alongside the stats counters."""
        m = trace.metrics
        # A retune may have moved the capacity: keep the stats record (the
        # bench/chaos artifact) consistent with the ring's live bound.
        self.stats.ring_capacity = self.config.ring_capacity
        m.gauge("ingest_ring_depth", self._ring.depth())
        m.gauge("ingest_decode_threads", self.config.decode_threads)
        m.gauge("ingest_decode_ahead", self.config.decode_ahead)
        m.gauge("ingest_ring_capacity", self.config.ring_capacity)
        m.gauge("ingest_producer_stalls", self.stats.producer_stalls)
        m.gauge("ingest_consumer_stalls", self.stats.consumer_stalls)

    def _drain(self):
        pending: collections.deque = collections.deque()
        try:
            while True:
                with trace.span("ingest.ring_get", cat="ingest"):
                    item = self._ring.get()
                if item is _Ring._END:
                    break
                if self._transfer:
                    # Async dispatch: the H2D for this chunk starts now and
                    # overlaps the consumer's work on the PREVIOUS chunk
                    # still being featurized.
                    item.device = jax.device_put(item.host)
                self._publish_metrics()
                if self.tuner is not None:
                    # Chunk boundary: the closed-loop controller reads the
                    # stall counters/gauges and may retune the config.
                    self.tuner.on_chunk(self)
                pending.append(item)
                if len(pending) >= DEVICE_BUFFERS:
                    yield from self._yield_consumed(pending.popleft())
            while pending:
                yield from self._yield_consumed(pending.popleft())
        finally:
            self.close()

    def __iter__(self):
        return self

    def __next__(self) -> StreamBatch:
        return next(self._iter)

    def __enter__(self) -> "IngestStream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        """Stop the producer and release the ring.  Idempotent; called
        automatically on stream exhaustion, consumer exception, or context
        exit."""
        self._ring.stop()

    def join(self, timeout: float = 10.0) -> bool:
        """Wait for the producer and every decoder thread to exit; returns
        True when no ingest thread remains alive (the no-leak assertion the
        tier-1 suite runs under pytest)."""
        end = time.monotonic() + timeout
        self._thread.join(max(0.0, end - time.monotonic()))
        for t in list(self._workers):
            t.join(max(0.0, end - time.monotonic()))
        return not (
            self._thread.is_alive()
            or any(t.is_alive() for t in self._workers)
        )


def stream_batches(
    path: str,
    batch_size: int,
    *,
    keep: Callable[[str], bool] | None = None,
    num_threads: int | None = None,
    decode_ahead_slots: int | None = None,
    capacity: int | None = None,
    transfer: bool = True,
    config: StreamConfig | None = None,
    tuner=None,
) -> IngestStream:
    """Stream shape-bucketed device batches from a tar (or directory of
    tars) of images.

    ``keep``: member-name predicate (label filtering before decode).
    ``config``: a :class:`StreamConfig` — the stream's LIVE knob set
    (env-seeded via :meth:`StreamConfig.from_env` when omitted); mutate it
    mid-stream to retune, or set ``config.autotune`` (env
    ``KEYSTONE_AUTOTUNE=1``) for the closed-loop controller.
    ``num_threads`` / ``decode_ahead_slots`` / ``capacity``: legacy
    per-stream overrides of the config's initial values.
    ``transfer=False`` skips the H2D stage (host-only consumers, decode
    benchmarking).  ``tuner``: an explicit controller (anything with
    ``attach(stream)`` / ``on_chunk(stream)``) instead of the default.

    Yields :class:`StreamBatch` in assembly order; use as a context
    manager (or iterate to exhaustion) so the decode threads are released,
    and ``stream.join()`` to assert they exited."""
    return IngestStream(
        path,
        batch_size,
        keep=keep,
        num_threads=num_threads,
        decode_ahead_slots=decode_ahead_slots,
        capacity=capacity,
        transfer=transfer,
        config=config,
        tuner=tuner,
    )
