"""Numerics & model-quality observatory: on-device tensor-stat probes,
conditioning monitors, NaN provenance, and serving output-drift detection.

The repo's standing invariant — "predictions equal fault-free OR
typed+counted error, never a silent wrong model" — is enforced
structurally (bit-parity checks, finite-guards at fit exit), but nothing
watches the *numeric content* flowing through a pipeline or out of a
serving engine: a conditioning collapse, a quietly saturating feature, or
a drifting request distribution is invisible until a hard fault.  The
profiler (core.profiler) made the device's COST observable; this module
makes its VALUES observable.  Four coordinated pieces:

* **Tensor-stat probes** — :func:`probe` computes a small per-tensor
  reduction (count / mean / std / min / max / abs-max / zero-frac /
  nonfinite-count) on every ``KEYSTONE_NUMERICS_SAMPLE``-th visit to a
  probe site.  Device arrays reduce through ONE jitted on-device program
  (eight scalars cross to host, never the tensor); host arrays reduce in
  numpy.  Sites are attached at every pipeline node boundary
  (``Pipeline.__call__`` / ``Pipeline.profile``), at the streamed
  featurize output (``StreamBatch.apply``), and at every serving bucket's
  output (``ServingEngine``).  Stats export as ``numerics_*`` gauges/
  histograms in ``trace.metrics`` (Prometheus free-rides) and as
  ``numerics.node`` trace instants.  Probes are BIT-INERT: the probed
  value is returned unchanged (the reducer reads, never donates), so
  enabling the observatory can never change a model or an answer — the
  tier-1 suite asserts bit-identity on every probed path.
* **Conditioning monitor** — :func:`estimate_gram_condition` runs a
  few-step power iteration on a gram block (riding the blocks the solvers
  already form; design-matrix blocks are row-subsampled to a bounded
  probe) for a cheap κ estimate, recorded per solve in
  ``FitReport.conditioning`` and emitted as a PREDICTIVE ``cond_warn``
  counted fault when κ exceeds ``KEYSTONE_COND_WARN`` — before the
  Cholesky jitter-retry ladder in ``solvers.normal_equations`` trips.
  This is the ACCURACY.md §6 offline κ-sweep turned into a live monitor.
* **NaN provenance** — when a probe's nonfinite-count trips on a streamed
  or served batch, :func:`nonfinite_rows` host-bisects to the offending
  rows and the provenance (tar member names for ingest, request ids for
  serving) is counted (``numerics_nonfinite``, a postmortem family),
  stored for :func:`provenance_note`, and appended to the typed error
  ``resilience.assert_all_finite`` raises — "batch had a NaN" becomes
  "member n042.jpg produced it".
* **Serving output-drift detection** — each :class:`DriftMonitor` keeps a
  streaming :class:`OutputSketch` of an engine's answer distribution
  (class histogram for classifier heads, decile sketch otherwise) against
  a fit-time reference baseline persisted in the checkpoint manifest
  (``core.checkpoint.save_pipeline(numerics_baseline=)``).  Divergence
  beyond ``KEYSTONE_DRIFT_TOL`` is counted ``serve_output_drift`` (a
  postmortem family, so the flight-recorder dump and a triggered xprof
  window fire) and surfaces per-engine in ``ShapeRouter`` stats and
  ``serve_bench`` records.  Detection only — answers are never altered.

Overhead discipline: :func:`active` is one env-flag check (the
``KEYSTONE_NUMERICS=1`` opt-in or the programmatic :func:`monitored`
override); with the observatory OFF every hook on the pipeline/ingest/
serve paths is exactly that check and NO per-site state is retained (the
tier-1 suite pins zero retained allocation in disabled mode).  ON, a
sampled probe costs one small reduction + one 8-scalar host transfer;
``KEYSTONE_NUMERICS_SAMPLE`` thins the cadence and the bench bounds the
probed-serve p99 overhead at <= 5%.

This module is deliberately jax-free at import (it sits on the spawned
decode workers' import path via core.ingest — see
tests/test_lazy_import.py); the one jax consumer builds its jitted
reducer lazily.
"""

from __future__ import annotations

import contextlib
import logging
import os
import re
import threading
import time
from collections import deque

import numpy as np

from . import trace
from .resilience import counters

_logger = logging.getLogger("keystone_tpu.numerics")

#: env var: ``1`` turns the numerics observatory on (probes, conditioning
#: monitor, drift detection).
NUMERICS_ENV = "KEYSTONE_NUMERICS"
#: env var: probe every Nth visit to each probe site (default 1 = every).
SAMPLE_ENV = "KEYSTONE_NUMERICS_SAMPLE"
#: env var: output-distribution divergence tolerance before a counted
#: ``serve_output_drift`` fires (total-variation distance for class
#: histograms, IQR-normalized max decile shift otherwise).
DRIFT_TOL_ENV = "KEYSTONE_DRIFT_TOL"
#: env var: κ estimate above this emits the predictive ``cond_warn``.
COND_WARN_ENV = "KEYSTONE_COND_WARN"

DEFAULT_SAMPLE = 1
DEFAULT_DRIFT_TOL = 0.25
#: ACCURACY.md §6: the f32 direct solve degrades smoothly to κ~1e7 and
#: breaks down (jitter escalations begin) near κ~1/eps_f32.  The few-step
#: Ritz estimate LOWER-bounds true κ by roughly one order of magnitude at
#: :data:`COND_ITERS` steps, so the default threshold sits one decade
#: under the true-κ comfort bound: an estimate past 1e5 means the true
#: gram is at ~1e6+, two decades before the jitter ladder trips —
#: predictive, with normalized-feature pipelines (true κ well under 1e5)
#: never paging.
DEFAULT_COND_WARN = 1e5

#: Answers observed before a drift verdict can fire — a divergent first
#: handful of requests is noise, not a page.
DRIFT_MIN_COUNT = 32
#: Bounded value reservoir backing the quantile sketch.
QUANTILE_RESERVOIR = 4096
#: Class-histogram cardinality cap: wider heads fall back to quantiles.
MAX_CLASSES = 1024
#: Offending rows reported per provenance record (the FIRST rows carry
#: the information; a fully-poisoned batch must not flood the ledger).
MAX_PROVENANCE_ROWS = 32
#: Krylov (Lanczos) steps per κ estimate — each is one gram matvec.
COND_ITERS = 32
#: Row cap for design-block conditioning probes (a κ estimate must never
#: re-upload an 8 GB host-staged design matrix).
COND_ROWS_CAP = 4096
#: Block cap per solve for design conditioning (first blocks suffice as
#: a conditioning fingerprint of the featurization).
COND_BLOCKS_CAP = 8

_NAME_RE = re.compile(r"[^a-zA-Z0-9_.:-]")

_override: bool | None = None


def _env_flag(name: str) -> bool:
    return os.environ.get(name, "").strip().lower() in ("1", "true", "on", "yes")


def active() -> bool:
    """Is the numerics observatory on?  ``KEYSTONE_NUMERICS=1`` or the
    programmatic :func:`monitored` override.  THE hot-path check — every
    probe hook on the pipeline/ingest/serve paths is gated on it."""
    if _override is not None:
        return _override
    return _env_flag(NUMERICS_ENV)


def _env_pos_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        val = int(raw)
    except ValueError:
        _logger.error("%s=%r is not an integer — using %d", name, raw, default)
        return default
    return max(1, val)


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        _logger.error("%s=%r is not a number — using %g", name, raw, default)
        return default


def sample_every() -> int:
    return _env_pos_int(SAMPLE_ENV, DEFAULT_SAMPLE)


def drift_tol() -> float:
    return max(1e-6, _env_float(DRIFT_TOL_ENV, DEFAULT_DRIFT_TOL))


def cond_warn_threshold() -> float:
    return max(1.0, _env_float(COND_WARN_ENV, DEFAULT_COND_WARN))


# -- the tensor-stat reducer ---------------------------------------------------

_STAT_FIELDS = (
    "count", "nonfinite", "mean", "std", "min", "max", "abs_max", "zero_frac",
)

_stats_fn = None  # lazily-built jitted reducer (one per process)


def _build_stats_fn():
    import jax
    import jax.numpy as jnp

    def reduce(v):
        f = jnp.ravel(v).astype(jnp.float32)
        finite = jnp.isfinite(f)
        nfin = jnp.sum(finite)
        denom = jnp.maximum(nfin, 1).astype(jnp.float32)
        xf = jnp.where(finite, f, 0.0)
        mean = jnp.sum(xf) / denom
        var = jnp.maximum(jnp.sum(xf * xf) / denom - mean * mean, 0.0)
        return jnp.stack(
            [
                jnp.asarray(f.size, jnp.float32),
                jnp.asarray(f.size, jnp.float32) - nfin.astype(jnp.float32),
                mean,
                jnp.sqrt(var),
                jnp.min(jnp.where(finite, f, jnp.inf)),
                jnp.max(jnp.where(finite, f, -jnp.inf)),
                jnp.max(jnp.where(finite, jnp.abs(f), 0.0)),
                jnp.sum(jnp.where(finite, (f == 0.0).astype(jnp.float32), 0.0))
                / denom,
            ]
        )

    return jax.jit(reduce)


def _np_stats_vector(arr: np.ndarray) -> np.ndarray:
    f = np.asarray(arr, np.float32).ravel()
    finite = np.isfinite(f)
    nfin = int(finite.sum())
    denom = max(nfin, 1)
    xf = np.where(finite, f, 0.0)
    mean = float(xf.sum()) / denom
    var = max(float((xf * xf).sum()) / denom - mean * mean, 0.0)
    return np.array(
        [
            f.size,
            f.size - nfin,
            mean,
            var ** 0.5,
            float(f[finite].min()) if nfin else np.inf,
            float(f[finite].max()) if nfin else -np.inf,
            float(np.abs(f[finite]).max()) if nfin else 0.0,
            (float((f[finite] == 0.0).sum()) / denom) if nfin else 0.0,
        ],
        np.float64,
    )


def tensor_stats(x) -> dict:
    """The probe reduction of one tensor: ``count`` / ``nonfinite`` /
    ``mean`` / ``std`` / ``min`` / ``max`` / ``abs_max`` / ``zero_frac``
    (moments over the FINITE values, so a NaN-poisoned batch still reports
    a meaningful center).  Device arrays reduce on-device through one
    jitted program — only eight scalars cross to host; host arrays reduce
    in numpy.  Integer and extended-float dtypes reduce in f32."""
    global _stats_fn
    if isinstance(x, (np.ndarray, np.generic)):
        vec = _np_stats_vector(np.asarray(x))
    else:
        if _stats_fn is None:
            _stats_fn = _build_stats_fn()
        vec = np.asarray(_stats_fn(x), np.float64)
    out = dict(zip(_STAT_FIELDS, (float(v) for v in vec)))
    out["count"] = int(out["count"])
    out["nonfinite"] = int(round(out["nonfinite"]))
    if out["count"] == out["nonfinite"]:
        # No finite value at all: the masked extremes are sentinel ±inf —
        # report zeros rather than leaking the sentinels into gauges/JSON.
        out["min"] = out["max"] = out["abs_max"] = 0.0
    return out


def _is_array_like(x) -> bool:
    return hasattr(x, "shape") and hasattr(x, "dtype")


# -- NaN provenance ------------------------------------------------------------


def nonfinite_rows(x, limit: int = MAX_PROVENANCE_ROWS) -> list[int]:
    """Host-side bisect to the rows of ``x`` holding non-finite values:
    the row range halves recursively and only halves that report
    non-finite are descended, so a batch with one poisoned member touches
    ``O(log n)`` interval reductions.  Returns at most ``limit`` row
    indices, ascending."""
    arr = np.asarray(x)
    if arr.ndim == 0:
        return [0] if not np.isfinite(arr) else []
    flat = arr.reshape(arr.shape[0], -1)
    out: list[int] = []
    stack = [(0, flat.shape[0])]
    while stack and len(out) < limit:
        lo, hi = stack.pop()
        if np.isfinite(flat[lo:hi]).all():
            continue
        if hi - lo == 1:
            out.append(lo)
            continue
        mid = (lo + hi) // 2
        # Right half pushed first so the pop order walks rows ascending.
        stack.append((mid, hi))
        stack.append((lo, mid))
    return sorted(out)


_prov_lock = threading.Lock()
_provenance: deque = deque(maxlen=8)


def record_provenance(
    site: str, rows: list[int], labels: list | None = None, kind: str = "batch"
) -> dict:
    """Store (and count) one non-finite provenance record: WHICH rows of
    WHICH site went non-finite, named by tar member (``kind="member"``) or
    request id (``kind="request"``) when the caller knows them.  The
    count (``numerics_nonfinite``) is a postmortem family, so the dump
    carries the names; :func:`provenance_note` feeds them into the typed
    error ``assert_all_finite`` raises."""
    named = [str(v) for v in labels] if labels else [str(r) for r in rows]
    rec = {
        "site": site,
        "kind": kind,
        "rows": list(rows),
        "names": named,
        "time_unix": time.time(),
    }
    with _prov_lock:
        _provenance.append(rec)
    counters.record(
        "numerics_nonfinite",
        f"{site}: {len(rows)} non-finite row(s) — {kind}(s) "
        f"{', '.join(named[:8])}{'...' if len(named) > 8 else ''}",
    )
    return rec


def provenance_records() -> list[dict]:
    with _prov_lock:
        return [dict(r) for r in _provenance]


def provenance_note(max_age_s: float = 60.0) -> str | None:
    """One-line summary of the most recent non-finite provenance (None
    when nothing tripped within ``max_age_s``) — appended to
    ``assert_all_finite``'s typed error so the failure names the
    member/request that produced the NaN instead of just the batch that
    carried it.  Worded as a CORRELATION, and age-bounded, because the
    record is process-global: a trip on another stream/engine minutes ago
    must not masquerade as this failure's cause."""
    now = time.time()
    with _prov_lock:
        if not _provenance:
            return None
        rec = _provenance[-1]
        if now - rec["time_unix"] > max_age_s:
            return None
    names = ", ".join(rec["names"][:8])
    more = "..." if len(rec["names"]) > 8 else ""
    return (
        f"most recent non-finite probe trip ({now - rec['time_unix']:.1f}s "
        f"ago) traced to {rec['kind']}(s) {names}{more} at probe site "
        f"{rec['site']!r}"
    )


# -- probe sites ---------------------------------------------------------------


class _SiteState:
    __slots__ = ("visits", "sampled", "nonfinite_total", "last")

    def __init__(self):
        self.visits = 0
        self.sampled = 0
        self.nonfinite_total = 0
        self.last: dict | None = None


_site_lock = threading.Lock()
_sites: dict[str, _SiteState] = {}
_SITES_MAX = 512


def probe(site: str, value, *, names=None, request_ids=None):
    """Record tensor stats for ``value`` at probe site ``site`` (every
    ``KEYSTONE_NUMERICS_SAMPLE``-th visit) and return ``value`` UNCHANGED
    — the probe reads, never mutates, donates, or raises, so a probed
    path is bit-identical to an unmonitored one by construction.

    ``names`` (tar member names) / ``request_ids`` give non-finite trips
    their provenance.  Callers gate on :func:`active` (cheap to call
    unconditionally too — the off path is one flag check and retains no
    state)."""
    if not active() or not _is_array_like(value):
        return value
    try:
        with _site_lock:
            state = _sites.get(site)
            if state is None:
                if len(_sites) >= _SITES_MAX:
                    _sites.pop(next(iter(_sites)))
                state = _sites[site] = _SiteState()
            state.visits += 1
            if (state.visits - 1) % sample_every() != 0:
                return value
            state.sampled += 1
        stats = tensor_stats(value)
        with _site_lock:
            state.last = stats
            if stats["nonfinite"]:
                state.nonfinite_total += stats["nonfinite"]
        metric = _NAME_RE.sub("_", site)
        for field in ("mean", "std", "min", "max", "abs_max", "zero_frac"):
            trace.metrics.gauge(f"numerics_{metric}_{field}", stats[field])
        trace.metrics.gauge(f"numerics_{metric}_nonfinite", stats["nonfinite"])
        trace.metrics.observe(f"numerics_{metric}_abs_max", stats["abs_max"])
        trace.instant("numerics.node", site=site, **stats)
        if stats["nonfinite"]:
            rows = nonfinite_rows(value)
            labels = kind = None
            if request_ids is not None:
                labels = [request_ids[r] for r in rows if r < len(request_ids)]
                kind = "request"
            elif names is not None:
                labels = [names[r] for r in rows if r < len(names)]
                kind = "member"
            record_provenance(site, rows, labels, kind or "row")
    except Exception:  # noqa: BLE001 — observability must never break the path
        _logger.exception("numerics probe at %r failed", site)
    return value


def site_stats() -> dict:
    """site -> {visits, sampled, nonfinite_total, last stats}."""
    with _site_lock:
        return {
            site: {
                "visits": s.visits,
                "sampled": s.sampled,
                "nonfinite_total": s.nonfinite_total,
                **({"last": dict(s.last)} if s.last else {}),
            }
            for site, s in _sites.items()
        }


# -- conditioning monitor ------------------------------------------------------

_cond_tls = threading.local()
_cond_lock = threading.Lock()
_cond_recent: deque = deque(maxlen=64)


@contextlib.contextmanager
def collect_conditioning():
    """Collect every κ estimate recorded inside the block —
    ``BlockLeastSquaresEstimator.fit`` wraps its solve with this so the
    per-solve ``solve_gram_l2`` estimates join the design-block probes in
    ``FitReport.conditioning`` (the fused BWLS path factors inside its
    jitted programs and contributes design-block probes only).
    Per-thread; nesting keeps the inner collector until it exits."""
    rows: list = []
    prev = getattr(_cond_tls, "rows", None)
    _cond_tls.rows = rows
    try:
        yield rows
    finally:
        _cond_tls.rows = prev


def _note_condition(row: dict) -> None:
    rows = getattr(_cond_tls, "rows", None)
    if rows is not None:
        rows.append(row)
    with _cond_lock:
        _cond_recent.append(row)
    metric = _NAME_RE.sub("_", row["label"])
    if row.get("kappa") is not None:
        trace.metrics.gauge(f"numerics_{metric}_kappa", row["kappa"])
    trace.instant("numerics.conditioning", **row)
    if row["warned"]:
        counters.record(
            "cond_warn",
            f"{row['label']}: estimated kappa {row['kappa']:.3g} exceeds "
            f"{cond_warn_threshold():.3g} — the f32 Cholesky is heading "
            "into its ACCURACY.md §6 breakdown range (escalation likely)",
        )


def estimate_gram_condition(
    gram, lam: float = 0.0, label: str = "gram", iters: int = COND_ITERS
) -> dict:
    """Cheap κ estimate of a (PSD) gram block via a few-step Lanczos
    (Krylov power iteration): ``iters`` gram matvecs build an
    orthogonalized Krylov basis whose tridiagonal Ritz values bracket-in
    on BOTH spectrum ends, riding the gram the solver already formed.
    The reported κ is of the REGULARIZED system ``G + λI`` (what the
    Cholesky actually factors), so the predictive ``cond_warn`` fires for
    the solve that will actually struggle.  Ritz values lie inside
    ``[λ_min, λ_max]``, so the estimate LOWER-bounds the true κ — a
    warning is never a false alarm; the few-step form is a monitor, not
    an eigensolver.

    NEVER raises: a non-finite gram (the very fault the solver's finite
    guard exists to convert into a typed error) or any estimator failure
    returns a ``kappa=None`` row — the monitor steps aside so the typed
    recovery path downstream stays intact."""
    try:
        return _estimate_gram_condition(gram, lam, label, iters)
    except Exception:  # noqa: BLE001 — observability must never break the path
        _logger.exception("conditioning estimate for %r failed", label)
        return {
            "label": label,
            "kappa": None,
            "lam_max": None,
            "lam_min": None,
            "warned": False,
            "error": "estimate failed",
        }


def _estimate_gram_condition(gram, lam: float, label: str, iters: int) -> dict:
    import jax.numpy as jnp

    g = jnp.asarray(gram)
    d = int(g.shape[0])
    k = max(2, min(int(iters), d))
    rng = np.random.default_rng(20260804)
    v = jnp.asarray(rng.standard_normal(d).astype(np.float32))
    v = v / jnp.linalg.norm(v)
    basis = [v]
    alphas: list[float] = []
    betas: list[float] = []
    for j in range(k):
        w = g @ basis[j]
        alphas.append(float(basis[j] @ w))
        # Full reorthogonalization, TWICE (Parlett's "twice is enough"):
        # k is small, and f32 Lanczos without it manufactures spurious
        # Ritz copies that would poison λ_min.
        for _ in range(2):
            for b in basis:
                w = w - (b @ w) * b
        beta = float(jnp.linalg.norm(w))
        # Happy breakdown, judged RELATIVE to the spectrum scale seen so
        # far: once the Krylov space is exhausted the residual is pure
        # f32 noise, and normalizing it would inject junk directions
        # whose off-diagonals smear the Ritz extremes (measured: κ(I)
        # read 2.2 instead of 1.0 without this stop).
        scale = max(abs(a) for a in alphas) or 1.0
        if beta <= 1e-6 * scale or j == k - 1:
            break
        betas.append(beta)
        basis.append(w / beta)
    tri = np.diag(np.asarray(alphas))
    if betas:
        off = np.asarray(betas)
        tri += np.diag(off, 1) + np.diag(off, -1)
    if not np.isfinite(tri).all():
        # A NaN/Inf gram: κ is meaningless and eigvalsh would raise —
        # report the non-finiteness instead (the solver's own finite
        # guard raises the TYPED error right after this hook returns).
        row = {
            "label": label,
            "dim": d,
            "lam": max(float(lam), 0.0),
            "lam_max": None,
            "lam_min": None,
            "kappa": None,
            "iters": len(alphas),
            "warned": False,
            "nonfinite_gram": True,
        }
        _note_condition(row)
        return row
    ritz = np.linalg.eigvalsh(tri)
    lam_max = float(ritz[-1])
    lam_min = max(float(ritz[0]), 0.0)
    lam = max(float(lam), 0.0)
    # Relative floor on the denominator: an exactly-singular gram reads
    # κ ≈ 1e12 (far past every threshold, and past anything f32 can
    # resolve) instead of inf — every artifact embedding this row stays
    # strict JSON.
    denom = max(lam_min + lam, (lam_max + lam) * 1e-12, 1e-30)
    kappa = (lam_max + lam) / denom
    row = {
        "label": label,
        "dim": d,
        "lam": lam,
        "lam_max": lam_max,
        "lam_min": lam_min,
        "kappa": kappa,
        "iters": len(alphas),
        "warned": bool(kappa > cond_warn_threshold()),
    }
    _note_condition(row)
    return row


def design_conditioning(
    x,
    widths,
    lam: float,
    label: str = "solve",
    rows_cap: int = COND_ROWS_CAP,
    blocks_cap: int = COND_BLOCKS_CAP,
) -> list[dict]:
    """Per-block κ estimates for a blocked design matrix (the solvers'
    ``_blocked_design_matrix`` layout: block i occupies columns
    ``[i·bs, (i+1)·bs)``).  Each probed block's gram forms from a bounded
    row sample (``rows_cap``), so the probe's cost — and, for host-staged
    matrices, its H2D — stays fixed no matter how big the fit is.  Gated
    by the caller on :func:`active`."""
    import jax.numpy as jnp

    bs = max(widths)
    rows = min(int(np.shape(x)[0]), rows_cap)
    out = []
    for i, w in enumerate(widths[:blocks_cap]):
        blk = jnp.asarray(
            np.asarray(x[:rows, i * bs : i * bs + w])
            if isinstance(x, np.ndarray)
            else x[:rows, i * bs : i * bs + w]
        ).astype(jnp.float32)
        gram = blk.T @ blk
        row = estimate_gram_condition(gram, lam, label=f"{label}:block{i}")
        row["block"] = i
        row["rows_sampled"] = rows
        out.append(row)
    if len(widths) > blocks_cap:
        _logger.info(
            "%s: conditioning probed on the first %d of %d blocks",
            label, blocks_cap, len(widths),
        )
    return out


def recent_conditioning() -> list[dict]:
    with _cond_lock:
        return [dict(r) for r in _cond_recent]


# -- serving output-drift detection --------------------------------------------


class OutputSketch:
    """Streaming sketch of an output distribution.

    ``class_histogram`` for classifier heads (integer answers under
    :data:`MAX_CLASSES` distinct values): per-class counts, divergence is
    total-variation distance.  ``quantile`` otherwise: a bounded strided
    reservoir of values, divergence is the max decile shift normalized by
    the BASELINE's inter-decile range — scale-aware, so a regression head
    whose answers drift by a fraction of their spread fires at the same
    tolerance a classifier does."""

    DECILES = tuple(q / 10.0 for q in range(1, 10))

    #: values appended per observe() call (strided) — bounds the per-call
    #: cost no matter how wide the output batch is.
    OBSERVE_CAP = 1024

    def __init__(self, kind: str):
        self.kind = kind
        self.observed = 0
        # BOTH kinds sketch a SLIDING window of the most recent
        # :data:`QUANTILE_RESERVOIR` values, not a from-the-beginning
        # accumulation: a distribution that shifts only after a long
        # healthy serving prefix must still move the sketch (an
        # accumulate-forever histogram dilutes the shift by
        # O(healthy-prefix) and a fill-once reservoir freezes on it).
        self.counts: dict[int, int] = {}
        self._window: deque = deque()  # class values backing `counts`
        self.reservoir: deque = deque(maxlen=QUANTILE_RESERVOIR)

    # -- construction ---------------------------------------------------------

    @classmethod
    def for_outputs(cls, arr) -> "OutputSketch":
        """Fresh sketch whose kind fits ``arr``'s answers: NON-NEGATIVE
        integer dtype with values under :data:`MAX_CLASSES` -> class
        histogram (classifier heads), anything else -> quantiles.  The
        value bound is the memory bound too — a wide-range/negative
        integer head (quantized regression, hashes) must fall to the
        quantile sketch, never grow an unbounded per-value counts dict."""
        a = np.asarray(arr)
        kind = "quantile"
        if a.dtype.kind in "iub" and (
            a.size == 0
            or (
                int(a.min(initial=0)) >= 0
                and int(a.max(initial=0)) < MAX_CLASSES
            )
        ):
            kind = "class_histogram"
        sk = cls(kind)
        sk.observe(a)
        return sk

    def observe(self, arr) -> None:
        a = np.asarray(arr)
        if a.size == 0:
            return
        self.observed += int(a.shape[0]) if a.ndim else 1
        if self.kind == "class_histogram":
            for v in a.astype(np.int64).ravel().tolist():
                self._window.append(v)
                self.counts[v] = self.counts.get(v, 0) + 1
                if len(self._window) > QUANTILE_RESERVOIR:
                    old = self._window.popleft()
                    left = self.counts.get(old, 1) - 1
                    if left:
                        self.counts[old] = left
                    else:
                        self.counts.pop(old, None)
        else:
            flat = np.asarray(a, np.float64).ravel()
            flat = flat[np.isfinite(flat)]
            if flat.size:
                stride = max(1, flat.size // self.OBSERVE_CAP)
                self.reservoir.extend(
                    flat[::stride][: self.OBSERVE_CAP].tolist()
                )

    # -- summaries ------------------------------------------------------------

    def quantiles(self) -> dict[str, float]:
        if not self.reservoir:
            return {}
        qs = np.quantile(np.asarray(self.reservoir), self.DECILES)
        return {f"q{int(q * 100)}": float(v) for q, v in zip(self.DECILES, qs)}

    def record(self) -> dict:
        out: dict = {"kind": self.kind, "observed": self.observed}
        if self.kind == "class_histogram":
            out["counts"] = {str(k): v for k, v in sorted(self.counts.items())}
        else:
            out["quantiles"] = self.quantiles()
        return out

    @classmethod
    def from_record(cls, rec: dict) -> "OutputSketch":
        sk = cls(rec.get("kind", "quantile"))
        sk.observed = int(rec.get("observed", 0))
        if sk.kind == "class_histogram":
            sk.counts = {int(k): int(v) for k, v in rec.get("counts", {}).items()}
        else:
            # A baseline restored from a manifest carries quantiles, not
            # raw values; divergence() reads them via _baseline_quantiles.
            sk._frozen_quantiles = dict(rec.get("quantiles", {}))
        return sk

    def _quantile_view(self) -> dict[str, float]:
        frozen = getattr(self, "_frozen_quantiles", None)
        return frozen if frozen else self.quantiles()

    def divergence(self, live: "OutputSketch") -> float | None:
        """How far ``live``'s distribution sits from THIS (baseline)
        sketch: TV distance in [0, 1] for class histograms, baseline-IQR-
        normalized max decile shift for quantiles.  None when either side
        has nothing to compare."""
        if self.kind != live.kind:
            return 1.0  # the head changed families — maximally divergent
        if self.kind == "class_histogram":
            tot_b = sum(self.counts.values())
            tot_l = sum(live.counts.values())
            if not tot_b or not tot_l:
                return None
            keys = set(self.counts) | set(live.counts)
            return 0.5 * sum(
                abs(
                    self.counts.get(k, 0) / tot_b
                    - live.counts.get(k, 0) / tot_l
                )
                for k in keys
            )
        qb, ql = self._quantile_view(), live._quantile_view()
        shared = sorted(set(qb) & set(ql))
        if not shared:
            return None
        scale = max(abs(qb.get("q90", 0.0) - qb.get("q10", 0.0)), 1e-9)
        return max(abs(qb[k] - ql[k]) for k in shared) / scale


class DriftMonitor:
    """Per-engine output-drift watcher: a fit-time baseline sketch vs a
    live sketch of served answers, judged at ``KEYSTONE_DRIFT_TOL`` once
    :data:`DRIFT_MIN_COUNT` answers are in.  A breach is counted ONCE
    (``serve_output_drift`` — a postmortem family, so the flight-recorder
    dump and a bounded xprof capture window fire) and latches; it re-arms
    when divergence falls back under half the tolerance, so a persistent
    shift cannot storm the ledger.  Observation only: the monitor never
    touches an answer."""

    def __init__(self, label: str, baseline: dict, tol: float | None = None):
        self.label = label
        self.baseline = OutputSketch.from_record(baseline)
        self.live = OutputSketch(self.baseline.kind)
        self.tol = tol if tol is not None else drift_tol()
        self.latched = False
        self.breaches = 0
        self.last_divergence: float | None = None
        self._lock = threading.Lock()
        with _drift_lock:
            _monitors[label] = self

    def _noise_allowance(self, observed: int) -> float:
        """Sampling-noise slack added to the tolerance while the live
        window is small: the TV distance of an n-sample empirical
        histogram from its own k-class source is ~0.5·sqrt(k/n) in
        expectation (decile noise ~1/sqrt(n) for the quantile kind), so
        judging a 32-answer window at the bare tolerance pages on pure
        sampling noise (measured: a healthy 10-class engine's warmup
        breached tol 0.25 at n≈32).  The allowance decays to ~0 as the
        window fills — a real shift still fires, just not off a handful
        of answers."""
        n = max(observed, 1)
        if self.baseline.kind == "class_histogram":
            k = max(len(self.baseline.counts), 1)
            return 0.5 * (k / n) ** 0.5
        return 2.0 / n ** 0.5

    def observe(self, outputs) -> None:
        try:
            with self._lock:
                self.live.observe(outputs)
                if self.live.observed < DRIFT_MIN_COUNT:
                    return
                d = self.baseline.divergence(self.live)
                if d is None:
                    return
                self.last_divergence = d
                threshold = self.tol + self._noise_allowance(
                    min(self.live.observed, QUANTILE_RESERVOIR)
                )
                fire = d > threshold and not self.latched
                if fire:
                    self.latched = True
                    self.breaches += 1
                elif self.latched and d < 0.5 * self.tol:
                    self.latched = False
            metric = _NAME_RE.sub("_", self.label)
            trace.metrics.gauge(f"numerics_{metric}_output_divergence", d)
            if fire:
                counters.record(
                    "serve_output_drift",
                    f"serve:{self.label}: output distribution diverged "
                    f"{d:.4f} from the fit-time baseline (tol {self.tol:g}, "
                    f"{self.live.observed} answers observed) — the request "
                    "mix or the model moved",
                )
        except Exception:  # noqa: BLE001 — detection must never break serving
            _logger.exception("drift monitor %r failed", self.label)

    def rearm(self, baseline: dict) -> None:
        """Swap in a NEW fit-time baseline and reset the live window and
        the latch (counted ``drift_rearmed``).  The lifecycle hot-swap
        calls this after a refit lands so post-swap answers are judged
        against the CANDIDATE's baseline from the swap instant — without
        this, answers observed during validation/warmup contaminate the
        live sketch and the stale baseline re-pages on the healthy new
        model.  ``breaches`` is cumulative across re-arms (the monitor's
        lifetime ledger)."""
        with self._lock:
            self.baseline = OutputSketch.from_record(baseline)
            self.live = OutputSketch(self.baseline.kind)
            self.latched = False
            self.last_divergence = None
        counters.record(
            "drift_rearmed",
            f"serve:{self.label}: drift monitor re-armed on a fresh "
            f"fit-time baseline ({self.baseline.kind}, "
            f"{self.baseline.observed} fit-time answers)",
        )

    def record(self) -> dict:
        with self._lock:
            return {
                "label": self.label,
                "kind": self.baseline.kind,
                "tol": self.tol,
                "observed": self.live.observed,
                "divergence": (
                    round(self.last_divergence, 6)
                    if self.last_divergence is not None
                    else None
                ),
                "drifted": self.latched,
                "breaches": self.breaches,
                "baseline_observed": self.baseline.observed,
            }


_drift_lock = threading.Lock()
_monitors: dict[str, DriftMonitor] = {}


def drift_monitors() -> dict:
    with _drift_lock:
        monitors = list(_monitors.values())
    return {m.label: m.record() for m in monitors}


def unregister_drift(label: str) -> None:
    with _drift_lock:
        _monitors.pop(label, None)


# -- the adopted metrics group / lifecycle -------------------------------------


def snapshot() -> dict:
    """The observatory's whole surface as one JSON-able dict (the adopted
    ``numerics`` metrics group; also what ``/statusz`` and postmortem
    dumps embed)."""
    return {
        "active": active(),
        "sample_every": sample_every(),
        "sites": site_stats(),
        "conditioning": recent_conditioning(),
        "provenance": provenance_records(),
        "drift": drift_monitors(),
    }


class _NumericsGroup:
    def snapshot(self, reset: bool = False) -> dict:
        out = snapshot()
        if reset:
            reset_state(keep_monitors=True)
        return out


trace.metrics.adopt("numerics", _NumericsGroup())


def reset_state(keep_monitors: bool = False) -> None:
    """Test isolation: forget sites, provenance, and conditioning history
    (and drift monitors unless ``keep_monitors``)."""
    with _site_lock:
        _sites.clear()
    with _prov_lock:
        _provenance.clear()
    with _cond_lock:
        _cond_recent.clear()
    if not keep_monitors:
        with _drift_lock:
            _monitors.clear()


@contextlib.contextmanager
def monitored(on: bool = True):
    """Programmatic enable/disable for benches, chaos, and tests —
    overrides the ``KEYSTONE_NUMERICS`` env gate for the block and
    restores the previous state on exit."""
    global _override
    prev = _override
    _override = on
    try:
        yield
    finally:
        _override = prev
