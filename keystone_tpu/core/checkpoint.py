"""Checkpoint/restore of fitted pipelines — the load-or-fit pattern,
generalized (reference GaussianMixtureModel.scala:83-90 loads fitted GMM
state from CSV flags; SURVEY §5 calls this the artifact-checkpoint idiom).

KeystoneML got fitted-artifact reuse per node via ad-hoc CSV flags and fault
tolerance from Spark lineage.  Here every node is a registered pytree
(core.pipeline.register_node), so any fitted node — or a whole ``a >> b``
pipeline, or a dict/list bundle of them — serializes generically:

* all array leaves land in ONE ``<stem>.npz`` (host numpy arrays; extended
  dtypes like bfloat16 ride as raw bytes with the true dtype recorded);
* the tree structure goes to a ``<stem>.json`` manifest: a versioned schema
  naming each node class (resolved through ``pipeline.NODE_REGISTRY`` on
  load) plus per-array dtype/shape, validated before any state is touched.

Writes are atomic (tmp file + ``os.replace``) so a preempted save never
leaves a half-written artifact that a later ``load_or_fit`` would trust.

Public surface:
  save_pipeline(path, pipe)   -> writes <stem>.npz + <stem>.json
  load_pipeline(path)         -> rebuilt object (arrays as jax.Arrays)
  checkpoint_exists(path)     -> bool (both files present)
  load_or_fit(path, est, *a)  -> load if present, else fit + save
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .pipeline import NODE_REGISTRY, Pipeline

_logger = logging.getLogger("keystone_tpu.checkpoint")

FORMAT_NAME = "keystone-tpu-checkpoint"
FORMAT_VERSION = 1

# dtypes numpy serializes natively inside an .npz; anything else (bfloat16,
# fp8, ...) is stored as raw bytes and re-viewed on load.
_NATIVE_KINDS = frozenset("biufc")


class CheckpointError(RuntimeError):
    """Unserializable node, missing/corrupt artifact, or schema mismatch."""


class CheckpointMismatch(CheckpointError):
    """The checkpoint was written under a DIFFERENT device/mesh topology
    than the loading process and its arrays were not fully replicated —
    restoring would silently change placement/sharding of a model that was
    solved distributed.  Re-load on the recorded topology, or re-fit."""


def _current_topology() -> dict:
    """Device/mesh fingerprint recorded into every manifest: the platform,
    the visible device count, and the ambient ``use_mesh`` shape (if any)."""
    from ..parallel.mesh import current_mesh

    devs = jax.devices()
    mesh = current_mesh()
    return {
        "platform": devs[0].platform,
        "device_count": len(devs),
        "mesh": dict(mesh.shape) if mesh is not None else None,
    }


def _is_replicated(v) -> bool:
    """True unless ``v`` is a jax.Array actually sharded over >1 device."""
    if not isinstance(v, jax.Array):
        return True
    try:
        return len(v.sharding.device_set) <= 1 or v.is_fully_replicated
    except Exception:  # noqa: BLE001 — unknown sharding: assume sharded
        return False


def checkpoint_paths(path: str) -> tuple[str, str]:
    """``path`` is a stem (``.npz``/``.json`` suffixes are stripped if
    given); returns (npz_path, manifest_path)."""
    stem, ext = os.path.splitext(path)
    if ext not in (".npz", ".json"):
        stem = path
    return stem + ".npz", stem + ".json"


def checkpoint_exists(path: str) -> bool:
    npz, manifest = checkpoint_paths(path)
    return os.path.exists(npz) and os.path.exists(manifest)


def _atomic_write_bytes(path: str, data: bytes) -> None:
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=os.path.basename(path) + ".tmp")
    try:
        with os.fdopen(fd, "wb") as fh:
            fh.write(data)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _is_array(v) -> bool:
    return isinstance(v, (np.ndarray, np.generic, jax.Array))


def _dtype_name(v) -> str | None:
    """Name for a dtype-like meta value (np.dtype, numpy scalar type, or a
    jnp dtype alias like ``jnp.bfloat16``), else None."""
    if isinstance(v, np.dtype):
        return v.name
    if isinstance(v, type) and issubclass(v, np.generic):
        return np.dtype(v).name
    # jnp scalar aliases (jnp.bfloat16 / jnp.float32 ...) are _ScalarMeta
    # instances, not types — the compute/activation dtype knobs nodes like
    # FusedConvFeaturizer and SIFTExtractor carry.  np.dtype() resolves
    # them; decode rebuilds the equivalent numpy scalar TYPE (ml_dtypes
    # for extended floats), which every jnp dtype= site accepts — so a
    # servable pipeline with bf16 activations checkpoints whole.
    if type(v).__name__ == "_ScalarMeta":
        try:
            return np.dtype(v).name
        except TypeError:
            return None
    return None


class _Encoder:
    def __init__(self):
        self.arrays: dict[str, np.ndarray] = {}
        self.specs: dict[str, dict] = {}
        self.all_replicated = True
        self._n = 0

    def add_array(self, v) -> str:
        key = f"a{self._n}"
        self._n += 1
        if not _is_replicated(v):
            self.all_replicated = False
        arr = np.asarray(jax.device_get(v))
        spec = {"dtype": arr.dtype.name, "shape": list(arr.shape)}
        if arr.dtype.kind not in _NATIVE_KINDS:
            # raw-bytes transport for npz-hostile dtypes (e.g. bfloat16)
            spec["raw"] = True
            arr = np.frombuffer(arr.tobytes(), np.uint8)
        self.arrays[key] = arr
        self.specs[key] = spec
        return key

    def encode(self, v, where: str) -> dict:
        if v is None:
            return {"t": "none"}
        if isinstance(v, (bool, int, float, str)):
            return {"t": "py", "v": v}
        if _is_array(v):
            return {"t": "arr", "k": self.add_array(v)}
        dt = _dtype_name(v)
        if dt is not None:
            return {"t": "dtype", "v": dt, "as_type": not isinstance(v, np.dtype)}
        if isinstance(v, (list, tuple)):
            return {
                "t": "tuple" if isinstance(v, tuple) else "list",
                "v": [self.encode(x, f"{where}[{i}]") for i, x in enumerate(v)],
            }
        if isinstance(v, dict):
            if not all(isinstance(k, str) for k in v):
                raise CheckpointError(f"{where}: dict keys must be strings")
            return {
                "t": "dict",
                "v": {k: self.encode(x, f"{where}[{k!r}]") for k, x in v.items()},
            }
        if isinstance(v, Pipeline):
            return {
                "t": "pipeline",
                "nodes": [
                    self.encode(n, f"{where}.nodes[{i}]")
                    for i, n in enumerate(v.nodes)
                ],
            }
        # BlockLinearMapper registers its pytree manually (solvers.block),
        # so it is looked up by name rather than through NODE_REGISTRY.
        if type(v).__name__ == "BlockLinearMapper":
            return {
                "t": "blm",
                "xs": self.encode(list(v.xs), f"{where}.xs"),
                "b": self.encode(v.b, f"{where}.b"),
                "scalers": self.encode(
                    list(v.feature_scalers), f"{where}.feature_scalers"
                ),
                "block_size": int(v.block_size),
            }
        entry = NODE_REGISTRY.get(type(v).__name__)
        if entry is not None and type(v) is entry[0]:
            _, data_fields, meta_fields = entry
            return {
                "t": "node",
                "cls": type(v).__name__,
                "data": {
                    f: self.encode(getattr(v, f), f"{where}.{f}")
                    for f in data_fields
                },
                "meta": {
                    f: self.encode(getattr(v, f), f"{where}.{f}")
                    for f in meta_fields
                },
            }
        raise CheckpointError(
            f"{where}: cannot serialize {type(v).__name__!r} — not a "
            "registered node (see core.pipeline.register_node) and not a "
            "plain array/scalar/container.  Function-valued nodes "
            "(FunctionTransformer, Cacher with a sharding) hold live Python "
            "objects and are not checkpointable."
        )


def _decode(spec: dict, arrays, array_specs: dict, where: str) -> Any:
    t = spec.get("t")
    if t == "none":
        return None
    if t == "py":
        return spec["v"]
    if t == "arr":
        key = spec["k"]
        if key not in arrays:
            raise CheckpointError(f"{where}: array {key!r} missing from .npz")
        aspec = array_specs.get(key)
        if aspec is None:
            raise CheckpointError(f"{where}: array {key!r} missing from manifest")
        arr = arrays[key]
        if aspec.get("raw"):
            arr = np.frombuffer(arr.tobytes(), np.dtype(aspec["dtype"])).reshape(
                aspec["shape"]
            )
        if arr.dtype.name != aspec["dtype"] or list(arr.shape) != list(
            aspec["shape"]
        ):
            raise CheckpointError(
                f"{where}: array {key!r} is {arr.dtype.name}{list(arr.shape)}, "
                f"manifest says {aspec['dtype']}{aspec['shape']} — artifact "
                "corrupt or schema drift"
            )
        return jnp.asarray(arr)
    if t == "dtype":
        dt = np.dtype(spec["v"])
        return dt.type if spec.get("as_type") else dt
    if t in ("list", "tuple"):
        vals = [
            _decode(s, arrays, array_specs, f"{where}[{i}]")
            for i, s in enumerate(spec["v"])
        ]
        return tuple(vals) if t == "tuple" else vals
    if t == "dict":
        return {
            k: _decode(s, arrays, array_specs, f"{where}[{k!r}]")
            for k, s in spec["v"].items()
        }
    if t == "pipeline":
        return Pipeline(
            [
                _decode(s, arrays, array_specs, f"{where}.nodes[{i}]")
                for i, s in enumerate(spec["nodes"])
            ]
        )
    if t == "blm":
        from ..solvers.block import BlockLinearMapper

        return BlockLinearMapper(
            list(_decode(spec["xs"], arrays, array_specs, f"{where}.xs")),
            int(spec["block_size"]),
            _decode(spec["b"], arrays, array_specs, f"{where}.b"),
            list(
                _decode(spec["scalers"], arrays, array_specs, f"{where}.scalers")
            ),
        )
    if t == "node":
        name = spec["cls"]
        entry = NODE_REGISTRY.get(name)
        if entry is None:
            raise CheckpointError(
                f"{where}: node class {name!r} is not registered in this "
                "process — import the module defining it before loading"
            )
        cls, data_fields, meta_fields = entry
        missing = (set(spec["data"]) ^ set(data_fields)) | (
            set(spec["meta"]) ^ set(meta_fields)
        )
        if missing:
            raise CheckpointError(
                f"{where}: field schema of {name!r} changed since this "
                f"checkpoint was written (mismatched fields: {sorted(missing)})"
            )
        # Rebuild exactly the way jax unflattens the pytree: bypass __init__
        # and set the registered fields (core.pipeline.register_node).
        obj = object.__new__(cls)
        for f in data_fields:
            object.__setattr__(
                obj, f, _decode(spec["data"][f], arrays, array_specs, f"{where}.{f}")
            )
        for f in meta_fields:
            object.__setattr__(
                obj, f, _decode(spec["meta"][f], arrays, array_specs, f"{where}.{f}")
            )
        return obj
    raise CheckpointError(f"{where}: unknown manifest entry type {t!r}")


def save_pipeline(path: str, pipe, numerics_baseline: dict | None = None) -> str:
    """Serialize a fitted node / ``Pipeline`` / container of them to
    ``<stem>.npz`` (array leaves) + ``<stem>.json`` (treedef manifest).
    Returns the stem.  Atomic: a crash mid-save leaves no partial artifact.

    ``numerics_baseline``: an optional fit-time output-distribution sketch
    (``core.numerics.OutputSketch.record()``) persisted in the manifest —
    the reference the serving tier's output-drift monitor judges live
    answers against (``serve.load_engine`` arms it on warm load).  Pure
    metadata: it never affects what the pipeline computes.
    """
    npz_path, manifest_path = checkpoint_paths(path)
    enc = _Encoder()
    root = enc.encode(pipe, "root")
    import hashlib
    import io

    buf = io.BytesIO()
    np.savez(buf, **enc.arrays)
    npz_bytes = buf.getvalue()
    manifest = {
        "format": FORMAT_NAME,
        "version": FORMAT_VERSION,
        # Ties the pair together: the two files are replaced in separate
        # atomic renames, so a preemption between them could leave a new
        # .npz next to an old .json (or vice versa) — the hash check on
        # load rejects any mixed pair.
        "npz_sha256": hashlib.sha256(npz_bytes).hexdigest(),
        # Where this checkpoint was solved: the load path refuses to
        # restore NON-replicated arrays onto a different topology (see
        # CheckpointMismatch) instead of silently resharding them.
        "topology": _current_topology(),
        "all_replicated": enc.all_replicated,
        "root": root,
        "arrays": enc.specs,
    }
    if numerics_baseline is not None:
        manifest["numerics_baseline"] = numerics_baseline
    _atomic_write_bytes(npz_path, npz_bytes)
    _atomic_write_bytes(
        manifest_path, json.dumps(manifest, indent=1).encode("utf-8")
    )
    _logger.info(
        "saved checkpoint %s (%d arrays, %.1f KiB)",
        npz_path,
        len(enc.arrays),
        buf.getbuffer().nbytes / 1024,
    )
    return os.path.splitext(npz_path)[0]


def _ensure_standard_registry() -> None:
    """Import the library modules that register the stock node classes, so
    a FRESH process can load a checkpoint without the caller knowing which
    modules define its nodes.  (Out-of-tree nodes still need their defining
    module imported by the caller.)"""
    import importlib

    for mod in (
        "ops.stats", "ops.util", "ops.images", "ops.fisher", "ops.sift",
        "ops.lcs", "ops.hog", "ops.daisy", "ops.conv_fused",
        "solvers.pca", "solvers.gmm", "solvers.linear", "solvers.whitening",
        "solvers.naive_bayes", "solvers.block",
    ):
        try:
            importlib.import_module(f"keystone_tpu.{mod}")
        except ImportError as e:  # pragma: no cover - partial installs
            _logger.warning("registry bootstrap: could not import %s: %s", mod, e)


def load_pipeline(path: str):
    """Rebuild a fitted node/pipeline saved by :func:`save_pipeline`.
    Validates format version and every array's dtype/shape against the
    manifest before constructing anything."""
    _ensure_standard_registry()
    npz_path, manifest_path = checkpoint_paths(path)
    try:
        with open(manifest_path) as fh:
            manifest = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        raise CheckpointError(f"cannot read manifest {manifest_path}: {e}") from e
    if manifest.get("format") != FORMAT_NAME:
        raise CheckpointError(
            f"{manifest_path}: not a {FORMAT_NAME} manifest"
        )
    if manifest.get("version") != FORMAT_VERSION:
        raise CheckpointError(
            f"{manifest_path}: format version {manifest.get('version')} "
            f"(this build reads {FORMAT_VERSION})"
        )
    recorded = manifest.get("topology")
    if recorded is not None and not manifest.get("all_replicated", True):
        # Sharded state is only restorable onto the topology it was
        # solved on; anything else must fail TYPED, not reshard silently.
        current = _current_topology()
        if recorded != current:
            raise CheckpointMismatch(
                f"{manifest_path}: checkpoint holds sharded (non-replicated) "
                f"arrays solved on topology {recorded}, but this process is "
                f"{current} — refusing to silently reshard; load on the "
                "recorded topology or re-fit"
            )
    elif recorded is None:
        _logger.warning(
            "%s: no topology recorded (pre-mesh-guard checkpoint) — "
            "loading without a placement check",
            manifest_path,
        )
    import hashlib
    import io

    try:
        with open(npz_path, "rb") as fh:
            npz_bytes = fh.read()
        want_hash = manifest.get("npz_sha256")
        if want_hash is not None:
            got_hash = hashlib.sha256(npz_bytes).hexdigest()
            if got_hash != want_hash:
                raise CheckpointError(
                    f"{npz_path}: content hash does not match the manifest — "
                    "the .npz/.json pair is from two different saves "
                    "(preempted overwrite?)"
                )
        with np.load(io.BytesIO(npz_bytes)) as zf:
            arrays = {k: zf[k] for k in zf.files}
    except (OSError, ValueError) as e:
        raise CheckpointError(f"cannot read arrays {npz_path}: {e}") from e
    extra = set(manifest["arrays"]) - set(arrays)
    if extra:
        raise CheckpointError(
            f"{npz_path}: arrays {sorted(extra)} named in manifest are missing"
        )
    obj = _decode(manifest["root"], arrays, manifest["arrays"], "root")
    _logger.info("loaded checkpoint %s (%d arrays)", npz_path, len(arrays))
    return obj


def load_numerics_baseline(path: str) -> dict | None:
    """The fit-time output-distribution sketch persisted by
    ``save_pipeline(numerics_baseline=...)``, or None (absent entry,
    pre-observatory artifact, unreadable manifest).  Advisory metadata for
    the drift monitor — this NEVER raises: a missing baseline means an
    unmonitored engine, not a failed load (``load_pipeline`` holds the
    manifest to the strict bar)."""
    _, manifest_path = checkpoint_paths(path)
    try:
        with open(manifest_path) as fh:
            manifest = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        _logger.warning(
            "numerics baseline unreadable from %s (%s)", manifest_path, e
        )
        return None
    baseline = manifest.get("numerics_baseline")
    return dict(baseline) if isinstance(baseline, dict) else None


def load_or_fit(path: str | None, est, *fit_args, save: bool = True, **fit_kwargs):
    """The GMM/PCA CSV-flag pattern generalized: reload the fitted artifact
    at ``path`` if present, else fit and (by default) save it there.

    ``est`` is an Estimator/LabelEstimator (``.fit`` is called with the
    remaining args) or any callable returning the fitted object.  With
    ``path=None`` this is just the fit."""
    if path and checkpoint_exists(path):
        _logger.info("load_or_fit: restoring fitted state from %s", path)
        return load_pipeline(path)
    fit = est.fit if hasattr(est, "fit") else est
    fitted = fit(*fit_args, **fit_kwargs)
    if path and save:
        save_pipeline(path, fitted)
    return fitted
