"""Checkpoint/restore of fitted pipelines — the load-or-fit pattern,
generalized (reference GaussianMixtureModel.scala:83-90 loads fitted GMM
state from CSV flags; SURVEY §5 calls this the artifact-checkpoint idiom).

KeystoneML got fitted-artifact reuse per node via ad-hoc CSV flags and fault
tolerance from Spark lineage.  Here every node is a registered pytree
(core.pipeline.register_node), so any fitted node — or a whole ``a >> b``
pipeline, or a dict/list bundle of them — serializes generically:

* all array leaves land in ONE ``<stem>.npz`` (host numpy arrays; extended
  dtypes like bfloat16 ride as raw bytes with the true dtype recorded);
* the tree structure goes to a ``<stem>.json`` manifest: a versioned schema
  naming each node class (resolved through ``pipeline.NODE_REGISTRY`` on
  load) plus per-array dtype/shape, validated before any state is touched.

Writes are atomic (tmp file + ``os.replace``) so a preempted save never
leaves a half-written artifact that a later ``load_or_fit`` would trust.

Public surface:
  save_pipeline(path, pipe)   -> writes <stem>.npz + <stem>.json
  load_pipeline(path)         -> rebuilt object (arrays as jax.Arrays)
  load_pipeline(path, mesh=M) -> same, with every array leaf redistributed
                                 onto mesh M (topology-portable restore)
  checkpoint_exists(path)     -> bool (both files present)
  load_or_fit(path, est, *a)  -> load if present, else fit + save
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .pipeline import NODE_REGISTRY, Pipeline

_logger = logging.getLogger("keystone_tpu.checkpoint")

FORMAT_NAME = "keystone-tpu-checkpoint"
FORMAT_VERSION = 1

# dtypes numpy serializes natively inside an .npz; anything else (bfloat16,
# fp8, ...) is stored as raw bytes and re-viewed on load.
_NATIVE_KINDS = frozenset("biufc")

#: Transfer granularity of the reshard loader: arrays larger than this go
#: host-staged shard-by-shard (jax.make_array_from_callback) instead of one
#: whole-array device_put, so the transient footprint of a restore stays
#: bounded even when no single device could stage the whole array.
RESHARD_CHUNK_ENV = "KEYSTONE_RESHARD_CHUNK_BYTES"
_DEFAULT_RESHARD_CHUNK = 64 * 2**20


class CheckpointError(RuntimeError):
    """Unserializable node, missing/corrupt artifact, or schema mismatch."""


class CheckpointMismatch(CheckpointError):
    """The checkpoint was written under a DIFFERENT device/mesh topology
    than the loading process and its arrays were not fully replicated —
    restoring would silently change placement/sharding of a model that was
    solved distributed.  Re-load on the recorded topology, or re-fit."""


def _current_topology() -> dict:
    """Device/mesh fingerprint recorded into every manifest: the platform,
    the visible device count, and the ambient ``use_mesh`` shape (if any)."""
    from ..parallel.mesh import current_mesh

    devs = jax.devices()
    mesh = current_mesh()
    topo = {
        "platform": devs[0].platform,
        "device_count": len(devs),
        "mesh": dict(mesh.shape) if mesh is not None else None,
    }
    # Multi-process runs fingerprint their world size too (the key is
    # omitted single-process so pre-ISSUE-17 checkpoints still compare
    # equal under the topology guard).
    if jax.process_count() > 1:
        topo["processes"] = jax.process_count()
    return topo


def _is_replicated(v) -> bool:
    """True unless ``v`` is a jax.Array actually sharded over >1 device."""
    if not isinstance(v, jax.Array):
        return True
    try:
        return len(v.sharding.device_set) <= 1 or v.is_fully_replicated
    except Exception:  # noqa: BLE001 — unknown sharding: assume sharded
        return False


def _sharding_spec(v) -> str:
    """The autoshard spec string (``'replicated'`` / ``'data@dimN'`` /
    ``'model@dimN'``) an array leaf is laid out as — what the manifest
    records per array so a reshard load can re-lower the SAME layout onto
    whatever mesh survived.  A sharding outside that vocabulary (multi-axis
    partitioning, foreign axis names) records as ``'opaque'``; the reshard
    loader places those replicated."""
    if _is_replicated(v):
        return "replicated"
    from ..parallel.mesh import DATA_AXIS, MODEL_AXIS

    try:
        pspec = tuple(v.sharding.spec)
    except Exception:  # noqa: BLE001 — non-NamedSharding layouts
        return "opaque"
    parts: list[tuple[str, int]] = []
    for i, part in enumerate(pspec):
        names = (
            part if isinstance(part, tuple)
            else ((part,) if part is not None else ())
        )
        parts.extend((str(name), i) for name in names)
    if not parts:
        return "replicated"
    if len(parts) == 1 and parts[0][0] in (DATA_AXIS, MODEL_AXIS):
        return f"{parts[0][0]}@dim{parts[0][1]}"
    return "opaque"


def checkpoint_paths(path: str) -> tuple[str, str]:
    """``path`` is a stem (``.npz``/``.json`` suffixes are stripped if
    given); returns (npz_path, manifest_path)."""
    stem, ext = os.path.splitext(path)
    if ext not in (".npz", ".json"):
        stem = path
    return stem + ".npz", stem + ".json"


def checkpoint_exists(path: str) -> bool:
    npz, manifest = checkpoint_paths(path)
    return os.path.exists(npz) and os.path.exists(manifest)


def _atomic_write_bytes(path: str, data: bytes) -> None:
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=os.path.basename(path) + ".tmp")
    try:
        with os.fdopen(fd, "wb") as fh:
            fh.write(data)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _is_array(v) -> bool:
    return isinstance(v, (np.ndarray, np.generic, jax.Array))


def _dtype_name(v) -> str | None:
    """Name for a dtype-like meta value (np.dtype, numpy scalar type, or a
    jnp dtype alias like ``jnp.bfloat16``), else None."""
    if isinstance(v, np.dtype):
        return v.name
    if isinstance(v, type) and issubclass(v, np.generic):
        return np.dtype(v).name
    # jnp scalar aliases (jnp.bfloat16 / jnp.float32 ...) are _ScalarMeta
    # instances, not types — the compute/activation dtype knobs nodes like
    # FusedConvFeaturizer and SIFTExtractor carry.  np.dtype() resolves
    # them; decode rebuilds the equivalent numpy scalar TYPE (ml_dtypes
    # for extended floats), which every jnp dtype= site accepts — so a
    # servable pipeline with bf16 activations checkpoints whole.
    if type(v).__name__ == "_ScalarMeta":
        try:
            return np.dtype(v).name
        except TypeError:
            return None
    return None


class _Encoder:
    def __init__(self):
        self.arrays: dict[str, np.ndarray] = {}
        self.specs: dict[str, dict] = {}
        self.all_replicated = True
        self._n = 0

    def add_array(self, v) -> str:
        key = f"a{self._n}"
        self._n += 1
        sharding = _sharding_spec(v)
        if not _is_replicated(v):
            self.all_replicated = False
        arr = np.asarray(jax.device_get(v))
        spec = {"dtype": arr.dtype.name, "shape": list(arr.shape)}
        if sharding != "replicated":
            # Per-array layout provenance (absent == replicated): the
            # reshard loader re-lowers this spec onto the target mesh.
            spec["sharding"] = sharding
        if arr.dtype.kind not in _NATIVE_KINDS:
            # raw-bytes transport for npz-hostile dtypes (e.g. bfloat16)
            spec["raw"] = True
            arr = np.frombuffer(arr.tobytes(), np.uint8)
        self.arrays[key] = arr
        self.specs[key] = spec
        return key

    def encode(self, v, where: str) -> dict:
        if v is None:
            return {"t": "none"}
        if isinstance(v, (bool, int, float, str)):
            return {"t": "py", "v": v}
        if _is_array(v):
            return {"t": "arr", "k": self.add_array(v)}
        dt = _dtype_name(v)
        if dt is not None:
            return {"t": "dtype", "v": dt, "as_type": not isinstance(v, np.dtype)}
        if isinstance(v, (list, tuple)):
            return {
                "t": "tuple" if isinstance(v, tuple) else "list",
                "v": [self.encode(x, f"{where}[{i}]") for i, x in enumerate(v)],
            }
        if isinstance(v, dict):
            if not all(isinstance(k, str) for k in v):
                raise CheckpointError(f"{where}: dict keys must be strings")
            return {
                "t": "dict",
                "v": {k: self.encode(x, f"{where}[{k!r}]") for k, x in v.items()},
            }
        if isinstance(v, Pipeline):
            return {
                "t": "pipeline",
                "nodes": [
                    self.encode(n, f"{where}.nodes[{i}]")
                    for i, n in enumerate(v.nodes)
                ],
            }
        # BlockLinearMapper registers its pytree manually (solvers.block),
        # so it is looked up by name rather than through NODE_REGISTRY.
        if type(v).__name__ == "BlockLinearMapper":
            return {
                "t": "blm",
                "xs": self.encode(list(v.xs), f"{where}.xs"),
                "b": self.encode(v.b, f"{where}.b"),
                "scalers": self.encode(
                    list(v.feature_scalers), f"{where}.feature_scalers"
                ),
                "block_size": int(v.block_size),
            }
        entry = NODE_REGISTRY.get(type(v).__name__)
        if entry is not None and type(v) is entry[0]:
            _, data_fields, meta_fields = entry
            return {
                "t": "node",
                "cls": type(v).__name__,
                "data": {
                    f: self.encode(getattr(v, f), f"{where}.{f}")
                    for f in data_fields
                },
                "meta": {
                    f: self.encode(getattr(v, f), f"{where}.{f}")
                    for f in meta_fields
                },
            }
        raise CheckpointError(
            f"{where}: cannot serialize {type(v).__name__!r} — not a "
            "registered node (see core.pipeline.register_node) and not a "
            "plain array/scalar/container.  Function-valued nodes "
            "(FunctionTransformer, Cacher with a sharding) hold live Python "
            "objects and are not checkpointable."
        )


def _decode(spec: dict, arrays, array_specs: dict, where: str, put=None) -> Any:
    t = spec.get("t")
    if t == "none":
        return None
    if t == "py":
        return spec["v"]
    if t == "arr":
        key = spec["k"]
        if key not in arrays:
            raise CheckpointError(f"{where}: array {key!r} missing from .npz")
        aspec = array_specs.get(key)
        if aspec is None:
            raise CheckpointError(f"{where}: array {key!r} missing from manifest")
        arr = arrays[key]
        if aspec.get("raw"):
            arr = np.frombuffer(arr.tobytes(), np.dtype(aspec["dtype"])).reshape(
                aspec["shape"]
            )
        if arr.dtype.name != aspec["dtype"] or list(arr.shape) != list(
            aspec["shape"]
        ):
            raise CheckpointError(
                f"{where}: array {key!r} is {arr.dtype.name}{list(arr.shape)}, "
                f"manifest says {aspec['dtype']}{aspec['shape']} — artifact "
                "corrupt or schema drift"
            )
        # ``put`` is the reshard hook (load_pipeline(mesh=)): it places the
        # host array onto the target mesh instead of the default device.
        return put(arr, key, where) if put is not None else jnp.asarray(arr)
    if t == "dtype":
        dt = np.dtype(spec["v"])
        return dt.type if spec.get("as_type") else dt
    if t in ("list", "tuple"):
        vals = [
            _decode(s, arrays, array_specs, f"{where}[{i}]", put)
            for i, s in enumerate(spec["v"])
        ]
        return tuple(vals) if t == "tuple" else vals
    if t == "dict":
        return {
            k: _decode(s, arrays, array_specs, f"{where}[{k!r}]", put)
            for k, s in spec["v"].items()
        }
    if t == "pipeline":
        return Pipeline(
            [
                _decode(s, arrays, array_specs, f"{where}.nodes[{i}]", put)
                for i, s in enumerate(spec["nodes"])
            ]
        )
    if t == "blm":
        from ..solvers.block import BlockLinearMapper

        return BlockLinearMapper(
            list(_decode(spec["xs"], arrays, array_specs, f"{where}.xs", put)),
            int(spec["block_size"]),
            _decode(spec["b"], arrays, array_specs, f"{where}.b", put),
            list(
                _decode(
                    spec["scalers"], arrays, array_specs, f"{where}.scalers", put
                )
            ),
        )
    if t == "node":
        name = spec["cls"]
        entry = NODE_REGISTRY.get(name)
        if entry is None:
            raise CheckpointError(
                f"{where}: node class {name!r} is not registered in this "
                "process — import the module defining it before loading"
            )
        cls, data_fields, meta_fields = entry
        missing = (set(spec["data"]) ^ set(data_fields)) | (
            set(spec["meta"]) ^ set(meta_fields)
        )
        if missing:
            raise CheckpointError(
                f"{where}: field schema of {name!r} changed since this "
                f"checkpoint was written (mismatched fields: {sorted(missing)})"
            )
        # Rebuild exactly the way jax unflattens the pytree: bypass __init__
        # and set the registered fields (core.pipeline.register_node).
        obj = object.__new__(cls)
        for f in data_fields:
            object.__setattr__(
                obj,
                f,
                _decode(spec["data"][f], arrays, array_specs, f"{where}.{f}", put),
            )
        for f in meta_fields:
            object.__setattr__(
                obj,
                f,
                _decode(spec["meta"][f], arrays, array_specs, f"{where}.{f}", put),
            )
        return obj
    raise CheckpointError(f"{where}: unknown manifest entry type {t!r}")


class _Resharder:
    """Redistributes checkpointed host arrays onto a TARGET mesh — the
    ``load_pipeline(mesh=)`` placement engine.

    Per array: the recorded spec (manifest ``"sharding"``) is re-lowered
    onto the new mesh when its named dimension still divides there, else the
    array lands replicated; every placement is charged analytically against
    the target's min per-chip budget (``memory.plan_bytes`` — the
    plan_program-style admission without a compile).  A replicated placement
    denied per-chip falls back to the best dividing spec (the "no common
    device fits a whole array" tier); a placement nothing admits is a TYPED
    ``CheckpointError``, never an OOM mid-restore.  Arrays above
    ``KEYSTONE_RESHARD_CHUNK_BYTES`` transfer host-staged shard-by-shard via
    ``jax.make_array_from_callback`` so the transient footprint stays
    bounded by one shard, not one whole array.

    On a mesh spanning PROCESSES every placement goes through the
    callback path unconditionally (counted ``ckpt_reshard_crosshost``):
    ``make_array_from_callback`` materializes only the shards addressable
    from each process, so every destination host pulls its own slices and
    no single host stages the whole fleet's state — the cross-host
    generalization of the chunked path, with per-host transient bounded
    by that host's largest local shard.  (``device_put`` would refuse the
    non-addressable devices outright; the single-process paths are kept
    unchanged as the fallback.)"""

    def __init__(self, mesh, array_specs: dict, manifest_path: str):
        from . import memory as kmem
        from ..parallel.mesh import mesh_spans_processes

        self.mesh = mesh
        self.crosshost = mesh_spans_processes(mesh)
        self.mesh_shape = dict(mesh.shape)
        self.array_specs = array_specs
        self.manifest_path = manifest_path
        raw = os.environ.get(RESHARD_CHUNK_ENV, "").strip()
        self.chunk_bytes = (
            kmem.parse_bytes(raw) if raw else _DEFAULT_RESHARD_CHUNK
        )
        # One budget read per load: admission below is analytic and the
        # mesh does not change mid-restore.
        self.budget, _ = kmem.min_chip_budget(mesh)
        self.stats = {
            "arrays": 0, "resharded": 0, "host_staged": 0,
            "spec_fallback": 0, "crosshost": 0, "bytes": 0,
        }

    def _target_spec(self, arr: np.ndarray, recorded: str) -> str:
        from . import autoshard

        if recorded not in ("replicated", "opaque"):
            try:
                autoshard.spec_pspec(recorded, arr.ndim)
                autoshard.spec_chip_bytes(
                    arr.shape, arr.dtype, recorded, self.mesh_shape
                )
                return recorded
            except ValueError:
                pass  # recorded dim no longer divides: replicate instead
        return "replicated"

    def put(self, arr: np.ndarray, key: str, where: str):
        from . import autoshard
        from . import memory as kmem

        recorded = self.array_specs.get(key, {}).get("sharding", "replicated")
        spec = self._target_spec(arr, recorded)
        self.stats["arrays"] += 1
        per_chip = autoshard.spec_chip_bytes(
            arr.shape, arr.dtype, spec, self.mesh_shape
        )
        plan = kmem.plan_bytes(
            f"ckpt_reshard:{key}",
            output_bytes=per_chip,
            mesh=self.mesh,
            budget=self.budget,
        )
        if not plan.admitted and spec == "replicated":
            # No chip fits the whole array: shard it instead — the
            # host-staged fallback tier of the reshard ladder.
            cand = autoshard.best_spec(arr, self.mesh_shape)
            if cand["spec"] != "replicated":
                spec = cand["spec"]
                per_chip = int(cand["per_chip_bytes"])
                self.stats["spec_fallback"] += 1
                plan = kmem.plan_bytes(
                    f"ckpt_reshard:{key}:{spec}",
                    output_bytes=per_chip,
                    mesh=self.mesh,
                    budget=self.budget,
                )
        if not plan.admitted:
            raise CheckpointError(
                f"{where}: array {key!r} "
                f"({arr.dtype.name}{list(arr.shape)}) does not fit the "
                f"target mesh — {kmem.fmt_bytes(per_chip)}/chip under spec "
                f"{spec!r} vs budget "
                f"{kmem.fmt_bytes(self.budget or 0)} ({plan.reason})"
            )
        sharding = autoshard.spec_sharding(spec, self.mesh, arr.ndim)
        if spec != "replicated" or recorded != "replicated":
            self.stats["resharded"] += 1
        self.stats["bytes"] += int(arr.nbytes)
        if self.crosshost:
            # Destination-host pull: only the shards addressable from
            # THIS process are materialized by the callback, so state is
            # redistributed across the fleet without staging through one
            # host's RAM.
            self.stats["crosshost"] += 1
            if arr.nbytes > self.chunk_bytes and arr.ndim:
                self.stats["host_staged"] += 1
            return jax.make_array_from_callback(
                arr.shape, sharding, lambda idx: np.asarray(arr[idx])
            )
        if arr.nbytes > self.chunk_bytes and arr.ndim:
            # Host-staged, per-shard transfer: each device receives only
            # its own slice, one shard in flight at a time.
            self.stats["host_staged"] += 1
            return jax.make_array_from_callback(
                arr.shape, sharding, lambda idx: arr[idx]
            )
        return jax.device_put(arr, sharding)


def save_pipeline(path: str, pipe, numerics_baseline: dict | None = None) -> str:
    """Serialize a fitted node / ``Pipeline`` / container of them to
    ``<stem>.npz`` (array leaves) + ``<stem>.json`` (treedef manifest).
    Returns the stem.  Atomic: a crash mid-save leaves no partial artifact.

    ``numerics_baseline``: an optional fit-time output-distribution sketch
    (``core.numerics.OutputSketch.record()``) persisted in the manifest —
    the reference the serving tier's output-drift monitor judges live
    answers against (``serve.load_engine`` arms it on warm load).  Pure
    metadata: it never affects what the pipeline computes.
    """
    npz_path, manifest_path = checkpoint_paths(path)
    enc = _Encoder()
    root = enc.encode(pipe, "root")
    import hashlib
    import io

    buf = io.BytesIO()
    np.savez(buf, **enc.arrays)
    npz_bytes = buf.getvalue()
    manifest = {
        "format": FORMAT_NAME,
        "version": FORMAT_VERSION,
        # Ties the pair together: the two files are replaced in separate
        # atomic renames, so a preemption between them could leave a new
        # .npz next to an old .json (or vice versa) — the hash check on
        # load rejects any mixed pair.
        "npz_sha256": hashlib.sha256(npz_bytes).hexdigest(),
        # Where this checkpoint was solved: the load path refuses to
        # restore NON-replicated arrays onto a different topology (see
        # CheckpointMismatch) instead of silently resharding them.
        "topology": _current_topology(),
        "all_replicated": enc.all_replicated,
        "root": root,
        "arrays": enc.specs,
    }
    if numerics_baseline is not None:
        manifest["numerics_baseline"] = numerics_baseline
    _atomic_write_bytes(npz_path, npz_bytes)
    _atomic_write_bytes(
        manifest_path, json.dumps(manifest, indent=1).encode("utf-8")
    )
    _logger.info(
        "saved checkpoint %s (%d arrays, %.1f KiB)",
        npz_path,
        len(enc.arrays),
        buf.getbuffer().nbytes / 1024,
    )
    return os.path.splitext(npz_path)[0]


def _ensure_standard_registry() -> None:
    """Import the library modules that register the stock node classes, so
    a FRESH process can load a checkpoint without the caller knowing which
    modules define its nodes.  (Out-of-tree nodes still need their defining
    module imported by the caller.)"""
    import importlib

    for mod in (
        "ops.stats", "ops.util", "ops.images", "ops.fisher", "ops.sift",
        "ops.lcs", "ops.hog", "ops.daisy", "ops.conv_fused",
        "solvers.pca", "solvers.gmm", "solvers.linear", "solvers.whitening",
        "solvers.naive_bayes", "solvers.block",
    ):
        try:
            importlib.import_module(f"keystone_tpu.{mod}")
        except ImportError as e:  # pragma: no cover - partial installs
            _logger.warning("registry bootstrap: could not import %s: %s", mod, e)


def load_pipeline(path: str, mesh=None):
    """Rebuild a fitted node/pipeline saved by :func:`save_pipeline`.
    Validates format version and every array's dtype/shape against the
    manifest before constructing anything.

    ``mesh``: the topology-portable restore path.  ``None`` (the default)
    keeps the strict posture — sharded state recorded under a different
    topology raises the typed :class:`CheckpointMismatch` instead of
    resharding silently.  Passing a target ``jax.sharding.Mesh``
    OPTS IN to redistribution: every array leaf is placed onto that mesh
    (its recorded spec re-lowered where it still divides, replicated
    otherwise), each placement admitted per-chip (``memory.plan_bytes``)
    and transferred chunked/host-staged above
    ``KEYSTONE_RESHARD_CHUNK_BYTES`` — see :class:`_Resharder`.  A
    placement no tier admits is a typed ``CheckpointError``."""
    _ensure_standard_registry()
    npz_path, manifest_path = checkpoint_paths(path)
    try:
        with open(manifest_path) as fh:
            manifest = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        raise CheckpointError(f"cannot read manifest {manifest_path}: {e}") from e
    if manifest.get("format") != FORMAT_NAME:
        raise CheckpointError(
            f"{manifest_path}: not a {FORMAT_NAME} manifest"
        )
    if manifest.get("version") != FORMAT_VERSION:
        raise CheckpointError(
            f"{manifest_path}: format version {manifest.get('version')} "
            f"(this build reads {FORMAT_VERSION})"
        )
    recorded = manifest.get("topology")
    if mesh is not None:
        pass  # explicit reshard target: the topology guard is satisfied below
    elif recorded is not None and not manifest.get("all_replicated", True):
        # Sharded state is only restorable onto the topology it was
        # solved on; anything else must fail TYPED, not reshard silently.
        current = _current_topology()
        if recorded != current:
            raise CheckpointMismatch(
                f"{manifest_path}: checkpoint holds sharded (non-replicated) "
                f"arrays recorded under topology {recorded} but this process "
                f"is {current} — refusing to silently reshard.  Pass "
                "load_pipeline(..., mesh=<target Mesh>) to redistribute the "
                "state onto the mesh you have, load on the recorded "
                "topology, or re-fit"
            )
    elif recorded is None:
        _logger.warning(
            "%s: no topology recorded (pre-mesh-guard checkpoint) — "
            "loading without a placement check",
            manifest_path,
        )
    import hashlib
    import io

    try:
        with open(npz_path, "rb") as fh:
            npz_bytes = fh.read()
        want_hash = manifest.get("npz_sha256")
        if want_hash is not None:
            got_hash = hashlib.sha256(npz_bytes).hexdigest()
            if got_hash != want_hash:
                raise CheckpointError(
                    f"{npz_path}: content hash does not match the manifest — "
                    "the .npz/.json pair is from two different saves "
                    "(preempted overwrite?)"
                )
        with np.load(io.BytesIO(npz_bytes)) as zf:
            arrays = {k: zf[k] for k in zf.files}
    except (OSError, ValueError) as e:
        raise CheckpointError(f"cannot read arrays {npz_path}: {e}") from e
    extra = set(manifest["arrays"]) - set(arrays)
    if extra:
        raise CheckpointError(
            f"{npz_path}: arrays {sorted(extra)} named in manifest are missing"
        )
    resharder = (
        _Resharder(mesh, manifest["arrays"], manifest_path)
        if mesh is not None
        else None
    )
    obj = _decode(
        manifest["root"], arrays, manifest["arrays"], "root",
        resharder.put if resharder is not None else None,
    )
    if resharder is not None and resharder.stats["arrays"]:
        from ..parallel.mesh import mesh_desc
        from .resilience import counters

        st = resharder.stats
        counters.record(
            "ckpt_reshard",
            f"{npz_path}: {st['arrays']} array(s) "
            f"({st['bytes']} B) placed onto mesh {mesh_desc(mesh)} "
            f"[{st['resharded']} resharded, {st['host_staged']} "
            f"host-staged, {st['spec_fallback']} spec-fallback]",
        )
        if st["crosshost"]:
            counters.record(
                "ckpt_reshard_crosshost",
                f"{npz_path}: {st['crosshost']} array(s) pulled by "
                f"destination hosts across a process-spanning mesh "
                f"{mesh_desc(mesh)}",
            )
        _logger.info(
            "loaded checkpoint %s resharded onto mesh %s (%d arrays, "
            "%d host-staged)",
            npz_path, mesh_desc(mesh), st["arrays"], st["host_staged"],
        )
    else:
        _logger.info("loaded checkpoint %s (%d arrays)", npz_path, len(arrays))
    return obj


def load_numerics_baseline(path: str) -> dict | None:
    """The fit-time output-distribution sketch persisted by
    ``save_pipeline(numerics_baseline=...)``, or None (absent entry,
    pre-observatory artifact, unreadable manifest).  Advisory metadata for
    the drift monitor — this NEVER raises: a missing baseline means an
    unmonitored engine, not a failed load (``load_pipeline`` holds the
    manifest to the strict bar)."""
    _, manifest_path = checkpoint_paths(path)
    try:
        with open(manifest_path) as fh:
            manifest = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        _logger.warning(
            "numerics baseline unreadable from %s (%s)", manifest_path, e
        )
        return None
    baseline = manifest.get("numerics_baseline")
    return dict(baseline) if isinstance(baseline, dict) else None


def load_or_fit(path: str | None, est, *fit_args, save: bool = True, **fit_kwargs):
    """The GMM/PCA CSV-flag pattern generalized: reload the fitted artifact
    at ``path`` if present, else fit and (by default) save it there.

    ``est`` is an Estimator/LabelEstimator (``.fit`` is called with the
    remaining args) or any callable returning the fitted object.  With
    ``path=None`` this is just the fit."""
    if path and checkpoint_exists(path):
        _logger.info("load_or_fit: restoring fitted state from %s", path)
        return load_pipeline(path)
    fit = est.fit if hasattr(est, "fit") else est
    fitted = fit(*fit_args, **fit_kwargs)
    if path and save:
        save_pipeline(path, fitted)
    return fitted
