"""Closed-loop model lifecycle: drift-triggered warm refit with
zero-downtime hot-swap.

Every earlier subsystem leaves the loop OPEN at the point production
cares about: the numerics observatory detects a served model going stale
(``serve_output_drift``, PR 15) and the router can add/retire/re-anchor
engines with zero request loss (PR 12/16), but nothing ever *acts* on
drift — a stale model pages and keeps answering wrong.  The TensorFlow
production papers (PAPERS.md: 1605.08695, tf.data 2101.12127) frame the
fix: detect → retrain → validate → swap must be an automated subsystem,
not an operator runbook.  :class:`LifecycleController` is that subsystem.

The healing cycle (one stitched trace: the drift instant, the refit and
validate spans, the swap span — all under one ``lifecycle.cycle`` span)::

      IDLE ──trip──▶ REFITTING ──▶ VALIDATING ──▶ SWAPPING ──▶ COOLDOWN ──▶ IDLE
                         │              │                         ▲
                         │ refit_failed │ refit_rejected          │
                         └──────────────┴─────────────────────────┘

* **Trip** — a watcher thread polls the signals the repo already
  exports: the ``serve_output_drift`` fault counter, ``cond_warn``
  conditioning pages, SLO error-budget burn (``telemetry.slo_summaries``)
  — plus the operator knob :meth:`LifecycleController.request_refit`.
  The controller's state is a ``/statusz`` section (``lifecycle:<label>``).
* **Warm refit** — the per-block BCD machinery (``fit(checkpoint=)``
  forces the stepwise path, so a refit interrupted mid-solve resumes
  from its own block checkpoint via ``resume_from``) re-solves the MODEL
  over fresh streamed data without refitting featurizers: features come
  through :func:`featurized_training_set`, keyed by the fitted
  featurizer's digest (``core.snapshot.featurizer_digest``), so an
  unchanged featurizer streams features straight from the committed
  snapshot (zero featurizer recompute) while a CHANGED featurizer moves
  the key and forces a cold featurize pass — counted ``refit_cold_fit``,
  never a silent reuse of stale features.
* **Validation** — the invariant: **no request is ever answered by an
  unvalidated or half-swapped model.**  The candidate must be all-finite
  (``resilience.assert_all_finite``), must pass the serving parity check
  (``ServingEngine.warmup``), and must beat the incumbent on a fresh
  holdout (the quality gate) — a candidate that is WORSE is refused,
  counted ``refit_rejected`` (postmortem-linked), and the incumbent
  keeps serving.  A fresh numerics baseline (the candidate's own output
  sketch over the holdout mix) is persisted with the checkpoint
  (``save_pipeline(numerics_baseline=)``).
* **Hot-swap** — checkpoint → :func:`~.serve.load_engine` →
  :meth:`~.frontend.ShapeRouter.replace_engine` (ONE routing-table
  update: a request arriving at any instant routes to the incumbent or
  the successor, never a transient ``RetryLater``; the incumbent drains
  after it is unrouted, zero request loss).  Drift monitors re-arm on
  the NEW baseline (``DriftMonitor.rearm``, counted ``drift_rearmed``)
  so validation/warmup answers never contaminate the post-swap judgment.
* **Cooldown/debounce** — ``KEYSTONE_REFIT_COOLDOWN_S`` after every
  cycle (landed, rejected, or failed): a flapping drift signal cannot
  thrash compile/fit capacity — a trip inside the window is suppressed,
  counted ``refit_suppressed``.

Typed degradation, never a gap: a refit that dies (OOM materializing the
fresh features, a solver fault) is counted ``refit_failed``; a rejected
candidate is counted ``refit_rejected``; both leave the incumbent
serving and the cycle record says why.  A landed swap is counted
``lifecycle_refit``.  All three are postmortem families
(``telemetry.POSTMORTEM_KINDS``).

Env knobs (README ``KEYSTONE_*`` table):

* ``KEYSTONE_REFIT_COOLDOWN_S`` — refit debounce window (default 300).
* ``KEYSTONE_REFIT_POLL_S`` — watcher poll period (default 1.0).
* ``KEYSTONE_REFIT_MARGIN`` — quality slack: the candidate is accepted
  when ``quality >= incumbent_quality - margin`` (default 0.0).
* ``KEYSTONE_REFIT_BURN`` — SLO burn-rate trip threshold (default 0 =
  burn does not trip refits).
"""

from __future__ import annotations

import dataclasses
import logging
import math
import os
import threading
import time
from typing import Any, Callable

import numpy as np

from . import numerics as knum
from . import telemetry
from . import trace
from .resilience import assert_all_finite, counters

_logger = logging.getLogger("keystone_tpu.lifecycle")

COOLDOWN_ENV = "KEYSTONE_REFIT_COOLDOWN_S"
POLL_ENV = "KEYSTONE_REFIT_POLL_S"
MARGIN_ENV = "KEYSTONE_REFIT_MARGIN"
BURN_ENV = "KEYSTONE_REFIT_BURN"

DEFAULT_COOLDOWN_S = 300.0
DEFAULT_POLL_S = 1.0

#: Lifecycle states, in cycle order.  COOLDOWN decays to IDLE lazily
#: (the state property consults the clock) — no timer thread needed.
STATES = ("IDLE", "REFITTING", "VALIDATING", "SWAPPING", "COOLDOWN")

#: The fault-counter signals the watcher trips on (process-global deltas
#: since the controller armed / last acted).
WATCHED_COUNTERS = ("serve_output_drift", "cond_warn")


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        _logger.warning("ignoring malformed %s=%r", name, raw)
        return default


@dataclasses.dataclass
class LifecycleConfig:
    """Knobs for one controller (env-seeded via :meth:`from_env`)."""

    cooldown_s: float = DEFAULT_COOLDOWN_S
    poll_interval_s: float = DEFAULT_POLL_S
    #: candidate accepted when quality >= incumbent - margin
    quality_margin: float = 0.0
    #: SLO burn-rate that trips a refit; 0 disables the burn signal
    burn_threshold: float = 0.0
    #: watch the cond_warn counter (ill-conditioned refit solves page
    #: the same loop the drift counter does)
    watch_cond: bool = True

    @classmethod
    def from_env(cls, **overrides) -> "LifecycleConfig":
        cfg = cls(
            cooldown_s=_env_float(COOLDOWN_ENV, DEFAULT_COOLDOWN_S),
            poll_interval_s=_env_float(POLL_ENV, DEFAULT_POLL_S),
            quality_margin=_env_float(MARGIN_ENV, 0.0),
            burn_threshold=_env_float(BURN_ENV, 0.0),
        )
        for k, v in overrides.items():
            setattr(cfg, k, v)
        return cfg


def featurized_training_set(
    root: str,
    *,
    tar_path: str,
    featurizer: Any,
    compute: Callable[[], tuple],
    batch_size: int = 256,
    extra: str | None = None,
) -> tuple[np.ndarray, np.ndarray, dict]:
    """Featurizer-digest-keyed training set for warm refits.

    The snapshot key folds in :func:`~.snapshot.featurizer_digest` of the
    fitted ``featurizer``: an unchanged featurizer HITS the committed
    featurized snapshot and the ``(features, labels)`` stream straight
    from the shards — zero featurizer recompute, ``compute`` never called.
    A changed featurizer (or input tar) moves the key, classifies the old
    snapshot STALE (counted ``snapshot_stale``), and forces the cold
    ``compute()`` pass, whose output is committed for the next refit.

    ``compute``: ``() -> (features [n, D], labels [n, k])`` — the live
    featurize pass.  Labels ride as the trailing ``label_cols`` columns
    of each shard's payload (one artifact, one atomic commit; recorded in
    the manifest meta so the reader knows where to split).

    Returns ``(features f32, labels f32, info)`` with ``info`` carrying
    the digest, the snapshot key, and ``source`` ("snapshot" — warm — or
    "computed").
    """
    from . import snapshot as ksnap

    digest = ksnap.featurizer_digest(featurizer)
    key = ksnap.snapshot_key(
        tar_path,
        batch_size=batch_size,
        mode="featurized",
        extra=extra,
        featurizer=digest,
    )
    info: dict = {"digest": digest, "key": key, "stale": False}
    snap, reason = ksnap.lookup(root, key, tar_path=tar_path, mode="featurized")
    if reason == "stale":
        info["stale"] = True
        counters.record(
            "snapshot_stale",
            f"{root}: featurized refit snapshot keyed differently "
            "(featurizer or input moved) — cold featurize pass",
        )
    if snap is not None:
        try:
            label_cols = int(snap.manifest.get("meta", {})["label_cols"])
            parts = []
            for _entry, arrays in snap.iter_chunks():
                parts.append(np.asarray(arrays["payload"], np.float32))
            packed = np.concatenate(parts, axis=0)
            info["source"] = "snapshot"
            return packed[:, :-label_cols], packed[:, -label_cols:], info
        except (KeyError, ValueError, ksnap.SnapshotCorrupt) as e:
            counters.record(
                "snapshot_fallback",
                f"{snap.path}: {e} — recomputing refit features live",
            )
    feats, labels = compute()
    feats = np.asarray(feats, np.float32)
    labels = np.asarray(labels, np.float32)
    if labels.ndim == 1:
        labels = labels[:, None]
    packed = np.concatenate([feats, labels], axis=1)
    info["source"] = "computed"
    try:
        writer = ksnap.SnapshotWriter(
            root,
            key,
            mode="featurized",
            meta={
                "tar": ksnap.tar_identity(tar_path),
                "label_cols": int(labels.shape[1]),
            },
        )
        for i in range(0, packed.shape[0], batch_size):
            chunk = packed[i : i + batch_size]
            idx = np.arange(i, i + chunk.shape[0], dtype=np.int64)
            writer.add_chunk(
                i // batch_size, idx, [str(j) for j in idx.tolist()], chunk
            )
        writer.commit()
    except (OSError, ksnap.SnapshotError) as e:
        # The cache is an optimization — a full disk drops the writer,
        # not the refit (same contract as the ingest tee).
        counters.record(
            "snapshot_write_failed",
            f"cannot commit featurized refit snapshot: {e}",
        )
    return feats, labels, info


class LifecycleController:
    """The closed loop for ONE served pipeline behind a
    :class:`~.frontend.ShapeRouter` (see the module docstring for the
    cycle).  The deployment supplies the model-specific pieces as plain
    callables — the controller owns the state machine, the gates, the
    counters, and the swap:

    ``featurizer``
        The fitted featurizer object (or a zero-arg callable returning
        it) — digest-checked every cycle; a changed digest is counted
        ``refit_cold_fit`` and the snapshot keying recomputes features.
    ``fetch``
        ``(digest: str) -> (features, labels)`` — fresh featurized
        training data for the refit (route it through
        :func:`featurized_training_set` to get the warm snapshot path).
    ``estimator``
        ``() -> BlockLeastSquaresEstimator`` — a fresh solver per cycle.
    ``assemble``
        ``(model) -> pipe`` — the full servable pipeline
        (featurizer ∘ model), checkpointable by ``core.checkpoint``.
    ``holdout``
        ``() -> (x, y)`` — a request-space holdout batch drawn from the
        CURRENT mix (the quality gate and the fresh numerics baseline
        both judge on it).
    ``quality``
        ``(predict, x, y) -> float`` — higher is better; ``predict`` is
        a batch callable (the candidate pipe, or the incumbent engine's
        offline oracle).
    ``example``
        One request row (no batch axis) — fixes the routed shape and
        feeds ``load_engine``.
    """

    def __init__(
        self,
        router,
        *,
        workdir: str,
        featurizer: Any,
        fetch: Callable[[str], tuple],
        estimator: Callable[[], Any],
        assemble: Callable[[Any], Any],
        holdout: Callable[[], tuple],
        quality: Callable[[Callable, Any, Any], float],
        example,
        label: str = "lifecycle",
        serve_config=None,
        config: LifecycleConfig | None = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self._router = router
        self._workdir = workdir
        os.makedirs(workdir, exist_ok=True)
        # A fitted featurizer is usually itself callable (a Transformer),
        # so "callable" cannot distinguish the object from a provider:
        # only plain functions/methods/partials are treated as zero-arg
        # providers returning the CURRENT featurizer.
        import functools
        import types

        if isinstance(
            featurizer,
            (types.FunctionType, types.MethodType, functools.partial),
        ):
            self._featurizer = featurizer
        else:
            self._featurizer = lambda: featurizer
        self._fetch = fetch
        self._estimator = estimator
        self._assemble = assemble
        self._holdout = holdout
        self._quality = quality
        self._example = example
        self._shape = tuple(int(d) for d in np.asarray(example).shape)
        self.label = label
        self._serve_config = serve_config
        self.config = config or LifecycleConfig.from_env()
        self._clock = clock
        self.generation = 0
        self._state = "IDLE"
        self._state_lock = threading.Lock()
        self._cycle_lock = threading.Lock()
        self._cooldown_until = -math.inf
        self._last_cycle: dict | None = None
        self._armed_digest: str | None = None
        self._stop = threading.Event()
        self._watcher: threading.Thread | None = None
        self._refit_requested = threading.Event()
        self._request_reason = "operator"
        #: process-global counter baselines the watcher diffs against —
        #: re-based after every cycle so the trip that CAUSED a refit
        #: cannot immediately re-trip it.
        self._sig_base = {k: counters.get(k) for k in WATCHED_COUNTERS}
        self._closed = False
        # The controller's live state is a /statusz section, same
        # identity-guarded contract as the router's.
        self._statusz_provider = self.record
        telemetry.register_statusz(f"lifecycle:{label}", self._statusz_provider)

    # -- state ----------------------------------------------------------------

    @property
    def state(self) -> str:
        """Current lifecycle state; COOLDOWN decays to IDLE when the
        debounce window has passed."""
        with self._state_lock:
            s = self._state
            if s == "COOLDOWN" and self._clock() >= self._cooldown_until:
                self._state = s = "IDLE"
            return s

    def _set_state(self, state: str) -> None:
        with self._state_lock:
            self._state = state
        trace.instant("lifecycle_state", label=self.label, state=state)

    def cooldown_remaining_s(self) -> float:
        return max(0.0, self._cooldown_until - self._clock())

    # -- trip signals ---------------------------------------------------------

    def request_refit(self, reason: str = "operator") -> dict | None:
        """The operator knob: ask for a refit.  With the watcher running
        the request is picked up on its next poll (returns None);
        without it the cycle runs synchronously and returns its record.
        Cooldown still applies — an operator cannot storm the loop
        either (suppressions are counted)."""
        self._request_reason = reason
        self._refit_requested.set()
        if self._watcher is not None and self._watcher.is_alive():
            return None
        return self.run_refit(reason=reason)

    def check_signals(self) -> str | None:
        """One watcher poll: the trip reason, or None.  Operator requests
        win; then counted drift, conditioning pages, SLO burn."""
        if self._refit_requested.is_set():
            self._refit_requested.clear()
            return self._request_reason
        for kind in WATCHED_COUNTERS:
            if kind == "cond_warn" and not self.config.watch_cond:
                continue
            now = counters.get(kind)
            if now > self._sig_base.get(kind, 0):
                self._sig_base[kind] = now
                return kind
        if self.config.burn_threshold > 0:
            for label, s in telemetry.slo_summaries().items():
                burn = (s.get("window") or {}).get(
                    "burn_rate", s.get("burn_rate", 0.0)
                )
                if burn is not None and burn >= self.config.burn_threshold:
                    return f"slo_burn:{label}"
        return None

    def start(self) -> None:
        """Start the background watcher (idempotent)."""
        if self._watcher is not None and self._watcher.is_alive():
            return
        self._stop.clear()
        self._watcher = threading.Thread(
            target=self._watch_loop, name=f"keystone-lifecycle-{self.label}",
            daemon=True,
        )
        self._watcher.start()

    def _watch_loop(self) -> None:
        while not self._stop.is_set():
            try:
                reason = self.check_signals()
                if reason is not None:
                    self.run_refit(reason=reason)
            except Exception:  # noqa: BLE001 — the watcher must not die
                _logger.exception("lifecycle %s: watcher poll failed", self.label)
            self._stop.wait(self.config.poll_interval_s)

    def close(self) -> None:
        """Stop the watcher and unregister the statusz section
        (idempotent; the router and its engines are NOT closed — they
        outlive the controller)."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        if self._watcher is not None:
            self._watcher.join(timeout=10.0)
        telemetry.unregister_statusz(
            f"lifecycle:{self.label}", self._statusz_provider
        )

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- the healing cycle ----------------------------------------------------

    def run_refit(self, *, reason: str = "operator") -> dict:
        """Run one full cycle synchronously and return its record
        (``outcome`` ∈ swapped / rejected / refit_failed / suppressed).
        Serialized: a trip while a cycle is mid-flight is a suppression,
        not a queue — the running cycle already answers the signal."""
        if not self._cycle_lock.acquire(blocking=False):
            counters.record(
                "refit_suppressed",
                f"lifecycle:{self.label}: refit requested ({reason}) while "
                "a cycle is mid-flight — suppressed",
            )
            return {"outcome": "suppressed", "why": "cycle in flight",
                    "reason": reason}
        try:
            now = self._clock()
            if now < self._cooldown_until:
                counters.record(
                    "refit_suppressed",
                    f"lifecycle:{self.label}: refit requested ({reason}) "
                    f"inside the {self.config.cooldown_s:g}s cooldown "
                    f"({self._cooldown_until - now:.1f}s remaining) — "
                    "storm guard",
                )
                rec = {"outcome": "suppressed", "why": "cooldown",
                       "reason": reason,
                       "cooldown_remaining_s":
                           round(self._cooldown_until - now, 3)}
                self._last_cycle = rec
                return rec
            return self._run_cycle(reason)
        finally:
            self._cycle_lock.release()

    def _finish(self, rec: dict) -> dict:
        """Arm the cooldown (EVERY terminal outcome debounces — a failing
        refit must not retry-storm either) and park in COOLDOWN."""
        self._cooldown_until = self._clock() + self.config.cooldown_s
        self._set_state("COOLDOWN")
        self._last_cycle = rec
        return rec

    def _run_cycle(self, reason: str) -> dict:
        self.generation += 1
        gen = self.generation
        t0 = time.perf_counter()
        rec: dict = {"generation": gen, "reason": reason}
        with trace.span(
            "lifecycle.cycle", cat="lifecycle", label=self.label,
            generation=gen, reason=reason,
        ):
            trace.instant(
                "lifecycle_trip", label=self.label, kind=reason,
                generation=gen,
            )
            _logger.info(
                "lifecycle %s: cycle g%d tripped (%s)", self.label, gen, reason
            )
            # ---- REFITTING ---------------------------------------------------
            self._set_state("REFITTING")
            t_refit = time.perf_counter()
            try:
                with trace.span(
                    "lifecycle.refit", cat="lifecycle", generation=gen,
                ):
                    import jax.numpy as jnp

                    digest = _featurizer_digest(self._featurizer())
                    cold = (
                        self._armed_digest is not None
                        and digest != self._armed_digest
                    )
                    rec["cold_fit"] = cold
                    if cold:
                        counters.record(
                            "refit_cold_fit",
                            f"lifecycle:{self.label}: featurizer digest "
                            "moved since the incumbent fit — warm start "
                            "invalid, cold featurize pass forced",
                        )
                    feats, labels = self._fetch(digest)
                    est = self._estimator()
                    # checkpoint= forces the stepwise per-block path, so
                    # a preempted refit resumes from its own block
                    # checkpoint (the warm-start substrate); the stepwise
                    # math is bit-identical to the fused solve.
                    ckpt = None
                    if getattr(est, "mesh", None) is None:
                        ckpt = os.path.join(self._workdir, f"g{gen:04d}_bcd")
                    model = est.fit(
                        jnp.asarray(feats), jnp.asarray(labels),
                        checkpoint=ckpt,
                    )
                    pipe = self._assemble(model)
                    self._armed_digest = digest
            except Exception as e:  # noqa: BLE001 — typed degrade, never a gap
                rec.update(self._degrade("refit", e, gen))
                rec["refit_wall_s"] = round(time.perf_counter() - t_refit, 6)
                rec["total_wall_s"] = round(time.perf_counter() - t0, 6)
                return self._finish(rec)
            rec["refit_wall_s"] = round(time.perf_counter() - t_refit, 6)
            # ---- VALIDATING --------------------------------------------------
            self._set_state("VALIDATING")
            t_val = time.perf_counter()
            try:
                with trace.span(
                    "lifecycle.validate", cat="lifecycle", generation=gen,
                ):
                    import jax.numpy as jnp

                    try:
                        assert_all_finite(model, f"refit candidate g{gen}")
                    except FloatingPointError as e:
                        rec["validate_wall_s"] = round(
                            time.perf_counter() - t_val, 6
                        )
                        return self._reject(rec, gen, t0, f"non-finite: {e}")
                    hx, hy = self._holdout()
                    cand_q = float(self._quality(pipe, hx, hy))
                    inc_q = None
                    incumbent = self._incumbent_engine()
                    if incumbent is not None:
                        inc_q = float(self._quality(incumbent.offline, hx, hy))
                    rec["quality"] = {"candidate": cand_q, "incumbent": inc_q}
                    if not math.isfinite(cand_q) or (
                        inc_q is not None
                        and cand_q < inc_q - self.config.quality_margin
                    ):
                        rec["validate_wall_s"] = round(
                            time.perf_counter() - t_val, 6
                        )
                        return self._reject(
                            rec, gen, t0,
                            f"holdout quality {cand_q:.6g} vs incumbent "
                            f"{inc_q if inc_q is None else round(inc_q, 6)} "
                            f"(margin {self.config.quality_margin:g})",
                        )
                    # The candidate's OWN output sketch over the current
                    # mix: the fresh baseline the swapped engine re-arms
                    # on (and save_pipeline persists).
                    baseline = knum.OutputSketch.for_outputs(
                        np.asarray(pipe(jnp.asarray(hx)))
                    ).record()
            except Exception as e:  # noqa: BLE001
                rec.update(self._degrade("validate", e, gen))
                rec["validate_wall_s"] = round(time.perf_counter() - t_val, 6)
                rec["total_wall_s"] = round(time.perf_counter() - t0, 6)
                return self._finish(rec)
            rec["validate_wall_s"] = round(time.perf_counter() - t_val, 6)
            # ---- SWAPPING ----------------------------------------------------
            self._set_state("SWAPPING")
            t_swap = time.perf_counter()
            try:
                with trace.span(
                    "lifecycle.swap", cat="lifecycle", generation=gen,
                ):
                    from .checkpoint import save_pipeline
                    from .serve import load_engine

                    stem = save_pipeline(
                        os.path.join(self._workdir, f"g{gen:04d}"),
                        pipe,
                        numerics_baseline=baseline,
                    )
                    rec["checkpoint"] = stem
                    engine, cold_rec = load_engine(
                        stem,
                        self._example,
                        config=self._serve_config,
                        label=f"{self.label}@g{gen}",
                    )
                    rec["cold_start"] = cold_rec
                    if not engine.parity_ok:
                        return self._reject(
                            rec, gen, t0,
                            "candidate engine failed the bucket parity "
                            "check — served answers would not be "
                            "bit-equal to the refit pipeline",
                        )
                    self._router.replace_engine(
                        engine,
                        why=f"lifecycle refit g{gen} ({reason})",
                    )
                    # Re-arm on the candidate's baseline from the swap
                    # instant (counted drift_rearmed): warmup/validation
                    # answers must not contaminate the live window.
                    engine.rearm_drift_baseline(baseline)
                    rec["engine_label"] = engine.label
            except Exception as e:  # noqa: BLE001
                rec.update(self._degrade("swap", e, gen))
                rec["swap_wall_s"] = round(time.perf_counter() - t_swap, 6)
                rec["total_wall_s"] = round(time.perf_counter() - t0, 6)
                return self._finish(rec)
            rec["swap_wall_s"] = round(time.perf_counter() - t_swap, 6)
            rec["total_wall_s"] = round(time.perf_counter() - t0, 6)
            rec["outcome"] = "swapped"
            # The trip that caused this cycle must not immediately
            # re-trip the next one.
            self._sig_base = {k: counters.get(k) for k in WATCHED_COUNTERS}
            counters.record(
                "lifecycle_refit",
                f"lifecycle:{self.label}: refit g{gen} landed ({reason}) — "
                f"refit {rec['refit_wall_s']:.3f}s, validate "
                f"{rec['validate_wall_s']:.3f}s, swap "
                f"{rec['swap_wall_s']:.3f}s; engine {rec['engine_label']} "
                "serving, drift re-armed on the fresh baseline",
            )
            _logger.info(
                "lifecycle %s: cycle g%d swapped in %.3fs",
                self.label, gen, rec["total_wall_s"],
            )
            return self._finish(rec)

    def _reject(self, rec: dict, gen: int, t0: float, why: str) -> dict:
        """The no-unvalidated-model invariant firing: the candidate is
        refused, the incumbent keeps serving, counted + postmortem."""
        rec["outcome"] = "rejected"
        rec["why"] = why
        rec["total_wall_s"] = round(time.perf_counter() - t0, 6)
        counters.record(
            "refit_rejected",
            f"lifecycle:{self.label}: refit candidate g{gen} REJECTED "
            f"({why}) — incumbent keeps serving",
        )
        return self._finish(rec)

    def _degrade(self, phase: str, e: Exception, gen: int) -> dict:
        """A cycle dying mid-flight is typed + counted, never a service
        gap: the router was not touched (or, in the swap phase, the
        atomic replace either landed whole or not at all) — the incumbent
        keeps serving."""
        counters.record(
            "refit_failed",
            f"lifecycle:{self.label}: refit cycle g{gen} died in {phase} "
            f"({type(e).__name__}: {e}) — incumbent keeps serving",
        )
        _logger.warning(
            "lifecycle %s: cycle g%d failed in %s: %s",
            self.label, gen, phase, e,
        )
        return {
            "outcome": "refit_failed",
            "phase": phase,
            "error_type": type(e).__name__,
            "error": str(e)[:300],
        }

    def _incumbent_engine(self):
        from .frontend import NoRouteForShape

        try:
            return self._router.server_for(self._shape).engine
        except NoRouteForShape:
            return None

    # -- records --------------------------------------------------------------

    def record(self) -> dict:
        """JSON-able controller state (the ``lifecycle:<label>``
        ``/statusz`` section; also what the bench drills embed)."""
        return {
            "label": self.label,
            "state": self.state,
            "generation": self.generation,
            "shape": list(self._shape),
            "cooldown_s": self.config.cooldown_s,
            "cooldown_remaining_s": round(self.cooldown_remaining_s(), 3),
            "watching": bool(self._watcher is not None
                             and self._watcher.is_alive()),
            "signals": {
                k: counters.get(k) - self._sig_base.get(k, 0)
                for k in WATCHED_COUNTERS
            },
            "last_cycle": self._last_cycle,
        }


def _featurizer_digest(obj) -> str:
    from . import snapshot as ksnap

    return ksnap.featurizer_digest(obj)
