"""Socket wire protocol for the serving front-end: length-prefixed
numpy-native framing, a threaded accept loop, per-client in-flight
windows, and explicit backpressure.

``core.frontend``'s :class:`~.frontend.ShapeRouter` (and ``core.serve``'s
:class:`~.serve.Server`) are in-process APIs; this module makes them
WIRE-VISIBLE — the TensorFlow-paper bar for "training framework becomes
production infrastructure": inference as a first-class network service.

**Frame layout** (everything big-endian, no external serializer — numpy's
own dtype strings and raw C-order bytes are the only encoding):

.. code-block:: text

    frame    := u32 payload_len | payload          (payload_len <= max frame)
    payload  := u8 version (=1) | u8 type | u64 request_id | body
    type     := 1 REQUEST | 2 RESPONSE | 3 ERROR | 4 RETRY_AFTER
                | 5 PING | 6 PONG
    array    := u8 ndim | u16 dtype_len | dtype_str (numpy .str, e.g "<f4")
                | ndim * u32 dim | raw C-order bytes       (REQUEST/RESPONSE)
    error    := u16 etype_len | etype utf-8 | u32 msg_len | msg utf-8
    retry    := f64 retry_after_s | u32 msg_len | msg utf-8

**Server** (:class:`WireServer`) — a threaded accept loop
(``KEYSTONE_WIRE_PORT``; ``0`` binds an ephemeral port) with one reader +
one responder thread per connection, so a slow-loris client trickling a
partial frame parks ITS reader on its own buffer and stalls nobody — the
accept loop keeps accepting and other connections keep answering.
Fairness and backpressure are explicit:

* every connection gets a bounded in-flight window
  (``KEYSTONE_WIRE_MAX_INFLIGHT``): requests beyond it answer a
  RETRY_AFTER frame instead of queueing unboundedly — one flooding client
  cannot monopolize the batcher;
* a typed :class:`~.frontend.RetryLater` from the router (shape not warm,
  admission out of headroom) maps 1:1 onto RETRY_AFTER with the router's
  own retry hint; ``MalformedRequest`` / ``NoRouteForShape`` /
  ``ServingUnavailable`` map onto ERROR frames carrying the error type —
  the in-process typed-failure taxonomy survives the wire;
* a client that disconnects MID-BATCH (in-flight requests pending) is
  counted ``wire_client_disconnect``; its submitted requests still ride
  their micro-batches to completion (the batcher neither cancels nor
  poisons batchmates) and the responder discards the unsendable answers.

Request ids ride the trace end to end: each REQUEST's wire id is tied to
the serve-side ``request_id`` by a ``wire.request`` instant, so the
existing per-request ``serve.*`` spans correlate with the connection that
carried them.

**Client** (:class:`WireClient`) — the reference client:
``predict``/``predict_many`` absorb RETRY_AFTER honestly (sleep the hint,
resubmit), surface ERROR frames as typed :class:`WireRemoteError`, and
pipeline a bounded window of outstanding requests.
``tools/serve_client.py`` is the CLI face; ``tools/serve_bench.py
--wire`` drives real sockets from separate client processes.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import socket
import struct
import threading
import time
from collections import deque

import numpy as np

from . import trace
from .frontend import RetryLater, _env_pos_int
from .resilience import counters
from .serve import ServeError, ServingUnavailable

_logger = logging.getLogger("keystone_tpu.wire")

PORT_ENV = "KEYSTONE_WIRE_PORT"
MAX_INFLIGHT_ENV = "KEYSTONE_WIRE_MAX_INFLIGHT"
MAX_FRAME_MB_ENV = "KEYSTONE_WIRE_MAX_FRAME_MB"

WIRE_VERSION = 1

T_REQUEST = 1
T_RESPONSE = 2
T_ERROR = 3
T_RETRY_AFTER = 4
T_PING = 5
T_PONG = 6
#: clock-offset handshake (ISSUE 14 cross-process stitching): the client
#: sends its trace clock (``trace.now_us``), the server echoes it with its
#: OWN trace clock appended — offset = server - (client + rtt/2).
T_CLOCK = 7
#: REQUEST with a trace-context prefix (u64 client span id before the
#: array body): the server's ``wire.request`` instant records the
#: client-side span the request rode in, so ``trace_view --stitch`` can
#: join the two processes' timelines by more than the rid alone.
T_REQUEST_TRACED = 8
#: Fleet observability (ISSUE 20, core.fleetobs): the collector asks a
#: member for its process-local observability surface.  The reply echoes
#: the frame type with a JSON body (utf-8) — the member's registry
#: snapshot + statusz + raw histogram sample windows (T_OBS_SNAPSHOT) or
#: its flight-recorder ring (T_OBS_FLIGHT), each stamped with the
#: member's ``trace.now_us`` so the collector can clock-align it via the
#: T_CLOCK offset handshake.  Old servers answer the unknown type with an
#: ERROR frame — a collector scraping a pre-obs member degrades, it does
#: not die.
T_OBS_SNAPSHOT = 9
T_OBS_FLIGHT = 10

_LEN = struct.Struct("!I")
_HEAD = struct.Struct("!BBQ")  # version, type, request_id
_NDIM = struct.Struct("!B")
_U16 = struct.Struct("!H")
_DIM = struct.Struct("!I")
_RETRY = struct.Struct("!d")
_CLOCK = struct.Struct("!d")  # one trace-clock sample (us)
_CLOCK2 = struct.Struct("!dd")  # client clock echoed + server clock
_SPAN = struct.Struct("!Q")  # trace-context prefix: client span id

DEFAULT_MAX_INFLIGHT = 32
DEFAULT_MAX_FRAME_MB = 64

#: Blocking waits poll at this period so stop flags are always observed
#: (the ingest/serve discipline, applied to sockets).
_POLL_SECONDS = 0.05


class WireError(ServeError):
    """Base of the wire tier's typed failures."""


class WireProtocolError(WireError):
    """A frame that violates the protocol: bad version, runt/oversized
    frame, or an array body whose declared shape/dtype does not match its
    bytes.  The server answers an ERROR frame and closes the connection —
    a protocol violator cannot be trusted with a parser state machine."""


class WireRemoteError(WireError):
    """Client-side surface of a server ERROR frame: carries the remote
    typed error's name so the in-process taxonomy survives the wire."""

    def __init__(self, etype: str, message: str):
        super().__init__(f"{etype}: {message}")
        self.etype = etype
        self.remote_message = message


def max_frame_bytes() -> int:
    return _env_pos_int(MAX_FRAME_MB_ENV, DEFAULT_MAX_FRAME_MB) * 2**20


# -- framing ------------------------------------------------------------------


def encode_array(arr: np.ndarray) -> bytes:
    """numpy-native array body: dtype string + dims + raw C-order bytes."""
    arr = np.asarray(arr)
    if not arr.flags.c_contiguous:
        # (ascontiguousarray would also promote rank-0 to rank-1 — only
        # touch layouts that actually need the copy)
        arr = np.ascontiguousarray(arr)
    if arr.dtype.hasobject:
        raise WireProtocolError(
            f"dtype {arr.dtype} is not wire-encodable (object arrays have "
            "no defined byte layout)"
        )
    if arr.ndim > 255:
        raise WireProtocolError(f"rank {arr.ndim} exceeds the u8 ndim field")
    dt = arr.dtype.str.encode("ascii")
    parts = [_NDIM.pack(arr.ndim), _U16.pack(len(dt)), dt]
    parts.extend(_DIM.pack(int(d)) for d in arr.shape)
    parts.append(arr.tobytes())
    return b"".join(parts)


def decode_array(body) -> np.ndarray:
    """Inverse of :func:`encode_array`; every declared size is validated
    against the actual bytes before numpy touches them."""
    body = memoryview(body)
    try:
        (ndim,) = _NDIM.unpack_from(body, 0)
        (dt_len,) = _U16.unpack_from(body, 1)
        off = 3 + dt_len
        dt_str = bytes(body[3:off]).decode("ascii")
        dims = []
        for _ in range(ndim):
            (d,) = _DIM.unpack_from(body, off)
            dims.append(int(d))
            off += _DIM.size
    except (struct.error, UnicodeDecodeError) as e:
        raise WireProtocolError(f"truncated array header: {e}") from None
    try:
        dtype = np.dtype(dt_str)
    except TypeError as e:
        raise WireProtocolError(f"bad dtype string {dt_str!r}: {e}") from None
    if dtype.hasobject:
        raise WireProtocolError(f"dtype {dt_str!r} is not wire-decodable")
    expected = int(np.prod(dims, dtype=np.int64)) * dtype.itemsize if dims \
        else dtype.itemsize
    if len(body) - off != expected:
        raise WireProtocolError(
            f"array body holds {len(body) - off} bytes but shape "
            f"{tuple(dims)} dtype {dt_str} declares {expected}"
        )
    arr = np.frombuffer(body[off:], dtype=dtype)
    return arr.reshape(dims) if dims else arr.reshape(())


def _encode_str(s: str, width: struct.Struct) -> bytes:
    raw = s.encode("utf-8", errors="replace")
    return width.pack(len(raw)) + raw


def encode_frame(ftype: int, rid: int, body: bytes = b"") -> bytes:
    payload = _HEAD.pack(WIRE_VERSION, ftype, rid) + body
    return _LEN.pack(len(payload)) + payload


def encode_error(rid: int, etype: str, message: str) -> bytes:
    body = _encode_str(etype, _U16) + _encode_str(message[:2000], _LEN)
    return encode_frame(T_ERROR, rid, body)


def encode_retry_after(rid: int, seconds: float, message: str = "") -> bytes:
    body = _RETRY.pack(float(seconds)) + _encode_str(message[:2000], _LEN)
    return encode_frame(T_RETRY_AFTER, rid, body)


def decode_error(body) -> tuple[str, str]:
    body = memoryview(body)
    try:
        (et_len,) = _U16.unpack_from(body, 0)
        etype = bytes(body[2 : 2 + et_len]).decode("utf-8")
        (msg_len,) = _LEN.unpack_from(body, 2 + et_len)
        off = 2 + et_len + _LEN.size
        msg = bytes(body[off : off + msg_len]).decode("utf-8")
    except (struct.error, UnicodeDecodeError) as e:
        raise WireProtocolError(f"truncated error body: {e}") from None
    return etype, msg


def encode_clock(rid: int, t_client_us: float) -> bytes:
    """Client -> server clock-sync probe carrying the client trace clock."""
    return encode_frame(T_CLOCK, rid, _CLOCK.pack(float(t_client_us)))


def encode_clock_reply(
    rid: int, t_client_us: float, t_server_us: float
) -> bytes:
    """Server -> client clock-sync echo: the probe's clock + the server's."""
    return encode_frame(
        T_CLOCK, rid, _CLOCK2.pack(float(t_client_us), float(t_server_us))
    )


def decode_clock(body) -> float:
    try:
        (t,) = _CLOCK.unpack_from(memoryview(body), 0)
    except struct.error as e:
        raise WireProtocolError(f"truncated clock body: {e}") from None
    return t


def decode_clock_reply(body) -> tuple[float, float]:
    try:
        tc, ts = _CLOCK2.unpack_from(memoryview(body), 0)
    except struct.error as e:
        raise WireProtocolError(f"truncated clock reply: {e}") from None
    return tc, ts


def encode_traced_request(rid: int, client_span: int, arr) -> bytes:
    """REQUEST with the optional trace-context field: the client's span id
    rides ahead of the array body (old servers answer an ERROR frame for
    the unknown type — a traced client degrades to plain REQUESTs)."""
    return encode_frame(
        T_REQUEST_TRACED,
        rid,
        _SPAN.pack(int(client_span)) + encode_array(np.asarray(arr)),
    )


def split_trace_context(body) -> tuple[int, memoryview]:
    """``(client_span, array_body)`` of a T_REQUEST_TRACED payload."""
    body = memoryview(body)
    try:
        (span,) = _SPAN.unpack_from(body, 0)
    except struct.error as e:
        raise WireProtocolError(f"truncated trace context: {e}") from None
    return int(span), body[_SPAN.size:]


def encode_obs(ftype: int, rid: int, payload: dict) -> bytes:
    """Observability frame: ``payload`` as a JSON utf-8 body (the obs
    surface is nested/stringly — numpy framing buys nothing here)."""
    import json

    return encode_frame(
        ftype, rid, json.dumps(payload, separators=(",", ":")).encode("utf-8")
    )


def decode_obs(body) -> dict:
    import json

    try:
        doc = json.loads(bytes(memoryview(body)).decode("utf-8"))
    except (UnicodeDecodeError, ValueError) as e:
        raise WireProtocolError(f"bad obs body: {e}") from None
    if not isinstance(doc, dict):
        raise WireProtocolError(
            f"obs body is {type(doc).__name__}, expected an object"
        )
    return doc


def decode_retry_after(body) -> tuple[float, str]:
    body = memoryview(body)
    try:
        (seconds,) = _RETRY.unpack_from(body, 0)
        (msg_len,) = _LEN.unpack_from(body, _RETRY.size)
        off = _RETRY.size + _LEN.size
        msg = bytes(body[off : off + msg_len]).decode("utf-8")
    except (struct.error, UnicodeDecodeError) as e:
        raise WireProtocolError(f"truncated retry body: {e}") from None
    return seconds, msg


def extract_frame(buf: bytearray, max_bytes: int):
    """Pop one complete frame off ``buf`` (in place).  Returns
    ``(type, request_id, body_memoryview)`` or None when the buffer holds
    only a partial frame — the caller keeps reading.  Raises
    :class:`WireProtocolError` on a frame that can never become valid."""
    if len(buf) < _LEN.size:
        return None
    (plen,) = _LEN.unpack_from(buf, 0)
    if plen < _HEAD.size:
        raise WireProtocolError(f"runt frame ({plen} payload bytes)")
    if plen > max_bytes:
        raise WireProtocolError(
            f"frame of {plen} bytes exceeds the {max_bytes}-byte cap "
            f"({MAX_FRAME_MB_ENV})"
        )
    if len(buf) < _LEN.size + plen:
        return None
    payload = bytes(buf[_LEN.size : _LEN.size + plen])
    del buf[: _LEN.size + plen]
    version, ftype, rid = _HEAD.unpack_from(payload, 0)
    if version != WIRE_VERSION:
        raise WireProtocolError(
            f"wire version {version} != {WIRE_VERSION}"
        )
    return ftype, rid, memoryview(payload)[_HEAD.size :]


# -- the server ---------------------------------------------------------------


@dataclasses.dataclass
class WireStats:
    """Counters of one wire server's lifetime (bench/chaos artifact)."""

    connections: int = 0
    disconnects: int = 0  #: clean closes (no in-flight work at EOF)
    mid_batch_disconnects: int = 0  #: EOF with requests still in flight
    requests: int = 0
    responses: int = 0
    errors: int = 0  #: ERROR frames sent (typed failures crossed the wire)
    retry_after: int = 0  #: RETRY_AFTER frames sent (backpressure)
    protocol_errors: int = 0
    bytes_in: int = 0
    bytes_out: int = 0

    def record(self) -> dict:
        return dataclasses.asdict(self)


class _Conn:
    """One live client connection: its socket, its bounded in-flight
    window, and the FIFO of futures its responder thread answers."""

    __slots__ = (
        "cid", "sock", "addr", "open", "reader_done", "inflight", "queue",
        "cond", "wlock", "reader", "responder",
    )

    def __init__(self, cid: int, sock: socket.socket, addr):
        self.cid = cid
        self.sock = sock
        self.addr = addr
        self.open = True
        self.reader_done = False
        self.inflight = 0
        self.queue: deque = deque()  # (wire_rid, future, t_received)
        self.cond = threading.Condition()
        self.wlock = threading.Lock()
        self.reader: threading.Thread | None = None
        self.responder: threading.Thread | None = None


class WireServer:
    """Serve a :class:`~.frontend.ShapeRouter` (or a bare
    :class:`~.serve.Server` — anything with a typed ``submit``) over a
    listening socket.  Constructing binds and starts accepting; use as a
    context manager or call :meth:`close`.

    ``port=None`` reads ``KEYSTONE_WIRE_PORT`` (``0``/unset = ephemeral;
    the bound port is ``self.port``).  ``max_inflight`` is the per-client
    fairness window (``KEYSTONE_WIRE_MAX_INFLIGHT``)."""

    def __init__(
        self,
        target,
        host: str = "127.0.0.1",
        port: int | None = None,
        *,
        max_inflight: int | None = None,
        request_timeout_s: float = 60.0,
        retry_after_s: float = 0.05,
        label: str = "wire",
    ):
        if port is None:
            raw = os.environ.get(PORT_ENV, "").strip()
            port = int(raw) if raw else 0
        self.target = target
        self.label = label
        self.max_inflight = (
            max_inflight
            if max_inflight is not None
            else _env_pos_int(MAX_INFLIGHT_ENV, DEFAULT_MAX_INFLIGHT)
        )
        self.request_timeout_s = float(request_timeout_s)
        self.retry_after_s = float(retry_after_s)
        self._max_frame = max_frame_bytes()
        self.stats = WireStats()
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._conns: dict[int, _Conn] = {}
        self._next_cid = 0
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, int(port)))
        self._listener.listen(64)
        self._listener.settimeout(_POLL_SECONDS)
        self.host, self.port = self._listener.getsockname()[:2]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="keystone-wire-accept", daemon=True
        )
        self._accept_thread.start()
        _logger.info(
            "wire server %s listening on %s:%d (max_inflight %d/client)",
            label, self.host, self.port, self.max_inflight,
        )

    # -- accept loop ----------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break  # listener closed under us — shutdown
            sock.settimeout(_POLL_SECONDS)
            with self._lock:
                self._next_cid += 1
                conn = _Conn(self._next_cid, sock, addr)
                self._conns[conn.cid] = conn
                self.stats.connections += 1
                active = len(self._conns)
            trace.metrics.inc("wire_connections")
            trace.metrics.gauge("wire_active_connections", active)
            conn.reader = threading.Thread(
                target=self._reader_loop, args=(conn,),
                name=f"keystone-wire-reader-{conn.cid}", daemon=True,
            )
            conn.responder = threading.Thread(
                target=self._responder_loop, args=(conn,),
                name=f"keystone-wire-responder-{conn.cid}", daemon=True,
            )
            conn.reader.start()
            conn.responder.start()

    # -- per-connection reader ------------------------------------------------

    def _reader_loop(self, conn: _Conn) -> None:
        buf = bytearray()
        eof = False
        try:
            while conn.open and not self._stop.is_set():
                try:
                    frame = extract_frame(buf, self._max_frame)
                except WireProtocolError as e:
                    with self._lock:
                        self.stats.protocol_errors += 1
                    trace.metrics.inc("wire_protocol_errors")
                    self._send(conn, encode_error(
                        0, "WireProtocolError", str(e)
                    ))
                    break  # a protocol violator loses its connection
                if frame is not None:
                    self._dispatch(conn, *frame)
                    continue
                try:
                    chunk = conn.sock.recv(65536)
                except socket.timeout:
                    continue  # poll: re-check stop flags
                except (ConnectionError, OSError):
                    eof = True
                    break
                if not chunk:
                    eof = True
                    break
                with self._lock:
                    self.stats.bytes_in += len(chunk)
                buf.extend(chunk)
        finally:
            with conn.cond:
                conn.reader_done = True
                pending = conn.inflight > 0 or bool(conn.queue)
                conn.cond.notify_all()
            if self._stop.is_set():
                pass  # server shutdown, not a client behavior — no verdict
            elif eof and pending:
                # Mid-batch disconnect: the batcher still completes the
                # micro-batches these requests ride in (batchmates are
                # never poisoned); the responder discards the unsendable
                # answers.  Counted — an operator-visible fault.
                with self._lock:
                    self.stats.mid_batch_disconnects += 1
                counters.record(
                    "wire_client_disconnect",
                    f"wire:{self.label}: client {conn.addr} disconnected "
                    "with requests in flight — batch completes, answers "
                    "discarded",
                )
            elif eof:
                with self._lock:
                    self.stats.disconnects += 1

    def _dispatch(self, conn: _Conn, ftype: int, rid: int, body) -> None:
        if ftype == T_PING:
            self._send(conn, encode_frame(T_PONG, rid))
            return
        if ftype == T_CLOCK:
            # Clock-offset handshake (cross-process stitching): echo the
            # client's trace clock with ours appended — the client
            # estimates offset = server - (client + rtt/2) and records it
            # in its own trace so --stitch can align the two timelines.
            try:
                t_client = decode_clock(body)
            except WireProtocolError as e:
                self._send(conn, encode_error(rid, "WireProtocolError", str(e)))
                return
            self._send(
                conn, encode_clock_reply(rid, t_client, trace.now_us())
            )
            return
        if ftype in (T_OBS_SNAPSHOT, T_OBS_FLIGHT):
            # Fleet observability scrape (core.fleetobs): EVERY wire
            # server doubles as its process's obs agent — the collector
            # reuses the serving port it already knows.  A failing
            # payload build answers a typed ERROR frame; the serving
            # path is never touched.
            try:
                from . import fleetobs

                payload = fleetobs.agent_payload(
                    "flight" if ftype == T_OBS_FLIGHT else "snapshot"
                )
                self._send(conn, encode_obs(ftype, rid, payload))
            except Exception as e:  # noqa: BLE001 — typed delivery
                self._send(
                    conn, encode_error(rid, type(e).__name__, str(e))
                )
            return
        if ftype not in (T_REQUEST, T_REQUEST_TRACED):
            with self._lock:
                self.stats.protocol_errors += 1
            self._send(conn, encode_error(
                rid, "WireProtocolError",
                f"unexpected client frame type {ftype}",
            ))
            return
        t0 = time.perf_counter()
        with self._lock:
            self.stats.requests += 1
        trace.metrics.inc("wire_requests")
        client_span = None
        try:
            if ftype == T_REQUEST_TRACED:
                client_span, body = split_trace_context(body)
            arr = decode_array(body)
        except WireProtocolError as e:
            with self._lock:
                self.stats.errors += 1
            self._send(conn, encode_error(rid, "WireProtocolError", str(e)))
            return
        # Per-client fairness window: beyond it the client is pushed back
        # with RETRY_AFTER, never queued unboundedly — a flooder cannot
        # starve other connections out of the batcher.
        with conn.cond:
            if conn.inflight >= self.max_inflight:
                window_full = True
            else:
                window_full = False
                conn.inflight += 1
        if window_full:
            with self._lock:
                self.stats.retry_after += 1
            trace.metrics.inc("wire_retry_after")
            self._send(conn, encode_retry_after(
                rid, self.retry_after_s,
                f"in-flight window ({self.max_inflight}) full",
            ))
            return
        try:
            fut = self.target.submit(arr)
        except RetryLater as e:
            self._release(conn)
            with self._lock:
                self.stats.retry_after += 1
            trace.metrics.inc("wire_retry_after")
            self._send(conn, encode_retry_after(rid, e.retry_after_s, str(e)))
            return
        except Exception as e:  # noqa: BLE001 — typed delivery, never a hang
            # MalformedRequest / NoRouteForShape / ServingUnavailable and
            # any unexpected failure all cross the wire the same way: an
            # ERROR frame named after the exception type.
            self._release(conn)
            with self._lock:
                self.stats.errors += 1
            trace.metrics.inc("wire_errors")
            self._send(conn, encode_error(rid, type(e).__name__, str(e)))
            return
        # The wire id <-> serve id tie: every serve.* span of this request
        # correlates back to the connection that carried it (and, for a
        # traced client, to the CLIENT-side span it rode in).
        trace.instant(
            "wire.request", conn=conn.cid, wire_rid=rid,
            request_id=getattr(fut, "request_id", 0),
            **({"client_span": client_span} if client_span is not None else {}),
        )
        with conn.cond:
            conn.queue.append((rid, fut, t0))
            conn.cond.notify_all()

    def _release(self, conn: _Conn) -> None:
        with conn.cond:
            conn.inflight -= 1
            conn.cond.notify_all()

    # -- per-connection responder ---------------------------------------------

    def _responder_loop(self, conn: _Conn) -> None:
        try:
            self._respond_until_done(conn)
        finally:
            # The responder is the LAST writer on this connection: once it
            # returns (reader finished AND the answer queue drained) the
            # socket can be torn down — a protocol violator or EOF'd client
            # is actually disconnected, not parked until server close.
            self._drop_conn(conn)

    def _respond_until_done(self, conn: _Conn) -> None:
        while True:
            with conn.cond:
                while not conn.queue:
                    if conn.reader_done or self._stop.is_set():
                        return
                    conn.cond.wait(_POLL_SECONDS)
                rid, fut, t0 = conn.queue.popleft()
            try:
                value = self._await(fut)
            except BaseException as e:  # noqa: BLE001 — typed over the wire
                with self._lock:
                    self.stats.errors += 1
                trace.metrics.inc("wire_errors")
                self._send(conn, encode_error(
                    rid, type(e).__name__, str(e)
                ))
            else:
                ms = (time.perf_counter() - t0) * 1e3
                sent = self._send(
                    conn, encode_frame(T_RESPONSE, rid, encode_array(value))
                )
                if sent:
                    with self._lock:
                        self.stats.responses += 1
                    trace.metrics.inc("wire_responses")
                    trace.metrics.observe("wire_request_ms", ms)
                    trace.instant(
                        "wire.response", conn=conn.cid, wire_rid=rid,
                        ms=round(ms, 3),
                    )
            finally:
                self._release(conn)

    def _await(self, fut):
        """Wait out one future with the stop flag observed (a server
        shutting down must not leave a responder parked on a future the
        closing batcher is about to fail typed anyway)."""
        end = time.monotonic() + self.request_timeout_s
        while True:
            try:
                return fut.result(_POLL_SECONDS)
            except TimeoutError:
                if self._stop.is_set():
                    raise ServingUnavailable(
                        "wire server closing"
                    ) from None
                if time.monotonic() >= end:
                    raise TimeoutError(
                        f"request unanswered after {self.request_timeout_s}s"
                    ) from None

    # -- sends ----------------------------------------------------------------

    def _send(self, conn: _Conn, data: bytes, timeout_s: float = 30.0) -> bool:
        """Write one frame with the socket's short poll timeout survived:
        the 50ms settimeout that keeps recv responsive also governs send,
        and a client that merely PAUSES reading (full TCP receive buffer —
        e.g. one sleeping out a RETRY_AFTER hint) must get backpressure,
        not a dropped connection.  ``send`` (unlike ``sendall``) reports
        progress, so a timeout between partial writes is retryable without
        corrupting the frame stream; only a dead peer or the overall
        ``timeout_s`` budget closes the connection."""
        view = memoryview(data)
        off = 0
        end = time.monotonic() + timeout_s
        with conn.wlock:
            if not conn.open:
                return False
            while off < len(view):
                try:
                    off += conn.sock.send(view[off:])
                except socket.timeout:
                    if (
                        self._stop.is_set()
                        or not conn.open
                        or time.monotonic() >= end
                    ):
                        conn.open = False
                        return False
                    continue  # poll: the peer is slow, not gone
                except (ConnectionError, OSError):
                    conn.open = False
                    return False
        with self._lock:
            self.stats.bytes_out += len(data)
        return True

    # -- lifecycle ------------------------------------------------------------

    def _drop_conn(self, conn: _Conn) -> None:
        with self._lock:
            known = self._conns.pop(conn.cid, None) is not None
            active = len(self._conns)
        with conn.wlock:
            conn.open = False
            try:
                conn.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.sock.close()
            except OSError:  # pragma: no cover
                pass
        if known:
            trace.metrics.gauge("wire_active_connections", active)

    def close(self) -> None:
        """Stop accepting, close every connection, join the threads.
        Idempotent.  The serving target is NOT closed — it outlives its
        wire front-ends."""
        if self._stop.is_set():
            return
        self._stop.set()
        try:
            self._listener.close()
        except OSError:  # pragma: no cover
            pass
        self._accept_thread.join(5.0)
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for conn in conns:
            with conn.wlock:
                conn.open = False
                try:
                    conn.sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    conn.sock.close()
                except OSError:  # pragma: no cover
                    pass
            with conn.cond:
                conn.cond.notify_all()
        for conn in conns:
            for t in (conn.reader, conn.responder):
                if t is not None:
                    t.join(5.0)
        trace.metrics.gauge("wire_active_connections", 0)

    def __enter__(self) -> "WireServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def record(self) -> dict:
        with self._lock:
            active = len(self._conns)
            stats = self.stats.record()
        return {
            "label": self.label,
            "host": self.host,
            "port": self.port,
            "max_inflight": self.max_inflight,
            "active_connections": active,
            "stats": stats,
        }


# -- the reference client -----------------------------------------------------


@dataclasses.dataclass
class WireReply:
    """One decoded server frame."""

    type: int
    request_id: int
    value: np.ndarray | None = None
    etype: str | None = None
    message: str | None = None
    retry_after_s: float | None = None
    #: T_CLOCK reply: (client trace clock echoed, server trace clock) us
    clock: tuple | None = None
    #: T_OBS_* reply: the member's JSON observability payload
    obs: dict | None = None


class WireClient:
    """Blocking reference client for the wire protocol (one socket, used
    from one thread).  ``predict``/``predict_many`` honor RETRY_AFTER
    backpressure (sleep the hint, resubmit) and surface ERROR frames as
    typed :class:`WireRemoteError`."""

    def __init__(
        self, host: str = "127.0.0.1", port: int | None = None,
        timeout: float = 30.0,
    ):
        if port is None:
            raw = os.environ.get(PORT_ENV, "").strip()
            if not raw:
                raise ValueError(
                    f"no port given and {PORT_ENV} is unset"
                )
            port = int(raw)
        self._sock = socket.create_connection((host, int(port)), timeout)
        self._sock.settimeout(timeout)
        self.timeout = timeout
        self._max_frame = max_frame_bytes()
        self._buf = bytearray()
        self._next_id = 0

    def submit(self, arr, client_span: int | None = None) -> int:
        """Send one REQUEST frame; returns its wire request id.
        ``client_span`` rides as the optional trace-context field
        (T_REQUEST_TRACED) so the server's ``wire.request`` instant names
        the client-side span this request belongs to."""
        self._next_id += 1
        rid = self._next_id
        if client_span is not None:
            self._sock.sendall(encode_traced_request(rid, client_span, arr))
        else:
            self._sock.sendall(
                encode_frame(T_REQUEST, rid, encode_array(np.asarray(arr)))
            )
        return rid

    def clock_sync(self, samples: int = 5) -> dict | None:
        """Estimate the server-trace-clock offset: ``samples`` T_CLOCK
        round trips, keeping the minimum-RTT one (the least queue-skewed
        estimate).  Returns ``{"offset_us", "rtt_us"}`` — add ``offset_us``
        to a client trace timestamp to land on the server's timeline — or
        None when the server predates the handshake (it answers the
        unknown frame type with an ERROR; the client degrades, it does
        not die)."""
        from . import trace as ktrace

        best = None
        for _ in range(max(1, samples)):
            self._next_id += 1
            rid = self._next_id
            t0 = ktrace.now_us()
            self._sock.sendall(encode_clock(rid, t0))
            reply = self.read()
            t1 = ktrace.now_us()
            if reply.type == T_ERROR:
                return None  # pre-handshake server — degrade quietly
            if reply.type != T_CLOCK or reply.request_id != rid:
                raise WireProtocolError(
                    f"expected CLOCK {rid}, got type {reply.type} "
                    f"id {reply.request_id}"
                )
            t_client, t_server = reply.clock
            rtt = t1 - t_client
            offset = t_server - (t_client + rtt / 2.0)
            if best is None or rtt < best["rtt_us"]:
                best = {
                    "offset_us": round(offset, 1), "rtt_us": round(rtt, 1)
                }
        return best

    def _obs(self, ftype: int) -> dict | None:
        """One observability round trip; None when the server predates
        the obs frames (it answers ERROR — the collector degrades)."""
        self._next_id += 1
        rid = self._next_id
        self._sock.sendall(encode_frame(ftype, rid))
        reply = self.read()
        if reply.type == T_ERROR:
            return None
        if reply.type != ftype or reply.request_id != rid:
            raise WireProtocolError(
                f"expected OBS {ftype} id {rid}, got type {reply.type} "
                f"id {reply.request_id}"
            )
        return reply.obs

    def obs_snapshot(self) -> dict | None:
        """The member's observability snapshot: statusz + registry
        snapshot + raw histogram sample windows, stamped with its
        ``trace.now_us`` (core.fleetobs scrapes through this)."""
        return self._obs(T_OBS_SNAPSHOT)

    def obs_flight(self) -> dict | None:
        """The member's flight-recorder ring (incident capture)."""
        return self._obs(T_OBS_FLIGHT)

    def ping(self) -> float:
        """Round-trip one PING; returns seconds."""
        t0 = time.perf_counter()
        self._next_id += 1
        self._sock.sendall(encode_frame(T_PING, self._next_id))
        reply = self.read()
        if reply.type != T_PONG or reply.request_id != self._next_id:
            raise WireProtocolError(
                f"expected PONG {self._next_id}, got type {reply.type} "
                f"id {reply.request_id}"
            )
        return time.perf_counter() - t0

    def read(self) -> WireReply:
        """Block for the next server frame (socket timeout raises
        ``TimeoutError``)."""
        while True:
            frame = extract_frame(self._buf, self._max_frame)
            if frame is not None:
                break
            try:
                chunk = self._sock.recv(65536)
            except socket.timeout:
                raise TimeoutError(
                    f"no server frame within {self.timeout}s"
                ) from None
            if not chunk:
                raise ConnectionError("server closed the connection")
            self._buf.extend(chunk)
        ftype, rid, body = frame
        if ftype == T_RESPONSE:
            return WireReply(ftype, rid, value=decode_array(body))
        if ftype == T_ERROR:
            etype, msg = decode_error(body)
            return WireReply(ftype, rid, etype=etype, message=msg)
        if ftype == T_RETRY_AFTER:
            seconds, msg = decode_retry_after(body)
            return WireReply(ftype, rid, retry_after_s=seconds, message=msg)
        if ftype == T_CLOCK:
            return WireReply(ftype, rid, clock=decode_clock_reply(body))
        if ftype in (T_OBS_SNAPSHOT, T_OBS_FLIGHT):
            return WireReply(ftype, rid, obs=decode_obs(body))
        return WireReply(ftype, rid)

    def predict(self, arr, timeout: float = 30.0) -> np.ndarray:
        """Submit one request and block for ITS answer, absorbing
        backpressure until ``timeout``."""
        return self.predict_many([arr], window=1, timeout=timeout)[0]

    def predict_many(
        self, arrs, window: int = 8, timeout: float = 60.0
    ) -> list:
        """Drive ``arrs`` through the server with a bounded pipeline of
        ``window`` outstanding requests; returns the answers in input
        order.  RETRY_AFTER frames are honored (sleep, resubmit); ERROR
        frames raise :class:`WireRemoteError` carrying the remote type."""
        arrs = list(arrs)
        answers: list = [None] * len(arrs)
        pending: dict[int, int] = {}  # wire rid -> input index
        done = 0
        next_i = 0
        end = time.monotonic() + timeout
        while done < len(arrs):
            if time.monotonic() >= end:
                raise TimeoutError(
                    f"{done}/{len(arrs)} answered within {timeout}s"
                )
            while next_i < len(arrs) and len(pending) < max(1, window):
                pending[self.submit(arrs[next_i])] = next_i
                next_i += 1
            reply = self.read()
            if reply.type == T_RESPONSE:
                idx = pending.pop(reply.request_id, None)
                if idx is None:
                    raise WireProtocolError(
                        f"response for unknown request id {reply.request_id}"
                    )
                answers[idx] = reply.value
                done += 1
            elif reply.type == T_RETRY_AFTER:
                idx = pending.pop(reply.request_id, None)
                if idx is None:
                    raise WireProtocolError(
                        f"retry for unknown request id {reply.request_id}"
                    )
                time.sleep(min(max(reply.retry_after_s, 0.0), 1.0))
                pending[self.submit(arrs[idx])] = idx
            elif reply.type == T_ERROR:
                raise WireRemoteError(reply.etype, reply.message or "")
            # PONGs (or future frame types) are ignored here.
        return answers

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:  # pragma: no cover
            pass

    def __enter__(self) -> "WireClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
