"""Fault-tolerance primitives: IO retry with backoff, corrupt-item
accounting, and finite-state assertions.

KeystoneML inherited fault tolerance from Spark — task retry, lineage
recompute, and per-record skip counters came with the substrate.  A JAX
pipeline has no substrate doing that, so the primitives live here:

* :func:`retry` — bounded exponential-backoff retry for transient IO
  (tar/file reads, the native decoder's one-time g++ build).  Tunable via
  ``KEYSTONE_IO_RETRIES`` / ``KEYSTONE_IO_BACKOFF`` / ``KEYSTONE_IO_TIMEOUT``.
* :class:`FaultCounters` / module singleton :data:`counters` — named counts
  of survived faults (corrupt images, unreadable tar members, retried
  opens), logged through the ``keystone_tpu`` logger hierarchy
  (core.logging) instead of being silently dropped.
* :func:`assert_all_finite` — the fit-path guard: every float leaf of a
  fitted model pytree must be finite, else the fit fails loudly instead of
  serving NaN predictions.
* :func:`deadline` / :class:`DeadlineExceeded` — the wall-clock watchdog:
  a phase that hangs (dead interconnect, a collective waiting on a
  preempted peer, an IO mount that went away) is converted into a typed,
  counted error naming the phase, instead of stalling the whole pipeline
  forever.  Spark got this from task speculation + executor heartbeats;
  a single-controller process has to arm its own timer.
"""

from __future__ import annotations

import contextlib
import errno
import functools
import logging
import os
import signal
import sys
import threading
import time
from typing import Callable

import numpy as np

from . import trace

# NO module-level jax import, deliberately: this module sits on the import
# path of every spawned decode worker (core.ingest pulls `counters` from
# here), and jax costs multi-second interpreter startup those numpy-only
# processes must not pay.  The one jax consumer (assert_all_finite) imports
# it lazily; tests/test_lazy_import.py enforces the discipline.

_logger = logging.getLogger("keystone_tpu.resilience")

# Exception types treated as transient by default: filesystem hiccups,
# truncated reads, interrupted syscalls.  (tarfile raises tarfile.TarError
# subclasses for corrupt archives — those are *data* faults, counted and
# skipped by the loaders, not retried.)
DEFAULT_RETRY_ON: tuple[type[BaseException], ...] = (OSError, EOFError)

# OSError subclasses that can never succeed on retry — a typo'd path or a
# permissions problem should fail fast, not sleep through the backoff
# schedule logging misleading io_retry warnings.
PERMANENT_ERRORS: tuple[type[BaseException], ...] = (
    FileNotFoundError,
    IsADirectoryError,
    NotADirectoryError,
    PermissionError,
)


def is_addr_in_use(e: BaseException) -> bool:
    """Is this failure an ``EADDRINUSE`` bind collision?  Transient by
    nature (auto-picked ports race between pick and bind; TIME_WAIT
    lingers), so callers retry it — but it surfaces inconsistently: a
    proper ``OSError`` with errno from Python sockets, an opaque
    ``RuntimeError``/``XlaRuntimeError`` string from grpc-backed services
    (the ``jax.distributed`` coordinator).  Both spellings are matched."""
    if isinstance(e, OSError) and e.errno == errno.EADDRINUSE:
        return True
    return "address already in use" in str(e).lower()


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        val = int(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r} is not an integer") from None
    if val < 1:
        raise ValueError(f"{name}={raw!r} must be >= 1")
    return val


def _env_float(name: str, default: float | None) -> float | None:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r} is not a number") from None


def retry(
    fn: Callable | None = None,
    *,
    attempts: int | None = None,
    backoff: float | None = None,
    timeout: float | None = None,
    retry_on: tuple[type[BaseException], ...] = DEFAULT_RETRY_ON,
    name: str | None = None,
):
    """Wrap ``fn`` with bounded retry + exponential backoff.

    ``attempts``: total tries (default ``KEYSTONE_IO_RETRIES`` or 3).
    ``backoff``: first sleep in seconds, doubling per retry (default
    ``KEYSTONE_IO_BACKOFF`` or 0.1).
    ``timeout``: total wall-clock budget across attempts (default
    ``KEYSTONE_IO_TIMEOUT`` or unlimited) — when exceeded, the last error
    is raised instead of sleeping again.
    ``retry_on``: exception types considered transient; anything else —
    including the :data:`PERMANENT_ERRORS` subclasses (missing paths,
    permissions) — propagates immediately.

    Usable as a decorator (``@retry``/``@retry(attempts=5)``) or inline
    (``retry(tarfile.open)(path)``).  Every retried failure is logged and
    counted under ``io_retry``.
    """
    if fn is None:
        return functools.partial(
            retry,
            attempts=attempts,
            backoff=backoff,
            timeout=timeout,
            retry_on=retry_on,
            name=name,
        )

    label = name or getattr(fn, "__name__", "fn")

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        n = attempts if attempts is not None else _env_int("KEYSTONE_IO_RETRIES", 3)
        pause = (
            backoff
            if backoff is not None
            else (_env_float("KEYSTONE_IO_BACKOFF", 0.1) or 0.0)
        )
        budget = (
            timeout if timeout is not None else _env_float("KEYSTONE_IO_TIMEOUT", None)
        )
        t0 = time.monotonic()
        for attempt in range(1, n + 1):
            try:
                return fn(*args, **kwargs)
            except retry_on as e:
                if isinstance(e, PERMANENT_ERRORS):
                    raise  # user error, not a transient fault
                out_of_budget = (
                    budget is not None and time.monotonic() - t0 + pause > budget
                )
                if attempt >= n or out_of_budget:
                    _logger.error(
                        "%s failed after %d attempt(s)%s: %s",
                        label,
                        attempt,
                        " (timeout budget exhausted)" if out_of_budget else "",
                        e,
                    )
                    raise
                counters.record(
                    "io_retry", f"{label} attempt {attempt}/{n}: {e}"
                )
                time.sleep(pause)
                pause *= 2.0
        raise AssertionError("unreachable")  # pragma: no cover

    return wrapped


class FaultCounters:
    """Thread-safe named counters for survived faults.

    Loaders and solvers call :meth:`record`; each event is logged (WARNING)
    through the keystone_tpu logger tree so operators see skips as they
    happen, and the totals are queryable (:meth:`counts`) so pipelines and
    tests can assert "N items skipped" instead of guessing from log grep.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: dict[str, int] = {}

    def record(self, kind: str, detail: str | None = None) -> int:
        with self._lock:
            self._counts[kind] = self._counts.get(kind, 0) + 1
            total = self._counts[kind]
            # Every survived fault is also a point event on the trace
            # timeline (no-op when tracing is disabled), so a trace shows
            # WHEN each fault landed relative to the spans it interrupted.
            # Emitted INSIDE the counter lock: any snapshot that observes
            # this count is guaranteed the event is already buffered, so
            # the chaos --trace verifier (counted fault -> trace event)
            # can never see a torn pair.
            trace.instant(
                "fault", kind=kind, total=total,
                **({"detail": detail[:200]} if detail else {}),
            )
        _logger.warning(
            "%s #%d%s", kind, total, f": {detail}" if detail else ""
        )
        # Flight-recorder postmortem (core.telemetry): a typed fault of a
        # postmortem family dumps the recent-event ring + a counters
        # snapshot when KEYSTONE_POSTMORTEM_DIR is set.  OUTSIDE the
        # counter lock: the dump snapshots the metrics registry, whose
        # "faults" group re-enters THIS ledger's snapshot.  Function-local
        # import (a sys.modules lookup at this point) because the module-
        # level binding only exists below this class definition.
        from . import telemetry

        telemetry.maybe_postmortem(kind, detail=detail, total=total)
        return total

    def counts(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def snapshot(self, reset: bool = False) -> dict[str, int]:
        """Atomic copy of the counts; ``reset=True`` clears them under the
        SAME lock acquisition.  Separate ``counts()`` + ``reset()`` calls
        lose any fault recorded between them — every record emitter
        (bench, chaos, the multichip dryrun) snapshots through here."""
        with self._lock:
            out = dict(self._counts)
            if reset:
                self._counts.clear()
        return out

    def get(self, kind: str) -> int:
        with self._lock:
            return self._counts.get(kind, 0)

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()


#: Process-wide fault ledger (loaders/image_loaders, loaders/native_decode).
counters = FaultCounters()

# The fault ledger rides along in every metrics snapshot as the "faults"
# group — one atomic record captures perf metrics AND degradation events.
trace.metrics.adopt("faults", counters)

# Activate the telemetry exporters (KEYSTONE_METRICS_FILE / _PORT) for any
# process that can survive a fault — i.e. any importer of this module.
# telemetry is jax-free and defers http.server until a port is asked for,
# so the decode workers' import-cost discipline holds.
from . import telemetry  # noqa: E402,F401  (env-activated exporters)


def numerics_guard_enabled() -> bool:
    """Non-finite checks + Cholesky jitter-retry are on unless
    ``KEYSTONE_NUMERICS_GUARD=0`` (the checks cost one host sync per
    guarded solve)."""
    return os.environ.get("KEYSTONE_NUMERICS_GUARD", "").strip() != "0"


def assert_all_finite(tree, name: str = "fitted model"):
    """Raise ``FloatingPointError`` if any inexact-dtype array leaf of
    ``tree`` contains NaN/Inf.  Returns ``tree`` so fit paths can guard
    inline: ``model = assert_all_finite(est.fit(x, y), "block solve")``."""
    import jax

    bad = []
    for i, leaf in enumerate(jax.tree_util.tree_leaves(tree)):
        if not isinstance(leaf, (np.ndarray, np.generic, jax.Array)):
            continue
        dtype = np.dtype(getattr(leaf, "dtype", np.float32))
        if dtype.kind not in "fc":
            continue
        finite = np.isfinite(np.asarray(jax.device_get(leaf), np.float64)).all()
        if not finite:
            bad.append(i)
    if bad:
        # NaN provenance (core.numerics, ISSUE 15): when a probe already
        # bisected a non-finite streamed/served batch to its tar members /
        # request ids, the typed error names the culprit instead of just
        # the model that absorbed it.  Function-local import (numerics is
        # jax-free, but this module must not grow import weight).
        from . import numerics

        note = numerics.provenance_note()
        suffix = f"; {note}" if note else ""
        counters.record(
            "nonfinite_model",
            f"{name}: {len(bad)} non-finite leaf/leaves{suffix}",
        )
        raise FloatingPointError(
            f"{name} contains non-finite values in {len(bad)} leaf/leaves "
            f"(indices {bad}) — refusing to ship a silently-broken model "
            "(ill-conditioned solve, NaN input batch, or overflow upstream)"
            + suffix
        )
    return tree


# -- wall-clock watchdog ------------------------------------------------------


class DeadlineExceeded(RuntimeError):
    """A pipeline phase blew its wall-clock budget.  Typed (never a bare
    traceback), carries the ``phase`` name and the budget so operators and
    the chaos harness can assert WHICH stage hung."""

    def __init__(self, phase: str, seconds: float):
        super().__init__(
            f"phase {phase!r} exceeded its {seconds:g}s deadline — "
            "converting the hang into a typed failure"
        )
        self.phase = phase
        self.seconds = seconds
        #: When the trip fired — lets an enclosing deadline's handler tell
        #: "this error is still UNWINDING (raised microseconds ago)" from
        #: "someone caught it and their recovery path is now hanging".
        self.raised_at = time.monotonic()


@contextlib.contextmanager
def deadline(seconds: float, phase: str = "work"):
    """Bound a pipeline phase by wall clock: the block either finishes
    within ``seconds`` or dies with :class:`DeadlineExceeded` (counted
    under ``deadline_exceeded``), never hangs silently.

    On the main thread of a POSIX process the watchdog is a real
    ``SIGALRM`` interval timer, so a genuine hang (a sleep, a stuck read,
    a collective waiting on a dead peer — anything that re-enters the
    Python interpreter) is interrupted mid-flight.  Off the main thread
    (or on platforms without ``setitimer``) signals cannot be armed; the
    fallback checks elapsed time on exit, converting an overrun — though
    not a true never-returns hang — into the same typed error.  Deadlines
    nest: the TIGHTER of the inner budget and the enclosing deadline's
    remaining time is armed (so an outer bound is never suspended by a
    looser inner block), and on inner exit the outer timer is re-armed
    with whatever it has left.
    """
    if seconds <= 0:
        raise ValueError(f"deadline seconds must be positive, got {seconds}")

    armed = False
    old_handler = None
    old_delay = 0.0
    budget = seconds
    t0 = time.monotonic()

    def _trip(signum, frame):
        current = sys.exc_info()[1]
        if (
            isinstance(current, DeadlineExceeded)
            and time.monotonic() - getattr(current, "raised_at", 0.0) < 0.25
        ):
            # A deadline error raised MOMENTS ago is still unwinding
            # through this thread: an inner trip racing the enclosing
            # deadline's re-armed timer (the 1e-3 floor below).  Raising
            # now would REPLACE the inner trip's phase attribution
            # mid-unwind, so postpone briefly.  The recency bound keeps
            # the enclosing deadline REAL: an `except DeadlineExceeded:`
            # suite holds exc_info for its whole body, and without the
            # bound a hung recovery path would be postponed forever.
            signal.setitimer(signal.ITIMER_REAL, 0.05)
            return
        counters.record(
            "deadline_exceeded", f"{phase}: wall clock exceeded {budget:g}s"
        )
        raise DeadlineExceeded(phase, budget)

    try:
        old_handler = signal.signal(signal.SIGALRM, _trip)
        old_delay = signal.setitimer(signal.ITIMER_REAL, seconds)[0]
        if 0.0 < old_delay < seconds:
            # An ENCLOSING deadline had less time left than this block asks
            # for: arming the full inner budget would suspend the outer
            # bound for the inner block's whole duration.  The tighter
            # remaining budget wins (the trip is attributed to the phase
            # that was executing — this one).
            budget = old_delay
            signal.setitimer(signal.ITIMER_REAL, old_delay)
        armed = True
    except (ValueError, AttributeError, OSError):
        # Not the main thread / no setitimer: post-hoc fallback below.
        pass
    try:
        yield
        if not armed and time.monotonic() - t0 > seconds:
            counters.record(
                "deadline_exceeded",
                f"{phase}: wall clock exceeded {seconds:g}s (post-hoc)",
            )
            raise DeadlineExceeded(phase, seconds)
    finally:
        if armed:
            signal.setitimer(signal.ITIMER_REAL, 0.0)
            signal.signal(signal.SIGALRM, old_handler)
            if old_delay > 0.0:
                # Re-arm the enclosing deadline with whatever it has left
                # (floor at a tick so it still fires if already overdue).
                remaining = max(old_delay - (time.monotonic() - t0), 1e-3)
                signal.setitimer(signal.ITIMER_REAL, remaining)
